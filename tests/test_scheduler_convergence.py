"""Independent mathematical validation of the samplers (round-2 verdict
"what's weak" #4): the NumPy transcription fixtures in
reference_schedulers.py share an author with the implementation, so a shared
misreading would pass both. These tests rely only on *mathematical
properties* of the exact probability-flow ODE, not on any transcription:

1. Constant-x0 exactness: if the model's x0-prediction is a constant c, the
   exact ODE solution between any two timesteps is
   x_s = α_s·c + (σ_s/σ_t)·(x_t − α_t·c). DDIM and first-order DPM-Solver++
   are exponential integrators that are EXACT for constant x0 at ANY step
   size — a sharp closed-form check of the α/σ/λ/expm1 coefficient algebra
   (a wrong λ definition or swapped α/σ fails it immediately).

2. Empirical convergence order: for a smooth linear-in-x model, the global
   error against a 1000-step fine solution must shrink ~2× per step-count
   doubling for DDIM (order 1) and ~4× for DPM-Solver++(2M) (order 2).
   Transcription slips that stay consistent (so golden tests pass) but
   break the ODE consistency order fail here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcr_tpu.models import schedulers as S

pytestmark = pytest.mark.fast


def _sched():
    return S.make_schedule(1000, "scaled_linear", 0.00085, 0.012,
                           prediction_type="epsilon")


def _alpha_sigma(sched, t):
    acp = np.asarray(sched.alphas_cumprod)[t]
    return float(np.sqrt(acp)), float(np.sqrt(1.0 - acp))


def test_ddim_exact_for_constant_x0():
    sched = _sched()
    c = jnp.asarray([[0.7, -1.3, 0.25]])
    x_t = jnp.asarray([[1.1, 0.4, -0.8]])
    for t, prev_t in ((999, 499), (700, 123), (400, 0)):
        a_t, s_t = _alpha_sigma(sched, t)
        a_s, s_s = _alpha_sigma(sched, prev_t)
        eps = (x_t - a_t * c) / s_t          # model consistent with x0 == c
        got = S.ddim_step(sched, eps, x_t, jnp.asarray(t), jnp.asarray(prev_t))
        want = a_s * c + (s_s / s_t) * (x_t - a_t * c)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)


def test_dpmpp_first_order_exact_for_constant_x0():
    sched = _sched()
    c = jnp.asarray([[0.7, -1.3, 0.25]])
    x_t = jnp.asarray([[1.1, 0.4, -0.8]])
    for t, prev_t in ((999, 499), (700, 123)):
        a_t, s_t = _alpha_sigma(sched, t)
        a_s, s_s = _alpha_sigma(sched, prev_t)
        eps = (x_t - a_t * c) / s_t
        state = S.dpm_init_state(x_t.shape)   # step_index 0: first-order
        got, _ = S.dpmpp_2m_step(sched, eps, x_t, jnp.asarray(t),
                                 jnp.asarray(prev_t), state)
        want = a_s * c + (s_s / s_t) * (x_t - a_t * c)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5, rtol=1e-5)


# Fixed integration domain t: 999 -> 99, entirely inside the training grid.
# The production trajectory's final hop to t=-1 crosses the sigma->0 clamp —
# a fixed-size lambda-step that cannot shrink under refinement and would
# pollute an order measurement (it's also why diffusers applies
# lower_order_final on short trajectories). Order is a property of the
# smooth interior; the endpoint hop is covered by the exactness tests above
# and the trajectory golden tests.
T_HI, T_LO = 999, 99


def _grid(n_steps):
    step = (T_HI - T_LO) // n_steps
    assert step * n_steps == T_HI - T_LO     # integer grid only
    return np.arange(T_HI, T_LO - 1, -step)


def _run_ddim(sched, x_init, n_steps, model):
    ts = _grid(n_steps)
    x = x_init
    for t, prev_t in zip(ts[:-1], ts[1:]):
        x = S.ddim_step(sched, model(x, int(t)), x, jnp.asarray(int(t)),
                        jnp.asarray(int(prev_t)))
    return x


def _run_2m(sched, x_init, n_steps, model):
    ts = _grid(n_steps)
    x = x_init
    state = S.dpm_init_state(x_init.shape)
    for t, prev_t in zip(ts[:-1], ts[1:]):
        x, state = S.dpmpp_2m_step(sched, model(x, int(t)), x,
                                   jnp.asarray(int(t)),
                                   jnp.asarray(int(prev_t)), state)
    return x


def _linear_model(sched):
    """Smooth, nontrivial ε-model, linear in x so the ODE is well-behaved."""

    def model(x, t):
        return 0.35 * x + 0.1

    return model


def test_ddim_first_order_convergence():
    sched = _sched()
    model = _linear_model(sched)
    x0 = jnp.asarray([[0.9, -0.4, 0.2]])
    ref = _run_ddim(sched, x0, 900, model)
    errs = [float(jnp.max(jnp.abs(_run_ddim(sched, x0, n, model) - ref)))
            for n in (25, 50, 100)]
    r1, r2 = errs[0] / errs[1], errs[1] / errs[2]
    # order 1: halving h halves the error (1000-step ref adds slack)
    assert 1.5 < r1 < 2.6, (errs, r1)
    assert 1.5 < r2 < 2.6, (errs, r2)


def test_dpmpp_2m_second_order_convergence():
    sched = _sched()
    model = _linear_model(sched)
    x0 = jnp.asarray([[0.9, -0.4, 0.2]])
    ref = _run_2m(sched, x0, 900, model)
    errs = [float(jnp.max(jnp.abs(_run_2m(sched, x0, n, model) - ref)))
            for n in (9, 18, 36)]
    r1, r2 = errs[0] / errs[1], errs[1] / errs[2]
    # order 2: halving h quarters the error; generous band for the integer
    # timestep grid's quantization and the first-order bootstrap step
    assert 2.6 < r1 < 6.5, (errs, r1)
    assert 2.6 < r2 < 6.5, (errs, r2)
    # and 2M must beat DDIM at equal step count (the point of order 2)
    err_ddim18 = float(jnp.max(jnp.abs(_run_ddim(sched, x0, 18, model) - ref)))
    assert errs[1] < err_ddim18

"""Manifest-driven converter/exporter validation (VERDICT round-1 items 3-5).

Round 1's UNet/VAE converter tests synthesized torch state dicts from the
converters' own inverse name maps — circular. Here the source of truth is the
vendored SD-2.1 manifests (tests/fixtures/sd21_*_keys.json): key names +
shapes of the real diffusers 0.14 / transformers state dicts (the text one is
dumped from a live transformers CLIPTextModel; generator:
tools/gen_sd21_manifest.py). Converters must consume exactly the manifest key
set; exporters must produce it byte-for-byte.
"""

import json
from pathlib import Path

import jax
import numpy as np
import pytest

from dcr_tpu.core.config import ModelConfig
from dcr_tpu.models import convert as CV
from dcr_tpu.models import export as EX

FIXTURES = Path(__file__).parent / "fixtures"


def _load(name: str) -> dict[str, list[int]]:
    return json.loads((FIXTURES / name).read_text())


def _zeros_sd(manifest: dict[str, list[int]]) -> dict[str, np.ndarray]:
    return {k: np.zeros(s, np.float16) for k, s in manifest.items()}


def _shape_tree(init_fn, *args):
    """Param tree of ShapeDtypeStructs without materializing 865M params."""
    return jax.eval_shape(init_fn, *args)


@pytest.fixture(scope="module")
def sd21_cfg() -> ModelConfig:
    return ModelConfig()          # full SD-2.1 dims


def test_unet_converter_consumes_real_sd21_manifest(sd21_cfg):
    from dcr_tpu.models.unet2d import init_unet

    manifest = _load("sd21_unet_keys.json")
    converted = CV.convert_unet(_zeros_sd(manifest))
    expected = _shape_tree(lambda k: init_unet(sd21_cfg, k)[1], jax.random.key(0))
    problems = CV.check_converted(expected, converted)
    assert not problems, problems[:10]


def test_vae_converter_consumes_real_sd21_manifest(sd21_cfg):
    """The manifest uses the 0.14-era AttentionBlock naming
    (query/key/value/proj_attn) that on-hub SD VAE checkpoints carry; the
    converter must normalize it."""
    from dcr_tpu.models.vae import init_vae

    manifest = _load("sd21_vae_keys.json")
    converted = CV.convert_vae(_zeros_sd(manifest))
    expected = _shape_tree(lambda k: init_vae(sd21_cfg, k)[1], jax.random.key(0))
    problems = CV.check_converted(expected, converted)
    assert not problems, problems[:10]


def test_text_converter_consumes_real_sd21_manifest(sd21_cfg):
    from dcr_tpu.models.clip_text import init_clip_text

    manifest = _load("sd21_text_keys.json")
    converted = CV.convert_clip_text(_zeros_sd(manifest),
                                     layers=sd21_cfg.text_layers,
                                     heads=sd21_cfg.text_heads)
    expected = _shape_tree(lambda k: init_clip_text(sd21_cfg, k)[1],
                           jax.random.key(0))
    problems = CV.check_converted(expected, converted)
    assert not problems, problems[:10]


# ---------------------------------------------------------------------------
# export: key set must equal the manifest byte-for-byte
# ---------------------------------------------------------------------------

def _assert_sd_matches_manifest(sd: dict, manifest: dict) -> None:
    missing = sorted(set(manifest) - set(sd))
    extra = sorted(set(sd) - set(manifest))
    assert not missing and not extra, {"missing": missing[:10], "extra": extra[:10]}
    bad = [k for k in manifest if list(sd[k].shape) != manifest[k]]
    assert not bad, [(k, sd[k].shape, manifest[k]) for k in bad[:10]]


def test_unet_export_keys_byte_for_byte(sd21_cfg):
    manifest = _load("sd21_unet_keys.json")
    converted = CV.convert_unet(_zeros_sd(manifest))
    _assert_sd_matches_manifest(EX.unet_to_diffusers(converted), manifest)


def test_vae_export_keys_byte_for_byte(sd21_cfg):
    manifest = _load("sd21_vae_keys.json")
    converted = CV.convert_vae(_zeros_sd(manifest))
    _assert_sd_matches_manifest(EX.vae_to_diffusers(converted), manifest)


def test_text_export_keys_byte_for_byte(sd21_cfg):
    manifest = _load("sd21_text_keys.json")
    converted = CV.convert_clip_text(_zeros_sd(manifest),
                                     layers=sd21_cfg.text_layers,
                                     heads=sd21_cfg.text_heads)
    _assert_sd_matches_manifest(EX.text_to_transformers(converted), manifest)


def test_text_export_loads_into_real_transformers():
    """Round-trip through a LIVE transformers CLIPTextModel: our export must
    load_state_dict with strict=True and reproduce our activations."""
    torch = pytest.importorskip("torch")
    from transformers import CLIPTextConfig, CLIPTextModel as HFCLIPText

    from dcr_tpu.models.clip_text import init_clip_text

    cfg = ModelConfig(text_vocab_size=99, text_hidden_size=32, text_layers=2,
                      text_heads=2, text_max_length=16, text_act="gelu")
    ours, params = init_clip_text(cfg, jax.random.key(3))
    sd = EX.text_to_transformers(params)

    hf_cfg = CLIPTextConfig(
        vocab_size=99, hidden_size=32, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=2,
        max_position_embeddings=16, hidden_act="gelu")
    hf = HFCLIPText(hf_cfg).eval()
    missing, unexpected = hf.load_state_dict(
        {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in sd.items()},
        strict=False)
    assert not unexpected, unexpected
    assert all("position_ids" in m for m in missing), missing

    ids = np.array([[5, 7, 9, 11, 2, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0]], np.int64)
    with torch.no_grad():
        hf_out = hf(input_ids=torch.from_numpy(ids)).last_hidden_state.numpy()
    import jax.numpy as jnp

    our_out = ours.apply({"params": params},
                         jnp.asarray(ids, jnp.int32)).last_hidden_state
    np.testing.assert_allclose(np.asarray(our_out), hf_out, atol=2e-5, rtol=1e-4)


def test_hf_layout_export_tiny_end_to_end(tmp_path):
    """Integration: export_hf_layout writes npz + safetensors + configs, and
    the safetensors round-trip back through the forward converters."""
    from safetensors.numpy import load_file

    from dcr_tpu.core.checkpoint import export_hf_layout, import_hf_layout
    from dcr_tpu.core.config import to_dict
    from dcr_tpu.models.unet2d import init_unet
    from dcr_tpu.models.vae import init_vae

    cfg = ModelConfig.tiny()
    _, up = init_unet(cfg, jax.random.key(0))
    _, vp = init_vae(cfg, jax.random.key(1))
    export_hf_layout(tmp_path / "ckpt", unet=up, vae=vp,
                     scheduler_config={"num_train_timesteps": 1000},
                     model_config=to_dict(cfg))

    assert (tmp_path / "ckpt" / "unet" / "config.json").exists()
    sched = json.loads((tmp_path / "ckpt" / "scheduler" /
                        "scheduler_config.json").read_text())
    assert sched["_class_name"] == "DPMSolverMultistepScheduler"
    assert sched["steps_offset"] == 1

    # npz fast path unchanged
    assert CV.check_converted(up, import_hf_layout(tmp_path / "ckpt", "unet")) == []

    # safetensors -> forward converter -> identical tree
    sd = load_file(str(tmp_path / "ckpt" / "unet" /
                       "diffusion_pytorch_model.safetensors"))
    back = CV.convert_unet(sd, block_out_channels=cfg.block_out_channels,
                           layers_per_block=cfg.layers_per_block,
                           transformer_layers=cfg.transformer_layers)
    assert CV.check_converted(up, back) == []
    for (p1, a), (p2, b) in zip(sorted(EX._leaves(up)), sorted(EX._leaves(back))):
        assert p1 == p2
        np.testing.assert_array_equal(a, b, err_msg=p1)

    sd_vae = load_file(str(tmp_path / "ckpt" / "vae" /
                           "diffusion_pytorch_model.safetensors"))
    assert any(".query.weight" in k for k in sd_vae)   # 0.14-era naming
    back_vae = CV.convert_vae(sd_vae, block_out_channels=cfg.vae_block_out_channels,
                              layers_per_block=cfg.vae_layers_per_block)
    assert CV.check_converted(vp, back_vae) == []


# ---------------------------------------------------------------------------
# CLIP image tower converter (VERDICT round-1 item 5)
# ---------------------------------------------------------------------------

def test_clip_image_converter_parity_with_transformers():
    """REAL cross-framework parity: transformers CLIPVisionModelWithProjection
    (torch) -> convert_clip_image -> identical image embeddings."""
    torch = pytest.importorskip("torch")
    from transformers import CLIPVisionConfig, CLIPVisionModelWithProjection

    from dcr_tpu.models.clip_image import CLIPImageTower

    hf_cfg = CLIPVisionConfig(
        hidden_size=32, intermediate_size=128, num_hidden_layers=2,
        num_attention_heads=2, image_size=32, patch_size=16,
        hidden_act="quick_gelu", projection_dim=16)
    torch.manual_seed(0)
    hf = CLIPVisionModelWithProjection(hf_cfg).eval()
    sd = CV.torch_state_dict_to_numpy(hf)

    tower = CLIPImageTower(patch_size=16, width=32, layers=2, heads=2,
                           embed_dim=16)
    converted = CV.convert_clip_image(sd, layers=2)
    init = tower.init(jax.random.key(0), np.zeros((1, 32, 32, 3)))["params"]
    problems = CV.check_converted(init, converted)
    assert not problems, problems[:10]

    rng = np.random.default_rng(0)
    x01 = rng.uniform(0.2, 0.8, (2, 32, 32, 3)).astype(np.float32)
    mean = np.array([0.48145466, 0.4578275, 0.40821073], np.float32)
    std = np.array([0.26862954, 0.26130258, 0.27577711], np.float32)
    x_norm = (x01 - mean) / std
    with torch.no_grad():
        ref = hf(pixel_values=torch.from_numpy(
            x_norm.transpose(0, 3, 1, 2))).image_embeds.numpy()
    import jax.numpy as jnp

    out = tower.apply({"params": jax.tree.map(jnp.asarray, converted)},
                      jnp.asarray(x01))
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4, rtol=1e-3)


def test_openai_clip_structural_roundtrip():
    """OpenAI CLIP archive naming (visual.* fused in_proj + text resblocks) ->
    full scorer params with matching structure."""
    from dcr_tpu.models.clip_image import (CLIPImageTower, clip_b16_text_config,
                                           init_clip_scorer, make_clip_scorer)

    width, layers, heads, embed = 32, 2, 2, 16
    tw, tl, th = 32, 2, 2
    sd: dict[str, np.ndarray] = {}
    z = lambda *s: np.zeros(s, np.float32)
    sd["visual.conv1.weight"] = z(width, 3, 16, 16)
    sd["visual.class_embedding"] = z(width)
    sd["visual.positional_embedding"] = z(5, width)    # 2x2 grid + cls
    sd["visual.ln_pre.weight"] = z(width); sd["visual.ln_pre.bias"] = z(width)
    for i in range(layers):
        p = f"visual.transformer.resblocks.{i}"
        sd[f"{p}.ln_1.weight"] = z(width); sd[f"{p}.ln_1.bias"] = z(width)
        sd[f"{p}.attn.in_proj_weight"] = z(3 * width, width)
        sd[f"{p}.attn.in_proj_bias"] = z(3 * width)
        sd[f"{p}.attn.out_proj.weight"] = z(width, width)
        sd[f"{p}.attn.out_proj.bias"] = z(width)
        sd[f"{p}.ln_2.weight"] = z(width); sd[f"{p}.ln_2.bias"] = z(width)
        sd[f"{p}.mlp.c_fc.weight"] = z(4 * width, width)
        sd[f"{p}.mlp.c_fc.bias"] = z(4 * width)
        sd[f"{p}.mlp.c_proj.weight"] = z(width, 4 * width)
        sd[f"{p}.mlp.c_proj.bias"] = z(width)
    sd["visual.ln_post.weight"] = z(width); sd["visual.ln_post.bias"] = z(width)
    sd["visual.proj"] = z(width, embed)
    sd["token_embedding.weight"] = z(50, tw)
    sd["positional_embedding"] = z(8, tw)
    for i in range(tl):
        p = f"transformer.resblocks.{i}"
        sd[f"{p}.ln_1.weight"] = z(tw); sd[f"{p}.ln_1.bias"] = z(tw)
        sd[f"{p}.attn.in_proj_weight"] = z(3 * tw, tw)
        sd[f"{p}.attn.in_proj_bias"] = z(3 * tw)
        sd[f"{p}.attn.out_proj.weight"] = z(tw, tw)
        sd[f"{p}.attn.out_proj.bias"] = z(tw)
        sd[f"{p}.ln_2.weight"] = z(tw); sd[f"{p}.ln_2.bias"] = z(tw)
        sd[f"{p}.mlp.c_fc.weight"] = z(4 * tw, tw)
        sd[f"{p}.mlp.c_fc.bias"] = z(4 * tw)
        sd[f"{p}.mlp.c_proj.weight"] = z(tw, 4 * tw)
        sd[f"{p}.mlp.c_proj.bias"] = z(tw)
    sd["ln_final.weight"] = z(tw); sd["ln_final.bias"] = z(tw)
    sd["text_projection"] = z(tw, embed)

    params = CV.convert_openai_clip(sd, image_layers=layers,
                                    text_layers=tl, text_heads=th)
    tower = CLIPImageTower(patch_size=16, width=width, layers=layers,
                           heads=heads, embed_dim=embed)
    img_init = tower.init(jax.random.key(0), np.zeros((1, 32, 32, 3)))["params"]
    assert CV.check_converted(img_init, params["image"]) == []

    import dataclasses

    from dcr_tpu.models.clip_text import CLIPTextModel

    tcfg = dataclasses.replace(clip_b16_text_config(vocab_size=50),
                               text_hidden_size=tw, text_layers=tl,
                               text_heads=th, text_max_length=8)
    text_init = CLIPTextModel(tcfg).init(
        jax.random.key(1), np.zeros((1, 8), np.int32))["params"]
    assert CV.check_converted(text_init, params["text"]) == []
    assert params["text_projection"].shape == (tw, embed)


def test_sd1x_hf_layout_export(tmp_path):
    """SD-1.x family: export emits the scalar fixed head count +
    use_linear_projection=false diffusers config (the crash/mis-description
    regression), and the conv-projection safetensors round-trip."""
    import dataclasses

    from dcr_tpu.core.checkpoint import export_hf_layout
    from dcr_tpu.core.config import to_dict
    from dcr_tpu.models.unet2d import init_unet
    from safetensors.numpy import load_file

    cfg = dataclasses.replace(
        ModelConfig.sd1x(), sample_size=8, block_out_channels=(32, 64),
        layers_per_block=1, attention_num_heads=2, norm_num_groups=8,
        cross_attention_dim=48, flash_attention=False,
        vae_block_out_channels=(16, 32), vae_layers_per_block=1)
    _, up = init_unet(cfg, jax.random.key(0))
    export_hf_layout(tmp_path / "ckpt", unet=up, model_config=to_dict(cfg))

    ucfg = json.loads((tmp_path / "ckpt" / "unet" / "config.json").read_text())
    assert ucfg["attention_head_dim"] == 2          # scalar fixed head count
    assert ucfg["use_linear_projection"] is False
    sd = load_file(str(tmp_path / "ckpt" / "unet" /
                       "diffusion_pytorch_model.safetensors"))
    assert sd["mid_block.attentions.0.proj_in.weight"].ndim == 4
    back = CV.convert_unet(sd, block_out_channels=cfg.block_out_channels,
                           layers_per_block=cfg.layers_per_block,
                           transformer_layers=cfg.transformer_layers)
    assert CV.check_converted(up, back) == []


def test_genuine_diffusers_checkpoint_loads_turnkey(tmp_path):
    """A directory that looks exactly like a DOWNLOADED diffusers checkpoint
    (torch safetensors + per-subfolder config.json + pipeline model_index,
    no params.npz, no native model_config) loads through
    load_checkpoint_models with identical params — the reference's input
    format (diff_train.py:370-408) is consumable with zero manual steps."""
    from dcr_tpu.core.checkpoint import export_hf_layout
    from dcr_tpu.core.config import to_dict
    from dcr_tpu.diffusion.trainer import build_models
    from dcr_tpu.models.unet2d import attn_dims
    from dcr_tpu.sampling.pipeline import load_checkpoint_models
    from dcr_tpu.core.config import TrainConfig

    cfg = ModelConfig.tiny()
    tcfg = TrainConfig()
    tcfg.model = cfg
    _, params = build_models(tcfg, jax.random.key(0))
    export_hf_layout(tmp_path / "ckpt", unet=params["unet"], vae=params["vae"],
                     text_encoder=params["text"],
                     scheduler_config={
                         "num_train_timesteps": cfg.num_train_timesteps,
                         "beta_schedule": cfg.beta_schedule,
                         "beta_start": cfg.beta_start, "beta_end": cfg.beta_end,
                         "prediction_type": cfg.prediction_type},
                     model_config=to_dict(cfg))

    # make it indistinguishable from a downloaded checkpoint
    for comp in ("unet", "vae", "text_encoder"):
        (tmp_path / "ckpt" / comp / "params.npz").unlink()
    index = json.loads((tmp_path / "ckpt" / "model_index.json").read_text())
    del index["model_config"]
    (tmp_path / "ckpt" / "model_index.json").write_text(json.dumps(index))

    models, loaded, model_cfg = load_checkpoint_models(tmp_path / "ckpt")
    assert attn_dims(model_cfg, 64) == attn_dims(cfg, 64)
    assert model_cfg.use_linear_projection == cfg.use_linear_projection
    assert model_cfg.text_layers == cfg.text_layers
    assert model_cfg.prediction_type == cfg.prediction_type
    for comp in ("unet", "vae", "text"):
        want = sorted(EX._leaves(params[comp]))
        got = sorted(EX._leaves(loaded[comp]))
        assert [p for p, _ in want] == [p for p, _ in got], comp
        for (p1, a), (_, b) in zip(want, got):
            np.testing.assert_allclose(a, b, atol=1e-6, err_msg=f"{comp}:{p1}")


def test_mismatched_checkpoint_rejected(tmp_path):
    """A checkpoint whose config describes a different architecture than its
    weights must raise, not silently build a wrong model (SDXL-style configs
    are refused outright at the transformer-depth check)."""
    from dcr_tpu.core.checkpoint import (_uniform_transformer_layers,
                                         export_hf_layout)
    from dcr_tpu.core.config import TrainConfig, to_dict
    from dcr_tpu.diffusion.trainer import build_models
    from dcr_tpu.sampling.pipeline import load_checkpoint_models

    with pytest.raises(ValueError, match="SDXL"):
        _uniform_transformer_layers({"transformer_layers_per_block": [1, 2, 10]})

    cfg = ModelConfig.tiny()
    tcfg = TrainConfig()
    tcfg.model = cfg
    _, params = build_models(tcfg, jax.random.key(0))
    export_hf_layout(tmp_path / "ckpt", unet=params["unet"], vae=params["vae"],
                     text_encoder=params["text"],
                     scheduler_config={"num_train_timesteps": 1000},
                     model_config=to_dict(cfg))
    # corrupt the stored config: claims wider channels than the weights have
    index = json.loads((tmp_path / "ckpt" / "model_index.json").read_text())
    index["model_config"]["block_out_channels"] = [64, 128]
    (tmp_path / "ckpt" / "model_index.json").write_text(json.dumps(index))
    with pytest.raises(ValueError, match="does not match the architecture"):
        load_checkpoint_models(tmp_path / "ckpt")

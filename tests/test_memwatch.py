"""dcr-hbm tests: memory observability — static accounting, live telemetry,
OOM forensics, and the manifest memory budget.

Fast tier: pure-logic + tiny-compile units — memory_block extraction from a
real compiled program, the shared cost_analysis FLOPs helper, the
DCR_MEMWATCH_FAKE-driven stats/gauge/span paths (the CPU backend reports no
memory_stats, which is itself asserted), the ``oom`` fault kind, the
enriched oom_abort dump, the best-effort memory snapshot on EVERY
flight-recorder dump, the serve memory-budget admission check, and the
compile-manifest memory-budget diff (injected regression -> readable
failure; tolerance; shrinkage and version-skew never fail).

Slow tier (CI ``memory-budget`` job): a real trainer CLI subprocess with an
injected ``oom@step=N`` exits 85 leaving a memory-enriched flight-recorder
dump; a 2-worker fleet with ``oom@batch=0&rank=0`` requeues the dead
worker's in-flight requests with zero drops and responses bit-identical to
an uninjected fleet, with the typed dump present in the fleet dir.
"""

import json

import pytest

from dcr_tpu.core import tracing
from dcr_tpu.obs import memwatch
from dcr_tpu.utils import faults

FAKE = json.dumps({"bytes_in_use": 1000, "peak_bytes_in_use": 1500,
                   "bytes_limit": 10_000})


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv(memwatch.FAKE_ENV, raising=False)
    tracing.reset_for_tests()
    memwatch.reset_for_tests()
    faults.clear()
    yield
    tracing.reset_for_tests()
    memwatch.reset_for_tests()
    faults.clear()


# ---------------------------------------------------------------------------
# static accounting
# ---------------------------------------------------------------------------

def _toy_compiled():
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda x: x @ x)
    return fn.lower(jax.ShapeDtypeStruct((64, 64), jnp.float32)).compile()


@pytest.mark.fast
def test_memory_block_of_real_compiled_program(cpu_devices):
    mem = memwatch.memory_block(_toy_compiled())
    assert mem is not None
    # 64x64 float32 in and out: the byte accounting is exact, not heuristic
    assert mem["argument_bytes"] == 64 * 64 * 4
    assert mem["output_bytes"] == 64 * 64 * 4
    assert mem["total_bytes"] >= mem["argument_bytes"] + mem["output_bytes"]
    assert mem["flops"] > 0  # cost_analysis rides along


@pytest.mark.fast
def test_memory_block_degrades_to_none():
    class NoAnalysis:
        def memory_analysis(self):
            return None

    class Broken:
        def memory_analysis(self):
            raise RuntimeError("backend says no")

    assert memwatch.memory_block(NoAnalysis()) is None
    assert memwatch.memory_block(Broken()) is None


@pytest.mark.fast
def test_flops_helper_handles_every_analysis_shape():
    assert memwatch.flops_of_analysis({"flops": 12.0}) == 12.0
    assert memwatch.flops_of_analysis([{"flops": 7.0}, {"flops": 9.0}]) == 7.0
    assert memwatch.flops_of_analysis(None) == 0.0
    assert memwatch.flops_of_analysis([]) == 0.0
    assert memwatch.flops_of_analysis({}) == 0.0

    class NoCost:
        def cost_analysis(self):
            raise RuntimeError("nope")

    assert memwatch.flops_of_compiled(NoCost()) == 0.0


@pytest.mark.fast
def test_profiling_flops_routes_through_shared_helper(cpu_devices):
    import jax
    import jax.numpy as jnp

    from dcr_tpu.utils.profiling import flops_of_jitted

    fn = jax.jit(lambda x: x @ x)
    aval = jax.ShapeDtypeStruct((16, 16), jnp.float32)
    flops = flops_of_jitted(fn, aval)
    assert flops == memwatch.flops_of_compiled(fn.lower(aval).compile())
    assert flops > 0


# ---------------------------------------------------------------------------
# live telemetry: stats, gauges, sampler, span attrs
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_cpu_backend_reports_no_stats(cpu_devices):
    # the env fact the graceful no-ops exist for; if a future jaxlib grows
    # CPU memory_stats this test tells us the no-op paths went live
    assert memwatch.device_memory_stats() is None
    assert memwatch.peak_bytes() is None
    assert memwatch.remaining_device_bytes() is None
    assert memwatch.start_sampler() is False


@pytest.mark.fast
def test_fake_env_stats_and_gauges(monkeypatch):
    monkeypatch.setenv(memwatch.FAKE_ENV, FAKE)
    stats = memwatch.device_memory_stats()
    assert stats == {"bytes_in_use": 1000, "peak_bytes": 1500,
                     "bytes_limit": 10_000}
    assert memwatch.peak_bytes() == 1500
    assert memwatch.remaining_device_bytes() == 9000
    assert memwatch.update_memory_gauges() == stats
    text = tracing.registry().prometheus_text()
    assert "dcr_device_mem_in_use_bytes 1000" in text
    assert "dcr_device_mem_peak_bytes 1500" in text
    assert "dcr_device_mem_limit_bytes 10000" in text


@pytest.mark.fast
def test_fake_env_bad_json_is_loud_not_fatal(monkeypatch):
    monkeypatch.setenv(memwatch.FAKE_ENV, "{not json")
    assert memwatch.device_memory_stats() is None


@pytest.mark.fast
def test_sampler_runs_on_stats_backends(monkeypatch):
    monkeypatch.setenv(memwatch.FAKE_ENV, FAKE)
    sampler = memwatch.MemorySampler(period_s=0.1)
    try:
        assert sampler.start() is True
        assert sampler.active
        assert tracing.registry().gauge("device_mem/in_use_bytes").value \
            == 1000
    finally:
        sampler.stop()


@pytest.mark.fast
def test_span_hbm_attrs_present_with_stats_absent_without(monkeypatch,
                                                          cpu_devices):
    with tracing.span("serve/device_step") as sp, memwatch.span_hbm(sp):
        pass
    assert "hbm_peak" not in tracing.flight_records()[-1]["args"]
    monkeypatch.setenv(memwatch.FAKE_ENV, FAKE)
    with tracing.span("serve/device_step") as sp, memwatch.span_hbm(sp):
        pass
    args = tracing.flight_records()[-1]["args"]
    assert args["hbm_peak"] == 1500 and args["hbm_delta"] == 0


# ---------------------------------------------------------------------------
# live-surface registry + aot_compile capture
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_live_surface_registry_and_estimates():
    memwatch.note_surface("serve/batch_sampler", "k1",
                          {"temp_bytes": 100, "output_bytes": 50,
                           "generated_code_bytes": 10, "argument_bytes": 999})
    memwatch.note_surface("serve/batch_sampler", "k2", {"temp_bytes": 400})
    memwatch.note_surface("train/step", "k3", {"temp_bytes": 1000})
    # estimate = max non-argument footprint within the family (arguments are
    # the shared params, not a per-program cost)
    assert memwatch.estimate_surface_bytes("serve/batch_sampler") == 400
    assert memwatch.estimate_surface_bytes("eval/") is None
    assert memwatch.resident_program_bytes() == 160 + 400 + 1000


@pytest.mark.fast
def test_aot_compile_captures_surface_memory(cpu_devices):
    import jax
    import jax.numpy as jnp

    from dcr_tpu.core import warmcache

    res = warmcache.aot_compile(
        "toy/surface", jax.jit(lambda x: x @ x),
        (jax.ShapeDtypeStruct((32, 32), jnp.float32),))
    assert res.memory is not None
    assert res.memory["argument_bytes"] == 32 * 32 * 4
    foot = memwatch.live_footprints()
    assert any(k.startswith("toy/surface@") for k in foot)
    events = [r for r in tracing.flight_records()
              if r["name"] == "memwatch/surface_memory"]
    assert events and events[-1]["args"]["surface"] == "toy/surface"
    assert events[-1]["args"]["argument_bytes"] == 32 * 32 * 4


# ---------------------------------------------------------------------------
# OOM detection, fault kind, enriched dump
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_is_oom_error_classification():
    assert memwatch.is_oom_error(memwatch.InjectedOom("here"))
    assert memwatch.is_oom_error(
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating "
                     "13529146368 bytes"))
    assert memwatch.is_oom_error(
        RuntimeError("XlaRuntimeError: Allocator ran out of memory: "
                     "OOM when allocating tensor"))
    assert memwatch.is_oom_error(MemoryError())
    assert not memwatch.is_oom_error(ValueError("shape mismatch"))
    assert not memwatch.is_oom_error(FloatingPointError("nan loss"))


@pytest.mark.fast
def test_oom_fault_kind_parses_and_fires():
    faults.install("oom@step=2")
    assert not faults.fire("oom", step=1)
    assert faults.fire("oom", step=2)
    assert not faults.fire("oom", step=2)   # single-shot by default
    faults.install("oom@batch=1")
    assert not faults.fire("oom", batch=0)
    assert faults.fire("oom", batch=1)


@pytest.mark.fast
def test_oom_abort_dump_is_enriched_and_exits_85(tmp_path, monkeypatch):
    from dcr_tpu.core.coordination import EXIT_OOM

    assert EXIT_OOM == 85
    monkeypatch.setenv(memwatch.FAKE_ENV, FAKE)
    tracing.configure(tmp_path, rank=0)
    memwatch.note_surface("serve/batch_sampler", "k1",
                          {"temp_bytes": 123, "total_bytes": 456})
    codes: list = []
    memwatch.oom_abort("serve batch 0", memwatch.InjectedOom("serve batch 0"),
                       buckets=[(16, 2, 7.5, "ddim", 0.0)],
                       exit_fn=codes.append)
    assert codes == [85]
    doc = json.loads((tmp_path / "flightrec_0.json").read_text())
    assert doc["reason"].startswith("oom:")
    # OOM-specific fields under "oom"; the memory snapshot itself rides the
    # top-level "memory" key every dump carries (computed once, not twice)
    assert doc["oom"]["compiled_buckets"] == [[16, 2, 7.5, "ddim", 0.0]]
    assert doc["oom"]["where"] == "serve batch 0"
    assert doc["memory"]["device_memory_stats"]["bytes_in_use"] == 1000
    assert "serve/batch_sampler@k1" in doc["memory"]["live_surfaces"]
    # the registry snapshot and span ring ride along as on every fatal path
    assert "registry" in doc and "records" in doc


@pytest.mark.fast
def test_every_flight_rec_dump_carries_memory_snapshot(tmp_path, monkeypatch):
    # the satellite: NaN abort / hang / preempt / excepthook dumps (all go
    # through dump_flight_recorder) now answer "how full was the device"
    monkeypatch.setenv(memwatch.FAKE_ENV, FAKE)
    tracing.configure(tmp_path, rank=0)
    memwatch.note_surface("train/step", "k", {"temp_bytes": 7})
    path = tracing.dump_flight_recorder("nan_abort: step 3 loss nan")
    doc = json.loads(path.read_text())
    assert doc["memory"]["device_memory_stats"]["peak_bytes"] == 1500
    assert "train/step@k" in doc["memory"]["live_surfaces"]


# ---------------------------------------------------------------------------
# serve containment: memory-budget admission
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_memory_budget_admission_check(monkeypatch):
    import types

    from dcr_tpu.serve.queue import GenBucket, MemoryBudgetError
    from dcr_tpu.serve.worker import GenerationService

    stub = types.SimpleNamespace(_admitted_buckets=set(), _samplers={})
    bucket = GenBucket(16, 2, 7.5, "ddim", 0.0)
    # no live sibling surface -> no check (first program is readiness's)
    GenerationService._check_memory_budget(stub, bucket)
    memwatch.note_surface("serve/batch_sampler", "k1",
                          {"temp_bytes": 5000, "output_bytes": 0,
                           "generated_code_bytes": 0})
    # no backend stats (CPU) -> no check
    GenerationService._check_memory_budget(stub, bucket)
    # estimate 5000 > remaining 9000? no -> admits
    monkeypatch.setenv(memwatch.FAKE_ENV, FAKE)
    GenerationService._check_memory_budget(stub, bucket)
    # an admitted-but-uncompiled novel bucket RESERVES its estimate: the
    # second novel bucket needs 2x5000 > 9000 even though live stats have
    # not moved yet (the burst-of-novel-buckets hole)
    other = GenBucket(16, 4, 7.5, "ddim", 0.0)
    stub._admitted_buckets = {other}
    with pytest.raises(MemoryBudgetError):
        GenerationService._check_memory_budget(stub, bucket)
    # once that bucket's program is resident the reservation is released
    # (live stats are then the truth)
    stub._samplers = {other: object()}
    GenerationService._check_memory_budget(stub, bucket)
    # nearly-full device: remaining 100 < estimate 5000 -> typed rejection
    monkeypatch.setenv(memwatch.FAKE_ENV, json.dumps(
        {"bytes_in_use": 9900, "peak_bytes_in_use": 9900,
         "bytes_limit": 10_000}))
    with pytest.raises(MemoryBudgetError):
        GenerationService._check_memory_budget(stub, bucket)
    assert tracing.registry().counter(
        "serve/rejected_memory_budget").value == 2


@pytest.mark.fast
def test_queue_has_bucket_guards_admission_rollback():
    # the worker's rejected-admission rollback (a never-queued novel bucket
    # must not hold a resident-program slot / byte reservation forever)
    # keeps a bucket that a concurrently-queued request still references
    from dcr_tpu.serve.queue import GenBucket, Request, RequestQueue

    q = RequestQueue(4)
    b = GenBucket(16, 2, 7.5, "ddim", 0.0)
    other = GenBucket(16, 4, 7.5, "ddim", 0.0)
    assert not q.has_bucket(b)
    q.submit(Request(prompt="p", seed=0, bucket=b))
    assert q.has_bucket(b) and not q.has_bucket(other)


@pytest.mark.fast
def test_memory_budget_maps_to_typed_503():
    from dcr_tpu.serve.queue import MemoryBudgetError
    from dcr_tpu.serve.server import admission_response

    code, payload, _ = admission_response(MemoryBudgetError("too big"))
    assert code == 503 and payload["error"] == "memory_budget"


@pytest.mark.fast
def test_supervisor_names_oom_exits():
    from dcr_tpu.serve.supervisor import FleetSupervisor

    assert "EXIT_OOM" in FleetSupervisor._rc_reason(85)
    assert FleetSupervisor._rc_reason(1) == "process exited rc=1"


# ---------------------------------------------------------------------------
# manifest memory budget
# ---------------------------------------------------------------------------

def _entry_with_memory(temp=1_000_000, arg=2_000_000, flops=5e9) -> dict:
    return {
        "surface": "toy/surface", "variant": "default", "static_config": {},
        "donate_argnums": [], "donated_inputs": 0,
        "in_avals": {"leaves": 1, "digest": "d", "detail": []},
        "out_avals": {"leaves": 1, "digest": "d", "detail": []},
        "lowered_sha256": "abc",
        "memory": {"argument_bytes": arg, "output_bytes": 1024,
                   "temp_bytes": temp, "generated_code_bytes": 0,
                   "total_bytes": arg + 1024 + temp, "flops": flops},
    }


def _wrap(entry) -> dict:
    import jax

    return {"version": 1, "jax_version": jax.__version__,
            "entries": {"toy/surface@default": entry}}


@pytest.mark.fast
def test_manifest_memory_regression_is_readable_failure():
    from tools.check.manifest import diff_manifests

    old = _wrap(_entry_with_memory(temp=1_000_000))
    new = _wrap(_entry_with_memory(temp=2_000_000))
    diff = "\n".join(diff_manifests(old, new))
    assert "memory.temp_bytes" in diff
    assert "budget" in diff and "OOM" in diff
    assert "toy/surface@default" in diff
    # total_bytes moved with it
    assert "memory.total_bytes" in diff


@pytest.mark.fast
def test_manifest_memory_tolerance_and_shrinkage():
    from tools.check.manifest import diff_manifests

    old = _wrap(_entry_with_memory(temp=1_000_000))
    within = _wrap(_entry_with_memory(temp=1_050_000))   # +5% < 10% budget
    assert diff_manifests(old, within) == []
    over = _wrap(_entry_with_memory(temp=1_200_000))     # +20% > 10%
    assert diff_manifests(old, over)
    # a looser configured tolerance admits the same growth
    assert diff_manifests(old, over, memory_tolerance=0.5) == []
    # shrinkage never fails (a smaller footprint needs no sign-off)
    smaller = _wrap(_entry_with_memory(temp=100_000))
    assert diff_manifests(old, smaller) == []


@pytest.mark.fast
def test_manifest_memory_skips_on_version_skew_and_absent_fields():
    from tools.check.manifest import diff_manifests

    old = _wrap(_entry_with_memory(temp=1_000_000))
    old["jax_version"] = "0.0.0-other"
    new = _wrap(_entry_with_memory(temp=9_000_000))
    # different toolchain: memory budgets (like HLO digests) not compared
    assert diff_manifests(old, new) == []
    # pre-dcr-hbm manifest (no memory block): present-field degrade
    legacy = _wrap(_entry_with_memory())
    del legacy["entries"]["toy/surface@default"]["memory"]
    assert diff_manifests(legacy, _wrap(_entry_with_memory())) == []


@pytest.mark.fast
def test_manifest_flops_regression_fails_budget():
    from tools.check.manifest import diff_manifests

    old = _wrap(_entry_with_memory(flops=5e9))
    new = _wrap(_entry_with_memory(flops=7e9))
    diff = "\n".join(diff_manifests(old, new))
    assert "memory.flops" in diff


@pytest.mark.fast
def test_checked_in_manifest_carries_memory_blocks():
    import pathlib

    data = json.loads((pathlib.Path(__file__).parent.parent
                       / "compile_manifest.json").read_text())
    for key, entry in data["entries"].items():
        mem = entry.get("memory")
        assert mem, f"{key} has no banked memory block"
        assert mem["argument_bytes"] > 0, key
        assert "total_bytes" in mem, key


@pytest.mark.fast
def test_fingerprint_banks_memory_block(cpu_devices):
    import jax
    import jax.numpy as jnp

    from tools.check.manifest import fingerprint

    aval = jax.ShapeDtypeStruct((8, 8), jnp.float32)
    entry = fingerprint("toy/surface@default", jax.jit(lambda x: x + 1),
                        (aval,), static_config={}, surface="toy/surface")
    assert entry["memory"]["argument_bytes"] == 8 * 8 * 4
    assert entry["memory"]["output_bytes"] == 8 * 8 * 4


# ---------------------------------------------------------------------------
# trace_report "Memory" section
# ---------------------------------------------------------------------------

def _rec(name, ph="X", ts=0, dur=10, args=None, rid=1):
    rec = {"ph": ph, "name": name, "id": rid, "ts": ts, "pid": 0, "tid": 1,
           "tname": "t", "args": args or {}, "parent": None,
           "_proc": 0, "_plabel": "trace.jsonl"}
    if ph == "X":
        rec["dur"] = dur
    return rec


@pytest.mark.fast
def test_trace_report_memory_section_arithmetic():
    from tools.trace_report import memory_summary

    records = [
        _rec("train/step", ts=10, args={"hbm_peak": 100, "hbm_delta": 5}),
        _rec("train/step", ts=20, args={"hbm_peak": 300, "hbm_delta": -2}),
        _rec("serve/device_step", ts=30,
             args={"hbm_peak": 200, "hbm_delta": 7}),
        _rec("memwatch/surface_memory", ph="i", ts=5,
             args={"surface": "serve/batch_sampler", "key": "abcdef012345",
                   "temp_bytes": 900, "argument_bytes": 10,
                   "output_bytes": 20, "total_bytes": 930}),
        _rec("memwatch/surface_memory", ph="i", ts=6,
             args={"surface": "train/step", "key": "ffff",
                   "temp_bytes": 100, "total_bytes": 100}),
        _rec("train/data_wait", ts=40),   # no hbm attrs: not sampled
    ]
    mem = memory_summary(records)
    assert mem["sampled_spans"] == 3
    assert mem["peak_bytes"] == 300
    steps = mem["resident_delta_by_stage"]["train/step"]
    assert steps == {"count": 2, "delta_bytes": 3, "peak_bytes": 300}
    assert mem["resident_delta_by_stage"]["serve/device_step"][
        "delta_bytes"] == 7
    assert [t["peak_bytes"] for t in mem["peak_timeline"]] == [100, 300, 200]
    top = mem["top_surfaces_by_temp_bytes"]
    assert top[0]["surface"].startswith("serve/batch_sampler@abcdef01")
    assert top[0]["temp_bytes"] == 900 and top[1]["temp_bytes"] == 100


@pytest.mark.fast
def test_trace_report_memory_section_absent_without_data_and_renders():
    from pathlib import Path

    from tools.trace_report import memory_summary, render_text, summarize

    assert memory_summary([_rec("train/step")]) is None
    summary = summarize([
        _rec("train/step", args={"hbm_peak": 100, "hbm_delta": 5})], {})
    text = render_text(summary, Path("x"))
    assert "memory: peak 100 bytes" in text
    # a memory-less summary renders with no memory section
    no_mem = summarize([_rec("train/step")], {})
    assert no_mem["memory"] is None
    assert "memory: peak" not in render_text(no_mem, Path("x"))


@pytest.mark.fast
def test_trace_schema_accepts_surface_memory_events(tmp_path):
    # a real emitted memwatch/surface_memory event validates against the
    # checked-in schema (the observability job gates on this)
    from tools import trace_report

    tracing.configure(tmp_path, rank=0)
    tracing.event("memwatch/surface_memory", surface="toy/s", key="k",
                  attrs={"temp_bytes": 1})
    schema = trace_report.load_schema()
    records, errors = trace_report.load_trace(tmp_path, schema)
    assert errors == [] and len(records) == 1


# ---------------------------------------------------------------------------
# slow e2e: injected OOM through the real CLIs
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_trainer_oom_exits_85_with_enriched_dump(tmp_path, monkeypatch,
                                                 cpu_devices):
    """oom@step=3 in a real `dcr_tpu.cli.train` subprocess: typed exit 85
    (not a stack-trace exit 1), and the flight-recorder dump carries the
    oom section with the (faked) device stats and live-surface
    footprints."""
    import numpy as np
    from PIL import Image

    from dcr_tpu.core.config import (DataConfig, ModelConfig, OptimConfig,
                                     TrainConfig)
    from tests.test_fault_injection import _run_cli

    rng = np.random.default_rng(0)
    for cls in ["c0", "c1"]:
        d = tmp_path / "data" / cls
        d.mkdir(parents=True)
        for i in range(8):
            Image.fromarray(
                rng.integers(0, 255, (20, 20, 3), np.uint8)).save(
                d / f"{i}.png")
    cfg = TrainConfig(
        output_dir=str(tmp_path / "run"), seed=0, train_batch_size=2,
        max_train_steps=6, num_train_epochs=20, mixed_precision="no",
        save_steps=1000, modelsavesteps=1000, log_every=1,
        model=ModelConfig.tiny(),
        data=DataConfig(train_data_dir=str(tmp_path / "data"), resolution=16,
                        class_prompt="nolevel", num_workers=2, seed=0),
        optim=OptimConfig(learning_rate=1e-4, lr_scheduler="constant",
                          lr_warmup_steps=0))
    monkeypatch.setenv(memwatch.FAKE_ENV, FAKE)
    proc, out = _run_cli(cfg, tmp_path / "cfg.json", dcr_faults="oom@step=3")
    assert proc.returncode == 85, out[-4000:]
    dump = tmp_path / "run" / "flightrec_0.json"
    assert dump.exists(), out[-4000:]
    doc = json.loads(dump.read_text())
    assert doc["reason"].startswith("oom:"), doc["reason"]
    assert doc["memory"]["device_memory_stats"]["bytes_in_use"] == 1000
    # the injected fault is visible in the record (not a silent real OOM)
    assert "injected" in doc["oom"]["error"]
    assert doc["oom"]["where"].startswith("train step")


@pytest.mark.slow
def test_fleet_oom_requeues_zero_drops_bit_identical(tmp_path, monkeypatch,
                                                     cpu_devices):
    """Acceptance: 2 workers, worker 0 killed by an injected oom on every
    batch it touches (exit 85) — its journaled in-flight requests requeue
    onto worker 1, every accepted request completes bit-identical to an
    uninjected fleet with zero drops, and the worker left a memory-enriched
    oom dump in the fleet dir."""
    from tests.test_fleet import _run_fleet
    from tests.test_serve import _export_tiny_ckpt

    monkeypatch.setenv(memwatch.FAKE_ENV, FAKE)
    ckpt = _export_tiny_ckpt(tmp_path)

    clean, clean_counts, _ = _run_fleet(tmp_path, ckpt, "clean")
    assert clean_counts["dropped"] == 0 and clean_counts["failed"] == 0

    chaos, chaos_counts, status = _run_fleet(
        tmp_path, ckpt, "oom", faults="oom@batch=0&rank=0")
    assert chaos_counts["dropped"] == 0, chaos_counts
    assert chaos_counts["failed"] == 0, chaos_counts
    assert chaos_counts["accepted"] == 8 and chaos_counts["acked"] == 8
    assert chaos_counts["requeued_total"] >= 1, chaos_counts
    assert status["fleet"].get("workers_lost", 0) >= 1, status["fleet"]
    # bit-identical: which worker (or incarnation) rendered is invisible
    assert set(chaos) == set(clean)
    for job in clean:
        assert chaos[job] == clean[job], f"response diverged for {job}"
    # the typed post-mortem: worker 0's dump names oom and carries the
    # memory snapshot (fake stats propagate into the worker env; fleet
    # workers trace under <fleet.dir>/worker_<i>/)
    dump = tmp_path / "fleet_oom" / "worker_0" / "flightrec_w0_0.json"
    assert dump.exists()
    doc = json.loads(dump.read_text())
    assert doc["reason"].startswith("oom:"), doc["reason"]
    assert doc["memory"]["device_memory_stats"]["bytes_in_use"] == 1000
    assert doc["oom"]["compiled_buckets"], "resident bucket set missing"
    # the worker's resident serve programs are accounted in the snapshot
    assert any(k.startswith("serve/")
               for k in doc["memory"]["live_surfaces"])

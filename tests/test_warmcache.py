"""dcr-warm tests: persistent executable cache + warm-start readiness.

Fast tier — cache-poisoning robustness on trivial programs (no model
compiles): truncated entries, bit-flipped payloads, wrong-fingerprint
entries, same-key garbage payloads, the deterministic ``cache_corrupt``
fault kind, concurrent writers racing on one cache directory, the
``jax.export`` fallback tier, and the warm-start manifest. Every poisoning
case must recompile successfully, bump a ``warmcache/*`` counter, and
quarantine the bad entry — no crash, no wrong program.

Slow tier — the crash-to-ready acceptance paths: a trainer-shaped train
step (donated state + PRNG key + loader-batch pytree) round-trips the cache
bit-identically; a real ``dcr-serve`` subprocess restarts against a
populated cache with /healthz readiness gating and ZERO compiles
(trace_report-verified); a fleet worker SIGKILLed with a populated cache
respawns to ready with zero recompile spans and bit-identical responses.
"""

import json
import os
import signal
import struct
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dcr_tpu.core import resilience as R
from dcr_tpu.core import tracing, warmcache
from dcr_tpu.utils import faults


def _toy_fn():
    return jax.jit(lambda x, y: x * 2.0 + y)


def _toy_args():
    return (jnp.ones((4,), jnp.float32), jnp.full((4,), 3.0, jnp.float32))


def _aot(cache, k=1, surface="test/toy"):
    return warmcache.aot_compile(surface, _toy_fn(), _toy_args(),
                                 static_config={"k": k}, cache=cache)


def _counters():
    return {k: v for k, v in R.counters().items() if k.startswith("warmcache")}


def _parse_entry(blob: bytes):
    head = len(warmcache.MAGIC) + warmcache._LEN.size
    (mlen,) = warmcache._LEN.unpack(blob[len(warmcache.MAGIC):head])
    meta = json.loads(blob[head:head + mlen].decode())
    return meta, blob[head + mlen:]


def _build_entry(meta: dict, payload: bytes) -> bytes:
    mb = json.dumps(meta, sort_keys=True).encode()
    return warmcache.MAGIC + warmcache._LEN.pack(len(mb)) + mb + payload


# ---------------------------------------------------------------------------
# round-trip + keying
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_roundtrip_store_then_hit(tmp_path):
    cache = warmcache.WarmCache(tmp_path)
    r1 = _aot(cache)
    assert r1.source == "compiled" and r1.entry is not None and r1.entry.exists()
    out1 = np.asarray(r1.fn(*_toy_args()))
    # a fresh cache instance (= a new process incarnation) warm-loads
    r2 = _aot(warmcache.WarmCache(tmp_path))
    assert r2.source == "cache" and r2.key == r1.key
    assert np.array_equal(out1, np.asarray(r2.fn(*_toy_args())))


@pytest.mark.fast
def test_static_config_and_topology_change_the_key(tmp_path):
    cache = warmcache.WarmCache(tmp_path)
    r1 = _aot(cache, k=1)
    r2 = _aot(cache, k=2)
    assert r2.source == "compiled" and r2.key != r1.key
    # a version/topology-skewed fingerprint is a DIFFERENT key: a skewed
    # entry can never be found under the current program's key, so skew
    # degrades to a plain miss + recompile by construction
    fn = _toy_fn()
    lowered = fn.lower(*warmcache.abstract_args(_toy_args()))
    fp = warmcache.program_fingerprint("test/toy", lowered,
                                       warmcache.abstract_args(_toy_args()),
                                       static_config={"k": 1})
    skewed = dict(fp, topology=dict(fp["topology"], jaxlib="0.0.1"))
    assert warmcache.entry_key(skewed) != warmcache.entry_key(fp)


@pytest.mark.fast
def test_aot_without_cache_still_compiles(tmp_path):
    r = warmcache.aot_compile("test/toy", _toy_fn(), _toy_args(),
                              static_config={}, cache=None)
    assert r.source == "compiled" and r.entry is None
    assert np.array_equal(np.asarray(r.fn(*_toy_args())),
                          np.asarray(_toy_fn()(*_toy_args())))


# ---------------------------------------------------------------------------
# cache poisoning: every case recompiles, counts, quarantines
# ---------------------------------------------------------------------------

def _assert_poison_recovery(tmp_path, damage, kind):
    """Write a valid entry, apply ``damage(path)``, reload: recompile OK,
    ``warmcache/<kind>`` bumped, entry quarantined out of the key space."""
    cache = warmcache.WarmCache(tmp_path)
    r1 = _aot(cache)
    expected = np.asarray(r1.fn(*_toy_args()))
    damage(r1.entry)
    before = _counters().get(f"warmcache/{kind}", 0)
    r2 = _aot(warmcache.WarmCache(tmp_path))
    assert r2.source == "compiled", f"poisoned entry must recompile ({kind})"
    assert np.array_equal(expected, np.asarray(r2.fn(*_toy_args())))
    assert _counters().get(f"warmcache/{kind}", 0) == before + 1
    quarantined = list(tmp_path.glob("*.quarantined.*"))
    assert quarantined, "bad entry not quarantined"
    # self-healing: the recompile re-stored a GOOD entry at the key, so the
    # next incarnation warm-loads — and what it loads is the fresh bytes,
    # not the damaged ones (those live under the quarantine name)
    r3 = _aot(warmcache.WarmCache(tmp_path))
    assert r3.source == "cache"
    assert np.array_equal(expected, np.asarray(r3.fn(*_toy_args())))


@pytest.mark.fast
def test_truncated_entry_recovers(tmp_path):
    _assert_poison_recovery(
        tmp_path, lambda p: p.write_bytes(p.read_bytes()[:23]),
        "cache_truncated")


@pytest.mark.fast
def test_truncated_payload_recovers(tmp_path):
    def damage(p):
        blob = p.read_bytes()
        p.write_bytes(blob[:-64])      # header intact, payload short
    _assert_poison_recovery(tmp_path, damage, "cache_truncated")


@pytest.mark.fast
def test_bitflipped_payload_recovers(tmp_path):
    def damage(p):
        blob = bytearray(p.read_bytes())
        blob[-10] ^= 0xFF
        p.write_bytes(bytes(blob))
    _assert_poison_recovery(tmp_path, damage, "cache_corrupt")


@pytest.mark.fast
def test_bad_magic_recovers(tmp_path):
    def damage(p):
        blob = bytearray(p.read_bytes())
        blob[0] ^= 0xFF
        p.write_bytes(bytes(blob))
    _assert_poison_recovery(tmp_path, damage, "cache_corrupt")


@pytest.mark.fast
def test_wrong_fingerprint_entry_recovers(tmp_path):
    cache = warmcache.WarmCache(tmp_path)
    r1 = _aot(cache, k=1)
    r2 = _aot(cache, k=2)

    def damage(path):
        # an entry that is internally VALID (magic, sha, lengths all pass)
        # but is a different program: only the fingerprint check stands
        # between it and executing the wrong executable
        path.write_bytes(r2.entry.read_bytes())
    _assert_poison_recovery(tmp_path, damage, "fingerprint_mismatch")


@pytest.mark.fast
def test_same_key_garbage_payload_recovers(tmp_path):
    def damage(path):
        # meta fully consistent (sha/len recomputed for the garbage), so
        # every integrity check passes and deserialization itself must fail
        # safely — the version-skew-inside-a-same-key-entry case
        meta, _ = _parse_entry(path.read_bytes())
        garbage = b"\x80\x05not a pickled executable"
        meta["payload_len"] = len(garbage)
        meta["payload_sha256"] = warmcache._sha(garbage)
        path.write_bytes(_build_entry(meta, garbage))
    _assert_poison_recovery(tmp_path, damage, "load_error")


@pytest.mark.fast
def test_cache_corrupt_fault_kind_is_deterministic(tmp_path):
    """The DCR_FAULTS hook drives the full corrupt path in CI: damage is
    injected at a deterministic load index, and recovery is the REAL
    quarantine + recompile machinery, not a simulation."""
    cache = warmcache.WarmCache(tmp_path)
    r1 = _aot(cache)
    expected = np.asarray(r1.fn(*_toy_args()))
    fresh = warmcache.WarmCache(tmp_path)
    faults.install("cache_corrupt@load=0")
    try:
        before = _counters().get("warmcache/cache_corrupt", 0)
        r2 = _aot(fresh)
        assert r2.source == "compiled"
        assert np.array_equal(expected, np.asarray(r2.fn(*_toy_args())))
        assert _counters().get("warmcache/cache_corrupt", 0) == before + 1
        # the spec fired once; the re-stored entry loads clean afterwards
        r3 = _aot(fresh)
        assert r3.source == "cache"
    finally:
        faults.clear()


@pytest.mark.fast
def test_thread_race_on_one_cache_dir(tmp_path):
    """Two writers racing the same key: both must succeed (atomic replace,
    last writer wins) and the surviving entry must verify and load."""
    barrier = threading.Barrier(2)
    results = [None, None]

    def run(i):
        cache = warmcache.WarmCache(tmp_path)
        barrier.wait()
        r = _aot(cache)
        results[i] = np.asarray(r.fn(*_toy_args()))

    threads = [threading.Thread(target=run, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert all(r is not None for r in results)
    assert np.array_equal(results[0], results[1])
    r = _aot(warmcache.WarmCache(tmp_path))
    assert r.source == "cache"
    assert np.array_equal(results[0], np.asarray(r.fn(*_toy_args())))


_RACE_SCRIPT = """
import json, sys
import numpy as np
import jax, jax.numpy as jnp
from dcr_tpu.core import warmcache

cache = warmcache.WarmCache(sys.argv[1])
fn = jax.jit(lambda x: x * 3.0 + 1.0)
res = warmcache.aot_compile("race/toy", fn, (jnp.ones((8,), jnp.float32),),
                            static_config={}, cache=cache)
out = np.asarray(res.fn(np.ones((8,), np.float32)))
print(json.dumps({"source": res.source, "sum": float(out.sum())}))
"""


def test_two_processes_racing_one_cache_dir(tmp_path):
    """The real fleet shape: two separate PROCESSES compile/store the same
    surface into one shared cache dir concurrently. Both must produce the
    correct result and leave a loadable entry."""
    repo = Path(__file__).parent.parent
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=str(repo) + os.pathsep + os.environ.get("PYTHONPATH", ""))
    procs = [subprocess.Popen([sys.executable, "-c", _RACE_SCRIPT,
                               str(tmp_path)],
                              env=env, cwd=repo, stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
             for _ in range(2)]
    docs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"racer failed: {err[-2000:]}"
        docs.append(json.loads(out.strip().splitlines()[-1]))
    assert all(d["sum"] == 32.0 for d in docs), docs
    # whoever lost the race, the surviving entry must be valid: a third
    # incarnation loads it
    out = subprocess.run([sys.executable, "-c", _RACE_SCRIPT, str(tmp_path)],
                         env=env, cwd=repo, capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stderr[-2000:]
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    assert doc == {"source": "cache", "sum": 32.0}


@pytest.mark.fast
def test_export_tier_roundtrip(tmp_path, monkeypatch):
    """The lowered-StableHLO fallback tier (jax.export + compile-on-load)
    stores and loads correctly when forced — the path jaxlibs with fragile
    executable deserialization take."""
    monkeypatch.setenv("DCR_WARMCACHE_TIER", warmcache.TIER_EXPORT)
    cache = warmcache.WarmCache(tmp_path)
    r1 = _aot(cache)
    assert r1.source == "compiled"
    meta, _ = _parse_entry(r1.entry.read_bytes())
    assert meta["tier"] == warmcache.TIER_EXPORT
    out1 = np.asarray(r1.fn(*_toy_args()))
    r2 = _aot(warmcache.WarmCache(tmp_path))
    assert r2.source == "cache"
    assert np.array_equal(out1, np.asarray(r2.fn(*_toy_args())))
    # the tier lives in entry META, not the key: an executable-tier process
    # loads an export-tier entry transparently (this is what makes the
    # per-entry store degrade — build_payload validation failure — findable)
    monkeypatch.setenv("DCR_WARMCACHE_TIER", warmcache.TIER_EXECUTABLE)
    r3 = _aot(warmcache.WarmCache(tmp_path))
    assert r3.source == "cache" and r3.key == r1.key
    assert np.array_equal(out1, np.asarray(r3.fn(*_toy_args())))


@pytest.mark.fast
def test_guarded_one_way_fallback():
    calls = []

    def fast(*a):
        calls.append("fast")
        raise TypeError("aval mismatch")

    def slow(*a):
        calls.append("slow")
        return 42

    fn = warmcache.guarded(fast, slow, "test/guard")
    assert fn() == 42
    assert fn() == 42
    # one-way: the failing executable is tried exactly once
    assert calls == ["fast", "slow", "slow"]


# ---------------------------------------------------------------------------
# warm-start manifest
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_warm_manifest_is_lru_and_budget_capped(tmp_path):
    """The manifest keeps the most-recently-compiled entries (re-recording
    moves an entry to the tail) and max_entries trims the oldest — so a
    long-lived shared cache dir can never fill every future incarnation's
    resident-program budget with stale history."""
    buckets = [[16, s, 7.5, "ddim", 0.0] for s in range(1, 6)]
    for b in buckets:
        warmcache.update_warm_manifest(tmp_path, [b], max_entries=3)
    assert warmcache.read_warm_manifest(tmp_path) == buckets[2:]
    # re-recording an existing entry refreshes it to the tail
    warmcache.update_warm_manifest(tmp_path, [buckets[2]], max_entries=3)
    assert warmcache.read_warm_manifest(tmp_path) == [
        buckets[3], buckets[4], buckets[2]]


@pytest.mark.fast
def test_non_json_native_static_config_roundtrips(tmp_path):
    """A tuple (JSON-lossy: round-trips as a list) in static_config must not
    defeat the cache — the fingerprint is canonicalized once, so the second
    incarnation HITS instead of quarantining the entry it just wrote."""
    cache = warmcache.WarmCache(tmp_path)
    static = {"shape": (16, 2), "mode": "x"}
    r1 = warmcache.aot_compile("test/toy", _toy_fn(), _toy_args(),
                               static_config=static, cache=cache)
    assert r1.source == "compiled" and r1.entry is not None
    r2 = warmcache.aot_compile("test/toy", _toy_fn(), _toy_args(),
                               static_config=static,
                               cache=warmcache.WarmCache(tmp_path))
    assert r2.source == "cache"
    assert not list(tmp_path.glob("*.quarantined.*"))


@pytest.mark.fast
def test_warm_manifest_union_and_corrupt_quarantine(tmp_path):
    b1 = [16, 2, 7.5, "ddim", 0.0]
    b2 = [32, 4, 5.0, "ddpm", 0.1]
    warmcache.update_warm_manifest(tmp_path, [b1])
    warmcache.update_warm_manifest(tmp_path, [b1, b2])   # dedup + union
    assert warmcache.read_warm_manifest(tmp_path) == [b1, b2]
    # corrupt manifest: quarantined, read degrades to empty, counter bumped
    path = tmp_path / warmcache.MANIFEST_NAME
    path.write_text("{not json")
    before = _counters().get("warmcache/manifest_corrupt", 0)
    assert warmcache.read_warm_manifest(tmp_path) == []
    assert _counters().get("warmcache/manifest_corrupt", 0) == before + 1
    assert list(tmp_path.glob(f"{warmcache.MANIFEST_NAME}.quarantined.*"))
    # and the NEXT update starts a fresh manifest cleanly
    warmcache.update_warm_manifest(tmp_path, [b2])
    assert warmcache.read_warm_manifest(tmp_path) == [b2]


@pytest.mark.fast
def test_trace_report_recompile_budget(tmp_path):
    """--max-compiles counts per (stream, os_pid) incarnation — a cold boot
    and a warm respawn sharing one trace file are budgeted separately — and
    never double-bills a bucket compile's serve/compile event against its
    warmcache/compile span."""
    from tools import trace_report as TR

    recs = [
        {"ph": "i", "name": "serve/compile", "id": 1, "parent": None,
         "ts": 1000, "pid": 0, "tid": 1, "tname": "t",
         "args": {"bucket": "(16, 2)", "os_pid": 100}},
        {"ph": "X", "name": "warmcache/compile", "id": 2, "parent": None,
         "ts": 1000, "dur": 5, "pid": 0, "tid": 1, "tname": "t",
         "args": {"surface": "serve/batch_sampler", "os_pid": 100}},
        {"ph": "X", "name": "warmcache/compile", "id": 3, "parent": None,
         "ts": 2000, "dur": 5, "pid": 0, "tid": 1, "tname": "t",
         "args": {"surface": "serve/encode", "os_pid": 100}},
        {"ph": "X", "name": "warmcache/load", "id": 4, "parent": None,
         "ts": 3000, "dur": 5, "pid": 0, "tid": 1, "tname": "t",
         "args": {"surface": "serve/batch_sampler", "os_pid": 200}},
        # an export-tier entry's compile-on-load is a REAL XLA compile and
        # must count — else a broken executable tier passes --max-compiles 0
        {"ph": "X", "name": "warmcache/load_compile", "id": 5, "parent": None,
         "ts": 4000, "dur": 5, "pid": 0, "tid": 1, "tname": "t",
         "args": {"surface": "serve/encode", "os_pid": 300}},
    ]
    (tmp_path / "trace.jsonl").write_text(
        "\n".join(json.dumps(r) for r in recs) + "\n")
    records, errors, _ = TR.load_fleet([tmp_path], TR.load_schema())
    assert not errors
    counts = TR.compiles_per_incarnation(records)
    # event+span for the same compile counts once; pid 200 only loaded;
    # pid 300's export-tier compile-on-load is billed
    assert counts == {"trace.jsonl@pid100": 2, "trace.jsonl@pid300": 1}
    assert TR.main([str(tmp_path), "--max-compiles", "2"]) == 0
    assert TR.main([str(tmp_path), "--max-compiles", "1"]) == 3
    assert TR.main([str(tmp_path), "--max-compiles", "0"]) == 3


@pytest.mark.fast
def test_fingerprint_fields_cover_the_key_surface():
    fn = _toy_fn()
    avals = warmcache.abstract_args(_toy_args())
    lowered = fn.lower(*avals)
    fp = warmcache.program_fingerprint("test/toy", lowered, avals,
                                       static_config={"k": 1})
    assert fp["surface"] == "test/toy"
    assert fp["static_config"] == {"k": 1}
    assert fp["in_avals"] and fp["out_avals"] and fp["lowered_sha256"]
    topo = fp["topology"]
    assert topo["platform"] and topo["jax"] and topo["jaxlib"]
    assert topo["device_count"] >= 1 and topo["process_count"] >= 1


# ---------------------------------------------------------------------------
# slow: trainer-shaped program round-trip (donation + PRNG key + pytrees)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_train_step_warm_roundtrip_bit_identical(tmp_path, cpu_devices):
    """The train step — donated TrainState, loader-batch dict (incl. the
    jit-unused index leaf), typed PRNG key — survives the cache with
    bit-identical metrics and parameters, using avals constructed exactly
    like Trainer._warm_start does."""
    from dcr_tpu.core import rng as rngmod
    from dcr_tpu.core.config import MeshConfig, ModelConfig, TrainConfig
    from dcr_tpu.diffusion import train as T
    from dcr_tpu.diffusion.trainer import build_models
    from dcr_tpu.parallel import mesh as pmesh

    cfg = TrainConfig(train_batch_size=2, mixed_precision="no")
    cfg.model = ModelConfig.tiny()
    cfg.data.resolution = 16
    models, params = build_models(cfg, jax.random.key(0))
    mesh = pmesh.make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])

    def mkstate():
        p = jax.tree.map(lambda x: jnp.array(np.asarray(x)), params)
        s = T.init_train_state(cfg, models, unet_params=p["unet"],
                               text_params=p["text"], vae_params=p["vae"])
        return T.shard_train_state(s, mesh)

    step = T.make_train_step(cfg, models, mesh)
    key = rngmod.root_key(0)
    rng = np.random.default_rng(0)
    raw = {"pixel_values": rng.standard_normal((2, 16, 16, 3)).astype(np.float32),
           "input_ids": rng.integers(0, 100, (2, 16)).astype(np.int32),
           "index": np.arange(2, dtype=np.int64)}

    ref_state, ref_metrics = step(mkstate(), pmesh.shard_batch(mesh, dict(raw)),
                                  key)

    bs = pmesh.batch_sharding(mesh)
    avals = {
        "pixel_values": jax.ShapeDtypeStruct((2, 16, 16, 3), jnp.float32,
                                             sharding=bs),
        "input_ids": jax.ShapeDtypeStruct((2, 16), jnp.int32, sharding=bs),
        "index": jax.ShapeDtypeStruct(
            (2,), jax.dtypes.canonicalize_dtype(jnp.int64), sharding=bs),
    }
    r1 = warmcache.aot_compile("train/step", step, (mkstate(), avals, key),
                               static_config={}, cache=warmcache.WarmCache(tmp_path))
    assert r1.source == "compiled"
    r2 = warmcache.aot_compile("train/step", step, (mkstate(), avals, key),
                               static_config={},
                               cache=warmcache.WarmCache(tmp_path))
    assert r2.source == "cache", "second incarnation must warm-load"
    warm_state, warm_metrics = r2.fn(mkstate(),
                                     pmesh.shard_batch(mesh, dict(raw)), key)
    assert float(warm_metrics["loss"]) == float(ref_metrics["loss"])
    ref_leaves = jax.tree.leaves(ref_state)
    warm_leaves = jax.tree.leaves(warm_state)
    assert all(bool(jnp.array_equal(a, b))
               for a, b in zip(ref_leaves, warm_leaves)), \
        "warm-loaded step diverged from the jit path"


# ---------------------------------------------------------------------------
# slow: serve worker restart against a populated cache (real subprocess)
# ---------------------------------------------------------------------------

def _wait_health(get, port, want, deadline_s, proc):
    seen = []
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            status, doc = get(port, "/healthz", timeout=2)
            assert status == 200
            seen.append(doc["status"])
            if doc["status"] == want:
                return doc, seen
        except (AssertionError, OSError):
            pass
        if proc.poll() is not None:
            out = proc.stdout.read() if proc.stdout else ""
            raise AssertionError(
                f"server died (rc={proc.poll()}): {out[-3000:]}")
        time.sleep(0.2)
    raise AssertionError(f"no {want!r} within {deadline_s}s (saw {seen[-5:]})")


@pytest.mark.slow
def test_serve_warm_restart_readiness_and_zero_compiles(tmp_path, cpu_devices):
    """Crash-to-ready acceptance, single worker: incarnation 1 boots cold
    (populating the cache; /healthz holds at "warming" until the warm plan
    is compiled), incarnation 2 boots against the populated cache, reaches
    ready with ZERO XLA compiles (trace_report --max-compiles 0), and
    answers the same request bit-identically."""
    from tests.test_serve import (_export_tiny_ckpt, _free_port, _get,
                                  _post_generate, _serve_env)
    from dcr_tpu.core.coordination import EXIT_PREEMPTED

    ckpt = _export_tiny_ckpt(tmp_path)
    env, repo = _serve_env()
    # drop JAX's OWN persistent compile cache: with it, this jaxlib's CPU
    # backend returns executables whose raw serialization is broken
    # ("Symbols not found"), every entry degrades to the export tier, and an
    # export-tier load performs a counted compile-on-load — the zero-compile
    # assertion below would be vacuous. Without it, the executable tier is
    # genuinely exercised end to end (and a regression that breaks it now
    # FAILS the --max-compiles 0 gate instead of hiding behind XLA's cache).
    for k in list(env):
        if k.startswith("JAX_COMPILATION") or k.startswith("JAX_PERSISTENT"):
            env.pop(k)
    warm_dir = tmp_path / "warm"

    def start(logdir):
        port = _free_port()
        proc = subprocess.Popen(
            [sys.executable, "-m", "dcr_tpu.cli.serve",
             f"--model_path={ckpt}", f"--port={port}",
             "--resolution=16", "--num_inference_steps=2", "--sampler=ddim",
             "--max_batch=2", "--max_wait_ms=50", "--queue_depth=16",
             "--request_timeout_s=300", "--seed=0",
             f"--warm.dir={warm_dir}", f"--logdir={logdir}"],
            env=env, cwd=repo, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        return proc, port

    log1, log2 = tmp_path / "run1", tmp_path / "run2"
    proc, port = start(log1)
    try:
        doc, seen = _wait_health(_get, port, "ok", 300, proc)
        # the readiness phase was observable: never "ok" before the warm
        # plan compiled (cold compile leaves a wide "warming" window)
        assert "warming" in seen, f"cold boot never reported warming: {seen}"
        assert doc["buckets_warm"] >= 1 and doc["buckets_total"] >= 1
        status, resp1 = _post_generate(port, "a red square", seed=7)
        assert status == 200
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=120) == EXIT_PREEMPTED
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    proc, port = start(log2)
    try:
        doc, _ = _wait_health(_get, port, "ok", 300, proc)
        assert doc["buckets_warm"] >= 1
        status, resp2 = _post_generate(port, "a red square", seed=7)
        assert status == 200
        assert resp1["image_png_b64"] == resp2["image_png_b64"], \
            "warm-loaded sampler is not bit-identical to the cold one"
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=120) == EXIT_PREEMPTED
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    from tools import trace_report as TR

    # incarnation 2 served entirely from the cache: zero-compile budget holds
    assert TR.main([str(log2), "--max-compiles", "0"]) == 0
    # and the counter is not vacuous: the cold boot exceeds the same budget
    assert TR.main([str(log1), "--max-compiles", "0"]) == 3


# ---------------------------------------------------------------------------
# slow: fleet worker SIGKILL -> warm respawn, zero recompiles (acceptance)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_fleet_kill_warm_respawn_zero_recompiles(tmp_path, cpu_devices):
    """Kill a fleet worker whose cache is populated: the respawned worker
    reaches ready (lease-carried readiness; supervisor holds dispatch until
    then) with zero recompile spans, and responses stay bit-identical."""
    from tests.test_serve import _export_tiny_ckpt, _serve_env
    from dcr_tpu.core.config import (FleetConfig, ServeConfig,
                                     WarmCacheConfig)
    from dcr_tpu.serve.fleet import read_lease
    from dcr_tpu.serve.supervisor import FleetSupervisor

    _serve_env()   # ensures the subprocess env contract is importable
    ckpt = _export_tiny_ckpt(tmp_path)
    cfg = ServeConfig(
        model_path=str(ckpt), resolution=16, num_inference_steps=2,
        sampler="ddim", max_batch=2, max_wait_ms=30.0, queue_depth=64,
        request_timeout_s=300.0, seed=0,
        warm=WarmCacheConfig(dir=str(tmp_path / "warm")),
        fleet=FleetConfig(workers=1, dir=str(tmp_path / "fleet"),
                          heartbeat_s=0.5, lease_s=3.0,
                          dispatch_timeout_s=300.0, spawn_timeout_s=300.0,
                          max_attempts=8, respawn_max=10,
                          respawn_base_delay_s=0.2, respawn_max_delay_s=1.0))
    sup = FleetSupervisor(cfg)
    sup.start()
    try:
        deadline = time.monotonic() + 300
        while sup.status()["workers_alive"] == 0:
            assert time.monotonic() < deadline, \
                f"fleet never came up: {sup.status()!r}"
            time.sleep(0.25)
        lease1 = read_lease(sup.paths, 0)
        assert lease1 is not None and lease1.ready
        assert lease1.buckets_warm >= 1 and lease1.buckets_total >= 1
        pid1 = lease1.pid
        doc = sup.health_doc()
        assert doc["workers_ready"] == 1 and doc["buckets_warm"] >= 1

        r1 = sup.submit("a red square", seed=7).future.result(timeout=300)

        t_kill = time.monotonic()
        os.kill(pid1, signal.SIGKILL)
        pid2 = None
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            lease = read_lease(sup.paths, 0)
            if lease is not None and lease.ready and lease.pid != pid1:
                pid2 = lease.pid
                break
            time.sleep(0.1)
        assert pid2 is not None, "respawned worker never reached ready"
        ttr = time.monotonic() - t_kill

        r2 = sup.submit("a red square", seed=7).future.result(timeout=300)
        assert r1["image_png_b64"] == r2["image_png_b64"], \
            "respawned worker's response is not bit-identical"
        print(f"warm respawn time-to-ready: {ttr:.2f}s")
    finally:
        sup.begin_drain()
        sup.join_drained(120)
        sup.shutdown()

    from tools import trace_report as TR

    records, errors, _ = TR.load_fleet([Path(cfg.fleet.dir)],
                                       TR.load_schema())
    assert not errors, errors[:5]
    compiles = TR.compiles_per_incarnation(records)
    cold = {k: n for k, n in compiles.items() if k.endswith(f"@pid{pid1}")}
    respawn = {k: n for k, n in compiles.items() if k.endswith(f"@pid{pid2}")}
    assert any(n >= 1 for n in cold.values()), \
        f"cold incarnation shows no compiles — counter broken? {compiles}"
    assert not any(n > 0 for n in respawn.values()), \
        f"warm respawn recompiled: {respawn}"

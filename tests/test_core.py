import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcr_tpu.core import precision, rng
from dcr_tpu.core.checkpoint import CheckpointManager, export_hf_layout, import_hf_layout
from dcr_tpu.core.config import MeshConfig
from dcr_tpu.parallel import mesh as pmesh


def test_mesh_creation(cpu_devices):
    m = pmesh.make_mesh(MeshConfig(data=-1, fsdp=2))
    assert m.shape["data"] == 4 and m.shape["fsdp"] == 2 and m.shape["tensor"] == 1


def test_shard_batch_and_psum(cpu_devices):
    m = pmesh.make_mesh(MeshConfig())
    batch = {"x": np.arange(16, dtype=np.float32).reshape(16, 1)}
    sharded = pmesh.shard_batch(m, batch)
    assert sharded["x"].sharding.spec == jax.sharding.PartitionSpec(("data", "fsdp"))
    # global mean through jit matches numpy
    out = jax.jit(lambda b: jnp.mean(b["x"]))(sharded)
    assert np.isclose(float(out), np.mean(batch["x"]))


def test_fsdp_param_sharding(cpu_devices):
    m = pmesh.make_mesh(MeshConfig(data=-1, fsdp=4))
    params = {
        "big": jnp.zeros((1024, 256)),
        "small": jnp.zeros((3,)),
        "odd": jnp.zeros((1025, 3)),  # not divisible by 4 on any big-enough axis
    }
    shardings = pmesh.fsdp_sharding_for_params(m, params)
    assert shardings["big"].spec[0] == "fsdp"
    assert shardings["small"].spec == jax.sharding.PartitionSpec()
    assert shardings["odd"].spec == jax.sharding.PartitionSpec()


def test_precision_policy():
    pol = precision.policy_from_string("bf16")
    tree = {"w": jnp.ones((2, 2), jnp.float32), "ids": jnp.ones((2,), jnp.int32)}
    ct = pol.cast_to_compute(tree)
    assert ct["w"].dtype == jnp.bfloat16
    assert ct["ids"].dtype == jnp.int32
    back = pol.cast_to_param(ct)
    assert back["w"].dtype == jnp.float32
    with pytest.raises(ValueError):
        precision.policy_from_string("fp16")


def test_rng_streams_deterministic_and_distinct():
    root = rng.root_key(42)
    a1 = jax.random.normal(rng.step_key(rng.stream_key(root, "noise"), 3), (4,))
    a2 = jax.random.normal(rng.step_key(rng.stream_key(root, "noise"), 3), (4,))
    b = jax.random.normal(rng.step_key(rng.stream_key(root, "timesteps"), 3), (4,))
    c = jax.random.normal(rng.step_key(rng.stream_key(root, "noise"), 4), (4,))
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    assert not np.allclose(np.asarray(a1), np.asarray(b))
    assert not np.allclose(np.asarray(a1), np.asarray(c))


def test_host_rng_streams():
    g1 = rng.host_python_rng(1, "captions")
    g2 = rng.host_python_rng(1, "captions")
    g3 = rng.host_python_rng(1, "augs")
    s1, s2, s3 = g1.integers(0, 1 << 30, 5), g2.integers(0, 1 << 30, 5), g3.integers(0, 1 << 30, 5)
    np.testing.assert_array_equal(s1, s2)
    assert not np.array_equal(s1, s3)


def test_checkpoint_roundtrip(tmp_path):
    state = {
        "params": {"w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3)},
        "step": jnp.asarray(5),
    }
    mgr = CheckpointManager(tmp_path / "ckpt", async_save=False)
    assert mgr.save(5, state)
    mgr.wait()
    like = jax.tree.map(lambda x: jnp.zeros_like(x), state)
    restored = mgr.restore(like)
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.asarray(state["params"]["w"]))
    assert int(restored["step"]) == 5
    assert mgr.latest_step() == 5
    mgr.close()


def test_hf_layout_roundtrip(tmp_path):
    unet = {"conv_in": {"kernel": np.ones((3, 3, 4, 8), np.float32)},
            "time_mlp": {"bias": np.zeros(8, np.float32)}}
    export_hf_layout(tmp_path / "checkpoint", unet=unet,
                     scheduler_config={"num_train_timesteps": 1000})
    back = import_hf_layout(tmp_path / "checkpoint", "unet")
    np.testing.assert_array_equal(back["conv_in"]["kernel"], unet["conv_in"]["kernel"])
    assert (tmp_path / "checkpoint" / "scheduler" / "scheduler_config.json").exists()


@pytest.mark.fast
def test_lazy_public_api_resolves():
    """Every symbol in the curated lazy API imports and is callable/usable;
    unknown names raise AttributeError (not ImportError)."""
    import dcr_tpu

    for name in dcr_tpu._PUBLIC:
        obj = getattr(dcr_tpu, name)
        assert obj is not None, name
        assert name in dir(dcr_tpu)
    with pytest.raises(AttributeError):
        dcr_tpu.no_such_symbol

"""Cross-framework activation parity: Flax stack vs torch transcriptions.

The flagship UNet/VAE (and the eval backbones in torch_backbones.py) are
checked against independent torch implementations carrying the real
diffusers/torchvision state-dict naming. Weights flow through the actual
interop path — Flax params → models.export → torch `load_state_dict(strict=
True)` → torch forward — so these tests cover, in one pass:

- the exporter emits exactly the key set + layouts torch modules expect
  (VERDICT r1 items 3/4);
- NHWC Flax vs NCHW torch numerics: conv/GroupNorm/attention/GEGLU/
  resample semantics (SURVEY.md §7.3 "weight-conversion fidelity");
- the converters' inverse relationship (convert.py is exercised by loading
  the exported dict back in test_export.py).

Reference roles: diff_train.py:370-408 (UNet/VAE), metrics/ipr.py:41 (VGG),
diff_retrieval.py:277-285 (SSCD).
"""

from __future__ import annotations

import numpy as np
import pytest
import torch

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from dcr_tpu.core.config import ModelConfig  # noqa: E402


def tiny_cfg() -> ModelConfig:
    return ModelConfig(
        sample_size=8, block_out_channels=(32, 64), layers_per_block=1,
        attention_head_dim=16, cross_attention_dim=48, transformer_layers=1,
        norm_num_groups=8, flash_attention=False,
        vae_block_out_channels=(32, 64), vae_layers_per_block=1,
        vae_latent_channels=4)


def to_torch(sd: dict) -> dict:
    return {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in sd.items()}


def test_unet_matches_torch_diffusers_twin():
    from dcr_tpu.models.export import unet_to_diffusers
    from dcr_tpu.models.unet2d import init_unet
    from tests.fixtures.torch_diffusion import TorchUNet2DCondition

    cfg = tiny_cfg()
    model, params = init_unet(cfg, jax.random.key(0))
    sd = unet_to_diffusers(params, n_blocks=len(cfg.block_out_channels))

    twin = TorchUNet2DCondition(cfg)
    missing, unexpected = twin.load_state_dict(to_torch(sd), strict=True)
    assert not missing and not unexpected
    twin.eval()

    rng = np.random.default_rng(0)
    sample = rng.standard_normal((2, 8, 8, cfg.in_channels)).astype(np.float32)
    t = np.array([7, 421], np.int64)
    ctx = rng.standard_normal((2, 5, cfg.cross_attention_dim)).astype(np.float32)

    ours = model.apply({"params": params}, jnp.asarray(sample),
                       jnp.asarray(t), jnp.asarray(ctx))
    with torch.no_grad():
        theirs = twin(torch.from_numpy(sample).permute(0, 3, 1, 2),
                      torch.from_numpy(t), torch.from_numpy(ctx))
    np.testing.assert_allclose(np.asarray(ours),
                               theirs.permute(0, 2, 3, 1).numpy(),
                               atol=2e-4, rtol=1e-3)


def test_vae_matches_torch_diffusers_twin():
    from dcr_tpu.models.export import vae_to_diffusers
    from dcr_tpu.models.vae import AutoencoderKL, init_vae
    from tests.fixtures.torch_diffusion import TorchAutoencoderKL

    cfg = tiny_cfg()
    model, params = init_vae(cfg, jax.random.key(1))
    sd = vae_to_diffusers(params)

    twin = TorchAutoencoderKL(cfg)
    missing, unexpected = twin.load_state_dict(to_torch(sd), strict=True)
    assert not missing and not unexpected
    twin.eval()

    rng = np.random.default_rng(1)
    px = 2 ** (len(cfg.vae_block_out_channels) - 1) * cfg.sample_size
    img = rng.standard_normal((2, px, px, 3)).astype(np.float32)

    dist = model.apply({"params": params}, jnp.asarray(img),
                       method=AutoencoderKL.encode)
    moments = np.concatenate([np.asarray(dist.mean), np.asarray(dist.logvar)],
                             axis=-1)
    with torch.no_grad():
        t_moments = twin.encode(torch.from_numpy(img).permute(0, 3, 1, 2))
    np.testing.assert_allclose(moments,
                               t_moments.permute(0, 2, 3, 1).numpy(),
                               atol=2e-4, rtol=1e-3)

    z = rng.standard_normal((2, cfg.sample_size, cfg.sample_size,
                             cfg.vae_latent_channels)).astype(np.float32)
    dec = model.apply({"params": params}, jnp.asarray(z),
                      method=AutoencoderKL.decode)
    with torch.no_grad():
        t_dec = twin.decode(torch.from_numpy(z).permute(0, 3, 1, 2))
    np.testing.assert_allclose(np.asarray(dec),
                               t_dec.permute(0, 2, 3, 1).numpy(),
                               atol=2e-4, rtol=1e-3)


def test_sd1x_unet_matches_torch_twin_and_roundtrips():
    """SD-1.x variant (sd_mitigation.py:46's model family): fixed 8-head
    attention + 1x1-conv transformer projections. Parity through export AND
    back through convert_unet (conv-shaped proj weights)."""
    from dcr_tpu.models.convert import convert_unet
    from dcr_tpu.models.export import unet_to_diffusers
    from dcr_tpu.models.unet2d import init_unet
    from tests.fixtures.torch_diffusion import TorchUNet2DCondition

    cfg = tiny_cfg()
    cfg.attention_num_heads = 2
    cfg.use_linear_projection = False
    model, params = init_unet(cfg, jax.random.key(5))
    sd = unet_to_diffusers(params, n_blocks=len(cfg.block_out_channels))

    twin = TorchUNet2DCondition(cfg)
    missing, unexpected = twin.load_state_dict(to_torch(sd), strict=True)
    assert not missing and not unexpected
    twin.eval()

    rng = np.random.default_rng(5)
    sample = rng.standard_normal((2, 8, 8, cfg.in_channels)).astype(np.float32)
    t = np.array([0, 999], np.int64)
    ctx = rng.standard_normal((2, 5, cfg.cross_attention_dim)).astype(np.float32)

    ours = model.apply({"params": params}, jnp.asarray(sample),
                       jnp.asarray(t), jnp.asarray(ctx))
    with torch.no_grad():
        theirs = twin(torch.from_numpy(sample).permute(0, 3, 1, 2),
                      torch.from_numpy(t), torch.from_numpy(ctx))
    np.testing.assert_allclose(np.asarray(ours),
                               theirs.permute(0, 2, 3, 1).numpy(),
                               atol=5e-4, rtol=1e-3)

    # checkpoint-source direction: the exported dict converts back losslessly
    back = convert_unet(sd, block_out_channels=cfg.block_out_channels,
                        layers_per_block=cfg.layers_per_block,
                        transformer_layers=cfg.transformer_layers)
    again = model.apply({"params": back}, jnp.asarray(sample),
                        jnp.asarray(t), jnp.asarray(ctx))
    np.testing.assert_allclose(np.asarray(again), np.asarray(ours),
                               atol=1e-6, rtol=1e-6)


def _randomize(module: torch.nn.Module, seed: int) -> None:
    """Random weights AND random BatchNorm running stats (the defaults —
    zero mean, unit var — would mask conversion bugs in the stats)."""
    g = torch.Generator().manual_seed(seed)
    with torch.no_grad():
        for p in module.parameters():
            p.copy_(torch.randn(p.shape, generator=g) * 0.05)
        for name, b in module.named_buffers():
            if name.endswith("running_mean"):
                b.copy_(torch.randn(b.shape, generator=g) * 0.1)
            elif name.endswith("running_var"):
                b.copy_(torch.rand(b.shape, generator=g) + 0.5)


def test_sscd_matches_torch_twin():
    from dcr_tpu.models.convert import convert_sscd
    from dcr_tpu.models.resnet import SSCDModel
    from tests.fixtures.torch_backbones import TorchSSCD

    twin = TorchSSCD(embed_dim=64)
    _randomize(twin, 2)
    twin.eval()
    sd = {k: v.numpy() for k, v in twin.state_dict().items()}
    params = convert_sscd(sd)

    rng = np.random.default_rng(2)
    img = rng.standard_normal((2, 64, 64, 3)).astype(np.float32)
    ours = SSCDModel(embed_dim=64).apply({"params": params}, jnp.asarray(img))
    with torch.no_grad():
        theirs = twin(torch.from_numpy(img).permute(0, 3, 1, 2))
    np.testing.assert_allclose(np.asarray(ours), theirs.numpy(),
                               atol=2e-4, rtol=1e-3)


def test_inception_fid_matches_torch_twin():
    from dcr_tpu.models.convert import convert_inception_fid
    from dcr_tpu.models.inception import InceptionV3FID
    from tests.fixtures.torch_backbones import TorchInceptionFID

    twin = TorchInceptionFID()
    _randomize(twin, 4)
    twin.eval()
    sd = {k: v.numpy() for k, v in twin.state_dict().items()}
    params = convert_inception_fid(sd)

    rng = np.random.default_rng(4)
    model = InceptionV3FID()
    # 128->299 upsample AND 320->299 downsample: torch's F.interpolate never
    # antialiases, so ours must not either (FID would silently diverge)
    for size in (128, 320):
        img = rng.uniform(0.0, 1.0, (2, size, size, 3)).astype(np.float32)
        ours = model.apply({"params": params}, jnp.asarray(img))
        with torch.no_grad():
            theirs = twin(torch.from_numpy(img).permute(0, 3, 1, 2))
        np.testing.assert_allclose(np.asarray(ours), theirs.numpy(),
                                   atol=2e-4, rtol=1e-3, err_msg=f"size={size}")


def test_vgg16_matches_torch_twin():
    from dcr_tpu.models.convert import convert_vgg16
    from dcr_tpu.models.vgg import VGG16Features
    from tests.fixtures.torch_backbones import TorchVGG16

    twin = TorchVGG16()
    _randomize(twin, 3)
    twin.eval()
    sd = {k: v.numpy() for k, v in twin.state_dict().items()}
    params = convert_vgg16(sd)

    rng = np.random.default_rng(3)
    img = rng.uniform(0.0, 1.0, (2, 224, 224, 3)).astype(np.float32)
    ours = VGG16Features().apply({"params": params}, jnp.asarray(img))
    with torch.no_grad():
        theirs = twin(torch.from_numpy(img).permute(0, 3, 1, 2))
    # unnormalized random-weight activations reach ~5e3; 0.05 abs ≈ 1e-5 rel
    np.testing.assert_allclose(np.asarray(ours), theirs.numpy(),
                               atol=0.05, rtol=1e-3)


@pytest.mark.parametrize("patch_size", [16, 8])
def test_xcit_matches_torch_twin(patch_size):
    """XCiT vs an independent torch twin carrying the hub checkpoint naming
    (reference dino_vits.py:413-487 loads facebookresearch/xcit models).
    Covers the conv+BN patch tower, Fourier positions, XCA channel attention,
    depthwise LPI, and the tokens_norm class-attention blocks."""
    from dcr_tpu.models.convert import convert_xcit
    from dcr_tpu.models.xcit import XCiT
    from tests.fixtures.torch_backbones import TorchXCiT

    twin = TorchXCiT(patch_size=patch_size, embed_dim=64, depth=2,
                     num_heads=4, cls_attn_layers=2, eta=1.0)
    _randomize(twin, 5 + patch_size)
    twin.eval()
    sd = {k: v.numpy() for k, v in twin.state_dict().items()}
    params = convert_xcit(sd)

    rng = np.random.default_rng(5 + patch_size)
    img = rng.standard_normal((2, 2 * patch_size, 3 * patch_size, 3)).astype(np.float32)
    model = XCiT(patch_size=patch_size, embed_dim=64, depth=2, num_heads=4,
                 cls_attn_layers=2, eta=1.0)
    ours = model.apply({"params": params}, jnp.asarray(img))
    with torch.no_grad():
        theirs = twin(torch.from_numpy(img).permute(0, 3, 1, 2))
    np.testing.assert_allclose(np.asarray(ours), theirs.numpy(),
                               atol=2e-4, rtol=1e-3)


def test_xcit_converter_covers_every_twin_weight():
    """Every tensor in the hub-format state dict must land in the Flax tree
    (a silently dropped key would mean silently random weights)."""
    from dcr_tpu.models.convert import check_converted, convert_xcit
    from dcr_tpu.models.xcit import XCiT
    from tests.fixtures.torch_backbones import TorchXCiT

    twin = TorchXCiT(patch_size=16, embed_dim=64, depth=2, num_heads=4)
    sd = {k: v.numpy() for k, v in twin.state_dict().items()}
    n_stats = sum(1 for k in sd if "running_" in k)
    params = convert_xcit(sd)
    n_leaves = len(jax.tree_util.tree_leaves(params))
    # num_batches_tracked buffers are the only state-dict entries without a
    # Flax destination
    n_tracked = sum(1 for k in sd if k.endswith("num_batches_tracked"))
    assert n_leaves == len(sd) - n_tracked, (n_leaves, len(sd), n_tracked)
    assert n_stats > 0

    model = XCiT(patch_size=16, embed_dim=64, depth=2, num_heads=4)
    expected = jax.eval_shape(
        model.init, jax.random.key(0),
        jax.ShapeDtypeStruct((1, 32, 32, 3), jnp.float32))["params"]
    assert check_converted(expected, params) == []


def test_sscd_torchscript_file_drop(tmp_path):
    """The SSCD distribution format is a TorchScript archive
    (diff_retrieval.py:277-285). Trace the torch twin, save a real
    .torchscript.pt, and load it through the eval runner's weights_path
    machinery — features must match the torch module."""
    from dcr_tpu.eval.runner import build_backbone, load_backbone_params
    from tests.fixtures.torch_backbones import TorchSSCD

    twin = TorchSSCD().eval()
    _randomize(twin, 6)
    example = torch.zeros(1, 3, 64, 64)
    traced = torch.jit.trace(twin, example)
    path = tmp_path / "sscd_disc_mixup.torchscript.pt"
    traced.save(str(path))

    params = load_backbone_params("sscd", "resnet50_disc", str(path))
    apply_fn, params = build_backbone("sscd", "resnet50_disc",
                                     jax.random.key(0), params, 64)
    rng = np.random.default_rng(6)
    img = rng.standard_normal((2, 64, 64, 3)).astype(np.float32)
    ours = apply_fn(params, jnp.asarray(img))
    with torch.no_grad():
        theirs = twin(torch.from_numpy(img).permute(0, 3, 1, 2))
    np.testing.assert_allclose(np.asarray(ours), theirs.numpy(),
                               atol=2e-4, rtol=1e-3)

"""CLI surface tests: drive each L5 entry point's main() exactly as a user
would (argv lists), asserting the filesystem contracts MIGRATION.md promises.
The CLIs are the reference-script replacements (diff_train.py,
diff_inference.py, diff_retrieval.py, sd_mitigation.py, embedding_search/*),
so this is the migration contract under test."""

import json

import numpy as np
import pytest
from PIL import Image

from dcr_tpu.core.config import (DataConfig, ModelConfig, OptimConfig,
                                 TrainConfig, save_config, to_dict)

# every test here compiles real (tiny) models end-to-end: slow tier
pytestmark = pytest.mark.slow


def _images(dirpath, n, seed=0, size=20):
    dirpath.mkdir(parents=True, exist_ok=True)
    rng = np.random.default_rng(seed)
    for i in range(n):
        Image.fromarray(rng.integers(0, 255, (size, size, 3), np.uint8)).save(
            dirpath / f"{i}.png")


def _train_cfg(tmp_path, *, class_prompt):
    """One source of truth for the CLI tests' tiny train config."""
    return TrainConfig(
        output_dir=str(tmp_path / "run"), seed=0, train_batch_size=2,
        max_train_steps=2, mixed_precision="no", save_steps=1000,
        modelsavesteps=1000, log_every=1, model=ModelConfig.tiny(),
        data=DataConfig(train_data_dir=str(tmp_path / "data"), resolution=16,
                        class_prompt=class_prompt, num_workers=2, seed=0),
        optim=OptimConfig(learning_rate=1e-4, lr_scheduler="constant",
                          lr_warmup_steps=0))


@pytest.fixture(scope="module")
def cli_ckpt(tmp_path_factory):
    """Tiny HF-layout checkpoint + run dir with config.json, as dcr-train
    leaves it (module-scoped: sampling CLIs reuse it)."""
    import jax

    from dcr_tpu.core.checkpoint import export_hf_layout
    from dcr_tpu.diffusion.trainer import build_models

    tmp = tmp_path_factory.mktemp("cli_run")
    cfg = TrainConfig()
    cfg.model = ModelConfig.tiny()
    cfg.data = DataConfig(class_prompt="classlevel")
    models, params = build_models(cfg, jax.random.key(0))
    export_hf_layout(
        tmp / "checkpoint", unet=params["unet"], vae=params["vae"],
        text_encoder=params["text"],
        scheduler_config={"num_train_timesteps": 1000,
                          "beta_schedule": "scaled_linear",
                          "beta_start": 0.00085, "beta_end": 0.012,
                          "prediction_type": "epsilon"},
        model_config=to_dict(cfg.model))
    (tmp / "config.json").write_text(json.dumps(to_dict(cfg)))
    return tmp


def test_cli_train_main(tmp_path, cpu_devices):
    """dcr-train: --config file + dotted overrides -> checkpoints, config.json,
    metrics (MIGRATION.md train table)."""
    from dcr_tpu.cli import train as cli_train

    _images(tmp_path / "data" / "c0", 8, seed=1)
    _images(tmp_path / "data" / "c1", 8, seed=2)
    cfg = _train_cfg(tmp_path, class_prompt="nolevel")
    save_config(cfg, tmp_path / "cfg.json")
    cli_train.main([f"--config={tmp_path / 'cfg.json'}",
                    "--max_train_steps=2"])          # dotted override on top
    run = tmp_path / "run"
    assert (run / "config.json").exists()
    assert (run / "checkpoint" / "unet" / "params.npz").exists()
    lines = [json.loads(l) for l in
             (run / "logs" / "metrics.jsonl").read_text().splitlines()]
    assert any("loss" in l for l in lines)


def test_cli_sample_main_with_modelstyle_override(cli_ckpt, tmp_path,
                                                  cpu_devices):
    """dcr-sample: --modelstyle override beats the config.json regime; PNGs +
    prompts.txt contract (MIGRATION.md sample table)."""
    from dcr_tpu.cli import sample as cli_sample

    out = tmp_path / "inf"
    cli_sample.main([f"--model_path={cli_ckpt}", f"--savepath={out}",
                     "--num_batches=2", "--im_batch=1", "--resolution=16",
                     "--num_inference_steps=2", "--sampler=ddim", "--seed=0",
                     "--modelstyle=nolevel"])
    gens = sorted((out / "generations").glob("*.png"))
    assert len(gens) == 2
    prompts = (out / "prompts.txt").read_text().splitlines()
    # nolevel override: constant instance prompt, NOT classlevel from config
    assert prompts and all(p == prompts[0] for p in prompts)
    assert not prompts[0].startswith("An image of ")


def test_cli_sample_modelstyle_from_config_json(cli_ckpt, tmp_path,
                                                cpu_devices):
    """Without --modelstyle the regime comes from the run's config.json
    (classlevel here) — the reference's parse-the-path heuristic replacement."""
    from dcr_tpu.cli import sample as cli_sample

    out = tmp_path / "inf2"
    cli_sample.main([f"--model_path={cli_ckpt}", f"--savepath={out}",
                     "--num_batches=2", "--im_batch=1", "--resolution=16",
                     "--num_inference_steps=2", "--sampler=ddim", "--seed=0"])
    prompts = (out / "prompts.txt").read_text().splitlines()
    assert all(p.startswith("An image of ") for p in prompts)


def test_cli_mitigate_main(cli_ckpt, tmp_path, cpu_devices, monkeypatch):
    """dcr-mitigate: 12 known-replication prompts, savepath suffix encodes the
    mitigation, augmentation changes the prompts (MIGRATION.md mitigation)."""
    from dcr_tpu.cli import mitigate as cli_mitigate

    monkeypatch.chdir(tmp_path)
    cli_mitigate.main([f"--model_path={cli_ckpt}", "--im_batch=1",
                       "--resolution=16", "--num_inference_steps=2",
                       "--sampler=ddim", "--seed=2",
                       "--rand_augs=rand_word_add"])
    out = tmp_path / "inferences" / "mitigation_aug_rand_word_add"
    gens = sorted((out / "generations").glob("*.png"))
    assert len(gens) == len(cli_mitigate.KNOWN_REPLICATION_PROMPTS)
    prompts = (out / "prompts.txt").read_text().splitlines()
    assert len(prompts) == 12
    # each augmented prompt contains its original's words plus an insertion
    assert prompts != list(cli_mitigate.KNOWN_REPLICATION_PROMPTS)


def test_cli_evaluate_main(tmp_path, cpu_devices):
    """dcr-eval: similarity stats over query/values dirs land in
    similarityscores + scalars (MIGRATION.md evaluate table). Random-init
    backbone; heavy metrics off."""
    from dcr_tpu.cli import evaluate as cli_evaluate

    _images(tmp_path / "query" / "generations", 3, seed=3)
    (tmp_path / "query" / "prompts.txt").write_text("a\nb\nc\n")
    _images(tmp_path / "values" / "c0", 4, seed=4)
    cli_evaluate.main([
        f"--query_dir={tmp_path / 'query' / 'generations'}",
        f"--values_dir={tmp_path / 'values'}",
        "--pt_style=sscd", "--arch=resnet50_disc", "--batch_size=2",
        "--image_size=32", "--compute_fid=false",
        "--compute_clip_score=false", "--compute_complexity=true",
        "--galleries=false", f"--output_dir={tmp_path / 'plots'}"])
    assert (tmp_path / "plots").exists()


def test_cli_search_embed_and_search(tmp_path, cpu_devices):
    """dcr-search embed + search: embedding dumps, chunked top-1 merge, result
    file (MIGRATION.md search table)."""
    from dcr_tpu.cli import search as cli_search

    _images(tmp_path / "gens", 3, seed=5)
    _images(tmp_path / "laion" / "chunk0", 4, seed=6)
    cli_search.main(["embed", f"--gen_folder={tmp_path / 'gens'}",
                     "--image_size=32", "--batch_size=2"])
    cli_search.main(["embed", f"--gen_folder={tmp_path / 'laion' / 'chunk0'}",
                     "--image_size=32", "--batch_size=2"])
    assert (tmp_path / "gens" / "embedding.npz").exists()
    out = tmp_path / "result.npz"
    cli_search.main(["search", f"--gen_folder={tmp_path / 'gens'}",
                     f"--laion_folder={tmp_path / 'laion'}",
                     f"--out_path={out}"])
    res = np.load(out, allow_pickle=True)
    assert len(res["scores"]) == 3


def test_full_chain_train_sample_evaluate_search(tmp_path, cpu_devices):
    """The reference's complete four-stage workflow on ONE set of artifacts:
    train writes a checkpoint, sample reads it and writes generations,
    evaluate compares those generations to the training data, search embeds
    and matches them against a LAION-style chunk — every filesystem contract
    between stages exercised in sequence (reference: diff_train ->
    diff_inference -> diff_retrieval -> embedding_search)."""
    from dcr_tpu.cli import evaluate as cli_evaluate
    from dcr_tpu.cli import sample as cli_sample
    from dcr_tpu.cli import search as cli_search
    from dcr_tpu.cli import train as cli_train

    _images(tmp_path / "data" / "c0", 8, seed=11)
    _images(tmp_path / "data" / "c1", 8, seed=12)
    run = tmp_path / "run"
    cfg = _train_cfg(tmp_path, class_prompt="classlevel")
    save_config(cfg, tmp_path / "cfg.json")
    cli_train.main([f"--config={tmp_path / 'cfg.json'}"])

    inf = tmp_path / "inf"
    cli_sample.main([f"--model_path={run}", f"--savepath={inf}",
                     "--num_batches=3", "--im_batch=1", "--resolution=16",
                     "--num_inference_steps=2", "--sampler=ddim", "--seed=0"])
    gens = inf / "generations"
    assert len(list(gens.glob("*.png"))) == 3

    plots = tmp_path / "plots"
    cli_evaluate.main([
        f"--query_dir={gens}", f"--values_dir={tmp_path / 'data'}",
        "--pt_style=sscd", "--arch=resnet50_disc", "--batch_size=2",
        "--image_size=32", "--compute_fid=false",
        "--compute_clip_score=false", "--compute_complexity=false",
        "--galleries=false", f"--output_dir={plots}"])
    sim = np.load(plots / "similarity.npy")
    assert sim.shape == (3, 16)          # 3 generations vs 16 train images

    cli_search.main(["embed", f"--gen_folder={gens}",
                     "--image_size=32", "--batch_size=2"])
    chunk = tmp_path / "laion" / "chunk0"
    _images(chunk, 4, seed=13)
    cli_search.main(["embed", f"--gen_folder={chunk}",
                     "--image_size=32", "--batch_size=2"])
    out = tmp_path / "search.npz"
    cli_search.main(["search", f"--gen_folder={gens}",
                     f"--laion_folder={tmp_path / 'laion'}",
                     f"--out_path={out}"])
    res = np.load(out, allow_pickle=True)
    assert len(res["scores"]) == 3


def test_full_chain_with_real_bpe_tokenizer(tmp_path, cpu_devices):
    """The BPE end-to-end contract (VERDICT r4 #3): train with
    instancelevel_random captions through ClipBPETokenizer (picked up
    automatically from the pretrained dir's tokenizer/ files, reference
    diff_train.py:370-374), the trainer republishes the files into the run
    dir, and sample decodes token-id prompts through the SAME vocab — real
    BPE truncation and token-id decode in every stage, no HashTokenizer."""
    from pathlib import Path
    import shutil

    from dcr_tpu.cli import evaluate as cli_evaluate
    from dcr_tpu.cli import sample as cli_sample
    from dcr_tpu.cli import train as cli_train
    from dcr_tpu.data.tokenizer import ClipBPETokenizer, load_tokenizer

    fix = Path(__file__).parent / "fixtures" / "bpe"
    base = tmp_path / "sd_base" / "tokenizer"
    base.mkdir(parents=True)
    for f in ("vocab.json", "merges.txt"):
        shutil.copyfile(fix / f, base / f)

    _images(tmp_path / "data" / "c0", 8, seed=21)
    _images(tmp_path / "data" / "c1", 8, seed=22)
    tok = ClipBPETokenizer(fix / "vocab.json", fix / "merges.txt")
    from dcr_tpu.data.dataset import list_image_folder

    paths, _, _ = list_image_folder(tmp_path / "data")
    rng = np.random.default_rng(23)
    caps = {p: [str([int(i) for i in rng.integers(1, tok.vocab_size - 2, 6)])]
            for p in paths}
    capfile = tmp_path / "caps.json"
    capfile.write_text(json.dumps(caps))

    cfg = _train_cfg(tmp_path, class_prompt="instancelevel_random")
    cfg.pretrained_model = str(tmp_path / "sd_base")
    cfg.data.caption_jsons = (str(capfile),)
    save_config(cfg, tmp_path / "cfg.json")
    cli_train.main([f"--config={tmp_path / 'cfg.json'}"])
    run = tmp_path / "run"
    # trainer republished the BPE files -> downstream stages inherit them
    assert isinstance(load_tokenizer(run), ClipBPETokenizer)

    inf = tmp_path / "inf"
    cli_sample.main([f"--model_path={run}", f"--savepath={inf}",
                     "--num_batches=2", "--im_batch=1", "--resolution=16",
                     "--num_inference_steps=2", "--sampler=ddim", "--seed=0",
                     "--modelstyle=instancelevel_random",
                     f"--caption_json={capfile}"])
    prompts = (inf / "prompts.txt").read_text().splitlines()
    assert len(prompts) == 2
    # decoded through the real vocab: plain words, not "tokNNN" hash names
    assert all("tok" not in p for p in prompts)

    plots = tmp_path / "plots"
    cli_evaluate.main([
        f"--query_dir={inf / 'generations'}",
        f"--values_dir={tmp_path / 'data'}",
        "--pt_style=sscd", "--arch=resnet50_disc", "--batch_size=2",
        "--image_size=32", "--compute_fid=false",
        "--compute_clip_score=false", "--compute_complexity=false",
        "--galleries=false", f"--output_dir={plots}"])
    assert np.load(plots / "similarity.npy").shape == (2, 16)


def test_trainer_rejects_tokenizer_vocab_overflow(tmp_path, cpu_devices):
    """A tokenizer bigger than the text embedding table must fail loudly at
    init (XLA clamps out-of-range gathers, which would train silently wrong)."""
    from pathlib import Path
    import shutil

    from dcr_tpu.diffusion.trainer import Trainer

    fix = Path(__file__).parent / "fixtures" / "bpe"
    base = tmp_path / "sd_base" / "tokenizer"
    base.mkdir(parents=True)
    for f in ("vocab.json", "merges.txt"):
        shutil.copyfile(fix / f, base / f)
    _images(tmp_path / "data" / "c0", 4, seed=31)
    cfg = _train_cfg(tmp_path, class_prompt="nolevel")
    cfg.pretrained_model = str(tmp_path / "sd_base")
    cfg.model.text_vocab_size = 64          # < fixture's 668
    with pytest.raises(ValueError, match="vocab"):
        Trainer(cfg)


def test_cli_evaluate_with_xcit_arch(tmp_path, cpu_devices):
    """`--pt_style dino --arch dino_xcit_small_12_p16` through the evaluate
    CLI (the reference's hub-constructor selection, dino_vits.py:413-487) —
    the XCiT family is a first-class eval backbone, not just a registry entry."""
    from dcr_tpu.cli import evaluate as cli_evaluate

    _images(tmp_path / "gens", 2, seed=41, size=32)
    _images(tmp_path / "data" / "c0", 4, seed=42, size=32)
    plots = tmp_path / "plots"
    cli_evaluate.main([
        f"--query_dir={tmp_path / 'gens'}", f"--values_dir={tmp_path / 'data'}",
        "--pt_style=dino", "--arch=dino_xcit_small_12_p16", "--batch_size=2",
        "--image_size=32", "--compute_fid=false", "--compute_clip_score=false",
        "--compute_complexity=false", "--galleries=false",
        f"--output_dir={plots}"])
    sim = np.load(plots / "similarity.npy")
    assert sim.shape == (2, 4)
    assert np.isfinite(sim).all()

"""dcr-pipe tests: the fused→producer/denoiser split, the prefetch ring,
the persistent latent cache (verify/quarantine/recompute), the trainer
integration, and the trace_report Pipeline section."""

import json
import time
from pathlib import Path

import numpy as np
import pytest

from dcr_tpu.core.config import (DataConfig, MeshConfig, ModelConfig,
                                 OptimConfig, PipeConfig, TrainConfig,
                                 validate_train_config)

REPO = Path(__file__).resolve().parent.parent


def _cfg(**kw):
    cfg = TrainConfig(**kw)
    cfg.model = ModelConfig.tiny()
    cfg.mixed_precision = "no"
    cfg.optim.learning_rate = 1e-3
    cfg.optim.lr_scheduler = "constant"
    cfg.optim.lr_warmup_steps = 0
    return cfg


def _batch(key, cfg, bsz=8):
    import jax
    import jax.numpy as jnp

    px = 8 * 2 ** (len(cfg.model.vae_block_out_channels) - 1)
    return {
        "pixel_values": np.asarray(
            jax.random.uniform(key, (bsz, px, px, 3)) * 2 - 1),
        "input_ids": np.asarray(jax.random.randint(
            jax.random.fold_in(key, 1), (bsz, cfg.model.text_max_length), 0,
            cfg.model.text_vocab_size)),
        "index": np.arange(bsz, dtype=np.int64),
    }


@pytest.fixture(scope="module")
def setup():
    import jax

    from dcr_tpu.diffusion.trainer import build_models

    cfg = _cfg()
    models, params = build_models(cfg, jax.random.key(0))
    return cfg, models, params


def _make_state(cfg, models, params, mesh):
    import jax
    import jax.numpy as jnp

    from dcr_tpu.diffusion import train as T

    params = jax.tree.map(lambda x: jnp.array(np.asarray(x)), params)
    state = T.init_train_state(cfg, models, unet_params=params["unet"],
                               text_params=params["text"],
                               vae_params=params["vae"])
    return T.shard_train_state(state, mesh)


# ---------------------------------------------------------------------------
# stream ownership + state views
# ---------------------------------------------------------------------------

def test_rng_stream_ownership_partitions_the_fused_streams():
    """Every RNG stream the fused step draws has exactly one pipelined
    owner — a new stream must be assigned before it can ship."""
    from dcr_tpu.diffusion import encode_stage as E

    fused_streams = {"vae_sample", "noise", "timesteps", "emb_noise",
                     "mixup_beta", "mixup_perm"}
    producer = set(E.PRODUCER_STREAMS)
    denoiser = set(E.DENOISER_STREAMS)
    assert producer | denoiser == fused_streams
    assert not (producer & denoiser)


def test_split_merge_roundtrip():
    import jax

    from dcr_tpu.diffusion import encode_stage as E
    from dcr_tpu.diffusion.trainer import abstract_train_state

    for tte in (False, True):
        cfg = _cfg(train_text_encoder=tte)
        state = abstract_train_state(cfg)
        hot, frozen = E.split_state(state, tte)
        if tte:
            assert hot.text_params is not None and frozen["text"] is None
        else:
            assert hot.text_params is None and frozen["text"] is not None
        merged = E.merge_state(hot, frozen, tte)
        assert jax.tree.structure(merged) == jax.tree.structure(state)


# ---------------------------------------------------------------------------
# the split's numerics
# ---------------------------------------------------------------------------

def test_pipelined_matches_fused_loss_and_params(setup, cpu_devices):
    """encode∘denoise == fused within float-fusion tolerance, with the SAME
    q-sample draws (keys derive from the same streams at the same step)."""
    import jax

    from dcr_tpu.core import rng as rngmod
    from dcr_tpu.diffusion import encode_stage as E
    from dcr_tpu.diffusion import train as T
    from dcr_tpu.parallel import mesh as pmesh

    cfg, models, params = setup
    mesh = pmesh.make_mesh(MeshConfig())
    key = rngmod.root_key(0)
    raw = _batch(jax.random.key(1), cfg)

    fused = T.make_train_step(cfg, models, mesh)
    s1 = _make_state(cfg, models, params, mesh)
    fused_losses = []
    for _ in range(3):
        s1, m = fused(s1, pmesh.shard_batch(mesh, dict(raw)), key)
        fused_losses.append(float(m["loss"]))

    encode_fn = E.make_encode_stage(cfg, models, mesh)
    denoise_fn = E.make_denoise_step(cfg, models, mesh)
    s2 = _make_state(cfg, models, params, mesh)
    hot, frozen = E.split_state(s2, cfg.train_text_encoder)
    pipe_losses = []
    for i in range(3):
        enc = encode_fn(frozen, pmesh.shard_batch(mesh, dict(raw)), key,
                        np.uint32(i))
        hot, m = denoise_fn(hot, enc, key)
        pipe_losses.append(float(m["loss"]))

    np.testing.assert_allclose(pipe_losses, fused_losses, rtol=1e-4)
    merged = E.merge_state(hot, frozen, cfg.train_text_encoder)
    # adam's grad normalization turns float-fusion noise into O(lr)-scale
    # update flips on near-zero-grad elements, so relative tolerance is the
    # wrong gate post-optimizer — bound the ABSOLUTE drift instead (3 steps
    # at lr 1e-3 bounds honest drift well under 1e-4; observed ~2e-5)
    for a, b in zip(jax.tree.leaves(jax.device_get(s1.unet_params)),
                    jax.tree.leaves(jax.device_get(merged.unet_params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=0,
                                   atol=1e-4)


def test_pipelined_with_mitigations_and_trained_text_encoder(setup,
                                                             cpu_devices):
    """Embedding mitigations (denoiser-owned streams) and the
    train_text_encoder passthrough both reproduce the fused numerics."""
    import jax

    from dcr_tpu.core import rng as rngmod
    from dcr_tpu.diffusion import encode_stage as E
    from dcr_tpu.diffusion import train as T
    from dcr_tpu.parallel import mesh as pmesh

    _, models, params = setup
    key = rngmod.root_key(0)
    for kw in ({"rand_noise_lam": 0.5}, {"mixup_noise_lam": 0.3},
               {"train_text_encoder": True}):
        cfg = _cfg(**kw)
        cfg.model = ModelConfig.tiny()
        mesh = pmesh.make_mesh(MeshConfig())
        raw = _batch(jax.random.key(1), cfg)
        s1 = _make_state(cfg, models, params, mesh)
        _, m1 = T.make_train_step(cfg, models, mesh)(
            s1, pmesh.shard_batch(mesh, dict(raw)), key)
        s2 = _make_state(cfg, models, params, mesh)
        hot, frozen = E.split_state(s2, cfg.train_text_encoder)
        enc = E.make_encode_stage(cfg, models, mesh)(
            frozen, pmesh.shard_batch(mesh, dict(raw)), key, np.uint32(0))
        if cfg.train_text_encoder:
            assert "input_ids" in enc and "ctx" not in enc
        _, m2 = E.make_denoise_step(cfg, models, mesh)(hot, enc, key)
        np.testing.assert_allclose(float(m2["loss"]), float(m1["loss"]),
                                   rtol=1e-4)


def test_cache_stage_reconstructs_live_latents(setup, cpu_devices):
    """moments + vae_sample draw == the live encode's posterior sample
    (same stream, same step key) — one cache serves any step/epoch."""
    import jax

    from dcr_tpu.core import rng as rngmod
    from dcr_tpu.diffusion import encode_stage as E
    from dcr_tpu.parallel import mesh as pmesh

    cfg, models, params = setup
    mesh = pmesh.make_mesh(MeshConfig())
    key = rngmod.root_key(0)
    raw = _batch(jax.random.key(1), cfg)
    state = _make_state(cfg, models, params, mesh)
    _, frozen = E.split_state(state, cfg.train_text_encoder)
    live = E.make_encode_stage(cfg, models, mesh)
    mom = E.make_encode_stage(cfg, models, mesh, emit="moments")(
        frozen, pmesh.shard_batch(mesh, dict(raw)), key, np.uint32(0))
    cache_fn = E.make_cache_stage(cfg, models, mesh)
    for step in (0, 7):
        got = cache_fn({"mean": mom["mean"], "std": mom["std"],
                        "ctx": mom["ctx"], "index": mom["index"]},
                       key, np.uint32(step))
        want = live(frozen, pmesh.shard_batch(mesh, dict(raw)), key,
                    np.uint32(step))
        np.testing.assert_allclose(np.asarray(got["latents"]),
                                   np.asarray(want["latents"]), atol=1e-6)
        np.testing.assert_allclose(np.asarray(got["ctx"]),
                                   np.asarray(want["ctx"]), atol=1e-6)


# ---------------------------------------------------------------------------
# the producer ring (no jax needed — stub encode)
# ---------------------------------------------------------------------------

def _ring(batches, encode, depth=2, start=0):
    from dcr_tpu.diffusion.encode_stage import EncodeProducer

    return EncodeProducer(iter(batches), encode, depth=depth,
                          start_step=start)


def test_producer_ring_orders_and_terminates():
    seen = []

    def encode(batch, step):
        seen.append(step)
        return {"v": batch, "step": step}

    p = _ring(list(range(5)), encode, depth=2, start=3)
    try:
        for i in range(5):
            enc = p.get(3 + i)
            assert enc == {"v": i, "step": 3 + i}
        assert p.get(8) is None          # end-of-epoch sentinel
        assert seen == [3, 4, 5, 6, 7]
    finally:
        p.stop()


def test_producer_ring_bounded_depth():
    """The producer may run at most `depth` batches ahead of the consumer
    (plus the one blocked in put) — the ring is a real backpressure bound."""
    encoded = []

    def encode(batch, step):
        encoded.append(step)
        return step

    p = _ring(list(range(32)), encode, depth=2)
    try:
        time.sleep(0.5)                  # let the producer run ahead
        assert len(encoded) <= 3         # depth 2 in ring + 1 blocked in put
        for i in range(32):
            assert p.get(i) == i
    finally:
        p.stop()


def test_producer_ring_propagates_errors():
    def encode(batch, step):
        if step == 2:
            raise RuntimeError("encoder exploded")
        return step

    p = _ring(list(range(5)), encode)
    try:
        assert p.get(0) == 0
        assert p.get(1) == 1
        with pytest.raises(RuntimeError, match="encoder exploded"):
            p.get(2)
    finally:
        p.stop()


def test_producer_ring_stop_mid_stream_and_gauge():
    from dcr_tpu.core import tracing

    p = _ring(list(range(100)), lambda b, s: s, depth=3)
    assert p.get(0) == 0
    p.stop()
    p.stop()                             # idempotent
    assert not p._thread.is_alive()
    # the gauge exists and holds a small ring occupancy
    g = tracing.registry().gauge("data/queue_depth")
    assert 0 <= g.value <= 3


# ---------------------------------------------------------------------------
# the latent cache
# ---------------------------------------------------------------------------

def _write_cache(tmp_path, n=10, shard_size=4, fp=None):
    from dcr_tpu.data import latent_cache as LC

    fp = fp or {"version": 1, "test": "roundtrip"}
    w = LC.LatentCacheWriter(tmp_path, fp, shard_size=shard_size)
    rng = np.random.default_rng(0)
    mean = rng.standard_normal((n, 2, 2, 4)).astype(np.float32)
    std = np.abs(rng.standard_normal((n, 2, 2, 4))).astype(np.float32)
    ctx = rng.standard_normal((n, 3, 8)).astype(np.float32)
    idx = np.arange(100, 100 + n, dtype=np.int64)
    w.add(idx, mean, std, ctx)
    w.finalize()
    return fp, idx, mean, std, ctx


def test_latent_cache_roundtrip_multi_shard(tmp_path):
    from dcr_tpu.data import latent_cache as LC

    fp, idx, mean, std, ctx = _write_cache(tmp_path, n=10, shard_size=4)
    assert len(list(tmp_path.glob("shard_*.npz"))) == 3  # 4+4+2
    r = LC.LatentCacheReader(tmp_path, fp)
    assert r.coverage() == (10, 10)
    got = r.lookup(np.asarray([103, 100, 109]))
    assert got is not None
    np.testing.assert_array_equal(got[0], mean[[3, 0, 9]])
    np.testing.assert_array_equal(got[1], std[[3, 0, 9]])
    np.testing.assert_array_equal(got[2], ctx[[3, 0, 9]])
    assert r.lookup(np.asarray([100, 555])) is None  # any miss -> None


def test_latent_cache_fingerprint_mismatch(tmp_path):
    from dcr_tpu.data import latent_cache as LC

    fp, *_ = _write_cache(tmp_path)
    with pytest.raises(LC.LatentCacheError, match="different"):
        LC.LatentCacheReader(tmp_path, dict(fp, test="other"))


def test_latent_cache_corrupt_shard_quarantined(tmp_path):
    from dcr_tpu.data import latent_cache as LC

    fp, idx, mean, *_ = _write_cache(tmp_path, n=10, shard_size=4)
    shard = tmp_path / "shard_00001.npz"       # rows 4..7
    blob = bytearray(shard.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    shard.write_bytes(bytes(blob))
    r = LC.LatentCacheReader(tmp_path, fp)
    # the damaged shard is out of the key space, its indices are misses
    assert not shard.exists()
    assert any("quarantined" in p.name for p in tmp_path.iterdir())
    assert r.lookup(np.asarray([104])) is None
    got = r.lookup(np.asarray([100, 109]))     # other shards still serve
    assert got is not None
    np.testing.assert_array_equal(got[0], mean[[0, 9]])
    assert r.coverage()[0] == 6


def test_latent_cache_corrupt_fault_kind(tmp_path):
    """latent_cache_corrupt@load=N drives the verify/quarantine/recompute
    path deterministically, mirroring warmcache's cache_corrupt."""
    from dcr_tpu.core import resilience as R
    from dcr_tpu.data import latent_cache as LC
    from dcr_tpu.utils import faults

    fp, *_ = _write_cache(tmp_path, n=10, shard_size=4)
    before = R.counters().get("latentcache/shard_corrupt", 0)
    faults.install("latent_cache_corrupt@load=0")
    try:
        r = LC.LatentCacheReader(tmp_path, fp)
    finally:
        faults.clear()
    # the first shard load was poisoned in memory -> quarantined on disk
    assert not (tmp_path / "shard_00000.npz").exists()
    assert r.lookup(np.asarray([100])) is None
    assert r.coverage()[0] == 6
    after = R.counters().get("latentcache/shard_corrupt", 0)
    assert after == before + 1


def test_latent_cache_manifest_corrupt(tmp_path):
    from dcr_tpu.data import latent_cache as LC

    _write_cache(tmp_path)
    (tmp_path / "manifest.json").write_text("{not json")
    with pytest.raises(LC.LatentCacheError, match="corrupt"):
        LC.LatentCacheReader(tmp_path)
    assert any("quarantined" in p.name for p in tmp_path.iterdir())


def test_latent_cache_missing_manifest(tmp_path):
    from dcr_tpu.data import latent_cache as LC

    with pytest.raises(LC.LatentCacheError, match="precompute"):
        LC.LatentCacheReader(tmp_path / "nope")


def test_cached_encode_falls_back_on_miss(tmp_path, monkeypatch):
    """The recompute path: any uncached index re-encodes the batch live."""
    from dcr_tpu.core import resilience as R
    from dcr_tpu.data import latent_cache as LC
    from dcr_tpu.diffusion import encode_stage as E
    from dcr_tpu.parallel import mesh as pmesh_mod

    fp, *_ = _write_cache(tmp_path, n=4, shard_size=4)
    r = LC.LatentCacheReader(tmp_path, fp)
    calls = {"cache": 0, "live": 0}

    def cache_fn(moments, key, step):
        calls["cache"] += 1
        return {"from": "cache"}

    def fallback(batch, step):
        calls["live"] += 1
        return {"from": "live"}

    monkeypatch.setattr(pmesh_mod, "shard_batch", lambda mesh, d: d)
    enc = E.cached_encode(cache_fn, r, None, None, fallback)
    before = R.counters().get("latentcache/batch_recompute", 0)
    out = enc({"index": np.asarray([100, 101])}, 0)
    assert out == {"from": "cache"}
    out = enc({"index": np.asarray([100, 999])}, 1)
    assert out == {"from": "live"}
    after = R.counters().get("latentcache/batch_recompute", 0)
    assert after == before + 1
    assert calls == {"cache": 1, "live": 1}


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------

def test_pipe_config_validation():
    cfg = _cfg()
    cfg.pipe = PipeConfig(depth=0)
    with pytest.raises(ValueError, match="depth"):
        validate_train_config(cfg)
    cfg = _cfg(train_text_encoder=True)
    cfg.pipe = PipeConfig(latent_cache="/tmp/x")
    with pytest.raises(ValueError, match="train_text_encoder"):
        validate_train_config(cfg)
    cfg = _cfg()
    cfg.pipe = PipeConfig(latent_cache="/tmp/x")
    cfg.data.trainspecial = "allcaps"
    cfg.data.class_prompt = "instancelevel_blip"
    with pytest.raises(ValueError, match="trainspecial"):
        validate_train_config(cfg)
    # per-occurrence realizations the frozen cache cannot serve
    cfg = _cfg()
    cfg.pipe = PipeConfig(latent_cache="/tmp/x")
    cfg.data.random_flip = False
    cfg.data.duplication = "dup_image"
    with pytest.raises(ValueError, match="dup_image"):
        validate_train_config(cfg)
    cfg = _cfg()
    cfg.pipe = PipeConfig(latent_cache="/tmp/x")
    assert cfg.data.random_flip            # the default
    with pytest.raises(ValueError, match="random_flip"):
        validate_train_config(cfg)
    cfg = _cfg()
    cfg.pipe = PipeConfig(latent_cache="/tmp/x")
    cfg.data.random_flip = False
    cfg.data.center_crop = False
    with pytest.raises(ValueError, match="center_crop"):
        validate_train_config(cfg)
    cfg = _cfg()
    cfg.pipe = PipeConfig(latent_cache="/tmp/x")
    cfg.data.random_flip = False
    validate_train_config(cfg)           # valid cache config
    cfg = _cfg()
    cfg.pipe = PipeConfig(enabled=True, depth=3)
    validate_train_config(cfg)           # valid (live producer: any regime)


# ---------------------------------------------------------------------------
# trace_report Pipeline section
# ---------------------------------------------------------------------------

def _rec(name, ts, dur, ph="X", **args):
    rec = {"ph": ph, "name": name, "id": 1, "ts": float(ts), "pid": 0,
           "tid": 1, "tname": "t", "args": args, "_proc": 0, "_plabel": "p"}
    if ph == "X":
        rec["dur"] = float(dur)
        rec["parent"] = None
    return rec


def test_trace_report_pipeline_section():
    import tools.trace_report as tr

    # encoder spans overlap half of each denoise span; two 1 ms waits
    records = [
        _rec("train/encode", 0, 1000),
        _rec("train/encode", 2000, 1000),
        _rec("train/step", 500, 1000),
        _rec("train/step", 2500, 1000),
        _rec("train/encode_wait", 400, 1000),
        _rec("train/encode_wait", 2400, 1000),
        _rec("train/data_wait", 0, 500),
    ]
    pipe = tr.pipeline_summary(records)
    assert pipe["encoded_batches"] == 2
    assert pipe["encode_total_ms"] == 2.0
    assert pipe["denoise_total_ms"] == 2.0
    assert pipe["encode_wait_total_ms"] == 2.0
    assert pipe["bubble_pct"] == 50.0
    assert pipe["overlap_ms"] == 1.0     # half of each encode span
    assert pipe["overlap_pct"] == 50.0
    assert pipe["data_wait_total_ms"] == 0.5
    # fused-only traces keep their old shape
    assert tr.pipeline_summary([_rec("train/step", 0, 1000)]) is None
    # and the text renderer mentions the section
    summary = tr.summarize(records, {})
    text = tr.render_text(summary, [Path(".")])
    assert "pipeline:" in text and "bubble 50.0%" in text


def test_bench_pipe_schema():
    import tools.bench_pipe as bp

    doc = {
        "cores": 1, "steps": 10, "min_speedup": 1.25, "batch_sizes": [4],
        "legs": {"bs4": {
            "fused": {"steps_per_sec": 5.0, "step_ms": 200.0,
                      "hbm_peak_bytes": None},
            "pipelined": {"steps_per_sec": 5.5, "step_ms": 182.0,
                          "speedup": 1.1, "hbm_peak_bytes": 123456},
            "latent_cache": {"steps_per_sec": 7.0, "step_ms": 143.0,
                             "speedup": 1.4, "hbm_peak_bytes": None},
        }},
        "gate": {"batch_size": 4, "speedup": 1.4, "mode": "latent_cache",
                 "passed": True},
    }
    assert bp.validate_result(doc) == []
    bad = json.loads(json.dumps(doc))
    del bad["gate"]["passed"]
    bad["legs"]["bs4"]["pipelined"].pop("speedup")
    assert len(bp.validate_result(bad)) == 2
    # dcr-hbm: hbm_peak_bytes must be present (null on stats-less backends)
    # and integral where present
    missing = json.loads(json.dumps(doc))
    missing["legs"]["bs4"]["fused"].pop("hbm_peak_bytes")
    wrong = json.loads(json.dumps(doc))
    wrong["legs"]["bs4"]["fused"]["hbm_peak_bytes"] = "big"
    assert any("hbm_peak_bytes" in p for p in bp.validate_result(missing))
    assert any("hbm_peak_bytes" in p for p in bp.validate_result(wrong))


def test_banked_bench_pipe_artifact_is_valid_and_gated():
    """The checked-in BENCH_PIPE.json must parse, validate, and pass its
    own gate — a regressed re-bank cannot merge silently."""
    import tools.bench_pipe as bp

    path = REPO / "BENCH_PIPE.json"
    doc = json.loads(path.read_text())
    assert bp.validate_result(doc) == []
    assert doc["gate"]["passed"] is True
    assert doc["gate"]["speedup"] >= doc["min_speedup"] >= 1.25


# ---------------------------------------------------------------------------
# trainer integration (slow: real epochs through the Trainer)
# ---------------------------------------------------------------------------

@pytest.fixture()
def train_setup(tmp_path):
    from PIL import Image

    rng = np.random.default_rng(0)
    for cls in ["c0", "c1"]:
        d = tmp_path / "data" / cls
        d.mkdir(parents=True)
        for i in range(8):
            Image.fromarray(
                rng.integers(0, 255, (20, 20, 3), np.uint8)).save(
                    d / f"{i}.png")

    def make(out, **pipe):
        return TrainConfig(
            output_dir=str(tmp_path / out), seed=0, train_batch_size=2,
            max_train_steps=6, num_train_epochs=10, mixed_precision="no",
            save_steps=1000, modelsavesteps=4, log_every=2,
            model=ModelConfig.tiny(),
            data=DataConfig(train_data_dir=str(tmp_path / "data"),
                            resolution=16, class_prompt="nolevel",
                            num_workers=2, seed=0, random_flip=False),
            optim=OptimConfig(learning_rate=1e-4, lr_scheduler="constant",
                              lr_warmup_steps=0),
            pipe=PipeConfig(**pipe),
        )

    return make, tmp_path


@pytest.mark.slow
def test_trainer_pipelined_end_to_end(train_setup):
    """Pipelined Trainer run: same loss curve as fused within tolerance,
    checkpoints + resume + pipeline spans all work."""
    import jax

    from dcr_tpu.diffusion.trainer import Trainer

    make, tmp_path = train_setup
    m_fused = Trainer(make("run_fused")).train()
    t = Trainer(make("run_pipe", enabled=True, depth=2))
    assert t.pipelined
    m_pipe = t.train()
    assert abs(m_pipe["loss"] - m_fused["loss"]) <= \
        1e-3 * max(abs(m_fused["loss"]), 1e-9)
    assert t.ckpt.all_steps() == [4, 6]
    # the trace carries the pipeline spans
    names = {json.loads(l)["name"] for l in
             (tmp_path / "run_pipe" / "trace.jsonl").read_text().splitlines()}
    assert {"train/encode", "train/encode_wait", "train/step"} <= names
    # resume continues pipelined
    cfg2 = make("run_pipe", enabled=True)
    cfg2.max_train_steps = 8
    t2 = Trainer(cfg2)
    assert t2.maybe_resume() == 6
    t2.train()
    assert 8 in t2.ckpt.all_steps()
    assert int(jax.device_get(t2.state.step)) == 8


@pytest.mark.slow
def test_pipelined_nan_rollback(train_setup):
    """NaN rollback under the producer/consumer split: restore the last
    checkpoint, keep the ORIGINAL frozen buffers (the producer pins them),
    fast-forward past the bad window, and finish the run."""
    from dcr_tpu.diffusion.trainer import Trainer
    from dcr_tpu.utils import faults

    make, tmp_path = train_setup
    cfg = make("run_nanpipe", enabled=True)
    cfg.log_every = 1
    cfg.modelsavesteps = 2
    cfg.fault.max_rollbacks = 1
    faults.install("nan_loss@step=3")
    try:
        t = Trainer(cfg)
        frozen_before = t.state.vae_params
        m = t.train()
    finally:
        faults.clear()
    assert np.isfinite(m["loss"])
    assert t._rollbacks == 1
    assert "nan_rollback" in \
        (tmp_path / "run_nanpipe" / "quarantine.jsonl").read_text()
    # the run finished all 6 micro-steps despite the rollback
    import jax

    assert int(jax.device_get(t.state.step)) == 6
    # the frozen view still references the ORIGINAL buffers — the restore's
    # duplicate frozen copy was dropped, not kept alive alongside
    assert t._frozen["vae"] is frozen_before


@pytest.mark.slow
def test_precompute_and_cache_fed_training(train_setup):
    """dcr-precompute-latents -> Trainer(pipe.latent_cache): encoders never
    run in the hot path, loss matches fused within tolerance, and a corrupt
    shard degrades to live recompute instead of failing the run."""
    from dcr_tpu.cli.precompute import precompute
    from dcr_tpu.diffusion.trainer import Trainer

    make, tmp_path = train_setup
    cache = tmp_path / "lcache"
    cfgp = make("run_pre")
    cfgp.pipe.latent_cache = str(cache)
    # small shards so corrupting ONE leaves others serving (losing every
    # shard is correctly a typed error, not a silent recompute-everything)
    cfgp.pipe.cache_shard_size = 4
    summary = precompute(cfgp)
    assert len(list(cache.glob("shard_*.npz"))) == 4
    assert summary["indices"] == 16
    m_fused = Trainer(make("run_fused2")).train()
    t = Trainer(make("run_cache", latent_cache=str(cache)))
    assert t.pipelined
    m_cache = t.train()
    assert abs(m_cache["loss"] - m_fused["loss"]) <= \
        1e-3 * max(abs(m_fused["loss"]), 1e-9)
    # fingerprint mismatch is a loud typed failure, not silent retraining
    from dcr_tpu.data.latent_cache import LatentCacheError

    bad = make("run_badcache", latent_cache=str(cache))
    bad.seed = 1                          # different frozen params
    with pytest.raises(LatentCacheError, match="different"):
        Trainer(bad).train()
    # corrupt one shard: training still completes (recompute path)
    shard = next(cache.glob("shard_*.npz"))
    blob = bytearray(shard.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    shard.write_bytes(bytes(blob))
    t3 = Trainer(make("run_cache2", latent_cache=str(cache)))
    m3 = t3.train()
    assert np.isfinite(m3["loss"])
    assert any("quarantined" in p.name for p in cache.iterdir())

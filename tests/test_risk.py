"""dcr-watch tests: live copy-risk observability.

Fast tier (pure logic + tiny jit only): embedding-dump loading (.npz and
the reference toolchain's pickle format, torn/non-finite/corrupt dumps
quarantined), the top-k cosine scorer, the exact-transform property of
prepare_images, bounded evidence dumps, the flagged-pair gallery,
trace_report's "Copy risk" section and tools/risk_report, lease/health
risk-state plumbing and supervisor /check routing (stub HTTP worker).

Slow tier (real tiny compiled stack): a request seeded to reproduce a
train image is flagged while a normal request is not, generated images are
bit-identical with scoring on vs off, the trainer-hook gauges land in
MetricWriter, and the HTTP e2e — /generate copy_risk + /check + Prometheus
counters + evidence dump, then a warm-cache restart whose second
incarnation scores with ZERO XLA compiles (trace_report --max-compiles 0).
"""

import base64
import io
import json
import pickle
import threading
import time

import numpy as np
import pytest

from dcr_tpu.core import resilience as R
from dcr_tpu.core import tracing
from dcr_tpu.core.config import RiskConfig
from dcr_tpu.obs.copyrisk import (EMBED_DIM, CopyRiskIndex, EvidenceRecorder,
                                  RiskIndexError, decode_image_b64,
                                  load_risk_dump, prepare_images,
                                  verify_risk_dump)


@pytest.fixture(autouse=True)
def _clean_tracing():
    tracing.reset_for_tests()
    yield
    tracing.reset_for_tests()


def _features(n: int, dim: int = EMBED_DIM) -> np.ndarray:
    """Deterministic, non-degenerate [n, dim] float32 features."""
    base = np.arange(n * dim, dtype=np.float32).reshape(n, dim)
    return np.cos(base * 0.37) + 0.01 * base / (n * dim)


def _keys(n: int) -> list:
    return [f"train/img_{i:04d}.png" for i in range(n)]


def _png_b64(image: np.ndarray) -> str:
    from PIL import Image

    buf = io.BytesIO()
    arr = (np.clip(image, 0, 1) * 255).round().astype(np.uint8)
    Image.fromarray(arr).save(buf, format="PNG")
    return base64.b64encode(buf.getvalue()).decode()


def _grad_image(i: int, size: int = 16) -> np.ndarray:
    x = np.linspace(0, 1, size * size * 3, dtype=np.float32)
    return np.roll(x, i * 97).reshape(size, size, 3) * ((i % 3 + 1) / 3.0)


# ---------------------------------------------------------------------------
# dump loading: both formats, verify-before-load, quarantine
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_dump_roundtrip_npz_and_reference_pickle(tmp_path):
    from dcr_tpu.search.embed import save_embeddings

    feats, keys = _features(5), _keys(5)
    save_embeddings(tmp_path / "embedding.npz", feats, keys)
    with open(tmp_path / "embedding.pkl", "wb") as f:
        pickle.dump({"features": feats, "indexes": keys}, f)

    for name in ("embedding.npz", "embedding.pkl"):
        got_feats, got_keys = load_risk_dump(tmp_path / name)
        assert got_keys == keys, name
        np.testing.assert_allclose(got_feats, feats, rtol=1e-6)


@pytest.mark.fast
def test_corrupt_dump_quarantined_and_counted(tmp_path):
    path = tmp_path / "embedding.npz"
    path.write_bytes(b"this is not a zip archive at all")
    with pytest.raises(RiskIndexError):
        load_risk_dump(path)
    assert not path.exists(), "corrupt dump must be quarantined away"
    assert list(tmp_path.glob("embedding.npz.quarantined.*"))
    assert R.counters().get("copy_risk/index_corrupt_total", 0) == 1


@pytest.mark.fast
def test_torn_and_nonfinite_dumps_rejected(tmp_path):
    from dcr_tpu.search.embed import save_embeddings

    # torn: features/indexes disagree. A READABLE dump that fails
    # verification is a typed error but stays IN PLACE — it may be a valid
    # artifact of the wrong kind / shared by a fleet; only unparseable
    # files get the destructive quarantine rename.
    np.savez(tmp_path / "torn.npz", features=_features(4),
             indexes=np.asarray(_keys(3)))
    with pytest.raises(RiskIndexError, match="torn"):
        load_risk_dump(tmp_path / "torn.npz")
    assert (tmp_path / "torn.npz").exists()
    assert not list(tmp_path.glob("torn.npz.quarantined.*"))
    assert R.counters().get("copy_risk/index_invalid_total", 0) == 1

    # non-finite features
    bad = _features(4)
    bad[2, 7] = np.nan
    save_embeddings(tmp_path / "nan.npz", bad, _keys(4))
    with pytest.raises(RiskIndexError, match="non-finite"):
        load_risk_dump(tmp_path / "nan.npz")
    assert (tmp_path / "nan.npz").exists()

    # wrong width (verify_risk_dump directly: no file involved)
    with pytest.raises(RiskIndexError, match="width"):
        verify_risk_dump(np.zeros((3, 64), np.float32), _keys(3))
    with pytest.raises(RiskIndexError, match="non-empty"):
        verify_risk_dump(np.zeros((0, EMBED_DIM), np.float32), [])

    # absent path: typed, NOT quarantined (nothing to rename)
    with pytest.raises(RiskIndexError, match="no embedding dump"):
        load_risk_dump(tmp_path / "missing.npz")


# ---------------------------------------------------------------------------
# scorer + transform
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_risk_scorer_topk_is_cosine_and_sorted(cpu_devices):
    from dcr_tpu.obs.copyrisk import make_risk_scorer

    feats = _features(16)
    feats = feats / np.linalg.norm(feats, axis=-1, keepdims=True)
    # queries deliberately NOT normalized: the scorer must normalize
    q = np.stack([feats[3] * 7.5, feats[11] * 0.2])
    sims, idx = make_risk_scorer(3)(feats, q.astype(np.float32))
    sims, idx = np.asarray(sims), np.asarray(idx)
    assert idx[0, 0] == 3 and idx[1, 0] == 11
    np.testing.assert_allclose(sims[:, 0], [1.0, 1.0], atol=1e-5)
    assert (np.diff(sims, axis=1) <= 1e-6).all(), "top-k must sort desc"
    expected = feats @ feats[3]
    np.testing.assert_allclose(sims[0], np.sort(expected)[::-1][:3],
                               atol=1e-5)


@pytest.mark.fast
def test_prepare_images_matches_embed_pipeline_transform(tmp_path):
    """An index embedded from saved PNGs must score a live float image of
    the same pixels at ~1.0 — which requires prepare_images to be the
    embed pipeline's folder transform exactly, uint8 round-trip included."""
    from PIL import Image

    from dcr_tpu.eval.features import (IMAGENET_NORM, EvalImageFolder,
                                       reference_resize_for)

    img = _grad_image(1, size=24)
    Image.fromarray((img * 255).round().astype(np.uint8)).save(
        tmp_path / "gen_0.png")
    folder = EvalImageFolder(tmp_path, 16,
                             resize_to=reference_resize_for(16),
                             normalize=IMAGENET_NORM)
    via_disk = folder.load(0)
    via_live = prepare_images(img[None], 16)[0]
    np.testing.assert_allclose(via_live, via_disk, atol=1e-6)


@pytest.mark.fast
def test_decode_image_b64(cpu_devices):
    img = _grad_image(2)
    arr = decode_image_b64({"image_png_b64": _png_b64(img)})
    assert arr.shape == (16, 16, 3) and 0.0 <= arr.min() <= arr.max() <= 1.0
    with pytest.raises(ValueError, match="image_png_b64"):
        decode_image_b64({})
    with pytest.raises(ValueError, match="undecodable"):
        decode_image_b64({"image_png_b64": "bm90IGFuIGltYWdl"})


@pytest.mark.fast
def test_risk_config_validation():
    from dcr_tpu.core.config import (ServeConfig, TrainConfig,
                                     validate_serve_config,
                                     validate_train_config)

    cfg = ServeConfig()
    cfg.risk.top_k = 0
    with pytest.raises(ValueError, match="top_k"):
        validate_serve_config(cfg)
    cfg.risk.top_k = 1
    cfg.risk.image_size = 8
    with pytest.raises(ValueError, match="image_size"):
        validate_serve_config(cfg)
    cfg.risk.image_size = 224
    cfg.risk.max_evidence = -1
    with pytest.raises(ValueError, match="max_evidence"):
        validate_serve_config(cfg)
    # the trainer path validates the same block: a bad --risk.* must fail
    # at config time, not as a per-interval score_failed counter
    tcfg = TrainConfig()
    tcfg.risk.top_k = 0
    with pytest.raises(ValueError, match="top_k"):
        validate_train_config(tcfg)


# ---------------------------------------------------------------------------
# evidence recorder + gallery
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_evidence_recorder_bounded(tmp_path):
    from dcr_tpu.obs.copyrisk import RiskScore

    rec = EvidenceRecorder(tmp_path / "ev", max_evidence=2)
    score = RiskScore(max_sim=0.99, top_key="train/x.png",
                      topk=[("train/x.png", 0.99)])
    img = _grad_image(0)
    first = rec.record(img, score, 0.5, request_id=1, prompt="p", seed=7)
    second = rec.record(img, score, 0.5, request_id=2, prompt="p", seed=8)
    third = rec.record(img, score, 0.5, request_id=3, prompt="p", seed=9)
    assert first is not None and second is not None and third is None
    docs = sorted((tmp_path / "ev").glob("flagged_*.json"))
    pngs = sorted((tmp_path / "ev").glob("flagged_*.png"))
    assert len(docs) == 2 and len(pngs) == 2
    doc = json.loads(docs[0].read_text())
    assert doc["top_key"] == "train/x.png" and doc["request_id"] == 1
    assert (tmp_path / "ev" / doc["image"]).exists()
    counters = tracing.registry().counters("copy_risk/")
    assert counters["copy_risk/evidence_dumped_total"] == 2
    assert counters["copy_risk/evidence_dropped_total"] == 1
    # disabled recorder: no dir, no writes, no exceptions
    assert EvidenceRecorder(None, 8).record(img, score, 0.5) is None


@pytest.mark.fast
def test_evidence_write_failure_refunds_budget(tmp_path):
    """A transient write failure must not consume the bounded evidence
    budget: once writes succeed again, the recorder still keeps evidence."""
    from dcr_tpu.obs.copyrisk import RiskScore

    blocker = tmp_path / "ev"
    blocker.write_text("a file where the evidence dir should be")
    rec = EvidenceRecorder(blocker, max_evidence=1)
    score = RiskScore(max_sim=0.99, top_key="train/x.png",
                      topk=[("train/x.png", 0.99)])
    img = _grad_image(0)
    assert rec.record(img, score, 0.5, request_id=1) is None   # mkdir fails
    assert R.counters().get("copy_risk/evidence_write_failed", 0) == 1
    blocker.unlink()                                           # disk "frees"
    assert rec.record(img, score, 0.5, request_id=2) is not None
    assert len(list(blocker.glob("flagged_*.json"))) == 1


@pytest.mark.fast
def test_flagged_pair_gallery(tmp_path):
    from PIL import Image

    from dcr_tpu.eval.gallery import flagged_pair_gallery

    flags, matches = [], []
    for i in range(3):
        f, m = tmp_path / f"flag_{i}.png", tmp_path / f"match_{i}.png"
        Image.fromarray((_grad_image(i) * 255).astype(np.uint8)).save(f)
        Image.fromarray((_grad_image(i + 5) * 255).astype(np.uint8)).save(m)
        flags.append(f)
        matches.append(m)
    pages = flagged_pair_gallery(flags, matches, [0.7, 0.9, 0.8],
                                 tmp_path / "gallery", thumb=16)
    assert len(pages) == 1 and pages[0].exists()
    assert pages[0].name == "gallery_rank0_2.png"   # ranked_galleries paging
    from PIL import Image as I

    with I.open(pages[0]) as page:
        assert page.width == 2 * 16 + 2      # [flagged | match] + pad
        assert page.height == 3 * 16 + 2 * 2
    with pytest.raises(ValueError, match="aligned"):
        flagged_pair_gallery(flags, matches[:2], [0.1, 0.2, 0.3],
                             tmp_path / "bad")
    with pytest.raises(ValueError, match="no flagged"):
        flagged_pair_gallery([], [], [], tmp_path / "empty")


# ---------------------------------------------------------------------------
# report plumbing: trace_report "Copy risk" section + tools/risk_report
# ---------------------------------------------------------------------------

def _risk_trace_records(flag_key="train/img_0001.png"):
    """Schema-valid synthetic trace: two scored serve batches + one
    training risk/score span + one flagged event."""
    base = {"pid": 0, "tid": 1, "tname": "serve-worker"}
    recs = [
        {"ph": "X", "name": "serve/risk_score", "id": 1, "ts": 1e6,
         "dur": 1500.0, "parent": None,
         "args": {"batch": 2, "sims": [0.99, 0.42],
                  "prompts": ["dup prompt", "clean prompt"],
                  "flagged": 1}, **base},
        {"ph": "X", "name": "serve/risk_score", "id": 2, "ts": 2e6,
         "dur": 1500.0, "parent": None,
         "args": {"batch": 1, "sims": [0.41], "prompts": ["clean prompt"],
                  "flagged": 0}, **base},
        {"ph": "X", "name": "risk/score", "id": 3, "ts": 3e6, "dur": 900.0,
         "parent": None, "args": {"step": 500, "sims": [0.5, 0.6]}, **base},
        {"ph": "i", "name": "risk/flagged", "id": 4, "ts": int(1.1e6),
         "parent": None,
         "args": {"request_id": 12, "max_sim": 0.99, "top_key": flag_key,
                  "prompt": "dup prompt", "seed": 7, "threshold": 0.9},
         **base},
    ]
    return recs


@pytest.mark.fast
def test_trace_report_copy_risk_section(tmp_path, capsys):
    from tools import trace_report

    trace = tmp_path / "trace.jsonl"
    trace.write_text("".join(json.dumps(r) + "\n"
                             for r in _risk_trace_records()))
    assert trace_report.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "copy risk: 5 generation(s) scored, 1 flagged" in out
    assert "train/img_0001.png" in out

    records, errors, meta = trace_report.load_fleet(
        [tmp_path], trace_report.load_schema())
    assert not errors
    summary = trace_report.summarize(records, meta)
    risk = summary["copy_risk"]
    assert risk["scored"] == 5 and risk["flagged"] == 1
    assert risk["sim_max"] == 0.99
    assert risk["flagged_train_keys"] == {"train/img_0001.png": 1}
    # risk spans categorize as "risk", not "serve"
    assert summary["categories"]["risk"]["count"] == 3


@pytest.mark.fast
def test_risk_report_per_prompt_timeline_and_gallery(tmp_path, capsys):
    from PIL import Image

    from tools import risk_report

    train_key = tmp_path / "train_img.png"
    Image.fromarray((_grad_image(4) * 255).astype(np.uint8)).save(train_key)
    trace_dir = tmp_path / "logs"
    trace_dir.mkdir()
    (trace_dir / "trace.jsonl").write_text(
        "".join(json.dumps(r) + "\n"
                for r in _risk_trace_records(flag_key=str(train_key))))
    ev = trace_dir / "risk_evidence"
    ev.mkdir()
    Image.fromarray((_grad_image(0) * 255).astype(np.uint8)).save(
        ev / "flagged_0001_12.png")
    (ev / "flagged_0001_12.json").write_text(json.dumps({
        "max_sim": 0.99, "top_key": str(train_key),
        "topk": [[str(train_key), 0.99]], "threshold": 0.9,
        "image": "flagged_0001_12.png", "request_id": 12,
        "prompt": "dup prompt", "seed": 7, "time": time.time()}))

    gallery = tmp_path / "gallery"
    assert risk_report.main([str(trace_dir),
                             "--gallery", str(gallery)]) == 0
    out = capsys.readouterr().out
    assert "dup prompt" in out and "FLAGGED" in out
    assert "5 generation(s) scored, 1 flagged" in out
    assert list(gallery.glob("gallery_rank*.png"))

    # per-prompt arithmetic: the dup prompt carries the flagged max
    records, _, _ = risk_report.TR.load_fleet(
        [trace_dir], risk_report.TR.load_schema())
    per = risk_report.per_prompt_breakdown(records)
    assert per["dup prompt"] == {"count": 1, "mean_sim": 0.99,
                                 "max_sim": 0.99, "flagged": 1}
    assert per["clean prompt"]["count"] == 2
    assert per["<train sample grid>"]["count"] == 2


@pytest.mark.fast
def test_risk_report_empty_trace(tmp_path, capsys):
    from tools import risk_report

    trace = tmp_path / "trace.jsonl"
    trace.write_text(json.dumps({
        "ph": "X", "name": "serve/request", "id": 1, "ts": 1e6, "dur": 10.0,
        "parent": None, "pid": 0, "tid": 1, "tname": "t", "args": {}}) + "\n")
    assert risk_report.main([str(tmp_path)]) == 0
    assert "nothing scored" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# fleet plumbing: lease field, supervisor health + /check routing
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_worker_lease_risk_roundtrip(tmp_path):
    from dcr_tpu.serve.fleet import (WorkerLease, fleet_paths, read_lease,
                                     write_lease)

    paths = fleet_paths(tmp_path).ensure()
    lease = WorkerLease(index=0, pid=123, port=8001, vae_scale=8,
                        lease_s=5.0, risk="ok")
    write_lease(paths, lease)
    assert read_lease(paths, 0).risk == "ok"
    # a pre-dcr-watch lease (no risk field) still parses, as "absent"
    doc = json.loads(paths.lease_file(0).read_text())
    del doc["risk"]
    paths.lease_file(0).write_text(json.dumps(doc))
    assert read_lease(paths, 0).risk == "absent"


def _stub_check_server(doc, status=200):
    """Minimal HTTP worker answering POST /check (stdlib, one thread)."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            length = int(self.headers.get("Content-Length", "0"))
            self.rfile.read(length)
            body = json.dumps(doc).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):
            pass

    httpd = HTTPServer(("127.0.0.1", 0), Handler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    return httpd, httpd.server_address[1]


def _stub_supervisor(tmp_path, index_path="some/embedding.npz"):
    from dcr_tpu.core.config import FleetConfig, ServeConfig
    from dcr_tpu.serve.supervisor import FleetSupervisor

    cfg = ServeConfig(
        fleet=FleetConfig(workers=1, dir=str(tmp_path / "fleet")),
        risk=RiskConfig(index_path=index_path))
    return FleetSupervisor(cfg)     # not .start()ed: no real spawns


@pytest.mark.fast
def test_supervisor_risk_health_transitions(tmp_path):
    from dcr_tpu.serve.fleet import WorkerLease
    from dcr_tpu.serve.supervisor import ALIVE

    sup = _stub_supervisor(tmp_path, index_path="")
    assert sup.risk_health() == "absent"      # nothing configured

    sup = _stub_supervisor(tmp_path / "b")
    assert sup.risk_health() == "loading"     # configured, no lease yet
    slot = sup._slots[0]
    slot.state = ALIVE
    slot.lease = WorkerLease(index=0, pid=1, port=1, vae_scale=8,
                             lease_s=5.0, risk="loading")
    assert sup.risk_health() == "loading"
    slot.lease.risk = "failed"
    assert sup.risk_health() == "failed"      # every reporter failed: visible
    slot.lease.risk = "ok"
    assert sup.risk_health() == "ok"
    assert sup.health_doc()["risk"] == "ok"
    assert sup.status()["workers"][0]["risk"] == "ok"
    sup.journal.close()


@pytest.mark.fast
def test_supervisor_check_routes_to_risk_ok_worker(tmp_path):
    from dcr_tpu.obs.copyrisk import RiskUnavailableError
    from dcr_tpu.serve.fleet import WorkerLease
    from dcr_tpu.serve.supervisor import ALIVE

    sup = _stub_supervisor(tmp_path)
    with pytest.raises(RiskUnavailableError) as exc:
        sup.check({"image_png_b64": "ignored"})
    assert exc.value.status == "loading"

    doc = {"max_sim": 0.97, "top_key": "train/x.png", "flagged": True,
           "topk": [["train/x.png", 0.97]], "threshold": 0.5}
    httpd, port = _stub_check_server(doc)
    try:
        slot = sup._slots[0]
        slot.state = ALIVE
        slot.lease = WorkerLease(index=0, pid=1, port=port, vae_scale=8,
                                 lease_s=5.0, risk="ok")
        got = sup.check({"image_png_b64": "ignored"})
        assert got == {**doc, "worker": 0}
        # a worker whose index failed must NOT be routed to
        slot.lease.risk = "failed"
        with pytest.raises(RiskUnavailableError) as exc:
            sup.check({"image_png_b64": "ignored"})
        assert exc.value.status == "failed"
    finally:
        httpd.shutdown()
        sup.journal.close()


@pytest.mark.fast
def test_supervisor_check_fails_over_dead_worker(tmp_path):
    """The crash race the fleet exists for: the first risk-ready worker
    dies between the lease read and the POST — /check must fail over to
    the next ready lease, not 500."""
    import socket

    from dcr_tpu.obs.copyrisk import RiskUnavailableError
    from dcr_tpu.serve.fleet import WorkerLease
    from dcr_tpu.serve.supervisor import ALIVE, _WorkerSlot

    def dead_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]     # closed: connections refused

    sup = _stub_supervisor(tmp_path)
    sup._slots.append(_WorkerSlot(1))
    doc = {"max_sim": 0.4, "top_key": "train/y.png", "flagged": False,
           "topk": [["train/y.png", 0.4]], "threshold": 0.5}
    httpd, live_port = _stub_check_server(doc)
    try:
        for slot, port in zip(sup._slots, (dead_port(), live_port)):
            slot.state = ALIVE
            slot.lease = WorkerLease(index=slot.index, pid=1, port=port,
                                     vae_scale=8, lease_s=5.0, risk="ok")
        got = sup.check({"image_png_b64": "ignored"})
        assert got == {**doc, "worker": 1}      # served by the survivor
        assert R.counters()["fleet_check_transport_errors"] == 1
        # both dead: typed 503, never an unhandled transport error
        sup._slots[1].lease.port = dead_port()
        httpd.shutdown()
        with pytest.raises(RiskUnavailableError):
            sup.check({"image_png_b64": "ignored"})
    finally:
        sup.journal.close()


# ---------------------------------------------------------------------------
# slow tier: real tiny stack
# ---------------------------------------------------------------------------

def _tiny_stack():
    from tests.test_serve import _tiny_stack as build

    return build()


def _risk_service(stack, risk=None, **cfg_kw):
    from dcr_tpu.core.config import ServeConfig
    from dcr_tpu.serve.worker import GenerationService

    kw = dict(resolution=16, num_inference_steps=2, sampler="ddim",
              max_batch=4, max_wait_ms=30.0, queue_depth=32, seed=0)
    kw.update(cfg_kw)
    cfg = ServeConfig(**kw)
    if risk is not None:
        cfg.risk = risk
    svc = GenerationService(cfg, stack)
    svc.start()
    return svc


def _build_index_from_images(tmp_path, images, image_size=32):
    """Save images as the 'train set', embed with the real pipeline."""
    from PIL import Image

    from dcr_tpu.core.config import SearchConfig
    from dcr_tpu.search.embed import embed_images

    train = tmp_path / "train"
    train.mkdir(exist_ok=True)
    for i, img in enumerate(images):
        Image.fromarray((np.clip(img, 0, 1) * 255).round().astype(
            np.uint8)).save(train / f"gen_{i}.png")
    return embed_images(SearchConfig(image_size=image_size, batch_size=4),
                        source=train)


@pytest.mark.slow
def test_serve_flags_reproduced_train_image_and_stays_bit_identical(
        tmp_path, cpu_devices):
    """The acceptance core, in-process: a request seeded to reproduce a
    train image is flagged (copy_risk.max_sim >= threshold, flagged counter
    bumps, evidence dump written) while a normal request is not, and images
    are bit-identical with scoring on vs off."""
    stack = _tiny_stack()
    plain = _risk_service(stack)
    img_train = plain.submit("a red square", seed=1).future.result(timeout=300)
    img_clean = plain.submit("a blue circle", seed=2).future.result(timeout=300)
    plain.stop(timeout=60)

    index_path = _build_index_from_images(tmp_path, [img_train])

    # threshold strictly between the reproduced image's ~1.0 and the
    # unrelated image's background similarity (random-init SSCD backgrounds
    # run high, so the margin is measured, not assumed)
    probe = CopyRiskIndex.load(
        RiskConfig(index_path=str(index_path), image_size=32), batch=4)
    sim_hit = probe.score_batch(img_train[None])[0].max_sim
    sim_miss = probe.score_batch(img_clean[None])[0].max_sim
    assert sim_hit > sim_miss + 0.005, (sim_hit, sim_miss)
    threshold = (sim_hit + sim_miss) / 2

    risk = RiskConfig(index_path=str(index_path), image_size=32,
                      threshold=threshold,
                      evidence_dir=str(tmp_path / "ev"), max_evidence=4)
    svc = _risk_service(stack, risk=risk)
    assert svc.wait_risk_ready(timeout=300) and svc.risk_status() == "ok"

    req_hit = svc.submit("a red square", seed=1)
    req_miss = svc.submit("a blue circle", seed=2)
    out_hit = req_hit.future.result(timeout=300)
    out_miss = req_miss.future.result(timeout=300)

    assert req_hit.risk["flagged"] is True
    assert req_hit.risk["max_sim"] >= threshold
    assert req_hit.risk["top_key"].endswith("gen_0.png")
    assert req_miss.risk["flagged"] is False
    # bit-identical with scoring on vs off
    assert np.array_equal(out_hit, img_train)
    assert np.array_equal(out_miss, img_clean)
    # telemetry: flagged counter, sim histogram, evidence dump
    counters = tracing.registry().counters("copy_risk/")
    assert counters["copy_risk/flagged_total"] == 1
    assert counters["copy_risk/scored_total"] >= 2
    evidence = sorted((tmp_path / "ev").glob("flagged_*.json"))
    assert len(evidence) == 1
    doc = json.loads(evidence[0].read_text())
    assert doc["request_id"] == req_hit.id and doc["prompt"] == "a red square"
    # /check: the train image itself is flagged; garbage body is a 400-class
    check = svc.check({"image_png_b64": _png_b64(img_train)})
    assert check["flagged"] is True and check["index_size"] == 1
    with pytest.raises(ValueError):
        svc.check({"image_png_b64": "!!!"})
    assert svc.health_doc()["risk"] == "ok"
    svc.stop(timeout=60)


@pytest.mark.slow
def test_failed_index_load_degrades_to_unscored_serving(tmp_path,
                                                        cpu_devices):
    """A bad index file must produce risk=failed + a counter — and a worker
    that still answers /generate (unscored), with /check a typed 503."""
    from dcr_tpu.obs.copyrisk import RiskUnavailableError

    bad = tmp_path / "embedding.npz"
    bad.write_bytes(b"garbage")
    stack = _tiny_stack()
    svc = _risk_service(stack, risk=RiskConfig(index_path=str(bad),
                                               image_size=32))
    assert svc.wait_risk_ready(timeout=120)
    assert svc.risk_status() == "failed"
    assert svc.health_doc()["risk"] == "failed"
    assert R.counters().get("copy_risk/index_load_failed", 0) == 1
    req = svc.submit("still serving", seed=3)
    assert req.future.result(timeout=300) is not None
    assert req.risk is None
    with pytest.raises(RiskUnavailableError) as exc:
        svc.check({"image_png_b64": "x"})
    assert exc.value.status == "failed"
    svc.stop(timeout=60)


@pytest.mark.slow
def test_trainer_sample_hook_emits_risk_gauges(tmp_path, cpu_devices):
    """score_sample_grid with a stub trainer: risk/* gauges through
    MetricWriter (jsonl + registry), risk/score span recorded."""
    from dcr_tpu.core.config import TrainConfig
    from dcr_tpu.core.metrics import MetricWriter
    from dcr_tpu.diffusion.sample_hook import score_sample_grid

    imgs = [np.clip(_grad_image(i), 0, 1) for i in range(2)]
    index_path = _build_index_from_images(tmp_path, [imgs[0]])

    cfg = TrainConfig(output_dir=str(tmp_path / "run"))
    cfg.risk = RiskConfig(index_path=str(index_path), image_size=32,
                          threshold=0.999)

    class StubTrainer:
        pass

    trainer = StubTrainer()
    trainer.cfg = cfg
    trainer.writer = MetricWriter(tmp_path / "logs", use_tensorboard=False)
    state = {}
    tracing.configure(tmp_path / "trace")
    score_sample_grid(trainer, state, 500, np.stack(imgs))
    # the index memoizes in hook state; a second call reuses it
    first_index = state["risk_index"]
    score_sample_grid(trainer, state, 1000, np.stack(imgs))
    assert state["risk_index"] is first_index is not None
    trainer.writer.close()

    metrics = [json.loads(l) for l in
               (tmp_path / "logs" / "metrics.jsonl").read_text().splitlines()]
    assert [row["step"] for row in metrics] == [500, 1000]
    row = metrics[0]
    assert row["risk/scored"] == 2 and row["risk/flagged"] == 1
    assert row["risk/max_sim"] >= 0.999
    # gauges mirrored into the registry (the /metrics surface)
    assert tracing.registry().snapshot()["gauges"]["risk/max_sim"] >= 0.999
    # spans: risk/score recorded with sims
    trace = (tmp_path / "trace" / "trace.jsonl").read_text()
    assert '"risk/score"' in trace


@pytest.mark.slow
def test_serve_http_e2e_risk_and_warm_restart_zero_compiles(tmp_path,
                                                            cpu_devices):
    """Full HTTP acceptance: a dcr-serve subprocess with a risk index flags
    the reproduced request over /generate, answers POST /check, exports
    dcr_copy_risk_* Prometheus series, dumps evidence — then a SECOND
    incarnation against the same warm cache reaches risk=ok and serves a
    scored request with ZERO XLA compiles (trace_report --max-compiles 0):
    scoring does not trip the recompile budget."""
    import signal
    import subprocess
    import sys

    from tests.test_serve import _export_tiny_ckpt, _free_port, _get, _serve_env
    from tools import trace_report

    ckpt = _export_tiny_ckpt(tmp_path)
    env, repo = _serve_env()
    # no XLA persistent cache in the subprocesses: with it active this
    # jaxlib emits unserializable executables, every warm entry degrades to
    # the export tier, and incarnation 2's compile-on-load would
    # (correctly) fail the --max-compiles 0 gate (same discipline as the
    # test_warmcache restart e2e)
    for k in list(env):
        if k.startswith("JAX_COMPILATION") or k.startswith("JAX_PERSISTENT"):
            env.pop(k)

    # train image + threshold from an offline probe of the same stack
    stack = _tiny_stack()
    plain = _risk_service(stack, max_batch=2)
    img_train = plain.submit("a red square", seed=1).future.result(timeout=300)
    img_clean = plain.submit("a blue circle", seed=2).future.result(timeout=300)
    plain.stop(timeout=60)
    index_path = _build_index_from_images(tmp_path, [img_train])
    probe = CopyRiskIndex.load(
        RiskConfig(index_path=str(index_path), image_size=32), batch=2)
    sim_hit = probe.score_batch(img_train[None])[0].max_sim
    sim_miss = probe.score_batch(img_clean[None])[0].max_sim
    threshold = (sim_hit + sim_miss) / 2

    warm_dir = tmp_path / "warmcache"

    def spawn(logdir):
        port = _free_port()
        argv = [sys.executable, "-m", "dcr_tpu.cli.serve",
                f"--model_path={ckpt}", f"--port={port}",
                "--resolution=16", "--num_inference_steps=2",
                "--sampler=ddim", "--max_batch=2", "--max_wait_ms=100",
                "--queue_depth=16", "--request_timeout_s=300", "--seed=0",
                f"--logdir={logdir}", f"--warm.dir={warm_dir}",
                f"--risk.index_path={index_path}", "--risk.image_size=32",
                f"--risk.threshold={threshold}"]
        proc = subprocess.Popen(argv, env=env, cwd=repo,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)
        deadline = time.monotonic() + 300
        while True:
            try:
                status, health = _get(port, "/healthz", timeout=2)
                if health["status"] == "ok" and health["risk"] == "ok":
                    break
            except OSError:
                pass
            if proc.poll() is not None or time.monotonic() > deadline:
                out = proc.stdout.read() if proc.stdout else ""
                raise AssertionError(
                    f"server not ready (rc={proc.poll()}): {out[-3000:]}")
            time.sleep(0.5)
        return proc, port

    def post(port, path, payload, timeout=300):
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def drain(proc):
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=120) == 83      # EXIT_PREEMPTED

    log1 = tmp_path / "log1"
    proc, port = spawn(log1)
    try:
        status, doc_hit = post(port, "/generate",
                               {"prompt": "a red square", "seed": 1})
        assert status == 200
        assert doc_hit["copy_risk"]["flagged"] is True
        assert doc_hit["copy_risk"]["max_sim"] >= threshold
        status, doc_miss = post(port, "/generate",
                                {"prompt": "a blue circle", "seed": 2})
        assert status == 200 and doc_miss["copy_risk"]["flagged"] is False
        # bit-identical to the risk-off in-process generation
        from PIL import Image

        with Image.open(io.BytesIO(
                base64.b64decode(doc_hit["image_png_b64"]))) as im:
            served = np.asarray(im, np.uint8)
        expected = (np.clip(img_train, 0, 1) * 255).round().astype(np.uint8)
        assert np.array_equal(served, expected)
        # /check over HTTP
        status, check = post(port, "/check",
                             {"image_png_b64": _png_b64(img_train)})
        assert status == 200 and check["flagged"] is True
        # prometheus export carries the dcr_copy_risk_* family
        import urllib.request

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics?format=prometheus",
                timeout=10) as resp:
            prom = resp.read().decode()
        assert "dcr_copy_risk_flagged_total 1" in prom
        assert "dcr_copy_risk_sim" in prom
        # evidence dump landed under the logdir
        assert list((log1 / "risk_evidence").glob("flagged_*.json"))
    finally:
        if proc.poll() is None:
            drain(proc)

    # incarnation 2: same warm dir, fresh logdir — risk-ready with ZERO
    # compiles, and a scored request still flags
    log2 = tmp_path / "log2"
    proc, port = spawn(log2)
    try:
        status, doc = post(port, "/generate",
                           {"prompt": "a red square", "seed": 1})
        assert status == 200 and doc["copy_risk"]["flagged"] is True
    finally:
        if proc.poll() is None:
            drain(proc)
    assert trace_report.main([str(log2), "--max-compiles", "0"]) == 0

"""Blockwise 8-bit AdamW (core/adam8bit.py — the reference's CUDA-only
bitsandbytes --use_8bit_adam role, diff_train.py:424-435)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dcr_tpu.core import adam8bit as A8

# unit tests are fast-tier; the full-train-step integration test traces the
# whole tiny model (~50s on one core) and lives in the slow tier
fast = pytest.mark.fast


@fast
def test_linear_roundtrip_bound(rng_np):
    x = jnp.asarray(rng_np.standard_normal(10_000).astype(np.float32)) * 3.0
    t = A8.quantize_linear(x)
    assert t.q.dtype == jnp.int8
    back = A8.dequantize_linear(t, x.shape, x.size)
    # symmetric int8: error <= half a step of the block's absmax
    blocks = np.asarray(x.ravel())
    pad = (-blocks.size) % A8.BLOCK
    blocks = np.pad(blocks, (0, pad)).reshape(-1, A8.BLOCK)
    bound = np.abs(blocks).max(axis=1, keepdims=True) / 127.0
    err = np.abs(np.asarray(back) - np.asarray(x)).reshape(-1)[:x.size]
    assert (err <= np.repeat(bound, A8.BLOCK, 1).reshape(-1)[:x.size] + 1e-7).all()


@fast
def test_log_roundtrip_relative_error(rng_np):
    # 6 decades of magnitude in one tensor: the regime where linear int8
    # fails and the log code must hold ~3% relative error
    mags = rng_np.uniform(-6, 0, 10_000).astype(np.float32)
    x = jnp.asarray(10.0 ** mags)
    t = A8.quantize_log(x)
    assert t.q.dtype == jnp.uint8
    back = np.asarray(A8.dequantize_log(t, x.shape, x.size))
    rel = np.abs(back - np.asarray(x)) / np.asarray(x)
    assert np.median(rel) < 0.02
    assert rel.max() < 0.04
    # exact zeros stay exact
    z = A8.quantize_log(jnp.zeros(512))
    assert float(jnp.max(A8.dequantize_log(z, (512,), 512))) == 0.0


@fast
def test_spike_block_zero_grad_does_not_diverge():
    """Regression: one coordinate's v dwarfed by a spike elsewhere in its
    block must NOT quantize to the exact-zero code — a later zero-gradient
    step would then divide its surviving m by eps and emit a divergent
    update (observed 854468 vs exact adam's 0.9 before the clamp)."""
    tx = A8.scale_by_adam8(min_quantize_size=1)
    ref = optax.scale_by_adam()
    w = jnp.zeros((A8.BLOCK,))
    s8, sref = tx.init(w), ref.init(w)
    # step 1: coordinate 0 takes a huge spike, coordinate 1 a small gradient
    g1 = jnp.zeros((A8.BLOCK,)).at[0].set(1e3).at[1].set(1e-2)
    u8, s8 = tx.update(g1, s8, w)
    uref, sref = ref.update(g1, sref, w)
    # step 2: coordinate 1's gradient is zero (e.g. embedding row absent)
    g2 = jnp.zeros((A8.BLOCK,))
    u8, s8 = tx.update(g2, s8, w)
    uref, sref = ref.update(g2, sref, w)
    assert abs(float(u8[1])) < 10 * abs(float(uref[1])) + 1e-3, float(u8[1])


@fast
def test_state_is_8bit_and_small(rng_np):
    params = {"w": jnp.asarray(rng_np.standard_normal((128, 128)), jnp.float32),
              "b": jnp.zeros((16,))}
    tx = A8.adamw8bit(1e-3)
    state = tx.init(params)
    mo = state[0].moments
    assert mo["w"].m.q.dtype == jnp.int8
    assert mo["w"].v.q.dtype == jnp.uint8
    assert isinstance(mo["b"], dict)        # tiny leaf stays f32
    w_bytes = (mo["w"].m.q.nbytes + mo["w"].m.scale.nbytes
               + mo["w"].v.q.nbytes + mo["w"].v.scale.nbytes)
    assert w_bytes < 0.3 * (2 * 4 * 128 * 128)   # vs two f32 moments


@fast
def test_tracks_exact_adamw_on_quadratic(rng_np):
    """200 steps on a least-squares problem: the 8-bit trajectory must reach
    within 2x of exact adamw's final loss (and both must crush the start)."""
    A = jnp.asarray(rng_np.standard_normal((64, 4096)).astype(np.float32) / 64)
    y = jnp.asarray(rng_np.standard_normal(64).astype(np.float32))

    def loss(w):
        return jnp.mean((A @ w - y) ** 2)

    def run(tx):
        w0 = jnp.zeros((4096,))
        state0 = tx.init(w0)

        @jax.jit
        def many(w, state):
            def body(carry, _):
                w, state = carry
                g = jax.grad(loss)(w)
                updates, state = tx.update(g, state, w)
                return (optax.apply_updates(w, updates), state), ()

            (w, state), _ = jax.lax.scan(body, (w, state), None, length=200)
            return w

        return float(loss(many(w0, state0)))

    l8 = run(A8.adamw8bit(1e-2, weight_decay=0.0))
    lref = run(optax.adamw(1e-2, weight_decay=0.0))
    l0 = float(loss(jnp.zeros((4096,))))
    assert l8 < 0.1 * l0                    # actually optimizes
    assert l8 < max(2.0 * lref, lref + 1e-4)


@pytest.mark.slow
def test_train_step_with_8bit_adam(cpu_devices):
    """Full tiny train step with use_8bit_adam: loss finite, opt state holds
    int8 moment codes for the big leaves."""
    from dcr_tpu.core import rng as rngmod
    from dcr_tpu.core.config import MeshConfig, ModelConfig, TrainConfig
    from dcr_tpu.diffusion import train as T
    from dcr_tpu.diffusion.trainer import build_models
    from dcr_tpu.parallel import mesh as pmesh

    cfg = TrainConfig(mixed_precision="no")
    cfg.optim = dataclasses.replace(cfg.optim, use_8bit_adam=True,
                                    lr_warmup_steps=0)
    cfg.model = ModelConfig.tiny()
    cfg.mesh = MeshConfig(data=-1)
    mesh = pmesh.make_mesh(cfg.mesh)
    models, params = build_models(cfg, jax.random.key(0), mesh=mesh)
    state = T.init_train_state(cfg, models, unet_params=params["unet"],
                               text_params=params["text"],
                               vae_params=params["vae"])
    state = T.shard_train_state(state, mesh)
    batch = pmesh.shard_batch(mesh, {
        "pixel_values": np.random.default_rng(0).standard_normal(
            (8, 16, 16, 3)).astype(np.float32),
        "input_ids": np.ones((8, cfg.model.text_max_length), np.int32),
    })
    state, m = T.make_train_step(cfg, models, mesh)(state, batch,
                                                    rngmod.root_key(0))
    assert np.isfinite(float(jax.device_get(m["loss"])))
    int8_leaves = [x for x in jax.tree.leaves(state.opt_state)
                   if hasattr(x, "dtype") and x.dtype == jnp.int8]
    assert int8_leaves, "no quantized moment state found in opt_state"

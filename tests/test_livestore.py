"""dcr-live: crash-safe streaming provenance ingest (ISSUE 16).

The recovery matrix for search/livestore.py + serve/ingest.py: WAL frame
scanning and torn-tail truncation at every byte boundary, single-writer
lease contention (in-process and two-process) with stale takeover, crash-
during-compaction snapshot rollback, reader snapshot isolation, the
bounded never-blocks ingest queue, the CLI recover/compact surface — and
the crash-equivalence gate: subprocesses SIGKILLed mid-append and
mid-compaction recover into a store that answers queries EXACTLY equal
(scores and keys) to a post-hoc rebuilt store over the acked rows.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from dcr_tpu.core import tracing
from dcr_tpu.search.livestore import (COMMIT_MAGIC, LiveStore, RECORD_MAGIC,
                                      _encode_record, load_wal_tail,
                                      query_live, scan_wal_bytes)
from dcr_tpu.search.store import (EmbeddingStoreReader, EmbeddingStoreWriter,
                                  CURRENT_NAME, StoreError,
                                  StoreLeaseHeldError,
                                  StoreSnapshotChangedError, StoreWriterLease,
                                  read_store_manifest, snapshot_version)
from dcr_tpu.utils import faults

DIM = 8


def _counter(name: str) -> int:
    reg = tracing.registry()
    return {**reg.counters("ingest/"), **reg.counters("search/")}.get(name, 0)


def _rows(rng, n, dim=DIM):
    return rng.standard_normal((n, dim)).astype(np.float32)


def _fill(live, rows_mat, prefix="k", batch=4):
    seqs = []
    for start in range(0, rows_mat.shape[0], batch):
        chunk = rows_mat[start:start + batch]
        seqs.append(live.append(
            chunk, [f"{prefix}{start + j}" for j in range(len(chunk))]))
    return seqs


def _child_env():
    repo = Path(__file__).parent.parent
    env = {k: v for k, v in os.environ.items() if k != "DCR_FAULTS"}
    env["PYTHONPATH"] = str(repo) + os.pathsep + env.get("PYTHONPATH", "")
    return env, repo


# ---------------------------------------------------------------------------
# 1. WAL framing + the torn-tail truncation matrix
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_wal_record_roundtrip_and_garbage_suffix(rng_np):
    feats = _rows(rng_np, 3)
    keys = np.asarray(["a", "b", "c"], dtype=str)
    blob = _encode_record(7, feats, keys)
    records, good_end = scan_wal_bytes(blob)
    assert good_end == len(blob) and len(records) == 1
    seq, f, k = records[0]
    assert seq == 7 and np.array_equal(f, feats) and list(k) == ["a", "b", "c"]
    # a garbage suffix after a committed frame is a torn tail, not a crash
    records, good_end = scan_wal_bytes(blob + b"\x00garbage")
    assert len(records) == 1 and good_end == len(blob)


@pytest.mark.fast
def test_torn_tail_truncated_at_every_frame_boundary(rng_np):
    """A crash can interrupt the writer between ANY two bytes: whatever
    prefix of the last frame survives, scanning keeps exactly the committed
    records and reports the torn offset."""
    r1 = _encode_record(1, _rows(rng_np, 2), np.asarray(["a", "b"]))
    r2 = _encode_record(2, _rows(rng_np, 2), np.asarray(["c", "d"]))
    cuts = [
        len(r1) + 2,                              # inside r2's magic
        len(r1) + 6,                              # inside the header length
        len(r1) + 30,                             # inside the header JSON
        len(r1) + len(r2) // 2,                   # inside the payload
        len(r1) + len(r2) - len(COMMIT_MAGIC),    # before the commit marker
        len(r1) + len(r2) - 1,                    # inside the commit marker
    ]
    for cut in cuts:
        records, good_end = scan_wal_bytes((r1 + r2)[:cut])
        assert len(records) == 1 and good_end == len(r1), cut
    # bit rot inside the payload: sha mismatch = torn, never served
    damaged = bytearray(r1 + r2)
    damaged[len(r1) + 60] ^= 0xFF
    records, good_end = scan_wal_bytes(bytes(damaged))
    assert len(records) == 1 and good_end == len(r1)


@pytest.mark.fast
def test_recovery_truncates_torn_tail_counts_and_serves_acked(tmp_path,
                                                              rng_np):
    store = tmp_path / "store"
    rows_mat = _rows(rng_np, 8)
    with LiveStore.open(store, embed_dim=DIM) as live:
        _fill(live, rows_mat, batch=4)
    wal = sorted((store / "wal").glob("wal_*.log"))[-1]
    data = wal.read_bytes()
    wal.write_bytes(data[:len(data) - 5])  # tear the second record
    before = _counter("ingest/torn_total")
    with LiveStore.open(store) as live:
        assert live.torn_segments == 1
        assert live.recovered_rows == 4          # the acked-and-committed rows
        feats, keys = live.tail()
        assert np.array_equal(feats, rows_mat[:4])
        # recovery truncated: the next append lands after the good prefix
        live.append(rows_mat[4:], [f"re{j}" for j in range(4)])
    assert _counter("ingest/torn_total") == before + 1
    with LiveStore.open(store) as live:
        assert live.torn_segments == 0           # truncation healed the file
        feats, keys = live.tail()
        assert feats.shape[0] == 8 and list(keys[4:]) == [
            f"re{j}" for j in range(4)]


@pytest.mark.fast
def test_append_validation_rejects_bad_batches(tmp_path, rng_np):
    with LiveStore.open(tmp_path / "s", embed_dim=DIM) as live:
        live.append(_rows(rng_np, 2), ["a", "b"])
        with pytest.raises(StoreError, match="width"):
            live.append(rng_np.standard_normal((2, 5)).astype(np.float32),
                        ["a", "b"])
        with pytest.raises(StoreError, match="keys"):
            live.append(_rows(rng_np, 2), ["a"])
        with pytest.raises(StoreError, match="empty"):
            live.append(np.zeros((0, DIM), np.float32), [])
        bad = _rows(rng_np, 2)
        bad[1, 3] = np.nan
        with pytest.raises(StoreError, match="finite"):
            live.append(bad, ["a", "b"])


# ---------------------------------------------------------------------------
# 2. compaction: versioned snapshots, idempotent replay, WAL GC
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_compact_publishes_versioned_snapshots(tmp_path, rng_np):
    store = tmp_path / "store"
    rows_mat = _rows(rng_np, 12)
    with LiveStore.open(store, embed_dim=DIM, seal_rows=4) as live:
        _fill(live, rows_mat[:8], batch=4)
        rep = live.compact()
        assert rep["snapshot"] == 1 and rep["folded_rows"] == 8
        assert (store / "store_manifest.v1.json").exists()
        assert (store / CURRENT_NAME).read_text().strip().endswith("v1.json")
        assert not list((store / "wal").glob("wal_*.log"))  # folded + GC'd
        _fill(live, rows_mat[8:], prefix="t", batch=4)
        assert live.compact()["snapshot"] == 2
    doc = read_store_manifest(store)
    assert doc["snapshot"] == 2 and doc["total"] == 12
    assert doc["wal_through"] == 3               # 3 appends -> seqs 1..3
    reader = EmbeddingStoreReader(store)
    assert reader.snapshot == 2 and reader.total == 12
    # v1 manifest remains on disk: in-flight readers keep their snapshot
    assert (store / "store_manifest.v1.json").exists()


@pytest.mark.fast
def test_recovery_skips_rows_already_folded(tmp_path, rng_np):
    """Crash between manifest commit and WAL GC: the segment survives but
    every record's seq <= wal_through — replay must not double-ingest."""
    store = tmp_path / "store"
    rows_mat = _rows(rng_np, 8)
    with LiveStore.open(store, embed_dim=DIM) as live:
        _fill(live, rows_mat, batch=4)
        wal_files = sorted((store / "wal").glob("wal_*.log"))
        stash = [(p.name, p.read_bytes()) for p in wal_files]
        live.compact()
    for name, data in stash:                     # resurrect the folded WAL
        (store / "wal" / name).write_bytes(data)
    before = _counter("ingest/recovered_total")
    with LiveStore.open(store) as live:
        assert live.recovered_rows == 0          # nothing unfolded
        assert live.total_rows == 8              # and nothing doubled
        assert not list((store / "wal").glob("wal_*.log"))  # GC finished
    assert _counter("ingest/recovered_total") == before


@pytest.mark.fast
def test_live_store_refuses_normalized_store(tmp_path, rng_np):
    store = tmp_path / "store"
    w = EmbeddingStoreWriter(store, embed_dim=DIM, normalize=True)
    w.add(_rows(rng_np, 4), [f"k{j}" for j in range(4)])
    w.finalize()
    with pytest.raises(StoreError, match="normaliz"):
        LiveStore.open(store)


@pytest.mark.fast
def test_seal_rows_rolls_wal_segments(tmp_path, rng_np):
    store = tmp_path / "store"
    with LiveStore.open(store, embed_dim=DIM, seal_rows=4) as live:
        _fill(live, _rows(rng_np, 12), batch=4)
    assert len(list((store / "wal").glob("wal_*.log"))) == 3


# ---------------------------------------------------------------------------
# 3. the writer lease: one writer per store, stale takeover
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_concurrent_builders_get_typed_lease_error(tmp_path, rng_np):
    store = tmp_path / "store"
    w1 = EmbeddingStoreWriter(store, embed_dim=DIM)
    with pytest.raises(StoreLeaseHeldError, match="one writer per store"):
        EmbeddingStoreWriter(store, embed_dim=DIM)
    with pytest.raises(StoreLeaseHeldError):
        LiveStore.open(store)
    w1.add(_rows(rng_np, 4), [f"k{j}" for j in range(4)])
    w1.finalize()                                # releases the lease
    with LiveStore.open(store) as live:          # now acquirable
        assert live.committed_total == 4


@pytest.mark.fast
def test_two_process_writer_contention(tmp_path, rng_np):
    """A second PROCESS appending to a held store gets the typed error —
    the ROADMAP-flagged single-builder race, closed."""
    store = tmp_path / "store"
    w = EmbeddingStoreWriter(store, embed_dim=DIM)
    w.add(_rows(rng_np, 4), [f"k{j}" for j in range(4)])
    env, repo = _child_env()
    child = (
        "import sys\n"
        "from dcr_tpu.search.store import EmbeddingStoreWriter, "
        "StoreLeaseHeldError\n"
        "try:\n"
        f"    EmbeddingStoreWriter({str(store)!r}, embed_dim={DIM})\n"
        "except StoreLeaseHeldError as e:\n"
        "    print('HELD:', e); sys.exit(21)\n"
        "sys.exit(0)\n")
    proc = subprocess.run([sys.executable, "-c", child], env=env, cwd=repo,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 21, proc.stdout + proc.stderr
    assert "writer lease held" in proc.stdout
    w.finalize()


@pytest.mark.fast
def test_stale_lease_taken_over(tmp_path, rng_np):
    store = tmp_path / "store"
    live = LiveStore.open(store, embed_dim=DIM, lease_s=0.3)
    live._lease._thread = None                   # silence its heartbeat
    live._lease._stop.set()
    time.sleep(0.5)                              # let the lease expire
    before = _counter("search/store_lease_takeover")
    with LiveStore.open(store, embed_dim=DIM) as live2:
        live2.append(_rows(rng_np, 2), ["a", "b"])
    assert _counter("search/store_lease_takeover") == before + 1


# ---------------------------------------------------------------------------
# 4. reader snapshot isolation
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_reader_raises_typed_retryable_on_current_swap(tmp_path, rng_np):
    store = tmp_path / "store"
    rows_mat = _rows(rng_np, 12)
    with LiveStore.open(store, embed_dim=DIM, store_shard_rows=2) as live:
        _fill(live, rows_mat[:8], batch=2)       # 4 shards via compaction
        live.compact()
    reader = EmbeddingStoreReader(store)
    it = reader.iter_shards()
    next(it)                                     # mid-iteration...
    with LiveStore.open(store,
                        store_shard_rows=2) as live:  # ...the snapshot moves
        _fill(live, rows_mat[8:], prefix="t", batch=4)
        live.compact()
    with pytest.raises(StoreSnapshotChangedError, match="re-open") as ei:
        for _ in it:
            pass
    assert ei.value.retryable is True
    # the retry lands on the new snapshot and reads a consistent corpus
    reader2 = EmbeddingStoreReader(store)
    assert reader2.snapshot == 2
    assert sum(f.shape[0] for f, _ in reader2.iter_shards()) == 12


@pytest.mark.fast
def test_query_live_pairs_engine_snapshot_with_wal_tail(tmp_path, rng_np,
                                                        cpu_devices):
    """Committed + tail = one consistent corpus: no row twice, none lost,
    and results EXACTLY equal a one-shot rebuilt store."""
    rows_mat = _rows(rng_np, 24)
    keys = [f"k{j:02d}" for j in range(24)]
    live_dir = tmp_path / "live"
    with LiveStore.open(live_dir, embed_dim=DIM) as live:
        for s in range(0, 16, 4):
            live.append(rows_mat[s:s + 4], keys[s:s + 4])
        live.compact()
        for s in range(16, 24, 4):
            live.append(rows_mat[s:s + 4], keys[s:s + 4])
    rebuilt_dir = tmp_path / "rebuilt"
    w = EmbeddingStoreWriter(rebuilt_dir, embed_dim=DIM)
    w.add(rows_mat, keys)
    w.finalize()
    q = _rows(rng_np, 5)
    from dcr_tpu.search.shardindex import open_engine

    live_scores, live_keys = query_live(live_dir, q, top_k=3,
                                        segment_rows=8)
    reb_scores, reb_keys = open_engine(rebuilt_dir, top_k=3, query_batch=5,
                                       segment_rows=8).query(q)
    assert np.array_equal(live_scores, reb_scores)
    assert np.array_equal(np.asarray(live_keys, dtype=str),
                          np.asarray(reb_keys, dtype=str))


@pytest.mark.fast
def test_query_live_tail_only_matches_numpy_brute(tmp_path, rng_np,
                                                  cpu_devices):
    store = tmp_path / "walonly"
    rows_mat = _rows(rng_np, 10)
    with LiveStore.open(store, embed_dim=DIM) as live:
        _fill(live, rows_mat, batch=5)
    q = _rows(rng_np, 3)
    scores, keys = query_live(store, q, top_k=2)
    sims = q @ rows_mat.T
    expect = np.sort(sims, axis=1)[:, ::-1][:, :2]
    assert np.allclose(scores, expect, atol=1e-6)
    with pytest.raises(StoreError, match="neither"):
        query_live(tmp_path / "empty", q, top_k=1)


# ---------------------------------------------------------------------------
# 5. crash equivalence: SIGKILL mid-append and mid-compaction
# ---------------------------------------------------------------------------

def _open_live_retry(store_dir: Path, timeout: float = 60.0, **kw) -> LiveStore:
    """Open after a SIGKILLed writer: its heartbeat died with it, so the
    lease must AGE OUT before takeover — exactly the production restart."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            return LiveStore.open(store_dir, **kw)
        except StoreLeaseHeldError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.3)


def _rebuild_over(store_dir: Path, out_dir: Path) -> int:
    """Post-hoc rebuild: committed shards + every acked WAL row."""
    w = EmbeddingStoreWriter(out_dir, embed_dim=DIM)
    total = 0
    if (store_dir / CURRENT_NAME).exists() or (
            store_dir / "store_manifest.json").exists():
        for feats, keys in EmbeddingStoreReader(store_dir).iter_shards():
            w.add(feats, [str(k) for k in keys])
            total += feats.shape[0]
    feats, keys, _ = load_wal_tail(store_dir, embed_dim=DIM)
    if len(feats):
        w.add(feats, [str(k) for k in keys])
        total += feats.shape[0]
    w.finalize()
    return total


_CHILD_APPEND = """
import sys
import numpy as np
from dcr_tpu.search.livestore import LiveStore
from dcr_tpu.utils import faults

store, spec = sys.argv[1], sys.argv[2]
faults.install(spec)
rng = np.random.default_rng(11)
with LiveStore.open(store, embed_dim={dim}, lease_s=2.0) as live:
    for i in range(10):
        live.append(rng.standard_normal((3, {dim})).astype(np.float32),
                    ["b%d_%d" % (i, j) for j in range(3)])
print("SURVIVED")  # only reachable if the fault never fired
sys.exit(7)
"""

_CHILD_COMPACT = """
import sys
import numpy as np
from dcr_tpu.search.livestore import LiveStore
from dcr_tpu.utils import faults

store, spec = sys.argv[1], sys.argv[2]
faults.install(spec)
rng = np.random.default_rng(12)
with LiveStore.open(store, lease_s=2.0) as live:
    live.append(rng.standard_normal((4, {dim})).astype(np.float32),
                ["c%d" % j for j in range(4)])
    live.compact()
print("SURVIVED")
sys.exit(7)
"""


def _run_child(script, store, spec, *, expect_sigkill=True):
    env, repo = _child_env()
    proc = subprocess.run(
        [sys.executable, "-c", script.format(dim=DIM), str(store), spec],
        env=env, cwd=repo, capture_output=True, text=True, timeout=240)
    if expect_sigkill:
        assert proc.returncode == -signal.SIGKILL, (
            proc.returncode, proc.stdout, proc.stderr)
    return proc


@pytest.mark.fast
def test_sigkill_mid_append_recovers_query_equal(tmp_path, rng_np,
                                                 cpu_devices):
    """The crash-equivalence gate, kill point 1: a child process dies BY
    SIGKILL halfway through an append; recovery serves exactly the acked
    rows, query-equal (scores AND keys) to a post-hoc rebuilt store."""
    store = tmp_path / "store"
    # a committed base + live WAL before the crash
    with LiveStore.open(store, embed_dim=DIM) as live:
        _fill(live, _rows(rng_np, 8), prefix="base", batch=4)
        live.compact()
    _run_child(_CHILD_APPEND, store, "ingest_crash@append=4")
    before = _counter("ingest/torn_total")
    with _open_live_retry(store) as live:        # stale lease taken over
        assert live.torn_segments >= 1           # the partial frame
        assert live.recovered_rows == 12         # appends 0..3 acked, 3 rows each
        report = live.report()
    assert _counter("ingest/torn_total") == before + 1
    rebuilt = tmp_path / "rebuilt"
    assert _rebuild_over(store, rebuilt) == 8 + 12
    q = _rows(rng_np, 4)
    from dcr_tpu.search.shardindex import open_engine

    live_scores, live_keys = query_live(store, q, top_k=3, segment_rows=8)
    reb_scores, reb_keys = open_engine(rebuilt, top_k=3, query_batch=4,
                                       segment_rows=8).query(q)
    assert np.array_equal(live_scores, reb_scores), report
    assert np.array_equal(np.asarray(live_keys, dtype=str),
                          np.asarray(reb_keys, dtype=str))


@pytest.mark.fast
def test_sigkill_mid_compaction_previous_snapshot_serves(tmp_path, rng_np,
                                                         cpu_devices):
    """Kill point 2: SIGKILL lands after the new manifest is written but
    before the CURRENT flip. The previous snapshot keeps serving, the WAL
    replays, the next compaction self-heals — and the final store is
    query-equal to the rebuild."""
    store = tmp_path / "store"
    with LiveStore.open(store, embed_dim=DIM) as live:
        _fill(live, _rows(rng_np, 8), prefix="base", batch=4)
        live.compact()                           # snapshot v1
    _run_child(_CHILD_COMPACT, store, "compact_crash@seal=0")
    # the commit point never happened: v1 still serves
    assert snapshot_version(store) == 1
    assert read_store_manifest(store)["total"] == 8
    # the orphaned v2 manifest may exist — it must be ignored and later
    # overwritten, never served
    feats, keys, stats = load_wal_tail(store, embed_dim=DIM)
    assert feats.shape[0] == 4                   # the acked crash-era rows
    with _open_live_retry(store) as live:
        assert live.snapshot == 1 and live.recovered_rows == 4
        rep = live.compact()                     # self-heals: v2 for real
        assert rep["snapshot"] == 2
    assert read_store_manifest(store)["total"] == 12
    rebuilt = tmp_path / "rebuilt"
    assert _rebuild_over(store, rebuilt) == 12
    q = _rows(rng_np, 4)
    from dcr_tpu.search.shardindex import open_engine

    live_scores, live_keys = query_live(store, q, top_k=2, segment_rows=8)
    reb_scores, reb_keys = open_engine(rebuilt, top_k=2, query_batch=4,
                                       segment_rows=8).query(q)
    assert np.array_equal(live_scores, reb_scores)
    assert np.array_equal(np.asarray(live_keys, dtype=str),
                          np.asarray(reb_keys, dtype=str))


@pytest.mark.fast
def test_wal_torn_fault_rolls_segment_and_preserves_later_appends(
        tmp_path, rng_np):
    """The in-process wal_torn fault writes a torn frame WITHOUT acking;
    the segment rolls so later appends stay recoverable."""
    store = tmp_path / "store"
    faults.install("wal_torn@append=1")
    try:
        with LiveStore.open(store, embed_dim=DIM) as live:
            live.append(_rows(rng_np, 2), ["a", "b"])
            with pytest.raises(StoreError, match="wal_torn"):
                live.append(_rows(rng_np, 2), ["c", "d"])
            live.append(_rows(rng_np, 2), ["e", "f"])
    finally:
        faults.clear()
    with LiveStore.open(store) as live:
        assert live.torn_segments == 1
        feats, keys = live.tail()
        assert list(keys) == ["a", "b", "e", "f"]    # torn rows never served


# ---------------------------------------------------------------------------
# 6. the serve ingest pump: bounded, never blocks, drops-and-counts
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_pump_offer_never_blocks_and_drops_when_full(tmp_path, rng_np):
    from dcr_tpu.serve.ingest import IngestPump

    store = tmp_path / "store"
    # hold the lease so the pump can never open the store: the queue fills
    blocker = StoreWriterLease(store, owner="blocker").acquire()
    try:
        pump = IngestPump(store, embed_dim=DIM, queue_max=4, batch_rows=2,
                          lease_s=30.0).start()
        before = _counter("ingest/dropped_total")
        accepted = dropped = 0
        t0 = time.perf_counter()
        for i in range(32):
            if pump.offer(_rows(rng_np, 1)[0], f"g{i}"):
                accepted += 1
            else:
                dropped += 1
        elapsed = time.perf_counter() - t0
        assert accepted == 4 and dropped == 28
        assert _counter("ingest/dropped_total") == before + 28
        assert pump.dropped_rows == 28
        assert elapsed < 1.0                     # 32 offers, zero blocking
        deadline = time.monotonic() + 20
        while pump.status != "waiting_lease" and time.monotonic() < deadline:
            time.sleep(0.05)
        assert pump.status == "waiting_lease"
        assert pump.stats()["queued"] == 4
        pump.stop(timeout=5.0)
    finally:
        blocker.release()


@pytest.mark.fast
def test_pump_appends_compacts_and_fires_snapshot_callback(tmp_path, rng_np):
    from dcr_tpu.serve.ingest import IngestPump

    store = tmp_path / "store"
    snapshots = []
    with IngestPump(store, embed_dim=DIM, queue_max=64, batch_rows=4,
                    compact_rows=8,
                    on_snapshot=snapshots.append) as pump:
        for i in range(16):
            assert pump.offer(_rows(rng_np, 1)[0], f"g{i}")
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            s = pump.stats()
            if s["appended_rows"] >= 16 and s.get("compactions", 0) >= 1:
                break
            time.sleep(0.05)
        s = pump.stats()
        assert s["appended_rows"] == 16 and s["compactions"] >= 1, s
    assert snapshots and snapshots[0] >= 1
    reader = EmbeddingStoreReader(store)
    recovered = load_wal_tail(store, embed_dim=DIM)[0].shape[0]
    assert reader.total + recovered == 16        # every acked row durable


# ---------------------------------------------------------------------------
# 7. CLI + bench + schema surfaces
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_cli_recover_and_compact(tmp_path, rng_np, capsys):
    from dcr_tpu.cli import search as cli

    store = tmp_path / "store"
    with LiveStore.open(store, embed_dim=DIM) as live:
        _fill(live, _rows(rng_np, 8), batch=4)
    cli.main(["recover", f"--store_dir={store}"])
    rep = json.loads(capsys.readouterr().out)
    assert rep["tail_rows"] == 8 and rep["snapshot"] == 0
    cli.main(["compact", f"--store_dir={store}"])
    rep = json.loads(capsys.readouterr().out)
    assert rep["compaction"]["snapshot"] == 1
    assert read_store_manifest(store)["total"] == 8


@pytest.mark.fast
def test_banked_bench_ingest_schema():
    from tools.bench_ingest import validate_result

    banked = Path(__file__).parent.parent / "BENCH_INGEST.json"
    assert banked.exists(), "BENCH_INGEST.json must be committed"
    doc = json.loads(banked.read_text())
    assert validate_result(doc) == []
    assert doc["equality"] == {"scores_equal": True, "keys_equal": True}
    assert doc["response_path"]["passed"] is True


@pytest.mark.fast
def test_trace_schema_and_report_know_ingest():
    from tools import trace_report

    schema = json.loads(
        (Path(__file__).parent.parent / "tools" /
         "trace_schema.json").read_text())
    assert "ingest/" in schema["known_names"]["span_prefixes"]
    for name in ("ingest/append", "ingest/compact", "ingest/recover"):
        assert name in schema["known_names"]["spans"]
    records = [
        {"ph": "X", "name": "ingest/append", "id": 1, "ts": 1e6, "dur": 900.0,
         "pid": 1, "tid": 1, "tname": "t", "args": {"rows": 16}},
        {"ph": "X", "name": "ingest/compact", "id": 2, "ts": 2e6,
         "dur": 5000.0, "pid": 1, "tid": 1, "tname": "t",
         "args": {"rows": 16, "records": 4, "snapshot": 1}},
        {"ph": "X", "name": "ingest/recover", "id": 3, "ts": 3e6,
         "dur": 700.0, "pid": 1, "tid": 1, "tname": "t",
         "args": {"rows": 4, "torn": 1, "segments": 2}},
    ]
    summary = trace_report.ingest_summary(records)
    assert summary["append"]["rows"] == 16
    assert summary["compactions"][0]["snapshot"] == 1
    assert summary["recoveries"][0]["torn"] == 1
    text = trace_report.render_text(
        trace_report.summarize(records), [Path(".")])
    assert "ingest:" in text and "snapshot v1" in text


@pytest.mark.fast
def test_ingest_metrics_have_required_prometheus_names(tmp_path, rng_np):
    with LiveStore.open(tmp_path / "s", embed_dim=DIM) as live:
        live.append(_rows(rng_np, 2), ["a", "b"])
        live.compact()
    text = tracing.registry().prometheus_text()
    for metric in ("dcr_ingest_acked_total", "dcr_store_rows_total"):
        assert metric in text, metric
    # the full required surface resolves through the same sanitizer
    assert tracing.sanitize_metric_name(
        "ingest/lag_seconds") == "dcr_ingest_lag_seconds"
    assert tracing.sanitize_metric_name(
        "ingest/queue_depth") == "dcr_ingest_queue_depth"
    for name in ("dropped", "recovered", "torn"):
        assert tracing.sanitize_metric_name(
            f"ingest/{name}_total") == f"dcr_ingest_{name}_total"


# ---------------------------------------------------------------------------
# 8. slow: the live-ingesting serve worker, crash-equivalent end to end
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_serve_live_ingest_check_sees_new_generations_and_snapshots(
        tmp_path, cpu_devices):
    """In-process serve e2e: with ingest on, a generation streamed into the
    store is findable by /check IMMEDIATELY (live tail), then still after
    compaction publishes a new snapshot (engine refresh, no restart) — and
    the check result equals the same check against a post-hoc rebuilt
    store over the acked rows."""
    from tests.test_risk import _png_b64, _risk_service, _tiny_stack
    from tests.test_store import _embed_train_images
    from dcr_tpu.core.config import IngestConfig, RiskConfig
    from dcr_tpu.obs.copyrisk import CopyRiskIndex

    stack = _tiny_stack()
    plain = _risk_service(stack)
    img_train = plain.submit("a red square", seed=1).future.result(timeout=300)
    img_new = plain.submit("a blue circle", seed=2).future.result(timeout=300)
    plain.stop(timeout=60)

    store = tmp_path / "livestore"
    writer = EmbeddingStoreWriter.create(store, shard_rows=4)
    writer.add_dump(_embed_train_images(tmp_path, [img_train]))
    writer.finalize()

    ingest = IngestConfig(enabled=True, queue_max=64, batch_rows=1,
                          seal_rows=8, compact_rows=2)
    risk = RiskConfig(store_dir=str(store), image_size=32, threshold=0.999)
    svc = _risk_service(stack, risk=risk, ingest=ingest)
    try:
        assert svc.wait_risk_ready(timeout=300) and svc.risk_status() == "ok"
        req = svc.submit("a blue circle", seed=2)
        out = np.asarray(req.future.result(timeout=300))
        assert np.array_equal(out, img_new)      # ingest never perturbs
        # the scored generation becomes durable + queryable without restart
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            stats = svc._pump.stats() if svc._pump else {}
            if stats.get("appended_rows", 0) >= 1:
                break
            time.sleep(0.1)
        assert stats.get("appended_rows", 0) >= 1, stats
        check = svc.check({"image_png_b64": _png_b64(img_new)})
        assert check["max_sim"] > 0.999
        assert check["top_key"].startswith("gen/"), check
        # drive past compact_rows: the snapshot advances and /check still
        # answers from the refreshed engine — no restart, no duplicate rows
        svc.submit("a red square", seed=3).future.result(timeout=300)
        svc.submit("a blue circle", seed=4).future.result(timeout=300)
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline:
            stats = svc._pump.stats()
            if stats.get("compactions", 0) >= 1 and stats.get(
                    "appended_rows", 0) >= 3:
                break
            time.sleep(0.1)
        assert stats.get("compactions", 0) >= 1, stats
        check2 = svc.check({"image_png_b64": _png_b64(img_new)})
        assert check2["max_sim"] > 0.999
        assert check2["top_key"] == check["top_key"]
        assert svc.health_doc()["ingest"]["status"] == "ok"
    finally:
        svc.stop(timeout=120)

    # crash-equivalence of the final state: recover the live store and pin
    # /check (score_batch) equal against a post-hoc rebuild over acked rows
    with LiveStore.open(store) as live:
        live.compact()
    rebuilt = tmp_path / "rebuilt"
    w = EmbeddingStoreWriter(rebuilt, embed_dim=512)
    for feats, keys in EmbeddingStoreReader(store).iter_shards():
        w.add(feats, [str(k) for k in keys])
    w.finalize()
    probe_live = CopyRiskIndex.load(
        RiskConfig(store_dir=str(store), image_size=32), batch=4)
    probe_reb = CopyRiskIndex.load(
        RiskConfig(store_dir=str(rebuilt), image_size=32), batch=4)
    s_live = probe_live.score_batch(img_new[None])[0]
    s_reb = probe_reb.score_batch(img_new[None])[0]
    assert s_live.max_sim == s_reb.max_sim
    assert s_live.top_key == s_reb.top_key


@pytest.mark.slow
def test_serve_subprocess_sigkill_mid_ingest_recovers_equal(tmp_path,
                                                            cpu_devices):
    """The full chaos e2e over HTTP: a live-ingesting dcr-serve subprocess
    is SIGKILLed MID-APPEND by the ingest_crash fault; a fresh incarnation
    recovers the WAL (stale lease taken over, torn tail truncated) and
    serves /check answers equal to a post-hoc rebuilt store over the acked
    rows. Unacked rows may be lost; nothing is corrupted."""
    import urllib.request

    from tests.test_risk import _png_b64, _risk_service, _tiny_stack
    from tests.test_store import _embed_train_images
    from tests.test_serve import (_export_tiny_ckpt, _free_port, _get,
                                  _serve_env)
    from dcr_tpu.core.config import RiskConfig
    from dcr_tpu.obs.copyrisk import CopyRiskIndex

    stack = _tiny_stack()
    plain = _risk_service(stack, max_batch=2)
    img_train = plain.submit("a red square", seed=1).future.result(timeout=300)
    img_probe = plain.submit("a blue circle", seed=2).future.result(
        timeout=300)
    plain.stop(timeout=60)
    store = tmp_path / "livestore"
    writer = EmbeddingStoreWriter.create(store, shard_rows=4)
    writer.add_dump(_embed_train_images(tmp_path, [img_train]))
    writer.finalize()

    ckpt = _export_tiny_ckpt(tmp_path)
    env, repo = _serve_env()

    def serve_argv(port):
        return [sys.executable, "-m", "dcr_tpu.cli.serve",
                f"--model_path={ckpt}", f"--port={port}",
                "--resolution=16", "--num_inference_steps=2",
                "--sampler=ddim", "--max_batch=2", "--max_wait_ms=100",
                "--queue_depth=16", "--request_timeout_s=300", "--seed=0",
                f"--risk.store_dir={store}", "--risk.image_size=32",
                "--risk.threshold=0.999", "--ingest.enabled=true",
                "--ingest.batch_rows=1", "--ingest.compact_rows=0",
                "--ingest.lease_s=3"]

    def wait_risk_ok(proc, port, deadline_s=300):
        deadline = time.monotonic() + deadline_s
        while True:
            try:
                _, health = _get(port, "/healthz", timeout=2)
                if health["status"] == "ok" and health["risk"] == "ok":
                    return
            except OSError:
                pass
            if proc.poll() is not None or time.monotonic() > deadline:
                out = proc.stdout.read() if proc.stdout else ""
                raise AssertionError(
                    f"server not risk-ready (rc={proc.poll()}): {out[-3000:]}")
            time.sleep(0.5)

    def post_generate(port, prompt, seed):
        body = json.dumps({"prompt": prompt, "seed": seed}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300) as resp:
            return json.loads(resp.read())

    # incarnation 1: the 2nd WAL append SIGKILLs the worker mid-frame
    port = _free_port()
    env1 = dict(env, DCR_FAULTS="ingest_crash@append=1")
    proc = subprocess.Popen(serve_argv(port), env=env1, cwd=repo,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    killed_rc = None
    try:
        wait_risk_ok(proc, port)
        for seed in (10, 11, 12):
            try:
                doc = post_generate(port, "a blue circle", seed)
                assert doc.get("copy_risk") is not None
            except OSError:
                break                            # the SIGKILL landed
        killed_rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=60)
    assert killed_rc == -signal.SIGKILL, killed_rc

    # the acked set survives on disk; the torn in-flight frame does not
    acked_feats, acked_keys, stats = load_wal_tail(store, embed_dim=512)
    assert acked_feats.shape[0] == 1, stats      # append 0 acked, 1 torn
    assert stats["torn_segments"] >= 1
    assert all(str(k).startswith("gen/") for k in acked_keys)

    # post-hoc rebuild over committed + acked rows
    rebuilt = tmp_path / "rebuilt"
    w = EmbeddingStoreWriter(rebuilt, embed_dim=512)
    for feats, keys in EmbeddingStoreReader(store).iter_shards():
        w.add(feats, [str(k) for k in keys])
    w.add(acked_feats, [str(k) for k in acked_keys])
    w.finalize()

    # incarnation 2: recovers (stale lease, torn tail) and serves /check
    port2 = _free_port()
    proc2 = subprocess.Popen(serve_argv(port2), env=env, cwd=repo,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT, text=True)
    try:
        wait_risk_ok(proc2, port2)
        deadline = time.monotonic() + 120
        while True:                              # wait for WAL recovery
            _, health = _get(port2, "/healthz", timeout=2)
            if health.get("ingest", {}).get("status") == "ok":
                break
            assert time.monotonic() < deadline, health
            time.sleep(0.5)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port2}/check",
            data=json.dumps(
                {"image_png_b64": _png_b64(img_probe)}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300) as resp:
            recovered_check = json.loads(resp.read())
    finally:
        if proc2.poll() is None:
            proc2.send_signal(signal.SIGTERM)
            proc2.wait(timeout=120)

    probe = CopyRiskIndex.load(
        RiskConfig(store_dir=str(rebuilt), image_size=32), batch=4)
    expect = probe.score_batch(img_probe[None])[0]
    assert recovered_check["max_sim"] == pytest.approx(expect.max_sim,
                                                       abs=1e-6)
    assert recovered_check["top_key"] == expect.top_key

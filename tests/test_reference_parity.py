"""Parity against activations recorded from the EXECUTED torch reference.

tests/goldens/{dino,retrieval_metrics}_reference.npz are produced by
tools/gen_reference_fixtures.py, which imports /root/reference/dino_vits.py
and /root/reference/utils_ret.py and runs them as numerical oracles
(SURVEY.md §4 item 2). These tests prove cross-framework parity of:

- the DINO VisionTransformer (reference dino_vits.py:171-275) against
  models/vit.py + convert.convert_dino_vit, including the bicubic
  positional-embedding interpolation path (dino_vits.py:213-233) and
  get_intermediate_layers (267-275);
- the retrieval-metric toolkit (utils_ret.py:322-417) against
  eval/retrieval_metrics.compute_map_revisited.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np
import pytest

GOLD = Path(__file__).parent / "goldens"


@pytest.fixture(scope="module")
def dino_ref():
    data = np.load(GOLD / "dino_reference.npz")
    sd = {k[len("sd/"):]: data[k] for k in data.files if k.startswith("sd/")}
    return data, sd


@pytest.fixture(scope="module")
def dino_params(dino_ref):
    from dcr_tpu.models.convert import convert_dino_vit

    _, sd = dino_ref
    return {"params": convert_dino_vit(sd, depth=3)}


def _model():
    from dcr_tpu.models.vit import VisionTransformer

    return VisionTransformer(patch_size=8, embed_dim=64, depth=3, num_heads=2,
                             img_size=32)


def _nhwc(x):
    return np.transpose(x, (0, 2, 3, 1))


def test_dino_vit_matches_reference_native(dino_ref, dino_params):
    data, _ = dino_ref
    out = _model().apply(dino_params, _nhwc(data["x_native"]))
    np.testing.assert_allclose(np.asarray(out), data["out_native"],
                               atol=7e-5, rtol=5e-4)


def test_dino_vit_matches_reference_interpolated(dino_ref, dino_params):
    """48px input against a 32px pos table exercises the bicubic
    interpolation path end to end (reference dino_vits.py:213-233)."""
    data, _ = dino_ref
    out = _model().apply(dino_params, _nhwc(data["x_interp"]))
    np.testing.assert_allclose(np.asarray(out), data["out_interp"],
                               atol=7e-5, rtol=5e-4)


def test_dino_vit_matches_reference_nonsquare_same_count(dino_ref, dino_params):
    """16x64 input has a 2x8 grid whose patch count equals the 4x4 table's —
    the reference interpolates anyway because the grid is non-square
    (dino_vits.py:216); skipping would silently misplace every embedding."""
    data, _ = dino_ref
    out = _model().apply(dino_params, _nhwc(data["x_rect"]))
    np.testing.assert_allclose(np.asarray(out), data["out_rect"],
                               atol=7e-5, rtol=5e-4)


def test_dino_vit_matches_reference_nondivisible_input(dino_ref, dino_params):
    """36px input with patch 8: the reference's padding-0 patch conv floors
    to a 4x4 grid (dino_vits.py:164-167); VALID padding must reproduce that
    (SAME would emit a 5x5 grid and desync from the positional table)."""
    data, _ = dino_ref
    out = _model().apply(dino_params, _nhwc(data["x_ragged"]))
    np.testing.assert_allclose(np.asarray(out), data["out_ragged"],
                               atol=7e-5, rtol=5e-4)


def test_dino_vit_matches_reference_intermediate_layers(dino_ref, dino_params):
    data, _ = dino_ref
    outs = _model().apply(dino_params, _nhwc(data["x_native"]),
                          return_layers=2)
    assert len(outs) == 2
    np.testing.assert_allclose(np.asarray(outs[0]), data["inter_0"],
                               atol=7e-5, rtol=5e-4)
    np.testing.assert_allclose(np.asarray(outs[1]), data["inter_1"],
                               atol=7e-5, rtol=5e-4)


def test_compute_map_matches_reference():
    from dcr_tpu.eval.retrieval_metrics import compute_map_revisited

    data = np.load(GOLD / "retrieval_metrics_reference.npz")
    gnd = []
    for q in range(data["ok"].shape[0]):
        ok = [int(i) for i in data["ok"][q] if i >= 0]
        junk = [int(i) for i in data["junk"][q] if i >= 0]
        gnd.append({"ok": ok, "junk": junk})
    m, pr, recs, mrr = compute_map_revisited(
        data["ranks"], gnd, [int(k) for k in data["kappas"]])
    assert m == pytest.approx(float(data["map"]), abs=1e-12)
    assert mrr == pytest.approx(float(data["mrr"]), abs=1e-12)
    np.testing.assert_allclose(pr, data["pr"], atol=1e-12)
    np.testing.assert_allclose(recs, data["recs"], atol=1e-12)

import json

import jax
import numpy as np
import pytest

from dcr_tpu.core.config import MeshConfig, ModelConfig, SampleConfig
from dcr_tpu.core import rng as rngmod
from dcr_tpu.data.tokenizer import HashTokenizer
from dcr_tpu.diffusion.trainer import build_models
from dcr_tpu.parallel import mesh as pmesh
from dcr_tpu.sampling import prompts as P
from dcr_tpu.sampling.sampler import make_sampler


@pytest.fixture(scope="module")
def tiny_models():
    from dcr_tpu.core.config import TrainConfig

    cfg = TrainConfig()
    cfg.model = ModelConfig.tiny()
    return build_models(cfg, jax.random.key(0))


def _sample_cfg(**kw):
    d = dict(resolution=16, num_inference_steps=4, guidance_scale=7.5,
             sampler="ddim", im_batch=2, seed=0)
    d.update(kw)
    return SampleConfig(**d)


def test_sampler_shapes_and_determinism(tiny_models, cpu_devices):
    models, params = tiny_models
    mesh = pmesh.make_mesh(MeshConfig())
    cfg = _sample_cfg()
    sampler = make_sampler(cfg, models, mesh)
    tok = HashTokenizer(models.text_encoder.config.text_vocab_size,
                        models.text_encoder.config.text_max_length)
    ids = np.repeat(tok(["a church", "a truck"]), 4, axis=0)  # [8, L]
    unc = np.broadcast_to(tok([""])[0], ids.shape).copy()
    p = {"unet": params["unet"], "vae": params["vae"], "text": params["text"]}
    imgs = np.asarray(sampler(p, ids, unc, rngmod.root_key(1)))
    assert imgs.shape == (8, 16, 16, 3)
    assert imgs.min() >= 0.0 and imgs.max() <= 1.0
    assert np.isfinite(imgs).all()
    imgs2 = np.asarray(sampler(p, ids, unc, rngmod.root_key(1)))
    np.testing.assert_array_equal(imgs, imgs2)
    imgs3 = np.asarray(sampler(p, ids, unc, rngmod.root_key(2)))
    assert not np.array_equal(imgs, imgs3)


@pytest.mark.parametrize("sampler_name", ["dpm++", "ddpm"])
def test_other_samplers_run(tiny_models, cpu_devices, sampler_name):
    models, params = tiny_models
    mesh = pmesh.make_mesh(MeshConfig())
    cfg = _sample_cfg(sampler=sampler_name)
    sampler = make_sampler(cfg, models, mesh)
    tok = HashTokenizer(models.text_encoder.config.text_vocab_size,
                        models.text_encoder.config.text_max_length)
    ids = np.repeat(tok(["x"]), 8, axis=0)
    unc = np.broadcast_to(tok([""])[0], ids.shape).copy()
    p = {"unet": params["unet"], "vae": params["vae"], "text": params["text"]}
    imgs = np.asarray(sampler(p, ids, unc, rngmod.root_key(0)))
    assert imgs.shape == (8, 16, 16, 3) and np.isfinite(imgs).all()


def test_rand_noise_lam_changes_output(tiny_models, cpu_devices):
    models, params = tiny_models
    mesh = pmesh.make_mesh(MeshConfig())
    tok = HashTokenizer(models.text_encoder.config.text_vocab_size,
                        models.text_encoder.config.text_max_length)
    ids = np.repeat(tok(["x"]), 8, axis=0)
    unc = np.broadcast_to(tok([""])[0], ids.shape).copy()
    p = {"unet": params["unet"], "vae": params["vae"], "text": params["text"]}
    base = np.asarray(make_sampler(_sample_cfg(), models, mesh)(p, ids, unc,
                                                                rngmod.root_key(1)))
    noised = np.asarray(make_sampler(_sample_cfg(rand_noise_lam=0.5), models, mesh)(
        p, ids, unc, rngmod.root_key(1)))
    assert not np.array_equal(base, noised)


def test_prompt_lists_all_styles(tmp_path):
    tok = HashTokenizer(1000, 16)
    assert P.build_prompt_list("nolevel", 3, seed=0, tokenizer=tok) == ["An image"] * 3
    cl = P.build_prompt_list("classlevel", 5, seed=0, tokenizer=tok)
    assert len(cl) == 5 and all(p.startswith("An image of ") for p in cl)
    assert cl == P.build_prompt_list("classlevel", 5, seed=0, tokenizer=tok)
    assert cl != P.build_prompt_list("classlevel", 5, seed=1, tokenizer=tok)

    caps = {f"img{i}": [f"caption number {i}", "alt"] for i in range(10)}
    j = tmp_path / "caps.json"
    j.write_text(json.dumps(caps))
    bl = P.build_prompt_list("instancelevel_blip", 4, seed=0, tokenizer=tok,
                             caption_json=j)
    assert len(bl) == 4 and all(p.startswith("caption number") for p in bl)

    rnd_caps = {f"img{i}": [str([i + 1, i + 2, i + 3])] for i in range(5)}
    j2 = tmp_path / "rnd.json"
    j2.write_text(json.dumps(rnd_caps))
    rl = P.build_prompt_list("instancelevel_random", 3, seed=0, tokenizer=tok,
                             caption_json=j2)
    assert all(len(p.split()) == 3 for p in rl)

    with pytest.raises(ValueError):
        P.build_prompt_list("instancelevel_blip", 2, seed=0, tokenizer=tok)


def test_prompt_augmentations(tmp_path):
    tok = HashTokenizer(1000, 16)
    rng = np.random.default_rng(0)
    base = "a photo of a church"
    n = P.prompt_augmentation(base, "rand_numb_add", tokenizer=tok, rng=rng)
    assert len(n.split()) == 7
    assert sum(w.isdigit() for w in n.split()) == 2
    w = P.prompt_augmentation(base, "rand_word_add", tokenizer=tok, rng=rng)
    assert len(w.split()) == 7
    r = P.prompt_augmentation(base, "rand_word_repeat", tokenizer=tok, rng=rng)
    assert len(r.split()) == 7 and set(r.split()) == set(base.split())
    with pytest.raises(ValueError):
        P.prompt_augmentation(base, "bogus", tokenizer=tok, rng=rng)
    # augs gate: only instancelevel_blip (reference diff_inference.py:241-242)
    caps = {"a": ["c"]}
    j = tmp_path / "c.json"
    j.write_text(json.dumps(caps))
    with pytest.raises(ValueError):
        P.build_prompt_list("nolevel", 2, seed=0, tokenizer=tok, rand_augs="rand_word_add")


def test_save_prompts(tmp_path):
    path = P.save_prompts(["a", "b"], tmp_path / "out")
    assert path.read_text() == "a\nb\n"

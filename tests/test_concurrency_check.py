"""dcr-race self-tests: thread-safety + durability static analysis.

Mirrors tests/test_check.py's fixture style: every fixture is a small
multi-module tmp package, because the point of DCR011–DCR015 is exactly
the facts that cross a method/module boundary (thread roots, locksets
through helpers, lock-order graphs, fsync closures). Three layers:

1. per-rule positive/negative fixtures — each rule has at least one
   firing case and one structurally-similar clean case (lock through a
   helper method, exempted Queue-typed attribute, consistent lock order,
   fsync-through-helper, stored thread handle);
2. suppression round-trips — the shared ``# dcr-lint: disable=`` pragma
   and the justified-baseline file both silence a program-layer finding;
3. the repo self-scan — the full tree is clean under DCR011–DCR015 with
   every baseline entry consumed (none stale).

Pure-AST fixtures (nothing is imported at check time); fast tier.
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from tools.check.config import CheckConfig
from tools.check.engine import run_layer1, scan_program

pytestmark = pytest.mark.fast

REPO = Path(__file__).resolve().parents[1]


def write_pkg(root: Path, files: dict[str, str]) -> None:
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src, encoding="utf-8")


def race_rules(tmp_path: Path, files: dict[str, str], *,
               hot_paths=(), wal_modules=()) -> list:
    write_pkg(tmp_path, files)
    cfg = CheckConfig(roots=("pkg",), hot_paths=tuple(hot_paths),
                      entry_modules=(), wal_modules=tuple(wal_modules),
                      best_effort_writers=(), root=tmp_path,
                      manifest="compile_manifest.json")
    findings, _, _ = scan_program(cfg)
    return findings


def rule_set(findings) -> set[str]:
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# DCR011 — unguarded shared state across thread roots
# ---------------------------------------------------------------------------

def test_dcr011_unguarded_counter_fires(tmp_path):
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/pump.py": """
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()

    def _run(self):
        while True:
            self.count += 1

    def stats(self):
        return {"count": self.count}
""",
    })
    assert rule_set(findings) == {"DCR011"}
    (f,) = findings
    assert "Pump.count" in f.message and "_run" in f.message


def test_dcr011_annotated_param_helper_fires(tmp_path):
    # the racy write goes through a helper that receives the shared object
    # as an ANNOTATED parameter (`slot: Slot`) rather than iterating the
    # container — parameter annotations must type the access too
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/fleet.py": """
import threading

class Slot:
    def __init__(self):
        self.state = 0

class Fleet:
    def __init__(self):
        self._lock = threading.Lock()
        self._slots = [Slot() for _ in range(2)]
        self._thread = threading.Thread(target=self._monitor, daemon=True)

    def start(self):
        self._thread.start()

    def _monitor(self):
        for slot in self._slots:
            self._bump(slot)

    def _bump(self, slot: Slot):
        slot.state += 1

    def status(self):
        out = []
        with self._lock:
            for s in self._slots:
                out.append(s.state)
        return out
""",
    })
    assert rule_set(findings) == {"DCR011"}
    assert any("Slot.state" in f.message for f in findings)


def test_dcr011_annotated_param_guarded_is_clean(tmp_path):
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/fleet.py": """
import threading

class Slot:
    def __init__(self):
        self.state = 0

class Fleet:
    def __init__(self):
        self._lock = threading.Lock()
        self._slots = [Slot() for _ in range(2)]
        self._thread = threading.Thread(target=self._monitor, daemon=True)

    def start(self):
        self._thread.start()

    def _monitor(self):
        for slot in self._slots:
            with self._lock:
                self._bump(slot)

    def _bump(self, slot: Slot):
        slot.state += 1

    def status(self):
        out = []
        with self._lock:
            for s in self._slots:
                out.append(s.state)
        return out
""",
    })
    assert findings == []


def test_dcr011_lock_through_helper_is_clean(tmp_path):
    # the write happens inside a private helper whose EVERY call site holds
    # the lock — the guaranteed-lockset fixpoint must resolve it
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/pump.py": """
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()

    def _bump(self):
        self.count += 1

    def _run(self):
        while True:
            with self._lock:
                self._bump()

    def stats(self):
        with self._lock:
            return {"count": self.count}
""",
    })
    assert findings == []


def test_dcr011_queue_typed_attr_is_exempt(tmp_path):
    # queue.Queue is internally synchronized: cross-thread use is its job
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/pump.py": """
import queue
import threading

class Pump:
    def __init__(self):
        self.q = queue.Queue(maxsize=8)
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()

    def _run(self):
        while True:
            self.q.put_nowait(1)

    def take(self):
        return self.q.get(timeout=1.0)
""",
    })
    assert findings == []


def test_dcr011_no_thread_entry_is_clean(tmp_path):
    # a class that never starts a thread has a single root: no pair exists
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/plain.py": """
class Plain:
    def __init__(self):
        self.count = 0

    def bump(self):
        self.count += 1

    def stats(self):
        return self.count
""",
    })
    assert findings == []


# ---------------------------------------------------------------------------
# DCR012 — lock-order inversion / deadlock cycles
# ---------------------------------------------------------------------------

THREE_LOCK_CYCLE = """
import threading

class C:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._c = threading.Lock()

    def m1(self):
        with self._a:
            with self._b:
                pass

    def m2(self):
        with self._b:
            with self._c:
                pass

    def m3(self):
        with self._c:
            with self._a:
                pass
"""


def test_dcr012_three_lock_cycle_fires(tmp_path):
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/locks.py": THREE_LOCK_CYCLE,
    })
    assert rule_set(findings) == {"DCR012"}
    msg = findings[0].message
    # the witness path names all three locks
    for attr in ("_a", "_b", "_c"):
        assert attr in msg


def test_dcr012_consistent_order_is_clean(tmp_path):
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/locks.py": """
import threading

class C:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def m1(self):
        with self._a:
            with self._b:
                pass

    def m2(self):
        with self._a:
            with self._b:
                pass
""",
    })
    assert findings == []


def test_dcr012_interprocedural_cycle_through_call(tmp_path):
    # m3 holds _c and CALLS m1, which acquires _a: the c->a edge exists
    # only through the call graph
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/locks.py": """
import threading

class C:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._c = threading.Lock()

    def m1(self):
        with self._a:
            with self._b:
                pass

    def m2(self):
        with self._b:
            with self._c:
                pass

    def m3(self):
        with self._c:
            self.m1()
""",
    })
    assert "DCR012" in rule_set(findings)


def test_dcr012_nonreentrant_self_deadlock(tmp_path):
    # plain Lock re-acquired under itself deadlocks; RLock is fine
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/locks.py": """
import threading

class C:
    def __init__(self):
        self._mu = threading.Lock()
        self._re = threading.RLock()

    def bad(self):
        with self._mu:
            with self._mu:
                pass

    def fine(self):
        with self._re:
            with self._re:
                pass
""",
    })
    assert rule_set(findings) == {"DCR012"}
    assert all("_mu" in f.message for f in findings)


# ---------------------------------------------------------------------------
# DCR013 — blocking call under a held lock (hot paths)
# ---------------------------------------------------------------------------

SLEEPER = """
import threading
import time

class S:
    def __init__(self):
        self._mu = threading.Lock()

    def bad(self):
        with self._mu:
            time.sleep(1.0)

    def fine(self):
        time.sleep(1.0)
        with self._mu:
            pass
"""


def test_dcr013_sleep_under_lock_on_hot_path(tmp_path):
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/hot.py": SLEEPER,
    }, hot_paths=("pkg/",))
    assert rule_set(findings) == {"DCR013"}
    (f,) = findings
    assert "time.sleep" in f.message and "_mu" in f.message


def test_dcr013_silent_off_hot_path(tmp_path):
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/cold.py": SLEEPER,
    }, hot_paths=())
    assert findings == []


def test_dcr013_untimed_queue_get_under_lock(tmp_path):
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/hot.py": """
import queue
import threading

class S:
    def __init__(self):
        self._mu = threading.Lock()
        self.q = queue.Queue()

    def bad(self):
        with self._mu:
            return self.q.get()

    def fine(self):
        with self._mu:
            return self.q.get(timeout=0.5)
""",
    }, hot_paths=("pkg/",))
    assert rule_set(findings) == {"DCR013"}


# ---------------------------------------------------------------------------
# DCR014 — torn publish / ack-before-fsync
# ---------------------------------------------------------------------------

def test_dcr014_rename_without_fsync_fires(tmp_path):
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/save.py": """
import json
import os

def publish(path, doc):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(doc))
    os.replace(tmp, path)
""",
    })
    assert rule_set(findings) == {"DCR014"}


def test_dcr014_fsync_before_rename_is_clean(tmp_path):
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/save.py": """
import json
import os

def publish(path, doc):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(doc))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
""",
    })
    assert findings == []


def test_dcr014_fsync_through_helper_is_resolved(tmp_path):
    # the fsync lives in another module's helper; the call-graph closure
    # must credit it to the publishing scope
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/fsio.py": """
import os

def flush_hard(f):
    f.flush()
    os.fsync(f.fileno())
""",
        "pkg/save.py": """
import os
from pkg.fsio import flush_hard

def publish(path, blob):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        flush_hard(f)
    os.replace(tmp, path)
""",
    })
    assert findings == []


def test_dcr014_pure_rename_is_exempt(tmp_path):
    # rotation/quarantine: nothing was written, nothing can be torn
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/rotate.py": """
import os

def quarantine(path):
    os.replace(path, path + ".quarantined")
""",
    })
    assert findings == []


def test_dcr014_wal_ack_without_fsync_fires(tmp_path):
    files = {
        "pkg/__init__.py": "",
        "pkg/wal.py": """
def append(f, record):
    f.write(record)
    f.flush()
    return True
""",
    }
    findings = race_rules(tmp_path, dict(files), wal_modules=("pkg/wal.py",))
    assert rule_set(findings) == {"DCR014"}
    # the same module NOT marked as WAL is clean: leg 2 is contract-scoped
    assert race_rules(tmp_path, files, wal_modules=()) == []


def test_dcr014_wal_fsync_after_last_write_is_clean(tmp_path):
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/wal.py": """
import os

def append(f, record):
    f.write(record)
    f.flush()
    os.fsync(f.fileno())
    return True
""",
    }, wal_modules=("pkg/wal.py",))
    assert findings == []


def test_dcr014_wal_staging_buffer_is_exempt(tmp_path):
    # serializing into BytesIO is not a file write — both as a .write()
    # receiver and as a serializer argument
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/wal.py": """
import io
import json

def encode(doc):
    buf = io.BytesIO()
    buf.write(b"MAGIC")
    json.dump(doc, buf)
    return buf.getvalue()
""",
    }, wal_modules=("pkg/wal.py",))
    assert findings == []


# ---------------------------------------------------------------------------
# DCR015 — leaked thread handle
# ---------------------------------------------------------------------------

def test_dcr015_discarded_thread_fires(tmp_path):
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/spawn.py": """
import threading

def fire_and_forget(fn):
    threading.Thread(target=fn, daemon=True).start()
""",
    })
    assert rule_set(findings) == {"DCR015"}


def test_dcr015_local_started_never_joined_fires(tmp_path):
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/spawn.py": """
import threading

def run(fn):
    t = threading.Thread(target=fn)
    t.start()
    return None
""",
    })
    assert rule_set(findings) == {"DCR015"}


def test_dcr015_stored_or_joined_is_clean(tmp_path):
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/spawn.py": """
import threading

class Owner:
    def __init__(self, fn):
        self._t = threading.Thread(target=fn, daemon=True)
        self._t.start()

def run_sync(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join()
""",
    })
    assert findings == []


# ---------------------------------------------------------------------------
# suppression round-trips: pragma + justified baseline
# ---------------------------------------------------------------------------

LEAKY = """
import threading

def fire_and_forget(fn):
    threading.Thread(target=fn, daemon=True).start()
"""


def test_pragma_suppresses_program_finding(tmp_path):
    findings = race_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/spawn.py": LEAKY.replace(
            ".start()", ".start()  # dcr-lint: disable=DCR015"),
    })
    assert findings == []


def test_baseline_suppresses_program_finding(tmp_path):
    write_pkg(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/spawn.py": LEAKY,
        "pyproject.toml": """
[tool.dcr-lint]
baseline = "baseline.json"

[tool.dcr-check]
roots = ["pkg"]
entry-modules = []
hot-paths = []
wal-modules = []
""",
    })
    snippet = "threading.Thread(target=fn, daemon=True).start()"
    (tmp_path / "baseline.json").write_text(json.dumps({"entries": [{
        "rule": "DCR015", "path": "pkg/spawn.py", "snippet": snippet,
        "justification": "daemon helper outlives no resource; test fixture",
    }]}))
    report = run_layer1(pyproject=tmp_path / "pyproject.toml",
                        include_local=False, manifest_path=tmp_path / "m.json")
    assert report.program == []
    assert report.local.baseline_suppressed == 1
    assert report.local.stale_baseline == []
    # without the entry the same tree fails: the suppression is doing work
    (tmp_path / "baseline.json").write_text(json.dumps({"entries": []}))
    report = run_layer1(pyproject=tmp_path / "pyproject.toml",
                        include_local=False, manifest_path=tmp_path / "m.json")
    assert [f.rule for f in report.program] == ["DCR015"]


def test_stale_program_rule_entry_is_reported(tmp_path):
    # the file-local lint layer never runs DCR011–015, so it refuses to
    # call their entries stale; run_layer1 must report an entry the
    # program scan didn't consume, or fixed hazards rot in the baseline
    write_pkg(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/spawn.py": "def quiet():\n    return 1\n",
        "pyproject.toml": """
[tool.dcr-lint]
baseline = "baseline.json"

[tool.dcr-check]
roots = ["pkg"]
entry-modules = []
hot-paths = []
wal-modules = []
""",
    })
    (tmp_path / "baseline.json").write_text(json.dumps({"entries": [{
        "rule": "DCR015", "path": "pkg/spawn.py",
        "snippet": "threading.Thread(target=fn, daemon=True).start()",
        "justification": "long gone",
    }]}))
    report = run_layer1(pyproject=tmp_path / "pyproject.toml",
                        include_local=False, manifest_path=tmp_path / "m.json")
    assert [e["rule"] for e in report.local.stale_baseline] == ["DCR015"]


# ---------------------------------------------------------------------------
# repo self-scan: the tree is race/durability-clean, baseline fully consumed
# ---------------------------------------------------------------------------

def test_repo_clean_under_concurrency_rules():
    from tools.check.config import load_check_config

    cfg = load_check_config(pyproject=REPO / "pyproject.toml")
    report = run_layer1(cfg, pyproject=REPO / "pyproject.toml",
                        include_local=False)
    mine = [f for f in report.program
            if f.rule in ("DCR011", "DCR012", "DCR013", "DCR014", "DCR015")]
    pretty = "\n".join(f"{f.path}:{f.line}: {f.rule} {f.message}"
                       for f in mine)
    assert mine == [], f"race/durability findings:\n{pretty}"
    # every DCR011–015 baseline entry still matches a real site: a fixed
    # hazard must drop its entry, not rot in the file
    stale = [e for e in report.local.stale_baseline
             if e["rule"].startswith("DCR01")]
    assert stale == [], f"stale baseline entries: {stale}"

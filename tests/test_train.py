import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcr_tpu.core.config import MeshConfig, ModelConfig, TrainConfig
from dcr_tpu.core import rng as rngmod
from dcr_tpu.diffusion import train as T
from dcr_tpu.diffusion.trainer import build_models
from dcr_tpu.parallel import mesh as pmesh


def _cfg(**kw):
    cfg = TrainConfig(**kw)
    cfg.model = ModelConfig.tiny()
    cfg.mixed_precision = "no"
    cfg.optim.learning_rate = 1e-3
    cfg.optim.lr_scheduler = "constant"
    cfg.optim.lr_warmup_steps = 0
    return cfg


def _batch(key, cfg, bsz=8):
    px = 8 * 2 ** (len(cfg.model.vae_block_out_channels) - 1)
    return {
        "pixel_values": jax.random.uniform(key, (bsz, px, px, 3)) * 2 - 1,
        "input_ids": jax.random.randint(jax.random.fold_in(key, 1),
                                        (bsz, cfg.model.text_max_length), 0,
                                        cfg.model.text_vocab_size),
        "index": jnp.arange(bsz),
    }


@pytest.fixture(scope="module")
def setup():
    cfg = _cfg()
    models, params = build_models(cfg, jax.random.key(0))
    return cfg, models, params


def _make_state(cfg, models, params, mesh):
    # the train step donates its input state; copy so the shared fixture params
    # survive across tests
    params = jax.tree.map(lambda x: jnp.array(np.asarray(x)), params)
    state = T.init_train_state(cfg, models, unet_params=params["unet"],
                               text_params=params["text"], vae_params=params["vae"])
    return T.shard_train_state(state, mesh)


def test_train_step_runs_and_loss_decreases(setup, cpu_devices):
    cfg, models, params = setup
    mesh = pmesh.make_mesh(MeshConfig())
    state = _make_state(cfg, models, params, mesh)
    step_fn = T.make_train_step(cfg, models, mesh)
    key = rngmod.root_key(0)
    batch = pmesh.shard_batch(mesh, jax.device_get(_batch(jax.random.key(1), cfg)))
    losses = []
    for _ in range(30):
        state, metrics = step_fn(state, batch, key)
        losses.append(float(metrics["loss"]))
    assert int(jax.device_get(state.step)) == 30
    assert np.isfinite(losses).all()
    # same batch repeatedly -> loss must drop substantially
    assert np.mean(losses[-5:]) < np.mean(losses[:5]) * 0.9, losses


def test_train_step_deterministic(setup, cpu_devices):
    cfg, models, params = setup
    mesh = pmesh.make_mesh(MeshConfig())
    step_fn = T.make_train_step(cfg, models, mesh)
    key = rngmod.root_key(0)
    batch = pmesh.shard_batch(mesh, jax.device_get(_batch(jax.random.key(1), cfg)))
    s1 = _make_state(cfg, models, params, mesh)
    s1, m1 = step_fn(s1, batch, key)
    s2 = _make_state(cfg, models, params, mesh)
    s2, m2 = step_fn(s2, batch, key)
    assert float(m1["loss"]) == float(m2["loss"])
    leaves1, leaves2 = jax.tree.leaves(s1.unet_params), jax.tree.leaves(s2.unet_params)
    for a, b in zip(leaves1, leaves2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fsdp_mesh_train_step(setup, cpu_devices):
    """Same step under data=4 x fsdp=2 sharding must match pure-DP numerics."""
    cfg, models, params = setup
    key = rngmod.root_key(0)
    raw = jax.device_get(_batch(jax.random.key(1), cfg))

    mesh_dp = pmesh.make_mesh(MeshConfig())
    s_dp = _make_state(cfg, models, params, mesh_dp)
    f_dp = T.make_train_step(cfg, models, mesh_dp)
    s_dp, m_dp = f_dp(s_dp, pmesh.shard_batch(mesh_dp, raw), key)

    mesh_f = pmesh.make_mesh(MeshConfig(data=-1, fsdp=2))
    s_f = _make_state(cfg, models, params, mesh_f)
    f_f = T.make_train_step(cfg, models, mesh_f)
    s_f, m_f = f_f(s_f, pmesh.shard_batch(mesh_f, raw), key)

    np.testing.assert_allclose(float(m_dp["loss"]), float(m_f["loss"]), rtol=1e-5)


def test_mitigations_change_loss(setup, cpu_devices):
    cfg, models, params = setup
    mesh = pmesh.make_mesh(MeshConfig())
    key = rngmod.root_key(0)
    batch = pmesh.shard_batch(mesh, jax.device_get(_batch(jax.random.key(1), cfg)))

    base_state = _make_state(cfg, models, params, mesh)
    _, m0 = T.make_train_step(cfg, models, mesh)(base_state, batch, key)

    cfg_noise = _cfg(rand_noise_lam=0.5)
    cfg_noise.model = cfg.model
    s = _make_state(cfg_noise, models, params, mesh)
    _, m1 = T.make_train_step(cfg_noise, models, mesh)(s, batch, key)
    assert float(m1["loss"]) != float(m0["loss"])

    cfg_mix = _cfg(mixup_noise_lam=0.3)
    cfg_mix.model = cfg.model
    s = _make_state(cfg_mix, models, params, mesh)
    _, m2 = T.make_train_step(cfg_mix, models, mesh)(s, batch, key)
    assert float(m2["loss"]) != float(m0["loss"])


def test_v_prediction_target(setup, cpu_devices):
    cfg, models, params = setup
    import dataclasses

    cfg_v = _cfg()
    cfg_v.model = dataclasses.replace(cfg.model, prediction_type="v_prediction")
    models_v, params_v = build_models(cfg_v, jax.random.key(0))
    mesh = pmesh.make_mesh(MeshConfig())
    s = _make_state(cfg_v, models_v, params_v, mesh)
    batch = pmesh.shard_batch(mesh, jax.device_get(_batch(jax.random.key(1), cfg_v)))
    s, m = T.make_train_step(cfg_v, models_v, mesh)(s, batch, rngmod.root_key(0))
    assert np.isfinite(float(m["loss"]))


def test_gradient_accumulation(setup, cpu_devices):
    cfg, models, params = setup
    import dataclasses

    cfg_ga = _cfg()
    cfg_ga.model = cfg.model
    cfg_ga.optim = dataclasses.replace(cfg_ga.optim, gradient_accumulation_steps=2)
    mesh = pmesh.make_mesh(MeshConfig())
    s = _make_state(cfg_ga, models, params, mesh)
    step_fn = T.make_train_step(cfg_ga, models, mesh)
    batch = pmesh.shard_batch(mesh, jax.device_get(_batch(jax.random.key(1), cfg_ga)))
    before = np.asarray(jax.tree.leaves(s.unet_params)[0])  # materialize pre-donation
    s, _ = step_fn(s, batch, rngmod.root_key(0))
    mid = np.asarray(jax.tree.leaves(s.unet_params)[0])
    # first micro-step: no param change yet
    np.testing.assert_array_equal(before, mid)
    s, _ = step_fn(s, batch, rngmod.root_key(0))
    after = np.asarray(jax.tree.leaves(s.unet_params)[0])
    assert not np.array_equal(mid, after)


def test_ema_updates(setup, cpu_devices):
    cfg, models, params = setup
    cfg_ema = _cfg(ema_decay=0.9)
    cfg_ema.model = cfg.model
    mesh = pmesh.make_mesh(MeshConfig())
    s = _make_state(cfg_ema, models, params, mesh)
    assert s.ema_params is not None
    step_fn = T.make_train_step(cfg_ema, models, mesh)
    batch = pmesh.shard_batch(mesh, jax.device_get(_batch(jax.random.key(1), cfg_ema)))
    p0 = np.asarray(jax.tree.leaves(s.unet_params)[0])
    s, _ = step_fn(s, batch, rngmod.root_key(0))
    ema1 = np.asarray(jax.tree.leaves(s.ema_params)[0])
    p1 = np.asarray(jax.tree.leaves(s.unet_params)[0])
    np.testing.assert_allclose(ema1, 0.9 * p0 + 0.1 * p1, atol=1e-6)


def test_train_text_encoder_updates_text_params(setup, cpu_devices):
    cfg, models, params = setup
    cfg_t = _cfg(train_text_encoder=True)
    cfg_t.model = cfg.model
    mesh = pmesh.make_mesh(MeshConfig())
    s = _make_state(cfg_t, models, params, mesh)
    step_fn = T.make_train_step(cfg_t, models, mesh)
    batch = pmesh.shard_batch(mesh, jax.device_get(_batch(jax.random.key(1), cfg_t)))
    t0 = np.asarray(jax.tree.leaves(s.text_params)[0])
    s, _ = step_fn(s, batch, rngmod.root_key(0))
    t1 = np.asarray(jax.tree.leaves(s.text_params)[0])
    assert not np.array_equal(t0, t1)
    # frozen by default
    s2 = _make_state(setup[0], models, params, mesh)
    f2 = T.make_train_step(setup[0], models, mesh)
    u0 = np.asarray(jax.tree.leaves(s2.text_params)[0])
    s2, _ = f2(s2, batch, rngmod.root_key(0))
    u1 = np.asarray(jax.tree.leaves(s2.text_params)[0])
    np.testing.assert_array_equal(u0, u1)


def test_lr_schedules():
    from dcr_tpu.core.config import OptimConfig

    sched = T.make_lr_schedule(OptimConfig(learning_rate=1e-4,
                                           lr_scheduler="constant_with_warmup",
                                           lr_warmup_steps=100))
    assert float(sched(0)) == 0.0
    assert float(sched(50)) == pytest.approx(5e-5)
    assert float(sched(100)) == pytest.approx(1e-4)
    assert float(sched(10000)) == pytest.approx(1e-4)


def test_ema_gated_on_accumulation_boundary(setup, cpu_devices):
    """Regression: EMA must blend once per optimizer update, not per micro-step."""
    import dataclasses

    cfg, models, params = setup
    cfg_ga = _cfg(ema_decay=0.5)
    cfg_ga.model = cfg.model
    cfg_ga.optim = dataclasses.replace(cfg_ga.optim, gradient_accumulation_steps=2)
    mesh = pmesh.make_mesh(MeshConfig())
    s = _make_state(cfg_ga, models, params, mesh)
    step_fn = T.make_train_step(cfg_ga, models, mesh)
    batch = pmesh.shard_batch(mesh, jax.device_get(_batch(jax.random.key(1), cfg_ga)))
    ema0 = np.asarray(jax.tree.leaves(s.ema_params)[0])
    s, m1 = step_fn(s, batch, rngmod.root_key(0))
    ema1 = np.asarray(jax.tree.leaves(s.ema_params)[0])
    np.testing.assert_array_equal(ema0, ema1)  # micro-step: no EMA move
    # lr reported as applied (first optimizer update not yet taken at micro-step 0)
    s, m2 = step_fn(s, batch, rngmod.root_key(0))
    ema2 = np.asarray(jax.tree.leaves(s.ema_params)[0])
    p2 = np.asarray(jax.tree.leaves(s.unet_params)[0])
    np.testing.assert_allclose(ema2, 0.5 * ema1 + 0.5 * p2, atol=1e-6)


def test_tensor_parallel_train_step_matches_dp(setup, cpu_devices):
    """Megatron-style TP over the tensor axis must reproduce DP numerics."""
    cfg, models, params = setup
    key = rngmod.root_key(0)
    raw = jax.device_get(_batch(jax.random.key(1), cfg))

    mesh_dp = pmesh.make_mesh(MeshConfig())
    s_dp = _make_state(cfg, models, params, mesh_dp)
    _, m_dp = T.make_train_step(cfg, models, mesh_dp)(
        s_dp, pmesh.shard_batch(mesh_dp, raw), key)

    mesh_tp = pmesh.make_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    s_tp = _make_state(cfg, models, params, mesh_tp)
    # check some transformer projection actually got tensor-sharded
    from dcr_tpu.parallel.mesh import TENSOR_AXIS

    def has_tensor_axis(tree):
        found = []
        def visit(x):
            spec = getattr(x.sharding, "spec", ())
            found.append(any(TENSOR_AXIS == s or (isinstance(s, tuple) and TENSOR_AXIS in s)
                             for s in spec if s))
        jax.tree.map(visit, tree)
        return any(found)

    assert has_tensor_axis(s_tp.unet_params)
    s_tp, m_tp = T.make_train_step(cfg, models, mesh_tp)(
        s_tp, pmesh.shard_batch(mesh_tp, raw), key)
    np.testing.assert_allclose(float(m_dp["loss"]), float(m_tp["loss"]), rtol=1e-5)
    assert int(jax.device_get(s_tp.step)) == 1


def test_ring_attention_seq_parallel_train_step(setup, cpu_devices):
    """Ring attention wired into the UNet (VERDICT round-1 item 7): a seq=2
    mesh trains one step at doubled resolution with the ring path active, and
    the loss matches the dense seq=1 run on the same params/batch."""
    import dataclasses

    cfg0, _, params = setup
    cfg = _cfg()
    # 16px latents -> S=256 top-level spatial attention; threshold 64 puts
    # every self-attention on the ring path
    cfg.model = dataclasses.replace(ModelConfig.tiny(), seq_parallel_min_seq=64)
    key = rngmod.root_key(0)
    px = 16 * 2 ** (len(cfg.model.vae_block_out_channels) - 1)
    batch = {
        "pixel_values": jax.random.uniform(jax.random.key(5), (8, px, px, 3)) * 2 - 1,
        "input_ids": jax.random.randint(jax.random.key(6),
                                        (8, cfg.model.text_max_length), 0,
                                        cfg.model.text_vocab_size),
    }

    losses = {}
    for name, mesh_cfg in (("dense", MeshConfig(data=-1)),
                           ("ring", MeshConfig(data=-1, fsdp=1, tensor=1, seq=2))):
        mesh = pmesh.make_mesh(mesh_cfg)
        models, p = build_models(cfg, jax.random.key(0), mesh=mesh)
        p = {k: jax.tree.map(lambda x: jnp.array(np.asarray(x)), params[k])
             for k in p}  # same weights for both runs
        state = T.init_train_state(cfg, models, unet_params=p["unet"],
                                   text_params=p["text"], vae_params=p["vae"])
        state = T.shard_train_state(state, mesh)
        step = T.make_train_step(cfg, models, mesh)
        state, m = step(state, pmesh.shard_batch(mesh, batch), key)
        losses[name] = float(jax.device_get(m["loss"]))
        assert np.isfinite(losses[name])
    np.testing.assert_allclose(losses["ring"], losses["dense"],
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_ring_attention_512px_geometry_matches_dense(cpu_devices):
    """512px-geometry ring guard (VERDICT r2 item 8): S=4096 top-level spatial
    self-attention — the flagship 512px latent geometry — crosses the
    PRODUCTION dispatch gate (ModelConfig.seq_parallel_min_seq default 4096,
    untouched here, guarding CrossAttention._ring_ok, models/layers.py) on a
    seq=2 mesh; one train step must match the dense seq=1 run's loss."""
    import dataclasses

    cfg = _cfg()
    # tiny channels, real 512px latent grid: 64x64 -> S=4096 at the top level.
    # one head (head_dim=32 at ch=32) keeps the dense run's S^2 logits small
    # enough for CPU while the geometry stays the production one.
    cfg.model = dataclasses.replace(ModelConfig.tiny(), sample_size=64,
                                    attention_head_dim=32)
    assert cfg.model.seq_parallel_min_seq == 4096   # the production gate
    key = rngmod.root_key(0)
    px = 64 * 2 ** (len(cfg.model.vae_block_out_channels) - 1)
    bsz = 4                                          # divisible by data=4 below
    batch = {
        "pixel_values": jax.random.uniform(jax.random.key(5), (bsz, px, px, 3)) * 2 - 1,
        "input_ids": jax.random.randint(jax.random.key(6),
                                        (bsz, cfg.model.text_max_length), 0,
                                        cfg.model.text_vocab_size),
    }

    losses = {}
    for name, mesh_cfg in (("dense", MeshConfig(data=4, fsdp=1, tensor=1, seq=1)),
                           ("ring", MeshConfig(data=2, fsdp=1, tensor=1, seq=2))):
        mesh = pmesh.make_mesh(mesh_cfg, devices=jax.devices()[:4])
        models, p = build_models(cfg, jax.random.key(0), mesh=mesh)
        state = T.init_train_state(cfg, models, unet_params=p["unet"],
                                   text_params=p["text"], vae_params=p["vae"])
        state = T.shard_train_state(state, mesh)
        step = T.make_train_step(cfg, models, mesh)
        state, m = step(state, pmesh.shard_batch(mesh, batch), key)
        losses[name] = float(jax.device_get(m["loss"]))
        assert np.isfinite(losses[name])
    np.testing.assert_allclose(losses["ring"], losses["dense"],
                               rtol=1e-5, atol=1e-5)

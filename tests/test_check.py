"""dcr-check self-tests: interprocedural rules + compile-surface manifest.

Layers mirror the dcr-lint self-tests (tests/test_lint.py):

1. per-rule positive/negative fixtures for the whole-program rules — each
   fixture is a *multi-module* tmp package, because the point of dcr-check
   is exactly the facts that cross a file boundary;
2. the manifest machinery on tiny synthetic surfaces — fingerprints are
   deterministic, an injected recompile hazard (changed static arg, changed
   aval, changed donation) produces a detected AND readable diff;
3. the repo self-scan — ``python -m tools.check --no-manifest`` is clean on
   this tree, the checked-in compile_manifest.json covers every registered
   surface, and the acceptance surfaces (train step, all default serve
   buckets, both/all samplers, eval embed) are present.

The rule fixtures are pure-AST (no jax import at check time) and ride the
fast tier; the synthetic-manifest tests use one trivial jitted lambda.
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import pytest

from tools.check.config import CheckConfig
from tools.check.engine import scan_program
from tools.check.graph import load_program
from tools.check.rules import registered_surfaces

pytestmark = pytest.mark.fast

REPO = Path(__file__).resolve().parents[1]


def write_pkg(root: Path, files: dict[str, str]) -> None:
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src, encoding="utf-8")


def program_rules(tmp_path: Path, files: dict[str, str], *,
                  hot_paths=(), entry_modules=()) -> list:
    write_pkg(tmp_path, files)
    cfg = CheckConfig(roots=("pkg",), hot_paths=tuple(hot_paths),
                      entry_modules=tuple(entry_modules), root=tmp_path,
                      manifest="compile_manifest.json")
    findings, _, _ = scan_program(cfg)
    return findings


def rule_set(findings) -> set[str]:
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# 1a. interprocedural DCR002 — donation across function/module boundaries
# ---------------------------------------------------------------------------

TRAINLIB = """
import jax
def make_step(cfg):
    def step(state, batch):
        return state
    return jax.jit(step, donate_argnums=(0,))
"""


def test_x002_cross_module_builder_use_after_donation(tmp_path):
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/trainlib.py": TRAINLIB,
        "pkg/driver.py": """
from pkg.trainlib import make_step
def run(cfg, state, batch):
    step = make_step(cfg)
    new = step(state, batch)
    return state, new
""",
    })
    assert rule_set(findings) == {"DCR002"}
    (f,) = findings
    assert "make_step" in f.message and "state" in f.message


def test_x002_rebinding_is_clean(tmp_path):
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/trainlib.py": TRAINLIB,
        "pkg/driver.py": """
from pkg.trainlib import make_step
def run(cfg, state, batches):
    step = make_step(cfg)
    for b in batches:
        state = step(state, b)
    return state
""",
    })
    assert findings == []


def test_x002_loop_without_rebind(tmp_path):
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/trainlib.py": TRAINLIB,
        "pkg/driver.py": """
from pkg.trainlib import make_step
def run(cfg, state, batches):
    step = make_step(cfg)
    out = None
    for b in batches:
        out = step(state, b)
    return out
""",
    })
    assert rule_set(findings) == {"DCR002"}


def test_x002_loop_with_later_rebind_is_clean(tmp_path):
    # `new = step(state, b); state = new` rebinds the donated chain on a
    # LATER statement of the loop body — fresh before the next iteration,
    # so this is the correct idiom, not a hazard
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/trainlib.py": TRAINLIB,
        "pkg/driver.py": """
from pkg.trainlib import make_step
def run(cfg, state, batches):
    step = make_step(cfg)
    for b in batches:
        new_state = step(state, b)
        state = new_state
    return state
""",
    })
    assert rule_set(findings) == set()


def test_x002_class_attr_donation_across_methods(tmp_path):
    trainer = """
from pkg.trainlib import make_step
class Trainer:
    def __init__(self, cfg, state):
        self.step_fn = make_step(cfg)
        self.state = state
    def run(self, batch):
        out = self.step_fn(self.state, batch)
        print(self.state)
        return out
"""
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/trainlib.py": TRAINLIB,
        "pkg/trainer.py": trainer,
    })
    assert rule_set(findings) == {"DCR002"}
    # the real Trainer idiom — `self.state, m = self.step_fn(self.state, b)`
    # — rebinds in place and must stay clean
    clean = trainer.replace(
        "        out = self.step_fn(self.state, batch)\n"
        "        print(self.state)\n"
        "        return out\n",
        "        self.state, m = self.step_fn(self.state, batch)\n"
        "        return self.state, m\n")
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/trainlib.py": TRAINLIB,
        "pkg/trainer.py": clean,
    })
    assert findings == []


def test_x002_imported_jitted_donating_fn(tmp_path):
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/steps.py": """
import jax
from functools import partial
@partial(jax.jit, donate_argnums=(0,))
def apply_update(state, grads):
    return state
""",
        "pkg/use.py": """
from pkg.steps import apply_update
def run(state, grads):
    new = apply_update(state, grads)
    return state.step, new
""",
    })
    assert rule_set(findings) == {"DCR002"}


# ---------------------------------------------------------------------------
# 1b. interprocedural DCR003 — a key consumed through callees
# ---------------------------------------------------------------------------

DRAWLIB = """
import jax
def draw_noise(key, shape):
    return jax.random.normal(key, shape)
def draw_mask(key, shape):
    return jax.random.bernoulli(key, 0.5, shape)
"""


def test_x003_key_to_two_consuming_callees(tmp_path):
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/drawlib.py": DRAWLIB,
        "pkg/use.py": """
from pkg.drawlib import draw_noise, draw_mask
def f(key):
    a = draw_noise(key, (2,))
    b = draw_mask(key, (2,))
    return a, b
""",
    })
    assert rule_set(findings) == {"DCR003"}
    assert "draw_mask" in findings[0].message


def test_x003_split_before_callees_is_clean(tmp_path):
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/drawlib.py": DRAWLIB,
        "pkg/use.py": """
import jax
from pkg.drawlib import draw_noise, draw_mask
def f(key):
    k1, k2 = jax.random.split(key)
    a = draw_noise(k1, (2,))
    b = draw_mask(k2, (2,))
    return a, b
""",
    })
    assert findings == []


def test_x003_fold_in_helper_does_not_consume(tmp_path):
    # the repo's stream_key idiom: a helper that only DERIVES (fold_in) may
    # see the same root key many times — that is the sanctioned pattern
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/rnglib.py": """
import jax
def stream_key(root, tag):
    return jax.random.fold_in(root, tag)
""",
        "pkg/use.py": """
from pkg.rnglib import stream_key
def f(key):
    k1 = stream_key(key, 1)
    k2 = stream_key(key, 2)
    return k1, k2
""",
    })
    assert findings == []


def test_x003_transitive_consumption_and_loop(tmp_path):
    files = {
        "pkg/__init__.py": "",
        "pkg/drawlib.py": DRAWLIB,
        "pkg/mid.py": """
from pkg.drawlib import draw_noise
def sample_row(key, shape):
    return draw_noise(key, shape)
""",
        "pkg/use.py": """
from pkg.mid import sample_row
def f(key, n):
    out = []
    for i in range(n):
        out.append(sample_row(key, (2,)))
    return out
""",
    }
    findings = program_rules(tmp_path, files)
    assert rule_set(findings) == {"DCR003"}
    assert "every iteration" in findings[0].message


def test_x003_exclusive_branches_clean(tmp_path):
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/drawlib.py": DRAWLIB,
        "pkg/use.py": """
from pkg.drawlib import draw_noise, draw_mask
def f(key, cond):
    if cond:
        return draw_noise(key, (2,))
    else:
        return draw_mask(key, (2,))
""",
    })
    assert findings == []


# ---------------------------------------------------------------------------
# 1c. interprocedural DCR004 — wrappers that drop the collective timeout
# ---------------------------------------------------------------------------

SYNCLIB = """
def my_gather(payload, tag, timeout_s=0):
    from pkg import dist
    return dist.kv_allgather(payload, tag, timeout_s)
"""


def test_x004_wrapper_unbounded_default(tmp_path):
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/dist.py": "def kv_allgather(payload, tag, timeout_s):\n"
                       "    return [payload]\n",
        "pkg/synclib.py": SYNCLIB,
        "pkg/use.py": """
from pkg.synclib import my_gather
def sync(x):
    return my_gather(x, "t")
""",
    })
    assert rule_set(findings) == {"DCR004"}
    assert "my_gather" in findings[0].message and "timeout_s" in findings[0].message


def test_x004_threaded_timeout_is_clean(tmp_path):
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/dist.py": "def kv_allgather(payload, tag, timeout_s):\n"
                       "    return [payload]\n",
        "pkg/synclib.py": SYNCLIB,
        "pkg/use.py": """
from pkg.synclib import my_gather
def sync(x, t):
    return my_gather(x, "t", timeout_s=t)
""",
    })
    assert findings == []


def test_x004_zero_timeout_at_wrapper_call_site(tmp_path):
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/dist.py": "def kv_allgather(payload, tag, timeout_s):\n"
                       "    return [payload]\n",
        "pkg/synclib.py": """
def my_gather(payload, tag, timeout_s):
    from pkg import dist
    return dist.kv_allgather(payload, tag, timeout_s)
""",
        "pkg/use.py": """
from pkg.synclib import my_gather
def sync(x):
    return my_gather(x, "t", timeout_s=0)
""",
    })
    assert rule_set(findings) == {"DCR004"}


# ---------------------------------------------------------------------------
# 1d. DCR009 — untimed blocking waits on hot paths
# ---------------------------------------------------------------------------

HOT = dict(hot_paths=("pkg/serve/",))


def test_dcr009_untimed_queue_get(tmp_path):
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "", "pkg/serve/__init__.py": "",
        "pkg/serve/worker.py": """
import queue
q = queue.Queue()
def drain():
    return q.get()
""",
    }, **HOT)
    assert rule_set(findings) == {"DCR009"}


def test_dcr009_event_wait_and_thread_join(tmp_path):
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "", "pkg/serve/__init__.py": "",
        "pkg/serve/worker.py": """
import threading
class W:
    def __init__(self):
        self._stop = threading.Event()
        self._thread = threading.Thread(target=lambda: None)
    def wait_forever(self):
        self._stop.wait()
    def join_forever(self):
        self._thread.join()
""",
    }, **HOT)
    assert sorted(f.message.split("(")[0] for f in findings) and \
        rule_set(findings) == {"DCR009"}
    assert len(findings) == 2


def test_dcr009_bounded_and_nonblocking_are_clean(tmp_path):
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "", "pkg/serve/__init__.py": "",
        "pkg/serve/worker.py": """
import queue, threading
q = queue.Queue()
ev = threading.Event()
def ok(t):
    a = q.get(timeout=1.0)
    b = q.get(False)
    c = q.get_nowait()
    ev.wait(t if t else 5.0)
    return a, b, c
""",
    }, **HOT)
    assert findings == []


def test_dcr009_future_result_and_scope(tmp_path):
    files = {
        "pkg/__init__.py": "", "pkg/serve/__init__.py": "",
        "pkg/serve/handler.py": """
def answer(req):
    return req.future.result()
""",
        "pkg/data.py": """
import queue
q = queue.Queue()
def drain():
    return q.get()
""",
    }
    # future.result() untimed in the hot path is flagged; the identical
    # Queue.get outside the hot-path scope is NOT (precision by scoping)
    findings = program_rules(tmp_path, files, **HOT)
    assert rule_set(findings) == {"DCR009"}
    assert all(f.path.startswith("pkg/serve/") for f in findings)


def test_dcr009_pragma_suppression(tmp_path):
    write_pkg(tmp_path, {
        "pkg/__init__.py": "", "pkg/serve/__init__.py": "",
        "pkg/serve/worker.py": """
import threading
ev = threading.Event()
def wait_for_signal():
    ev.wait()  # dcr-lint: disable=DCR009
""",
    })
    cfg = CheckConfig(roots=("pkg",), hot_paths=("pkg/serve/",),
                      entry_modules=(), root=tmp_path)
    findings, suppressed, _ = scan_program(cfg)
    assert findings == [] and suppressed == 1


# ---------------------------------------------------------------------------
# 1e. DCR010 — unregistered jit entry points
# ---------------------------------------------------------------------------

def test_dcr010_unregistered_jit_entry(tmp_path):
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/worker.py": """
import jax
def make_sampler(cfg):
    def sample(params, x):
        return x
    return jax.jit(sample)
""",
    }, entry_modules=("pkg/worker.py",))
    assert rule_set(findings) == {"DCR010"}
    assert "not registered" in findings[0].message


def test_dcr010_registered_jit_entry_is_clean(tmp_path):
    manifest = {"version": 1, "entries": {
        "serve/sampler@default": {"surface": "serve/sampler"}}}
    (tmp_path / "compile_manifest.json").write_text(json.dumps(manifest))
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/surface.py": """
def compile_surface(name, manifest=True, reason=""):
    def deco(fn):
        return fn
    return deco
""",
        "pkg/worker.py": """
import jax
from pkg.surface import compile_surface
@compile_surface("serve/sampler")
def make_sampler(cfg):
    def sample(params, x):
        return x
    return jax.jit(sample)
""",
    }, entry_modules=("pkg/worker.py",))
    assert findings == []


def test_dcr010_registered_surface_missing_from_manifest(tmp_path):
    # same registered surface but an empty manifest -> coverage finding
    (tmp_path / "compile_manifest.json").write_text(
        json.dumps({"version": 1, "entries": {}}))
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/surface.py": """
def compile_surface(name, manifest=True, reason=""):
    def deco(fn):
        return fn
    return deco
""",
        "pkg/worker.py": """
import jax
from pkg.surface import compile_surface
@compile_surface("serve/sampler")
def make_sampler(cfg):
    def sample(params, x):
        return x
    return jax.jit(sample)
""",
    }, entry_modules=("pkg/worker.py",))
    assert rule_set(findings) == {"DCR010"}
    assert "no entry" in findings[0].message


def test_dcr010_manifest_false_is_exempt(tmp_path):
    (tmp_path / "compile_manifest.json").write_text(
        json.dumps({"version": 1, "entries": {}}))
    findings = program_rules(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/surface.py": """
def compile_surface(name, manifest=True, reason=""):
    def deco(fn):
        return fn
    return deco
""",
        "pkg/worker.py": """
import jax
from pkg.surface import compile_surface
@compile_surface("serve/score", manifest=False, reason="run-config shapes")
def make_scorer(cfg):
    return jax.jit(lambda p, x: x)
""",
    }, entry_modules=("pkg/worker.py",))
    assert findings == []


# ---------------------------------------------------------------------------
# 2. manifest machinery on synthetic surfaces
# ---------------------------------------------------------------------------

def _toy_entry(steps: int, donate: bool = False, batch: int = 4) -> dict:
    import jax
    import jax.numpy as jnp

    from tools.check.manifest import fingerprint

    def body(x, y):
        for _ in range(steps):
            x = x * y + 1.0
        return x

    fn = jax.jit(body, donate_argnums=(0,) if donate else ())
    aval = jax.ShapeDtypeStruct((batch, 3), jnp.float32)
    return fingerprint("toy/surface@default", fn, (aval, aval),
                       static_config={"steps": steps},
                       donate_argnums=(0,) if donate else (),
                       surface="toy/surface")


def test_fingerprint_is_deterministic_and_abstract():
    e1 = _toy_entry(3)
    e2 = _toy_entry(3)
    assert e1 == e2
    assert e1["in_avals"]["leaves"] == 2
    assert e1["out_avals"]["detail"] == [".: float32[4, 3]"]
    assert e1["donated_inputs"] == 0


def test_fingerprint_records_donation():
    e = _toy_entry(3, donate=True)
    assert e["donate_argnums"] == [0] and e["donated_inputs"] == 1


def test_manifest_diff_detects_injected_static_arg_change():
    # the satellite regression: inject a recompile hazard (a changed static
    # arg) and require the diff to be detected AND readable
    from tools.check.manifest import build_manifest, diff_manifests

    old = build_manifest({"toy/surface@default": _toy_entry(3)})
    new = build_manifest({"toy/surface@default": _toy_entry(4)})
    diff = diff_manifests(old, new)
    assert diff, "a changed static arg must produce a manifest diff"
    text = "\n".join(diff)
    assert "toy/surface@default" in text
    assert "static_config.steps" in text and "3" in text and "4" in text
    assert "recompile" in text
    # the changed loop bound also changes the program itself
    assert (old["entries"]["toy/surface@default"]["lowered_sha256"]
            != new["entries"]["toy/surface@default"]["lowered_sha256"])


def test_manifest_diff_detects_aval_change_readably():
    from tools.check.manifest import build_manifest, diff_manifests

    old = build_manifest({"toy/surface@default": _toy_entry(3, batch=4)})
    new = build_manifest({"toy/surface@default": _toy_entry(3, batch=8)})
    diff = "\n".join(diff_manifests(old, new))
    assert "in_avals" in diff
    assert "float32[4, 3]" in diff and "float32[8, 3]" in diff


def test_manifest_diff_detects_donation_change():
    from tools.check.manifest import build_manifest, diff_manifests

    old = build_manifest({"toy/surface@default": _toy_entry(3)})
    new = build_manifest({"toy/surface@default": _toy_entry(3, donate=True)})
    diff = "\n".join(diff_manifests(old, new))
    assert "donate_argnums" in diff and "use-after-donation" in diff


def test_manifest_diff_new_and_removed_entries():
    from tools.check.manifest import build_manifest, diff_manifests

    base = build_manifest({"toy/surface@default": _toy_entry(3)})
    grown = build_manifest({"toy/surface@default": _toy_entry(3),
                            "toy/other@default": _toy_entry(2)})
    diff = "\n".join(diff_manifests(base, grown))
    assert "toy/other@default" in diff and "NEW entry point" in diff
    diff = "\n".join(diff_manifests(grown, base))
    assert "entry removed" in diff


def test_manifest_clean_roundtrip(tmp_path):
    from tools.check.manifest import (build_manifest, diff_manifests,
                                      load_manifest, write_manifest)

    m = build_manifest({"toy/surface@default": _toy_entry(3)})
    write_manifest(tmp_path / "m.json", m)
    loaded = load_manifest(tmp_path / "m.json")
    assert diff_manifests(loaded, m) == []


def test_manifest_jax_version_mismatch_skips_hlo_digest():
    from tools.check.manifest import build_manifest, diff_manifests

    old = build_manifest({"toy/surface@default": _toy_entry(3)})
    old["jax_version"] = "0.0.0-other"
    new = build_manifest({"toy/surface@default": _toy_entry(3)})
    # identical shapes/statics, different recorded jax version: the HLO
    # digest must not be compared, so the diff stays empty
    assert diff_manifests(old, new) == []


# ---------------------------------------------------------------------------
# 3. repo self-scan — what the static-analysis + compile-manifest jobs gate
# ---------------------------------------------------------------------------

def test_repo_program_scan_is_clean():
    from tools.check.config import load_check_config
    from tools.check.engine import run_layer1

    # run_layer1 (not raw scan_program): the concurrency/durability rules
    # carry justified baseline entries, applied at this layer
    cfg = load_check_config(pyproject=REPO / "pyproject.toml")
    report = run_layer1(cfg, pyproject=REPO / "pyproject.toml",
                        include_local=False)
    pretty = "\n".join(f"{f.path}:{f.line}: {f.rule} {f.message}"
                       for f in report.program)
    assert report.program == [], f"whole-program findings:\n{pretty}"
    assert report.modules_analyzed > 50


def test_repo_registered_surfaces_match_expectations():
    from tools.check.config import load_check_config

    cfg = load_check_config(pyproject=REPO / "pyproject.toml")
    index = load_program(cfg.root, cfg.roots, cfg.exclude)
    surfaces = registered_surfaces(index, cfg)
    assert surfaces == {
        "train/step": True,
        "train/params_finite": True,
        "train/encode": True,          # dcr-pipe producer stage
        "train/encode_cached": True,   # dcr-pipe latent-cache stage
        "train/denoise": True,         # dcr-pipe denoiser hot step
        "serve/batch_sampler": True,
        "serve/encode": True,
        "sample/sampler": True,
        "eval/embed": True,
        "eval/clip_score": False,
        "risk/score": True,         # dcr-watch online copy-risk top-k
        "search/matmul": True,      # the LAION brute-force search kernel
        "search/topk": True,        # dcr-store mesh-sharded store top-k
        "search/kmeans": True,      # dcr-ann IVF quantizer Lloyd step
        "search/ivf_scan": True,    # dcr-ann nprobe-bounded list scan
    }


def test_checked_in_manifest_covers_acceptance_surfaces():
    data = json.loads((REPO / "compile_manifest.json").read_text())
    entries = data["entries"]
    by_surface: dict[str, set] = {}
    for e in entries.values():
        by_surface.setdefault(e["surface"], set()).add(e["variant"])
    # the acceptance list: train step, every default serve bucket sampler,
    # both/all samplers (plus the dcr-fast score-reuse variants at the
    # default operating point), eval embed step
    assert "default" in by_surface["train/step"]
    # dcr-pipe: producer (live + precompute-moments variants), denoiser hot
    # step, and the latent-cache stage are all fingerprinted
    assert by_surface["train/encode"] == {"default", "moments"}
    assert "default" in by_surface["train/denoise"]
    assert "default" in by_surface["train/encode_cached"]
    assert by_surface["serve/batch_sampler"] == {"ddim", "dpm++", "ddpm",
                                                 "dpm++-fast"}
    assert by_surface["sample/sampler"] == {"ddim", "dpm++", "ddpm",
                                            "dpm++-fast"}
    assert "default" in by_surface["eval/embed"]
    # dcr-ann: both approximate-tier surfaces are fingerprinted
    assert "default" in by_surface["search/kmeans"]
    assert "default" in by_surface["search/ivf_scan"]
    for entry in entries.values():
        assert entry["lowered_sha256"] and entry["in_avals"]["leaves"] > 0
        # every serve bucket records the default bucket's static knobs —
        # including the fast plan's, so a changed default operating point
        # is a readable manifest diff
        if entry["surface"] == "serve/batch_sampler":
            from dcr_tpu.core.config import FastSampleConfig

            assert entry["static_config"]["resolution"] == 256
            assert entry["static_config"]["steps"] == 50
            assert entry["static_config"]["fast_order"] in (1, 2)
            # the fast variant pins the FastSampleConfig DEFAULT operating
            # point (the one bench_fastsample gates), dense variants 0
            want_ratio = (FastSampleConfig().reuse_ratio
                          if entry["variant"].endswith("-fast") else 0.0)
            assert entry["static_config"]["fast_ratio"] == want_ratio
    # a fast variant's program really differs from its dense twin
    assert (entries["serve/batch_sampler@dpm++-fast"]["lowered_sha256"]
            != entries["serve/batch_sampler@dpm++"]["lowered_sha256"])
    assert (entries["sample/sampler@dpm++-fast"]["lowered_sha256"]
            != entries["sample/sampler@dpm++"]["lowered_sha256"])


def test_surface_specs_agree_with_registrations():
    # tools/check/surfaces.py must build >=1 variant for every manifest=True
    # registration — the same invariant check_manifest_coverage enforces on
    # the checked-in JSON, asserted here at the spec level
    from tools.check.config import load_check_config
    from tools.check.surfaces import SURFACES

    cfg = load_check_config(pyproject=REPO / "pyproject.toml")
    index = load_program(cfg.root, cfg.roots, cfg.exclude)
    registered = registered_surfaces(index, cfg)
    spec_surfaces = {s.surface for s in SURFACES}
    want = {name for name, m in registered.items() if m}
    assert spec_surfaces == want


def _run_cli(*argv, cwd=REPO):
    return subprocess.run([sys.executable, "-m", "tools.check", *argv],
                          capture_output=True, text=True, cwd=cwd)


def test_cli_no_manifest_is_clean_on_repo():
    proc = _run_cli("--no-manifest")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 findings" in proc.stdout


def test_cli_program_only_skips_file_local_scan():
    # the CI static-analysis job runs dcr-lint separately; --program-only
    # must not re-report (and re-annotate) the file-local layer
    proc = _run_cli("--no-manifest", "--program-only")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert " 0 files" in proc.stdout  # file-local layer did not run


def test_cli_github_format(tmp_path):
    # a seeded DCR009 under a fake repo root surfaces as a ::error line
    import os

    write_pkg(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/serve/__init__.py": "",
        "pkg/serve/w.py": "import queue\nq = queue.Queue()\n"
                          "def d():\n    return q.get()\n",
        # stubs for the file-local lint layer's default scan paths
        "dcr_tpu/__init__.py": "", "tests/__init__.py": "",
        "tools/keep.py": "KEEP = 1\n",
        "pyproject.toml": """
[tool.dcr-check]
roots = ["pkg"]
entry-modules = []
hot-paths = ["pkg/serve/"]
manifest = "compile_manifest.json"
""",
    })
    env = dict(os.environ, PYTHONPATH=str(REPO))
    proc = subprocess.run(
        [sys.executable, "-m", "tools.check", "--no-manifest",
         "--format", "github", "--config", str(tmp_path / "pyproject.toml")],
        capture_output=True, text=True, cwd=tmp_path, env=env)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "::error file=pkg/serve/w.py" in proc.stdout
    assert "title=DCR009" in proc.stdout

"""dcr-lint checker self-tests.

Three layers:

1. per-rule fixtures — a seeded violation of each of DCR001–DCR008 is
   caught, and the idiomatic clean variant is NOT (the precision contract);
2. suppression/workflow — per-line pragmas, the justified baseline
   (including the unjustified-entry failure mode), config select/ignore
   and per-path-ignores, JSON schema, CLI exit codes;
3. the repo self-scan — ``python -m tools.lint dcr_tpu tests tools`` is
   clean on this tree, which is what the static-analysis CI job enforces.

Everything here is pure-AST (no jax import needed at lint time), so the
whole module rides the fast tier.
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import pytest

from tools.lint.config import LintConfig, load_config
from tools.lint.engine import (JSON_SCHEMA_VERSION, LintError, lint_source,
                               lint_source_counted, load_baseline, scan,
                               write_baseline)
from tools.lint.rules import RULES

pytestmark = pytest.mark.fast

REPO = Path(__file__).resolve().parents[1]


def rules_of(src: str, path: str = "fixture.py") -> set[str]:
    return {f.rule for f in lint_source(src, path)}


# ---------------------------------------------------------------------------
# 1. per-rule positive/negative fixtures
# ---------------------------------------------------------------------------

FIXTURES = {
    # rule: (violating snippet, clean snippet)
    "DCR001": (
        """
import jax
@jax.jit
def f(x):
    return x.item()
""",
        """
import jax, jax.numpy as jnp, numpy as np
@jax.jit
def f(x):
    return jnp.mean(x)
def host(y):
    return float(np.asarray(y).item())  # outside jit: fine
""",
    ),
    "DCR002": (
        """
import jax
step = jax.jit(lambda s, b: s, donate_argnums=(0,))
def train(state, batch):
    new = step(state, batch)
    return state, new
""",
        """
import jax
step = jax.jit(lambda s, b: s, donate_argnums=(0,))
def train(state, batches):
    for b in batches:
        state = step(state, b)
    return state
""",
    ),
    "DCR003": (
        """
import jax
def f(key):
    a = jax.random.normal(key, (2,))
    b = jax.random.uniform(key, (2,))
    return a + b
""",
        """
import jax
def f(key):
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, (2,))
    b = jax.random.uniform(k2, (2,))
    return a + b
""",
    ),
    "DCR004": (
        """
from dcr_tpu.core import dist
def save():
    dist.barrier("ckpt")
""",
        """
from dcr_tpu.core import dist
def save(t):
    dist.barrier("ckpt", timeout_s=t)
""",
    ),
    "DCR005": (
        """
import jax
from dcr_tpu.core import dist
def sync():
    if jax.process_index() == 0:
        dist.barrier("rank0-only", timeout_s=60)
""",
        """
import jax
from dcr_tpu.core import dist
def sync():
    dist.barrier("all-ranks", timeout_s=60)
    if jax.process_index() == 0:
        print("synced")
""",
    ),
    "DCR006": (
        """
def load(p):
    try:
        return open(p).read()
    except Exception:
        pass
""",
        """
import logging
def load(p):
    try:
        return open(p).read()
    except Exception as e:
        logging.warning("load failed: %r", e)
        return None
""",
    ),
    "DCR007": (
        """
import jax
@jax.jit
def f(x, flag):
    if flag:
        return x * 2
    return x
""",
        """
import jax
from functools import partial
@partial(jax.jit, static_argnames=("flag",))
def f(x, flag):
    if flag:
        return x * 2
    return x
""",
    ),
    "DCR008": (
        """
import numpy as np
def noise(shape):
    return np.random.randn(*shape)
""",
        """
import numpy as np
def noise(shape, seed):
    rng = np.random.default_rng(seed)
    return rng.normal(size=shape)
""",
    ),
}


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_catches_violation(rule):
    bad, _ = FIXTURES[rule]
    assert rule in rules_of(bad), f"{rule} missed its seeded violation"


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_accepts_clean_variant(rule):
    _, good = FIXTURES[rule]
    found = rules_of(good)
    assert rule not in found, f"{rule} false-positived on the clean variant"


# -- rule-specific edges -----------------------------------------------------

def test_dcr001_numpy_and_cast_variants():
    assert "DCR001" in rules_of(
        "import jax, numpy as np\n@jax.jit\ndef f(x):\n    return np.sum(x)\n")
    assert "DCR001" in rules_of(
        "import jax\n@jax.jit\ndef f(x):\n    return float(x)\n")
    assert "DCR001" in rules_of(
        "import jax\n@jax.jit\ndef f(x):\n    return jax.device_get(x)\n")
    # jax.jit(lambda ...) bodies are traced too
    assert "DCR001" in rules_of(
        "import jax\ng = jax.jit(lambda x: x.item())\n")


def test_dcr002_loop_without_rebind():
    src = """
import jax
step = jax.jit(lambda s, b: s, donate_argnums=(0,))
def train(state, batches):
    for b in batches:
        out = step(state, b)
    return out
"""
    assert "DCR002" in rules_of(src)


def test_dcr002_decorated_donation():
    src = """
import jax
from functools import partial
@partial(jax.jit, donate_argnums=(0,))
def step(s, b):
    return s
def train(state, batch):
    new = step(state, batch)
    print(state)
    return new
"""
    assert "DCR002" in rules_of(src)


def test_dcr003_loop_reuse_and_exclusive_branches():
    loop = """
import jax
def f(key, n):
    out = []
    for i in range(n):
        out.append(jax.random.normal(key, (2,)))
    return out
"""
    assert "DCR003" in rules_of(loop)
    fold = """
import jax
def f(key, n):
    out = []
    for i in range(n):
        k = jax.random.fold_in(key, i)
        out.append(jax.random.normal(k, (2,)))
    return out
"""
    assert "DCR003" not in rules_of(fold)
    branches = """
import jax
def f(key, cond):
    if cond:
        return jax.random.normal(key, (2,))
    else:
        return jax.random.uniform(key, (2,))
"""
    assert "DCR003" not in rules_of(branches)


def test_dcr004_zero_timeout_and_wrapped():
    assert "DCR004" in rules_of(
        "from dcr_tpu.core import dist\n"
        "def g(p):\n    return dist.kv_allgather(p, 't', timeout_s=0)\n")
    wrapped = """
from dcr_tpu.core import dist
from jax.experimental import multihost_utils
def g(name, t):
    dist.run_with_timeout(
        lambda: multihost_utils.sync_global_devices(name), t, name=name)
"""
    assert "DCR004" not in rules_of(wrapped)
    bare = """
from jax.experimental import multihost_utils
def g(name):
    multihost_utils.sync_global_devices(name)
"""
    assert "DCR004" in rules_of(bare)


def test_dcr005_process_count_guard_is_fine():
    src = """
import jax
from dcr_tpu.core import dist
def sync():
    if jax.process_count() == 1:
        return
    dist.barrier("all", timeout_s=30)
"""
    assert "DCR005" not in rules_of(src)


def test_dcr006_narrow_type_is_fine():
    src = """
def probe(p):
    try:
        return open(p).read()
    except FileNotFoundError:
        pass
"""
    assert "DCR006" not in rules_of(src)


def test_dcr007_none_check_is_structural():
    src = """
import jax
@jax.jit
def f(x, opt):
    if opt is not None:
        return x + opt
    return x
"""
    assert "DCR007" not in rules_of(src)


def test_dcr008_wall_clock_only_inside_jit():
    assert "DCR008" in rules_of(
        "import jax, time\n@jax.jit\ndef f(x):\n    return x + time.time()\n")
    assert "DCR008" not in rules_of(
        "import time\ndef stamp():\n    return time.time()\n")
    # stdlib global RNG flagged anywhere; jax.random never is
    assert "DCR008" in rules_of(
        "import random\ndef j():\n    return random.random()\n")
    assert "DCR008" not in rules_of(
        "import jax\ndef j(key):\n    return jax.random.normal(key, (2,))\n")


def test_syntax_error_becomes_dcr000():
    findings = lint_source("def broken(:\n", "bad.py")
    assert [f.rule for f in findings] == ["DCR000"]


# ---------------------------------------------------------------------------
# 2. suppression + workflow
# ---------------------------------------------------------------------------

def test_pragma_suppresses_only_named_rule():
    src = ("import random\n"
           "def j():\n"
           "    return random.random()  # dcr-lint: disable=DCR008\n")
    findings, n_pragma = lint_source_counted(src, "p.py")
    assert findings == [] and n_pragma == 1
    # a pragma for a DIFFERENT rule does not suppress
    src2 = ("import random\n"
            "def j():\n"
            "    return random.random()  # dcr-lint: disable=DCR006\n")
    assert "DCR008" in {f.rule for f in lint_source(src2, "p.py")}


def test_baseline_suppression_and_staleness(tmp_path):
    bad = tmp_path / "pkg"
    bad.mkdir()
    (bad / "m.py").write_text(
        "import random\nx = random.random()\n", encoding="utf-8")
    cfg = LintConfig(root=tmp_path, baseline="baseline.json")
    report = scan([bad], cfg)
    assert report.counts() == {"DCR008": 1}
    # grandfather it with a justification -> clean, suppressed counted
    (tmp_path / "baseline.json").write_text(json.dumps({"entries": [{
        "rule": "DCR008", "path": "pkg/m.py",
        "snippet": "x = random.random()",
        "justification": "fixture: intentional for this test"}]}))
    report = scan([bad], cfg)
    assert report.findings == [] and report.baseline_suppressed == 1
    assert report.stale_baseline == []
    # fix the code -> the entry goes stale and is reported
    (bad / "m.py").write_text("x = 4\n", encoding="utf-8")
    report = scan([bad], cfg)
    assert report.findings == [] and len(report.stale_baseline) == 1


def test_baseline_entry_is_count_bounded(tmp_path):
    # one grandfathered swallow must NOT absolve a second identical-looking
    # one added later to the same file
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    body = ("def a(p):\n    try:\n        return open(p).read()\n"
            "    except Exception:\n        pass\n")
    (pkg / "m.py").write_text(body, encoding="utf-8")
    entry = {"rule": "DCR006", "path": "pkg/m.py",
             "snippet": "except Exception:",
             "justification": "fixture: the first swallow is grandfathered"}
    (tmp_path / "baseline.json").write_text(json.dumps({"entries": [entry]}))
    cfg = LintConfig(root=tmp_path, baseline="baseline.json")
    report = scan([pkg], cfg)
    assert report.findings == [] and report.baseline_suppressed == 1
    # add a second identical swallow -> it must surface
    (pkg / "m.py").write_text(
        body + "def b(p):\n    try:\n        return open(p).read()\n"
               "    except Exception:\n        pass\n", encoding="utf-8")
    report = scan([pkg], cfg)
    assert report.counts() == {"DCR006": 1}
    assert report.baseline_suppressed == 1
    # an explicit count raises the budget
    entry["count"] = 2
    (tmp_path / "baseline.json").write_text(json.dumps({"entries": [entry]}))
    report = scan([pkg], cfg)
    assert report.findings == [] and report.baseline_suppressed == 2


def test_explicit_non_python_file_is_an_error(tmp_path):
    f = tmp_path / "notes.txt"
    f.write_text("hi", encoding="utf-8")
    with pytest.raises(LintError):
        scan([f], LintConfig(root=tmp_path, baseline=None))


def test_unjustified_baseline_entry_is_an_error(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps({"entries": [{
        "rule": "DCR008", "path": "m.py", "snippet": "x",
        "justification": "TODO: justify"}]}))
    with pytest.raises(LintError):
        load_baseline(p)
    p.write_text(json.dumps({"entries": [{
        "rule": "DCR008", "path": "m.py", "snippet": "x",
        "justification": ""}]}))
    with pytest.raises(LintError):
        load_baseline(p)


def test_write_baseline_roundtrip_requires_justification(tmp_path):
    bad = tmp_path / "m.py"
    bad.write_text("import random\nx = random.random()\n", encoding="utf-8")
    cfg = LintConfig(root=tmp_path, baseline="bl.json")
    report = scan([bad], cfg)
    write_baseline(tmp_path / "bl.json", report.findings)
    # freshly-written entries are unjustified on purpose: the run must fail
    # until a human writes down why each one is acceptable
    with pytest.raises(LintError):
        scan([bad], cfg)


def test_config_select_ignore_and_per_path(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "a.py").write_text(
        "import random\nx = random.random()\n"
        "try:\n    y = 1\nexcept Exception:\n    pass\n", encoding="utf-8")
    cfg = LintConfig(root=tmp_path, baseline=None, select=("DCR006",))
    assert scan([pkg], cfg).counts() == {"DCR006": 1}
    cfg = LintConfig(root=tmp_path, baseline=None, ignore=("DCR006",))
    assert scan([pkg], cfg).counts() == {"DCR008": 1}
    cfg = LintConfig(root=tmp_path, baseline=None,
                     per_path_ignores={"pkg/": ("DCR006", "DCR008")})
    assert scan([pkg], cfg).counts() == {}
    cfg = LintConfig(root=tmp_path, baseline=None, exclude=("pkg",))
    report = scan([tmp_path], cfg)
    assert report.files_scanned == 0


def test_load_config_reads_pyproject(tmp_path):
    (tmp_path / "pyproject.toml").write_text("""
[tool.dcr-lint]
select = ["DCR004", "DCR006"]
ignore = ["DCR006"]
exclude = ["vendored"]
baseline = "bl.json"

[tool.dcr-lint.per-path-ignores]
"bench/" = ["DCR008"]
""", encoding="utf-8")
    cfg = load_config(pyproject=tmp_path / "pyproject.toml")
    assert cfg.select == ("DCR004", "DCR006")
    assert cfg.ignore == ("DCR006",)
    assert cfg.exclude == ("vendored",)
    assert cfg.baseline == "bl.json"
    assert cfg.per_path_ignores == {"bench/": ("DCR008",)}
    assert cfg.root == tmp_path
    assert cfg.rules_for("bench/x.py", ("DCR004", "DCR008")) == {"DCR004"}


def test_repo_pyproject_parses_with_mini_toml():
    # the 3.10 fallback parser must agree with what the config needs from
    # THIS repo's real pyproject.toml (tomllib isn't in this container)
    from tools.lint.config import _mini_toml

    data = _mini_toml((REPO / "pyproject.toml").read_text(encoding="utf-8"))
    section = data["tool"]["dcr-lint"]
    assert section["select"] == [f"DCR00{i}" for i in range(1, 9)]
    assert "tests/fixtures" in section["exclude"]
    assert section["baseline"] == "tools/lint/baseline.json"


# ---------------------------------------------------------------------------
# JSON schema + CLI contract
# ---------------------------------------------------------------------------

def _run_cli(*argv, cwd=REPO):
    return subprocess.run([sys.executable, "-m", "tools.lint", *argv],
                          capture_output=True, text=True, cwd=cwd)


def test_json_output_schema(tmp_path):
    bad = tmp_path / "m.py"
    bad.write_text("import random\nx = random.random()\n", encoding="utf-8")
    proc = _run_cli(str(bad), "--format", "json", "--no-baseline")
    assert proc.returncode == 1, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["version"] == JSON_SCHEMA_VERSION
    assert set(payload) == {"version", "files_scanned", "findings", "counts",
                            "suppressed", "stale_baseline"}
    (finding,) = payload["findings"]
    assert set(finding) == {"rule", "path", "line", "col", "message",
                            "snippet"}
    assert finding["rule"] == "DCR008" and finding["line"] == 2
    assert payload["counts"] == {"DCR008": 1}
    assert set(payload["suppressed"]) == {"pragma", "baseline"}


def test_cli_exit_codes(tmp_path):
    good = tmp_path / "ok.py"
    good.write_text("x = 1\n", encoding="utf-8")
    assert _run_cli(str(good)).returncode == 0
    bad = tmp_path / "bad.py"
    bad.write_text("import random\nx = random.random()\n", encoding="utf-8")
    assert _run_cli(str(bad), "--no-baseline").returncode == 1
    assert _run_cli(str(tmp_path / "missing.py")).returncode == 2
    assert _run_cli(str(good), "--select", "DCR999").returncode == 2


def test_cli_list_rules():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    for rule_id in RULES:
        assert rule_id in proc.stdout


# ---------------------------------------------------------------------------
# unreadable-input edge cases — each a structured exit-2 diagnostic, never a
# traceback: an unparseable file means the scan is INCOMPLETE, which must not
# read as either "clean" (0) or an ordinary finding (1)
# ---------------------------------------------------------------------------

def test_cli_syntax_error_file_exits_2(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def broken(:\n", encoding="utf-8")
    proc = _run_cli(str(bad), "--no-baseline")
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "Traceback" not in proc.stderr
    # the DCR000 pseudo-finding is the structured diagnostic
    assert "DCR000" in proc.stdout
    assert "could not be parsed" in proc.stderr
    assert "broken.py" in proc.stderr


def test_cli_non_utf8_file_exits_2(tmp_path):
    bad = tmp_path / "latin1.py"
    bad.write_bytes(b"# caf\xe9\nx = 1\n")
    proc = _run_cli(str(bad), "--no-baseline")
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "Traceback" not in proc.stderr
    assert "not valid UTF-8" in proc.stderr
    assert "latin1.py" in proc.stderr


def test_cli_empty_file_exits_2(tmp_path):
    empty = tmp_path / "empty.py"
    empty.write_text("", encoding="utf-8")
    proc = _run_cli(str(empty), "--no-baseline")
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "Traceback" not in proc.stderr
    assert "empty file" in proc.stderr
    assert "empty.py" in proc.stderr
    # an empty file inside a scanned DIRECTORY is not an error: only an
    # explicitly named empty file marks a misconfigured invocation
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("", encoding="utf-8")
    (pkg / "ok.py").write_text("x = 1\n", encoding="utf-8")
    assert _run_cli(str(pkg), "--no-baseline").returncode == 0


def test_baselined_parse_failure_still_exits_2(tmp_path):
    # a DCR000 entry in the baseline must NOT turn an unparseable file into
    # a "clean" exit-0 scan — parse failures can never be grandfathered
    bad = tmp_path / "broken.py"
    bad.write_text("def broken(:\n", encoding="utf-8")
    (tmp_path / "baseline.json").write_text(json.dumps({"entries": [
        {"rule": "DCR000", "path": "broken.py", "snippet": "def broken(:",
         "justification": "fixture: someone tried to grandfather a parse "
                          "failure — must not work"}]}))
    proc = _run_cli(str(bad), "--baseline", str(tmp_path / "baseline.json"))
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "could not be parsed" in proc.stderr
    # and --write-baseline refuses to record DCR000 in the first place
    from tools.lint.engine import write_baseline
    from tools.lint.rules import Finding
    out = tmp_path / "bl2.json"
    write_baseline(out, [Finding(rule="DCR000", path="broken.py", line=1,
                                 col=0, message="syntax error", snippet="")])
    assert json.loads(out.read_text())["entries"] == []


def test_dcr002_loop_with_later_rebind_is_clean():
    # `new = step(state, b); state = new` rebinds before the next iteration
    src = """
import jax
step = jax.jit(lambda s, b: s, donate_argnums=(0,))
def train(state, batches):
    for b in batches:
        new = step(state, b)
        state = new
    return state
"""
    assert "DCR002" not in rules_of(src)
    # the loop target itself is a fresh binding every iteration too
    src2 = """
import jax
step = jax.jit(lambda s, b: s, donate_argnums=(0,))
def train(states, b):
    for state in states:
        step(state, b)
"""
    assert "DCR002" not in rules_of(src2)


def test_stale_baseline_reported_for_deleted_file(tmp_path):
    # an entry whose file no longer EXISTS is stale even when that file is
    # not in the scanned path set — a deleted file can never match any scan
    scanned = tmp_path / "pkg"
    scanned.mkdir()
    (scanned / "m.py").write_text("x = 1\n", encoding="utf-8")
    unscanned = tmp_path / "other"
    unscanned.mkdir()
    (unscanned / "live.py").write_text(
        "import random\nx = random.random()\n", encoding="utf-8")
    (tmp_path / "baseline.json").write_text(json.dumps({"entries": [
        {"rule": "DCR008", "path": "gone/deleted.py",
         "snippet": "x = random.random()",
         "justification": "fixture: file was deleted after grandfathering"},
        {"rule": "DCR008", "path": "other/live.py",
         "snippet": "x = random.random()",
         "justification": "fixture: real finding in an unscanned file"},
    ]}))
    cfg = LintConfig(root=tmp_path, baseline="baseline.json")
    report = scan([scanned], cfg)
    # the deleted file's entry is flagged; the existing-but-unscanned file's
    # entry is NOT (partial scans must not cry wolf about live files)
    assert [e["path"] for e in report.stale_baseline] == ["gone/deleted.py"]


# ---------------------------------------------------------------------------
# 3. repo self-scan — what the static-analysis CI job enforces
# ---------------------------------------------------------------------------

def test_repo_scan_is_clean():
    cfg = load_config(pyproject=REPO / "pyproject.toml")
    report = scan([REPO / "dcr_tpu", REPO / "tests", REPO / "tools"], cfg)
    pretty = "\n".join(f"{f.path}:{f.line}: {f.rule} {f.message}"
                       for f in report.findings)
    assert report.findings == [], f"non-baselined findings:\n{pretty}"
    assert report.stale_baseline == [], (
        f"stale baseline entries: {report.stale_baseline}")
    assert report.files_scanned > 100  # the scan actually covered the tree


def test_repo_baseline_entries_are_justified():
    entries = load_baseline(REPO / "tools" / "lint" / "baseline.json")
    for entry in entries:  # load_baseline raises on unjustified ones
        assert len(entry["justification"]) > 20


def test_every_rule_is_exercised_by_fixtures():
    # the acceptance criterion: a seeded violation of each DCR001-DCR008 is
    # caught by the checker self-tests — keep FIXTURES in lockstep with RULES
    assert set(FIXTURES) == set(RULES)

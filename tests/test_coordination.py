"""Multi-host resilience: the fault-agreement protocol, coordinated
preemption, the collective-hang watchdog, and rendezvous hardening.

Unit layer (fast, no subprocesses): FaultWord encode/decode, the pure
reduce_fault_words precedence table, Coordinator exchange with an injected
allgather, timeout-wrapped barriers (BarrierTimeout), the HangWatchdog
heartbeat, the coordinated checkpoint-fallback agreement loop against a
scripted peer, rank-targeted fault-spec parsing, and the quarantine-merge
tool.

E2E layer (slow, ISSUE 2 acceptance): real 2-process localhost
``jax.distributed`` runs through the actual train CLI — per the Orbax
heap-corruption memory every training leg is its own subprocess:

- rank-targeted NaN at step 5 → BOTH ranks roll back to the same checkpoint
  and the final state is bit-exact vs the symmetric-injection run;
- SIGTERM on rank 0 → one synchronized final checkpoint, both ranks exit
  EXIT_PREEMPTED, and the restarted pod reproduces the uninterrupted run's
  final state bit-exactly;
- injected hang on rank 1 → the watchdog fires within its timeout on both
  ranks (stack dumps + last agreement word in the log), both exit EXIT_HANG
  — no test-level timeout kill.
"""

import json
import re
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest
from PIL import Image

from dcr_tpu.core import coordination as C
from dcr_tpu.core import dist
from dcr_tpu.core.config import (DataConfig, FaultToleranceConfig, ModelConfig,
                                 OptimConfig, TrainConfig, save_config)
from dcr_tpu.utils import faults
from tests._multiproc import REPO, run_two_process, worker_base_env


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("DCR_FAULTS", raising=False)
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# Unit: rank-targeted fault specs
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_parse_rank_suffix_and_mixed_separators():
    specs = faults.parse_faults(
        "nan_loss@step=5@rank=1,sigterm@step=7@rank=0,"
        "decode_error@step=3&slot=2@rank=1,hang@step=9@rank=1x2")
    assert [(s.kind, s.where, s.times) for s in specs] == [
        ("nan_loss", {"step": 5, "rank": 1}, 1),
        ("sigterm", {"step": 7, "rank": 0}, 1),
        ("decode_error", {"step": 3, "slot": 2, "rank": 1}, 1),
        ("hang", {"step": 9, "rank": 1}, 2),
    ]
    with pytest.raises(ValueError, match="malformed"):
        faults.parse_faults("nan_loss@step=5@rank=")


@pytest.mark.fast
def test_rank_coordinate_matches_explicit_and_implicit(monkeypatch):
    reg = faults.install("nan_loss@step=5@rank=1")
    # explicit rank coordinate from a hook point wins
    assert not reg.fire("nan_loss", step=5, rank=0)
    assert reg.fire("nan_loss", step=5, rank=1)
    # implicit: the registry fills rank from the process index
    reg = faults.install("sigterm@step=7@rank=1")
    monkeypatch.setattr(faults, "_current_rank", lambda: 0)
    assert not reg.fire("sigterm", step=7)
    monkeypatch.setattr(faults, "_current_rank", lambda: 1)
    assert reg.fire("sigterm", step=7)


@pytest.mark.fast
def test_rankless_specs_ignore_process_rank(monkeypatch):
    # no spec names a rank -> the implicit coordinate is never injected and
    # every process matches (the historical single-host behavior)
    reg = faults.install("nan_loss@step=5")
    monkeypatch.setattr(faults, "_current_rank",
                        lambda: pytest.fail("rank must not be resolved"))
    assert reg.fire("nan_loss", step=5)


# ---------------------------------------------------------------------------
# Unit: agreement word + reduce (pure, no collectives)
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_fault_word_encode_decode_roundtrip():
    w = C.FaultWord(nan_step=17, rollback_ok=True, preempt=True, bad_samples=9)
    assert C.FaultWord.decode(w.encode()) == w
    assert C.FaultWord.decode(C.FaultWord().encode()) == C.FaultWord()
    with pytest.raises(ValueError, match="fields"):
        C.FaultWord.decode(np.zeros(3, np.int64))


@pytest.mark.fast
def test_reduce_precedence_table():
    W = C.FaultWord
    # all quiet -> continue (bad totals still summed for telemetry)
    d = C.reduce_fault_words([W(bad_samples=2), W(bad_samples=3)])
    assert d.action is C.Action.CONTINUE and d.bad_total == 5
    # any nan + all nan-hosts can roll back -> ROLLBACK to the EARLIEST step
    d = C.reduce_fault_words([W(nan_step=9, rollback_ok=True),
                              W(nan_step=5, rollback_ok=True)])
    assert d.action is C.Action.ROLLBACK and d.nan_step == 5
    assert d.nan_ranks == (0, 1)
    # a nan host that cannot roll back -> the whole pod fails together
    d = C.reduce_fault_words([W(), W(nan_step=5, rollback_ok=False)])
    assert d.action is C.Action.FAIL and d.nan_ranks == (1,)
    # nan outranks preemption: never checkpoint poisoned params
    d = C.reduce_fault_words([W(preempt=True),
                              W(nan_step=5, rollback_ok=True)])
    assert d.action is C.Action.ROLLBACK and d.preempt_ranks == (0,)
    # preemption -> checkpoint-and-exit, even past the bad-sample budget
    d = C.reduce_fault_words([W(preempt=True, bad_samples=50), W()],
                             bad_budget=10)
    assert d.action is C.Action.CHECKPOINT_AND_EXIT and d.preempt_ranks == (0,)
    # per-host counts under the line, pod total over it -> global abort
    d = C.reduce_fault_words([W(bad_samples=6), W(bad_samples=6)],
                             bad_budget=10)
    assert d.action is C.Action.ABORT_BAD_SAMPLES and d.bad_total == 12
    # no budget configured -> counts are telemetry only
    d = C.reduce_fault_words([W(bad_samples=100)], bad_budget=None)
    assert d.action is C.Action.CONTINUE


@pytest.mark.fast
def test_coordinator_single_host_is_pure_and_one_shot():
    coord = C.Coordinator(process_index=0, process_count=1,
                          allgather=lambda v: pytest.fail("no collectives on one host"))
    assert coord.exchange(1).action is C.Action.CONTINUE
    coord.note_nan(3, rollback_ok=True)
    d = coord.exchange(3, tag="loss")
    assert d.action is C.Action.ROLLBACK and d.nan_step == 3
    # nan is one-shot: consumed by the exchange
    assert coord.exchange(4).action is C.Action.CONTINUE
    # preemption is sticky until the process exits
    coord.note_preempt()
    assert coord.exchange(5).action is C.Action.CHECKPOINT_AND_EXIT
    assert coord.exchange(6).action is C.Action.CHECKPOINT_AND_EXIT
    assert coord.last_agreement["action"] == "checkpoint_and_exit"


@pytest.mark.fast
def test_coordinator_peer_fault_reaches_local_decision():
    """A fault observed ONLY on the peer must still decide locally — the
    heart of the agreement protocol."""
    peer = C.FaultWord(nan_step=7, rollback_ok=True)

    def fake_allgather(vec):
        return np.stack([vec, peer.encode()])

    coord = C.Coordinator(process_index=0, process_count=2,
                          allgather=fake_allgather)
    d = coord.exchange(7, tag="loss")
    assert d.action is C.Action.ROLLBACK
    assert d.nan_step == 7 and d.nan_ranks == (1,)
    assert coord.last_agreement["nan_step"] == 7


@pytest.mark.fast
def test_coordinator_assert_same_raises_on_divergence():
    coord = C.Coordinator(process_index=0, process_count=2,
                          allgather=lambda v: np.stack([v, v + 2]))
    with pytest.raises(C.CoordinationError, match="resume_step"):
        coord.assert_same("resume_step", 4)


# ---------------------------------------------------------------------------
# Unit: timeout-wrapped sync points + hang watchdog
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_run_with_timeout_and_barrier_timeout_typed():
    assert dist.run_with_timeout(lambda: 42, 0.0) == 42       # inline path
    assert dist.run_with_timeout(lambda: 42, 5.0) == 42       # threaded path
    t0 = time.monotonic()
    with pytest.raises(dist.BarrierTimeout, match="slowpoke"):
        dist.run_with_timeout(lambda: time.sleep(5), 0.1, name="slowpoke")
    assert time.monotonic() - t0 < 2.0                        # did not wait 5s

    def boom():
        raise ValueError("inner")

    with pytest.raises(ValueError, match="inner"):            # errors surface
        dist.run_with_timeout(boom, 1.0)
    # single-host barrier returns immediately regardless of timeout
    dist.barrier("unit", timeout_s=0.01)


@pytest.mark.fast
def test_hang_watchdog_arms_on_first_beat_and_fires():
    fired = []
    wd = C.HangWatchdog(0.15, poll_s=0.02, abort=fired.append)
    wd.start()
    time.sleep(0.3)
    assert not fired            # never beat: not armed (long first compile)
    wd.beat(5)
    time.sleep(0.4)
    assert fired and "last step 5" in fired[0]
    wd.stop()


@pytest.mark.fast
def test_hang_watchdog_quiet_while_beating_and_disabled_noop():
    fired = []
    wd = C.HangWatchdog(0.2, poll_s=0.02, abort=fired.append)
    wd.start()
    for _ in range(10):
        wd.beat()
        time.sleep(0.03)
    wd.stop()
    assert not fired
    off = C.HangWatchdog(0.0)   # disabled: all no-ops
    off.start()
    off.beat()
    off.stop()
    assert off._thread is None


@pytest.mark.fast
def test_dump_stacks_includes_this_frame():
    text = C.dump_stacks()
    assert "--- thread" in text
    assert "test_dump_stacks_includes_this_frame" in text


@pytest.mark.fast
def test_hang_abort_logs_word_and_exits(monkeypatch, caplog):
    codes = []
    monkeypatch.setattr(C, "_exit_fn", codes.append)
    coord = C.Coordinator(process_index=0, process_count=1,
                          allgather=lambda v: v)
    coord.exchange(11)
    with caplog.at_level("WARNING", logger="dcr_tpu"):
        C.hang_abort("unit", coordinator=coord, detail="test detail")
    assert codes == [C.EXIT_HANG]
    joined = " ".join(r.getMessage() for r in caplog.records)
    assert "hang_abort" in joined and "thread stacks" in joined


# ---------------------------------------------------------------------------
# Unit: coordinated checkpoint-fallback agreement (scripted peer)
# ---------------------------------------------------------------------------

class ScriptedCoordinator:
    """agree_int plays back preset per-call responses (value -> row)."""

    process_count = 2
    timeout_s = 0.0

    def __init__(self, responses):
        self.responses = list(responses)
        self.calls = []

    def agree_int(self, value, name):
        self.calls.append((name, int(value)))
        return self.responses.pop(0)(int(value))


def _mk_ckpts(tmp_path, steps):
    import jax.numpy as jnp

    from dcr_tpu.core.checkpoint import CheckpointManager

    mgr = CheckpointManager(tmp_path / "ckpt", async_save=False)
    for step in steps:
        mgr.save(step, {"w": jnp.full((8,), float(step))})
    mgr.wait()
    mgr.close()


@pytest.mark.fast
def test_coordinated_restore_takes_pod_minimum(tmp_path):
    """Local host has steps 2 and 4; the peer only proposes 2 (its 4 is torn
    or missing) -> the pod agrees on 2 even though 4 is locally fine."""
    import jax.numpy as jnp

    from dcr_tpu.core.checkpoint import CheckpointManager

    _mk_ckpts(tmp_path, [2, 4])
    coord = ScriptedCoordinator([
        lambda v: [v, 2],    # proposals: local 4, peer 2 -> agreed 2
        lambda v: [v, 1],    # validation of step 2: both ok
    ])
    mgr = CheckpointManager(tmp_path / "ckpt", async_save=False,
                            coordinator=coord)
    state, step, skipped = mgr.restore_latest_valid({"w": jnp.zeros(8)})
    assert step == 2 and skipped == []
    np.testing.assert_array_equal(np.asarray(state["w"]), np.full(8, 2.0))
    assert [name for name, _ in coord.calls] == ["ckpt_candidate", "ckpt_valid"]
    mgr.close()


@pytest.mark.fast
def test_coordinated_restore_quarantines_peer_rejected_step(tmp_path):
    """Both propose 4; the peer fails validating it -> 4 is quarantined
    pod-wide and the next round lands on 2."""
    import jax.numpy as jnp

    from dcr_tpu.core.checkpoint import CheckpointManager

    _mk_ckpts(tmp_path, [2, 4])
    coord = ScriptedCoordinator([
        lambda v: [v, 4],    # round 1 proposals -> agreed 4
        lambda v: [v, 0],    # round 1 validation: peer says no
        lambda v: [v, 2],    # round 2 proposals -> agreed 2
        lambda v: [v, 1],    # round 2 validation: both ok
    ])
    mgr = CheckpointManager(tmp_path / "ckpt", async_save=False,
                            coordinator=coord)
    state, step, skipped = mgr.restore_latest_valid({"w": jnp.zeros(8)})
    assert step == 2
    assert [s for s, _ in skipped] == [4]
    assert "peer host" in skipped[0][1]
    assert (tmp_path / "ckpt" / "quarantined" / "4").exists()
    mgr.close()


@pytest.mark.fast
def test_coordinated_restore_raises_when_any_host_is_empty(tmp_path):
    import jax.numpy as jnp

    from dcr_tpu.core.checkpoint import CheckpointManager

    _mk_ckpts(tmp_path, [2])
    coord = ScriptedCoordinator([lambda v: [v, -1]])  # peer has nothing
    mgr = CheckpointManager(tmp_path / "ckpt", async_save=False,
                            coordinator=coord)
    with pytest.raises(FileNotFoundError, match="every host"):
        mgr.restore_latest_valid({"w": jnp.zeros(8)})
    mgr.close()


# ---------------------------------------------------------------------------
# Unit: quarantine-manifest merge tool
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_merge_quarantine_reports_per_kind_and_rank(tmp_path):
    run = tmp_path / "run"
    run.mkdir()
    (run / "quarantine.jsonl").write_text(
        json.dumps({"kind": "bad_sample", "time": 3.0, "index": 7}) + "\n"
        + json.dumps({"kind": "nan_rollback", "time": 5.0, "at_step": 9}) + "\n")
    (run / "quarantine.p1.jsonl").write_text(
        json.dumps({"kind": "bad_sample", "time": 4.0, "index": 8}) + "\n")
    out = tmp_path / "report.json"
    merged = tmp_path / "merged.jsonl"
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "merge_quarantine.py"),
         str(run), "--out", str(out), "--merged", str(merged)],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    report = json.loads(out.read_text())
    assert report["total"] == 3
    assert report["processes"] == [0, 1]
    assert report["by_kind"] == {"bad_sample": 2, "nan_rollback": 1}
    assert report["by_rank"] == {"0": 2, "1": 1}
    assert report["by_kind_rank"] == {"bad_sample@rank0": 1,
                                      "bad_sample@rank1": 1,
                                      "nan_rollback@rank0": 1}
    recs = [json.loads(l) for l in merged.read_text().splitlines()]
    assert [r["rank"] for r in recs] == [0, 1, 0]       # time-sorted
    # empty dir is distinguishable from a clean run
    empty = tmp_path / "empty"
    empty.mkdir()
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "merge_quarantine.py"), str(empty)],
        capture_output=True, text=True)
    assert proc.returncode == 2


# ---------------------------------------------------------------------------
# E2E: 2-process coordinated recovery through the real CLI (slow)
# ---------------------------------------------------------------------------

_FP_RE = re.compile(r"state fingerprint at step (\d+): ([0-9a-f]{8})")


def _fingerprint(out: str) -> str:
    m = _FP_RE.search(out)
    assert m, f"no state fingerprint in output:\n{out[-3000:]}"
    return m.group(2)


def _make_data(base: Path) -> Path:
    rng = np.random.default_rng(0)
    for cls in ["c0", "c1"]:
        d = base / "data" / cls
        d.mkdir(parents=True)
        for i in range(8):
            Image.fromarray(rng.integers(0, 255, (20, 20, 3), np.uint8)).save(
                d / f"{i}.png")
    return base / "data"


def _pod_cfg(base: Path, out_name: str, **overrides) -> TrainConfig:
    defaults = dict(
        output_dir=str(base / out_name),
        seed=0,
        train_batch_size=2,
        max_train_steps=6,
        num_train_epochs=20,
        mixed_precision="no",
        save_steps=1000,
        modelsavesteps=2,
        log_every=1,
        model=ModelConfig.tiny(),
        data=DataConfig(train_data_dir=str(base / "data"), resolution=16,
                        class_prompt="nolevel", num_workers=2, seed=0),
        optim=OptimConfig(learning_rate=1e-4, lr_scheduler="constant",
                          lr_warmup_steps=0),
    )
    defaults.update(overrides)
    return TrainConfig(**defaults)


def _run_pod(cfg, cfg_path: Path, *, dcr_faults: str = "",
             extra_env: dict | None = None, timeout: int = 600):
    """One 2-process training leg = two fresh CLI processes, 1 CPU device
    each (mesh data axis spans the DCN boundary)."""
    import os

    save_config(cfg, cfg_path)
    env = worker_base_env(local_devices=1, inherit=True)
    cache = os.environ.get("DCR_TEST_CACHE_DIR") or str(
        REPO / "tests" / ".jax_cache_cpu")
    env.update(
        DCR_TPU_PLATFORM="cpu",
        JAX_THREEFRY_PARTITIONABLE="1",
        JAX_COMPILATION_CACHE_DIR=cache,
        JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="1.0",
        JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES="0",
    )
    if dcr_faults:
        env["DCR_FAULTS"] = dcr_faults
    if extra_env:
        env.update(extra_env)
    return run_two_process(
        [sys.executable, "-m", "dcr_tpu.cli.train", f"--config={cfg_path}"],
        env=env, timeout=timeout)


def _final_state_arrays(cfg, step: int) -> dict:
    with np.load(Path(cfg.output_dir) / "checkpoints" / str(step)
                 / "state.npz") as z:
        return {k: z[k].copy() for k in z.files}


def _rollback_records(run_dir: Path) -> dict[int, list[dict]]:
    out = {}
    for rank, name in ((0, "quarantine.jsonl"), (1, "quarantine.p1.jsonl")):
        path = run_dir / name
        entries = ([json.loads(l) for l in path.read_text().splitlines()]
                   if path.exists() else [])
        out[rank] = [e for e in entries if e["kind"] == "nan_rollback"]
    return out


@pytest.mark.slow
def test_two_process_rank_targeted_nan_rolls_back_both_ranks(tmp_path):
    """Acceptance: nan on rank 1 only -> the agreement makes BOTH ranks roll
    back to the same checkpoint at the same step, and the final state is
    bit-exact vs the symmetric-injection run (identical recovery action,
    identical trajectory)."""
    _make_data(tmp_path)
    ft = FaultToleranceConfig(max_rollbacks=1)

    ref_cfg = _pod_cfg(tmp_path, "run_nan_sym", fault=ft)
    ref = _run_pod(ref_cfg, tmp_path / "nan_sym.json",
                   dcr_faults="nan_loss@step=5")
    for rank, (rc, out) in enumerate(ref):
        assert rc == 0, f"sym rank {rank}:\n{out[-3000:]}"

    tgt_cfg = _pod_cfg(tmp_path, "run_nan_tgt", fault=ft)
    tgt = _run_pod(tgt_cfg, tmp_path / "nan_tgt.json",
                   dcr_faults="nan_loss@step=5@rank=1")
    for rank, (rc, out) in enumerate(tgt):
        assert rc == 0, f"tgt rank {rank}:\n{out[-3000:]}"

    # rank 0 saw a finite local loss yet took the agreed rollback action
    assert "agreement" in tgt[0][1] and '"action": "rollback"' in tgt[0][1]
    # both ranks recorded the identical rollback: at step 5, restored from 4
    for run_dir in (Path(ref_cfg.output_dir), Path(tgt_cfg.output_dir)):
        recs = _rollback_records(run_dir)
        for rank in (0, 1):
            assert len(recs[rank]) == 1, (run_dir, rank, recs)
            assert recs[rank][0]["at_step"] == 5
            assert recs[rank][0]["restored_step"] == 4
    # bit-exact: every rank of both runs ends at the same fingerprint...
    fps = {_fingerprint(out) for _, out in ref + tgt}
    assert len(fps) == 1, f"divergent final states: {fps}"
    # ...and the final checkpoints match array-for-array
    ref_arrays = _final_state_arrays(ref_cfg, 6)
    tgt_arrays = _final_state_arrays(tgt_cfg, 6)
    assert set(ref_arrays) == set(tgt_arrays)
    for key in ref_arrays:
        np.testing.assert_array_equal(ref_arrays[key], tgt_arrays[key])


@pytest.mark.slow
def test_two_process_sigterm_synchronized_checkpoint_and_exit(tmp_path):
    """Acceptance: SIGTERM on rank 0 -> one synchronized final checkpoint,
    both ranks exit EXIT_PREEMPTED, and the restarted pod reproduces the
    uninterrupted run bit-exactly."""
    _make_data(tmp_path)

    ref_cfg = _pod_cfg(tmp_path, "run_pre_ref")
    ref = _run_pod(ref_cfg, tmp_path / "pre_ref.json")
    for rank, (rc, out) in enumerate(ref):
        assert rc == 0, f"ref rank {rank}:\n{out[-3000:]}"
    ref_fp = {_fingerprint(out) for _, out in ref}
    assert len(ref_fp) == 1

    cfg = _pod_cfg(tmp_path, "run_pre")
    res = _run_pod(cfg, tmp_path / "pre.json",
                   dcr_faults="sigterm@step=3@rank=0")
    for rank, (rc, out) in enumerate(res):
        assert rc == C.EXIT_PREEMPTED, \
            f"rank {rank} exit {rc} != EXIT_PREEMPTED:\n{out[-3000:]}"
        # both ranks acknowledged the SAME stop point, attributed to rank 0
        assert "preemption: checkpointing at step 3" in out
        assert "signaled on ranks [0]" in out
        assert "exiting with code 83" in out
    assert (Path(cfg.output_dir) / "checkpoints" / "3").exists()

    resumed = _run_pod(cfg, tmp_path / "pre.json")
    for rank, (rc, out) in enumerate(resumed):
        assert rc == 0, f"resume rank {rank}:\n{out[-3000:]}"
        assert "resumed from checkpoint step 3" in out
    assert {_fingerprint(out) for _, out in resumed} == ref_fp
    ref_arrays = _final_state_arrays(ref_cfg, 6)
    got_arrays = _final_state_arrays(cfg, 6)
    for key in ref_arrays:
        np.testing.assert_array_equal(got_arrays[key], ref_arrays[key])


@pytest.mark.slow
def test_two_process_injected_hang_trips_watchdog_on_both_ranks(tmp_path):
    """Acceptance: rank 1 wedges at step 5 -> its heartbeat watchdog fires
    within the timeout; rank 0's agreement allgather times out the same way;
    both dump stacks + the last agreement word and exit EXIT_HANG. The
    processes end themselves — the launcher's timeout is never the thing
    that kills them."""
    _make_data(tmp_path)
    cfg = _pod_cfg(tmp_path, "run_hang")
    t0 = time.monotonic()
    res = _run_pod(cfg, tmp_path / "hang.json",
                   dcr_faults="hang@step=5@rank=1",
                   extra_env={"DCR_HANG_TIMEOUT_S": "45"}, timeout=900)
    elapsed = time.monotonic() - t0
    (rc0, out0), (rc1, out1) = res
    assert rc1 == C.EXIT_HANG, f"rank1 exit {rc1}:\n{out1[-3000:]}"
    assert rc0 == C.EXIT_HANG, f"rank0 exit {rc0}:\n{out0[-3000:]}"
    assert "injected_hang" in out1                  # the fault fired on rank 1
    for rank, out in ((0, out0), (1, out1)):
        assert "hang_abort" in out, f"rank {rank} missing hang_abort"
        assert "--- thread" in out, f"rank {rank} missing stack dump"
        assert "last_agreement" in out, f"rank {rank} missing agreement word"
    # watchdog-bounded exit, not a scheduler/test kill: well under launcher
    # timeout and roughly startup + 5 steps + the 45s watchdog window
    assert elapsed < 880, f"workers took {elapsed:.0f}s"

"""MetricWriter sink contract, including the wandb branch.

wandb is not installed in this image, so every prior run exercised only the
jsonl/TB fallbacks (VERDICT r4 weak #6). These tests drive the wandb code
path against a stub module injected into sys.modules carrying the real API
surface the writer uses (init → run.log/finish, wandb.Image) — the branch is
now executed, its call shapes asserted, and the reference's dashboard
contract (scalar dict + step per log call, diff_train.py:544-553,703-705)
is pinned down without the dependency.
"""

from __future__ import annotations

import json
import sys
import types

import numpy as np
import pytest

from dcr_tpu.core.metrics import MetricWriter

pytestmark = pytest.mark.fast


class _StubRun:
    def __init__(self):
        self.logged: list[tuple[dict, int]] = []
        self.finished = False

    def log(self, values, step=None):
        self.logged.append((values, step))

    def finish(self):
        self.finished = True


class _StubImage:
    def __init__(self, array):
        self.array = np.asarray(array)


def _install_stub(monkeypatch):
    stub = types.ModuleType("wandb")
    stub.runs = []

    def init(**kwargs):
        run = _StubRun()
        run.init_kwargs = kwargs
        stub.runs.append(run)
        return run

    stub.init = init
    stub.Image = _StubImage
    monkeypatch.setitem(sys.modules, "wandb", stub)
    return stub


def test_wandb_branch_logs_scalars_images_and_finishes(tmp_path, monkeypatch):
    stub = _install_stub(monkeypatch)
    w = MetricWriter(tmp_path, use_tensorboard=False, use_wandb=True,
                     wandb_project="diffrep_ft", run_name="r5",
                     config={"lr": 1e-4})
    (run,) = stub.runs
    assert run.init_kwargs["project"] == "diffrep_ft"  # reference project name
    assert run.init_kwargs["name"] == "r5"
    assert run.init_kwargs["config"] == {"lr": 1e-4}

    w.scalars(3, {"loss": np.float32(0.5), "lr": 1e-4})
    w.image(4, "samples", np.zeros((8, 8, 3), np.uint8))
    w.close()

    scalar_logs = [(v, s) for v, s in run.logged
                   if not any(isinstance(x, _StubImage) for x in v.values())]
    assert scalar_logs == [({"loss": 0.5, "lr": 1e-4}, 3)]
    image_logs = [(v, s) for v, s in run.logged if "samples" in v]
    assert len(image_logs) == 1 and image_logs[0][1] == 4
    assert isinstance(image_logs[0][0]["samples"], _StubImage)
    assert run.finished
    # jsonl sink still wrote alongside wandb (dual system of record)
    lines = [json.loads(l) for l in
             (tmp_path / "metrics.jsonl").read_text().splitlines()]
    assert lines and lines[0]["loss"] == 0.5


def test_wandb_init_failure_falls_back_silently(tmp_path, monkeypatch):
    stub = _install_stub(monkeypatch)

    def broken_init(**kwargs):
        raise RuntimeError("no network")

    stub.init = broken_init
    w = MetricWriter(tmp_path, use_tensorboard=False, use_wandb=True)
    w.scalars(0, {"loss": 1.0})      # must not raise
    w.close()
    assert (tmp_path / "metrics.jsonl").exists()

import io
import pickle
import tarfile

import numpy as np
import pytest

pytestmark = pytest.mark.fast
from PIL import Image

from dcr_tpu.core.config import SearchConfig
from dcr_tpu.search import embed as E
from dcr_tpu.search import search as S


def _write_tar(path, names, rng):
    with tarfile.open(path, "w") as tf:
        for name in names:
            buf = io.BytesIO()
            Image.fromarray(rng.integers(0, 255, (32, 32, 3), np.uint8)).save(
                buf, format="JPEG")
            data = buf.getvalue()
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))


def test_embedding_roundtrip_npz_and_reference_pickle(tmp_path):
    feats = np.random.default_rng(0).standard_normal((5, 8)).astype(np.float32)
    keys = [f"k{i}" for i in range(5)]
    E.save_embeddings(tmp_path / "embedding.npz", feats, keys)
    f2, k2 = E.load_embeddings(tmp_path / "embedding.npz")
    np.testing.assert_array_equal(f2, feats)
    assert k2 == keys
    # reference-format pickle reads too
    with open(tmp_path / "embedding.pkl", "wb") as f:
        pickle.dump({"features": feats, "indexes": keys}, f)
    f3, k3 = E.load_embeddings(tmp_path / "embedding.pkl")
    np.testing.assert_array_equal(f3, feats)
    assert k3 == keys
    assert E.find_embedding_file(tmp_path).name == "embedding.npz"


def test_iter_webdataset_images_skips_corrupt(tmp_path):
    rng = np.random.default_rng(0)
    _write_tar(tmp_path / "000.tar", ["a.jpg", "b.jpg"], rng)
    # corrupt member
    with tarfile.open(tmp_path / "001.tar", "w") as tf:
        info = tarfile.TarInfo("bad.jpg")
        payload = b"not an image"
        info.size = len(payload)
        tf.addfile(info, io.BytesIO(payload))
        buf = io.BytesIO()
        Image.fromarray(rng.integers(0, 255, (16, 16, 3), np.uint8)).save(
            buf, format="PNG")
        info2 = tarfile.TarInfo("ok.png")
        info2.size = buf.tell()
        buf.seek(0)
        tf.addfile(info2, buf)
    items = list(E.iter_webdataset_images(sorted(tmp_path.glob("*.tar")), 16))
    names = [k for k, _ in items]
    assert names == ["000/a", "000/b", "001/ok"]
    assert items[0][1].shape == (16, 16, 3)


def test_embed_images_from_tars_and_folder(tmp_path, cpu_devices):
    rng = np.random.default_rng(0)
    tar_dir = tmp_path / "laion"
    tar_dir.mkdir()
    _write_tar(tar_dir / "000.tar", [f"{i}.jpg" for i in range(5)], rng)
    cfg = SearchConfig(image_size=32, batch_size=2)
    out = E.embed_images(cfg, source=tar_dir)
    feats, keys = E.load_embeddings(out)
    assert feats.shape == (5, 512) and len(keys) == 5

    folder = tmp_path / "gens"
    folder.mkdir()
    for i in range(3):
        Image.fromarray(rng.integers(0, 255, (32, 32, 3), np.uint8)).save(
            folder / f"{i}.png")
    out2 = E.embed_images(cfg, source=folder)
    feats2, keys2 = E.load_embeddings(out2)
    assert feats2.shape == (3, 512)


def test_topk_merge():
    s = np.array([[0.9, 0.5], [0.3, 0.1]])
    k = np.array([["a", "b"], ["c", "d"]], dtype=object)
    ns = np.array([[0.7, 0.1], [0.8, 0.2]])
    nk = np.array([["x", "y"], ["z", "w"]], dtype=object)
    ms, mk = S.topk_merge(s, k, ns, nk)
    np.testing.assert_allclose(ms, [[0.9, 0.7], [0.8, 0.3]])
    assert mk.tolist() == [["a", "x"], ["z", "c"]]


def test_search_end_to_end(tmp_path, cpu_devices):
    rng = np.random.default_rng(0)
    # two laion folders with known embeddings; gen 0 matches laion1/k1 exactly
    d = 16
    gen = rng.standard_normal((4, d)).astype(np.float32)
    gen /= np.linalg.norm(gen, axis=1, keepdims=True)
    l1 = rng.standard_normal((10, d)).astype(np.float32) * 0.1
    l1[3] = gen[0]  # exact copy
    l2 = rng.standard_normal((7, d)).astype(np.float32) * 0.1
    l2[5] = gen[1] * 0.9
    for i, (folder, feats) in enumerate([("laion1", l1), ("laion2", l2)]):
        fdir = tmp_path / folder
        fdir.mkdir()
        E.save_embeddings(fdir / "embedding.npz", feats,
                          [f"{folder}_img{j}" for j in range(len(feats))])
    gdir = tmp_path / "gens"
    gdir.mkdir()
    E.save_embeddings(gdir / "embedding.npz", gen, [f"g{i}" for i in range(4)])
    # corrupt folder tolerated
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "embedding.npz").write_bytes(b"garbage")

    cfg = SearchConfig(gen_folder=str(gdir), out_path=str(tmp_path / "res.npz"),
                       num_chunks=3)
    out = S.run_search(cfg, laion_folders=[tmp_path / "laion1",
                                           tmp_path / "laion2", bad,
                                           tmp_path / "missing"])
    with np.load(out, allow_pickle=False) as z:
        scores, keys, gens = z["scores"], z["keys"], z["gen_images"]
    assert keys[0, 0] == "laion1_img3"
    assert scores[0, 0] == pytest.approx(1.0, abs=1e-5)
    assert keys[1, 0] == "laion2_img5"
    assert list(gens) == ["g0", "g1", "g2", "g3"]


def test_download_raises_with_command_when_tool_missing(tmp_path):
    with pytest.raises(RuntimeError, match="img2dataset"):
        E.download_laion_chunk("part.parquet", str(tmp_path))

"""Real multi-process DCN test: two localhost processes join via
jax.distributed, shard a batch across their devices, and verify a global
reduction + process_allgather (SURVEY.md §4 item 3: 'multi-process DCN paths
tested with jax.distributed over localhost subprocesses')."""

import sys

import pytest

from tests._multiproc import run_two_process, worker_base_env

WORKER = r"""
import sys
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import jax.numpy as jnp

from dcr_tpu.core import dist
from dcr_tpu.core.config import MeshConfig
from dcr_tpu.parallel import make_mesh, shard_batch, to_host

dist.initialize()
assert jax.process_count() == 2, jax.process_count()
mesh = make_mesh(MeshConfig())
rank = dist.process_index()
# each process contributes its local half of a global batch of 4
local = {"x": np.arange(2, dtype=np.float32) + 10 * rank}
batch = shard_batch(mesh, local)
total = float(jax.jit(lambda b: jnp.sum(b["x"]))(batch))
assert abs(total - (0 + 1 + 10 + 11)) < 1e-6, total
gathered = to_host(batch["x"])
assert gathered.shape == (4,), gathered.shape
assert sorted(gathered.tolist()) == [0.0, 1.0, 10.0, 11.0], gathered
print(f"RANK{rank}_OK")
"""


WORKER_SEQ_PARALLEL = r"""
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from dcr_tpu.core import dist
from dcr_tpu.core.config import MeshConfig
from dcr_tpu.ops.attention import dot_product_attention
from dcr_tpu.ops.ring_attention import ring_self_attention
from dcr_tpu.ops.ulysses_attention import ulysses_self_attention
from dcr_tpu.parallel import make_mesh, to_host

dist.initialize()
assert jax.process_count() == 2, jax.process_count()
assert jax.local_device_count() == 2, jax.local_device_count()
# seq axis of 4 spans both processes: ring's ppermute hops and ulysses'
# all_to_all both cross the process (DCN) boundary
mesh = make_mesh(MeshConfig(data=1, fsdp=1, tensor=1, seq=4))

rng = np.random.default_rng(0)          # same arrays on both processes
b, s, h, d = 2, 64, 4, 8
full = {n: rng.standard_normal((b, s, h, d)).astype(np.float32)
        for n in ("q", "k", "v")}
sharding = NamedSharding(mesh, P(None, "seq", None, None))
glob = {n: jax.make_array_from_callback(
            (b, s, h, d), sharding, lambda idx, n=n: full[n][idx])
        for n in full}

ref = np.asarray(dot_product_attention(      # process-local dense reference
    jnp.asarray(full["q"]), jnp.asarray(full["k"]), jnp.asarray(full["v"]),
    use_flash=False))
for name, fn in (("ring", ring_self_attention),
                 ("ulysses", ulysses_self_attention)):
    out = to_host(fn(glob["q"], glob["k"], glob["v"], mesh))
    err = float(np.max(np.abs(np.asarray(out) - ref)))
    assert err < 2e-5, (name, err)
print(f"RANK{dist.process_index()}_SP_OK")
"""


WORKER_SHARDED_SIMILARITY = r"""
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from dcr_tpu.core import dist
from dcr_tpu.core.config import MeshConfig
from dcr_tpu.eval import similarity as SIM
from dcr_tpu.parallel import make_mesh

dist.initialize()
assert jax.process_count() == 2, jax.process_count()
mesh = make_mesh(MeshConfig(data=4))    # 2 procs x 2 local = 4 global devices
rng = np.random.default_rng(0)
v = SIM.l2_normalize(rng.standard_normal((20, 16)).astype(np.float32))
q = SIM.l2_normalize(rng.standard_normal((13, 16)).astype(np.float32))
# row-sharded matmul spans both processes; outputs come back via the
# process allgather (device_get would raise on non-addressable shards)
sim = SIM.similarity_matrix(v, q, mesh=mesh)
bg = SIM.train_train_background(v, mesh=mesh)
ref = q @ v.T
full = v @ v.T
np.fill_diagonal(full, -np.inf)
assert np.allclose(sim, ref, atol=1e-5)
assert np.allclose(bg, full.max(axis=1), atol=1e-5)
print(f"RANK{dist.process_index()}_SIM_OK")
"""


def _run_two_process(worker_src: str, ok_token: str, *, local_devices: int = 1,
                     timeout: int = 240) -> None:
    # launch (with rendezvous-port-race retry) via the shared helper
    try:
        results = run_two_process(
            [sys.executable, "-c", worker_src],
            env=worker_base_env(local_devices=local_devices), timeout=timeout)
    except TimeoutError as e:
        pytest.fail(f"multi-process workers timed out: {e}")
    for rank, (rc, out) in enumerate(results):
        assert rc == 0, f"rank {rank} failed:\n{out[-2000:]}"
        assert ok_token.format(rank=rank) in out


@pytest.mark.slow
def test_two_process_dcn():
    _run_two_process(WORKER, "RANK{rank}_OK")


@pytest.mark.slow
def test_two_process_seq_parallel_attention():
    """Ring ppermute + Ulysses all_to_all across a seq axis spanning two
    processes (collectives over the DCN boundary), exact vs dense."""
    _run_two_process(WORKER_SEQ_PARALLEL, "RANK{rank}_SP_OK",
                     local_devices=2, timeout=360)


@pytest.mark.slow
def test_two_process_sharded_similarity():
    """Mesh-sharded eval similarity with the mesh spanning two processes —
    the multi-host regime SURVEY §3.5's design targets; guards the
    to_host-not-device_get output fetch."""
    _run_two_process(WORKER_SHARDED_SIMILARITY, "RANK{rank}_SIM_OK",
                     local_devices=2, timeout=360)

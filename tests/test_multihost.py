"""Real multi-process DCN test: two localhost processes join via
jax.distributed, shard a batch across their devices, and verify a global
reduction + process_allgather (SURVEY.md §4 item 3: 'multi-process DCN paths
tested with jax.distributed over localhost subprocesses')."""

import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = r"""
import sys
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import jax.numpy as jnp

from dcr_tpu.core import dist
from dcr_tpu.core.config import MeshConfig
from dcr_tpu.parallel import make_mesh, shard_batch, to_host

dist.initialize()
assert jax.process_count() == 2, jax.process_count()
mesh = make_mesh(MeshConfig())
rank = dist.process_index()
# each process contributes its local half of a global batch of 4
local = {"x": np.arange(2, dtype=np.float32) + 10 * rank}
batch = shard_batch(mesh, local)
total = float(jax.jit(lambda b: jnp.sum(b["x"]))(batch))
assert abs(total - (0 + 1 + 10 + 11)) < 1e-6, total
gathered = to_host(batch["x"])
assert gathered.shape == (4,), gathered.shape
assert sorted(gathered.tolist()) == [0.0, 1.0, 10.0, 11.0], gathered
print(f"RANK{rank}_OK")
"""


@pytest.mark.slow
def test_two_process_dcn(tmp_path):
    port = socket.socket()
    port.bind(("127.0.0.1", 0))
    addr = f"127.0.0.1:{port.getsockname()[1]}"
    port.close()
    repo = str(Path(__file__).parent.parent)
    procs = []
    for rank in range(2):
        env = {
            "COORDINATOR_ADDRESS": addr,
            "NUM_PROCESSES": "2",
            "PROCESS_ID": str(rank),
            "PYTHONPATH": repo,
            "PATH": "/usr/bin:/bin:/usr/local/bin",
            "HOME": "/tmp",
        }
        procs.append(subprocess.Popen([sys.executable, "-c", WORKER], env=env,
                                      stdout=subprocess.PIPE,
                                      stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process workers timed out")
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-2000:]}"
        assert f"RANK{rank}_OK" in out

"""dcr-store acceptance: sharded embedding store + mesh-sharded top-k.

Layers:

1. store build/append/verify roundtrip + writer validation (fast);
2. damage discipline: on-disk shard corruption, the deterministic
   ``store_shard_corrupt`` / ``search_dump_corrupt`` fault kinds, the
   sha256+rows dump sidecar, the search-folder quarantine/keep contract;
3. the exact-equality pins: store-backed top-k vs the brute force on the
   same dump (scores AND keys), single-device and 8-way mesh-sharded,
   padded-query invariance, host-streamed vs device-resident;
4. CLI subcommands + trace_report "Search" section + bench schema;
5. slow legs: serve ``/check`` answered from a store-backed index (HTTP
   e2e) and a warm-restarted ``dcr-search query`` answering with ZERO XLA
   compiles (``trace_report --max-compiles 0``).
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from dcr_tpu.core import tracing
from dcr_tpu.core.config import RiskConfig, SearchConfig
from dcr_tpu.search import embed as E
from dcr_tpu.search import search as S
from dcr_tpu.search.embed import EmbeddingDumpError
from dcr_tpu.search.store import (EmbeddingStoreReader, EmbeddingStoreWriter,
                                  MANIFEST_NAME, StoreError, ingest_dumps)
from dcr_tpu.utils import faults


def _counter(name: str) -> int:
    return tracing.registry().counters("search/").get(name, 0)


def _dump_folders(tmp_path, rng, sizes, dim=16, prefix="laion"):
    folders = []
    for i, n in enumerate(sizes):
        folder = tmp_path / f"{prefix}{i}"
        folder.mkdir()
        feats = rng.standard_normal((n, dim)).astype(np.float32)
        E.save_embeddings(folder / "embedding.npz", feats,
                          [f"{prefix}{i}_img{j}" for j in range(n)])
        folders.append(folder)
    return folders


def _build_store(tmp_path, folders, name="store", **writer_kw):
    writer = EmbeddingStoreWriter.create(tmp_path / name, **writer_kw)
    report = ingest_dumps(writer, folders)
    return tmp_path / name, report


# ---------------------------------------------------------------------------
# 1. store build/append/verify roundtrip
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_store_build_append_verify_roundtrip(tmp_path, rng_np):
    folders = _dump_folders(tmp_path, rng_np, [10, 7, 13])
    store, report = _build_store(tmp_path, folders, shard_rows=8)
    assert report == {**report, "rows": 30, "dumps": 3, "skipped": 0}
    reader = EmbeddingStoreReader(store)
    assert reader.total == 30 and reader.embed_dim == 16
    # committed shards are fixed-capacity except the tail
    counts = [s["count"] for s in reader.shards]
    assert counts == [8, 8, 8, 6]
    feats, keys = reader.load_all()
    want = np.concatenate([E.load_embeddings(f / "embedding.npz")[0]
                           for f in folders])
    np.testing.assert_array_equal(feats, want)   # ingest preserves bytes
    assert keys[:2] == ["laion0_img0", "laion0_img1"]
    assert reader.verify() == {"shards": 4, "ok": 4, "corrupt": 0,
                               "rows_ok": 30, "total": 30}

    # append-only growth: committed shards untouched, manifest re-commits
    extra = _dump_folders(tmp_path, rng_np, [5], prefix="extra")
    before = {s["file"]: s["sha256"] for s in reader.shards}
    report2 = ingest_dumps(EmbeddingStoreWriter.append(store), extra)
    assert report2["rows"] == 5 and report2["total"] == 35
    reader2 = EmbeddingStoreReader(store)
    assert reader2.total == 35
    for s in reader2.shards:
        if s["file"] in before:
            assert s["sha256"] == before[s["file"]]
    feats2, keys2 = reader2.load_all()
    assert len(keys2) == 35 and keys2[-1] == "extra0_img4"
    np.testing.assert_array_equal(feats2[:30], want)


@pytest.mark.fast
def test_store_writer_validation_and_clobber_refusal(tmp_path, rng_np):
    w = EmbeddingStoreWriter.create(tmp_path / "s", shard_rows=4)
    w.add(rng_np.standard_normal((3, 8)).astype(np.float32), ["a", "b", "c"])
    with pytest.raises(StoreError, match="width"):
        w.add(np.zeros((2, 9), np.float32), ["d", "e"])
    with pytest.raises(StoreError, match="torn"):
        w.add(np.zeros((2, 8), np.float32), ["d"])
    with pytest.raises(StoreError, match="non-finite"):
        w.add(np.full((1, 8), np.nan, np.float32), ["d"])
    with pytest.raises(StoreError, match="N, D"):
        w.add(np.zeros((4,), np.float32), list("abcd"))
    w.finalize()
    with pytest.raises(StoreError, match="committed store"):
        EmbeddingStoreWriter.create(tmp_path / "s")
    # append on a directory that is not a store is typed
    with pytest.raises(StoreError, match="not an embedding store"):
        EmbeddingStoreWriter.append(tmp_path / "nowhere")


@pytest.mark.fast
def test_store_normalize_at_ingest(tmp_path, rng_np):
    folders = _dump_folders(tmp_path, rng_np, [6])
    store, _ = _build_store(tmp_path, folders, shard_rows=4, normalize=True)
    reader = EmbeddingStoreReader(store)
    assert reader.normalized is True
    feats, _ = reader.load_all()
    np.testing.assert_allclose(np.linalg.norm(feats, axis=1), 1.0, atol=1e-6)


# ---------------------------------------------------------------------------
# 2. damage discipline
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_shard_corruption_quarantined_and_survivors_serve(tmp_path, rng_np):
    folders = _dump_folders(tmp_path, rng_np, [16])
    store, _ = _build_store(tmp_path, folders, shard_rows=4)
    shard1 = store / "shard_00001.npz"
    blob = shard1.read_bytes()
    shard1.write_bytes(blob[:len(blob) // 2] + b"\xff" + blob[len(blob) // 2:])
    before = _counter("search/store_shard_corrupt")
    reader = EmbeddingStoreReader(store)
    feats, keys = reader.load_all()
    # 3 of 4 shards survive; the damaged one is renamed out of the key space
    assert len(keys) == 12
    assert "laion0_img4" not in keys          # rows 4..7 lived in shard 1
    assert _counter("search/store_shard_corrupt") == before + 1
    assert not shard1.exists()
    assert list(store.glob("shard_00001.npz.quarantined.*"))


@pytest.mark.fast
def test_store_verify_readonly_leaves_damage_in_place(tmp_path, rng_np):
    folders = _dump_folders(tmp_path, rng_np, [8])
    store, _ = _build_store(tmp_path, folders, shard_rows=4)
    shard0 = store / "shard_00000.npz"
    shard0.write_bytes(b"garbage")
    reader = EmbeddingStoreReader(store, quarantine=False)
    report = reader.verify()
    assert report["shards"] == 2 and report["ok"] == 1
    assert report["corrupt"] == 1 and report["rows_ok"] == 4
    assert shard0.exists()                    # read-only: nothing renamed


@pytest.mark.fast
def test_store_shard_corrupt_fault_kind(tmp_path, rng_np):
    folders = _dump_folders(tmp_path, rng_np, [12])
    store, _ = _build_store(tmp_path, folders, shard_rows=4)
    faults.install("store_shard_corrupt@load=1")
    try:
        before = _counter("search/store_shard_corrupt")
        feats, keys = EmbeddingStoreReader(store).load_all()
        assert len(keys) == 8                  # shard 1 (reads 0,1,2) poisoned
        assert _counter("search/store_shard_corrupt") == before + 1
        assert list(store.glob("shard_00001.npz.quarantined.*"))
    finally:
        faults.clear()


@pytest.mark.fast
def test_store_zero_survivors_and_corrupt_manifest(tmp_path, rng_np):
    folders = _dump_folders(tmp_path, rng_np, [4])
    store, _ = _build_store(tmp_path, folders, shard_rows=4)
    (store / "shard_00000.npz").write_bytes(b"x")
    with pytest.raises(StoreError, match="no shard survived"):
        EmbeddingStoreReader(store).load_all()

    store2, _ = _build_store(tmp_path, folders, name="store2", shard_rows=4)
    (store2 / MANIFEST_NAME).write_text("{not json")
    # read-only inspection first: typed error, nothing renamed
    with pytest.raises(StoreError, match="manifest corrupt"):
        EmbeddingStoreReader(store2, quarantine=False)
    assert (store2 / MANIFEST_NAME).exists()
    with pytest.raises(StoreError, match="manifest corrupt"):
        EmbeddingStoreReader(store2)
    assert list(store2.glob(f"{MANIFEST_NAME}.quarantined.*"))


@pytest.mark.fast
def test_zero_row_ingest_refuses_to_commit(tmp_path, rng_np):
    # every source dump corrupt: no manifest may commit — a committed
    # empty store would defer the failure to the first query AND block the
    # corrected rebuild behind the clobber refusal
    bad = tmp_path / "badchunk"
    bad.mkdir()
    (bad / "embedding.npz").write_bytes(b"garbage")
    with pytest.raises(StoreError, match="0 rows"):
        ingest_dumps(EmbeddingStoreWriter.create(tmp_path / "s"), [bad])
    assert not (tmp_path / "s" / MANIFEST_NAME).exists()
    # ...so the corrected rebuild works in place
    good = _dump_folders(tmp_path, rng_np, [3], dim=8)
    report = ingest_dumps(EmbeddingStoreWriter.create(tmp_path / "s"), good)
    assert report["rows"] == 3


@pytest.mark.fast
def test_save_embeddings_appends_npz_suffix(tmp_path, rng_np):
    # np.savez semantics preserved: a non-.npz name gets the suffix, so
    # load_embeddings' suffix dispatch can never misparse npz bytes as
    # pickle
    feats = rng_np.standard_normal((2, 4)).astype(np.float32)
    out = E.save_embeddings(tmp_path / "gen_embs", feats, ["a", "b"])
    assert out.name == "gen_embs.npz" and out.exists()
    f2, k2 = E.load_embeddings(out)
    np.testing.assert_array_equal(f2, feats)
    assert k2 == ["a", "b"]


@pytest.mark.fast
def test_dump_sidecar_detects_torn_dump(tmp_path, rng_np):
    feats = rng_np.standard_normal((5, 8)).astype(np.float32)
    path = tmp_path / "embedding.npz"
    E.save_embeddings(path, feats, [f"k{i}" for i in range(5)])
    side = Path(str(path) + ".sha256")
    assert side.exists()
    doc = json.loads(side.read_text())
    assert doc["rows"] == 5
    f2, k2 = E.load_embeddings(path)           # verified load round-trips
    np.testing.assert_array_equal(f2, feats)

    # torn write: truncate the dump — detected at load, typed
    blob = path.read_bytes()
    path.write_bytes(blob[:-20])
    before = _counter("search/dump_corrupt")
    with pytest.raises(EmbeddingDumpError, match="sha256"):
        E.load_embeddings(path)
    assert _counter("search/dump_corrupt") == before + 1

    # row-count mismatch: sidecar promises different rows
    path.write_bytes(blob)
    side.write_text(json.dumps({**doc, "rows": 7,
                                "sha256": doc["sha256"]}))
    with pytest.raises(EmbeddingDumpError, match="rows"):
        E.load_embeddings(path)

    # a corrupt SIDECAR degrades to an unverified load, loudly — never
    # takes down a possibly-fine dump
    side.write_text("{broken")
    before = tracing.registry().counters("search/").get(
        "search/dump_sidecar_unreadable", 0)
    f3, _ = E.load_embeddings(path)
    np.testing.assert_array_equal(f3, feats)
    assert tracing.registry().counters("search/")[
        "search/dump_sidecar_unreadable"] == before + 1


@pytest.mark.fast
def test_search_dump_corrupt_fault_kind(tmp_path, rng_np):
    path = tmp_path / "embedding.npz"
    E.save_embeddings(path, rng_np.standard_normal((3, 8)).astype(np.float32),
                      ["a", "b", "c"])
    E.reset_dump_load_seq()
    faults.install("search_dump_corrupt@load=0")
    try:
        with pytest.raises(EmbeddingDumpError, match="sha256"):
            E.load_embeddings(path)
        # the fault fired once; the next load is clean
        feats, keys = E.load_embeddings(path)
        assert keys == ["a", "b", "c"]
    finally:
        faults.clear()


@pytest.mark.fast
def test_search_folders_quarantines_unreadable_keeps_invalid(
        tmp_path, rng_np, cpu_devices):
    d = 8
    gen = rng_np.standard_normal((2, d)).astype(np.float32)
    good = _dump_folders(tmp_path, rng_np, [5], dim=d, prefix="good")[0]

    # UNREADABLE dump: quarantine-renamed + counted
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "embedding.npz").write_bytes(b"garbage")
    # readable-but-INVALID dump (features/keys row mismatch): left in place
    invalid = tmp_path / "invalid"
    invalid.mkdir()
    np.savez(invalid / "embedding.npz",
             features=np.zeros((4, d), np.float32),
             indexes=np.asarray(["only", "two"]))

    c_before = _counter("search/folder_corrupt")
    i_before = _counter("search/folder_invalid")
    result = S.search_folders(gen, ["g0", "g1"], [good, bad, invalid],
                              top_k=1)
    assert _counter("search/folder_corrupt") == c_before + 1
    assert _counter("search/folder_invalid") == i_before + 1
    assert not (bad / "embedding.npz").exists()
    assert list(bad.glob("embedding.npz.quarantined.*"))
    assert (invalid / "embedding.npz").exists()   # valid-looking artifact
    assert all(k.startswith("good0_") for k in result["keys"].ravel())

    # a sidecar-verified dump that fails its sha is quarantined WITH its
    # sidecar — a stale sidecar left behind would condemn any restored
    # replacement dump to a false-mismatch loop
    torn = tmp_path / "torn"
    torn.mkdir()
    dump = E.save_embeddings(torn / "embedding.npz",
                             rng_np.standard_normal((3, d)).astype(np.float32),
                             ["a", "b", "c"])
    dump.write_bytes(dump.read_bytes()[:-10])
    S.search_folders(gen, ["g0", "g1"], [torn], top_k=1)
    assert not dump.exists()
    assert not Path(str(dump) + ".sha256").exists()
    assert list(torn.glob("embedding.npz.sha256.quarantined.*"))
    # ...so a restored good dump (fresh write = fresh sidecar) serves again
    E.save_embeddings(torn / "embedding.npz",
                      rng_np.standard_normal((3, d)).astype(np.float32),
                      ["x", "y", "z"])
    res2 = S.search_folders(gen, ["g0", "g1"], [torn], top_k=1)
    assert all(k in ("x", "y", "z") for k in res2["keys"].ravel())


# ---------------------------------------------------------------------------
# 3. exact-equality pins
# ---------------------------------------------------------------------------

def _equality_fixture(tmp_path, rng_np, dim=16, sizes=(10, 7, 13), n_gen=5):
    folders = _dump_folders(tmp_path, rng_np, list(sizes), dim=dim)
    gen = rng_np.standard_normal((n_gen, dim)).astype(np.float32)
    gen_keys = [f"g{i}" for i in range(n_gen)]
    store, _ = _build_store(tmp_path, folders, shard_rows=8)
    return folders, store, gen, gen_keys


def test_store_backed_equals_brute_force_single_device(
        tmp_path, rng_np, cpu_devices):
    folders, store, gen, gen_keys = _equality_fixture(tmp_path, rng_np)
    brute = S.search_folders(gen, gen_keys, folders, top_k=3, num_chunks=2)
    res = S.search_store(gen, gen_keys, store, top_k=3, query_batch=4)
    np.testing.assert_array_equal(brute["scores"], res["scores"])
    assert (brute["keys"] == res["keys"]).all()
    assert list(res["gen_images"]) == gen_keys

    # and through the full run_search stage: the banked .npz files match
    gdir = tmp_path / "gens"
    gdir.mkdir()
    E.save_embeddings(gdir / "embedding.npz", gen, gen_keys)
    cfg_brute = SearchConfig(gen_folder=str(gdir), top_k=3,
                             out_path=str(tmp_path / "brute.npz"))
    cfg_store = SearchConfig(gen_folder=str(gdir), top_k=3,
                             store_dir=str(store), query_batch=4,
                             out_path=str(tmp_path / "store.npz"))
    S.run_search(cfg_brute, laion_folders=folders)
    S.run_search(cfg_store)
    with np.load(tmp_path / "brute.npz") as zb, \
            np.load(tmp_path / "store.npz") as zs:
        np.testing.assert_array_equal(zb["scores"], zs["scores"])
        assert (zb["keys"] == zs["keys"]).all()
        assert (zb["gen_images"] == zs["gen_images"]).all()


def test_mesh_sharded_equals_single_device(tmp_path, rng_np, cpu_devices):
    from dcr_tpu.core.config import MeshConfig
    from dcr_tpu.parallel import mesh as pmesh
    from dcr_tpu.search.shardindex import open_engine

    folders, store, gen, gen_keys = _equality_fixture(
        tmp_path, rng_np, sizes=(20, 11), n_gen=6)
    brute = S.search_folders(gen, gen_keys, folders, top_k=4)
    mesh8 = pmesh.make_mesh(MeshConfig(data=8))
    engine = open_engine(store, mesh=mesh8, top_k=4, query_batch=3)
    scores, keys = engine.query(gen)
    # 8-way row sharding: same dots, same merge — bit-equal, key-equal
    np.testing.assert_array_equal(brute["scores"], scores)
    assert (brute["keys"] == keys).all()
    # segment padded to the row-shard multiple
    assert engine.segment_rows % 8 == 0


def test_padded_query_invariance_and_chunking(tmp_path, rng_np, cpu_devices):
    from dcr_tpu.search.shardindex import open_engine

    _, store, gen, _ = _equality_fixture(tmp_path, rng_np, n_gen=10)
    engine = open_engine(store, top_k=2, query_batch=4)
    # 10 queries through the fixed batch-4 program (3 chunks, last padded)
    scores, keys = engine.query(gen)
    for i in range(len(gen)):
        s1, k1 = engine.query(gen[i:i + 1])    # padded 1-of-4
        np.testing.assert_array_equal(s1[0], scores[i])
        assert (k1[0] == keys[i]).all()


def test_streamed_segments_match_resident(tmp_path, rng_np, cpu_devices):
    from dcr_tpu.search.shardindex import ShardedTopK

    _, store, gen, _ = _equality_fixture(tmp_path, rng_np, sizes=(9, 9, 9))
    resident = ShardedTopK(EmbeddingStoreReader(store), top_k=3,
                           query_batch=4, segment_rows=8).build()
    streamed = ShardedTopK(EmbeddingStoreReader(store), top_k=3,
                           query_batch=4, segment_rows=8,
                           max_resident_rows=1).build()
    assert resident.resident and not streamed.resident
    assert resident.num_segments == 4          # 27 rows / 8-row segments
    assert resident._segments == []            # host copies dropped
    assert len(streamed._segments) == 4        # streamed keeps host copies
    s_r, k_r = resident.query(gen)
    s_s, k_s = streamed.query(gen)
    np.testing.assert_array_equal(s_r, s_s)
    assert (k_r == k_s).all()


def test_store_smaller_than_topk_pads_like_brute(tmp_path, rng_np,
                                                 cpu_devices):
    folders = _dump_folders(tmp_path, rng_np, [2], dim=8)
    store, _ = _build_store(tmp_path, folders, shard_rows=4)
    gen = rng_np.standard_normal((2, 8)).astype(np.float32)
    brute = S.search_folders(gen, ["g0", "g1"], folders, top_k=5)
    res = S.search_store(gen, ["g0", "g1"], store, top_k=5, query_batch=2)
    np.testing.assert_array_equal(brute["scores"], res["scores"])
    assert (brute["keys"] == res["keys"]).all()
    assert np.isneginf(res["scores"][:, 2:]).all()
    assert (res["keys"][:, 2:] == "").all()


# ---------------------------------------------------------------------------
# 4. CLI + telemetry + bench schema
# ---------------------------------------------------------------------------

def test_cli_build_append_verify_query(tmp_path, rng_np, cpu_devices,
                                       capsys):
    from dcr_tpu.cli import search as cli

    folders_root = tmp_path / "corpus"
    folders_root.mkdir()
    _dump_folders(folders_root, rng_np, [6, 5], dim=8, prefix="chunk")
    store = tmp_path / "store"
    cli.main(["build", f"--store_dir={store}",
              f"--laion_folder={folders_root}", "--shard_rows=4"])
    report = json.loads(capsys.readouterr().out)
    assert report["rows"] == 11 and report["skipped"] == 0

    extra_root = tmp_path / "more"
    extra_root.mkdir()
    _dump_folders(extra_root, rng_np, [3], dim=8, prefix="late")
    cli.main(["append", f"--store_dir={store}",
              f"--laion_folder={extra_root}"])
    assert json.loads(capsys.readouterr().out)["total"] == 14

    cli.main(["verify", f"--store_dir={store}"])
    assert json.loads(capsys.readouterr().out)["corrupt"] == 0

    gen = rng_np.standard_normal((3, 8)).astype(np.float32)
    gdir = tmp_path / "gens"
    gdir.mkdir()
    E.save_embeddings(gdir / "embedding.npz", gen, ["g0", "g1", "g2"])
    out = tmp_path / "res.npz"
    cli.main(["query", f"--store_dir={store}", f"--gen_folder={gdir}",
              f"--out_path={out}", "--top_k=2", "--query_batch=2"])
    with np.load(out) as z:
        assert z["scores"].shape == (3, 2)
        assert list(z["gen_images"]) == ["g0", "g1", "g2"]

    # verify on a damaged store: exit 1, read-only (nothing renamed)
    shard = store / "shard_00000.npz"
    shard.write_bytes(b"junk")
    with pytest.raises(SystemExit) as exc:
        cli.main(["verify", f"--store_dir={store}"])
    assert exc.value.code == 1
    assert shard.exists()


def test_trace_report_search_section(tmp_path, rng_np, cpu_devices):
    from tools import trace_report

    tracing.configure(tmp_path / "trace")
    folders = _dump_folders(tmp_path, rng_np, [12], dim=8)
    store, _ = _build_store(tmp_path, folders, shard_rows=4)
    gen = rng_np.standard_normal((2, 8)).astype(np.float32)
    S.search_store(gen, ["g0", "g1"], store, top_k=1, query_batch=2)

    records, errors, meta = trace_report.load_fleet(
        [tmp_path / "trace"], trace_report.load_schema())
    assert errors == []
    summary = trace_report.summarize(records, meta)
    search = summary["search"]
    assert search["ingest"]["shards"] == 3 and search["ingest"]["rows"] == 12
    topk = search["store_topk"]
    assert topk["segment_scans"] >= 1 and topk["rows_scanned"] >= 12
    assert topk["rows_per_s"] > 0
    text = trace_report.render_text(summary, tmp_path / "trace")
    assert "store top-k" in text and "ingest" in text


@pytest.mark.fast
def test_bench_search_schema_validation():
    from tools.bench_search import validate_result

    good = {
        "version": 1,
        "config": {"corpus_rows": 8, "folders": 1, "queries": 2, "top_k": 1,
                   "embed_dim": 4, "query_batch": 2, "repeats": 1,
                   "ingested_rows": 8},
        "brute": {"seconds": 0.1, "rows_per_s": 160},
        "store": {"seconds": 0.01, "rows_per_s": 1600, "build_seconds": 0.1,
                  "ready_seconds": 0.1, "segments": 1, "resident": True},
        "equality": {"scores_equal": True, "keys_equal": True},
        "gate": {"min_speedup": 1.5, "speedup": 10.0, "enforced": True,
                 "passed": True},
    }
    assert validate_result(good) == []
    bad = json.loads(json.dumps(good))
    del bad["equality"]["keys_equal"]
    bad["gate"]["speedup"] = "fast"
    problems = validate_result(bad)
    assert any("keys_equal" in p for p in problems)
    assert any("speedup" in p for p in problems)


@pytest.mark.fast
def test_banked_bench_search_passes_schema_and_gate():
    from tools.bench_search import validate_result

    path = Path(__file__).resolve().parents[1] / "BENCH_SEARCH.json"
    doc = json.loads(path.read_text())
    assert validate_result(doc) == []
    assert doc["equality"] == {"scores_equal": True, "keys_equal": True}
    # the banked run is the enforced full-mode gate
    assert doc["gate"]["enforced"] is True and doc["gate"]["passed"] is True
    assert doc["gate"]["speedup"] >= doc["gate"]["min_speedup"] >= 1.5


# ---------------------------------------------------------------------------
# 5. slow legs: store-backed /check + warm-restart zero compiles
# ---------------------------------------------------------------------------

def _embed_train_images(tmp_path, images, image_size=32):
    from tests.test_risk import _build_index_from_images

    return _build_index_from_images(tmp_path, images, image_size=image_size)


@pytest.mark.slow
def test_check_served_from_store_backed_index(tmp_path, cpu_devices):
    """The acceptance e2e: serve answers /check (and per-response
    copy_risk) from a STORE-BACKED index — a corpus scored through the
    mesh-sharded search/topk engine instead of one resident matmul."""
    from tests.test_risk import _png_b64, _risk_service, _tiny_stack
    from dcr_tpu.obs.copyrisk import CopyRiskIndex

    stack = _tiny_stack()
    plain = _risk_service(stack)
    img_train = plain.submit("a red square", seed=1).future.result(timeout=300)
    img_clean = plain.submit("a blue circle", seed=2).future.result(
        timeout=300)
    plain.stop(timeout=60)

    dump = _embed_train_images(tmp_path, [img_train])
    store = tmp_path / "riskstore"
    writer = EmbeddingStoreWriter.create(store, shard_rows=4)
    writer.add_dump(dump)
    writer.finalize()

    # threshold from a store-backed probe (margins measured, not assumed)
    probe = CopyRiskIndex.load(
        RiskConfig(store_dir=str(store), image_size=32), batch=4)
    assert len(probe) == 1
    sim_hit = probe.score_batch(img_train[None])[0].max_sim
    sim_miss = probe.score_batch(img_clean[None])[0].max_sim
    assert sim_hit > sim_miss + 0.005, (sim_hit, sim_miss)
    threshold = (sim_hit + sim_miss) / 2

    risk = RiskConfig(store_dir=str(store), image_size=32,
                      threshold=threshold)
    svc = _risk_service(stack, risk=risk)
    try:
        assert svc.wait_risk_ready(timeout=300) and svc.risk_status() == "ok"
        req_hit = svc.submit("a red square", seed=1)
        req_miss = svc.submit("a blue circle", seed=2)
        out_hit = req_hit.future.result(timeout=300)
        req_miss.future.result(timeout=300)
        assert req_hit.risk["flagged"] is True
        assert req_hit.risk["top_key"].endswith("gen_0.png")
        assert req_miss.risk["flagged"] is False
        # scoring never perturbs generation, store-backed included
        assert np.array_equal(out_hit, img_train)
        # /check through the service front-end path
        check = svc.check({"image_png_b64": _png_b64(img_train)})
        assert check["flagged"] is True and check["index_size"] == 1
        assert svc.health_doc()["risk"] == "ok"
    finally:
        svc.stop(timeout=60)


@pytest.mark.slow
def test_serve_http_check_answers_from_store(tmp_path, cpu_devices):
    """HTTP leg: a dcr-serve subprocess configured with --risk.store_dir
    (no index_path at all) reaches risk=ok and answers POST /check."""
    import signal

    from tests.test_risk import _png_b64, _risk_service, _tiny_stack
    from tests.test_serve import (_export_tiny_ckpt, _free_port, _get,
                                  _serve_env)

    stack = _tiny_stack()
    plain = _risk_service(stack, max_batch=2)
    img_train = plain.submit("a red square", seed=1).future.result(timeout=300)
    plain.stop(timeout=60)
    dump = _embed_train_images(tmp_path, [img_train])
    store = tmp_path / "riskstore"
    writer = EmbeddingStoreWriter.create(store, shard_rows=4)
    writer.add_dump(dump)
    writer.finalize()

    ckpt = _export_tiny_ckpt(tmp_path)
    env, repo = _serve_env()
    port = _free_port()
    argv = [sys.executable, "-m", "dcr_tpu.cli.serve",
            f"--model_path={ckpt}", f"--port={port}",
            "--resolution=16", "--num_inference_steps=2", "--sampler=ddim",
            "--max_batch=2", "--max_wait_ms=100", "--queue_depth=16",
            "--request_timeout_s=300", "--seed=0",
            f"--logdir={tmp_path / 'log'}",
            f"--risk.store_dir={store}", "--risk.image_size=32",
            "--risk.threshold=0.999"]
    proc = subprocess.Popen(argv, env=env, cwd=repo, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.monotonic() + 300
        while True:
            try:
                _, health = _get(port, "/healthz", timeout=2)
                if health["status"] == "ok" and health["risk"] == "ok":
                    break
            except OSError:
                pass
            if proc.poll() is not None or time.monotonic() > deadline:
                out = proc.stdout.read() if proc.stdout else ""
                raise AssertionError(
                    f"server not risk-ready (rc={proc.poll()}): {out[-3000:]}")
            time.sleep(0.5)
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/check",
            data=json.dumps({"image_png_b64": _png_b64(img_train)}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300) as resp:
            doc = json.loads(resp.read())
        assert resp.status == 200
        assert doc["flagged"] is True and doc["index_size"] == 1
        assert doc["max_sim"] >= 0.999
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            assert proc.wait(timeout=120) == 83    # EXIT_PREEMPTED drain


@pytest.mark.slow
def test_query_warm_restart_zero_compiles(tmp_path, rng_np, cpu_devices):
    """A second `dcr-search query` incarnation against the same warm cache
    answers with ZERO XLA compiles (trace_report --max-compiles 0) and
    bit-identical results."""
    from tests.test_serve import _serve_env
    from tools import trace_report

    folders_root = tmp_path / "corpus"
    folders_root.mkdir()
    _dump_folders(folders_root, rng_np, [24, 17], dim=8, prefix="chunk")
    store = tmp_path / "store"
    ingest_dumps(EmbeddingStoreWriter.create(store, shard_rows=8),
                 [folders_root])
    gen = rng_np.standard_normal((5, 8)).astype(np.float32)
    gdir = tmp_path / "gens"
    gdir.mkdir()
    E.save_embeddings(gdir / "embedding.npz", gen,
                      [f"g{i}" for i in range(5)])

    env, repo = _serve_env()
    # no XLA persistent cache in the subprocesses: with it active this
    # jaxlib emits unserializable executables, every warm entry degrades
    # to the export tier, and incarnation 2's compile-on-load would
    # (correctly) fail the --max-compiles 0 gate (same discipline as the
    # test_risk / test_warmcache restart e2e)
    for k in list(env):
        if k.startswith("JAX_COMPILATION") or k.startswith("JAX_PERSISTENT"):
            env.pop(k)
    warm = tmp_path / "warm"

    def run_query(logdir, out):
        argv = [sys.executable, "-m", "dcr_tpu.cli.search", "query",
                f"--store_dir={store}", f"--gen_folder={gdir}",
                f"--out_path={out}", "--top_k=2", "--query_batch=4",
                f"--warm_dir={warm}", f"--logdir={logdir}"]
        proc = subprocess.run(argv, env=env, cwd=repo, capture_output=True,
                              text=True, timeout=600)
        assert proc.returncode == 0, proc.stdout + proc.stderr

    run_query(tmp_path / "log1", tmp_path / "res1.npz")
    run_query(tmp_path / "log2", tmp_path / "res2.npz")
    with np.load(tmp_path / "res1.npz") as z1, \
            np.load(tmp_path / "res2.npz") as z2:
        np.testing.assert_array_equal(z1["scores"], z2["scores"])
        assert (z1["keys"] == z2["keys"]).all()
    # incarnation 1 compiled (and populated the cache); incarnation 2 warm
    records, _, _ = trace_report.load_fleet(
        [tmp_path / "log1"], trace_report.load_schema())
    assert any(r["name"] == "warmcache/compile" for r in records)
    assert trace_report.main([str(tmp_path / "log2"),
                              "--max-compiles", "0"]) == 0

"""Weight-converter tests.

- CLIP text: REAL golden parity against transformers.CLIPTextModel (torch cpu)
  — converted weights must reproduce activations (SURVEY.md §4 model-parity).
- Other backbones: structural round-trip — synthesize a torch-style state dict
  with reference naming/shapes from our randomly-initialized param tree, convert,
  and require exact tree/shape agreement plus numeric equality of leaves.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcr_tpu.models import convert as CV


def _leaves(tree, path=""):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _leaves(v, f"{path}/{k}" if path else k)
    else:
        yield path, np.asarray(tree)


def _inv_leaf(path: str, value: np.ndarray, name_map) -> tuple[str, np.ndarray]:
    """Our leaf -> (torch name, torch-shaped array)."""
    parts = path.split("/")
    leaf = parts[-1]
    prefix = name_map("/".join(parts[:-1]))
    if leaf == "kernel":
        if value.ndim == 4:
            return f"{prefix}.weight", np.transpose(value, (3, 2, 0, 1))
        return f"{prefix}.weight", np.transpose(value, (1, 0))
    if leaf == "scale":
        return f"{prefix}.weight", value
    if leaf == "mean":
        return f"{prefix}.running_mean", value
    if leaf == "var":
        return f"{prefix}.running_var", value
    return f"{prefix}.{leaf}", value


def test_resnet50_sscd_structural_roundtrip():
    from dcr_tpu.models.resnet import init_sscd

    model, params = init_sscd(jax.random.key(0), image_size=64)

    def name_map(p: str) -> str:
        p = re.sub(r"^backbone/", "backbone.", p)
        p = re.sub(r"layer(\d)_(\d+)", r"layer\1.\2", p)
        p = p.replace("downsample_conv", "downsample.0")
        p = p.replace("downsample_bn", "downsample.1")
        return p.replace("/", ".")

    sd = dict(_inv_leaf(path, v, name_map) for path, v in _leaves(params))
    converted = CV.convert_sscd(sd)
    problems = CV.check_converted(params, converted)
    assert not problems, problems[:10]
    for (p1, a), (p2, b) in zip(sorted(_leaves(params)), sorted(_leaves(converted))):
        assert p1 == p2
        np.testing.assert_array_equal(a, b, err_msg=p1)
    # converted weights must drive the model identically
    x = jax.random.normal(jax.random.key(1), (1, 64, 64, 3))
    out1 = model.apply({"params": params}, x)
    out2 = model.apply({"params": jax.tree.map(jnp.asarray, converted)}, x)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=1e-6)


def test_inception_structural_roundtrip():
    from dcr_tpu.models.inception import init_inception

    model, params = init_inception(jax.random.key(0), image_size=96)

    def name_map(p: str) -> str:
        return p.replace("/", ".").replace(".conv", ".conv").replace(".bn", ".bn")

    sd = {}
    for path, v in _leaves(params):
        # path like Mixed_5b/branch1x1/conv/kernel -> Mixed_5b.branch1x1.conv.weight
        sd.update([_inv_leaf(path, v, lambda q: q.replace("/", "."))])
    converted = CV.convert_inception_fid(sd)
    assert not CV.check_converted(params, converted)


def test_vgg16_structural_roundtrip_with_chw_flatten():
    """fc1 consumes a flattened feature map: torch orders it CHW, we order HWC.
    The converter must reorder — verified by an exact numeric round-trip."""
    from dcr_tpu.models.vgg import init_vgg

    model, params = init_vgg(jax.random.key(0))
    tv_conv_indices = [0, 2, 5, 7, 10, 12, 14, 17, 19, 21, 24, 26, 28]

    def name_map(p: str) -> str:
        m = re.match(r"conv_(\d+)", p)
        if m:
            return f"features.{tv_conv_indices[int(m.group(1))]}"
        return {"fc1": "classifier.0", "fc2": "classifier.3"}[p]

    sd = {}
    for path, v in _leaves(params):
        if path == "fc1/kernel":
            # our [25088(HWC), 4096] -> torch [4096, 25088(CHW)]
            w = v.T.reshape(4096, 7, 7, 512).transpose(0, 3, 1, 2).reshape(4096, -1)
            sd["classifier.0.weight"] = w
        else:
            sd.update([_inv_leaf(path, v, name_map)])
    converted = CV.convert_vgg16(sd)
    assert not CV.check_converted(params, converted)
    for (p1, a), (p2, b) in zip(sorted(_leaves(params)), sorted(_leaves(converted))):
        np.testing.assert_array_equal(a, b, err_msg=p1)


def test_dino_vit_structural_roundtrip():
    from dcr_tpu.models.vit import vit_tiny

    model = vit_tiny(16)
    params = model.init(jax.random.key(0), jnp.zeros((1, 224, 224, 3)))["params"]

    def name_map(p: str) -> str:
        p = re.sub(r"blocks_(\d+)", r"blocks.\1", p)
        p = p.replace("patch_embed/proj", "patch_embed.proj")
        p = re.sub(r"blocks\.(\d+)/qkv", r"blocks.\1.attn.qkv", p)
        p = re.sub(r"blocks\.(\d+)/proj", r"blocks.\1.attn.proj", p)
        p = re.sub(r"blocks\.(\d+)/fc(\d)", r"blocks.\1.mlp.fc\2", p)
        return p.replace("/", ".")

    sd = {}
    for path, v in _leaves(params):
        if path == "cls_token":
            sd["cls_token"] = v
        elif path == "pos_embed":
            sd["pos_embed"] = v
        else:
            sd.update([_inv_leaf(path, v, name_map)])
    converted = CV.convert_dino_vit(sd, depth=12)
    assert not CV.check_converted(params, converted)


@pytest.mark.parametrize("act", ["gelu", "quick_gelu"])
def test_clip_text_golden_parity_with_transformers(act):
    """Verified against the real torch implementation, at both activations:
    "gelu" (SD-2.x OpenCLIP ViT-H tower) and "quick_gelu" (OpenAI CLIP-B/L)."""
    torch = pytest.importorskip("torch")
    from transformers import CLIPTextConfig, CLIPTextModel as HFCLIPText

    hf_cfg = CLIPTextConfig(
        vocab_size=99, hidden_size=32, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=2,
        max_position_embeddings=16, hidden_act=act)
    torch.manual_seed(0)
    hf_model = HFCLIPText(hf_cfg).eval()
    sd = CV.torch_state_dict_to_numpy(hf_model)

    from dcr_tpu.core.config import ModelConfig
    from dcr_tpu.models.clip_text import CLIPTextModel

    cfg = ModelConfig(text_vocab_size=99, text_hidden_size=32, text_layers=2,
                      text_heads=2, text_max_length=16, text_act=act)
    ours = CLIPTextModel(cfg)
    init_params = ours.init(jax.random.key(0),
                            jnp.zeros((1, 16), jnp.int32))["params"]
    converted = CV.convert_clip_text(sd, layers=2, heads=2)
    problems = CV.check_converted(init_params, converted)
    assert not problems, problems[:10]

    ids = np.array([[5, 7, 9, 11, 2, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0]], np.int64)
    with torch.no_grad():
        hf_out = hf_model(input_ids=torch.from_numpy(ids)).last_hidden_state.numpy()
    our_out = ours.apply({"params": jax.tree.map(jnp.asarray, converted)},
                         jnp.asarray(ids, jnp.int32)).last_hidden_state
    np.testing.assert_allclose(np.asarray(our_out), hf_out, atol=2e-5, rtol=1e-4)


def test_unet_and_vae_structural_roundtrip():
    """Synthesize diffusers-style state dicts for a tiny config and require the
    converted tree to match our init tree exactly."""
    from dcr_tpu.core.config import ModelConfig
    from dcr_tpu.models.unet2d import init_unet
    from dcr_tpu.models.vae import init_vae

    cfg = ModelConfig.tiny()
    unet, uparams = init_unet(cfg, jax.random.key(0))

    def unet_name_map(p: str) -> str:
        n = len(cfg.block_out_channels)
        p = re.sub(r"^down_(\d+)_res_(\d+)", r"down_blocks.\1.resnets.\2", p)
        p = re.sub(r"^down_(\d+)_attn_(\d+)", r"down_blocks.\1.attentions.\2", p)
        p = re.sub(r"^down_(\d+)_downsample", r"down_blocks.\1.downsamplers.0", p)
        p = re.sub(r"^up_(\d+)_res_(\d+)",
                   lambda m: f"up_blocks.{n - 1 - int(m.group(1))}.resnets.{m.group(2)}", p)
        p = re.sub(r"^up_(\d+)_attn_(\d+)",
                   lambda m: f"up_blocks.{n - 1 - int(m.group(1))}.attentions.{m.group(2)}", p)
        p = re.sub(r"^up_(\d+)_upsample",
                   lambda m: f"up_blocks.{n - 1 - int(m.group(1))}.upsamplers.0", p)
        p = re.sub(r"^mid_res_(\d)", r"mid_block.resnets.\1", p)
        p = re.sub(r"^mid_attn", r"mid_block.attentions.0", p)
        p = re.sub(r"blocks_(\d+)", r"transformer_blocks.\1", p)
        p = re.sub(r"/(attn\d)/to_out", r"/\1/to_out.0", p)
        p = p.replace("/ff/proj_in", "/ff/net.0.proj")
        p = p.replace("/ff/proj_out", "/ff/net.2")
        p = p.replace("/GroupNorm_0", "")
        return p.replace("/", ".")

    sd = dict(_inv_leaf(path, v, unet_name_map) for path, v in _leaves(uparams))
    converted = CV.convert_unet(sd, block_out_channels=cfg.block_out_channels,
                                layers_per_block=cfg.layers_per_block,
                                transformer_layers=cfg.transformer_layers)
    problems = CV.check_converted(uparams, converted)
    assert not problems, problems[:10]

    vae, vparams = init_vae(cfg, jax.random.key(1))

    def vae_name_map(p: str) -> str:
        p = re.sub(r"^encoder/down_(\d+)_res_(\d+)",
                   r"encoder.down_blocks.\1.resnets.\2", p)
        p = re.sub(r"^encoder/down_(\d+)_downsample",
                   r"encoder.down_blocks.\1.downsamplers.0", p)
        p = re.sub(r"^(encoder|decoder)/mid_res_(\d)", r"\1.mid_block.resnets.\2", p)
        p = re.sub(r"^(encoder|decoder)/mid_attn", r"\1.mid_block.attentions.0", p)
        p = re.sub(r"^decoder/up_(\d+)_res_(\d+)",
                   r"decoder.up_blocks.\1.resnets.\2", p)
        p = re.sub(r"^decoder/up_(\d+)_upsample",
                   r"decoder.up_blocks.\1.upsamplers.0", p)
        p = p.replace("encoder/quant_conv", "quant_conv")
        p = p.replace("decoder/post_quant_conv", "post_quant_conv")
        p = p.replace("/to_out", "/to_out.0")
        p = p.replace("/GroupNorm_0", "")
        return p.replace("/", ".")

    sd_vae = dict(_inv_leaf(path, v, vae_name_map) for path, v in _leaves(vparams))
    converted_vae = CV.convert_vae(sd_vae,
                                   block_out_channels=cfg.vae_block_out_channels,
                                   layers_per_block=cfg.vae_layers_per_block)
    problems = CV.check_converted(vparams, converted_vae)
    assert not problems, problems[:10]


def test_conv_bn_numeric_parity_with_torch():
    """Conversion transposes verified against real torch modules (not just our
    own inverse): conv OIHW->HWIO and BN running stats must reproduce torch's
    outputs on the same input."""
    torch = pytest.importorskip("torch")
    import flax.linen as nn

    from dcr_tpu.models.resnet import FrozenBatchNorm

    torch.manual_seed(0)
    conv = torch.nn.Conv2d(3, 8, 3, stride=2, padding=1, bias=True).eval()
    bn = torch.nn.BatchNorm2d(8).eval()
    bn.running_mean.uniform_(-1, 1)
    bn.running_var.uniform_(0.5, 2.0)
    bn.weight.data.uniform_(0.5, 1.5)
    bn.bias.data.uniform_(-1, 1)

    x = torch.randn(2, 3, 16, 16)
    with torch.no_grad():
        ref = bn(conv(x)).numpy().transpose(0, 2, 3, 1)  # NCHW -> NHWC

    class Mini(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Conv(8, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)),
                        name="conv")(x)
            return FrozenBatchNorm(name="bn")(x)

    params = {
        "conv": {"kernel": CV.conv_kernel(conv.weight.detach().numpy()),
                 "bias": conv.bias.detach().numpy()},
        "bn": {"scale": bn.weight.detach().numpy(),
               "bias": bn.bias.detach().numpy(),
               "mean": bn.running_mean.numpy(),
               "var": bn.running_var.numpy()},
    }
    x_nhwc = jnp.asarray(x.numpy().transpose(0, 2, 3, 1))
    out = Mini().apply({"params": jax.tree.map(jnp.asarray, params)}, x_nhwc)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)


def test_linear_numeric_parity_with_torch():
    torch = pytest.importorskip("torch")
    torch.manual_seed(1)
    lin = torch.nn.Linear(6, 4).eval()
    x = torch.randn(3, 6)
    with torch.no_grad():
        ref = lin(x).numpy()
    out = x.numpy() @ CV.linear_kernel(lin.weight.detach().numpy()) + lin.bias.detach().numpy()
    np.testing.assert_allclose(out, ref, atol=1e-6)


def test_vae_downsample_asymmetric_pad_parity_with_torch():
    """The VAE encoder downsampler must reproduce diffusers' AutoencoderKL
    behavior: F.pad(x, (0,1,0,1)) then Conv2d(stride=2, padding=0). Verified
    against real torch ops (ADVICE round-1: symmetric padding silently shifts
    encoder activations under pretrained weights)."""
    torch = pytest.importorskip("torch")

    from dcr_tpu.models.layers import Downsample2D

    torch.manual_seed(2)
    conv = torch.nn.Conv2d(3, 5, 3, stride=2, padding=0, bias=True).eval()
    x = torch.randn(2, 3, 16, 16)
    with torch.no_grad():
        ref = conv(torch.nn.functional.pad(x, (0, 1, 0, 1)))
    ref = ref.numpy().transpose(0, 2, 3, 1)

    params = {"conv": {"kernel": CV.conv_kernel(conv.weight.detach().numpy()),
                       "bias": conv.bias.detach().numpy()}}
    out = Downsample2D(5, asymmetric_pad=True).apply(
        {"params": jax.tree.map(jnp.asarray, params)},
        jnp.asarray(x.numpy().transpose(0, 2, 3, 1)))
    assert out.shape == ref.shape
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)

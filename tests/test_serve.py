"""dcr-serve subsystem tests.

Fast tier: pure-logic units for the batching policy, admission queue, LRU
embedding cache, latency tracker, tokenizer fingerprints, and the modelstyle
fallback warning — no models, no compiles.

Slow tier: the properties that need a real (tiny) compiled stack —
batch-composition independence of per-request PRNG keys, cache semantics
through the worker — plus the HTTP end-to-end: a real `dcr-serve` subprocess
answering concurrent requests from dynamically formed batches, then SIGTERM
draining in-flight work and exiting with EXIT_PREEMPTED (83).
"""

import json
import threading
import time

import numpy as np
import pytest

from dcr_tpu.serve.batcher import Batcher, should_flush
from dcr_tpu.serve.cache import EmbeddingCache, embedding_key, mitigation_tag
from dcr_tpu.serve.queue import (DrainingError, GenBucket, QueueFullError,
                                 Request, RequestQueue)


def _bucket(**kw) -> GenBucket:
    d = dict(resolution=16, steps=2, guidance=7.5, sampler="ddim",
             rand_noise_lam=0.0)
    d.update(kw)
    return GenBucket(**d)


def _req(prompt="p", seed=0, **bucket_kw) -> Request:
    return Request(prompt=prompt, seed=seed, bucket=_bucket(**bucket_kw))


# ---------------------------------------------------------------------------
# batching policy
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_should_flush_policy():
    # full group flushes regardless of age
    assert should_flush(4, 4, 0.0, 1.0)
    assert should_flush(5, 4, 0.0, 1.0)
    # partial group holds until the deadline...
    assert not should_flush(2, 4, 0.01, 1.0)
    # ...then flushes
    assert should_flush(2, 4, 1.0, 1.0)
    # empty never flushes, even during drain
    assert not should_flush(0, 4, 99.0, 1.0, draining=True)
    # drain flushes partials immediately
    assert should_flush(1, 4, 0.0, 1.0, draining=True)


@pytest.mark.fast
def test_batcher_flushes_full_batch_immediately():
    q = RequestQueue(maxsize=16)
    for i in range(4):
        q.submit(_req(seed=i))
    b = Batcher(max_batch=4, max_wait_s=60.0)     # deadline far away
    t0 = time.monotonic()
    batch = b.next_batch(q, stop=threading.Event())
    assert len(batch) == 4
    assert time.monotonic() - t0 < 5.0            # did not wait for the deadline
    assert q.empty()


@pytest.mark.fast
def test_batcher_max_wait_flushes_partial_batch():
    q = RequestQueue(maxsize=16)
    q.submit(_req(seed=1))
    q.submit(_req(seed=2))
    b = Batcher(max_batch=8, max_wait_s=0.08)
    t0 = time.monotonic()
    batch = b.next_batch(q, stop=threading.Event())
    elapsed = time.monotonic() - t0
    assert [r.seed for r in batch] == [1, 2]      # FIFO, partial
    assert elapsed >= 0.05                        # held for (about) the deadline


@pytest.mark.fast
def test_batcher_groups_by_bucket():
    """Requests from different buckets never share a batch; the leftover
    bucket group is preserved in FIFO order for the next pop."""
    q = RequestQueue(maxsize=16)
    q.submit(_req(seed=1, steps=2))
    q.submit(_req(seed=2, steps=4))               # different compiled program
    q.submit(_req(seed=3, steps=2))
    b = Batcher(max_batch=8, max_wait_s=0.02)
    first = b.next_batch(q, stop=threading.Event())
    assert [r.seed for r in first] == [1, 3]      # head bucket group only
    second = b.next_batch(q, stop=threading.Event())
    assert [r.seed for r in second] == [2]
    assert q.empty()


@pytest.mark.fast
def test_batcher_drain_flushes_without_deadline():
    q = RequestQueue(maxsize=16)
    q.submit(_req(seed=1))
    stop = threading.Event()
    stop.set()                                    # draining
    b = Batcher(max_batch=8, max_wait_s=60.0)
    t0 = time.monotonic()
    batch = b.next_batch(q, stop=stop)
    assert len(batch) == 1
    assert time.monotonic() - t0 < 5.0
    # queue empty + stop set -> the loop's termination signal
    assert b.next_batch(q, stop=stop) is None


# ---------------------------------------------------------------------------
# admission queue
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_queue_overload_typed_reject():
    q = RequestQueue(maxsize=2)
    q.submit(_req(seed=1))
    q.submit(_req(seed=2))
    with pytest.raises(QueueFullError):
        q.submit(_req(seed=3))
    assert q.depth() == 2                         # rejected request not queued


@pytest.mark.fast
def test_queue_draining_typed_reject():
    q = RequestQueue(maxsize=4)
    q.submit(_req(seed=1))
    q.close()
    with pytest.raises(DrainingError):
        q.submit(_req(seed=2))
    # pops continue after close — that is the drain contract
    assert [r.seed for r in q.take_group(4)] == [1]


# ---------------------------------------------------------------------------
# request validation (client-controlled params must never reach jit)
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_validate_bucket_rejects_bad_params():
    from dcr_tpu.serve.queue import InvalidRequestError
    from dcr_tpu.serve.worker import validate_bucket

    ok = _bucket()
    validate_bucket(ok, vae_scale=2)                  # tiny model: scale 2
    for bad in [_bucket(sampler="foo"),
                _bucket(steps=0), _bucket(steps=10_001),
                _bucket(resolution=0), _bucket(resolution=17),  # % 2 != 0
                _bucket(resolution=1 << 20),
                _bucket(guidance=-1.0), _bucket(guidance=1e6),
                _bucket(rand_noise_lam=-0.1)]:
        with pytest.raises(InvalidRequestError):
            validate_bucket(bad, vae_scale=2)
    # SD-scale: resolution must be a multiple of the VAE factor
    with pytest.raises(InvalidRequestError):
        validate_bucket(_bucket(resolution=260), vae_scale=8)
    validate_bucket(_bucket(resolution=256), vae_scale=8)


# ---------------------------------------------------------------------------
# embedding cache
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_cache_lru_eviction_and_recency():
    c = EmbeddingCache(capacity=2)
    k1, k2, k3 = (("fp", f"p{i}", "lam=0") for i in range(3))
    c.put(k1, np.ones(3)); c.put(k2, np.ones(3) * 2)
    assert c.get(k1) is not None                  # refreshes k1's recency
    c.put(k3, np.ones(3) * 3)                     # evicts k2 (LRU), not k1
    assert k2 not in c and k1 in c and k3 in c
    assert len(c) == 2


@pytest.mark.fast
def test_cache_key_binds_mitigation_and_tokenizer():
    b0 = _bucket(rand_noise_lam=0.0)
    b1 = _bucket(rand_noise_lam=0.1)
    assert mitigation_tag(b0) != mitigation_tag(b1)
    k_clean = embedding_key("fp", "a dog", mitigation_tag(b0))
    k_mit = embedding_key("fp", "a dog", mitigation_tag(b1))
    k_other_tok = embedding_key("fp2", "a dog", mitigation_tag(b0))
    assert len({k_clean, k_mit, k_other_tok}) == 3
    c = EmbeddingCache(capacity=8)
    c.put(k_clean, np.zeros(2))
    assert c.get(k_mit) is None                   # mitigation params miss
    assert c.get(k_other_tok) is None             # tokenizer swap misses
    assert c.stats() == {"hits": 0, "misses": 2, "size": 1, "capacity": 8,
                         "hit_rate": 0.0}


@pytest.mark.fast
def test_cache_capacity_zero_disables():
    c = EmbeddingCache(capacity=0)
    c.put(("a",), np.zeros(1))
    assert c.get(("a",)) is None and len(c) == 0


@pytest.mark.fast
def test_tokenizer_fingerprint():
    from dcr_tpu.data.tokenizer import HashTokenizer

    a = HashTokenizer(vocab_size=100, model_max_length=16)
    b = HashTokenizer(vocab_size=100, model_max_length=16)
    c = HashTokenizer(vocab_size=200, model_max_length=16)
    assert a.fingerprint() == b.fingerprint()     # same mapping, same id
    assert a.fingerprint() != c.fingerprint()     # vocab change changes id


# ---------------------------------------------------------------------------
# telemetry
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_latency_tracker_percentiles():
    from dcr_tpu.core.metrics import LatencyTracker

    t = LatencyTracker(window=100)
    assert t.percentiles() == {"p50": 0.0, "p99": 0.0}
    for v in range(1, 101):
        t.observe(v / 1000.0)
    p = t.percentiles((50, 99))
    assert 0.045 <= p["p50"] <= 0.055
    assert p["p99"] >= 0.09
    # window bounds memory: old observations fall out
    for _ in range(200):
        t.observe(1.0)
    assert t.percentiles()["p50"] == 1.0


@pytest.mark.fast
def test_serve_metrics_occupancy():
    from dcr_tpu.serve.worker import ServeMetrics

    m = ServeMetrics()
    m.note_batch(4, 4, ok=True)
    m.note_batch(1, 4, ok=True)
    s = m.snapshot()
    assert s["batch_occupancy_max"] == 1.0
    assert s["batch_occupancy_last"] == 0.25
    assert s["batch_occupancy_avg"] == pytest.approx(0.625)
    assert s["completed_total"] == 5


# ---------------------------------------------------------------------------
# modelstyle fallback warning (satellite: DCR006 no-silent-swallow)
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_infer_modelstyle_warns_on_missing_key(tmp_path, caplog):
    from dcr_tpu.cli.sample import infer_modelstyle

    (tmp_path / "config.json").write_text(json.dumps({"data": {}}))
    with caplog.at_level("WARNING", logger="dcr_tpu"):
        style = infer_modelstyle(str(tmp_path))
    assert style == "nolevel"
    [rec] = [r for r in caplog.records if "modelstyle_fallback" in r.getMessage()]
    msg = rec.getMessage()
    assert str(tmp_path / "config.json") in msg   # names the path
    assert "data.class_prompt" in msg             # names the missing key


@pytest.mark.fast
def test_infer_modelstyle_no_warning_when_key_present(tmp_path, caplog):
    from dcr_tpu.cli.sample import infer_modelstyle

    (tmp_path / "config.json").write_text(
        json.dumps({"data": {"class_prompt": "classlevel"}}))
    with caplog.at_level("WARNING", logger="dcr_tpu"):
        assert infer_modelstyle(str(tmp_path)) == "classlevel"
    assert not [r for r in caplog.records
                if "modelstyle_fallback" in r.getMessage()]


# ---------------------------------------------------------------------------
# compiled-stack properties (slow: build + compile tiny models)
# ---------------------------------------------------------------------------

def _tiny_stack():
    import jax

    from dcr_tpu.core.config import MeshConfig, ModelConfig, TrainConfig
    from dcr_tpu.data.tokenizer import HashTokenizer
    from dcr_tpu.diffusion.trainer import build_models
    from dcr_tpu.parallel import mesh as pmesh
    from dcr_tpu.sampling.pipeline import GenerationStack

    tiny = ModelConfig.tiny()
    tcfg = TrainConfig(mixed_precision="no")
    tcfg.model = tiny
    models, params = build_models(tcfg, jax.random.key(0))
    tok = HashTokenizer(vocab_size=tiny.text_vocab_size,
                        model_max_length=tiny.text_max_length)
    return GenerationStack(models, params, tiny,
                           tok, pmesh.make_mesh(MeshConfig()))


def _service(stack, **cfg_kw):
    from dcr_tpu.core.config import ServeConfig
    from dcr_tpu.serve.worker import GenerationService

    kw = dict(resolution=16, num_inference_steps=2, sampler="ddim",
              max_batch=2, max_wait_ms=30.0, queue_depth=16, seed=0)
    kw.update(cfg_kw)
    return GenerationService(ServeConfig(**kw), stack)


@pytest.mark.slow
def test_per_request_keys_independent_of_batch(cpu_devices):
    """The tentpole determinism contract: the same request produces the
    bit-identical image whether it runs alone (padded batch) or alongside
    other requests — per-request fold_in keys + one fixed compiled shape.
    rand_noise_lam > 0 so the vmapped per-request mitigation noise is
    exercised too (ddpm then covers per-step ancestral noise)."""
    stack = _tiny_stack()
    svc = _service(stack, rand_noise_lam=0.1)
    b = svc.default_bucket()

    alone = svc.execute([Request(prompt="a red square", seed=7, bucket=b)])
    mixed = svc.execute([Request(prompt="a red square", seed=7, bucket=b),
                         Request(prompt="a blue circle", seed=9, bucket=b)])
    assert np.array_equal(alone[0], mixed[0])
    # and the neighbors really are different images (keys independent)
    assert not np.array_equal(mixed[0], mixed[1])
    # same prompt, different seed -> different image
    reseeded = svc.execute([Request(prompt="a red square", seed=8, bucket=b)])
    assert not np.array_equal(alone[0], reseeded[0])


@pytest.mark.slow
def test_ddpm_per_request_ancestral_noise_independent(cpu_devices):
    """The stochastic sampler's per-step noise is also per-request (vmapped
    fold_in), so ancestral sampling keeps batch-composition independence."""
    stack = _tiny_stack()
    svc = _service(stack, sampler="ddpm")
    b = svc.default_bucket()
    alone = svc.execute([Request(prompt="x", seed=3, bucket=b)])
    mixed = svc.execute([Request(prompt="x", seed=3, bucket=b),
                         Request(prompt="y", seed=4, bucket=b)])
    assert np.array_equal(alone[0], mixed[0])


@pytest.mark.slow
def test_worker_cache_and_batching_end_to_end(cpu_devices):
    """Through the real worker thread: repeated prompts hit the embedding
    cache, batches form dynamically, metrics/status report it all."""
    stack = _tiny_stack()
    svc = _service(stack, max_batch=4, max_wait_ms=150.0)
    svc.start()
    try:
        reqs = [svc.submit("a red square", seed=i) for i in range(4)]
        imgs = [r.future.result(timeout=300) for r in reqs]
        assert all(i.shape == (16, 16, 3) for i in imgs)
        # 4 identical prompts: one text-tower run, three cache hits
        assert svc.cache.stats()["hits"] >= 3
        assert svc.cache.stats()["misses"] <= 2   # prompt + possible uncond
        status = svc.status()
        assert status["batch_occupancy_max"] > 0.25   # requests shared batches
        assert status["completed_total"] == 4
        assert status["latency_ms"]["p99"] > 0
        # per-request keys: same prompt+seed later reproduces bit-exactly,
        # now entirely from cache
        again = svc.submit("a red square", seed=2).future.result(timeout=300)
        assert np.array_equal(again, imgs[2])
        # resident-program budget: a second distinct bucket is rejected with
        # a typed error BEFORE any compile (max_compiled_buckets=1 here)
        from dcr_tpu.serve.queue import BucketLimitError, InvalidRequestError

        svc.cfg.max_compiled_buckets = 1
        other = svc.default_bucket()._replace(steps=3)
        with pytest.raises(BucketLimitError):
            svc.submit("x", bucket=other)
        # invalid bucket params are typed client errors, not compile crashes
        with pytest.raises(InvalidRequestError):
            svc.submit("x", bucket=svc.default_bucket()._replace(sampler="foo"))
        assert svc.status()["rejected_bucket_limit"] == 1
        assert svc.status()["rejected_invalid"] == 1
    finally:
        assert svc.stop(timeout=60)


# ---------------------------------------------------------------------------
# HTTP end-to-end: real dcr-serve subprocess (slow; own CI job)
# ---------------------------------------------------------------------------

def _export_tiny_ckpt(tmp_path):
    import jax

    from dcr_tpu.core.checkpoint import export_hf_layout
    from dcr_tpu.core.config import (DataConfig, ModelConfig, TrainConfig,
                                     to_dict)
    from dcr_tpu.diffusion.trainer import build_models

    cfg = TrainConfig()
    cfg.model = ModelConfig.tiny()
    cfg.data = DataConfig(class_prompt="nolevel")
    models, params = build_models(cfg, jax.random.key(0))
    export_hf_layout(
        tmp_path / "checkpoint", unet=params["unet"], vae=params["vae"],
        text_encoder=params["text"],
        scheduler_config={"num_train_timesteps": 1000,
                          "beta_schedule": "scaled_linear",
                          "beta_start": 0.00085, "beta_end": 0.012,
                          "prediction_type": "epsilon"},
        model_config=to_dict(cfg.model))
    return tmp_path / "checkpoint"


def _serve_env():
    import os
    from pathlib import Path

    repo = Path(__file__).parent.parent
    cache = os.environ.get("DCR_TEST_CACHE_DIR") or str(
        repo / "tests" / ".jax_cache_cpu")
    env = dict(os.environ)
    env.update(
        DCR_TPU_PLATFORM="cpu",
        PYTHONPATH=str(repo) + os.pathsep + env.get("PYTHONPATH", ""),
        JAX_THREEFRY_PARTITIONABLE="1",
        JAX_COMPILATION_CACHE_DIR=cache,
        JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="1.0",
        JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES="0",
    )
    return env, repo


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _post_generate(port, prompt, seed, timeout=300):
    import urllib.request

    body = json.dumps({"prompt": prompt, "seed": seed}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(port, path, timeout=10):
    import urllib.request

    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


@pytest.mark.slow
def test_serve_e2e_http_batching_cache_and_sigterm_drain(tmp_path, cpu_devices):
    """Acceptance e2e: concurrent HTTP requests are answered from dynamically
    formed batches (occupancy > 1 request), repeated prompts hit the embedding
    cache, and SIGTERM drains in-flight work then exits EXIT_PREEMPTED."""
    import base64
    import io
    import signal
    import subprocess
    import sys
    from concurrent.futures import ThreadPoolExecutor

    from PIL import Image

    from dcr_tpu.core.coordination import EXIT_PREEMPTED

    ckpt = _export_tiny_ckpt(tmp_path)
    env, repo = _serve_env()
    port = _free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "dcr_tpu.cli.serve",
         f"--model_path={ckpt}", f"--port={port}",
         "--resolution=16", "--num_inference_steps=2", "--sampler=ddim",
         "--max_batch=4", "--max_wait_ms=300", "--queue_depth=32",
         "--request_timeout_s=300", "--seed=0"],
        env=env, cwd=repo, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        # wait for the port (jax import + stack load; no compile needed yet)
        deadline = time.monotonic() + 240
        while True:
            try:
                status, health = _get(port, "/healthz", timeout=2)
                assert status == 200 and health["status"] == "ok"
                break
            except (AssertionError, OSError):
                if proc.poll() is not None or time.monotonic() > deadline:
                    out = proc.stdout.read() if proc.stdout else ""
                    raise AssertionError(
                        f"server did not come up (rc={proc.poll()}): {out[-3000:]}")
                time.sleep(0.5)

        # wave 1: 8 concurrent requests, 2 unique prompts -> batches + cache
        prompts = ["a red square", "a blue circle"] * 4
        with ThreadPoolExecutor(max_workers=8) as ex:
            results = list(ex.map(
                lambda a: _post_generate(port, a[1], seed=a[0]),
                enumerate(prompts)))
        assert all(status == 200 for status, _ in results)
        png = base64.b64decode(results[0][1]["image_png_b64"])
        img = Image.open(io.BytesIO(png))
        assert img.size == (16, 16)

        _, metrics = _get(port, "/metrics")
        # dynamic batching proof: some batch held more than one request
        assert metrics["batch_occupancy_max"] * 4 > 1, metrics
        assert metrics["cache"]["hits"] >= 1, metrics
        assert metrics["completed_total"] == 8
        assert metrics["latency_ms"]["p99"] > 0

        # invalid bucket params over HTTP: typed 400, no compile, port alive
        import urllib.error
        import urllib.request

        body = json.dumps({"prompt": "x", "sampler": "bogus"}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 400

        # wave 2: requests in flight when SIGTERM lands must still complete
        with ThreadPoolExecutor(max_workers=4) as ex:
            futs = [ex.submit(_post_generate, port, "a green dot", 100 + i)
                    for i in range(4)]
            time.sleep(0.4)                       # let them reach the queue
            proc.send_signal(signal.SIGTERM)
            drained = [f.result(timeout=300) for f in futs]
        assert all(status == 200 for status, _ in drained)

        rc = proc.wait(timeout=120)
        assert rc == EXIT_PREEMPTED, (rc, proc.stdout.read()[-3000:])
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

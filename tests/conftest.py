"""Test configuration: force an 8-device virtual CPU platform so every
mesh/pjit/collective test runs without TPU hardware (SURVEY.md §4 item 3)."""

import os

# jax is pre-imported at interpreter startup in this environment (so env vars are
# too late for platform selection) — use jax.config, which takes effect as long as
# no backend has been initialized yet.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

# Persistent compile cache: the suite's dominant cost on a small box is XLA
# recompiles of identical programs (every Trainer/make_train_step call is a new
# closure -> new jit object). Cache survives across tests AND across runs.
from pathlib import Path  # noqa: E402

_cache = Path(os.environ.get("DCR_TEST_CACHE_DIR")
              or Path(__file__).parent / ".jax_cache_cpu")
jax.config.update("jax_compilation_cache_dir", str(_cache))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
try:  # CPU-backend caching is gated behind an allowlist in some jax versions
    jax.config.update("jax_persistent_cache_enable_xla_caches",
                      "xla_gpu_per_fusion_autotune_cache_dir")
except Exception:  # dcr-lint: disable=DCR006 — version probe, not a recovery path: absence of the flag IS the expected outcome on older jax, and the cache works without it
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402


_cache_before: set = set()


def pytest_sessionstart(session):
    global _cache_before
    _cache_before = {p.name for p in _cache.glob("*")} if _cache.exists() else set()


def pytest_sessionfinish(session, exitstatus):
    """Cache hit/miss accounting: entries present before the session that the
    run did NOT touch are prune candidates (an entry is rewritten/refreshed on
    miss, so `new` counts this run's compiles). Regenerate the committed cache
    with DCR_TEST_CACHE_DIR=<fresh dir> + a full run, then swap directories."""
    if not _cache.exists():
        return
    now = {p.name for p in _cache.glob("*")}
    new = now - _cache_before
    tr = session.config.pluginmanager.get_plugin("terminalreporter")
    if tr is not None:
        tr.write_line(
            f"jax compile cache [{_cache.name}]: {len(now)} entries, "
            f"{len(new)} written this run (cache misses)")


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual cpu devices, got {devs}"
    return devs


@pytest.fixture()
def rng_np():
    return np.random.default_rng(0)

"""Test configuration: force an 8-device virtual CPU platform so every
mesh/pjit/collective test runs without TPU hardware (SURVEY.md §4 item 3)."""

import os

# jax is pre-imported at interpreter startup in this environment (so env vars are
# too late for platform selection) — use jax.config, which takes effect as long as
# no backend has been initialized yet.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual cpu devices, got {devs}"
    return devs


@pytest.fixture()
def rng_np():
    return np.random.default_rng(0)

"""End-to-end fault-injection harness: prove the recovery paths work.

Scenarios (ISSUE acceptance criteria), all on the virtual-CPU platform:

(a) injected SIGTERM mid-train, then restart -> bit-exact final state vs an
    uninterrupted run;
(b) corrupt latest checkpoint -> restore falls back to the previous step with
    a logged quarantine, not an exception;
(c) injected decode failure under budget -> epoch completes with the bad
    index quarantined; over budget -> clear abort;
(d) injected NaN with rollback enabled -> restore, skip, continue (finite
    final loss); default config -> fail-fast exactly as the seed.

Plus unit coverage of the primitives: fault-spec parsing/firing,
retry/backoff, watchdog/stage deadlines, quarantine manifests, checkpoint
content manifests.
"""

import json
import threading
import time

import numpy as np
import pytest
from PIL import Image

from dcr_tpu.core import resilience as R
from dcr_tpu.core.config import (DataConfig, FaultToleranceConfig, ModelConfig,
                                 OptimConfig, TrainConfig)
from dcr_tpu.utils import faults


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("DCR_FAULTS", raising=False)
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# Unit: fault registry
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_parse_faults_syntax():
    specs = faults.parse_faults(
        "decode_error@step=3,ckpt_corrupt@step=200x2,nan_loss@step=5&epoch=1")
    assert [(s.kind, s.where, s.times) for s in specs] == [
        ("decode_error", {"step": 3}, 1),
        ("ckpt_corrupt", {"step": 200}, 2),
        ("nan_loss", {"step": 5, "epoch": 1}, 1),
    ]
    assert faults.parse_faults("") == []
    with pytest.raises(ValueError, match="malformed"):
        faults.parse_faults("decode_error")          # no coordinates
    with pytest.raises(ValueError, match="malformed"):
        faults.parse_faults("nan_loss@step=abc")     # non-integer


@pytest.mark.fast
def test_registry_fires_once_and_matches_coords():
    reg = faults.install("decode_error@step=3")
    assert not reg.fire("decode_error", step=2, slot=0)
    assert not reg.fire("nan_loss", step=3)
    assert reg.fire("decode_error", step=3, slot=7)   # extra coords ignored
    assert not reg.fire("decode_error", step=3, slot=8)  # single-shot
    assert reg.pending() == []


@pytest.mark.fast
def test_registry_respects_times_and_env(monkeypatch):
    reg = faults.install("nan_loss@step=1x3")
    assert sum(reg.fire("nan_loss", step=1) for _ in range(5)) == 3
    # module-level fire() reads DCR_FAULTS lazily after clear()
    faults.clear()
    monkeypatch.setenv("DCR_FAULTS", "sigterm@step=9")
    assert not faults.fire("sigterm", step=8)
    assert faults.fire("sigterm", step=9)


@pytest.mark.fast
def test_registry_fire_is_atomic_across_threads():
    reg = faults.install("decode_error@step=1x10")
    hits = []

    def worker():
        for _ in range(100):
            if reg.fire("decode_error", step=1):
                hits.append(1)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(hits) == 10


# ---------------------------------------------------------------------------
# Unit: retry / deadline / quarantine primitives
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_retry_call_backs_off_then_succeeds():
    delays = []
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert R.retry_call(flaky, attempts=4, base_delay=0.1, jitter=0.0,
                        sleep=delays.append) == "ok"
    assert len(calls) == 3
    assert delays == [0.1, 0.2]  # exponential, jitter disabled


@pytest.mark.fast
def test_retry_call_exhausts_and_reraises():
    def always():
        raise OSError("down")

    with pytest.raises(OSError, match="down"):
        R.retry_call(always, attempts=3, sleep=lambda s: None)


@pytest.mark.fast
def test_retry_give_up_on_wins_over_retry_on(tmp_path):
    calls = []

    def missing():
        calls.append(1)
        raise FileNotFoundError("gone")

    with pytest.raises(FileNotFoundError):
        R.retry_call(missing, attempts=5, retry_on=(OSError,),
                     give_up_on=R.NONTRANSIENT_IO, sleep=lambda s: None)
    assert len(calls) == 1  # not retried
    with pytest.raises(FileNotFoundError):
        R.read_bytes_with_retry(tmp_path / "nope.bin")


@pytest.mark.fast
def test_watchdog_fires_on_overrun_and_deadline_checks():
    fired = []
    with R.watchdog("slowpoke", 0.05, on_timeout=lambda: fired.append(1)) as dl:
        time.sleep(0.15)
        assert dl.expired()
        with pytest.raises(R.DeadlineExceeded):
            dl.check()
    assert fired == [1]
    # disabled watchdog never fires, never expires
    with R.watchdog("fast", 0.0) as dl:
        assert not dl.expired()
        dl.check()


@pytest.mark.fast
def test_stage_logs_failure_and_reraises(caplog):
    with caplog.at_level("WARNING", logger="dcr_tpu"):
        with pytest.raises(ValueError):
            with R.stage("explodes"):
                raise ValueError("boom")
    assert any("stage_failed" in r.message for r in caplog.records)


@pytest.mark.fast
def test_quarantine_manifest_records_and_counts(tmp_path):
    q = R.QuarantineManifest(tmp_path / "q.jsonl")
    q.record("bad_sample", index=3, path="x.jpg")
    q.record("bad_sample", index=9, path="y.jpg")
    q.record("bad_checkpoint", step=100)
    assert q.count("bad_sample") == 2 and q.count("bad_checkpoint") == 1
    entries = q.entries()
    assert [e["kind"] for e in entries] == ["bad_sample", "bad_sample",
                                           "bad_checkpoint"]
    assert entries[0]["index"] == 3
    # each line is standalone JSON (appendable, tail-able)
    for line in (tmp_path / "q.jsonl").read_text().splitlines():
        json.loads(line)


# ---------------------------------------------------------------------------
# Unit: checkpoint content manifests + fallback restore
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_state_manifest_detects_tampering():
    import jax.numpy as jnp

    from dcr_tpu.core.checkpoint import state_manifest, verify_manifest

    state = {"params": {"w": jnp.arange(8.0)}, "step": jnp.asarray(4)}
    manifest = state_manifest(state)
    assert verify_manifest(manifest, state) == []
    tampered = {"params": {"w": jnp.arange(8.0).at[3].set(99.0)},
                "step": jnp.asarray(4)}
    problems = verify_manifest(manifest, tampered)
    assert problems and "checksum mismatch" in problems[0]
    missing = {"params": {}, "step": jnp.asarray(4)}
    assert any("missing" in p for p in verify_manifest(manifest, missing))


def test_checkpoint_fallback_restores_previous_step(tmp_path):
    """Acceptance (b), manager level: corrupting the latest checkpoint makes
    restore fall back to N-1 with a logged quarantine, not an exception."""
    import jax.numpy as jnp

    from dcr_tpu.core.checkpoint import CheckpointManager, _corrupt_step_dir

    q = R.QuarantineManifest(tmp_path / "quarantine.jsonl")
    mgr = CheckpointManager(tmp_path / "ckpt", async_save=False, quarantine=q)
    for step in (2, 4):
        mgr.save(step, {"w": jnp.full((16,), float(step)),
                        "step": jnp.asarray(step)})
    mgr.wait()
    _corrupt_step_dir(tmp_path / "ckpt" / "4")
    like = {"w": jnp.zeros(16), "step": jnp.asarray(0)}
    state, step, skipped = mgr.restore_latest_valid(like)
    assert step == 2
    assert [s for s, _ in skipped] == [4]
    np.testing.assert_array_equal(np.asarray(state["w"]), np.full(16, 2.0))
    assert (tmp_path / "ckpt" / "quarantined" / "4").exists()
    assert q.count("bad_checkpoint") == 1
    assert mgr.all_steps() == [2]  # quarantined step no longer offered
    mgr.close()


def test_checkpoint_explicit_restore_rejects_checksum_mismatch(tmp_path):
    """Silent corruption (orbax restores without complaint, bytes differ) is
    caught by the content manifest on an explicitly-requested step."""
    import jax.numpy as jnp

    from dcr_tpu.core.checkpoint import CheckpointCorrupt, CheckpointManager

    mgr = CheckpointManager(tmp_path / "ckpt", async_save=False)
    mgr.save(1, {"w": jnp.arange(16.0)})
    mgr.wait()
    # simulate silent corruption: tamper the manifest's recorded checksum so
    # the restored bytes no longer match what save-time recorded
    mpath = tmp_path / "ckpt" / "manifests" / "1.json"
    manifest = json.loads(mpath.read_text())
    key = next(iter(manifest["leaves"]))
    manifest["leaves"][key]["crc32"] ^= 0xFFFF
    mpath.write_text(json.dumps(manifest))
    with pytest.raises(CheckpointCorrupt, match="checksum mismatch"):
        mgr.restore({"w": jnp.zeros(16)}, 1)
    mgr.close()


def test_all_checkpoints_corrupt_raises_not_silent_restart(tmp_path):
    import jax.numpy as jnp

    from dcr_tpu.core.checkpoint import CheckpointManager, _corrupt_step_dir

    mgr = CheckpointManager(tmp_path / "ckpt", async_save=False)
    mgr.save(1, {"w": jnp.arange(4.0)})
    mgr.wait()
    _corrupt_step_dir(tmp_path / "ckpt" / "1")
    with pytest.raises(FileNotFoundError, match="quarantined"):
        mgr.restore_latest_valid({"w": jnp.zeros(4)})
    mgr.close()


# ---------------------------------------------------------------------------
# Data path: quarantine + deterministic replacement (acceptance c)
# ---------------------------------------------------------------------------

@pytest.fixture()
def image_folder(tmp_path):
    rng = np.random.default_rng(0)
    for cls in ["c0", "c1"]:
        d = tmp_path / "data" / cls
        d.mkdir(parents=True)
        for i in range(6):
            arr = rng.integers(0, 255, (40, 52, 3), np.uint8)
            Image.fromarray(arr).save(d / f"{cls}_{i}.png")
    return tmp_path / "data"


def _dataset(root, **fault_kw):
    from dcr_tpu.data.dataset import ObjectAttributeDataset
    from dcr_tpu.data.tokenizer import HashTokenizer

    cfg = DataConfig(train_data_dir=str(root), resolution=32,
                     class_prompt="nolevel", num_workers=2, seed=7)
    ft = FaultToleranceConfig(retry_base_delay=0.0, retry_max_delay=0.0,
                              **fault_kw)
    return ObjectAttributeDataset(cfg, HashTokenizer(100, 16), fault=ft), ft


def _corrupt_image(ds, position: int) -> int:
    index = int(ds.active_indices[position])
    with open(ds.paths[index], "wb") as f:
        f.write(b"garbage, not an image")
    return index


@pytest.mark.fast
def test_bad_sample_under_budget_quarantined_and_replaced(tmp_path, image_folder):
    from dcr_tpu.data.loader import DataLoader

    ds, ft = _dataset(image_folder, max_bad_sample_frac=0.5)
    bad = _corrupt_image(ds, 4)
    q = R.QuarantineManifest(tmp_path / "q.jsonl")
    loader = DataLoader(ds, batch_size=2, num_workers=2, seed=1,
                        fault=ft, quarantine=q)
    batches = list(loader.epoch(0))
    assert len(batches) == loader.steps_per_epoch()  # epoch completed
    served = np.concatenate([b.index for b in batches])
    assert bad not in served  # the bad sample never reaches the model
    assert loader.bad_samples == 1
    entries = q.entries()
    assert len(entries) == 1 and entries[0]["kind"] == "bad_sample"
    assert entries[0]["index"] == bad
    assert entries[0]["replacement_index"] in served


@pytest.mark.fast
def test_bad_sample_replacement_is_deterministic(tmp_path, image_folder):
    from dcr_tpu.data.loader import DataLoader

    ds, ft = _dataset(image_folder, max_bad_sample_frac=0.5)
    _corrupt_image(ds, 4)
    runs = []
    for _ in range(2):
        loader = DataLoader(ds, batch_size=2, num_workers=2, seed=1, fault=ft)
        runs.append(list(loader.epoch(0)))
    for a, b in zip(*runs):
        np.testing.assert_array_equal(a.pixel_values, b.pixel_values)
        np.testing.assert_array_equal(a.index, b.index)


@pytest.mark.fast
def test_bad_samples_over_budget_abort(image_folder):
    from dcr_tpu.data.loader import DataLoader, TooManyBadSamples

    ds, ft = _dataset(image_folder, max_bad_sample_frac=0.05)  # budget = 0
    _corrupt_image(ds, 0)
    loader = DataLoader(ds, batch_size=2, num_workers=2, seed=1, fault=ft)
    with pytest.raises(TooManyBadSamples, match="max_bad_sample_frac"):
        for _ in loader.epoch(0):
            pass


@pytest.mark.fast
def test_injected_decode_error_follows_quarantine_path(tmp_path, image_folder):
    """decode_error@step=1 drives the exact code path a real corrupt image
    takes — no file harmed."""
    from dcr_tpu.data.loader import DataLoader

    ds, ft = _dataset(image_folder, max_bad_sample_frac=0.5)
    q = R.QuarantineManifest(tmp_path / "q.jsonl")
    faults.install("decode_error@step=1")
    loader = DataLoader(ds, batch_size=2, num_workers=2, seed=1,
                        fault=ft, quarantine=q)
    batches = list(loader.epoch(0))
    assert len(batches) == loader.steps_per_epoch()
    entries = q.entries()
    assert len(entries) == 1
    assert entries[0]["step"] == 1
    assert "InjectedFault" in entries[0]["error"]


@pytest.mark.fast
def test_injected_decode_error_default_config_fails_fast(image_folder):
    from dcr_tpu.data.loader import DataLoader
    from dcr_tpu.utils.faults import InjectedFault

    ds, ft = _dataset(image_folder)  # max_bad_sample_frac=0 (seed behavior)
    faults.install("decode_error@step=0")
    loader = DataLoader(ds, batch_size=2, num_workers=2, seed=1, fault=ft)
    with pytest.raises(InjectedFault):
        for _ in loader.epoch(0):
            pass


# ---------------------------------------------------------------------------
# Trainer end-to-end scenarios (a), (b), (d) — marked slow (each leg is a
# fresh process paying interpreter+jax startup; ~7 subprocess runs total).
# CI runs them in a dedicated job (.github/workflows/ci.yml `fault-e2e`), so
# every PR still proves the recovery paths end to end.
#
# Every TRAINING leg runs as a subprocess through the real CLI
# (`python -m dcr_tpu.cli.train` + DCR_FAULTS env) — the faithful model of
# production runs (one process per run; a preempted process checkpoints and
# DIES), and a hard requirement in this environment: a real SIGTERM followed
# by further in-process jax/orbax work corrupts the heap inside the
# tensorstore/orbax thread stack (glibc 'corrupted size vs. prev_size'), and
# multiple Trainer instances inside one long-lived pytest process hit the
# same native flakiness. In-process we only inspect artifacts: quarantine
# manifests, metrics.jsonl, and orbax restores against an abstract state.
# ---------------------------------------------------------------------------

@pytest.fixture()
def train_setup(tmp_path):
    rng = np.random.default_rng(0)
    for cls in ["c0", "c1"]:
        d = tmp_path / "data" / cls
        d.mkdir(parents=True)
        for i in range(8):
            Image.fromarray(rng.integers(0, 255, (20, 20, 3), np.uint8)).save(
                d / f"{i}.png")
    cfg = TrainConfig(
        output_dir=str(tmp_path / "run"),
        seed=0,
        train_batch_size=2,
        max_train_steps=6,
        num_train_epochs=20,
        mixed_precision="no",
        save_steps=1000,
        modelsavesteps=2,
        log_every=1,
        model=ModelConfig.tiny(),
        data=DataConfig(train_data_dir=str(tmp_path / "data"), resolution=16,
                        class_prompt="nolevel", num_workers=2, seed=0),
        optim=OptimConfig(learning_rate=1e-4, lr_scheduler="constant",
                          lr_warmup_steps=0),
    )
    return cfg, tmp_path


def _run_cli(cfg, cfg_path, *, dcr_faults: str = "", timeout: int = 540):
    """One training run = one process, through the real CLI entry point."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    from dcr_tpu.core.config import save_config

    save_config(cfg, cfg_path)
    repo = Path(__file__).parent.parent
    cache = os.environ.get("DCR_TEST_CACHE_DIR") or str(
        repo / "tests" / ".jax_cache_cpu")
    env = dict(os.environ)
    env.pop("DCR_FAULTS", None)
    if dcr_faults:
        env["DCR_FAULTS"] = dcr_faults
    env.update(
        DCR_TPU_PLATFORM="cpu",
        PYTHONPATH=str(repo) + os.pathsep + env.get("PYTHONPATH", ""),
        # match the conftest jax config so trajectories are bit-identical to
        # in-process runs and the persistent compile cache is shared
        JAX_THREEFRY_PARTITIONABLE="1",
        JAX_COMPILATION_CACHE_DIR=cache,
        JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="1.0",
        JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES="0",
    )
    # conftest already forced --xla_force_host_platform_device_count=8 into
    # XLA_FLAGS (inherited via os.environ), so subprocesses see 8 devices
    proc = subprocess.run(
        [sys.executable, "-m", "dcr_tpu.cli.train", f"--config={cfg_path}"],
        env=env, cwd=repo, capture_output=True, text=True, timeout=timeout)
    return proc, proc.stdout + proc.stderr


def _restore_final(cfg, step: int):
    """Restore a run's checkpoint against an abstract (zero-memory) state and
    return its flat numpy leaves — verifies the content manifest on the way."""
    import jax
    from pathlib import Path

    from dcr_tpu.core.checkpoint import CheckpointManager
    from dcr_tpu.diffusion.trainer import abstract_train_state

    mgr = CheckpointManager(Path(cfg.output_dir) / "checkpoints", verify=True)
    state = mgr.restore(abstract_train_state(cfg), step)
    mgr.close()
    return [np.asarray(x) for x in jax.tree.leaves(jax.device_get(
        {"unet": state.unet_params, "opt": state.opt_state,
         "step": state.step}))]


@pytest.mark.slow
def test_sigterm_midtrain_resume_is_bit_exact(train_setup):
    """Acceptance (a): injected SIGTERM mid-train -> checkpoint-and-stop;
    a fresh process resumes and reproduces the uninterrupted run's final
    checkpoint bit-exactly (params, optimizer state, step)."""
    import dataclasses

    cfg, base = train_setup
    ref_cfg = dataclasses.replace(cfg, output_dir=str(base / "run_ref"))
    proc, out = _run_cli(ref_cfg, base / "ref_cfg.json")
    assert proc.returncode == 0, out[-3000:]

    # interrupted leg: real SIGTERM at micro-step 3; process checkpoints and
    # dies with the distinct preempted code a restart wrapper branches on
    from dcr_tpu.core.coordination import EXIT_PREEMPTED

    proc, out = _run_cli(cfg, base / "cfg.json", dcr_faults="sigterm@step=3")
    assert proc.returncode == EXIT_PREEMPTED, (proc.returncode, out[-3000:])
    assert "fault injection ACTIVE" in out       # CLI announced the harness
    assert "preemption: checkpointing at step 3" in out
    assert (base / "run" / "checkpoints" / "3").exists()

    # restart: fresh process resumes from the preemption checkpoint
    proc, out = _run_cli(cfg, base / "cfg.json")
    assert proc.returncode == 0, out[-3000:]
    assert "resumed from checkpoint step 3" in out

    ref_leaves = _restore_final(ref_cfg, 6)
    got_leaves = _restore_final(cfg, 6)
    assert len(got_leaves) == len(ref_leaves)
    for got, want in zip(got_leaves, ref_leaves):
        np.testing.assert_array_equal(got, want)


@pytest.mark.slow
def test_corrupt_latest_checkpoint_falls_back_on_resume(train_setup):
    """Acceptance (b), full-stack: ckpt_corrupt@step=4 tears the latest save
    post-commit; the restarted process falls back to step 2 with a logged
    quarantine (no exception) and finishes the run."""
    import dataclasses

    cfg, base = train_setup
    cfg = dataclasses.replace(cfg, max_train_steps=4,
                              output_dir=str(base / "run_ckpt"))
    proc, out = _run_cli(cfg, base / "ckpt_cfg.json",
                         dcr_faults="ckpt_corrupt@step=4")
    assert proc.returncode == 0, out[-3000:]

    proc, out = _run_cli(cfg, base / "ckpt_cfg.json")
    assert proc.returncode == 0, out[-3000:]
    assert "resume fell back past 1 corrupt checkpoint(s)" in out
    assert "resumed from checkpoint step 2" in out
    run = base / "run_ckpt"
    assert (run / "checkpoints" / "quarantined" / "4").exists()
    entries = [json.loads(l) for l in
               (run / "quarantine.jsonl").read_text().splitlines()]
    bad = [e for e in entries if e["kind"] == "bad_checkpoint"]
    assert bad and bad[0]["step"] == 4
    # the resumed run retrained through step 4 and the counter was reported
    lines = [json.loads(l) for l in
             (run / "logs" / "metrics.jsonl").read_text().splitlines()]
    assert any(l.get("faults/ckpt_fallbacks") == 1 for l in lines)
    assert _restore_final(cfg, 4)  # final checkpoint restores and verifies


@pytest.mark.slow
def test_nan_rollback_restores_skips_and_continues(train_setup):
    """Acceptance (d), opt-in half: nan_loss@step=3 with max_rollbacks=1 ->
    restore the step-2 checkpoint, fast-forward past the bad window, and
    converge to a finite final loss."""
    import dataclasses

    cfg, base = train_setup
    cfg = dataclasses.replace(
        cfg, max_train_steps=5, output_dir=str(base / "run_roll"),
        fault=FaultToleranceConfig(max_rollbacks=1))
    proc, out = _run_cli(cfg, base / "roll_cfg.json",
                         dcr_faults="nan_loss@step=3")
    assert proc.returncode == 0, out[-3000:]  # must NOT fail fast
    assert "quarantine_nan_rollback" in out   # structured [fault] line
    run = base / "run_roll"
    roll = [json.loads(l) for l in
            (run / "quarantine.jsonl").read_text().splitlines()
            if json.loads(l)["kind"] == "nan_rollback"]
    assert len(roll) == 1
    assert roll[0]["at_step"] == 3 and roll[0]["restored_step"] == 2
    lines = [json.loads(l) for l in
             (run / "logs" / "metrics.jsonl").read_text().splitlines()]
    assert any(l.get("faults/rollbacks") == 1 for l in lines)
    # converging loss curve: post-rollback losses observed and finite
    losses = [l["loss"] for l in lines if "loss" in l]
    assert losses and np.isfinite(losses[-1])
    assert _restore_final(cfg, 5)             # run reached its final step


@pytest.mark.slow
def test_nan_default_config_fails_fast_as_seed(train_setup):
    """Acceptance (d), default half: with max_rollbacks=0 an injected NaN
    fails fast exactly as the seed — FloatingPointError naming the last good
    checkpoint, which is left intact as the recovery point."""
    import dataclasses

    cfg, base = train_setup
    cfg = dataclasses.replace(cfg, output_dir=str(base / "run_nan"))
    proc, out = _run_cli(cfg, base / "nan_cfg.json",
                         dcr_faults="nan_loss@step=3")
    assert proc.returncode != 0
    assert "FloatingPointError" in out and "non-finite loss" in out
    assert "last good checkpoint" in out
    # step-2 checkpoint survived as the recovery point; the poisoned step
    # was never saved
    run = base / "run_nan"
    assert (run / "checkpoints" / "2").exists()
    assert not (run / "checkpoints" / "3").exists()

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcr_tpu.core.config import ModelConfig
from dcr_tpu.models import layers as L
from dcr_tpu.models.clip_text import CLIPTextModel, init_clip_text
from dcr_tpu.models.unet2d import UNet2DCondition, init_unet, unet_param_count
from dcr_tpu.models.vae import AutoencoderKL, init_vae, vae_scale_factor


@pytest.fixture(scope="module")
def tiny():
    return ModelConfig.tiny()


def test_timestep_embedding_properties():
    emb = L.timestep_embedding(jnp.array([0, 10, 999]), 32)
    assert emb.shape == (3, 32)
    # t=0: cos part = 1, sin part = 0 (flip_sin_to_cos puts cos first)
    np.testing.assert_allclose(np.asarray(emb[0, :16]), 1.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(emb[0, 16:]), 0.0, atol=1e-6)
    assert not np.allclose(np.asarray(emb[1]), np.asarray(emb[2]))


def test_unet_forward_shapes(tiny):
    model, params = init_unet(tiny, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 8, 8, 4))
    t = jnp.array([10, 500])
    ctx = jax.random.normal(jax.random.key(2), (2, 16, 32))
    out = model.apply({"params": params}, x, t, ctx)
    assert out.shape == (2, 8, 8, 4)
    assert out.dtype == jnp.float32
    assert np.all(np.isfinite(np.asarray(out)))


def test_unet_shape_polymorphic_in_spatial(tiny):
    """Same params serve any spatial size (SD trains 256/512 with one net)."""
    model, params = init_unet(tiny, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (1, 16, 16, 4))
    out = model.apply({"params": params}, x, jnp.array([3]),
                      jnp.zeros((1, 16, 32)))
    assert out.shape == (1, 16, 16, 4)


def test_unet_conditioning_matters(tiny):
    model, params = init_unet(tiny, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (1, 8, 8, 4))
    t = jnp.array([100])
    c1 = jax.random.normal(jax.random.key(2), (1, 16, 32))
    c2 = jax.random.normal(jax.random.key(3), (1, 16, 32))
    o1 = model.apply({"params": params}, x, t, c1)
    o2 = model.apply({"params": params}, x, t, c2)
    assert not np.allclose(np.asarray(o1), np.asarray(o2))
    # timestep matters too
    o3 = model.apply({"params": params}, x, jnp.array([900]), c1)
    assert not np.allclose(np.asarray(o1), np.asarray(o3))


def test_unet_bf16_compute(tiny):
    model = UNet2DCondition(tiny, dtype=jnp.bfloat16)
    x = jnp.zeros((1, 8, 8, 4))
    variables = model.init(jax.random.key(0), x, jnp.array([0]), jnp.zeros((1, 16, 32)))
    out = model.apply(variables, x, jnp.array([0]), jnp.zeros((1, 16, 32)))
    assert out.dtype == jnp.float32  # outputs promoted back
    # params stay f32
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(variables["params"]))


def test_unet_grads_flow_everywhere(tiny):
    model, params = init_unet(tiny, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (1, 8, 8, 4))

    def loss(p):
        out = model.apply({"params": p}, x, jnp.array([5]),
                          jnp.ones((1, 16, 32)))
        return jnp.mean(out ** 2)

    grads = jax.grad(loss)(params)
    flat = jax.tree.leaves_with_path(grads)
    dead = [jax.tree_util.keystr(k) for k, g in flat if float(jnp.max(jnp.abs(g))) == 0.0]
    # only params with no path to the loss may be dead; for this architecture
    # everything should receive gradient
    assert not dead, f"dead params: {dead[:10]}"


def test_sd21_unet_param_count():
    """Full-size config lands in the SD-2.1 ballpark (~0.87B params)."""
    cfg = ModelConfig()
    model = UNet2DCondition(cfg)
    x = jnp.zeros((1, 32, 32, 4))
    params = jax.eval_shape(
        lambda k: model.init(k, x, jnp.zeros((1,), jnp.int32),
                             jnp.zeros((1, 77, 1024)))["params"],
        jax.random.key(0),
    )
    n = sum(np.prod(s.shape) for s in jax.tree.leaves(params))
    assert 0.7e9 < n < 1.1e9, f"param count {n/1e9:.2f}B out of SD-2.1 range"


def test_vae_roundtrip_shapes(tiny):
    model, params = init_vae(tiny, jax.random.key(0))
    f = vae_scale_factor(tiny)
    px = 8 * f
    x = jax.random.normal(jax.random.key(1), (2, px, px, 3))
    dist = model.apply({"params": params}, x, method=model.encode)
    assert dist.mean.shape == (2, 8, 8, tiny.vae_latent_channels)
    z = dist.sample(jax.random.key(2))
    recon = model.apply({"params": params}, z, method=model.decode)
    assert recon.shape == x.shape
    # sampling is rng-deterministic
    z2 = dist.sample(jax.random.key(2))
    np.testing.assert_array_equal(np.asarray(z), np.asarray(z2))


def test_clip_text_shapes_and_causality(tiny):
    model, params = init_clip_text(tiny, jax.random.key(0))
    ids = jnp.array([[5, 7, 9, 11] + [0] * 12], jnp.int32)
    out = model.apply({"params": params}, ids)
    assert out.last_hidden_state.shape == (1, 16, tiny.text_hidden_size)
    assert out.penultimate_hidden_state.shape == (1, 16, tiny.text_hidden_size)
    assert out.pooled.shape == (1, tiny.text_hidden_size)
    # causality: changing a later token must not affect earlier positions
    ids2 = ids.at[0, 10].set(99)
    out2 = model.apply({"params": params}, ids2)
    np.testing.assert_allclose(np.asarray(out.last_hidden_state[0, :10]),
                               np.asarray(out2.last_hidden_state[0, :10]), atol=1e-5)
    assert not np.allclose(np.asarray(out.last_hidden_state[0, 10:]),
                           np.asarray(out2.last_hidden_state[0, 10:]))


def test_penultimate_differs_from_last(tiny):
    model, params = init_clip_text(tiny, jax.random.key(0))
    ids = jnp.arange(16, dtype=jnp.int32)[None]
    out = model.apply({"params": params}, ids)
    assert not np.allclose(np.asarray(out.last_hidden_state),
                           np.asarray(out.penultimate_hidden_state))


def test_unet_jit_compiles_once(tiny):
    model, params = init_unet(tiny, jax.random.key(0))
    calls = 0

    @jax.jit
    def fwd(p, x, t, c):
        nonlocal calls
        calls += 1
        return model.apply({"params": p}, x, t, c)

    x = jnp.zeros((1, 8, 8, 4))
    c = jnp.zeros((1, 16, 32))
    fwd(params, x, jnp.array([1]), c)
    fwd(params, x, jnp.array([2]), c)
    assert calls == 1  # traced once, different timestep values don't retrace

"""dcr-fleet tests: request-level fault tolerance for multi-worker serve.

Fast tier: pure-logic units for the request journal's state machine (the
zero-drop ledger), head-insertion requeue on the shared queue, lease
publish/expiry/corruption handling, fleet config validation, and the typed
admission -> HTTP response mapping (Retry-After on shed/no-workers).

Slow tier: the acceptance e2e — a real ``dcr-serve --fleet.workers=2``
supervisor subprocess (which spawns two real worker subprocesses), an
injected ``worker_crash`` SIGKILLing worker 0 on its first batch, and the
assertion that every accepted request still completes with a response
bit-identical to an uninjected run, with the durable journal replaying to
zero dropped requests. Launch plumbing (free ports, env) is shared with
tests/_multiproc.py and tests/test_serve.py; the fleet needs no
jax.distributed rendezvous — the supervisor spawns its own workers and the
control plane is the lease directory, so the two-process launcher itself is
not used.
"""

import json
import time

import pytest

from dcr_tpu.core.config import FleetConfig, ServeConfig, validate_serve_config
from dcr_tpu.serve.fleet import (ACKED, FAILED, IN_FLIGHT, QUEUED, FleetPaths,
                                 RequestJournal, WorkerLease,
                                 bucket_from_tuple, clear_lease, fleet_paths,
                                 read_lease, write_lease)
from dcr_tpu.serve.queue import (GenBucket, NoWorkersError, QueueFullError,
                                 Request, RequestQueue, SloShedError)
from dcr_tpu.serve.server import admission_response


def _bucket(**kw) -> GenBucket:
    d = dict(resolution=16, steps=2, guidance=7.5, sampler="ddim",
             rand_noise_lam=0.0)
    d.update(kw)
    return GenBucket(**d)


def _req(prompt="p", seed=0) -> Request:
    return Request(prompt=prompt, seed=seed, bucket=_bucket())


# ---------------------------------------------------------------------------
# request journal: the zero-drop state machine
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_journal_happy_path_and_counts():
    j = RequestJournal()
    r = _req()
    e = j.add(r)
    assert e.state == QUEUED and e.attempts == 0
    assert j.dispatch(r.id, worker=1) == 1
    assert j.entry(r.id).state == IN_FLIGHT
    assert j.inflight_for(1) == [r.id]
    assert j.ack(r.id, worker=1) is True
    assert j.entry(r.id).state == ACKED
    assert j.pending_count() == 0
    c = j.counts()
    assert c["accepted"] == 1 and c[ACKED] == 1 and c["requeued_total"] == 0


@pytest.mark.fast
def test_journal_requeue_ordering_and_attempts():
    j = RequestJournal()
    r = _req()
    j.add(r)
    assert j.dispatch(r.id, worker=0) == 1
    # worker 0 died: back to QUEUED, attempt count preserved
    assert j.requeue(r.id, worker=0, reason="crash") == 1
    assert j.entry(r.id).state == QUEUED
    assert j.inflight_for(0) == []
    # re-dispatch elsewhere is attempt 2
    assert j.dispatch(r.id, worker=1) == 2
    assert j.ack(r.id, worker=1) is True
    assert j.counts()["requeued_total"] == 1


@pytest.mark.fast
def test_journal_uncharged_requeue_refunds_attempt_budget():
    """A worker-state rejection (draining/overloaded) never executed the
    request, so it must not burn max_attempts: three bounces off draining
    workers still leave the budget at zero, while real losses charge it."""
    j = RequestJournal()
    r = _req()
    j.add(r)
    for worker in range(3):
        j.dispatch(r.id, worker=worker)
        assert j.requeue(r.id, worker=worker, reason="DrainingError: bye",
                         charge=False) == 0
    j.dispatch(r.id, worker=3)
    assert j.requeue(r.id, worker=3, reason="crash") == 1   # charged
    assert j.entry(r.id).attempts == 4                      # audit trail kept


@pytest.mark.fast
def test_journal_compacts_terminal_entries():
    """Terminal entries leave the live map (bounded memory in a long-lived
    supervisor) but stay addressable for duplicate-ack dedup and counts."""
    j = RequestJournal()
    reqs = [_req(seed=i) for i in range(5)]
    for r in reqs:
        j.add(r)
        j.dispatch(r.id, worker=0)
        assert j.ack(r.id, worker=0) is True
    assert j.pending_count() == 0
    assert len(j._entries) == 0                # live map fully drained
    assert j.entry(reqs[0].id).state == ACKED  # still addressable
    assert j.entry(reqs[0].id).prompt == ""    # heavy field dropped
    assert j.ack(reqs[0].id, worker=1) is False
    c = j.counts()
    assert c["accepted"] == 5 and c[ACKED] == 5 and c["duplicate_acks"] == 1


@pytest.mark.fast
def test_journal_no_duplicate_completion():
    """First completion wins: the requeued twin's late result is dropped."""
    j = RequestJournal()
    r = _req()
    j.add(r)
    j.dispatch(r.id, worker=0)
    j.requeue(r.id, worker=0, reason="presumed dead")
    j.dispatch(r.id, worker=1)
    assert j.ack(r.id, worker=1) is True       # winner
    assert j.ack(r.id, worker=0) is False      # zombie worker 0 delivered late
    assert j.fail(r.id, "too late") is False   # and can't be failed either
    c = j.counts()
    assert c["duplicate_acks"] == 1 and c[ACKED] == 1 and c[FAILED] == 0


@pytest.mark.fast
def test_journal_dispatch_of_terminal_entry_returns_none():
    """A requeued copy still sitting in the queue after its twin completed
    must be skipped at dispatch time, not re-executed."""
    j = RequestJournal()
    r = _req()
    j.add(r)
    j.dispatch(r.id, worker=0)
    j.ack(r.id, worker=0)
    assert j.dispatch(r.id, worker=1) is None


@pytest.mark.fast
def test_journal_invalid_transitions_raise():
    j = RequestJournal()
    r = _req()
    j.add(r)
    with pytest.raises(ValueError):
        j.add(r)                               # double add
    with pytest.raises(ValueError):
        j.requeue(r.id, worker=0, reason="x")  # requeue of QUEUED
    j.dispatch(r.id, worker=0)
    with pytest.raises(ValueError):
        j.dispatch(r.id, worker=1)             # double dispatch
    with pytest.raises(ValueError):
        j.reject(r.id, "x")                    # reject after dispatch


@pytest.mark.fast
def test_journal_reject_rolls_back_admission():
    j = RequestJournal()
    r = _req()
    j.add(r)
    j.reject(r.id, "queue full")
    assert j.entry(r.id) is None
    assert j.counts()["accepted"] == 0
    j.reject(r.id, "again")                    # idempotent on absent ids


@pytest.mark.fast
def test_journal_replay_from_durable_file(tmp_path):
    """The acceptance arithmetic reads the JSONL alone: requeues, duplicate
    acks, a terminal failure, a rejected admission, and one request the
    supervisor lost track of (still QUEUED) -> dropped = 1."""
    path = tmp_path / "journal.jsonl"
    j = RequestJournal(path)
    a, b, c, d = _req(), _req(), _req(), _req()
    j.add(a); j.dispatch(a.id, 0); j.requeue(a.id, 0, "crash")
    j.dispatch(a.id, 1); j.ack(a.id, 1); j.ack(a.id, 0)   # + duplicate
    j.add(b); j.dispatch(b.id, 1); j.fail(b.id, "attempts exhausted")
    j.add(c); j.reject(c.id, "queue full")                # never accepted
    j.add(d)                                              # lost: still QUEUED
    j.close()

    replay = RequestJournal.replay(path)
    counts = replay["counts"]
    assert replay["states"][a.id] == ACKED
    assert replay["states"][b.id] == FAILED
    assert c.id not in replay["states"]
    assert counts["accepted"] == 3
    assert counts["requeued_total"] == 1
    assert counts["duplicate_acks"] == 1
    assert counts["dropped"] == 1              # d was accepted, never resolved
    # every line is valid JSON with an op and a timestamp (the audit trail
    # external tools consume)
    for line in path.read_text().splitlines():
        rec = json.loads(line)
        assert "op" in rec and "t" in rec


@pytest.mark.fast
def test_journal_rotates_previous_incarnation(tmp_path):
    """Request ids restart per supervisor process, so a restarted supervisor
    must never append onto the previous run's journal — replay would merge
    two id spaces and corrupt the zero-drop arithmetic."""
    path = tmp_path / "journal.jsonl"
    j1 = RequestJournal(path)
    r1 = _req()
    j1.add(r1); j1.dispatch(r1.id, 0); j1.ack(r1.id, 0)
    j1.close()

    j2 = RequestJournal(path)          # "restart": same --fleet.dir
    r2 = _req()
    j2.add(r2)                         # run 2: accepted, never resolved
    j2.close()

    counts = RequestJournal.replay(path)["counts"]
    assert counts["accepted"] == 1     # run 1's records are NOT merged in
    assert counts["dropped"] == 1      # and run 2's pending request shows
    rotated = [p for p in tmp_path.iterdir()
               if p.name.startswith("journal.jsonl.")]
    assert len(rotated) == 1           # run 1 preserved for audit
    assert RequestJournal.replay(rotated[0])["counts"]["dropped"] == 0


@pytest.mark.fast
def test_queue_requeue_head_insertion_survives_drain():
    q = RequestQueue(maxsize=4)
    rs = [_req(seed=i) for i in range(4)]
    for r in rs:
        q.submit(r)
    taken = q.take_group(2)                    # worker took [0, 1] and died
    assert [r.seed for r in taken] == [0, 1]
    stamps = [r.enqueued_at for r in taken]
    q.close()                                  # drain began meanwhile
    with pytest.raises(Exception):
        q.submit(_req(seed=9))                 # admission IS closed...
    q.requeue(taken)                           # ...but requeue must land
    assert [r.seed for r in q.take_group(8)] == [0, 1, 2, 3]
    assert stamps == [r.enqueued_at for r in taken]   # true wait preserved
    # full queue: requeue bypasses the bound too (these were admitted once)
    q2 = RequestQueue(maxsize=1)
    q2.submit(_req(seed=0))
    q2.requeue([_req(seed=1)])
    assert q2.depth() == 2


# ---------------------------------------------------------------------------
# leases
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_lease_roundtrip_expiry_and_clear(tmp_path):
    paths = fleet_paths(tmp_path).ensure()
    lease = WorkerLease(index=2, pid=4242, port=18000, vae_scale=8,
                        lease_s=5.0)
    write_lease(paths, lease)
    got = read_lease(paths, 2)
    assert got == lease
    assert not got.expired()
    assert got.expired(now=time.time() + 6.0)   # silent past lease_s = dead
    assert got.age_s(now=got.renewed_at + 1.5) == pytest.approx(1.5)
    clear_lease(paths, 2)
    assert read_lease(paths, 2) is None
    clear_lease(paths, 2)                       # idempotent


@pytest.mark.fast
def test_lease_corrupt_file_reads_as_absent(tmp_path):
    paths = FleetPaths(tmp_path).ensure()
    paths.lease_file(0).write_text("{not json")
    assert read_lease(paths, 0) is None
    paths.lease_file(1).write_text('{"unexpected": "fields"}')
    assert read_lease(paths, 1) is None
    assert read_lease(paths, 7) is None         # absent


@pytest.mark.fast
def test_bucket_from_tuple_roundtrip():
    b = _bucket(guidance=3.5, sampler="dpm++")
    assert bucket_from_tuple(tuple(b)) == b
    assert bucket_from_tuple(list(b)) == b


# ---------------------------------------------------------------------------
# config validation + typed rejection -> HTTP mapping
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_fleet_config_validation():
    validate_serve_config(ServeConfig())                       # role off: ok
    validate_serve_config(ServeConfig(fleet=FleetConfig(workers=2)))
    with pytest.raises(ValueError, match="mutually"):
        validate_serve_config(ServeConfig(
            fleet=FleetConfig(workers=2, worker_index=0)))
    with pytest.raises(ValueError, match="lease_s"):
        validate_serve_config(ServeConfig(
            fleet=FleetConfig(workers=1, heartbeat_s=2.0, lease_s=1.0)))
    with pytest.raises(ValueError, match="dispatch_timeout_s"):
        validate_serve_config(ServeConfig(
            fleet=FleetConfig(workers=1, dispatch_timeout_s=0.0)))
    with pytest.raises(ValueError, match="max_attempts"):
        validate_serve_config(ServeConfig(
            fleet=FleetConfig(workers=1, max_attempts=0)))
    # a worker role validates the shared lease contract too
    with pytest.raises(ValueError, match="lease_s"):
        validate_serve_config(ServeConfig(
            fleet=FleetConfig(worker_index=0, heartbeat_s=0.0)))


@pytest.mark.fast
def test_admission_response_mapping():
    code, payload, headers = admission_response(
        SloShedError("p99 over SLO", retry_after_s=7.0))
    assert code == 503 and payload["error"] == "shed"
    assert headers["Retry-After"] == "7"
    code, payload, headers = admission_response(
        NoWorkersError("warming", retry_after_s=0.2))
    assert code == 503 and payload["error"] == "no_workers"
    assert headers["Retry-After"] == "1"        # floor: never Retry-After: 0
    code, payload, headers = admission_response(QueueFullError("full"))
    assert code == 503 and payload["error"] == "overloaded"
    assert "Retry-After" not in headers
    from dcr_tpu.serve.queue import InvalidRequestError

    code, payload, _ = admission_response(InvalidRequestError("bad steps"))
    assert code == 400 and "bad steps" in payload["error"]


@pytest.mark.fast
def test_retryable_item_error_classification():
    """Worker-state rejections (drain, local overload) requeue on survivors;
    request-shaped failures are terminal wherever they run."""
    from dcr_tpu.serve.supervisor import retryable_item_error

    assert retryable_item_error("DrainingError: service is draining")
    assert retryable_item_error("QueueFullError: queue is full")
    assert not retryable_item_error("InvalidRequestError: bad steps")
    assert not retryable_item_error("BucketLimitError: budget")
    assert not retryable_item_error("RuntimeError: generation failed")


@pytest.mark.fast
def test_rejected_request_does_not_leak_bucket_slot(tmp_path):
    """A novel bucket on a request the queue then rejects must not consume a
    max_compiled_buckets slot forever (no worker ever compiled it)."""
    from dcr_tpu.serve.queue import BucketLimitError
    from dcr_tpu.serve.supervisor import FleetSupervisor

    cfg = ServeConfig(resolution=16, num_inference_steps=2, sampler="ddim",
                      queue_depth=1, max_compiled_buckets=2,
                      fleet=FleetConfig(workers=1, dir=str(tmp_path)))
    sup = FleetSupervisor(cfg)        # not started: no subprocesses
    sup._vae_scale = 8                # pretend a worker joined
    sup.submit("a", seed=0)           # default bucket fills the queue
    novel = _bucket(steps=7)
    with pytest.raises(QueueFullError):
        sup.submit("b", seed=1, bucket=novel)
    assert novel not in sup._admitted_buckets     # slot rolled back
    # the budget's second slot is still available to an admitted bucket
    sup.queue.take_group(8)
    sup.submit("c", seed=2, bucket=novel)
    assert novel in sup._admitted_buckets
    # and a third distinct bucket now correctly hits the limit
    with pytest.raises(BucketLimitError):
        sup.submit("d", seed=3, bucket=_bucket(steps=9))
    sup.journal.close()


# ---------------------------------------------------------------------------
# kill-a-worker acceptance e2e (slow; serve-chaos CI job)
# ---------------------------------------------------------------------------

def _run_fleet(tmp_path, ckpt, tag, *, faults=None, n_requests=8):
    """One supervisor run: wait for both workers, POST n_requests, SIGTERM,
    return ({(prompt, seed): (png_b64, w, h)}, journal replay counts)."""
    import signal
    import subprocess
    import sys
    from concurrent.futures import ThreadPoolExecutor

    from dcr_tpu.core.coordination import EXIT_PREEMPTED
    from tests.test_serve import _get, _post_generate, _serve_env
    from tests._multiproc import free_port

    env, repo = _serve_env()
    if faults:
        # inherited by the spawned workers (the supervisor process itself
        # never reaches a serve-batch fault hook); @rank= targets the worker
        # index via the DCR_WORKER_INDEX the supervisor exports
        env["DCR_FAULTS"] = faults
    fleet_dir = tmp_path / f"fleet_{tag}"
    port = free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "dcr_tpu.cli.serve",
         f"--model_path={ckpt}", f"--port={port}",
         "--resolution=16", "--num_inference_steps=2", "--sampler=ddim",
         "--max_batch=2", "--max_wait_ms=60", "--queue_depth=64",
         "--request_timeout_s=300", "--seed=0",
         "--fleet.workers=2", f"--fleet.dir={fleet_dir}",
         "--fleet.heartbeat_s=0.5", "--fleet.lease_s=3",
         "--fleet.dispatch_timeout_s=240", "--fleet.spawn_timeout_s=240",
         "--fleet.max_attempts=6", "--fleet.respawn_max=2",
         "--fleet.respawn_base_delay_s=2"],
        env=env, cwd=repo, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        deadline = time.monotonic() + 300
        while True:
            try:
                _, health = _get(port, "/healthz", timeout=2)
                _, status = _get(port, "/metrics", timeout=2)
                if (health["status"] == "ok"
                        and status["workers_alive"] == 2):
                    break
            except OSError:
                pass
            if proc.poll() is not None or time.monotonic() > deadline:
                out = proc.stdout.read() if proc.stdout else ""
                raise AssertionError(
                    f"fleet did not come up (rc={proc.poll()}): {out[-4000:]}")
            time.sleep(0.5)

        prompts = ["a red square", "a blue circle"] * (n_requests // 2)
        with ThreadPoolExecutor(max_workers=n_requests) as ex:
            results = list(ex.map(
                lambda a: (a, _post_generate(port, a[1], seed=a[0],
                                             timeout=280)),
                enumerate(prompts)))
        responses = {}
        for (seed, prompt), (code, doc) in results:
            assert code == 200, (code, doc)
            responses[(prompt, seed)] = (doc["image_png_b64"], doc["width"],
                                         doc["height"])

        _, status = _get(port, "/metrics", timeout=10)
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=180)
        out = proc.stdout.read() if proc.stdout else ""
        assert rc == EXIT_PREEMPTED, (rc, out[-4000:])
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    from dcr_tpu.serve.fleet import RequestJournal

    replay = RequestJournal.replay(fleet_dir / "journal.jsonl")
    return responses, replay["counts"], status


@pytest.mark.slow
def test_fleet_kill_worker_zero_drops_bit_identical(tmp_path, cpu_devices):
    """Acceptance: two workers, worker 0 SIGKILLed (injected worker_crash)
    on every batch it ever touches — its in-flight requests are requeued
    onto worker 1 and every accepted request completes, bit-identical to an
    uninjected fleet, with the durable journal replaying to zero drops."""
    from tests.test_serve import _export_tiny_ckpt

    ckpt = _export_tiny_ckpt(tmp_path)

    clean, clean_counts, _ = _run_fleet(tmp_path, ckpt, "clean")
    assert clean_counts["dropped"] == 0 and clean_counts["failed"] == 0
    assert clean_counts["accepted"] == 8 and clean_counts["acked"] == 8

    chaos, chaos_counts, status = _run_fleet(
        tmp_path, ckpt, "chaos", faults="worker_crash@batch=0&rank=0")
    # zero dropped accepted requests, none failed, despite real SIGKILLs
    assert chaos_counts["dropped"] == 0, chaos_counts
    assert chaos_counts["failed"] == 0, chaos_counts
    assert chaos_counts["accepted"] == 8 and chaos_counts["acked"] == 8
    # the crash actually happened and the requeue path actually ran
    assert chaos_counts["requeued_total"] >= 1, chaos_counts
    assert status["fleet"].get("workers_lost", 0) >= 1, status["fleet"]
    # bit-identical responses: an image is a pure function of (ckpt, prompt,
    # seed, bucket) — which worker (or which incarnation) rendered it is
    # invisible to the client
    assert set(chaos) == set(clean)
    for job in clean:
        assert chaos[job] == clean[job], f"response diverged for {job}"

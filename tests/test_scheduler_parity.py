"""Cross-framework sampler parity: dcr_tpu schedulers vs an independent NumPy
transcription of the diffusers step semantics (tests/fixtures/
reference_schedulers.py). Covers VERDICT round-1 item 6: trajectory-level
evidence that our DDIM / DPM-Solver++(2M) step math matches the reference
pipeline's scheduler (diff_inference.py:93), not just self-consistency.
"""

import jax
import jax.numpy as jnp
import numpy as np

from dcr_tpu.models import schedulers as S
from tests.fixtures.reference_schedulers import (
    RefDDIMScheduler,
    RefDPMSolverMultistepScheduler,
)

import pytest

pytestmark = pytest.mark.fast

SHAPE = (1, 4, 4, 2)


def _fake_model(prediction_type: str):
    """Deterministic stand-in for the UNet: shape-preserving, t-dependent,
    identical bits on both sides (defined in float64 numpy)."""
    rs = np.random.RandomState(0)
    field = rs.randn(*SHAPE)

    def fn(x: np.ndarray, t: int) -> np.ndarray:
        return 0.3 * x + np.sin(t / 100.0) * field + 0.05

    return fn


def _init_latent():
    return np.random.RandomState(1).randn(*SHAPE)


def _run_ours_ddim(n_steps, prediction_type, model):
    s = S.make_schedule(prediction_type=prediction_type)
    ts = np.asarray(S.inference_timesteps(s, n_steps, spacing="leading"))
    # final prev_t=0 == diffusers set_alpha_to_one=False (sampler.py contract)
    prev = np.concatenate([ts[1:], [0]]).astype(np.int32)
    x = jnp.asarray(_init_latent(), jnp.float32)
    for i, t in enumerate(ts):
        out = jnp.asarray(model(np.asarray(x, np.float64), int(t)), jnp.float32)
        x = S.ddim_step(s, out, x, jnp.asarray(int(t)), jnp.asarray(int(prev[i])))
    return np.asarray(x)


def _run_ref_ddim(n_steps, prediction_type, model):
    ref = RefDDIMScheduler(prediction_type=prediction_type)
    ref.set_timesteps(n_steps)
    x = _init_latent()
    for t in ref.timesteps:
        x = ref.step(model(x, int(t)), int(t), x)
    return x


def _run_ours_dpm(n_steps, prediction_type, model):
    s = S.make_schedule(prediction_type=prediction_type)
    ts = np.asarray(S.inference_timesteps(s, n_steps, spacing="linspace"))
    prev = np.concatenate([ts[1:], [0]]).astype(np.int32)
    x = jnp.asarray(_init_latent(), jnp.float32)
    state = S.dpm_init_state(SHAPE)
    for i, t in enumerate(ts):
        out = jnp.asarray(model(np.asarray(x, np.float64), int(t)), jnp.float32)
        force1 = (n_steps < 15) and i == len(ts) - 1
        x, state = S.dpmpp_2m_step(s, out, x, jnp.asarray(int(t)),
                                   jnp.asarray(int(prev[i])), state,
                                   force_first_order=force1)
    return np.asarray(x)


def _run_ref_dpm(n_steps, prediction_type, model):
    ref = RefDPMSolverMultistepScheduler(prediction_type=prediction_type)
    ref.set_timesteps(n_steps)
    x = _init_latent()
    for t in ref.timesteps:
        x = ref.step(model(x, int(t)), int(t), x)
    return x


def test_timestep_grid_parity_leading():
    s = S.make_schedule()
    ref = RefDDIMScheduler()
    for n in (5, 10, 50):
        ref.set_timesteps(n)
        ours = np.asarray(S.inference_timesteps(s, n, spacing="leading"))
        np.testing.assert_array_equal(ours, ref.timesteps)


def test_timestep_grid_parity_linspace():
    s = S.make_schedule()
    ref = RefDPMSolverMultistepScheduler()
    for n in (5, 20, 50):
        ref.set_timesteps(n)
        ours = np.asarray(S.inference_timesteps(s, n, spacing="linspace"))
        np.testing.assert_array_equal(ours, ref.timesteps)


def test_ddim_trajectory_matches_reference_eps():
    model = _fake_model("epsilon")
    ours = _run_ours_ddim(5, "epsilon", model)
    ref = _run_ref_ddim(5, "epsilon", model)
    np.testing.assert_allclose(ours, ref, atol=2e-3, rtol=2e-3)


def test_ddim_trajectory_matches_reference_vpred():
    model = _fake_model("v_prediction")
    ours = _run_ours_ddim(5, "v_prediction", model)
    ref = _run_ref_ddim(5, "v_prediction", model)
    np.testing.assert_allclose(ours, ref, atol=2e-3, rtol=2e-3)


def test_dpmpp_trajectory_matches_reference_short():
    """5 steps: exercises first-order bootstrap AND lower_order_final."""
    model = _fake_model("epsilon")
    ours = _run_ours_dpm(5, "epsilon", model)
    ref = _run_ref_dpm(5, "epsilon", model)
    np.testing.assert_allclose(ours, ref, atol=2e-3, rtol=2e-3)


def test_dpmpp_trajectory_matches_reference_long():
    """20 steps (>=15): pure 2M multistep path, no lower_order_final."""
    model = _fake_model("epsilon")
    ours = _run_ours_dpm(20, "epsilon", model)
    ref = _run_ref_dpm(20, "epsilon", model)
    np.testing.assert_allclose(ours, ref, atol=2e-3, rtol=2e-3)


def test_ddpm_grid_has_no_offset():
    """diffusers' DDPMScheduler applies no steps_offset (unlike DDIM/PNDM)."""
    s = S.make_schedule()
    ours = np.asarray(S.inference_timesteps(s, 50, spacing="leading",
                                            steps_offset=0))
    expected = (np.arange(50) * 20).round()[::-1].astype(np.int64)
    np.testing.assert_array_equal(ours, expected)


def test_sampler_grid_production_mapping():
    """The production per-sampler wiring (sampler_grid) — not a re-derivation —
    must match the reference fixture grids and final-step targets."""
    from dcr_tpu.sampling.sampler import sampler_grid

    s = S.make_schedule()
    ref_dpm = RefDPMSolverMultistepScheduler()
    ref_dpm.set_timesteps(5)
    ts, prev, lof = sampler_grid("dpm++", s, 5)
    np.testing.assert_array_equal(np.asarray(ts), ref_dpm.timesteps)
    assert int(prev[-1]) == 0 and lof  # t=0 final target, lower_order_final

    ref_ddim = RefDDIMScheduler()
    ref_ddim.set_timesteps(50)
    ts, prev, lof = sampler_grid("ddim", s, 50)
    np.testing.assert_array_equal(np.asarray(ts), ref_ddim.timesteps)
    assert int(prev[-1]) == 0 and not lof

    ts, prev, _ = sampler_grid("ddpm", s, 50)
    assert int(ts[-1]) == 0 and int(prev[-1]) == -1  # no offset; acp=1 terminal


def test_dpmpp_trajectory_matches_reference_vpred():
    """SD 2.1 actually runs v_prediction through DPMSolverMultistep."""
    model = _fake_model("v_prediction")
    ours = _run_ours_dpm(5, "v_prediction", model)
    ref = _run_ref_dpm(5, "v_prediction", model)
    np.testing.assert_allclose(ours, ref, atol=2e-3, rtol=2e-3)

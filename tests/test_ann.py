"""dcr-ann acceptance: IVF + int8 approximate search tier (ISSUE 19).

The correctness matrix for search/ann.py + search/annindex.py:

1. training determinism — same seed + same shards produce BIT-IDENTICAL
   centroids and assignment (the one-hot-matmul Lloyd step, no scatter);
2. incremental folds — append-then-fold rewrites ONLY the affected lists
   (untouched manifest entries keep their exact file + sha256), and
   compaction drives the same fold through the live tier;
3. fault drills — ``ivf_list_corrupt@load=N`` lands quarantine + counter
   + rebuild-from-store; ``kmeans_nan@iter=N`` lands the bounded
   seed-shifted restart (and the typed failure when exhausted);
4. the query contract — shortlist re-rank scores are EXACT f32 dots,
   recall vs the exact oracle, ann-off bit-identity (the exact engine
   must not notice an ann tier on disk), and 8-way mesh == 1-device;
5. the operator surface — train-ivf/stats/query --ann CLI, the three-tier
   stats payload, trace schema + report, and the banked BENCH_ANN gate.
"""

import json
import shutil
from pathlib import Path

import numpy as np
import pytest

from dcr_tpu.core import tracing
from dcr_tpu.search import ann
from dcr_tpu.search.annindex import (AnnEngine, open_ann_engine,
                                     spot_check_recall)
from dcr_tpu.search.livestore import LiveStore
from dcr_tpu.search.shardindex import open_engine
from dcr_tpu.search.store import EmbeddingStoreReader, EmbeddingStoreWriter
from dcr_tpu.utils import faults

DIM = 16


def _counter(name: str) -> int:
    return tracing.registry().counters("ann/").get(name, 0)


def _clustered(rng, rows, clusters=8, dim=DIM, noise=0.1):
    centers = rng.standard_normal((clusters, dim)).astype(np.float32) * 4.0
    assign = rng.integers(0, clusters, rows)
    return (centers[assign]
            + rng.standard_normal((rows, dim)).astype(np.float32) * noise)


def _store(path, feats, *, shard_rows=64, normalize=False, prefix="r"):
    w = EmbeddingStoreWriter(path, embed_dim=feats.shape[1],
                             shard_rows=shard_rows, normalize=normalize)
    w.add(feats, [f"{prefix}{i}" for i in range(feats.shape[0])])
    w.finalize()
    return path


# ---------------------------------------------------------------------------
# 1. training determinism + storage discipline
# ---------------------------------------------------------------------------

def test_kmeans_training_is_bit_deterministic(tmp_path, rng_np):
    feats = _clustered(rng_np, 200)
    a = _store(tmp_path / "a", feats)
    b = _store(tmp_path / "b", feats)
    ra = ann.train_ivf(a, n_lists=8, iters=6, seed=7)
    rb = ann.train_ivf(b, n_lists=8, iters=6, seed=7)
    assert ra["rows"] == rb["rows"] == 200
    ca = ann.AnnIndexReader(a).load_centroids()
    cb = ann.AnnIndexReader(b).load_centroids()
    np.testing.assert_array_equal(ca, cb)          # bit-identical centroids
    np.testing.assert_array_equal(ann.assign_rows(feats, ca),
                                  ann.assign_rows(feats, cb))


@pytest.mark.fast
def test_int8_codes_roundtrip_within_scale(rng_np):
    feats = rng_np.standard_normal((50, DIM)).astype(np.float32) * 3
    codes, scale, zero = ann.quantize_list(feats)
    assert codes.dtype == np.int8
    assert np.abs(codes).max() <= 127
    back = ann.dequantize(codes, scale, zero)
    assert np.abs(back - feats).max() <= scale * 0.5 + 1e-6


def test_train_commits_current_flip_and_stats(tmp_path, rng_np):
    store = _store(tmp_path / "s", _clustered(rng_np, 120))
    assert not ann.has_ann_index(store)
    assert ann.ann_stats(store) is None
    report = ann.train_ivf(store, n_lists=4, iters=3, seed=0)
    adir = store / "ann"
    assert (adir / "CURRENT").read_text().strip() == "ann_manifest.v1.json"
    assert (adir / "ann_manifest.v1.json").exists()
    assert ann.has_ann_index(store) and ann.ann_snapshot_version(store) == 1
    stats = ann.ann_stats(store)
    assert stats["rows"] == 120 and stats["n_lists"] == 4
    assert stats["snapshot"] == 1 and stats["seed"] == 0
    assert report["nonempty_lists"] == stats["nonempty_lists"]
    # every nonempty list sha256-verifies clean
    assert ann.AnnIndexReader(store).verify()["corrupt"] == 0


def test_fold_rewrites_only_affected_lists(tmp_path, rng_np):
    """The drift pin: appending rows near ONE centroid must rewrite only
    that centroid's list — every other manifest entry keeps its exact
    file name and sha256 (and therefore its bytes on disk)."""
    store = _store(tmp_path / "s", _clustered(rng_np, 160))
    ann.train_ivf(store, n_lists=8, iters=4, seed=1)
    before = {int(e["list"]): (e["file"], e["sha256"])
              for e in ann.read_ann_manifest(store)["lists"]}
    centroids = ann.AnnIndexReader(store).load_centroids()
    new = (centroids[[3, 3, 3]]
           + rng_np.standard_normal((3, DIM)).astype(np.float32) * 1e-3)
    target = ann.assign_rows(new, centroids)
    assert (target == target[0]).all()             # all land in one list
    rep = ann.fold_rows(store, new.astype(np.float32), ["n0", "n1", "n2"])
    assert rep["lists_rewritten"] == 1 and rep["snapshot"] == 2
    after = {int(e["list"]): (e["file"], e["sha256"])
             for e in ann.read_ann_manifest(store)["lists"]}
    moved = int(target[0])
    for lid, entry in before.items():
        if lid == moved:
            assert after[lid] != entry             # rewritten under v2
            assert after[lid][0].endswith("_v2.npz")
        else:
            assert after[lid] == entry             # byte-identical entry
    assert ann.AnnIndexReader(store).total == 163


# ---------------------------------------------------------------------------
# 2. fault drills
# ---------------------------------------------------------------------------

def test_ivf_list_corrupt_quarantines_counts_and_rebuilds(tmp_path, rng_np):
    store = _store(tmp_path / "s", _clustered(rng_np, 100))
    ann.train_ivf(store, n_lists=4, iters=3, seed=0)
    reader = ann.AnnIndexReader(store)
    entry = next(e for e in reader.manifest["lists"] if e["count"])
    before = _counter("ann/ivf_list_corrupt")
    faults.install(f"ivf_list_corrupt@load=0")
    try:
        assert reader.load_list(entry) is None
    finally:
        faults.clear()
    assert _counter("ann/ivf_list_corrupt") == before + 1
    assert int(entry["list"]) in reader.failed_lists
    quarantined = list((store / "ann").glob("*.quarantine*"))
    assert quarantined, "damaged list must be quarantine-renamed"
    # rebuild-from-store re-derives the same rows under a new snapshot
    rep = ann.rebuild_list(store, int(entry["list"]))
    assert rep["rows"] == int(entry["count"])
    fresh = ann.AnnIndexReader(store)
    assert fresh.verify()["corrupt"] == 0
    assert fresh.total == 100


def test_kmeans_nan_fault_restarts_bounded(tmp_path, rng_np):
    store = _store(tmp_path / "s", _clustered(rng_np, 80))
    faults.install("kmeans_nan@iter=1")
    try:
        report = ann.train_ivf(store, n_lists=4, iters=3, seed=0)
    finally:
        faults.clear()
    assert report["restarts"] == 1                 # poisoned once, recovered
    assert ann.AnnIndexReader(store).verify()["corrupt"] == 0
    # exhausting every restart raises the typed error, commits nothing
    store2 = _store(tmp_path / "s2", _clustered(rng_np, 80))
    faults.install(f"kmeans_nan@iter=0x{ann.MAX_KMEANS_RESTARTS + 1}")
    try:
        with pytest.raises(ann.AnnError, match="non-finite"):
            ann.train_ivf(store2, n_lists=4, iters=3, seed=0)
    finally:
        faults.clear()
    assert not ann.has_ann_index(store2)


def test_engine_rebuilds_corrupt_list_on_build(tmp_path, rng_np):
    """A list damaged on disk degrades to a rebuild at engine build time —
    queries still see every committed row."""
    feats = _clustered(rng_np, 90)
    store = _store(tmp_path / "s", feats)
    ann.train_ivf(store, n_lists=4, iters=3, seed=0)
    entry = next(e for e in ann.read_ann_manifest(store)["lists"]
                 if e["count"])
    path = store / "ann" / entry["file"]
    path.write_bytes(b"rotten" + path.read_bytes()[6:])
    engine = open_ann_engine(store, top_k=1, nprobe=4, query_batch=8)
    assert engine.total == 90
    scores, keys = engine.query(feats[:4])
    exact = feats @ feats[:4].T
    for i in range(4):
        assert keys[i][0] == f"r{int(exact[:, i].argmax())}"


# ---------------------------------------------------------------------------
# 3. the query contract
# ---------------------------------------------------------------------------

def test_rerank_scores_are_exact_dots_and_recall_high(tmp_path, rng_np):
    feats = _clustered(rng_np, 300)
    store = _store(tmp_path / "s", feats)
    ann.train_ivf(store, n_lists=8, iters=5, seed=0)
    engine = open_ann_engine(store, top_k=5, nprobe=4, query_batch=16)
    q = (feats[:20] + 0.01).astype(np.float32)
    scores, keys = engine.query(q)
    # re-rank is exact f32: every returned score IS the true dot product
    for i in range(q.shape[0]):
        for j in range(5):
            row = int(str(keys[i][j])[1:])
            np.testing.assert_allclose(
                scores[i][j], np.float32(q[i] @ feats[row]), rtol=1e-6)
    exact = open_engine(store, top_k=10, query_batch=16)
    recall = spot_check_recall(engine, exact, q, k=5)
    assert recall >= 0.95


def test_ann_off_is_bit_identical_with_ann_tier_on_disk(tmp_path, rng_np):
    """The exact path must not notice <store>/ann/ existing: scores AND
    keys bit-equal before and after training the IVF tier."""
    feats = _clustered(rng_np, 150)
    store = _store(tmp_path / "s", feats)
    q = (feats[:10] + 0.02).astype(np.float32)
    e1 = open_engine(store, top_k=3, query_batch=8)
    s1, k1 = e1.query(q)
    ann.train_ivf(store, n_lists=4, iters=3, seed=0)
    e2 = open_engine(store, top_k=3, query_batch=8)
    s2, k2 = e2.query(q)
    np.testing.assert_array_equal(s1, s2)
    assert (k1 == k2).all()


def test_mesh_sharded_ann_equals_single_device(tmp_path, rng_np,
                                               cpu_devices):
    from dcr_tpu.core.config import MeshConfig
    from dcr_tpu.parallel import mesh as pmesh

    feats = _clustered(rng_np, 200)
    store = _store(tmp_path / "s", feats)
    ann.train_ivf(store, n_lists=8, iters=4, seed=0)
    q = (feats[:12] + 0.01).astype(np.float32)
    one = open_ann_engine(store, top_k=4, nprobe=4, query_batch=8)
    s1, k1 = one.query(q)
    mesh8 = pmesh.make_mesh(MeshConfig(data=8))
    eight = open_ann_engine(store, mesh=mesh8, top_k=4, nprobe=4,
                            query_batch=8)
    s8, k8 = eight.query(q)
    # 8-way row sharding never splits the contraction axis: bit-equal
    np.testing.assert_array_equal(s1, s8)
    assert (k1 == k8).all()
    assert eight.segment_rows % 8 == 0


def test_query_rows_tail_scan_is_exact(tmp_path, rng_np):
    """The live-tail path: tail rows (in no inverted list) scan exactly
    through the re-rank program."""
    feats = _clustered(rng_np, 120)
    store = _store(tmp_path / "s", feats)
    ann.train_ivf(store, n_lists=4, iters=3, seed=0)
    engine = open_ann_engine(store, top_k=2, nprobe=2, query_batch=4)
    tail = rng_np.standard_normal((7, DIM)).astype(np.float32)
    q = tail[:3] + 0.001
    scores, keys = engine.query_rows(q, tail, [f"t{i}" for i in range(7)])
    exact = q @ tail.T
    for i in range(3):
        assert keys[i][0] == f"t{int(exact[i].argmax())}"
        np.testing.assert_allclose(scores[i][0], exact[i].max(), rtol=1e-6)


def test_engine_refuses_width_mismatch_and_raw_rows_for_cosine(
        tmp_path, rng_np):
    feats = _clustered(rng_np, 60)
    store = _store(tmp_path / "s", feats)
    ann.train_ivf(store, n_lists=4, iters=2, seed=0)
    with pytest.raises(ann.AnnError, match="ivf_normalize"):
        AnnEngine(store, require_normalized_rows=True)
    # a normalized-trained index satisfies the cosine consumer
    store2 = _store(tmp_path / "s2", _clustered(rng_np, 60), normalize=True)
    ann.train_ivf(store2, n_lists=4, iters=2, seed=0, normalize=True)
    AnnEngine(store2, require_normalized_rows=True)


# ---------------------------------------------------------------------------
# 4. live-tier integration: compaction folds into lists
# ---------------------------------------------------------------------------

def test_compaction_folds_wal_rows_into_lists(tmp_path, rng_np):
    feats = _clustered(rng_np, 100)
    store = _store(tmp_path / "s", feats, shard_rows=32)
    ann.train_ivf(store, n_lists=4, iters=3, seed=0)
    before = {int(e["list"]): (e["file"], e["sha256"])
              for e in ann.read_ann_manifest(store)["lists"]}
    centroids = ann.AnnIndexReader(store).load_centroids()
    new = (centroids[[1, 1]]
           + rng_np.standard_normal((2, DIM)).astype(np.float32) * 1e-3)
    with LiveStore.open(store) as live:
        live.append(new.astype(np.float32), ["w0", "w1"])
        rep = live.compact()
    assert rep["ann_lists_folded"] == 1
    after = {int(e["list"]): (e["file"], e["sha256"])
             for e in ann.read_ann_manifest(store)["lists"]}
    assert sum(1 for lid in before if after[lid] != before[lid]) == 1
    assert ann.AnnIndexReader(store).total == 102
    # the folded rows are servable through the ann path: top-1 matches a
    # brute-force oracle over committed + folded rows (dot-product metric,
    # so the oracle is argmax, not "the appended row itself")
    engine = open_ann_engine(store, top_k=1, nprobe=4, query_batch=4)
    allf = np.concatenate([feats, new.astype(np.float32)])
    allk = [f"r{i}" for i in range(100)] + ["w0", "w1"]
    q = new.astype(np.float32)
    _, keys = engine.query(q)
    want = (q @ allf.T).argmax(axis=1)
    assert [str(keys[i][0]) for i in range(2)] == [allk[j] for j in want]


def test_compact_without_ann_tier_reports_zero_folds(tmp_path, rng_np):
    with LiveStore.open(tmp_path / "s", embed_dim=DIM) as live:
        live.append(rng_np.standard_normal((3, DIM)).astype(np.float32),
                    ["a", "b", "c"])
        rep = live.compact()
    assert rep["ann_lists_folded"] == 0
    assert not ann.has_ann_index(tmp_path / "s")


# ---------------------------------------------------------------------------
# 5. operator surface: CLI, stats, schema, banked bench
# ---------------------------------------------------------------------------

def test_cli_train_ivf_stats_and_query_ann(tmp_path, rng_np, capsys):
    from dcr_tpu.cli.search import main as cli_main, store_stats
    from dcr_tpu.search.embed import save_embeddings

    feats = _clustered(rng_np, 120)
    store = _store(tmp_path / "s", feats)
    st = store_stats(store)
    assert st["ann"] is None and st["committed"]["rows"] == 120
    cli_main(["train-ivf", f"--store_dir={store}",
              "--n_lists=4", "--ivf_iters=3"])
    out = json.loads(capsys.readouterr().out)
    assert out["snapshot"] == 1 and out["rows"] == 120
    cli_main(["stats", f"--store_dir={store}"])
    text = capsys.readouterr().out
    assert "committed  120 rows" in text
    assert "ann        120 rows in 4/4 lists" in text
    cli_main(["stats", f"--store_dir={store}", "--json_out=true"])
    doc = json.loads(capsys.readouterr().out)
    assert doc["ann"]["rows"] == 120 and doc["live"]["tail_rows"] == 0
    # query --ann end to end, against the exact path on the same gen set
    gen_dir = tmp_path / "gen"
    gen_dir.mkdir()
    q = (feats[:6] + 0.01).astype(np.float32)
    save_embeddings(gen_dir / "embedding.npz", q,
                    [f"g{i}" for i in range(6)])
    cli_main(["query", f"--store_dir={store}", f"--gen_folder={gen_dir}",
              f"--out_path={tmp_path / 'exact.npz'}", "--top_k=3"])
    cli_main(["query", f"--store_dir={store}", f"--gen_folder={gen_dir}",
              f"--out_path={tmp_path / 'ann.npz'}", "--top_k=3",
              "--ann=true", "--nprobe=4"])
    capsys.readouterr()
    with np.load(tmp_path / "exact.npz", allow_pickle=True) as ze, \
            np.load(tmp_path / "ann.npz", allow_pickle=True) as za:
        assert (ze["keys"][:, 0] == za["keys"][:, 0]).all()


@pytest.mark.fast
def test_ann_fault_kinds_are_documented():
    doc = faults.__doc__
    for kind in ("ivf_list_corrupt", "kmeans_nan"):
        assert f"``{kind}``" in doc, f"{kind} missing from faults registry"


@pytest.mark.fast
def test_trace_schema_and_report_know_ann():
    from tools import trace_report

    schema = json.loads(
        (Path(__file__).parent.parent / "tools" /
         "trace_schema.json").read_text())
    for name in ("search/kmeans", "search/ivf_scan", "search/ivf_rerank",
                 "search/ivf_merge"):
        assert name in schema["known_names"]["spans"]
    assert "ann/*" in schema["known_names"]["events"]
    records = [
        {"ph": "X", "name": "search/ivf_scan", "id": 1, "ts": 1e6,
         "dur": 800.0, "pid": 1, "tid": 1, "tname": "t",
         "args": {"segment": 0, "batch": 8, "nprobe": 4, "lists": 3,
                  "rows": 512, "index_size": 4096}},
        {"ph": "X", "name": "search/ivf_rerank", "id": 2, "ts": 2e6,
         "dur": 300.0, "pid": 1, "tid": 1, "tname": "t",
         "args": {"candidates": 40, "batch": 8}},
        {"ph": "X", "name": "search/kmeans", "id": 3, "ts": 3e6,
         "dur": 1500.0, "pid": 1, "tid": 1, "tname": "t",
         "args": {"iter": 0, "restart": 0}},
        {"ph": "i", "name": "ann/query_funnel", "id": 4, "ts": 4e6,
         "pid": 1, "tid": 1, "tname": "t",
         "args": {"batch": 8, "nprobe": 4, "lists_probed": 6,
                  "segments_scanned": 2, "segments_skipped": 6,
                  "shortlist": 64, "top_k": 5}},
        {"ph": "i", "name": "ann/recall_spot_check", "id": 5, "ts": 5e6,
         "pid": 1, "tid": 1, "tname": "t",
         "args": {"k": 10, "queries": 8, "recall": 0.98, "nprobe": 4}},
    ]
    summary = trace_report.ann_summary(records)
    assert summary["scan"]["segment_scans"] == 1
    assert summary["scan"]["nprobe_distribution"] == {"4": 1}
    assert summary["funnel"]["segment_skip_pct"] == 75.0
    assert summary["rerank"]["candidates"] == 40
    assert summary["train"]["lloyd_iters"] == 1
    assert summary["recall_spot_checks"]["mean_recall"] == 0.98
    text = trace_report.render_text(
        trace_report.summarize(records), [Path(".")])
    assert "ANN (IVF approximate search)" in text
    assert "nprobe distribution" in text and "recall spot-check" in text


@pytest.mark.fast
def test_ann_metrics_resolve_to_prometheus_names():
    for name, want in (
            ("ann/ivf_list_corrupt", "dcr_ann_ivf_list_corrupt"),
            ("ann/kmeans_restart", "dcr_ann_kmeans_restart"),
            ("ann/lists_scanned_total", "dcr_ann_lists_scanned_total"),
            ("ann/recall_spot_pct", "dcr_ann_recall_spot_pct")):
        assert tracing.sanitize_metric_name(name) == want


def test_banked_bench_ann_schema():
    from tools.bench_ann import validate_result

    banked = Path(__file__).parent.parent / "BENCH_ANN.json"
    assert banked.exists(), "BENCH_ANN.json must be committed"
    doc = json.loads(banked.read_text())
    assert validate_result(doc) == []
    assert doc["equality"] == {"exact_scores_equal": True,
                               "exact_keys_equal": True}
    assert doc["gate"]["enforced"] is True
    assert doc["gate"]["passed"] is True
    assert doc["gate"]["recall"] >= doc["gate"]["min_recall"]
    assert doc["gate"]["speedup"] >= doc["gate"]["min_speedup"]


@pytest.mark.fast
def test_risk_config_validates_ann_knobs():
    from dcr_tpu.core.config import RiskConfig, validate_risk_config

    with pytest.raises(ValueError, match="risk.ann"):
        validate_risk_config(RiskConfig(ann=True))
    with pytest.raises(ValueError, match="nprobe"):
        validate_risk_config(RiskConfig(ann=True, store_dir="/x", nprobe=0))
    validate_risk_config(RiskConfig(ann=True, store_dir="/x", nprobe=8))

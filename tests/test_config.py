import json

import pytest

pytestmark = pytest.mark.fast

from dcr_tpu.core import config as C


def test_roundtrip(tmp_path):
    cfg = C.TrainConfig()
    cfg.data.class_prompt = "instancelevel_blip"
    cfg.model.block_out_channels = (32, 64)
    p = tmp_path / "config.json"
    C.save_config(cfg, p)
    loaded = C.load_config(C.TrainConfig, p)
    assert loaded == cfg
    assert isinstance(loaded.model.block_out_channels, tuple)


def test_cli_overrides():
    cfg = C.parse_cli(
        C.TrainConfig,
        [
            "--train_batch_size=4",
            "--data.duplication=dup_both",
            "--data.weight_pc=0.25",
            "--model.block_out_channels=32,64",
            "--optim.learning_rate=1e-5",
            "--train_text_encoder=true",
        ],
    )
    assert cfg.train_batch_size == 4
    assert cfg.data.duplication == "dup_both"
    assert cfg.data.weight_pc == 0.25
    assert cfg.model.block_out_channels == (32, 64)
    assert cfg.optim.learning_rate == 1e-5
    assert cfg.train_text_encoder is True


def test_cli_unknown_key_rejected():
    with pytest.raises(KeyError):
        C.parse_cli(C.TrainConfig, ["--nonsense=1"])


def test_config_from_file(tmp_path):
    p = tmp_path / "c.json"
    p.write_text(json.dumps({"seed": 7, "data": {"resolution": 64}}))
    cfg = C.parse_cli(C.TrainConfig, [f"--config={p}", "--seed=9"])
    assert cfg.seed == 9
    assert cfg.data.resolution == 64


def test_run_name_encodes_regimes():
    cfg = C.TrainConfig()
    cfg.data.class_prompt = "instancelevel_blip"
    cfg.data.duplication = "dup_both"
    cfg.data.weight_pc = 0.2
    cfg.data.dup_weight = 10
    cfg.mixup_noise_lam = 0.5
    name = C.run_name(cfg)
    assert "instancelevel_blip" in name and "dup_both" in name
    assert "0.2" in name and "10" in name and "mixlam0.5" in name


def test_validation_rules():
    cfg = C.TrainConfig()
    cfg.data.duplication = "dup_image"
    cfg.data.class_prompt = "instancelevel_ogcap"
    with pytest.raises(ValueError):
        C.validate_train_config(cfg)
    cfg2 = C.TrainConfig()
    cfg2.data.trainspecial = "allcaps"
    cfg2.data.class_prompt = "nolevel"
    with pytest.raises(ValueError):
        C.validate_train_config(cfg2)
    cfg3 = C.TrainConfig()
    cfg3.data.trainspecial = "allcaps"
    cfg3.data.class_prompt = "instancelevel_blip"
    C.validate_train_config(cfg3)  # ok


def test_mesh_axis_sizes():
    m = C.MeshConfig(data=-1, fsdp=2, tensor=1)
    assert m.axis_sizes(8) == (4, 2, 1, 1)
    assert C.MeshConfig(data=-1, seq=4).axis_sizes(8) == (2, 1, 1, 4)
    with pytest.raises(ValueError):
        C.MeshConfig(data=3, fsdp=2, tensor=1).axis_sizes(8)


def test_cli_bare_bool_flag():
    cfg = C.parse_cli(C.TrainConfig, ["--train_text_encoder"])
    assert cfg.train_text_encoder is True
    with pytest.raises(ValueError):
        C.parse_cli(C.TrainConfig, ["--train_batch_size"])


def test_cli_config_plus_base_rejected(tmp_path):
    p = tmp_path / "c.json"
    p.write_text("{}")
    with pytest.raises(SystemExit):
        C.parse_cli(C.TrainConfig, [f"--config={p}"], base=C.TrainConfig())

"""Ring attention: exact-match vs single-device attention on an 8-way
sequence-sharded virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcr_tpu.core.config import MeshConfig
from dcr_tpu.ops.attention import dot_product_attention
from dcr_tpu.ops.ring_attention import ring_attention, ring_self_attention
from dcr_tpu.parallel import mesh as pmesh


@pytest.fixture()
def seq_mesh(cpu_devices):
    return pmesh.make_mesh(MeshConfig(data=1, seq=8))


def _qkv(key, b=2, s=64, h=2, d=16, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, s, h, d), dtype) for k in ks)


def test_ring_matches_full_attention(seq_mesh):
    q, k, v = _qkv(jax.random.key(0))
    ref = dot_product_attention(q, k, v, use_flash=False)
    out = ring_self_attention(q, k, v, seq_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


def test_ring_matches_with_data_parallel_too(cpu_devices):
    mesh = pmesh.make_mesh(MeshConfig(data=2, seq=4))
    q, k, v = _qkv(jax.random.key(1), b=4, s=32)
    ref = dot_product_attention(q, k, v, use_flash=False)
    out = ring_self_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


def test_ring_gradients_match(seq_mesh):
    q, k, v = _qkv(jax.random.key(2), b=1, s=32, h=1, d=8)

    def loss_ring(q, k, v):
        return jnp.sum(ring_self_attention(q, k, v, seq_mesh) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, use_flash=False) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4,
                                   rtol=1e-4)


def test_ring_softmax_stability(seq_mesh):
    q, k, v = _qkv(jax.random.key(3))
    q = q * 50.0
    out = ring_self_attention(q, k, v, seq_mesh)
    assert np.isfinite(np.asarray(out)).all()
    ref = dot_product_attention(q, k, v, use_flash=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4,
                               rtol=1e-4)


def test_ring_jit_compiles(seq_mesh):
    q, k, v = _qkv(jax.random.key(4))
    f = jax.jit(lambda q, k, v: ring_self_attention(q, k, v, seq_mesh))
    out = f(q, k, v)
    assert out.shape == q.shape

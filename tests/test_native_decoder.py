"""Native JPEG scaled-decode helper: parity with PIL and fast-path wiring."""

import io

import numpy as np
import pytest
from PIL import Image

from dcr_tpu.native import jpeg_decoder


def _jpeg_bytes(w, h, seed=0, quality=95):
    rng = np.random.default_rng(seed)
    # smooth image so JPEG artifacts are small and PIL-vs-libjpeg comparable
    base = rng.uniform(0, 255, (8, 8, 3))
    img = Image.fromarray(base.astype(np.uint8)).resize((w, h), Image.BILINEAR)
    buf = io.BytesIO()
    img.save(buf, format="JPEG", quality=quality)
    return buf.getvalue(), np.asarray(img)


def test_decode_full_scale_matches_pil():
    data, ref = _jpeg_bytes(64, 48)
    arr = jpeg_decoder.decode_scaled(data, min_side=48)
    if arr is None:
        pytest.skip("native decoder unavailable")
    assert arr.shape == (48, 64, 3)
    pil = np.asarray(Image.open(io.BytesIO(data)).convert("RGB"), np.float32)
    assert np.mean(np.abs(arr.astype(np.float32) - pil)) < 2.0


def test_decode_downscales_but_covers_min_side():
    data, _ = _jpeg_bytes(640, 480)
    arr = jpeg_decoder.decode_scaled(data, min_side=100)
    if arr is None:
        pytest.skip("native decoder unavailable")
    h, w, _ = arr.shape
    assert min(h, w) >= 100
    assert min(h, w) < 480  # actually downscaled during decode


def test_decode_garbage_returns_none():
    assert jpeg_decoder.decode_scaled(b"definitely not a jpeg", 64) is None
    # truncated real jpeg
    data, _ = _jpeg_bytes(64, 64)
    out = jpeg_decoder.decode_scaled(data[:40], 32)
    assert out is None


def test_sof_parser():
    data, _ = _jpeg_bytes(123, 77)
    assert jpeg_decoder._parse_sof_dims(data) == (123, 77)


def test_dataset_fast_path_jpg(tmp_path):
    from dcr_tpu.core.config import DataConfig
    from dcr_tpu.data.dataset import ObjectAttributeDataset
    from dcr_tpu.data.tokenizer import HashTokenizer

    d = tmp_path / "data" / "c"
    d.mkdir(parents=True)
    rng = np.random.default_rng(0)
    for i in range(2):
        Image.fromarray(rng.integers(0, 255, (200, 300, 3), np.uint8)).save(
            d / f"{i}.jpg", quality=95)
    ds = ObjectAttributeDataset(
        DataConfig(train_data_dir=str(tmp_path / "data"), resolution=64,
                   class_prompt="nolevel", num_workers=1),
        HashTokenizer(100, 16))
    ex = ds.get(0)
    assert ex.pixel_values.shape == (64, 64, 3)
    assert np.isfinite(ex.pixel_values).all()

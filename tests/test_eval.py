import numpy as np
import pytest

pytestmark = pytest.mark.fast

from dcr_tpu.eval import complexity as CX
from dcr_tpu.eval import fid as FID
from dcr_tpu.eval import ipr as IPR
from dcr_tpu.eval import similarity as SIM


def test_similarity_dotproduct_matches_numpy(rng_np):
    v = SIM.l2_normalize(rng_np.standard_normal((20, 16)).astype(np.float32))
    q = SIM.l2_normalize(rng_np.standard_normal((7, 16)).astype(np.float32))
    sim = SIM.similarity_matrix(v, q)
    np.testing.assert_allclose(sim, q @ v.T, atol=1e-5)
    # blocked path identical
    sim_b = SIM.similarity_matrix(v, q, block_size=3)
    np.testing.assert_allclose(sim_b, sim, atol=1e-6)


def test_similarity_splitloss(rng_np):
    v = rng_np.standard_normal((5, 8)).astype(np.float32)
    q = rng_np.standard_normal((4, 8)).astype(np.float32)
    sim = SIM.similarity_matrix(v, q, metric="splitloss", num_chunks=2)
    # manual: split into 2 chunks of 4, per-chunk dot, max
    expected = np.maximum(q[:, :4] @ v[:, :4].T, q[:, 4:] @ v[:, 4:].T)
    np.testing.assert_allclose(sim, expected, atol=1e-5)
    with pytest.raises(ValueError):
        SIM.similarity_matrix(v, q, metric="splitloss", num_chunks=3)


def test_similarity_sharded_matches_unsharded(rng_np, cpu_devices):
    """Mesh-sharded similarity (query rows over all 8 virtual devices, values
    replicated — SURVEY §3.5's sharded-matmul design) is bit-compatible with
    the single-device path, including non-divisible row counts (pad+trim) and
    both metrics."""
    from dcr_tpu.core.config import MeshConfig
    from dcr_tpu.parallel import mesh as pmesh

    mesh = pmesh.make_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    v = SIM.l2_normalize(rng_np.standard_normal((20, 16)).astype(np.float32))
    q = SIM.l2_normalize(rng_np.standard_normal((13, 16)).astype(np.float32))
    for kwargs in ({}, {"metric": "splitloss", "num_chunks": 2},
                   {"metric": "splitloss", "num_chunks": 2,
                    "chunk_style": "cross"}):
        plain = SIM.similarity_matrix(v, q, **kwargs)
        sharded = SIM.similarity_matrix(v, q, mesh=mesh, **kwargs)
        np.testing.assert_allclose(sharded, plain, atol=1e-6)
    # background (self-masked) path, rows not divisible by 8 either
    bg = SIM.train_train_background(v)
    bg_sharded = SIM.train_train_background(v, mesh=mesh)
    np.testing.assert_allclose(bg_sharded, bg, atol=1e-6)
    # blocked + sharded composes
    np.testing.assert_allclose(
        SIM.similarity_matrix(v, q, mesh=mesh, block_size=5), plain_dot(v, q),
        atol=1e-6)


def plain_dot(v, q):
    return q @ v.T


def test_gen_train_stats_and_threshold():
    sim = np.array([[0.9, 0.2], [0.3, 0.4], [0.1, 0.05]])
    stats = SIM.gen_train_stats(sim)
    np.testing.assert_allclose(stats.top1, [0.9, 0.4, 0.1])
    np.testing.assert_array_equal(stats.top1_index, [0, 1, 0])
    assert stats.sim_gt_05pc == pytest.approx(1 / 3)
    s = stats.scalars()
    assert set(s) == {"sim_mean", "sim_std", "sim_75pc", "sim_90pc", "sim_95pc",
                      "sim_gt_05pc"}


def test_train_train_background_excludes_self(rng_np):
    v = SIM.l2_normalize(rng_np.standard_normal((10, 8)).astype(np.float32))
    bg = SIM.train_train_background(v)
    full = v @ v.T
    np.fill_diagonal(full, -np.inf)
    np.testing.assert_allclose(bg, full.max(axis=1), atol=1e-5)
    # blocked path
    np.testing.assert_allclose(SIM.train_train_background(v, block_size=3), bg,
                               atol=1e-5)


def test_dup_vs_nondup_means():
    top1 = np.array([0.9, 0.2, 0.6, 0.5])
    idx = np.array([0, 1, 2, 1])
    weights = np.array([5, 1, 5])
    out = SIM.dup_vs_nondup_means(top1, idx, weights)
    assert out["dupsim_mean"] == pytest.approx((0.9 + 0.6) / 2)
    assert out["nondupsim_mean"] == pytest.approx((0.2 + 0.5) / 2)
    assert out["dup_match_fraction"] == pytest.approx(0.5)


def test_frechet_distance_identity_and_shift(rng_np):
    feats = rng_np.standard_normal((500, 8))
    mu, sigma = FID.activation_statistics(feats)
    assert FID.frechet_distance(mu, sigma, mu, sigma) == pytest.approx(0.0, abs=1e-6)
    # pure mean shift by d: FID = d^2 * dim? No: |mu1-mu2|^2 = sum of squares
    mu2 = mu + 2.0
    d = FID.frechet_distance(mu, sigma, mu2, sigma)
    assert d == pytest.approx(4.0 * len(mu), rel=1e-6)


def test_frechet_distance_matches_scipy(rng_np):
    """Our eigh-based trace term must equal scipy.linalg.sqrtm's result."""
    import scipy.linalg

    f1 = rng_np.standard_normal((300, 6))
    f2 = rng_np.standard_normal((300, 6)) @ np.diag([1, 2, 3, 1, 0.5, 1.5]) + 1.0
    mu1, s1 = FID.activation_statistics(f1)
    mu2, s2 = FID.activation_statistics(f2)
    ours = FID.frechet_distance(mu1, s1, mu2, s2)
    covmean = scipy.linalg.sqrtm(s1 @ s2)
    if np.iscomplexobj(covmean):
        covmean = covmean.real
    ref = (np.sum((mu1 - mu2) ** 2) + np.trace(s1) + np.trace(s2)
           - 2 * np.trace(covmean))
    assert ours == pytest.approx(ref, rel=1e-6)


def test_fid_stats_cache(tmp_path, rng_np):
    f1 = rng_np.standard_normal((100, 4))
    f2 = rng_np.standard_normal((100, 4))
    cache = tmp_path / "stats.npz"
    d1 = FID.fid_from_features(f1, f2, cache1=cache)
    assert cache.exists()
    # cache hit: garbage features for side 1 are ignored
    d2 = FID.fid_from_features(np.zeros((10, 4)), f2, cache1=cache)
    assert d1 == pytest.approx(d2)


def test_ipr_precision_recall_identical_sets(rng_np):
    feats = rng_np.standard_normal((50, 8))
    out = IPR.precision_recall(feats, feats.copy())
    assert out["precision"] == 1.0 and out["recall"] == 1.0
    far = feats + 100.0
    out2 = IPR.precision_recall(feats, far)
    assert out2["precision"] == 0.0 and out2["recall"] == 0.0


def test_ipr_realism(rng_np):
    feats = rng_np.standard_normal((50, 8))
    m = IPR.Manifold.build(feats)
    r_in = m.realism(feats[:5] + 0.01)
    r_out = m.realism(feats[:5] + 50.0)
    assert np.all(r_in > r_out)


def test_complexity_measures():
    flat = np.zeros((64, 64, 3), np.uint8)
    noisy = (np.random.default_rng(0).uniform(0, 255, (64, 64, 3))).astype(np.uint8)
    assert CX.shannon_entropy(flat) == pytest.approx(0.0)
    assert CX.shannon_entropy(noisy) > 5.0
    assert CX.jpeg_size(noisy) > CX.jpeg_size(flat)
    assert CX.tv_loss(noisy) > CX.tv_loss(flat)
    corr = CX.pearson([1, 2, 3, 4], [2, 4, 6, 8])
    assert corr == pytest.approx(1.0)
    assert np.isnan(CX.pearson([1, 1], [2, 3]))


def test_streamed_series_dedups_loads_and_matches_direct(rng_np):
    """LAION-scale complexity path: 100k top-1 indices over 8 unique match
    images must decode each unique image exactly once (bounded memory /
    bounded IO) and agree elementwise with the in-memory single-pass path."""
    images = [rng_np.uniform(0, 1, (16, 16, 3)).astype(np.float32)
              for _ in range(8)]
    loads: list[int] = []

    def load(i: int):
        loads.append(i)
        return images[i]

    indices = rng_np.integers(0, 8, size=100_000)
    series = CX.streamed_series(load, indices, workers=4)
    assert sorted(loads) == list(range(8))          # one decode per unique match
    assert all(v.shape == (100_000,) for v in series.values())
    _, direct = CX.complexity_correlations([images[i] for i in indices[:64]],
                                           np.zeros(64))
    for k in ("entropy", "jpeg_bytes", "tv"):
        np.testing.assert_allclose(series[k][:64], direct[k], rtol=1e-12)


def test_streamed_series_empty():
    series = CX.streamed_series(lambda i: None, np.zeros((0,), np.int64))
    assert all(len(v) == 0 for v in series.values())


def test_complexity_correlations_keys(rng_np):
    images = [rng_np.uniform(0, 1, (32, 32, 3)).astype(np.float32) for _ in range(6)]
    sims = rng_np.uniform(0, 1, 6)
    out, series = CX.complexity_correlations(images, sims)
    assert {"corr_entropy_sim", "corr_jpegsize_sim", "corr_tv_sim"} <= set(out)
    assert set(series) == {"entropy", "jpeg_bytes", "tv"}
    assert all(len(v) == 6 for v in series.values())


def test_native_jpeg_helper_matches_pil_scale():
    """If the C++ helper builds, its sizes must track PIL's (same libjpeg)."""
    from dcr_tpu.native import jpeg_helper

    noisy = (np.random.default_rng(1).uniform(0, 255, (48, 48, 3))).astype(np.uint8)
    size = jpeg_helper.encoded_size(noisy, 95)
    if size is None:
        pytest.skip("native helper unavailable in this environment")
    import io

    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(noisy).save(buf, format="JPEG", quality=95)
    assert abs(size - buf.tell()) / buf.tell() < 0.1


def test_splitloss_cross_style(rng_np):
    v = rng_np.standard_normal((3, 8)).astype(np.float32)
    q = rng_np.standard_normal((4, 8)).astype(np.float32)
    sim = SIM.similarity_matrix(v, q, metric="splitloss", num_chunks=2,
                                chunk_style="cross")
    # manual: every chunk pair, max over all four combos
    qc = [q[:, :4], q[:, 4:]]
    vc = [v[:, :4], v[:, 4:]]
    expected = np.max(np.stack([a @ b.T for a in qc for b in vc]), axis=0)
    np.testing.assert_allclose(sim, expected, atol=1e-5)

"""dcr-fast acceptance: plan math, score-reuse semantics, bit-identity of
the disabled path (bulk + serve), serve purity with fast on, trace/report
plumbing, and the BENCH_FASTSAMPLE schema contract."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcr_tpu.core import rng as rngmod
from dcr_tpu.core.config import (FastSampleConfig, MeshConfig, ModelConfig,
                                 SampleConfig, TrainConfig,
                                 validate_fast_config)
from dcr_tpu.data.tokenizer import HashTokenizer
from dcr_tpu.diffusion.trainer import build_models
from dcr_tpu.models import schedulers as S
from dcr_tpu.parallel import mesh as pmesh
from dcr_tpu.sampling import fastsample
from dcr_tpu.sampling.sampler import (encode_prompts, fast_plan_grid,
                                      make_sampler, sampler_grid)
from dcr_tpu.serve.fleet import bucket_from_tuple
from dcr_tpu.serve.queue import GenBucket, InvalidRequestError, Request
from dcr_tpu.serve.worker import validate_bucket


# ---------------------------------------------------------------------------
# plan math (pure host, no compiles)
# ---------------------------------------------------------------------------

def test_fast_plan_invariants():
    for steps in (4, 8, 16, 32, 50, 101):
        for ratio in (0.0, 0.25, 0.5, 0.75):
            plan = fastsample.fast_plan(steps, ratio)
            assert len(plan) == steps
            # first two and final step always full
            assert plan[0] and plan[1] and plan[-1]
            n_reuse = steps - fastsample.unet_calls(plan)
            want = min(int(round(ratio * steps)), max(0, steps - 3))
            assert n_reuse == want
            # deterministic
            assert plan == fastsample.fast_plan(steps, ratio)
    # ratio 0 (or an infeasible trajectory) degrades to dense, never errors
    assert fastsample.is_dense(fastsample.fast_plan(16, 0.0))
    assert fastsample.is_dense(fastsample.fast_plan(3, 0.75))
    assert fastsample.is_dense(fastsample.fast_plan(1, 0.5))
    with pytest.raises(ValueError):
        fastsample.fast_plan(16, 0.9)
    with pytest.raises(ValueError):
        fastsample.fast_plan(16, -0.1)


def test_fast_plan_default_point_hits_acceptance_reduction():
    # the ISSUE 12 floor: the default operating point (ratio 0.5) must save
    # >= 1.8x denoiser calls at realistic step counts
    for steps in (16, 32, 50):
        plan = fastsample.fast_plan(steps, FastSampleConfig().reuse_ratio)
        assert steps / fastsample.unet_calls(plan) >= 1.8


def test_fast_plan_grid_ratio_zero_identical_to_sampler_grid():
    # the satellite contract: a reuse plan with ratio 0 IS sampler_grid —
    # same timestep grids, same lower-order flag, all-full plan
    sched = S.make_schedule()
    for sampler in ("ddim", "dpm++", "ddpm"):
        for steps in (4, 12, 50):
            ts, prev_ts, lof = sampler_grid(sampler, sched, steps)
            fts, fprev, flof, plan = fast_plan_grid(sampler, sched, steps,
                                                    0.0)
            np.testing.assert_array_equal(np.asarray(ts), np.asarray(fts))
            np.testing.assert_array_equal(np.asarray(prev_ts),
                                          np.asarray(fprev))
            assert lof == flof
            assert plan == (True,) * steps
    # and a reuse plan never moves the solver's timestep positions
    ts, prev_ts, lof = sampler_grid("dpm++", sched, 20)
    fts, fprev, flof, plan = fast_plan_grid("dpm++", sched, 20, 0.5)
    np.testing.assert_array_equal(np.asarray(ts), np.asarray(fts))
    np.testing.assert_array_equal(np.asarray(prev_ts), np.asarray(fprev))
    assert not fastsample.is_dense(plan)


def test_score_bank_reuse_and_extrapolation():
    shape = (2, 3)
    bank = fastsample.bank_init(shape)
    assert int(bank.count) == 0
    p1 = jnp.full(shape, 2.0)
    bank = fastsample.bank_update(bank, p1, 100.0)
    # one banked score: both orders fall back to plain reuse
    np.testing.assert_array_equal(fastsample.reuse_score(bank, 80.0, 1), p1)
    np.testing.assert_array_equal(fastsample.reuse_score(bank, 80.0, 2), p1)
    p2 = jnp.full(shape, 3.0)
    bank = fastsample.bank_update(bank, p2, 90.0)
    assert int(bank.count) == 2
    # order 1: still plain reuse of the last score
    np.testing.assert_array_equal(fastsample.reuse_score(bank, 70.0, 1), p2)
    # order 2: linear past-difference extrapolation in timestep space:
    # slope = (3-2)/(90-100) = -0.1; at t=70: 3 + (-0.1)*(70-90) = 5.0
    np.testing.assert_allclose(
        np.asarray(fastsample.reuse_score(bank, 70.0, 2)),
        np.full(shape, 5.0), rtol=1e-6)


def test_validate_fast_config():
    validate_fast_config(FastSampleConfig())
    with pytest.raises(ValueError):
        validate_fast_config(FastSampleConfig(reuse_ratio=0.9))
    with pytest.raises(ValueError):
        validate_fast_config(FastSampleConfig(order=3))


def test_make_sampler_rejects_invalid_fast_config(tiny_models, cpu_devices):
    # the bulk path must reject what serve's validate_bucket rejects: an
    # invalid order silently running as a DIFFERENT order would mislabel
    # every banked fidelity number
    models, _ = tiny_models
    mesh = pmesh.make_mesh(MeshConfig())
    with pytest.raises(ValueError):
        make_sampler(_sample_cfg(fast=FastSampleConfig(
            enabled=True, order=3)), models, mesh)
    with pytest.raises(ValueError):
        make_sampler(_sample_cfg(fast=FastSampleConfig(
            enabled=True, reuse_ratio=0.9)), models, mesh)


def test_canonical_plan_params_folds_dense_parameterizations():
    # everything whose PLAN is dense is ONE identity: ratio 0 under any
    # valid order, a ratio that rounds to zero skips, and a trajectory too
    # short to skip — while reuse plans and invalid values pass through
    assert fastsample.canonical_plan_params(50, 0.0, 1) == (0.0, 2)
    assert fastsample.canonical_plan_params(50, 0.009, 1) == (0.0, 2)
    assert fastsample.canonical_plan_params(3, 0.75, 1) == (0.0, 2)
    assert fastsample.canonical_plan_params(50, 0.5, 1) == (0.5, 1)
    assert fastsample.canonical_plan_params(50, 0.9, 1) == (0.9, 1)
    assert fastsample.canonical_plan_params(50, 0.0, 7) == (0.0, 7)


def test_validate_bucket_fast_fields():
    def bucket(**kw):
        d = dict(resolution=16, steps=4, guidance=7.5, sampler="ddim",
                 rand_noise_lam=0.0)
        d.update(kw)
        return GenBucket(**d)

    validate_bucket(bucket(fast_ratio=0.5), vae_scale=4)
    with pytest.raises(InvalidRequestError):
        validate_bucket(bucket(fast_ratio=0.9), vae_scale=4)
    with pytest.raises(InvalidRequestError):
        validate_bucket(bucket(fast_ratio=-0.1), vae_scale=4)
    with pytest.raises(InvalidRequestError):
        validate_bucket(bucket(fast_order=0), vae_scale=4)


def test_bucket_tuple_roundtrip_and_legacy_five_tuple():
    b = GenBucket(resolution=32, steps=8, guidance=5.0, sampler="dpm++",
                  rand_noise_lam=0.1, fast_ratio=0.5, fast_order=1)
    assert bucket_from_tuple(tuple(b)) == b
    assert bucket_from_tuple(list(tuple(b))) == b
    # a pre-fast 5-element wire tuple (old journal / warm manifest) decodes
    # to the dense plan — exactly the program it named
    legacy = bucket_from_tuple((32, 8, 5.0, "dpm++", 0.1))
    assert legacy.fast_ratio == 0.0 and legacy.fast_order == 2
    assert legacy[:5] == b[:5]
    with pytest.raises(ValueError):
        bucket_from_tuple((32, 8, 5.0, "dpm++", 0.1, 0.5))


def test_serve_config_fast_maps_into_default_bucket():
    from dcr_tpu.core.config import ServeConfig
    from dcr_tpu.serve import server
    from dcr_tpu.serve.worker import GenerationService

    class FakeService:
        def __init__(self, cfg):
            self.cfg = cfg
        default_bucket = GenerationService.default_bucket

    off = FakeService(ServeConfig()).default_bucket()
    assert off.fast_ratio == 0.0
    on = FakeService(ServeConfig(
        fast=FastSampleConfig(enabled=True, reuse_ratio=0.25,
                              order=1))).default_bucket()
    assert on.fast_ratio == 0.25 and on.fast_order == 1
    # per-request overrides reach the bucket (and unknown fields still 400)
    svc = FakeService(ServeConfig())
    b = server.request_bucket(svc, {"prompt": "x", "fast_ratio": 0.5,
                                    "fast_order": 1})
    assert b.fast_ratio == 0.5 and b.fast_order == 1
    with pytest.raises(ValueError):
        server.request_bucket(svc, {"prompt": "x", "fast_nope": 1})
    # a hostile steps value is a typed 400 BEFORE the O(steps) canonical
    # plan computation — never a giant allocation on the handler thread
    with pytest.raises(ValueError):
        server.request_bucket(svc, {"prompt": "x", "steps": 2_000_000_000})
    with pytest.raises(ValueError):
        server.request_bucket(svc, {"prompt": "x", "steps": 0})


def test_fleet_dispatch_wire_round_trips_fast_fields():
    """The supervisor's /generate_batch wire item must carry the FULL
    bucket identity: a worker whose own default differs (e.g. a fast
    fleet serving a client-pinned dense bucket, or vice versa) has to
    execute the supervisor's plan, not silently back-fill its default."""
    from dcr_tpu.core.config import ServeConfig
    from dcr_tpu.serve import server
    from dcr_tpu.serve.supervisor import wire_item
    from dcr_tpu.serve.worker import GenerationService

    class FakeService:
        def __init__(self, cfg):
            self.cfg = cfg
        default_bucket = GenerationService.default_bucket

    sent = GenBucket(resolution=16, steps=8, guidance=7.5, sampler="ddim",
                     rand_noise_lam=0.0, fast_ratio=0.5, fast_order=1)
    req = Request(prompt="x", seed=3, bucket=sent)
    item = wire_item(req, sent, attempt=1)
    item.pop("trace")       # the handler pops it before bucket parsing
    # worker whose OWN default is a fast bucket: the wire's dense/other
    # plan must win
    worker_default_fast = FakeService(ServeConfig(
        resolution=16, num_inference_steps=8, sampler="ddim",
        fast=FastSampleConfig(enabled=True, reuse_ratio=0.25)))
    assert server.request_bucket(worker_default_fast, item) == sent
    # and a dense wire bucket stays dense on that worker
    dense = sent._replace(fast_ratio=0.0, fast_order=2)
    item2 = wire_item(Request(prompt="x", seed=3, bucket=dense), dense, 1)
    item2.pop("trace")
    assert server.request_bucket(worker_default_fast, item2) == dense


# ---------------------------------------------------------------------------
# trace_report section + bench schema (pure host)
# ---------------------------------------------------------------------------

def _fast_span(steps, calls, ts=1_000_000):
    return {"ph": "X", "name": "sample/fast", "id": 1, "ts": ts, "dur": 50,
            "pid": 0, "tid": 1, "tname": "t", "parent": None,
            "args": {"steps": steps, "unet_calls": calls, "batch": 2}}


def test_trace_report_fast_sampling_section():
    from tools import trace_report as TR

    records = [_fast_span(32, 16), _fast_span(32, 16, ts=2_000_000),
               _fast_span(16, 12, ts=3_000_000)]
    schema = TR.load_schema()
    for rec in records:
        assert TR.validate_record(rec, schema) == []
    summary = TR.summarize(records)
    fast = summary["fast_sampling"]
    # spans are per batch execution; totals weight by args.batch (2 here)
    assert fast["executions"] == 3
    assert fast["trajectories"] == 6
    assert fast["steps_total"] == 160
    assert fast["unet_calls_total"] == 88
    assert fast["calls_saved_total"] == 72
    assert fast["calls_saved_histogram"] == {"4": 2, "16": 4}
    text = TR.render_text(summary, [])
    assert "fast sampling" in text
    assert "4x trajectories saved 16 call(s)" in text
    # dense traces keep their pre-fast report shape
    dense = TR.summarize([{**_fast_span(8, 8), "name": "serve/device_step"}])
    assert dense["fast_sampling"] is None


def test_bench_fastsample_schema_validator():
    from tools.bench_fastsample import validate_result

    row = {"steps": 16, "ratio": 0.5, "order": 2, "unet_calls": 8,
           "call_reduction": 2.0, "wall_s": 0.1, "ref_wall_s": 0.2,
           "latency_speedup": 2.0, "sscd_sim_mean": 0.999,
           "sscd_sim_min": 0.998, "fid": 0.001}
    doc = {"model": "tiny", "sampler": "dpm++", "resolution": 16,
           "prompts": 8, "image_size": 32, "sim_budget_mean": 0.995,
           "sim_budget_min": 0.99, "min_call_reduction": 1.8,
           "background_sim_mean": 0.97, "curve": [row],
           "default_point": row, "pass": True}
    assert validate_result(doc) == []
    assert validate_result({**doc, "curve": []})
    assert validate_result({**doc, "pass": "yes"})
    bad_row = {**row, "sscd_sim_mean": "high"}
    assert validate_result({**doc, "curve": [bad_row]})
    # the banked artifact itself stays schema-valid
    banked = json.loads(
        (__import__("pathlib").Path(__file__).resolve().parent.parent
         / "BENCH_FASTSAMPLE.json").read_text())
    assert validate_result(banked) == []
    assert banked["pass"] is True
    assert banked["default_point"]["call_reduction"] >= 1.8


# ---------------------------------------------------------------------------
# sampler semantics (tiny-model compiles)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_models():
    cfg = TrainConfig()
    cfg.model = ModelConfig.tiny()
    return build_models(cfg, jax.random.key(0))


def _sample_cfg(**kw):
    d = dict(resolution=16, num_inference_steps=6, guidance_scale=7.5,
             sampler="dpm++", im_batch=2, seed=0)
    d.update(kw)
    return SampleConfig(**d)


def _inputs(models, n=4):
    tok = HashTokenizer(models.text_encoder.config.text_vocab_size,
                        models.text_encoder.config.text_max_length)
    ids = np.repeat(tok(["a church", "a truck"]), n // 2, axis=0)
    unc = np.broadcast_to(tok([""])[0], ids.shape).copy()
    return ids, unc


def test_bulk_fast_disabled_bit_identical(tiny_models, cpu_devices):
    """The disabled path is the ORIGINAL program: fast.enabled=False and
    fast enabled with an all-full plan (ratio 0) are byte-identical."""
    models, params = tiny_models
    mesh = pmesh.make_mesh(MeshConfig())
    ids, unc = _inputs(models)
    p = {"unet": params["unet"], "vae": params["vae"], "text": params["text"]}
    base = np.asarray(
        make_sampler(_sample_cfg(), models, mesh)(p, ids, unc,
                                                  rngmod.root_key(1)))
    ratio0 = np.asarray(
        make_sampler(_sample_cfg(fast=FastSampleConfig(
            enabled=True, reuse_ratio=0.0)), models, mesh)(
                p, ids, unc, rngmod.root_key(1)))
    np.testing.assert_array_equal(base, ratio0)


def test_bulk_fast_reuse_differs_but_stays_close(tiny_models, cpu_devices):
    models, params = tiny_models
    mesh = pmesh.make_mesh(MeshConfig())
    ids, unc = _inputs(models)
    p = {"unet": params["unet"], "vae": params["vae"], "text": params["text"]}
    base = np.asarray(
        make_sampler(_sample_cfg(), models, mesh)(p, ids, unc,
                                                  rngmod.root_key(1)))
    fast = np.asarray(
        make_sampler(_sample_cfg(fast=FastSampleConfig(
            enabled=True, reuse_ratio=0.5)), models, mesh)(
                p, ids, unc, rngmod.root_key(1)))
    assert not np.array_equal(base, fast)
    assert np.isfinite(fast).all()
    assert fast.min() >= 0.0 and fast.max() <= 1.0
    # score reuse approximates the dense trajectory, it does not replace
    # the image with something unrelated
    assert np.abs(base - fast).mean() < 0.15
    # and it is deterministic
    fast2 = np.asarray(
        make_sampler(_sample_cfg(fast=FastSampleConfig(
            enabled=True, reuse_ratio=0.5)), models, mesh)(
                p, ids, unc, rngmod.root_key(1)))
    np.testing.assert_array_equal(fast, fast2)


def test_dpmpp_fast_scan_matches_dense_reference_loop(tiny_models,
                                                      cpu_devices):
    """The dpm++ second-order multistep state must advance through skipped
    steps exactly as the spec says: a hand-unrolled python loop over the
    SAME plan (full steps call the real UNet+CFG and bank; reuse steps
    extrapolate from the bank; EVERY step runs dpmpp_2m_step) reproduces
    the jitted scan's trajectory."""
    models, params = tiny_models
    mesh = pmesh.make_mesh(MeshConfig())
    ids, unc = _inputs(models)
    p = {"unet": params["unet"], "vae": params["vae"], "text": params["text"]}
    cfg = _sample_cfg(fast=FastSampleConfig(enabled=True, reuse_ratio=0.5))
    key = rngmod.root_key(3)
    scan_images = np.asarray(make_sampler(cfg, models, mesh)(p, ids, unc,
                                                             key))

    sched = models.schedule
    ts, prev_ts, lof, plan = fast_plan_grid("dpm++", sched, 6, 0.5)
    assert not fastsample.is_dense(plan)
    # mirror sample_fn's stochastic setup exactly
    kp, kn, ks = (rngmod.stream_key(key, n)
                  for n in ("emb_noise", "init", "steps"))
    del kp, ks     # no mitigation noise; dpm++ draws no ancestral noise
    cond, uncond = encode_prompts(models, p["text"], ids, unc)
    ctx = jnp.concatenate([uncond, cond], axis=0)
    from dcr_tpu.models.vae import vae_scale_factor

    ls = 16 // vae_scale_factor(models.vae.config)
    latent = jax.random.normal(
        kn, (ids.shape[0], ls, ls,
             models.vae.config.vae_latent_channels))
    dpm = S.dpm_init_state(latent.shape)
    banked = []            # [(pred, t)], newest last
    x = latent
    for i in range(6):
        t, prev_t = int(ts[i]), int(prev_ts[i])
        if plan[i]:
            tb = jnp.full((2 * ids.shape[0],), t, jnp.int32)
            pred = models.unet.apply({"params": p["unet"]},
                                     jnp.concatenate([x, x], axis=0), tb,
                                     ctx)
            pred_u, pred_c = jnp.split(pred, 2, axis=0)
            pred = pred_u + cfg.guidance_scale * (pred_c - pred_u)
            banked.append((pred, float(t)))
        else:
            (p1, t1) = banked[-1]
            if len(banked) >= 2:
                (p0, t0) = banked[-2]
                pred = p1 + (p1 - p0) * (t - t1) / (t1 - t0)
            else:
                pred = p1
        x, dpm = S.dpmpp_2m_step(sched, pred, x, t, prev_t, dpm,
                                 force_first_order=bool(lof) and i == 5)
    images = models.vae.apply(
        {"params": p["vae"]},
        x / models.vae.config.vae_scaling_factor, method=models.vae.decode)
    ref_images = np.asarray(jnp.clip(images * 0.5 + 0.5, 0.0, 1.0))
    np.testing.assert_allclose(scan_images, ref_images, atol=2e-5, rtol=1e-4)


# ---------------------------------------------------------------------------
# serve path (slow: compiled service stacks)
# ---------------------------------------------------------------------------

def _tiny_stack():
    from dcr_tpu.sampling.pipeline import GenerationStack

    tiny = ModelConfig.tiny()
    tcfg = TrainConfig(mixed_precision="no")
    tcfg.model = tiny
    models, params = build_models(tcfg, jax.random.key(0))
    tok = HashTokenizer(vocab_size=tiny.text_vocab_size,
                        model_max_length=tiny.text_max_length)
    return GenerationStack(models, params, tiny, tok,
                           pmesh.make_mesh(MeshConfig()))


def _service(stack, **cfg_kw):
    from dcr_tpu.core.config import ServeConfig
    from dcr_tpu.serve.worker import GenerationService

    kw = dict(resolution=16, num_inference_steps=6, sampler="dpm++",
              max_batch=2, max_wait_ms=30.0, queue_depth=16, seed=0)
    kw.update(cfg_kw)
    return GenerationService(ServeConfig(**kw), stack)


@pytest.mark.slow
def test_serve_fast_bucket_purity_and_disabled_identity(tmp_path,
                                                        cpu_devices):
    """With fast on, the serve purity contract holds (alone vs mixed batch
    bit-identical — the plan is batch-uniform and the reuse math is
    elementwise); with ratio 0 the serve bucket is bit-identical to the
    dense service. The fast batch also stamps a schema-valid sample/fast
    span that trace_report turns into the calls-saved section."""
    from dcr_tpu.core import tracing
    from tools import trace_report as TR

    trace_path = tracing.configure(tmp_path, rank=0)
    stack = _tiny_stack()
    dense = _service(stack)
    fast = _service(stack, fast=FastSampleConfig(enabled=True,
                                                 reuse_ratio=0.5))
    ratio0 = _service(stack, fast=FastSampleConfig(enabled=True,
                                                   reuse_ratio=0.0))
    bd, bf, b0 = (s.default_bucket() for s in (dense, fast, ratio0))
    assert bf.fast_ratio == 0.5 and b0.fast_ratio == 0.0

    a = dense.execute([Request(prompt="a red square", seed=7, bucket=bd)])
    b = ratio0.execute([Request(prompt="a red square", seed=7, bucket=b0)])
    np.testing.assert_array_equal(a[0], b[0])

    alone = fast.execute([Request(prompt="a red square", seed=7, bucket=bf)])
    mixed = fast.execute([Request(prompt="a red square", seed=7, bucket=bf),
                          Request(prompt="a blue circle", seed=9,
                                  bucket=bf)])
    np.testing.assert_array_equal(alone[0], mixed[0])
    assert not np.array_equal(mixed[0], mixed[1])
    assert not np.array_equal(alone[0], a[0])   # fast really differs

    # trace plumbing: fast batches stamped, dense batches not
    schema = TR.load_schema()
    records = []
    for line in trace_path.read_text().splitlines():
        rec = json.loads(line)
        assert TR.validate_record(rec, schema) == []
        # summarize() runs on load_fleet() output, which stamps the stream
        # label/index onto every record
        rec["_plabel"], rec["_proc"] = "trace.jsonl", 0
        records.append(rec)
    fast_spans = [r for r in records
                  if r["ph"] == "X" and r["name"] == "sample/fast"]
    assert len(fast_spans) == 2      # the two fast.execute() batches
    plan = fastsample.fast_plan(6, 0.5)
    for sp in fast_spans:
        assert sp["args"]["steps"] == 6
        assert sp["args"]["unet_calls"] == fastsample.unet_calls(plan)
    summary = TR.summarize(records)
    # two executions (alone + mixed), three trajectories across them
    assert summary["fast_sampling"]["executions"] == 2
    assert summary["fast_sampling"]["trajectories"] == 3
    assert summary["fast_sampling"]["call_reduction"] == round(
        6 / fastsample.unet_calls(plan), 3)


@pytest.mark.slow
def test_serve_fast_ddpm_ancestral_purity(cpu_devices):
    """The stochastic sampler keeps per-request ancestral-noise purity with
    score reuse on (reuse substitutes the prediction; the per-row noise
    draws are untouched)."""
    stack = _tiny_stack()
    svc = _service(stack, sampler="ddpm",
                   fast=FastSampleConfig(enabled=True, reuse_ratio=0.5))
    b = svc.default_bucket()
    alone = svc.execute([Request(prompt="x", seed=3, bucket=b)])
    mixed = svc.execute([Request(prompt="x", seed=3, bucket=b),
                         Request(prompt="y", seed=4, bucket=b)])
    np.testing.assert_array_equal(alone[0], mixed[0])

import jax
import jax.numpy as jnp
import numpy as np

from dcr_tpu.ops import attention as A
from dcr_tpu.ops import flash_attention as FA


def _rand_qkv(key, b=2, sq=512, sk=256, h=2, d=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, sq, h, d), dtype)
    k = jax.random.normal(kk, (b, sk, h, d), dtype)
    v = jax.random.normal(kv, (b, sk, h, d), dtype)
    return q, k, v


def test_supported_shapes():
    q, k, v = _rand_qkv(jax.random.key(0))
    assert FA.supported(q, k, v)
    q2, k2, v2 = _rand_qkv(jax.random.key(0), sq=100)
    assert not FA.supported(q2, k2, v2)
    q3, k3, v3 = _rand_qkv(jax.random.key(0), sk=77)
    assert not FA.supported(q3, k3, v3)  # CLIP cross-attn length falls back to XLA
    q4, k4, v4 = _rand_qkv(jax.random.key(0), d=48)
    assert not FA.supported(q4, k4, v4)


def test_dispatch_policy():
    """should_use = capability AND the measured win threshold (FLASH_MIN_SEQ):
    short sequences go to XLA even though the kernel could run them."""
    q, k, v = _rand_qkv(jax.random.key(0), sq=512, sk=512)
    assert FA.supported(q, k, v) and not FA.should_use(q, k, v)
    ql, kl, vl = _rand_qkv(jax.random.key(0), sq=FA.FLASH_MIN_SEQ,
                           sk=FA.FLASH_MIN_SEQ)
    assert FA.should_use(ql, kl, vl)


def test_block_resolution():
    """Explicit blocks win; defaults clamp to divide the sequence lengths."""
    assert FA._resolve_blocks(4096, 4096, 256, 128) == (256, 128)
    bq, bk = FA._resolve_blocks(1024, 1024, None, None)
    assert 1024 % bq == 0 and 1024 % bk == 0
    bq, bk = FA._resolve_blocks(384, 384, None, None)  # 384 = 3*128
    assert 384 % bq == 0 and 384 % bk == 0


def test_flash_matches_xla_forward():
    q, k, v = _rand_qkv(jax.random.key(1))
    ref = A.dot_product_attention(q, k, v, use_flash=False)
    out = FA.flash_attention(q, k, v, True)  # interpret mode on CPU
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_matches_xla_self_attention_4096():
    """The 512px UNet shape the kernel exists for (S=4096)."""
    q, k, v = _rand_qkv(jax.random.key(2), b=1, sq=1024, sk=1024, h=1, d=64)
    ref = A.dot_product_attention(q, k, v, use_flash=False)
    out = FA.flash_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_bf16_close_to_f32():
    q, k, v = _rand_qkv(jax.random.key(3), dtype=jnp.bfloat16)
    ref = A.dot_product_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                                  v.astype(jnp.float32), use_flash=False)
    out = FA.flash_attention(q, k, v, True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32), np.asarray(ref),
                               atol=3e-2, rtol=3e-2)


def test_flash_gradients_match_xla():
    q, k, v = _rand_qkv(jax.random.key(4), b=1, sq=256, sk=128, h=1, d=64)

    def loss_flash(q, k, v):
        return jnp.sum(FA.flash_attention(q, k, v, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(A.dot_product_attention(q, k, v, use_flash=False) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_softmax_stability_large_logits():
    """Online softmax must survive logits that would overflow naive exp."""
    q, k, v = _rand_qkv(jax.random.key(5), b=1, sq=256, sk=128, h=1, d=64)
    q = q * 100.0
    out = FA.flash_attention(q, k, v, True)
    assert np.all(np.isfinite(np.asarray(out)))
    ref = A.dot_product_attention(q, k, v, use_flash=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4)


def test_fused_backward_rectangular_and_bf16():
    """dK/dV kernel loops over query blocks (sq != sk) and bf16 grads stay
    close to the f32 XLA reference."""
    q, k, v = _rand_qkv(jax.random.key(7), b=1, sq=512, sk=256, h=2, d=64)

    def loss_flash(q, k, v):
        return jnp.sum(FA.flash_attention(q, k, v, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(A.dot_product_attention(q, k, v, use_flash=False) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4)

    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    gb = jax.grad(lambda *xs: jnp.sum(FA.flash_attention(*xs, True).astype(jnp.float32) ** 2),
                  argnums=(0, 1, 2))(qb, kb, vb)
    for a, b in zip(gb, gr):
        np.testing.assert_allclose(np.asarray(a, dtype=np.float32), np.asarray(b),
                                   atol=0.15, rtol=0.1)

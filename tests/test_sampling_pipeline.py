"""Checkpoint-dir -> generate() -> PNGs on disk (the sample stage contract)."""

import jax
import numpy as np
import pytest
from PIL import Image

from dcr_tpu.core.checkpoint import export_hf_layout
from dcr_tpu.core.config import ModelConfig, SampleConfig, TrainConfig, to_dict
from dcr_tpu.data.tokenizer import HashTokenizer
from dcr_tpu.diffusion.trainer import build_models
from dcr_tpu.sampling.pipeline import generate, load_checkpoint_models, resolve_checkpoint

# checkpoint->PNG sampling: excluded from the quick suite (`pytest -m 'not slow'`)
pytestmark = pytest.mark.slow


def export_tiny_run(run_dir, model_cfg=None):
    """Write a tiny HF-layout checkpoint under run_dir/checkpoint."""
    cfg = TrainConfig()
    cfg.model = model_cfg or ModelConfig.tiny()
    models, params = build_models(cfg, jax.random.key(0))
    export_hf_layout(
        run_dir / "checkpoint", unet=params["unet"], vae=params["vae"],
        text_encoder=params["text"],
        scheduler_config={"num_train_timesteps": 1000,
                          "beta_schedule": "scaled_linear",
                          "beta_start": 0.00085, "beta_end": 0.012,
                          "prediction_type": "epsilon"},
        model_config=to_dict(cfg.model))
    return run_dir


def assert_images_close(dir_a, dir_b, n, tol=1):
    """PNG sets equal within tol uint8 LSB (reduction-order float drift)."""
    a_files = sorted(dir_a.glob("*.png"))
    b_files = sorted(dir_b.glob("*.png"))
    assert len(a_files) == len(b_files) == n
    for a, b in zip(a_files, b_files):
        with Image.open(a) as ia, Image.open(b) as ib:
            diff = np.abs(np.asarray(ia).astype(np.int16)
                          - np.asarray(ib).astype(np.int16))
            assert diff.max() <= tol, f"max pixel diff {diff.max()}"


@pytest.fixture(scope="module")
def exported_ckpt(tmp_path_factory):
    return export_tiny_run(tmp_path_factory.mktemp("ckpt") / "run")


def test_load_checkpoint_models(exported_ckpt):
    models, params, mcfg = load_checkpoint_models(exported_ckpt / "checkpoint")
    assert mcfg.sample_size == 8
    assert set(params) == {"unet", "vae", "text"}


def test_resolve_checkpoint(exported_ckpt):
    cfg = SampleConfig(model_path=str(exported_ckpt))
    assert resolve_checkpoint(cfg).name == "checkpoint"
    with pytest.raises(FileNotFoundError):
        resolve_checkpoint(SampleConfig(model_path=str(exported_ckpt), iternum=999))


def test_generate_end_to_end(exported_ckpt, tmp_path, cpu_devices):
    cfg = SampleConfig(
        model_path=str(exported_ckpt), savepath=str(tmp_path / "inf"),
        num_batches=3, im_batch=2, resolution=16, num_inference_steps=3,
        sampler="ddim", seed=0)
    tok = HashTokenizer(1000, 16)
    out = generate(cfg, modelstyle="classlevel", tokenizer=tok)
    gens = sorted((out / "generations").glob("*.png"))
    assert len(gens) == 3 * 2  # num_batches prompts x im_batch images
    with Image.open(gens[0]) as im:
        assert im.size == (16, 16)
        arr = np.asarray(im)
    assert arr.std() > 0  # not a constant image
    prompts = (out / "prompts.txt").read_text().splitlines()
    assert len(prompts) == 3 and all(p.startswith("An image of") for p in prompts)


def test_generate_with_tensor_parallel_mesh(exported_ckpt, tmp_path, cpu_devices):
    """Sampling on a tensor-axis mesh: params are sharded Megatron-style
    across chips (memory headroom for models too big for one chip's HBM)
    and the outputs stay deterministic vs the pure-DP run."""
    from dcr_tpu.core.config import MeshConfig

    common = dict(
        model_path=str(exported_ckpt), num_batches=2, im_batch=2,
        resolution=16, num_inference_steps=2, sampler="ddim", seed=0)
    tok = HashTokenizer(1000, 16)
    out_dp = generate(SampleConfig(savepath=str(tmp_path / "dp"), **common),
                      modelstyle="classlevel", tokenizer=tok)
    out_tp = generate(
        SampleConfig(savepath=str(tmp_path / "tp"),
                     mesh=MeshConfig(data=-1, tensor=2), **common),
        modelstyle="classlevel", tokenizer=tok)
    assert_images_close(out_dp / "generations", out_tp / "generations", 4)


def test_generate_with_sequence_parallel_mesh(tmp_path, cpu_devices,
                                              monkeypatch):
    """Long-context inference: a seq-axis mesh turns on ring-attention
    sequence parallelism inside the sampler's UNet (the same mechanism the
    train step uses), and outputs match the pure-DP run. The ring kernel is
    counted so the parity check can't pass vacuously if the gate (module
    mesh, S >= seq_parallel_min_seq, divisibility) silently stops firing."""
    import dataclasses

    import dcr_tpu.ops.ring_attention as ring_mod
    from dcr_tpu.core.config import MeshConfig

    # checkpoint whose config forces the seq-parallel path at 32px
    # (16x16 latent tokens >= threshold 64 at the UNet's top level)
    run = export_tiny_run(
        tmp_path / "ckpt_sp" / "run",
        dataclasses.replace(ModelConfig.tiny(), seq_parallel_min_seq=64))

    ring_calls = []
    orig_ring = ring_mod.ring_self_attention
    monkeypatch.setattr(
        ring_mod, "ring_self_attention",
        lambda *a, **k: (ring_calls.append(1), orig_ring(*a, **k))[1])

    tok = HashTokenizer(1000, 16)
    common = dict(model_path=str(run), num_batches=2, im_batch=1,
                  resolution=32, num_inference_steps=2, sampler="ddim", seed=0)
    out_dp = generate(SampleConfig(savepath=str(tmp_path / "dp"), **common),
                      modelstyle="nolevel", tokenizer=tok)
    assert not ring_calls        # dense path without a seq axis
    out_sp = generate(
        SampleConfig(savepath=str(tmp_path / "sp"),
                     mesh=MeshConfig(data=-1, seq=2), **common),
        modelstyle="nolevel", tokenizer=tok)
    assert ring_calls            # the ring kernel actually traced
    assert_images_close(out_dp / "generations", out_sp / "generations", 2)


def test_prebuilt_stale_mesh_models_get_reconciled(tmp_path, cpu_devices,
                                                   monkeypatch):
    """make_sampler reconciles the UNet's module mesh for EVERY caller:
    models prebuilt against a training mesh (seq=1) and passed into
    generate() with a seq-axis sampling mesh must still run ring attention
    — not silently sample dense on the stale mesh."""
    import dataclasses

    import dcr_tpu.ops.ring_attention as ring_mod
    from dcr_tpu.core.config import MeshConfig
    from dcr_tpu.parallel import mesh as pmesh

    cfg = TrainConfig()
    cfg.model = dataclasses.replace(ModelConfig.tiny(), seq_parallel_min_seq=64)
    train_mesh = pmesh.make_mesh(MeshConfig(data=-1))      # seq=1, stale
    models, params = build_models(cfg, jax.random.key(0), mesh=train_mesh)
    assert models.unet.mesh is train_mesh

    ring_calls = []
    orig_ring = ring_mod.ring_self_attention
    monkeypatch.setattr(
        ring_mod, "ring_self_attention",
        lambda *a, **k: (ring_calls.append(1), orig_ring(*a, **k))[1])

    out = generate(
        SampleConfig(savepath=str(tmp_path / "out"), num_batches=1,
                     im_batch=1, resolution=32, num_inference_steps=2,
                     sampler="ddim", seed=0, mesh=MeshConfig(data=-1, seq=2)),
        modelstyle="nolevel", tokenizer=HashTokenizer(1000, 16),
        models=models, params=params)
    assert ring_calls, "stale training mesh was not reconciled"
    assert len(list((out / "generations").glob("*.png"))) == 1

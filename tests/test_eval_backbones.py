import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcr_tpu.models.clip_image import init_clip_scorer, make_clip_scorer
from dcr_tpu.models.inception import InceptionV3FID
from dcr_tpu.models.resnet import SSCDModel, gem_pool
from dcr_tpu.models.vit import vit_tiny

# large backbone compiles: excluded from the quick suite (`pytest -m 'not slow'`)
pytestmark = pytest.mark.slow


def test_sscd_shapes():
    model = SSCDModel(embed_dim=512)
    x = jnp.zeros((2, 64, 64, 3))
    params = model.init(jax.random.key(0), x)["params"]
    out = model.apply({"params": params}, x)
    assert out.shape == (2, 512)
    # input-size polymorphic (224 eval, other sizes for multiscale)
    out2 = model.apply({"params": params}, jnp.zeros((1, 96, 96, 3)))
    assert out2.shape == (1, 512)


def test_gem_pool_reduces_to_mean_at_p1():
    x = jnp.abs(jax.random.normal(jax.random.key(0), (2, 4, 4, 8))) + 0.1
    np.testing.assert_allclose(np.asarray(gem_pool(x, p=1.0)),
                               np.asarray(jnp.mean(x, axis=(1, 2))), rtol=1e-5)
    # monotone in p, bounded by max
    g16 = np.asarray(gem_pool(x, p=16.0))
    g3 = np.asarray(gem_pool(x, p=3.0))
    mean = np.asarray(jnp.mean(x, axis=(1, 2)))
    mx = np.asarray(jnp.max(x, axis=(1, 2)))
    assert np.all(g16 <= mx + 1e-5)
    assert np.all(g16 >= g3 - 1e-6) and np.all(g3 >= mean - 1e-6)


def test_vit_cls_feature_and_resolution_change():
    model = vit_tiny(patch_size=16)
    x = jnp.zeros((1, 224, 224, 3))
    params = model.init(jax.random.key(0), x)["params"]
    out = model.apply({"params": params}, x)
    assert out.shape == (1, 192)
    # pos-embed interpolation: same params at a different resolution
    out2 = model.apply({"params": params}, jnp.zeros((1, 96, 96, 3)))
    assert out2.shape == (1, 192)
    # intermediate layers
    layers = model.apply({"params": params}, x, return_layers=2)
    assert len(layers) == 2
    assert layers[0].shape == (1, 196 + 1, 192)


def test_inception_fid_output_dim():
    model = InceptionV3FID(resize_input=False)
    x = jnp.zeros((1, 128, 128, 3))
    params = model.init(jax.random.key(0), x)["params"]
    out = model.apply({"params": params}, x)
    assert out.shape == (1, 2048)
    assert np.isfinite(np.asarray(out)).all()


def test_inception_resizes_input():
    model = InceptionV3FID(resize_input=True)
    x = jnp.zeros((1, 75, 75, 3))
    params = model.init(jax.random.key(0), x)["params"]
    out = model.apply({"params": params}, jnp.zeros((1, 64, 64, 3)))
    assert out.shape == (1, 2048)


def test_avg_pool_exclude_pad_math():
    from dcr_tpu.models.inception import _avg_pool_exclude_pad

    x = jnp.ones((1, 4, 4, 1))
    out = np.asarray(_avg_pool_exclude_pad(x))
    # with padding excluded, averaging ones stays exactly 1 everywhere
    np.testing.assert_allclose(out, 1.0, atol=1e-6)
    # include-pad averaging would give 4/9 at corners — confirm we differ
    import flax.linen as nn

    inc = np.asarray(nn.avg_pool(x, (3, 3), (1, 1), ((1, 1), (1, 1))))
    assert inc[0, 0, 0, 0] < 1.0


def test_clip_scorer_cosine_range():
    scorer = make_clip_scorer()
    params = init_clip_scorer(jax.random.key(0), scorer, image_size=32)
    images = jax.random.uniform(jax.random.key(1), (2, 32, 32, 3))
    ids = jnp.ones((2, 77), jnp.int32)
    s = np.asarray(scorer.score(params, images, ids))
    assert s.shape == (2,)
    assert np.all(np.abs(s) <= 1.0 + 1e-5)


def test_build_backbone_layer_selects_intermediate_cls():
    """--layer > 1 (reference utils_ret.py:731-745): the extractor feature
    must equal the CLS token of get_intermediate_layers(x, layer)[0], and
    differ from the final-layer default."""
    from dcr_tpu.eval.runner import build_backbone

    key = jax.random.key(0)
    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    f1, params = build_backbone("dino", "dino_vitb_cifar10", key, None,
                                image_size=32, layer=1)
    f2, _ = build_backbone("dino", "dino_vitb_cifar10", key, None,
                           image_size=32, layer=2)
    feats1 = np.asarray(f1(params, x))
    feats2 = np.asarray(f2(params, x))
    assert feats1.shape == feats2.shape
    assert not np.allclose(feats1, feats2)
    from dcr_tpu.models.vit import DINO_ARCHS

    model = DINO_ARCHS["dino_vitb_cifar10"]()
    direct = model.apply({"params": params}, x, return_layers=2)[0][:, 0]
    np.testing.assert_allclose(feats2, np.asarray(direct), atol=1e-6)


def test_build_backbone_layer_rejects_non_vit():
    from dcr_tpu.eval.runner import build_backbone

    with pytest.raises(ValueError, match="DINO ViT"):
        build_backbone("sscd", "resnet50_disc", jax.random.key(0), None,
                       layer=2)
    with pytest.raises(ValueError, match="DINO ViT"):
        build_backbone("dino", "dino_resnet50", jax.random.key(0), None, layer=2)


def test_build_backbone_token_features_for_splitloss():
    """splitloss + dino layer>1 (reference utils_ret.py:729-737): features are
    ALL tokens flattened, n_tokens = 1+hw carries the numpatches alias."""
    from dcr_tpu.eval.runner import build_backbone

    f, params = build_backbone("dino", "dino_vits16", jax.random.key(0), None,
                               image_size=32, layer=2, flatten_tokens=True)
    assert f.n_tokens == (32 // 16) ** 2 + 1   # 4 patches + CLS
    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    feats = np.asarray(f(params, x))
    assert feats.shape == (2, f.n_tokens * 384)
    # first token slice equals the CLS path
    f_cls, _ = build_backbone("dino", "dino_vits16", jax.random.key(0), None,
                              image_size=32, layer=2)
    np.testing.assert_allclose(feats[:, :384], np.asarray(f_cls(params, x)),
                               atol=1e-6)
    with pytest.raises(ValueError, match="token"):
        build_backbone("dino", "dino_vits16", jax.random.key(0), None,
                       image_size=32, layer=1, flatten_tokens=True)


def test_xcit_archs_registered_and_forward():
    """The four dino_xcit_* hub entries (reference dino_vits.py:413-487) are
    selectable via the standard (pt_style, arch) switch and produce CLS
    embeddings of the published widths at any stride-divisible resolution."""
    from dcr_tpu.eval.runner import build_backbone
    from dcr_tpu.models.vit import DINO_ARCHS

    for arch in ("dino_xcit_small_12_p16", "dino_xcit_small_12_p8",
                 "dino_xcit_medium_24_p16", "dino_xcit_medium_24_p8"):
        assert arch in DINO_ARCHS
    small = DINO_ARCHS["dino_xcit_small_12_p16"]()
    medium = DINO_ARCHS["dino_xcit_medium_24_p8"]()
    assert (small.embed_dim, small.depth, small.patch_size) == (384, 12, 16)
    assert (medium.embed_dim, medium.depth, medium.patch_size) == (512, 24, 8)

    f, params = build_backbone("dino", "dino_xcit_small_12_p16",
                               jax.random.key(0), None, image_size=48)
    x = jax.random.normal(jax.random.key(1), (2, 48, 48, 3))
    feats = np.asarray(f(params, x))
    assert feats.shape == (2, 384)
    assert np.isfinite(feats).all()
    # no positional table: a different resolution runs without interpolation
    y = jax.random.normal(jax.random.key(2), (1, 64, 64, 3))
    assert np.asarray(f(params, y)).shape == (1, 384)


def test_xcit_rejects_intermediate_layer():
    """--layer is a ViT-only surface in the reference (get_intermediate_layers);
    XCiT must fail loudly, not silently fall back."""
    from dcr_tpu.eval.runner import build_backbone

    with pytest.raises(ValueError, match="DINO ViT"):
        build_backbone("dino", "dino_xcit_small_12_p16", jax.random.key(0),
                       None, layer=2)

"""Shared launcher for real two-process ``jax.distributed`` tests.

Used by tests/test_multihost.py (DCN collectives) and
tests/test_coordination.py (resilience e2e). Centralizes the one genuinely
flaky part: the rendezvous port. The historical pattern — bind an ephemeral
port, close it, hand the number to the workers — races every other process
on the machine for the window between close() and the coordinator's bind;
under a parallel CI box that's a steady trickle of spurious failures. The
fix is pragmatic: keep the pick-then-close (jax's coordinator must bind the
port itself), but RETRY the whole two-process launch on a fresh port when
the failure output is recognizably a bind/conflict error rather than a real
test failure.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).parent.parent

# signatures of "the coordinator could not bind / a stale peer owns the
# port" — anything else is a genuine failure and must surface immediately
BIND_ERROR_MARKERS = (
    "address already in use",
    "Address already in use",
    "Failed to bind",
    "failed to bind",
    "errno: 98",
)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def looks_like_bind_race(outputs: list[str]) -> bool:
    return any(marker in out for out in outputs for marker in BIND_ERROR_MARKERS)


def run_two_process(argv: list[str], *, env: dict, timeout: int = 240,
                    attempts: int = 3,
                    extra_env_per_rank: list[dict] | None = None) -> list[tuple[int, str]]:
    """Launch ``argv`` twice as ranks 0/1 of a localhost jax.distributed job.

    ``env`` is the complete base environment for both workers; per-rank
    COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID are injected, plus
    ``extra_env_per_rank[rank]`` when given. Returns ``[(returncode,
    combined_output), ...]`` indexed by rank. Retries the whole launch on a
    fresh port when every-rank output points at a bind race (see module
    docstring); raises TimeoutError (after killing both) when a worker
    outlives ``timeout`` — callers asserting watchdog behavior rely on the
    workers exiting on their own well before that.
    """
    last_outputs: list[str] = []
    for attempt in range(1, attempts + 1):
        addr = f"127.0.0.1:{free_port()}"
        procs = []
        for rank in range(2):
            worker_env = dict(env)
            worker_env.update({
                "COORDINATOR_ADDRESS": addr,
                "NUM_PROCESSES": "2",
                "PROCESS_ID": str(rank),
            })
            if extra_env_per_rank:
                worker_env.update(extra_env_per_rank[rank])
            procs.append(subprocess.Popen(
                argv, env=worker_env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True))
        results: list[tuple[int, str]] = []
        deadline = time.monotonic() + timeout
        try:
            for p in procs:
                remaining = max(1.0, deadline - time.monotonic())
                out, _ = p.communicate(timeout=remaining)
                results.append((p.returncode, out))
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            tails = []
            for r, p in enumerate(procs):
                if r < len(results):  # finished before the timeout
                    tails.append(f"--- rank {r} (rc {results[r][0]}) tail ---\n"
                                 f"{results[r][1][-2000:]}")
                    continue
                try:  # communicate() closes stdout on ranks it completed
                    out, _ = p.communicate(timeout=5)
                except Exception:
                    out = "<output unavailable>"
                tails.append(f"--- rank {r} tail ---\n{out[-2000:]}")
            raise TimeoutError(
                f"two-process workers exceeded {timeout}s (attempt {attempt}); "
                f"partial output:\n" + "\n".join(tails))
        last_outputs = [out for _, out in results]
        failed = any(rc != 0 for rc, _ in results)
        if failed and attempt < attempts and looks_like_bind_race(last_outputs):
            continue  # rendezvous port race: relaunch on a fresh port
        return results
    raise AssertionError("unreachable")


def worker_base_env(*, local_devices: int = 1, inherit: bool = False) -> dict:
    """Environment for a two-process worker.

    ``inherit=False`` (collective unit tests): a minimal clean env, so the
    workers can't pick up the parent pytest's 8-device XLA_FLAGS or fault
    specs. ``inherit=True`` (CLI e2e tests): start from os.environ — the
    persistent XLA compile cache and platform pins carry over — then force
    the device count down to ``local_devices``.
    """
    if inherit:
        env = dict(os.environ)
        env.pop("DCR_FAULTS", None)
    else:
        env = {"PATH": "/usr/bin:/bin:/usr/local/bin", "HOME": "/tmp"}
    env["PYTHONPATH"] = str(REPO) + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={local_devices}"
    return env

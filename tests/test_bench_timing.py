"""Unit coverage for bench.py's loader-rung timing decision.

The slope method (t(1+N) − t(1)) / N cancels the tunnel sync RTT but can
go degenerate when a prefetch backlog inflates the t(1) sample; the
fallback and its same-window stall accounting are pure arithmetic, so
they get direct tests (a smoke run had produced 6e9 img/s from a negative
slope before the fallback existed)."""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from bench import loader_step_time

pytestmark = pytest.mark.fast


def test_healthy_slope_cancels_sync_overhead():
    # 100ms/step + 500ms fixed sync in both windows; 10% loader wait
    dt, method, stall = loader_step_time(0.6, 0.5 + 0.1 * 9, 0.01, 0.09, 8)
    assert method == "slope"
    assert dt == pytest.approx(0.1)
    assert stall == pytest.approx((0.09 - 0.01) / 8 / 0.1)


def test_degenerate_slope_falls_back_to_total_window():
    # backlogged t(1) sample >= long window per-step: slope would be <= 0
    dt, method, stall = loader_step_time(1.0, 0.9, 0.8, 0.45, 8)
    assert method == "total"
    assert dt == pytest.approx(0.9 / 9)
    # stall from the SAME window: wn/tn, not the unusable slope pair
    assert stall == pytest.approx(0.45 / 0.9)


def test_stall_fraction_clamped_to_unit_interval():
    _, _, stall = loader_step_time(0.1, 2.1, 0.0, 4.0, 8)
    assert stall == 1.0
    _, _, stall = loader_step_time(1.0, 0.5, 0.9, 0.6, 8)
    assert stall <= 1.0


def test_near_degenerate_slope_rejected_by_relative_guard():
    # tn - t1 passes the absolute 1e-3 floor but the implied 1.25ms/step is
    # absurd next to the 100ms whole-window estimate -> must fall back
    dt, method, _ = loader_step_time(0.89, 0.90, 0.0, 0.0, 8)
    assert method == "total"
    assert dt == pytest.approx(0.90 / 9)


def test_big_rtt_small_step_still_uses_slope():
    # legit regime: 174ms sync RTT, 5ms true step -> ratio ~0.2, keep slope
    dt, method, _ = loader_step_time(0.174 + 0.005, 0.174 + 0.045, 0.0, 0.0, 8)
    assert method == "slope"
    assert dt == pytest.approx(0.005)


def test_loader_wait_noise_never_goes_negative():
    # w1 > wn (first window caught the refill): slope stall clamps at 0
    _, method, stall = loader_step_time(0.6, 1.4, 0.5, 0.1, 8)
    assert method == "slope"
    assert stall == 0.0

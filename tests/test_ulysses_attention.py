"""Ulysses all-to-all sequence parallelism: exact-match vs single-device
attention on sequence-sharded virtual meshes, plus the in-model dispatch
(CrossAttention seq_parallel_mode="ulysses") and its ring fallback when
heads don't divide the seq axis."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcr_tpu.core.config import MeshConfig, ModelConfig
from dcr_tpu.ops.attention import dot_product_attention
from dcr_tpu.ops.ulysses_attention import ulysses_attention, ulysses_self_attention
from dcr_tpu.parallel import mesh as pmesh


@pytest.fixture()
def seq_mesh(cpu_devices):
    return pmesh.make_mesh(MeshConfig(data=1, seq=8))


def _qkv(key, b=2, s=64, h=8, d=16, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(k, (b, s, h, d), dtype) for k in ks)


@pytest.mark.fast
def test_ulysses_matches_full_attention(seq_mesh):
    q, k, v = _qkv(jax.random.key(0))
    ref = dot_product_attention(q, k, v, use_flash=False)
    out = ulysses_self_attention(q, k, v, seq_mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


@pytest.mark.fast
def test_ulysses_matches_with_data_parallel_too(cpu_devices):
    mesh = pmesh.make_mesh(MeshConfig(data=2, seq=4))
    q, k, v = _qkv(jax.random.key(1), b=4, s=32)
    ref = dot_product_attention(q, k, v, use_flash=False)
    out = ulysses_self_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


@pytest.mark.fast
def test_ulysses_gradients_match(seq_mesh):
    q, k, v = _qkv(jax.random.key(2), b=1, s=32, h=8, d=8)

    def loss_uly(q, k, v):
        return jnp.sum(ulysses_self_attention(q, k, v, seq_mesh) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, use_flash=False) ** 2)

    gu = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4,
                                   rtol=1e-4)


@pytest.mark.fast
def test_ulysses_rejects_non_dividing_heads(seq_mesh):
    q, k, v = _qkv(jax.random.key(3), h=3)   # 3 heads, seq axis 8
    with pytest.raises(ValueError, match="divisible"):
        ulysses_self_attention(q, k, v, seq_mesh)


@pytest.mark.fast
def test_ulysses_jit_compiles(seq_mesh):
    q, k, v = _qkv(jax.random.key(4))
    f = jax.jit(lambda q, k, v: ulysses_self_attention(q, k, v, seq_mesh))
    out = f(q, k, v)
    assert out.shape == q.shape


@pytest.mark.fast
def test_cross_attention_dispatches_ulysses_and_falls_back(cpu_devices):
    """CrossAttention with seq_parallel_mode='ulysses' matches the dense mesh
    run; with heads that don't divide the seq axis it silently takes the ring
    path (same numerics, no error)."""
    from dcr_tpu.models.layers import CrossAttention

    x = jax.random.normal(jax.random.key(5), (2, 64, 24))

    for heads in (4, 3):                     # 4 divides seq=2; 3 does not
        dense = CrossAttention(num_heads=heads, head_dim=8, out_dim=24,
                               use_flash=False, mesh=None)
        p = dense.init(jax.random.key(6), x)
        ref = dense.apply(p, x)
        # all 8 virtual devices: batch axes stay 1 (b=2 must divide them),
        # the tensor axis just replicates at this layer
        mesh = pmesh.make_mesh(MeshConfig(data=1, fsdp=1, tensor=4, seq=2))
        uly = CrossAttention(num_heads=heads, head_dim=8, out_dim=24,
                             use_flash=False, mesh=mesh,
                             seq_parallel_min_seq=32,
                             seq_parallel_mode="ulysses")
        out = uly.apply(p, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


def test_ulysses_seq_parallel_train_step(cpu_devices):
    """Full train step with seq_parallel_mode='ulysses' on a seq=2 mesh
    matches the dense seq=1 loss on the same params/batch (mirrors the ring
    train-step guard in test_train.py)."""
    from dcr_tpu.core import rng as rngmod
    from dcr_tpu.core.config import TrainConfig
    from dcr_tpu.diffusion import train as T
    from dcr_tpu.diffusion.trainer import build_models

    cfg = TrainConfig(mixed_precision="no")
    cfg.optim.lr_warmup_steps = 0
    cfg.model = dataclasses.replace(ModelConfig.tiny(), seq_parallel_min_seq=64,
                                    seq_parallel_mode="ulysses")
    key = rngmod.root_key(0)
    px = 16 * 2 ** (len(cfg.model.vae_block_out_channels) - 1)
    batch = {
        "pixel_values": jax.random.uniform(jax.random.key(5), (8, px, px, 3)) * 2 - 1,
        "input_ids": jax.random.randint(jax.random.key(6),
                                        (8, cfg.model.text_max_length), 0,
                                        cfg.model.text_vocab_size),
    }

    losses = {}
    params0 = None
    for name, mesh_cfg in (("dense", MeshConfig(data=-1)),
                           ("ulysses", MeshConfig(data=-1, fsdp=1, tensor=1, seq=2))):
        mesh = pmesh.make_mesh(mesh_cfg)
        models, p = build_models(cfg, jax.random.key(0), mesh=mesh)
        if params0 is None:
            params0 = {k: jax.tree.map(lambda x: np.asarray(x), p[k]) for k in p}
        p = {k: jax.tree.map(jnp.asarray, params0[k]) for k in params0}
        state = T.init_train_state(cfg, models, unet_params=p["unet"],
                                   text_params=p["text"], vae_params=p["vae"])
        state = T.shard_train_state(state, mesh)
        step = T.make_train_step(cfg, models, mesh)
        state, m = step(state, pmesh.shard_batch(mesh, batch), key)
        losses[name] = float(jax.device_get(m["loss"]))
        assert np.isfinite(losses[name])
    np.testing.assert_allclose(losses["ulysses"], losses["dense"],
                               rtol=1e-5, atol=1e-5)

"""run_eval end-to-end on a synthetic generations/train pair (tiny images,
random-init backbones — checks wiring, scalar names, artifacts; numeric
parity with pretrained weights is the converter's job)."""

import json

import numpy as np
import pytest
from PIL import Image

from dcr_tpu.core.config import EvalConfig
from dcr_tpu.data.tokenizer import HashTokenizer
from dcr_tpu.eval.features import EvalImageFolder
from dcr_tpu.eval.runner import run_eval

# full eval pipeline: excluded from the quick suite (`pytest -m 'not slow'`)
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def eval_dirs(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("evald")
    rng = np.random.default_rng(0)
    gen = tmp / "gens" / "generations"
    gen.mkdir(parents=True)
    for i in range(8):
        Image.fromarray(rng.integers(0, 255, (40, 40, 3), np.uint8)).save(
            gen / f"{i}.png")
    (tmp / "gens" / "generations" / "prompts.txt").write_text(
        "".join(f"prompt {i}\n" for i in range(4)))
    train = tmp / "train"
    caps = {}
    for cls in ["c0", "c1"]:
        d = train / cls
        d.mkdir(parents=True)
        for i in range(5):
            p = d / f"{i}.png"
            Image.fromarray(rng.integers(0, 255, (40, 40, 3), np.uint8)).save(p)
            caps[str(p)] = [f"{cls} image {i}"]
    capj = tmp / "caps.json"
    capj.write_text(json.dumps(caps))
    return tmp, gen, train, capj


def test_eval_image_folder_prompts_alignment(eval_dirs):
    tmp, gen, train, capj = eval_dirs
    q = EvalImageFolder(gen, 32)
    assert len(q) == 8
    # 8 images / 4 prompts -> 2 per prompt
    assert q.captions[0] == "prompt 0" and q.captions[1] == "prompt 0"
    assert q.captions[2] == "prompt 1"
    v = EvalImageFolder(train, 32, caption_json=capj)
    assert len(v) == 10
    assert v.captions[0].startswith("c0 image")


def test_natural_ordering(tmp_path):
    rng = np.random.default_rng(0)
    for name in ["2.png", "10.png", "1.png"]:
        Image.fromarray(rng.integers(0, 255, (8, 8, 3), np.uint8)).save(
            tmp_path / name)
    f = EvalImageFolder(tmp_path, 8)
    assert [p.name for p in f.paths] == ["1.png", "2.png", "10.png"]


def test_run_eval_end_to_end(eval_dirs, cpu_devices, tmp_path):
    tmp, gen, train, capj = eval_dirs
    cfg = EvalConfig(
        query_dir=str(gen), values_dir=str(train),
        pt_style="sscd", arch="resnet50_disc", batch_size=4, image_size=32,
        compute_fid=True, compute_clip_score=True, compute_complexity=True,
        galleries=True, gallery_topk=3, gallery_max_rank=8,
        output_dir=str(tmp_path / "ret_plots"))
    tok = HashTokenizer(1000, 77)
    scalars = run_eval(cfg, tokenizer=tok, values_caption_json=str(capj))
    for key in ("sim_mean", "sim_std", "sim_75pc", "sim_90pc", "sim_95pc",
                "sim_gt_05pc", "bg_mean", "bg_std", "FID_val", "precision",
                "recall", "gen_clipscore", "train_clipscore",
                "corr_entropy_sim", "corr_jpegsize_sim", "corr_tv_sim"):
        assert key in scalars, f"missing scalar {key}"
        assert np.isfinite(scalars[key]) or key.startswith("corr"), key
    out = tmp_path / "ret_plots"
    assert (out / "similarity.npy").exists()
    sim = np.load(out / "similarity.npy")
    assert sim.shape == (8, 10)
    assert (out / "histogram.png").exists()
    assert list((out / "galleries").glob("gallery_rank*.png"))
    assert (out / "fid_stats_values.npz").exists()
    assert (out / "logs" / "metrics.jsonl").exists()


def test_run_eval_splitloss_and_dup_pickle(eval_dirs, cpu_devices, tmp_path):
    import pickle

    tmp, gen, train, capj = eval_dirs
    wpath = tmp_path / "weights.pickle"
    with open(wpath, "wb") as f:
        pickle.dump([5] * 3 + [1] * 7, f)
    cfg = EvalConfig(
        query_dir=str(gen), values_dir=str(train),
        pt_style="sscd", arch="resnet50_disc", batch_size=4, image_size=32,
        similarity_metric="splitloss", num_loss_chunks=2,
        compute_fid=False, compute_clip_score=False, compute_complexity=False,
        galleries=False, dup_weights_pickle=str(wpath),
        output_dir=str(tmp_path / "ret2"))
    scalars = run_eval(cfg, tokenizer=HashTokenizer(1000, 77))
    assert "dupsim_mean" in scalars and "nondupsim_mean" in scalars
    assert "sim_gt_05pc" in scalars


def test_prompts_txt_found_in_parent_dir(tmp_path):
    """Regression: the sampling pipeline writes prompts.txt NEXT TO
    generations/ — eval must find it there."""
    rng = np.random.default_rng(0)
    gen = tmp_path / "run" / "generations"
    gen.mkdir(parents=True)
    for i in range(4):
        Image.fromarray(rng.integers(0, 255, (16, 16, 3), np.uint8)).save(
            gen / f"{i}.png")
    (tmp_path / "run" / "prompts.txt").write_text("a\nb\n")
    f = EvalImageFolder(gen, 16)
    assert f.captions == ["a", "a", "b", "b"]


def test_caption_json_path_alias_matching(tmp_path):
    """Regression: caption tables written with relative paths must still match
    absolute eval paths (basename fallback), with a warning on real misses."""
    rng = np.random.default_rng(0)
    d = tmp_path / "train" / "c0"
    d.mkdir(parents=True)
    for i in range(3):
        Image.fromarray(rng.integers(0, 255, (16, 16, 3), np.uint8)).save(
            d / f"im{i}.png")
    # table keyed by basename-ish relative path from a different root
    capj = tmp_path / "caps.json"
    capj.write_text(json.dumps({f"./other/root/im{i}.png": [f"cap {i}"]
                                for i in range(3)}))
    f = EvalImageFolder(tmp_path / "train", 16, caption_json=capj)
    assert f.captions == ["cap 0", "cap 1", "cap 2"]

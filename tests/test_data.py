import json
import pickle

import numpy as np
import pytest

pytestmark = pytest.mark.fast
from PIL import Image

from dcr_tpu.core.config import DataConfig
from dcr_tpu.data import captions as C
from dcr_tpu.data import duplication as D
from dcr_tpu.data.dataset import ObjectAttributeDataset, list_image_folder
from dcr_tpu.data.loader import DataLoader
from dcr_tpu.data.tokenizer import HashTokenizer, load_tokenizer


@pytest.fixture()
def image_folder(tmp_path):
    rng = np.random.default_rng(0)
    paths = {}
    for cls in ["c0", "c1"]:
        d = tmp_path / "data" / cls
        d.mkdir(parents=True)
        for i in range(6):
            arr = rng.integers(0, 255, (40, 52, 3), np.uint8)
            p = d / f"{cls}_{i}.png"
            Image.fromarray(arr).save(p)
            paths[str(p)] = [f"a {cls} photo number {i}", f"alt caption {i} for {cls}",
                             f"third caption {i}"]
    capfile = tmp_path / "caps.json"
    capfile.write_text(json.dumps(paths))
    return tmp_path / "data", capfile


def _cfg(root, capfile=None, **kw):
    d = dict(train_data_dir=str(root), resolution=32, num_workers=2, seed=7)
    if capfile:
        d["caption_jsons"] = (str(capfile),)
    d.update(kw)
    return DataConfig(**d)


def test_list_image_folder_deterministic(image_folder):
    root, _ = image_folder
    paths, labels, classes = list_image_folder(root)
    assert classes == ["c0", "c1"]
    assert len(paths) == 12
    assert labels == sorted(labels)
    assert paths == sorted(paths)


def test_dataset_nolevel(image_folder):
    root, _ = image_folder
    ds = ObjectAttributeDataset(_cfg(root, class_prompt="nolevel",
                                     instance_prompt="An image"), HashTokenizer(100, 16))
    ex = ds.get(0)
    assert ex.pixel_values.shape == (32, 32, 3)
    assert ex.pixel_values.min() >= -1.0 and ex.pixel_values.max() <= 1.0
    assert ex.caption == "An image"
    assert ex.input_ids.shape == (16,)


def test_dataset_classlevel(image_folder):
    root, _ = image_folder
    ds = ObjectAttributeDataset(_cfg(root, class_prompt="classlevel"), HashTokenizer(100, 16))
    assert ds.get(0).caption == "An image of c0"
    assert ds.get(len(ds) - 1).caption == "An image of c1"


def test_dataset_instancelevel_blip_first_caption(image_folder):
    root, caps = image_folder
    ds = ObjectAttributeDataset(
        _cfg(root, caps, class_prompt="instancelevel_blip"), HashTokenizer(100, 16))
    ex = ds.get(0)
    assert ex.caption.startswith("a c0 photo number")


def test_instancelevel_requires_captions(image_folder):
    root, _ = image_folder
    with pytest.raises(ValueError):
        ObjectAttributeDataset(_cfg(root, class_prompt="instancelevel_blip"),
                               HashTokenizer(100, 16))


def test_dup_image_randomizes_caption_only_for_duplicated(image_folder):
    root, caps = image_folder
    cfg = _cfg(root, caps, class_prompt="instancelevel_blip", duplication="dup_image",
               weight_pc=0.5, dup_weight=10)
    ds = ObjectAttributeDataset(cfg, HashTokenizer(100, 16))
    dup_idx = [i for i in range(len(ds)) if ds.sampling_weights[i] > 1]
    nondup_idx = [i for i in range(len(ds)) if ds.sampling_weights[i] == 1]
    assert dup_idx and nondup_idx
    # non-duplicated: always first caption, any epoch
    for i in nondup_idx[:3]:
        for e in range(3):
            assert ds.get(i, epoch=e).caption == ds.prompts[ds.paths[i]][0]
    # duplicated: caption varies across epochs (3 captions available)
    seen = {ds.get(dup_idx[0], epoch=e).caption for e in range(12)}
    assert len(seen) > 1


def test_weights_cache_roundtrip_and_reference_format(image_folder, tmp_path):
    root, _ = image_folder
    w1 = D.load_or_create_weights(root, 12, 0.25, 5, 42)
    w2 = D.load_or_create_weights(root, 12, 0.25, 5, 42)
    np.testing.assert_array_equal(w1, w2)
    assert (w1 == 5).sum() == 3 and (w1 == 1).sum() == 9
    # file is a plain pickled list of ints, like the reference writes
    path = D.weights_cache_path(root, 0.25, 5, 42)
    with open(path, "rb") as f:
        raw = pickle.load(f)
    assert isinstance(raw, list) and sorted(set(raw)) == [1, 5]
    with pytest.raises(ValueError):
        D.load_or_create_weights(root, 13, 0.25, 5, 42)  # stale cache detected


def test_trainsubset(image_folder):
    root, _ = image_folder
    ds = ObjectAttributeDataset(_cfg(root, class_prompt="nolevel", trainsubset=4),
                                HashTokenizer(100, 16))
    assert len(ds) == 4


def test_mitigation_allcaps_samples_all(image_folder):
    root, caps = image_folder
    cfg = _cfg(root, caps, class_prompt="instancelevel_blip", trainspecial="allcaps")
    ds = ObjectAttributeDataset(cfg, HashTokenizer(100, 16))
    seen = {ds.get(0, epoch=e).caption for e in range(20)}
    assert len(seen) == 3  # all three captions get sampled


def test_mitigation_randwordadd_inserts_two_words(image_folder):
    root, caps = image_folder
    cfg = _cfg(root, caps, class_prompt="instancelevel_blip",
               trainspecial="randwordadd", trainspecial_prob=1.0)
    ds = ObjectAttributeDataset(cfg, HashTokenizer(100, 16))
    base = ds.prompts[ds.paths[0]][0]
    cap = ds.get(0).caption
    assert len(cap.split()) == len(base.split()) + 2


def test_mitigation_wordrepeat_uses_existing_words(image_folder):
    root, caps = image_folder
    cfg = _cfg(root, caps, class_prompt="instancelevel_blip",
               trainspecial="wordrepeat", trainspecial_prob=1.0)
    ds = ObjectAttributeDataset(cfg, HashTokenizer(100, 16))
    base_words = set(ds.prompts[ds.paths[0]][0].split())
    cap = ds.get(0).caption
    assert set(cap.split()) == base_words  # only repeats, no new words
    assert len(cap.split()) == len(ds.prompts[ds.paths[0]][0].split()) + 2


def test_mitigation_randrepl_prob_zero_keeps_caption(image_folder):
    root, caps = image_folder
    cfg = _cfg(root, caps, class_prompt="instancelevel_blip",
               trainspecial="randrepl", trainspecial_prob=0.0)
    ds = ObjectAttributeDataset(cfg, HashTokenizer(100, 16))
    assert ds.get(0).caption == ds.prompts[ds.paths[0]][0]


def test_instancelevel_random_decodes_token_lists(image_folder):
    root, _ = image_folder
    tok = HashTokenizer(100, 16)
    paths, _, _ = list_image_folder(root)
    caps = {p: [str([int(i) for i in np.random.default_rng(7).integers(1, 90, 4)])]
            for p in paths}
    cfg = _cfg(root, class_prompt="instancelevel_random")
    ds = ObjectAttributeDataset(cfg, tok, caption_tables=caps)
    cap = ds.get(0).caption
    assert isinstance(cap, str) and len(cap.split()) == 4


def test_determinism_across_instances(image_folder):
    root, caps = image_folder
    cfg = _cfg(root, caps, class_prompt="instancelevel_blip", random_flip=True,
               center_crop=False)
    ds1 = ObjectAttributeDataset(cfg, HashTokenizer(100, 16))
    ds2 = ObjectAttributeDataset(cfg, HashTokenizer(100, 16))
    e1, e2 = ds1.get(3, epoch=5), ds2.get(3, epoch=5)
    np.testing.assert_array_equal(e1.pixel_values, e2.pixel_values)
    np.testing.assert_array_equal(e1.input_ids, e2.input_ids)
    # different epoch -> different crop
    e3 = ds1.get(3, epoch=6)
    assert not np.array_equal(e1.pixel_values, e3.pixel_values)


def test_loader_batches_and_sharding(image_folder):
    root, _ = image_folder
    ds = ObjectAttributeDataset(_cfg(root, class_prompt="nolevel"), HashTokenizer(100, 16))
    # two "processes" each batch_size=2: global order must partition
    loaders = [DataLoader(ds, batch_size=2, num_workers=2, seed=1,
                          process_index=p, process_count=2) for p in range(2)]
    assert loaders[0].steps_per_epoch() == 3
    all_indices = []
    batches0 = list(loaders[0].epoch(0))
    batches1 = list(loaders[1].epoch(0))
    assert len(batches0) == 3
    for b0, b1 in zip(batches0, batches1):
        assert b0.pixel_values.shape == (2, 32, 32, 3)
        all_indices.extend(b0.index.tolist())
        all_indices.extend(b1.index.tolist())
    assert len(all_indices) == 12 and len(set(all_indices)) == 12  # exact partition
    # reproducible
    again = list(loaders[0].epoch(0))
    np.testing.assert_array_equal(batches0[0].pixel_values, again[0].pixel_values)
    # resume mid-epoch
    resumed = list(loaders[0].epoch(0, start_step=2))
    np.testing.assert_array_equal(resumed[0].index, batches0[2].index)


def test_loader_weighted_replacement_oversamples(image_folder):
    root, caps = image_folder
    cfg = _cfg(root, caps, class_prompt="instancelevel_blip", duplication="dup_both",
               weight_pc=0.25, dup_weight=50)
    ds = ObjectAttributeDataset(cfg, HashTokenizer(100, 16))
    loader = DataLoader(ds, batch_size=4, num_workers=2, seed=3)
    counts = np.zeros(12)
    for e in range(30):
        for b in loader.epoch(e):
            for i in b.index:
                counts[i] += 1
    dup = np.asarray(ds.sampling_weights) > 1
    assert counts[dup].mean() > 5 * counts[~dup].mean()


def test_tokenizer_fallback_and_padding():
    tok = load_tokenizer(None, vocab_size=1000, model_max_length=16)
    ids = tok(["hello world", "a much longer caption with many more words than fit in the window easily truncated"])
    assert ids.shape == (2, 16)
    assert ids.dtype == np.int32
    assert ids[0, 0] == tok.bos_token_id
    assert tok.eos_token_id in ids[0]
    # deterministic
    ids2 = tok("hello world")
    np.testing.assert_array_equal(ids[0], ids2[0])
    # decode inverts for hash tokenizer
    assert tok.decode(tok.encode("hello world")) == "hello world"


def test_clip_bpe_tokenizer_roundtrip(tmp_path):
    from dcr_tpu.data.tokenizer import ClipBPETokenizer, _bytes_to_unicode

    # minimal vocab: all byte tokens, word-final variants, one merge
    b2u = _bytes_to_unicode()
    vocab = {}
    for ch in b2u.values():
        vocab[ch] = len(vocab)
        vocab[ch + "</w>"] = len(vocab)
    vocab["he"] = len(vocab)
    vocab["llo</w>"] = len(vocab)
    vocab["hello</w>"] = len(vocab)
    vocab["<|startoftext|>"] = len(vocab)
    vocab["<|endoftext|>"] = len(vocab)
    (tmp_path / "vocab.json").write_text(json.dumps(vocab))
    (tmp_path / "merges.txt").write_text("#version: 0.2\nh e\nl l\nll o</w>\nhe llo</w>\n")
    tok = ClipBPETokenizer(tmp_path / "vocab.json", tmp_path / "merges.txt",
                           model_max_length=8)
    ids = tok.encode("hello")
    assert ids == [vocab["hello</w>"]]
    assert tok.decode(ids) == "hello"
    batch = tok("hello hello")
    assert batch.shape == (1, 8)
    assert batch[0, 0] == tok.bos_token_id
    # unknown-ish text still tokenizes via byte fallback
    assert tok.decode(tok.encode("hexo")) == "hexo"
    # loader picks BPE when files exist
    from dcr_tpu.data.tokenizer import load_tokenizer
    got = load_tokenizer(tmp_path)
    assert isinstance(got, ClipBPETokenizer)


def test_dup_image_caption_varies_per_occurrence_within_epoch(image_folder):
    """Regression: the same duplicated image drawn at different plan slots in ONE
    epoch must redraw its caption (the reference redraws per __getitem__)."""
    root, caps = image_folder
    cfg = _cfg(root, caps, class_prompt="instancelevel_blip", duplication="dup_image",
               weight_pc=0.5, dup_weight=10)
    ds = ObjectAttributeDataset(cfg, HashTokenizer(100, 16))
    dup_pos = next(i for i in range(len(ds)) if ds.sampling_weights[i] > 1)
    seen_caps = {ds.get(dup_pos, epoch=0, slot=s).caption for s in range(12)}
    assert len(seen_caps) > 1
    seen_px = {ds.get(dup_pos, epoch=0, slot=s).pixel_values.tobytes()
               for s in range(6)}
    assert len(seen_px) > 1  # crops redraw per occurrence too (random crop on)


def test_loader_no_leaked_worker_threads(image_folder):
    """Regression: breaking out of an epoch mid-iteration must not leave worker
    threads blocked in queue.put."""
    import threading
    import time

    root, _ = image_folder
    ds = ObjectAttributeDataset(_cfg(root, class_prompt="nolevel"), HashTokenizer(100, 16))
    before = threading.active_count()
    loader = DataLoader(ds, batch_size=1, num_workers=6, seed=1, prefetch=2)
    it = loader.epoch(0)
    next(it)
    it.close()  # triggers the generator's finally
    time.sleep(0.3)
    assert threading.active_count() <= before + 1


def test_committed_bpe_fixture_is_real_format():
    """tests/fixtures/bpe holds a LEARNED byte-level BPE table in CLIP's exact
    file format (256 byte symbols + 256 word-final symbols + merges in rank
    order + specials; '#version' merges header) — regenerable with
    tools/gen_bpe_fixture.py. Guards the fixture against drift and exercises
    real-BPE truncation, which HashTokenizer can't."""
    from pathlib import Path

    from dcr_tpu.data.tokenizer import ClipBPETokenizer, load_tokenizer

    fix = Path(__file__).parent / "fixtures" / "bpe"
    assert (fix / "merges.txt").read_text().startswith("#version:")
    tok = load_tokenizer(fix)
    assert isinstance(tok, ClipBPETokenizer)
    vocab = json.loads((fix / "vocab.json").read_text())
    merges = [l for l in (fix / "merges.txt").read_text().splitlines()[1:] if l]
    assert len(vocab) == 512 + len(merges) + 2
    assert vocab["<|endoftext|>"] == len(vocab) - 1

    # corpus words merge to single tokens; every id is in range
    ids = tok.encode("an image of garbage truck")
    assert len(ids) == 5
    assert all(0 <= i < tok.vocab_size for i in ids)
    assert tok.decode(ids) == "an image of garbage truck"

    # real truncation: a caption longer than the context clips to 77 with
    # BOS first and EOS present (reference datasets.py:144-150 semantics)
    long_caption = " ".join(["unmergeablewordxyz"] * 40)
    batch = tok(long_caption)
    assert batch.shape == (1, 77)
    assert batch[0, 0] == tok.bos_token_id
    assert batch[0, -1] == tok.eos_token_id  # truncated -> EOS is the cap


def test_instancelevel_random_through_real_bpe(image_folder):
    """The token-id decode path (reference datasets.py:140-142) through the
    REAL BPE decoder: ids -> text -> re-encode stays in-vocab."""
    from pathlib import Path

    from dcr_tpu.data.tokenizer import load_tokenizer

    tok = load_tokenizer(Path(__file__).parent / "fixtures" / "bpe")
    root, _ = image_folder
    paths, _, _ = list_image_folder(root)
    rng = np.random.default_rng(3)
    caps = {p: [str([int(i) for i in rng.integers(1, 500, 4)])] for p in paths}
    cfg = _cfg(root, class_prompt="instancelevel_random")
    ds = ObjectAttributeDataset(cfg, tok, caption_tables=caps)
    ex = ds.get(0)
    assert ex.input_ids.shape == (77,)
    assert ex.input_ids.max() < tok.vocab_size

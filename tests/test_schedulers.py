import jax
import jax.numpy as jnp
import numpy as np

from dcr_tpu.models import schedulers as S

import pytest

pytestmark = pytest.mark.fast


def _sched(pred="epsilon"):
    return S.make_schedule(prediction_type=pred)


def test_beta_schedules_match_closed_form():
    s = S.make_schedule(num_train_timesteps=10, beta_schedule="linear",
                        beta_start=1e-4, beta_end=2e-2)
    betas = np.linspace(1e-4, 2e-2, 10)
    np.testing.assert_allclose(np.asarray(s.betas), betas, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(s.alphas_cumprod), np.cumprod(1 - betas), rtol=1e-6)

    s2 = S.make_schedule(num_train_timesteps=10, beta_schedule="scaled_linear",
                         beta_start=0.00085, beta_end=0.012)
    b2 = np.linspace(0.00085 ** 0.5, 0.012 ** 0.5, 10) ** 2
    np.testing.assert_allclose(np.asarray(s2.betas), b2, rtol=1e-6)

    s3 = S.make_schedule(num_train_timesteps=50, beta_schedule="squaredcos_cap_v2")
    assert np.all(np.asarray(s3.betas) > 0) and np.all(np.asarray(s3.betas) <= 0.999)


def test_add_noise_closed_form():
    s = _sched()
    x0 = jnp.ones((2, 4, 4, 1))
    noise = jnp.full_like(x0, 2.0)
    t = jnp.array([0, 500])
    xt = S.add_noise(s, x0, noise, t)
    acp = np.asarray(s.alphas_cumprod)
    for i, ti in enumerate([0, 500]):
        expect = np.sqrt(acp[ti]) * 1.0 + np.sqrt(1 - acp[ti]) * 2.0
        np.testing.assert_allclose(np.asarray(xt[i]), expect, rtol=1e-5)


def test_velocity_and_prediction_conversions_consistent():
    s = _sched("v_prediction")
    key = jax.random.key(0)
    x0 = jax.random.normal(key, (3, 8, 8, 4))
    noise = jax.random.normal(jax.random.fold_in(key, 1), x0.shape)
    t = jnp.array([10, 400, 900])
    v = S.get_velocity(s, x0, noise, t)
    # inverting the v-prediction must recover x0 and eps
    x0_hat, eps_hat = S.pred_to_x0_eps(s, v, S.add_noise(s, x0, noise, t), t)
    np.testing.assert_allclose(np.asarray(x0_hat), np.asarray(x0), atol=1e-4)
    np.testing.assert_allclose(np.asarray(eps_hat), np.asarray(noise), atol=1e-4)


def test_epsilon_conversion_consistent():
    s = _sched()
    key = jax.random.key(1)
    x0 = jax.random.normal(key, (2, 4, 4, 4))
    noise = jax.random.normal(jax.random.fold_in(key, 1), x0.shape)
    t = jnp.array([100, 800])
    xt = S.add_noise(s, x0, noise, t)
    x0_hat, eps_hat = S.pred_to_x0_eps(s, noise, xt, t)
    np.testing.assert_allclose(np.asarray(x0_hat), np.asarray(x0), atol=1e-4)
    np.testing.assert_allclose(np.asarray(eps_hat), np.asarray(noise), atol=1e-6)


def test_training_target_dispatch():
    key = jax.random.key(2)
    x0 = jax.random.normal(key, (2, 4, 4, 4))
    noise = jax.random.normal(jax.random.fold_in(key, 1), x0.shape)
    t = jnp.array([5, 99])
    np.testing.assert_array_equal(
        np.asarray(S.training_target(_sched("epsilon"), x0, noise, t)), np.asarray(noise))
    sv = _sched("v_prediction")
    np.testing.assert_array_equal(
        np.asarray(S.training_target(sv, x0, noise, t)),
        np.asarray(S.get_velocity(sv, x0, noise, t)))


def test_ddim_perfect_model_recovers_x0():
    """With a model that predicts the true eps, DDIM from x_T should march toward x0."""
    s = _sched()
    key = jax.random.key(3)
    x0 = jax.random.normal(key, (1, 4, 4, 1))
    noise = jax.random.normal(jax.random.fold_in(key, 1), x0.shape)
    ts = S.inference_timesteps(s, 10)
    x = S.add_noise(s, x0, noise, jnp.full((1,), int(ts[0])))
    for i in range(len(ts)):
        t = jnp.full((1,), int(ts[i]))
        prev_t = jnp.full((1,), int(ts[i + 1]) if i + 1 < len(ts) else -1)
        # oracle eps for current x: eps = (x - sqrt(acp) x0)/sqrt(1-acp)
        a = jnp.sqrt(s.alphas_cumprod[t]).reshape(-1, 1, 1, 1)
        sd = jnp.sqrt(1 - s.alphas_cumprod[t]).reshape(-1, 1, 1, 1)
        eps = (x - a * x0) / sd
        x = S.ddim_step(s, eps, x, t, prev_t)
    np.testing.assert_allclose(np.asarray(x), np.asarray(x0), atol=1e-3)


def test_ddpm_step_terminal_is_mean_only():
    s = _sched()
    key = jax.random.key(4)
    x0 = jax.random.normal(key, (1, 2, 2, 1))
    noise = jax.random.normal(jax.random.fold_in(key, 1), x0.shape)
    t = jnp.array([0])
    xt = S.add_noise(s, x0, noise, t)
    out1 = S.ddpm_step(s, noise, xt, t, jnp.array([-1]), jax.random.key(7))
    out2 = S.ddpm_step(s, noise, xt, t, jnp.array([-1]), jax.random.key(8))
    # at prev_t=-1 no noise is added -> deterministic, and equals x0_hat
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), atol=1e-6)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(x0), atol=1e-4)


def test_dpmpp_2m_perfect_model_recovers_x0():
    s = _sched()
    key = jax.random.key(5)
    x0 = jax.random.normal(key, (1, 4, 4, 1))
    ts = S.inference_timesteps(s, 20)
    x = jax.random.normal(jax.random.fold_in(key, 2), x0.shape) * float(
        jnp.sqrt(1 - s.alphas_cumprod[int(ts[0])]))
    x = x + x0 * float(jnp.sqrt(s.alphas_cumprod[int(ts[0])]))
    state = S.dpm_init_state(x.shape)
    for i in range(len(ts)):
        t = jnp.asarray(int(ts[i]))
        prev_t = jnp.asarray(int(ts[i + 1]) if i + 1 < len(ts) else -1)
        a = jnp.sqrt(s.alphas_cumprod[t])
        sd = jnp.sqrt(1 - s.alphas_cumprod[t])
        eps = (x - a * x0) / sd
        x, state = S.dpmpp_2m_step(s, eps, x, t, prev_t, state)
    np.testing.assert_allclose(np.asarray(x), np.asarray(x0), atol=5e-3)


def test_steps_jittable():
    s = _sched()
    x = jnp.zeros((1, 4, 4, 1))
    f = jax.jit(lambda m, x, t, p: S.ddim_step(s, m, x, t, p))
    out = f(x, x, jnp.array([500]), jnp.array([400]))
    assert out.shape == x.shape


def test_steps_support_batched_prev_t():
    """Regression: [B] t/prev_t must broadcast correctly (incl. C == B shapes)."""
    s = _sched()
    key = jax.random.key(6)
    x = jax.random.normal(key, (2, 4, 4, 2))  # channels == batch to catch misbroadcast
    eps = jax.random.normal(jax.random.fold_in(key, 1), x.shape)
    t = jnp.array([500, 300])
    prev_t = jnp.array([400, -1])
    out = S.ddim_step(s, eps, x, t, prev_t)
    assert out.shape == x.shape
    # per-sample result equals the scalar-t computation for that sample
    for i in range(2):
        single = S.ddim_step(s, eps[i:i + 1], x[i:i + 1],
                             t[i:i + 1], prev_t[i:i + 1])
        np.testing.assert_allclose(np.asarray(out[i]), np.asarray(single[0]), atol=1e-6)
    out2 = S.ddpm_step(s, eps, x, t, prev_t, jax.random.key(9))
    assert out2.shape == x.shape
    state = S.dpm_init_state(x.shape, batch_shape=t.shape)
    out3, state = S.dpmpp_2m_step(s, eps, x, t, prev_t, state)
    assert out3.shape == x.shape and state.prev_lambda.shape == t.shape


def test_inference_timesteps_guard():
    s = S.make_schedule(num_train_timesteps=10)
    import pytest
    with pytest.raises(ValueError):
        S.inference_timesteps(s, 50)

"""End-to-end smoke: real image folder -> Trainer.train() -> checkpoints,
metrics, HF-layout export, resume (BASELINE.json config 1 analogue on CPU)."""

import json

import numpy as np
import pytest
from PIL import Image

from dcr_tpu.core.config import DataConfig, ModelConfig, OptimConfig, TrainConfig
from dcr_tpu.diffusion.trainer import Trainer

# end-to-end train loops: excluded from the quick suite (`pytest -m 'not slow'`)
pytestmark = pytest.mark.slow


@pytest.fixture()
def train_setup(tmp_path):
    rng = np.random.default_rng(0)
    for cls in ["c0", "c1"]:
        d = tmp_path / "data" / cls
        d.mkdir(parents=True)
        for i in range(8):
            Image.fromarray(rng.integers(0, 255, (20, 20, 3), np.uint8)).save(
                d / f"{i}.png")
    cfg = TrainConfig(
        output_dir=str(tmp_path / "run"),
        seed=0,
        train_batch_size=2,
        max_train_steps=6,
        num_train_epochs=10,
        mixed_precision="no",
        save_steps=1000,
        modelsavesteps=4,
        log_every=2,
        model=ModelConfig.tiny(),
        data=DataConfig(train_data_dir=str(tmp_path / "data"), resolution=16,
                        class_prompt="nolevel", num_workers=2, seed=0),
        optim=OptimConfig(learning_rate=1e-4, lr_scheduler="constant",
                          lr_warmup_steps=0),
    )
    return cfg, tmp_path


def test_trainer_end_to_end(train_setup):
    cfg, tmp_path = train_setup
    trainer = Trainer(cfg)
    metrics = trainer.train()
    assert np.isfinite(metrics["loss"])
    run = tmp_path / "run"
    assert (run / "config.json").exists()
    # metrics jsonl written
    lines = [json.loads(l) for l in (run / "logs" / "metrics.jsonl").read_text().splitlines()]
    assert any("loss" in l for l in lines)
    assert any("images_per_sec" in l for l in lines)
    # orbax checkpoints at step 4 and final 6
    steps = trainer.ckpt.all_steps()
    assert 4 in steps and 6 in steps
    # HF-layout export
    assert (run / "checkpoint" / "unet" / "params.npz").exists()
    assert (run / "checkpoint" / "scheduler" / "scheduler_config.json").exists()
    assert (run / "checkpoint" / "model_index.json").exists()


def test_trainer_resume(train_setup):
    cfg, tmp_path = train_setup
    trainer = Trainer(cfg)
    trainer.train()
    # resume: a fresh Trainer on the same output_dir picks up step 6 and
    # continues to 8
    cfg2 = cfg
    cfg2.max_train_steps = 8
    trainer2 = Trainer(cfg2)
    assert trainer2.maybe_resume() == 6
    trainer2.train()
    assert 8 in trainer2.ckpt.all_steps()


def test_ema_weights_are_exported(train_setup):
    """Regression: with ema_decay>0 the exported unet must be the EMA weights."""
    cfg, tmp_path = train_setup
    cfg.ema_decay = 0.5
    cfg.output_dir = str(tmp_path / "run_ema")
    trainer = Trainer(cfg)
    trainer.train()
    import jax
    import numpy as np

    from dcr_tpu.core.checkpoint import import_hf_layout

    exported = import_hf_layout(tmp_path / "run_ema" / "checkpoint", "unet")
    ema_leaf = np.asarray(jax.tree.leaves(jax.device_get(trainer.state.ema_params))[0])
    raw_leaf = np.asarray(jax.tree.leaves(jax.device_get(trainer.state.unet_params))[0])
    exp_leaf = np.asarray(jax.tree.leaves(exported)[0])
    np.testing.assert_array_equal(exp_leaf, ema_leaf)
    assert not np.array_equal(exp_leaf, raw_leaf)


def test_sample_hook_writes_grids(train_setup):
    from dcr_tpu.diffusion.sample_hook import make_sample_hook

    cfg, tmp_path = train_setup
    cfg.output_dir = str(tmp_path / "run_hook")
    cfg.save_steps = 3
    cfg.max_train_steps = 3
    cfg.data.class_prompt = "classlevel"
    trainer = Trainer(cfg, sample_hook=make_sample_hook(
        num_inference_steps=2, images_per_prompt=2, max_prompts=2))
    trainer.train()
    grids = list((tmp_path / "run_hook" / "generations").glob("step_*.png"))
    assert grids, "no sample grids written"


def test_scale_lr(train_setup):
    cfg, tmp_path = train_setup
    cfg.output_dir = str(tmp_path / "run_slr")
    cfg.optim.scale_lr = True
    cfg.optim.learning_rate = 1e-6
    trainer = Trainer(cfg)
    import jax

    expected = 1e-6 * cfg.optim.gradient_accumulation_steps * \
        cfg.train_batch_size * jax.device_count()
    assert trainer.cfg.optim.learning_rate == pytest.approx(expected)


def test_nan_guard_checkpoints_and_raises(train_setup, monkeypatch):
    cfg, tmp_path = train_setup
    cfg.output_dir = str(tmp_path / "run_nan")
    cfg.log_every = 1
    trainer = Trainer(cfg)
    real_step = trainer.step_fn

    def poisoned(state, batch, key):
        state, metrics = real_step(state, batch, key)
        metrics["loss"] = np.float32("nan")
        return state, metrics

    trainer.step_fn = poisoned
    with pytest.raises(FloatingPointError, match="last good checkpoint"):
        trainer.train()
    # corrupted state must NOT have been saved (params absorbed the NaN update)
    assert trainer.ckpt.all_steps() == []
    trainer.ckpt.close()  # release orbax's async executor (train() never got to)


def test_preemption_checkpoints_and_resumes(train_setup):
    """Simulated preemption mid-training: checkpoint written, resume continues."""
    cfg, tmp_path = train_setup
    cfg.output_dir = str(tmp_path / "run_preempt")
    cfg.max_train_steps = 6
    cfg.modelsavesteps = 100
    trainer = Trainer(cfg)
    trainer.install_preemption_handler()
    real_step = trainer.step_fn
    calls = {"n": 0}

    def step_then_preempt(state, batch, key):
        calls["n"] += 1
        if calls["n"] == 2:
            trainer._preempted = True  # what the signal handler sets
        return real_step(state, batch, key)

    trainer.step_fn = step_then_preempt
    trainer.train()
    assert trainer.ckpt.all_steps() == [2]
    # resume from the preemption checkpoint
    trainer2 = Trainer(cfg)
    assert trainer2.maybe_resume() == 2
    trainer2.train()
    assert 6 in trainer2.ckpt.all_steps()


def test_config_file_presets_load():
    from dcr_tpu.core.config import TrainConfig, load_config
    from pathlib import Path

    repo = Path(__file__).parent.parent
    smoke = load_config(TrainConfig, repo / "configs" / "smoke_cpu.json")
    assert smoke.model.sample_size == 8
    full = load_config(TrainConfig, repo / "configs" / "imagenette_sd21_256.json")
    assert full.train_batch_size == 16
    assert full.optim.lr_warmup_steps == 5000
    assert full.model.block_out_channels == (320, 640, 1280, 1280)


def test_sync_step_cadence_with_grad_accum(train_setup):
    """With gradient accumulation N, the observable cadences (save_steps /
    modelsavesteps / max_train_steps) count optimizer (sync) steps — the
    reference's accelerate global_step semantics (diff_train.py:669) — while
    internal counting stays in micro-steps."""
    import jax

    cfg, tmp_path = train_setup
    cfg.output_dir = str(tmp_path / "run_accum")
    cfg.optim.gradient_accumulation_steps = 2
    cfg.max_train_steps = 4          # sync steps -> 8 micro-steps
    cfg.modelsavesteps = 2           # saves after sync steps 2 and 4
    cfg.save_steps = 3               # sample hook fires at sync step 3 only
    hook_calls = []
    trainer = Trainer(cfg, sample_hook=lambda tr, s: hook_calls.append(s))
    trainer.train()
    assert int(jax.device_get(trainer.state.step)) == 8
    steps = trainer.ckpt.all_steps()  # checkpoint labels stay micro-step
    assert 4 in steps and 8 in steps
    assert hook_calls == [3]


def test_sample_hook_instancelevel_prompts_from_captions(train_setup):
    """instancelevel_blip grids draw their prompts from the training caption
    tables, seeded by generation_seed (reference diff_train.py:579-607) —
    not from classnames or the instance prompt."""
    import json as _json

    from dcr_tpu.diffusion.sample_hook import make_sample_hook

    cfg, base = train_setup
    table = {}
    for cls in ("c0", "c1"):
        for p in sorted((base / "data" / cls).glob("*.png")):
            table[str(p)] = [f"a photo about {cls}/{p.stem}"]
    cap_json = base / "blip.json"
    cap_json.write_text(_json.dumps(table))
    cfg.output_dir = str(base / "run_hook_blip")
    cfg.save_steps = 2
    cfg.max_train_steps = 2
    cfg.data.class_prompt = "instancelevel_blip"
    cfg.data.caption_jsons = (str(cap_json),)
    cfg.train_batch_size = 1         # global batch 8 fits the 10-image subset
    cfg.data.trainsubset = 10        # grid prompts must respect the subset
    hook = make_sample_hook(num_inference_steps=2, images_per_prompt=2,
                            max_prompts=2)
    trainer = Trainer(cfg, sample_hook=hook)
    trainer.train()
    grids = list((base / "run_hook_blip" / "generations").glob("step_*.png"))
    assert grids, "no sample grids written"
    # provenance: prompts came from the caption table (first captions), and
    # only from images inside the training subset
    active_paths = {trainer.dataset.paths[int(i)]
                    for i in trainer.dataset.active_indices}
    allowed = {table[p][0] for p in table if p in active_paths}
    assert hook.state["prompts"], "hook never selected prompts"
    for p in hook.state["prompts"]:
        assert p in allowed, (p, sorted(allowed)[:3])

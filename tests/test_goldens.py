"""Golden activation fixtures: tiny-shape forward passes recorded once and
checked on every run, so numeric drift from refactors (layout changes, fusion
rewrites, epsilon edits) is caught immediately (SURVEY.md §4 item 2 — the
reference has nothing like this).

Regenerate deliberately after an intended numeric change:
    python tests/test_goldens.py regenerate
"""

import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# golden forwards incl. big models: excluded from the quick suite (`pytest -m 'not slow'`)
pytestmark = pytest.mark.slow

GOLDEN_DIR = Path(__file__).parent / "goldens"


def _compute_goldens() -> dict[str, np.ndarray]:
    from dcr_tpu.core.config import ModelConfig
    from dcr_tpu.models import schedulers as S
    from dcr_tpu.models.clip_text import init_clip_text
    from dcr_tpu.models.resnet import init_sscd
    from dcr_tpu.models.unet2d import init_unet
    from dcr_tpu.models.vae import init_vae

    cfg = ModelConfig.tiny()
    out: dict[str, np.ndarray] = {}

    unet, up = init_unet(cfg, jax.random.key(0))
    x = jax.random.normal(jax.random.key(10), (1, 8, 8, 4))
    ctx = jax.random.normal(jax.random.key(11), (1, 16, 32))
    out["unet"] = np.asarray(unet.apply({"params": up}, x, jnp.array([7]), ctx))

    vae, vp = init_vae(cfg, jax.random.key(1))
    img = jax.random.normal(jax.random.key(12), (1, 16, 16, 3))
    dist = vae.apply({"params": vp}, img, method=vae.encode)
    out["vae_mean"] = np.asarray(dist.mean)
    out["vae_decode"] = np.asarray(
        vae.apply({"params": vp}, dist.mean, method=vae.decode))

    clip, cp = init_clip_text(cfg, jax.random.key(2))
    ids = (jnp.arange(16, dtype=jnp.int32)[None] * 7) % cfg.text_vocab_size
    out["clip_text"] = np.asarray(clip.apply({"params": cp}, ids).last_hidden_state)

    sscd, sp = init_sscd(jax.random.key(3), image_size=32)
    out["sscd"] = np.asarray(
        sscd.apply({"params": sp}, jax.random.normal(jax.random.key(13),
                                                     (1, 32, 32, 3))))

    sched = S.make_schedule()
    x0 = jax.random.normal(jax.random.key(14), (1, 4, 4, 4))
    noise = jax.random.normal(jax.random.key(15), x0.shape)
    out["add_noise"] = np.asarray(S.add_noise(sched, x0, noise, jnp.array([321])))
    return out


def regenerate() -> None:
    GOLDEN_DIR.mkdir(exist_ok=True)
    np.savez(GOLDEN_DIR / "tiny_forward.npz", **_compute_goldens())
    print(f"wrote {GOLDEN_DIR / 'tiny_forward.npz'}")


@pytest.mark.skipif(not (GOLDEN_DIR / "tiny_forward.npz").exists(),
                    reason="no golden fixtures recorded")
def test_forward_passes_match_goldens():
    got = _compute_goldens()
    with np.load(GOLDEN_DIR / "tiny_forward.npz") as z:
        assert set(got) == set(z.files), (
            f"golden key set changed (recorded {sorted(z.files)}, computed "
            f"{sorted(got)}) — regenerate with "
            "`python tests/test_goldens.py regenerate`")
        for name in z.files:
            np.testing.assert_allclose(
                got[name], z[name], atol=2e-4, rtol=2e-4,
                err_msg=f"golden drift in {name!r} — if intended, regenerate "
                        "with `python tests/test_goldens.py regenerate`")


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "regenerate":
        sys.path.insert(0, str(Path(__file__).parent.parent))  # repo root
        import jax as _jax

        _jax.config.update("jax_platforms", "cpu")
        regenerate()

import numpy as np
import pytest

pytestmark = pytest.mark.fast

from dcr_tpu.eval import retrieval_metrics as RM
from dcr_tpu.utils import profiling, provenance


def test_retrieval_metrics_perfect_ranking():
    sim = np.array([[0.9, 0.1, 0.5], [0.2, 0.8, 0.3]])
    rel = np.array([[True, False, False], [False, True, True]])
    rep = RM.retrieval_report(sim, rel, ks=(1, 2))
    # q1: relevant at rank 1 -> AP 1; q2: relevant at ranks 1,2 -> AP 1
    assert rep["mAP"] == pytest.approx(1.0)
    assert rep["MRR"] == 1.0
    assert rep["precision@1"] == 1.0
    assert rep["recall@2"] == pytest.approx(1.0)
    # non-trivial case: q with rel at ranks 1 and 3 of 3
    sim2 = np.array([[0.9, 0.5, 0.1]])
    rel2 = np.array([[True, False, True]])
    assert RM.mean_average_precision(sim2, rel2) == pytest.approx((1 + 2 / 3) / 2)
    assert RM.recall_at_k(sim2, rel2, 2) == pytest.approx(0.5)


def test_average_precision_edge_cases():
    assert np.isnan(RM.average_precision([False, False], 0))
    assert RM.average_precision([False, False], 2) == 0.0
    assert RM.average_precision([True, True], 2) == 1.0


def test_step_timer_and_mfu():
    t = profiling.StepTimer(flops_per_step=1e9)
    for _ in range(3):
        t.tick(items=4)
    rep = t.report()
    assert rep["steps_per_sec"] > 0
    assert rep["items_per_sec"] > 0
    assert "mfu" in rep and rep["mfu"] >= 0


def test_step_timer_mfu_formula_is_per_device(monkeypatch):
    """Pin the MFU formula: flops_per_step is the PER-DEVICE share
    (flops_of_jitted is post-GSPMD cost analysis), so
    mfu = (flops_per_step * steps / dt) / (peak * 1e12) with NO device_count
    in the denominator — a run achieving exactly per-chip peak reports
    mfu == 1.0 whatever the device count (the old formula divided by
    device_count and under-reported by that factor)."""
    import jax

    n_dev = jax.device_count()
    assert n_dev > 1  # conftest forces 8 virtual devices; the regression
    #                   is only observable with more than one
    peak_tflops = profiling.chip_peak_tflops()
    t = profiling.StepTimer(flops_per_step=peak_tflops * 1e12)  # peak/step/chip
    t._t0 -= 1.0                      # pretend exactly 1s elapsed
    monkeypatch.setattr(profiling.time, "perf_counter", lambda: t._t0 + 1.0)
    t.tick(items=1)
    rep = t.report()
    assert rep["mfu"] == pytest.approx(1.0, rel=1e-6)
    assert rep["tflops_per_sec"] == pytest.approx(peak_tflops, rel=1e-6)
    assert rep["tflops_per_sec_total"] == pytest.approx(peak_tflops * n_dev,
                                                        rel=1e-6)


def test_compiled_flops_returns_positive():
    import jax.numpy as jnp

    flops = profiling.compiled_flops(lambda a, b: a @ b,
                                     jnp.zeros((64, 64)), jnp.zeros((64, 64)))
    if flops is not None:
        assert flops >= 2 * 64 ** 3 * 0.9


def test_provenance_stamp(tmp_path):
    p = provenance.stamp(tmp_path)
    import json

    d = json.loads(p.read_text())
    assert {"sha", "branch", "dirty", "python", "time"} <= set(d)
    assert len(d["sha"]) >= 7

"""dcr-slo tests: declarative SLO engine + continuous quality observability.

Fast tier (no model, no subprocess): the multi-window burn-rate state
machine (breach needs BOTH windows, a lone spike cannot breach, warn
hysteresis, recovery, sustained-breach flight-recorder dump), exposition
parsing tolerance, objective derivation from config (absent planes produce
absent objectives), the supervisor's signal snapshot over the scrape cache
(a stale scrape drives availability DOWN; shed/coverage come from per-tick
deltas so one burst can never latch; a restarted worker's
backwards-moving counter clamps instead of going negative), the online
recall probe vs the exact oracle (the ±0.05 acceptance) plus its
``recall_degrade`` drill, the dcr-live lag gauges draining to ~0 after
compaction, the ``ingest_stall`` drill (rows delayed, never dropped),
``GET /slo`` and the stdlib ``dcr-status`` CLI against a stub fleet (exit
codes 0/1/2), tools/bench_report over the banked artifacts, and the
trace_report SLO-timeline + sample-weighted recall sections.

Slow tier (CI `slo` job): the acceptance e2e — a real 2-worker fleet with
an injected ``worker_crash`` walks availability ok -> breach -> ok on
``GET /slo`` with zero dropped requests and ``slo/breach``/``slo/recover``
events in the fleet trace; and a real IngestPump under ``ingest_stall``
drives the ``ingest_lag_s`` objective through the same round trip via the
supervisor's own signal plumbing, recovering to ~0 lag after compaction.
"""

import json
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from dcr_tpu.cli import status as cli_status
from dcr_tpu.core import tracing
from dcr_tpu.core.config import (FleetConfig, IngestConfig, RiskConfig,
                                 ServeConfig, SloConfig)
from dcr_tpu.obs.recall_probe import RecallProbe
from dcr_tpu.obs.slo import (BREACH, OK, WARN, SloEngine, SloObjective,
                             default_objectives, parse_exposition)
from dcr_tpu.utils import faults
from tools import bench_report, trace_report

DIM = 16
REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _clean_tracing():
    tracing.reset_for_tests()
    yield
    tracing.reset_for_tests()


def _cfg(**kw) -> SloConfig:
    """Tight windows + budget 0.5 (all-bad burn = 2.0 = breach_burn), no
    flight-recorder dump unless a test asks for one."""
    base = dict(short_window_s=10.0, long_window_s=30.0, warn_burn=1.0,
                breach_burn=2.0, recover_burn=0.5, budget=0.5,
                dump_after_s=-1.0)
    base.update(kw)
    return SloConfig(**base)


def _avail_engine(cfg=None) -> SloEngine:
    return SloEngine(cfg or _cfg(), [SloObjective(
        "availability", "availability", "min", 0.9, "alive fraction")])


def _gauge(name: str) -> float:
    return tracing.registry().gauge(name).value


def _counter(name: str) -> float:
    return tracing.registry().counter(name).value


# ---------------------------------------------------------------------------
# 1. the burn-rate state machine
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_breach_needs_both_windows_then_recovers():
    eng = _avail_engine()
    t0 = 1000.0
    for i in range(30):                       # healthy history
        eng.observe({"availability": 1.0}, now=t0 + i)
    doc = eng.doc()
    assert doc["state"] == OK
    assert doc["objectives"]["availability"]["burn_short"] == 0.0

    seen = []
    for i in range(41):                       # sustained outage
        eng.observe({"availability": 0.5}, now=t0 + 30 + i)
        seen.append(eng.doc()["objectives"]["availability"]["state"])
    # the short window saturates first (warn), the long window only after
    # the healthy history ages out — warn strictly precedes breach
    assert WARN in seen and BREACH in seen
    assert seen.index(WARN) < seen.index(BREACH)
    assert seen[-1] == BREACH and eng.breached()
    assert _counter("slo/breach_total") == 1
    assert _counter("slo/breach_total/availability") == 1
    assert _gauge("slo/state/availability") == 2
    obj = eng.doc()["objectives"]["availability"]
    assert obj["breach_total"] == 1 and obj["value"] == 0.5
    assert obj["breach_for_s"] > 0

    for i in range(15):                       # recovery
        eng.observe({"availability": 1.0}, now=t0 + 72 + i)
    doc = eng.doc()
    assert doc["state"] == OK and not eng.breached()
    assert doc["objectives"]["availability"]["breach_for_s"] == 0.0
    assert doc["objectives"]["availability"]["breach_total"] == 1
    assert _counter("slo/breach_total") == 1   # latched history, not state
    assert _gauge("slo/state/availability") == 0


@pytest.mark.fast
def test_short_spike_warns_but_cannot_breach():
    eng = _avail_engine()
    t0 = 2000.0
    for i in range(30):
        eng.observe({"availability": 1.0}, now=t0 + i)
    states = set()
    for i in range(12):                       # 12s spike < long window
        eng.observe({"availability": 0.0}, now=t0 + 30 + i)
        states.add(eng.doc()["objectives"]["availability"]["state"])
    assert states == {OK, WARN}               # the long window vetoed it
    assert _counter("slo/breach_total") == 0
    for i in range(15):                       # hysteresis: warn -> ok
        eng.observe({"availability": 1.0}, now=t0 + 42 + i)
    assert eng.doc()["objectives"]["availability"]["state"] == OK


@pytest.mark.fast
def test_none_signal_drains_window_instead_of_latching():
    """Satellite 5b at the engine level: after a shed burst the signal goes
    None (no traffic). The verdict must decay by time, not latch."""
    eng = SloEngine(_cfg(), [SloObjective(
        "shed_rate", "shed_rate", "max", 0.05, "")])
    t0 = 3000.0
    for i in range(35):                       # burst long enough to breach
        eng.observe({"shed_rate": 0.5}, now=t0 + i)
    assert eng.doc()["objectives"]["shed_rate"]["state"] == BREACH
    for i in range(40):                       # silence: only time passes
        eng.observe({"shed_rate": None}, now=t0 + 35 + i)
    obj = eng.doc()["objectives"]["shed_rate"]
    assert obj["state"] == OK
    assert obj["samples"] == 0                # the burst fully aged out


@pytest.mark.fast
def test_sustained_breach_dumps_flight_recorder(tmp_path, monkeypatch):
    monkeypatch.delenv("DCR_WORKER_INDEX", raising=False)
    tracing.configure(tmp_path, rank=0)
    eng = _avail_engine(_cfg(dump_after_s=5.0))
    t0 = 4000.0
    for i in range(8):                        # all-bad: breach on tick 0
        eng.observe({"availability": 0.0}, now=t0 + i)
    dump = tmp_path / "flightrec_0.json"
    assert dump.exists()
    doc = json.loads(dump.read_text())
    assert doc["reason"] == "slo_breach_sustained: availability"
    # the extra= forensic section carries the full objective document
    assert doc["slo"]["objectives"]["availability"]["state"] == BREACH
    # transitions are trace events, not just log lines
    trace = (tmp_path / "trace.jsonl").read_text()
    assert '"slo/breach"' in trace


@pytest.mark.fast
def test_parse_exposition_skips_comments_labels_and_garbage():
    text = ("# HELP dcr_up h\n"
            "# TYPE dcr_up gauge\n"
            "dcr_up 1\n"
            "\n"
            'dcr_latency{quantile="0.99"} 0.5\n'
            "dcr_bad not-a-float\n"
            "dcr_ingest_lag_seconds 2.25\n")
    assert parse_exposition(text) == {"dcr_up": 1.0,
                                      "dcr_ingest_lag_seconds": 2.25}


@pytest.mark.fast
def test_default_objectives_follow_configured_planes():
    base = dict(resolution=16, num_inference_steps=2, sampler="ddim")
    names = {o.name for o in default_objectives(ServeConfig(**base))}
    # no ingest, no risk index, shedding disabled (target 0): only the
    # always-on fleet objectives exist
    assert names == {"availability", "shed_rate"}

    full = ServeConfig(**base,
                       fleet=FleetConfig(slo_queue_wait_p99_s=2.0),
                       ingest=IngestConfig(enabled=True),
                       risk=RiskConfig(store_dir="/s", ann=True))
    names = {o.name for o in default_objectives(full)}
    assert names == {"availability", "queue_wait_p99_s", "shed_rate",
                     "ingest_lag_s", "ann_staleness_rows", "recall",
                     "coverage"}

    off = ServeConfig(**base, slo=SloConfig(availability_min=0.0))
    assert "availability" not in {o.name for o in default_objectives(off)}

    with pytest.raises(ValueError):
        SloObjective("x", "x", "between", 1.0)
    with pytest.raises(ValueError):
        SloEngine(_cfg(), [SloObjective("a", "a", "min", 1.0),
                           SloObjective("a", "b", "max", 1.0)])


# ---------------------------------------------------------------------------
# 2. supervisor signal snapshot over the scrape cache (satellite 5)
# ---------------------------------------------------------------------------

_WORKER0_TEXT = ("# HELP h h\n# TYPE h gauge\n"
                 "dcr_ingest_lag_seconds 2.5\n"
                 "dcr_ingest_oldest_unfolded_age_s 7.5\n"
                 "dcr_ann_staleness_rows 1200\n"
                 "dcr_ann_recall_online_pct 90\n"
                 "dcr_ann_recall_online_samples 30\n"
                 "dcr_copy_risk_scored_total 5\n"
                 "dcr_serve_completed_total 10\n")
_WORKER1_TEXT = ("dcr_ingest_lag_seconds 40\n"
                 "dcr_ingest_oldest_unfolded_age_s 1\n"
                 "dcr_ann_staleness_rows 300\n"
                 "dcr_ann_recall_online_pct 50\n"
                 "dcr_ann_recall_online_samples 10\n")


def _supervisor(tmp_path, workers=2):
    from dcr_tpu.serve.supervisor import ALIVE, FleetSupervisor

    cfg = ServeConfig(resolution=16, num_inference_steps=2, sampler="ddim",
                      fleet=FleetConfig(workers=workers, dir=str(tmp_path)))
    sup = FleetSupervisor(cfg)                # never started: no subprocesses
    for slot in sup._slots:
        slot.state = ALIVE
    return sup


@pytest.mark.fast
def test_stale_scrape_drives_availability_down(tmp_path):
    """Satellite 5a: an ALIVE slot whose scrape went stale must count as
    unavailable — the SLO plane judges what it can still see, never a dead
    worker's last-good numbers."""
    sup = _supervisor(tmp_path)
    try:
        now = time.time()
        sup._scrape._cache = {0: (_WORKER0_TEXT, now),
                              1: (_WORKER1_TEXT, now)}
        sig = sup._slo_signals()
        assert sig["availability"] == 1.0
        assert sig["ingest_lag_s"] == 40.0            # worst worker wins
        assert sig["ann_staleness_rows"] == 1200.0
        # sample-weighted online recall: (0.9*30 + 0.5*10) / 40
        assert abs(sig["recall"] - 0.8) < 1e-9
        assert sig["shed_rate"] is None               # no traffic this tick

        # worker 1's scrape ages an hour: availability halves and its
        # last-good lag/recall numbers stop contributing entirely
        sup._scrape._cache = {0: (_WORKER0_TEXT, now),
                              1: (_WORKER1_TEXT, now - 3600.0)}
        sig = sup._slo_signals()
        assert sig["availability"] == 0.5
        assert sig["ingest_lag_s"] == 7.5             # worker 0's max only
        assert sig["ann_staleness_rows"] == 1200.0
        assert abs(sig["recall"] - 0.9) < 1e-9

        # a never-scraped ALIVE slot is just as invisible
        sup._scrape._cache = {0: (_WORKER0_TEXT, now)}
        assert sup._slo_signals()["availability"] == 0.5
    finally:
        sup.journal.close()


@pytest.mark.fast
def test_shed_rate_is_per_tick_delta_not_lifetime(tmp_path):
    """Satellite 5b at the supervisor level: one shed burst must read as one
    bad tick, then None — a lifetime ratio would latch the breach forever."""
    sup = _supervisor(tmp_path, workers=1)
    try:
        sup._scrape._cache = {0: (_WORKER0_TEXT, time.time())}
        reg = tracing.registry()
        reg.counter("fleet/accepted").inc(8)
        reg.counter("fleet/shed").inc(2)
        assert abs(sup._slo_signals()["shed_rate"] - 0.2) < 1e-9
        # no new traffic: no sample, NOT the stale 0.2 again
        assert sup._slo_signals()["shed_rate"] is None
        reg.counter("fleet/accepted").inc(4)
        assert sup._slo_signals()["shed_rate"] == 0.0
    finally:
        sup.journal.close()


@pytest.mark.fast
def test_coverage_delta_clamps_on_worker_restart(tmp_path):
    sup = _supervisor(tmp_path, workers=1)
    try:
        sup._scrape._cache = {0: (_WORKER0_TEXT, time.time())}
        assert abs(sup._slo_signals()["coverage"] - 0.5) < 1e-9  # 5/10
        # restarted worker: counters moved BACKWARDS — the delta clamps to
        # the fresh lifetime value instead of going negative
        restarted = ("dcr_copy_risk_scored_total 2\n"
                     "dcr_serve_completed_total 3\n")
        sup._scrape._cache = {0: (restarted, time.time())}
        assert abs(sup._slo_signals()["coverage"] - 2.0 / 3.0) < 1e-9
        # idle tick: completed didn't move, no sample
        assert sup._slo_signals()["coverage"] is None
    finally:
        sup.journal.close()


# ---------------------------------------------------------------------------
# 3. online recall probe vs the exact oracle (the ±0.05 acceptance)
# ---------------------------------------------------------------------------

def _ann_setup(tmp_path, rng_np, rows=256):
    from dcr_tpu.search import ann
    from dcr_tpu.search.annindex import open_ann_engine
    from dcr_tpu.search.shardindex import open_engine
    from dcr_tpu.search.store import EmbeddingStoreWriter

    centers = rng_np.standard_normal((8, DIM)).astype(np.float32) * 4.0
    assign = rng_np.integers(0, 8, rows)
    feats = (centers[assign]
             + rng_np.standard_normal((rows, DIM)).astype(np.float32) * 0.1)
    store = tmp_path / "store"
    w = EmbeddingStoreWriter(store, embed_dim=DIM, shard_rows=64)
    w.add(feats, [f"r{i}" for i in range(rows)])
    w.finalize()
    ann.train_ivf(store, n_lists=8, iters=5, seed=0)
    eng = open_ann_engine(store, top_k=10, nprobe=2, query_batch=16)
    exact = open_engine(store, top_k=10, query_batch=16)
    q = (centers[rng_np.integers(0, 8, 12)]
         + rng_np.standard_normal((12, DIM)).astype(np.float32) * 0.1)
    return eng, exact, q


def test_online_recall_matches_exact_oracle_within_tolerance(
        tmp_path, rng_np):
    from dcr_tpu.search.annindex import spot_check_recall

    eng, exact, q = _ann_setup(tmp_path, rng_np)
    _, ann_keys = eng.query(q)                # the production shortlist
    probe = RecallProbe(every_n=1, k=10, window=8)
    online = probe.observe(eng, q, ann_keys)
    offline = spot_check_recall(eng, exact, q, k=10)
    assert online is not None
    # same shortlist, same recall definition, shadow-exact oracle: the
    # online gauge must track the bench number (ISSUE acceptance: ±0.05)
    assert abs(online - offline) <= 0.05
    assert _gauge("ann/recall_online_pct") == int(round(online * 100))
    assert _gauge("ann/recall_online_samples") == 1
    assert _counter("ann/recall_probe_total") == 1
    stats = probe.stats()
    assert stats["probes"] == 1 and stats["rolling_recall"] is not None


def test_recall_probe_samples_every_nth_and_degrade_drill(tmp_path, rng_np):
    eng, _, q = _ann_setup(tmp_path, rng_np, rows=96)
    _, ann_keys = eng.query(q)
    probe = RecallProbe(every_n=4, k=10, window=8)
    results = [probe.observe(eng, q, ann_keys) for _ in range(8)]
    # calls 1 and 5 probe; the rest are free
    assert [r is not None for r in results] == [True, False, False, False,
                                                True, False, False, False]
    assert probe.stats()["probes"] == 2
    rolling_before = probe.stats()["rolling_recall"]
    try:
        faults.install("recall_degrade@probe=3")
        degraded = probe.observe(eng, q, ann_keys)     # call 9 = probe 3
    finally:
        faults.clear()
    assert degraded == 0.0                    # every corrupted key misses
    assert probe.stats()["rolling_recall"] < rolling_before
    with pytest.raises(ValueError):
        RecallProbe(every_n=0)


# ---------------------------------------------------------------------------
# 4. dcr-live lag gauges + the ingest_stall drill
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_livestore_lag_gauges_drain_to_zero_after_compact(tmp_path, rng_np):
    from dcr_tpu.search.livestore import LiveStore

    feats = rng_np.standard_normal((8, DIM)).astype(np.float32)
    with LiveStore.open(tmp_path / "s", embed_dim=DIM) as live:
        live.append(feats, [f"k{i}" for i in range(8)])
        live.update_lag_gauges()
        assert _gauge("ingest/backlog_rows") == 8
        assert _gauge("store/rows_total") == 8
        assert _gauge("ingest/lag_seqs") >= 1
        assert _gauge("ingest/oldest_unfolded_age_s") >= 0.0
        assert _gauge("store/growth_rows_per_s") > 0.0
        live.compact()
        # the acceptance pin: lag returns to ~0 once the WAL folds
        assert _gauge("ingest/backlog_rows") == 0
        assert _gauge("ingest/lag_seqs") == 0
        assert _gauge("ingest/oldest_unfolded_age_s") == 0.0
        assert _gauge("store/rows_total") == 8


def test_ingest_stall_delays_but_never_drops(tmp_path, rng_np, monkeypatch):
    from dcr_tpu.serve.ingest import IngestPump

    monkeypatch.setenv("DCR_INGEST_STALL_S", "0.6")
    row = rng_np.standard_normal(DIM).astype(np.float32)
    try:
        faults.install("ingest_stall@row=0")
        with IngestPump(tmp_path / "s", embed_dim=DIM, queue_max=8,
                        batch_rows=1) as pump:
            assert pump.offer(row, "k0") is True
            deadline = time.monotonic() + 10
            saw_stall = False
            while time.monotonic() < deadline:
                if pump.status == "stalled":
                    saw_stall = True
                if pump.stats()["appended_rows"] == 1:
                    break
                time.sleep(0.05)
            stats = pump.stats()
        assert saw_stall, "the stall fault never fired"
        assert stats["appended_rows"] == 1    # delayed, NOT dropped
        assert stats["dropped_rows"] == 0
        assert stats["status"] in ("ok", "stopped")
    finally:
        faults.clear()


@pytest.mark.fast
def test_faults_docstring_documents_slo_drills():
    for kind in ("ingest_stall", "recall_degrade"):
        assert f"``{kind}``" in faults.__doc__, kind


# ---------------------------------------------------------------------------
# 5. GET /slo + the dcr-status CLI (stub fleet, exit codes)
# ---------------------------------------------------------------------------

_STUB_SLO_DOC = {
    "enabled": True, "state": "breach", "breach_total": 2,
    "windows_s": [60.0, 300.0],
    "objectives": {
        "availability": {"state": "breach", "kind": "min", "target": 0.75,
                         "value": 0.5, "burn_short": 5.0, "burn_long": 2.1,
                         "samples": 40, "breach_total": 2,
                         "breach_for_s": 12.0, "description": ""},
        "shed_rate": {"state": "ok", "kind": "max", "target": 0.05,
                      "value": 0.0, "burn_short": 0.0, "burn_long": 0.0,
                      "samples": 40, "breach_total": 0, "breach_for_s": 0.0,
                      "description": ""}}}

_STUB_PROM = ("# HELP dcr_ingest_lag_seconds h\n"
              "# TYPE dcr_ingest_lag_seconds gauge\n"
              'dcr_ingest_lag_seconds{worker="0"} 2.5\n'
              'dcr_ingest_lag_seconds{worker="1"} 40\n'
              'dcr_ingest_backlog_rows{worker="0"} 4\n'
              'dcr_ingest_backlog_rows{worker="1"} 8\n'
              'dcr_ann_staleness_rows{worker="0"} 1200\n'
              'dcr_ann_recall_online_pct{worker="0"} 90\n'
              'dcr_ann_recall_online_samples{worker="0"} 30\n'
              'dcr_ann_recall_online_pct{worker="1"} 50\n'
              'dcr_ann_recall_online_samples{worker="1"} 10\n')


class _StubFleetService:
    draining = False

    def health(self):
        return "ok"

    def status(self):
        return {"workers_alive": 2, "queue_depth": 0,
                "workers": [{"index": 0, "state": "ALIVE", "failures": 0},
                            {"index": 1, "state": "ALIVE", "failures": 1}],
                "journal": {"pending": 0, "acked": 8}}

    def prometheus_merged(self):
        return _STUB_PROM

    def slo_doc(self):
        return dict(_STUB_SLO_DOC)


def _serve_stub(service):
    from dcr_tpu.serve.server import make_server

    cfg = ServeConfig(resolution=16, num_inference_steps=2, sampler="ddim",
                      port=0)
    httpd = make_server(cfg, service)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, httpd.server_address[1]


@pytest.mark.fast
def test_slo_endpoint_serves_doc_and_404_without_engine():
    httpd, port = _serve_stub(_StubFleetService())
    try:
        doc = cli_status.get_json("127.0.0.1", port, "/slo", 5.0)
        assert doc["_http_status"] == 200
        assert doc["enabled"] is True and doc["state"] == "breach"
    finally:
        httpd.shutdown()
        httpd.server_close()

    class _NoSlo:                             # pre-dcr-slo service shape
        draining = False

        def status(self):
            return {}

    httpd, port = _serve_stub(_NoSlo())
    try:
        doc = cli_status.get_json("127.0.0.1", port, "/slo", 5.0)
        assert doc["_http_status"] == 404
    finally:
        httpd.shutdown()
        httpd.server_close()


@pytest.mark.fast
def test_dcr_status_collect_aggregate_and_exit_codes(capsys):
    httpd, port = _serve_stub(_StubFleetService())
    try:
        doc = cli_status.collect("127.0.0.1", port, 5.0)
        assert doc["reachable"] and doc["workers_alive"] == 2
        live = doc["live"]
        assert live["ingest_lag_seconds"] == 40.0       # worst worker
        assert live["ingest_backlog_rows"] == 12.0      # summed
        assert live["ann_staleness_rows"] == 1200.0
        assert live["recall_online_pct"] == 80.0        # sample-weighted
        assert live["recall_online_samples"] == 40
        assert cli_status.exit_code(doc) == 1           # SLO breach
        text = cli_status.render_human(doc)
        assert "BREACH" in text and "availability" in text
        assert "online_recall=80.0%" in text
        with pytest.raises(SystemExit) as e:
            cli_status.main([f"--port={port}", "--json"])
        assert e.value.code == 1
        payload = json.loads(capsys.readouterr().out)
        assert payload["slo"]["state"] == "breach"
    finally:
        httpd.shutdown()
        httpd.server_close()

    # health "failed" alone is exit 1; SLO disabled stays informational
    assert cli_status.exit_code({"reachable": True,
                                 "health": {"status": "failed"},
                                 "slo": {"enabled": False}}) == 1
    assert cli_status.exit_code({"reachable": True,
                                 "health": {"status": "ok"},
                                 "slo": {"enabled": False}}) == 0

    # unreachable front end: typed exit 2, never a traceback
    from tests._multiproc import free_port

    with pytest.raises(SystemExit) as e:
        cli_status.main([f"--port={free_port()}", "--timeout=1", "--json"])
    assert e.value.code == 2
    assert json.loads(capsys.readouterr().out)["reachable"] is False


# ---------------------------------------------------------------------------
# 6. tools: bench_report, trace_report SLO sections, schema pins
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_bench_report_banked_artifacts_all_pass(capsys):
    rows, errors = bench_report.collect_rows(REPO)
    assert errors == []
    assert len(rows) >= 14                    # every banked artifact surfaced
    assert not any(r.get("passed") is False for r in rows)
    assert bench_report.main(["--dir", str(REPO), "--format=github"]) == 0
    out = capsys.readouterr().out
    assert "| artifact | gate |" in out and " FAIL " not in out


@pytest.mark.fast
def test_bench_report_fails_on_unknown_artifact(tmp_path, capsys):
    (tmp_path / "BENCH_MYSTERY.json").write_text("{}")
    rows, errors = bench_report.collect_rows(tmp_path)
    assert errors and "BENCH_MYSTERY.json" in errors[0]
    assert bench_report.main(["--dir", str(tmp_path)]) == 1
    assert bench_report.main(["--dir", str(tmp_path / "empty")]) == 1
    capsys.readouterr()


def _evt(name, ts, ident, **args):
    return {"ph": "i", "name": name, "id": ident, "ts": ts, "pid": 1,
            "tid": 1, "tname": "t", "args": args}


@pytest.mark.fast
def test_trace_report_slo_breach_timeline():
    records = [
        _evt("slo/breach", 2e6, 1, objective="availability", value=0.5,
             target=0.9, kind="min", burn_short=2.0, burn_long=2.1),
        _evt("slo/recover", 8e6, 2, objective="availability", value=1.0,
             target=0.9, breach_s=6.0, burn_short=0.2),
        _evt("slo/breach", 9e6, 3, objective="recall", value=0.6,
             target=0.8, kind="min", burn_short=3.0, burn_long=2.5),
    ]
    slo = trace_report.slo_summary(records)
    assert slo["objectives"] == {
        "availability": {"breaches": 1, "recoveries": 1},
        "recall": {"breaches": 1, "recoveries": 0}}
    assert slo["open_breaches"] == ["recall"]
    assert [t["event"] for t in slo["timeline"]] == ["breach", "recover",
                                                     "breach"]
    assert slo["timeline"][1]["breach_s"] == 6.0
    text = trace_report.render_text(trace_report.summarize(records),
                                    [Path(".")])
    assert "SLO:" in text and "BREACH" in text
    assert "still in breach at end of trace: recall" in text
    # no slo events -> no section, other traces keep their shape
    assert trace_report.slo_summary([_evt("risk/flagged", 1e6, 9)]) is None


@pytest.mark.fast
def test_trace_report_recall_is_sample_weighted():
    span = {"ph": "X", "name": "search/kmeans", "id": 1, "ts": 1e6,
            "dur": 1000.0, "pid": 1, "tid": 1, "tname": "t",
            "args": {"iter": 0}}
    records = [
        span,
        _evt("ann/recall_spot_check", 2e6, 2, k=10, queries=1, recall=1.0),
        _evt("ann/recall_spot_check", 3e6, 3, k=10, queries=99, recall=0.5),
        _evt("ann/recall_probe", 4e6, 4, k=10, queries=10, recall=0.9,
             rolling=0.95, samples=1),
        _evt("ann/recall_probe", 5e6, 5, k=10, queries=10, recall=0.5,
             rolling=0.7, samples=2),
    ]
    out = trace_report.ann_summary(records)
    # a 99-query check outweighs a 1-query one: (1*1 + 0.5*99) / 100
    assert out["recall_spot_checks"]["mean_recall"] == 0.505
    assert out["recall_spot_checks"]["samples"] == 100
    assert out["recall_online"]["mean_recall"] == 0.7
    assert out["recall_online"]["last_rolling"] == 0.7
    assert out["recall_online"]["probes"] == 2
    text = trace_report.render_text(trace_report.summarize(records),
                                    [Path(".")])
    assert "sample-weighted mean" in text
    assert "online recall (shadow-oracle probes)" in text


@pytest.mark.fast
def test_trace_schema_and_metric_names_pin_slo_surface():
    schema = json.loads((REPO / "tools" / "trace_schema.json").read_text())
    assert "slo/*" in schema["known_names"]["events"]
    assert "ann/*" in schema["known_names"]["events"]
    for raw, want in (
            ("slo/burn_rate/availability", "dcr_slo_burn_rate_availability"),
            ("slo/state/availability", "dcr_slo_state_availability"),
            ("slo/breach_total", "dcr_slo_breach_total"),
            ("ann/recall_online_pct", "dcr_ann_recall_online_pct"),
            ("ann/staleness_rows", "dcr_ann_staleness_rows"),
            ("ingest/oldest_unfolded_age_s",
             "dcr_ingest_oldest_unfolded_age_s"),
            ("store/growth_rows_per_s", "dcr_store_growth_rows_per_s")):
        assert tracing.sanitize_metric_name(raw) == want


# ---------------------------------------------------------------------------
# 7. slow: the acceptance e2e round trips
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_slo_fleet_availability_breach_and_recover_e2e(tmp_path, cpu_devices):
    """A real 2-worker fleet with an injected worker_crash: GET /slo walks
    availability ok -> breach (dcr-status exits 1) -> ok (exits 0) with
    zero dropped requests and slo/breach + slo/recover in the fleet trace."""
    import signal
    import subprocess
    import sys
    from concurrent.futures import ThreadPoolExecutor

    from dcr_tpu.core.coordination import EXIT_PREEMPTED
    from dcr_tpu.serve.fleet import RequestJournal
    from tests._multiproc import free_port
    from tests.test_serve import (_export_tiny_ckpt, _get, _post_generate,
                                  _serve_env)

    ckpt = _export_tiny_ckpt(tmp_path)
    env, repo = _serve_env()
    env["DCR_FAULTS"] = "worker_crash@batch=0&rank=0"
    fleet_dir = tmp_path / "fleet_slo"
    port = free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "dcr_tpu.cli.serve",
         f"--model_path={ckpt}", f"--port={port}",
         "--resolution=16", "--num_inference_steps=2", "--sampler=ddim",
         "--max_batch=2", "--max_wait_ms=60", "--queue_depth=64",
         "--request_timeout_s=300", "--seed=0",
         "--fleet.workers=2", f"--fleet.dir={fleet_dir}",
         "--fleet.heartbeat_s=0.5", "--fleet.lease_s=3",
         "--fleet.dispatch_timeout_s=240", "--fleet.spawn_timeout_s=240",
         "--fleet.max_attempts=6", "--fleet.respawn_max=6",
         "--fleet.respawn_base_delay_s=2",
         # tight windows so the outage (respawn + warm start, tens of
         # seconds) breaches quickly and recovery is observable in-test
         "--slo.short_window_s=3", "--slo.long_window_s=6",
         "--slo.budget=0.5", "--slo.availability_min=0.9"],
        env=env, cwd=repo, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)

    def fail(msg):
        out = proc.stdout.read() if proc.stdout else ""
        raise AssertionError(f"{msg}: {out[-4000:]}")

    def wait_slo(pred, deadline_s, what):
        deadline = time.monotonic() + deadline_s
        last = None
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                fail(f"fleet died waiting for {what} (rc={proc.poll()})")
            try:
                _, doc = _get(port, "/slo", timeout=2)
                last = doc
                if pred(doc):
                    return doc
            except OSError:
                pass
            time.sleep(0.25)
        raise AssertionError(f"timeout waiting for {what}; last /slo={last}")

    try:
        deadline = time.monotonic() + 300
        while True:
            try:
                _, health = _get(port, "/healthz", timeout=2)
                _, status = _get(port, "/metrics", timeout=2)
                if (health["status"] == "ok"
                        and status["workers_alive"] == 2):
                    break
            except OSError:
                pass
            if proc.poll() is not None or time.monotonic() > deadline:
                fail(f"fleet did not come up (rc={proc.poll()})")
            time.sleep(0.5)

        _, doc = _get(port, "/slo", timeout=5)
        assert doc["enabled"] is True
        assert "availability" in doc["objectives"]

        # the crash fires on worker 0's first batch
        with ThreadPoolExecutor(max_workers=4) as ex:
            futures = [ex.submit(_post_generate, port, p, seed=i,
                                 timeout=280)
                       for i, p in enumerate(["a red square",
                                              "a blue circle"] * 2)]

            doc = wait_slo(
                lambda d: d["objectives"]["availability"]["state"]
                == "breach", 240, "availability breach")
            assert doc["state"] == "breach"
            assert doc["objectives"]["availability"]["value"] is not None
            assert doc["objectives"]["availability"]["value"] < 0.9
            # dcr-status sees the same thing and exits 1
            sdoc = cli_status.collect("127.0.0.1", port, 5.0)
            assert cli_status.exit_code(sdoc) == 1
            assert "BREACH" in cli_status.render_human(sdoc)
            # the state gauge rides the merged Prometheus exposition
            prom = cli_status.get_text(
                "127.0.0.1", port, "/metrics?format=prometheus", 5.0)
            assert "dcr_slo_state_availability" in prom

            # every accepted request still completes (requeued onto the
            # survivor) while the objective is breached
            for f in futures:
                code, body = f.result(timeout=280)
                assert code == 200, (code, body)

        doc = wait_slo(
            lambda d: d["objectives"]["availability"]["state"] == "ok"
            and d["objectives"]["availability"]["breach_total"] >= 1,
            420, "availability recovery")
        assert doc["breach_total"] >= 1
        sdoc = cli_status.collect("127.0.0.1", port, 5.0)
        assert cli_status.exit_code(sdoc) == 0

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=180)
        assert rc == EXIT_PREEMPTED, rc
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    counts = RequestJournal.replay(fleet_dir / "journal.jsonl")["counts"]
    assert counts["accepted"] == 4 and counts["acked"] == 4
    assert counts["dropped"] == 0 and counts["failed"] == 0

    breach = recover = False
    for trace in fleet_dir.rglob("trace*.jsonl*"):
        text = trace.read_text(errors="replace")
        breach = breach or '"slo/breach"' in text
        recover = recover or '"slo/recover"' in text
    assert breach, "no slo/breach event in the fleet trace"
    assert recover, "no slo/recover event in the fleet trace"


@pytest.mark.slow
def test_slo_ingest_stall_breach_and_recover_integration(
        tmp_path, rng_np, monkeypatch):
    """The ingest_stall drill through the REAL signal chain: a stalled
    IngestPump's lag gauges ride the worker exposition into the
    supervisor's signal snapshot and walk the ingest_lag_s objective
    ok -> breach -> ok (lag ~0 after compaction), with zero rows lost."""
    from dcr_tpu.serve.ingest import IngestPump
    from dcr_tpu.serve.supervisor import ALIVE, FleetSupervisor

    monkeypatch.setenv("DCR_INGEST_STALL_S", "3")
    cfg = ServeConfig(
        resolution=16, num_inference_steps=2, sampler="ddim",
        fleet=FleetConfig(workers=1, dir=str(tmp_path / "fleet")),
        ingest=IngestConfig(enabled=True),
        risk=RiskConfig(store_dir=str(tmp_path / "store")),
        slo=SloConfig(short_window_s=0.8, long_window_s=1.6, budget=0.5,
                      ingest_lag_s_max=0.5, dump_after_s=-1.0))
    sup = FleetSupervisor(cfg)                # never started: we tick it
    sup._slots[0].state = ALIVE
    assert {o.name for o in sup._slo.objectives()} >= {"availability",
                                                       "ingest_lag_s"}

    def tick():
        # what the scrape loop would have cached: this process's own
        # registry, where the pump's gauges live
        sup._scrape._cache = {0: (tracing.registry().prometheus_text(),
                                  time.time())}
        sup._slo.observe(sup._slo_signals())
        return sup.slo_doc()["objectives"]["ingest_lag_s"]

    def tick_until(pred, deadline_s, what):
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            obj = tick()
            if pred(obj):
                return obj
            time.sleep(0.1)
        raise AssertionError(f"timeout waiting for {what}: {tick()}")

    row = rng_np.standard_normal(DIM).astype(np.float32)
    try:
        faults.install("ingest_stall@row=0")
        with IngestPump(tmp_path / "store", embed_dim=DIM, queue_max=16,
                        batch_rows=1, compact_rows=1) as pump:
            assert tick()["state"] == "ok"
            assert pump.offer(row, "k0") is True
            # the stall holds the ack for 3s; lag climbs past the 0.5s
            # target and both sub-second windows saturate
            breached = tick_until(lambda o: o["state"] == "breach", 30,
                                  "ingest_lag_s breach")
            assert breached["value"] > 0.5
            status_doc = {"reachable": True, "health": {"status": "ok"},
                          "slo": sup.slo_doc()}
            assert cli_status.exit_code(status_doc) == 1
            # stall ends -> append -> compact_rows=1 folds the WAL: lag
            # and backlog return to ~0 and the objective recovers
            recovered = tick_until(
                lambda o: o["state"] == "ok" and o["breach_total"] >= 1,
                30, "ingest_lag_s recovery")
            assert recovered["breach_total"] >= 1
            stats = pump.stats()
            assert stats["appended_rows"] == 1 and stats["dropped_rows"] == 0
            assert stats["compactions"] >= 1
        assert _gauge("ingest/backlog_rows") == 0
        assert _gauge("ingest/oldest_unfolded_age_s") == 0.0
        status_doc = {"reachable": True, "health": {"status": "ok"},
                      "slo": sup.slo_doc()}
        assert cli_status.exit_code(status_doc) == 0
    finally:
        faults.clear()
        sup.journal.close()

"""DataLoader failure/teardown/resume contract.

Locks in the semantics the fault-tolerance layer builds on: a worker-thread
error surfaces on the consumer (not swallowed, not hung), teardown after an
error or early break leaves no live worker threads, and a mid-epoch
``start_step`` resume reproduces an uninterrupted epoch byte-for-byte —
the property that makes preemption resume and quarantine replacement
deterministic.
"""

import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.fast
from PIL import Image

from dcr_tpu.core.config import DataConfig, FaultToleranceConfig
from dcr_tpu.data.dataset import ObjectAttributeDataset, SampleDecodeError
from dcr_tpu.data.loader import DataLoader
from dcr_tpu.data.tokenizer import HashTokenizer


@pytest.fixture()
def image_folder(tmp_path):
    rng = np.random.default_rng(0)
    for cls in ["c0", "c1"]:
        d = tmp_path / "data" / cls
        d.mkdir(parents=True)
        for i in range(6):
            arr = rng.integers(0, 255, (40, 52, 3), np.uint8)
            Image.fromarray(arr).save(d / f"{cls}_{i}.png")
    return tmp_path / "data"


def _dataset(root, **fault_kw):
    cfg = DataConfig(train_data_dir=str(root), resolution=32,
                     class_prompt="nolevel", num_workers=2, seed=7)
    # no backoff sleeps in tests
    ft = FaultToleranceConfig(retry_base_delay=0.0, retry_max_delay=0.0,
                              **fault_kw)
    return ObjectAttributeDataset(cfg, HashTokenizer(100, 16), fault=ft)


def _corrupt(ds, position: int) -> int:
    """Overwrite the image at dataset position with garbage; returns index."""
    index = int(ds.active_indices[position])
    with open(ds.paths[index], "wb") as f:
        f.write(b"this is not an image at all")
    return index


def test_worker_error_surfaces_on_consumer(image_folder):
    ds = _dataset(image_folder)
    bad = _corrupt(ds, 3)
    loader = DataLoader(ds, batch_size=2, num_workers=2, seed=1)
    with pytest.raises(SampleDecodeError) as ei:
        for _ in loader.epoch(0):
            pass
    assert ei.value.index == bad
    assert ds.paths[bad] in str(ei.value)


def test_teardown_after_worker_error_leaves_no_threads(image_folder):
    ds = _dataset(image_folder)
    _corrupt(ds, 0)
    before = threading.active_count()
    loader = DataLoader(ds, batch_size=2, num_workers=4, seed=1, prefetch=2)
    with pytest.raises(SampleDecodeError):
        for _ in loader.epoch(0):
            pass
    deadline = time.time() + 5.0
    while threading.active_count() > before + 1 and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before + 1


def test_teardown_after_early_break_leaves_no_threads(image_folder):
    ds = _dataset(image_folder)
    before = threading.active_count()
    loader = DataLoader(ds, batch_size=1, num_workers=4, seed=1, prefetch=2)
    it = loader.epoch(0)
    next(it)
    it.close()  # generator finally -> stop event -> workers drain and exit
    deadline = time.time() + 5.0
    while threading.active_count() > before + 1 and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before + 1


def test_start_step_resume_is_byte_identical(image_folder):
    """Resume at every possible start_step reproduces the uninterrupted
    epoch's remaining batches exactly — pixels, token ids, and indices."""
    ds = _dataset(image_folder)
    loader = DataLoader(ds, batch_size=3, num_workers=3, seed=5)
    full = list(loader.epoch(2))
    assert len(full) == loader.steps_per_epoch()
    for start in range(1, len(full)):
        resumed = list(loader.epoch(2, start_step=start))
        assert len(resumed) == len(full) - start
        for got, want in zip(resumed, full[start:]):
            np.testing.assert_array_equal(got.pixel_values, want.pixel_values)
            np.testing.assert_array_equal(got.input_ids, want.input_ids)
            np.testing.assert_array_equal(got.index, want.index)

"""dcr-obs tests: span tracer, telemetry registry, flight recorder, report.

Fast tier: pure-logic units — registry snapshot semantics, Prometheus text,
span parenting via contextvars, ring-buffer bounding, dump semantics,
log_event/log_trace level routing, trace_report aggregation + schema
validation + Chrome export.

Slow tier (the CI `observability` job): a tiny CPU train run and a real
dcr-serve session each produce a schema-valid trace.jsonl that
tools/trace_report.py renders (exit 0) and exports to loadable Chrome-trace
JSON; an injected hang (DCR_FAULTS) exits 89 with a flight-recorder dump
holding the last spans; an injected NaN fail-fast dumps with the nan_abort
reason; serve's /metrics?format=prometheus parses and includes faults
counters. Training/serve legs run as real CLI subprocesses (one process per
scenario — the production model, and required here: see the Orbax SIGABRT
note in tests/test_fault_injection.py).
"""

import json
import logging
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from dcr_tpu.core import resilience as R
from dcr_tpu.core import tracing
from tools import trace_report

pytest_plugins: list = []


@pytest.fixture(autouse=True)
def _clean_tracing():
    tracing.reset_for_tests()
    yield
    tracing.reset_for_tests()


# ---------------------------------------------------------------------------
# telemetry registry
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_registry_counter_gauge_histogram_snapshot():
    reg = tracing.registry()
    assert reg.counter("faults/x").inc() == 1
    assert reg.counter("faults/x").inc(2) == 3
    reg.gauge("loss").set(0.25)
    h = reg.histogram("lat", window=64)
    for v in range(1, 101):
        h.observe(v / 100.0)
    snap = reg.snapshot()
    assert snap["counters"]["faults/x"] == 3
    assert snap["gauges"]["loss"] == 0.25
    hs = snap["histograms"]["lat"]
    # lifetime count vs windowed percentiles: the reservoir holds 64, the
    # counter remembers all 100
    assert hs["count"] == 100
    assert hs["sum"] == pytest.approx(sum(v / 100.0 for v in range(1, 101)))
    assert 0.3 < hs["p50"] < 1.0 and hs["p99"] >= hs["p50"]
    # same object on re-lookup (get-or-create)
    assert reg.counter("faults/x").value == 3
    reg.reset("faults/")
    assert reg.counters("faults/") == {}
    assert reg.snapshot()["gauges"]["loss"] == 0.25  # other prefixes survive


@pytest.mark.fast
def test_registry_counters_thread_safe():
    reg = tracing.registry()

    def worker():
        for _ in range(500):
            reg.counter("faults/threads").inc()

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.counter("faults/threads").value == 4000


@pytest.mark.fast
def test_prometheus_text_renders_and_parses():
    from dcr_tpu.core.metrics import LatencyTracker

    R.bump_counter("kv_gc_errors", 2)
    tracing.registry().gauge("serve/queue_depth").set(3)
    lt = LatencyTracker(name="serve/request_latency_s")
    lt.observe(0.5)
    text = tracing.registry().prometheus_text()
    # minimal exposition-format parse: every non-comment line is
    # `name{labels}? value` with a float-parseable value
    samples = {}
    for line in text.strip().splitlines():
        if line.startswith("#"):
            parts = line.split()
            assert parts[1] in ("TYPE", "HELP")
            if parts[1] == "TYPE":
                assert parts[3] in ("counter", "gauge", "summary")
            continue
        name, value = line.rsplit(" ", 1)
        samples[name] = float(value)
    assert samples["dcr_faults_kv_gc_errors"] == 2.0
    assert samples["dcr_faults_total"] == 2.0
    assert samples["dcr_serve_queue_depth"] == 3.0
    assert samples['dcr_serve_request_latency_s{quantile="0.50"}'] == 0.5
    assert samples["dcr_serve_request_latency_s_count"] == 1.0


@pytest.mark.fast
def test_prometheus_faults_total_present_on_clean_process():
    """Scrapes must be able to alert on faults-rate before any fault exists."""
    text = tracing.registry().prometheus_text()
    assert "dcr_faults_total 0" in text


@pytest.mark.fast
def test_update_gauges_flattens_nested_and_bools():
    tracing.update_gauges({"a": 1, "nested": {"b": 2.5}, "flag": True,
                           "skip": "strings"}, prefix="s/")
    g = tracing.registry().snapshot()["gauges"]
    assert g["s/a"] == 1.0 and g["s/nested/b"] == 2.5 and g["s/flag"] == 1.0
    assert "s/skip" not in g


@pytest.mark.fast
def test_merge_counter_rows_sums_sparse_hosts():
    assert tracing.merge_counter_rows([
        {"bad_samples": 2}, {"bad_samples": 1, "kv_gc_errors": 3}, {},
    ]) == {"bad_samples": 3, "kv_gc_errors": 3}


# ---------------------------------------------------------------------------
# resilience integration: counters + log levels
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_bump_counter_rides_registry():
    R.bump_counter("decode_fallbacks")
    R.bump_counter("decode_fallbacks", 2)
    assert R.counters() == {"decode_fallbacks": 3}
    # visible to Prometheus under the faults/ prefix
    assert tracing.registry().counters("faults/") == {
        "faults/decode_fallbacks": 3}
    R.reset_counters()
    assert R.counters() == {}


@pytest.mark.fast
def test_log_event_levels_and_prefixes(caplog):
    with caplog.at_level(logging.INFO, logger="dcr_tpu"):
        R.log_event("something_failed", step=3)
        R.log_trace("stage_begin", name="eval")
    fault = [r for r in caplog.records if "something_failed" in r.getMessage()]
    trace = [r for r in caplog.records if "stage_begin" in r.getMessage()]
    assert fault[0].levelno == logging.WARNING
    assert fault[0].getMessage().startswith("[fault] ")
    assert trace[0].levelno == logging.INFO
    assert trace[0].getMessage().startswith("[trace] ")


@pytest.mark.fast
def test_log_event_lands_in_flight_recorder_as_fault_event():
    R.log_event("bad_thing", step=7)
    recs = tracing.flight_records()
    fault_events = [r for r in recs if r["name"] == "fault/bad_thing"]
    assert fault_events and fault_events[0]["args"]["step"] == 7


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_span_nesting_parents_via_contextvars(tmp_path):
    path = tracing.configure(tmp_path, rank=0)
    assert path == tmp_path / "trace.jsonl"
    with tracing.span("outer") as outer:
        assert tracing.current_span_id() == outer.id
        with tracing.span("inner", detail=1) as inner:
            pass
        tracing.event("mark")
    assert tracing.current_span_id() is None
    recs = {r["name"]: r for r in tracing.flight_records()}
    assert recs["inner"]["parent"] == outer.id
    assert recs["mark"]["parent"] == outer.id
    assert recs["outer"]["parent"] is None
    assert recs["inner"]["args"] == {"detail": 1}
    # inner closed first, so it appears first; durations nest
    assert recs["outer"]["dur"] >= recs["inner"]["dur"]
    # file got the same records, schema-valid
    schema = trace_report.load_schema()
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(lines) == 3
    for rec in lines:
        assert trace_report.validate_record(rec, schema) == []


@pytest.mark.fast
def test_span_records_error_and_reraises(tmp_path):
    tracing.configure(tmp_path, rank=0)
    with pytest.raises(ValueError):
        with tracing.span("boom"):
            raise ValueError("nope")
    [rec] = tracing.flight_records()
    assert rec["name"] == "boom" and "ValueError" in rec["args"]["error"]


@pytest.mark.fast
def test_span_threads_do_not_share_parents(tmp_path):
    tracing.configure(tmp_path, rank=0)
    seen = {}

    def worker():
        with tracing.span("thread_root") as h:
            seen["parent"] = h.parent

    with tracing.span("main_root"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    # a fresh thread starts a fresh context: no accidental cross-thread parent
    assert seen["parent"] is None


@pytest.mark.fast
def test_begin_end_handle_idempotent_and_complete_span(tmp_path):
    tracing.configure(tmp_path, rank=0)
    h = tracing.begin_span("serve/request", request_id=5)
    h.end(outcome="ok")
    h.end(outcome="double")                      # future callbacks can race
    tracing.complete_span("serve/queue_wait", start_wall=time.time() - 1.0,
                          dur_s=1.0, parent=h.id, request_id=5)
    recs = tracing.flight_records()
    assert [r["name"] for r in recs] == ["serve/request", "serve/queue_wait"]
    assert recs[0]["args"] == {"request_id": 5, "outcome": "ok"}
    assert recs[1]["parent"] == h.id
    assert recs[1]["dur"] == pytest.approx(1e6, rel=0.01)


@pytest.mark.fast
def test_ring_buffer_is_bounded():
    maxlen = tracing._state.ring.maxlen
    for i in range(maxlen + 50):
        tracing.event("e", i=i)
    recs = tracing.flight_records()
    assert len(recs) == maxlen
    assert recs[-1]["args"]["i"] == maxlen + 49   # newest kept, oldest dropped
    assert recs[0]["args"]["i"] == 50


@pytest.mark.fast
def test_trace_disabled_by_env_keeps_ring(tmp_path, monkeypatch):
    monkeypatch.setenv("DCR_TRACE", "0")
    assert tracing.configure(tmp_path, rank=0) is None
    with tracing.span("still_recorded"):
        pass
    assert not (tmp_path / "trace.jsonl").exists()
    assert [r["name"] for r in tracing.flight_records()] == ["still_recorded"]
    # flight recorder still anchored to the configured dir
    assert tracing.dump_flight_recorder("test") == tmp_path / "flightrec_0.json"


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_flight_recorder_dump_contents(tmp_path):
    tracing.configure(tmp_path, rank=0)
    with tracing.span("train/step", step=9):
        pass
    R.bump_counter("rollbacks")
    path = tracing.dump_flight_recorder("nan_abort: step 9 loss nan")
    doc = json.loads(path.read_text())
    assert doc["reason"].startswith("nan_abort")
    assert doc["rank"] == 0
    assert [r["name"] for r in doc["records"]] == ["train/step"]
    assert doc["registry"]["counters"]["faults/rollbacks"] == 1


@pytest.mark.fast
def test_flight_recorder_first_dump_wins(tmp_path):
    tracing.configure(tmp_path, rank=0)
    first = tracing.dump_flight_recorder("nan_abort")
    second = tracing.dump_flight_recorder("unhandled_exception: later")
    assert first == second
    assert json.loads(first.read_text())["reason"] == "nan_abort"


@pytest.mark.fast
def test_flight_recorder_unconfigured_is_noop(monkeypatch):
    monkeypatch.delenv("DCR_FLIGHTREC_DIR", raising=False)
    assert tracing.dump_flight_recorder("nowhere to go") is None


@pytest.mark.fast
def test_flight_recorder_env_dir_fallback(tmp_path, monkeypatch):
    monkeypatch.setenv("DCR_FLIGHTREC_DIR", str(tmp_path))
    tracing.event("before_death")
    path = tracing.dump_flight_recorder("env fallback")
    assert path is not None and path.parent == tmp_path


# ---------------------------------------------------------------------------
# trace_report
# ---------------------------------------------------------------------------

def _write_synthetic_trace(tmp_path: Path) -> Path:
    tracing.configure(tmp_path, rank=0)
    for step in range(3):
        with tracing.span("train/data_wait", step=step):
            pass
        with tracing.span("train/step", step=step):
            pass
    with tracing.span("ckpt/save", step=2):
        pass
    tracing.complete_span("serve/queue_wait", start_wall=time.time(),
                          dur_s=0.02, request_id=1)
    tracing.event("serve/compile", bucket="(16, 2)")
    tracing.event("serve/compile", bucket="(16, 2)")
    R.log_event("nan_rollback", at_step=3)
    tracing.reset_for_tests()        # close the file handle before reading
    return tmp_path


@pytest.mark.fast
def test_trace_report_summary_and_text(tmp_path, capsys):
    run_dir = _write_synthetic_trace(tmp_path)
    rc = trace_report.main([str(run_dir)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "stage-time breakdown" in out
    assert "train/step" in out and "ckpt/save" in out
    assert "serve queue wait" in out
    assert "2x (16, 2)" in out                      # recompile count per bucket
    assert "fault/nan_rollback" in out              # fault timeline

    schema = trace_report.load_schema()
    records, errors = trace_report.load_trace(run_dir, schema)
    assert not errors
    summary = trace_report.summarize(records)
    assert summary["categories"]["step"]["count"] == 3
    assert summary["categories"]["data"]["count"] == 3
    assert summary["categories"]["ckpt"]["count"] == 1
    assert summary["serve_queue_wait"]["p50_ms"] == pytest.approx(20.0, rel=0.05)
    assert summary["serve_recompiles_per_bucket"] == {"(16, 2)": 2}
    assert [f["name"] for f in summary["fault_timeline"]] == ["fault/nan_rollback"]


@pytest.mark.fast
def test_trace_report_chrome_export_loads(tmp_path, capsys):
    run_dir = _write_synthetic_trace(tmp_path)
    chrome = tmp_path / "chrome.json"
    assert trace_report.main([str(run_dir), "--chrome", str(chrome)]) == 0
    capsys.readouterr()
    doc = json.loads(chrome.read_text())
    events = doc["traceEvents"]
    assert any(e["ph"] == "M" and e["name"] == "thread_name" for e in events)
    xs = [e for e in events if e["ph"] == "X"]
    assert xs and all(isinstance(e["dur"], int) and isinstance(e["ts"], int)
                      and isinstance(e["pid"], int) for e in xs)
    names = {e["name"] for e in xs}
    assert {"train/step", "ckpt/save"} <= names


@pytest.mark.fast
def test_trace_report_exit_codes(tmp_path, capsys):
    assert trace_report.main([str(tmp_path)]) == 1          # empty dir
    (tmp_path / "trace.jsonl").write_text('{"ph": "X", "name": 3}\n')
    assert trace_report.main([str(tmp_path)]) == 2          # schema violation
    capsys.readouterr()


@pytest.mark.fast
def test_validate_record_catches_field_drift():
    schema = trace_report.load_schema()
    good = {"ph": "i", "name": "e", "id": 1, "ts": 1.0, "pid": 0, "tid": 1,
            "tname": "t", "args": {}}
    assert trace_report.validate_record(good, schema) == []
    assert trace_report.validate_record({**good, "ph": "Z"}, schema)
    assert trace_report.validate_record({**good, "name": 7}, schema)
    span = {**good, "ph": "X"}
    assert trace_report.validate_record(span, schema)        # missing dur
    assert trace_report.validate_record({**span, "dur": 5}, schema) == []


# ---------------------------------------------------------------------------
# subprocess e2e: train + hang + NaN + serve (slow; CI `observability` job)
# ---------------------------------------------------------------------------

def _tiny_train_cfg(tmp_path: Path):
    from PIL import Image

    from dcr_tpu.core.config import (DataConfig, ModelConfig, OptimConfig,
                                     TrainConfig)

    rng = np.random.default_rng(0)
    for cls in ["c0", "c1"]:
        d = tmp_path / "data" / cls
        d.mkdir(parents=True, exist_ok=True)
        for i in range(8):
            Image.fromarray(rng.integers(0, 255, (20, 20, 3), np.uint8)).save(
                d / f"{i}.png")
    return TrainConfig(
        output_dir=str(tmp_path / "run"),
        seed=0, train_batch_size=2, max_train_steps=4, num_train_epochs=20,
        mixed_precision="no", save_steps=1000, modelsavesteps=2, log_every=1,
        model=ModelConfig.tiny(),
        data=DataConfig(train_data_dir=str(tmp_path / "data"), resolution=16,
                        class_prompt="nolevel", num_workers=2, seed=0),
        optim=OptimConfig(learning_rate=1e-4, lr_scheduler="constant",
                          lr_warmup_steps=0),
    )


def _subprocess_env(extra=None):
    import os

    repo = Path(__file__).parent.parent
    cache = os.environ.get("DCR_TEST_CACHE_DIR") or str(
        repo / "tests" / ".jax_cache_cpu")
    env = dict(os.environ)
    env.pop("DCR_FAULTS", None)
    env.update(
        DCR_TPU_PLATFORM="cpu",
        PYTHONPATH=str(repo) + os.pathsep + env.get("PYTHONPATH", ""),
        JAX_THREEFRY_PARTITIONABLE="1",
        JAX_COMPILATION_CACHE_DIR=cache,
        JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="1.0",
        JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES="0",
    )
    env.update(extra or {})
    return env, repo


def _run_train_cli(cfg, cfg_path, *, extra_env=None, timeout=540):
    import subprocess
    import sys

    from dcr_tpu.core.config import save_config

    save_config(cfg, cfg_path)
    env, repo = _subprocess_env(extra_env)
    proc = subprocess.run(
        [sys.executable, "-m", "dcr_tpu.cli.train", f"--config={cfg_path}"],
        env=env, cwd=repo, capture_output=True, text=True, timeout=timeout)
    return proc, proc.stdout + proc.stderr


def _assert_valid_trace(run_dir: Path, required_names: set) -> dict:
    """trace.jsonl exists, every record passes the checked-in schema, the
    required span names are present; returns the trace_report summary."""
    schema = trace_report.load_schema()
    records, errors = trace_report.load_trace(run_dir, schema)
    assert not errors, errors[:5]
    assert records, f"no trace records under {run_dir}"
    names = {r["name"] for r in records}
    assert required_names <= names, names
    return trace_report.summarize(records)


@pytest.mark.slow
def test_train_run_produces_trace_and_report(tmp_path):
    """Acceptance: a tiny CPU train run produces a trace.jsonl that
    trace_report renders into a stage-time breakdown, and whose Chrome
    export is valid JSON."""
    import subprocess
    import sys

    cfg = _tiny_train_cfg(tmp_path)
    proc, out = _run_train_cli(cfg, tmp_path / "cfg.json")
    assert proc.returncode == 0, out[-3000:]

    run_dir = Path(cfg.output_dir)
    assert (run_dir / "trace.jsonl").exists()
    summary = _assert_valid_trace(
        run_dir, {"train/step", "train/data_wait", "data/batch", "ckpt/save"})
    assert summary["categories"]["step"]["count"] == 4      # one per micro-step
    assert summary["categories"]["ckpt"]["count"] >= 1
    assert summary["fault_timeline"] == []                  # clean run

    env, repo = _subprocess_env()
    chrome = tmp_path / "chrome.json"
    rep = subprocess.run(
        [sys.executable, "-m", "tools.trace_report", str(run_dir),
         "--chrome", str(chrome), "--json"],
        env=env, cwd=repo, capture_output=True, text=True, timeout=60)
    assert rep.returncode == 0, rep.stdout + rep.stderr
    assert json.loads(rep.stdout)["spans"] > 0              # --json parses
    doc = json.loads(chrome.read_text())                    # Perfetto-loadable
    assert any(e.get("name") == "train/step" for e in doc["traceEvents"])


@pytest.mark.slow
def test_injected_hang_dumps_flight_recorder_before_exit_89(tmp_path):
    """Acceptance: DCR_FAULTS hang -> watchdog exit 89, and flightrec_0.json
    holds the last spans before the wedge."""
    from dcr_tpu.core.coordination import EXIT_HANG

    cfg = _tiny_train_cfg(tmp_path)
    proc, out = _run_train_cli(
        cfg, tmp_path / "cfg.json",
        extra_env={"DCR_FAULTS": "hang@step=3", "DCR_HANG_TIMEOUT_S": "4"})
    assert proc.returncode == EXIT_HANG, (proc.returncode, out[-3000:])

    dump = Path(cfg.output_dir) / "flightrec_0.json"
    assert dump.exists(), out[-3000:]
    doc = json.loads(dump.read_text())
    assert doc["reason"].startswith("hang_abort")
    names = [r["name"] for r in doc["records"]]
    assert "train/step" in names            # the last working spans survive
    assert any(n == "fault/injected" for n in names)  # the injection itself
    # the post-mortem log folds the recorder in
    assert "last trace records" in out


@pytest.mark.slow
def test_nan_fail_fast_dumps_flight_recorder(tmp_path):
    """Acceptance: default-config NaN fail-fast writes the nan_abort dump
    (first dump wins over the excepthook's) and still raises as the seed."""
    cfg = _tiny_train_cfg(tmp_path)
    proc, out = _run_train_cli(cfg, tmp_path / "cfg.json",
                               extra_env={"DCR_FAULTS": "nan_loss@step=3"})
    assert proc.returncode != 0
    assert "FloatingPointError" in out
    doc = json.loads((Path(cfg.output_dir) / "flightrec_0.json").read_text())
    assert doc["reason"].startswith("nan_abort: step 3")
    assert any(r["name"] == "fault/injected" for r in doc["records"])


@pytest.mark.slow
def test_serve_session_trace_prometheus_and_drain_dump(tmp_path, cpu_devices):
    """Acceptance: a short serve session produces a schema-valid trace with
    one span tree per request id, /metrics?format=prometheus parses and
    includes the faults counters, trace_report exits 0 on the logdir, and
    SIGTERM drain leaves a flight-recorder dump next to it."""
    import signal
    import socket
    import subprocess
    import sys
    import urllib.request

    from dcr_tpu.core.coordination import EXIT_PREEMPTED

    from tests.test_serve import _export_tiny_ckpt

    ckpt = _export_tiny_ckpt(tmp_path)
    env, repo = _subprocess_env()
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    logdir = tmp_path / "servelogs"
    proc = subprocess.Popen(
        [sys.executable, "-m", "dcr_tpu.cli.serve",
         f"--model_path={ckpt}", f"--port={port}", f"--logdir={logdir}",
         "--resolution=16", "--num_inference_steps=2", "--sampler=ddim",
         "--max_batch=2", "--max_wait_ms=50", "--request_timeout_s=300",
         "--seed=0"],
        env=env, cwd=repo, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        deadline = time.monotonic() + 240
        while True:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz", timeout=2) as r:
                    assert json.loads(r.read())["status"] == "ok"
                break
            except (AssertionError, OSError):
                if proc.poll() is not None or time.monotonic() > deadline:
                    raise AssertionError(
                        f"server did not come up (rc={proc.poll()}): "
                        f"{proc.stdout.read()[-3000:]}")
                time.sleep(0.5)

        body = json.dumps({"prompt": "a red square", "seed": 1}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300) as resp:
            assert resp.status == 200

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics?format=prometheus",
                timeout=30) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
        samples = {}
        for line in text.strip().splitlines():
            if line.startswith("#"):
                continue
            name, value = line.rsplit(" ", 1)
            samples[name] = float(value)                  # parses as floats
        assert "dcr_faults_total" in samples              # faults/* section
        assert samples["dcr_serve_completed_total"] == 1.0
        assert "dcr_serve_request_latency_s_count" in samples

        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=120) == EXIT_PREEMPTED
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    assert (logdir / "trace.jsonl").exists()
    summary = _assert_valid_trace(
        logdir, {"serve/request", "serve/queue_wait", "serve/assemble",
                 "serve/device_step", "serve/respond", "stage/serve_load"})
    assert summary["serve_queue_wait"]["count"] >= 1
    assert summary["serve_recompiles_per_bucket"]         # one bucket compiled
    # span tree: children reference the request root
    schema = trace_report.load_schema()
    records, _ = trace_report.load_trace(logdir, schema)
    roots = {r["id"]: r for r in records if r["name"] == "serve/request"}
    waits = [r for r in records if r["name"] == "serve/queue_wait"]
    assert roots and all(w["parent"] in roots for w in waits)
    assert all(r["args"]["request_id"] in
               {w["args"]["request_id"] for w in waits} for r in roots.values())

    doc = json.loads((logdir / "flightrec_0.json").read_text())
    assert doc["reason"].startswith("preempted")

    import sys as _sys

    rep = subprocess.run(
        [_sys.executable, "-m", "tools.trace_report", str(logdir)],
        env=env, cwd=repo, capture_output=True, text=True, timeout=60)
    assert rep.returncode == 0, rep.stdout + rep.stderr
    assert "serve queue wait" in rep.stdout

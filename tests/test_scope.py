"""dcr-scope tests: fleet-wide tracing, metrics aggregation, profiling.

Fast tier: trace-file rotation (size cap, keep-N, report reads segments),
Prometheus exposition hygiene (HELP/TYPE headers, sanitized identifiers,
non-finite value tokens — validated with a strict format checker), the
wire-context round-trip through the request journal (requeue keeps the
trace id, increments attempt), worker-indexed flight-recorder filenames,
LatencyTracker under concurrent observe(), the scrape/label/merge helpers
(inject_labels, merge_expositions, ScrapeCache against a real socket), the
supervisor's merged exposition built purely from the scrape cache, the
profile armer state machine, and trace_report's fleet merge (clock-offset
anchoring, cross-process span trees, requeue attempts, orphan accounting,
per-process Chrome tracks) over synthetic multi-process trace files.

Slow tier (CI `observability` job): the dcr-scope acceptance e2e — a real
2-worker fleet with an injected ``worker_crash``, then (a) the merged
``/metrics?format=prometheus`` carries worker-labeled series and
up/staleness gauges from live workers without blocking on the dead one,
(b) a ``POST /debug/profile`` round-trip produces a readable jax.profiler
artifact, and (c) ``tools/trace_report`` over the fleet dir reconstructs
one connected span tree per request — including the requeued-after-crash
request as an attempt-tagged sibling under the same root.
"""

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import pytest

from dcr_tpu.core import tracing
from dcr_tpu.serve.scrape import (ScrapeCache, inject_labels,
                                  merge_expositions)
from tools import trace_report


@pytest.fixture(autouse=True)
def _clean_tracing():
    tracing.reset_for_tests()
    yield
    tracing.reset_for_tests()


# ---------------------------------------------------------------------------
# trace.jsonl size-capped rotation
# ---------------------------------------------------------------------------

def _emit_events(n: int, payload: str = "x" * 120) -> None:
    for i in range(n):
        tracing.event("rotation_test", i=i, payload=payload)


@pytest.mark.fast
def test_trace_rotation_caps_file_and_report_reads_segments(
        tmp_path, monkeypatch):
    monkeypatch.setenv("DCR_TRACE_MAX_MB", "0.003")      # 3000 bytes
    monkeypatch.setenv("DCR_TRACE_KEEP", "3")
    path = tracing.configure(tmp_path, rank=0)
    _emit_events(25)
    tracing.reset_for_tests()
    segments = sorted(p.name for p in tmp_path.iterdir()
                      if p.name.startswith("trace.jsonl"))
    assert "trace.jsonl.1" in segments                   # rotation happened
    assert len(segments) >= 2
    # the live file never grows past the cap by more than one record
    assert path.stat().st_size <= 3000 + 400
    # trace_report reads base + rotated segments as one stream, no loss
    records, errors = trace_report.load_trace(tmp_path, trace_report.load_schema())
    assert not errors
    assert [r["args"]["i"] for r in records] == list(range(25))
    assert {r["_plabel"] for r in records} == {"trace.jsonl"}


@pytest.mark.fast
def test_trace_rotation_drops_oldest_beyond_keep(tmp_path, monkeypatch):
    monkeypatch.setenv("DCR_TRACE_MAX_MB", "0.001")      # 1000 bytes
    monkeypatch.setenv("DCR_TRACE_KEEP", "1")
    tracing.configure(tmp_path, rank=0)
    _emit_events(40)
    tracing.reset_for_tests()
    segments = {p.name for p in tmp_path.iterdir()
                if p.name.startswith("trace.jsonl")}
    assert segments <= {"trace.jsonl", "trace.jsonl.1"}   # .2 never appears
    records, errors = trace_report.load_trace(tmp_path, trace_report.load_schema())
    assert not errors
    # oldest records were dropped with their segment, newest survive in order
    idx = [r["args"]["i"] for r in records]
    assert idx == sorted(idx) and idx[-1] == 39 and len(idx) < 40


@pytest.mark.fast
def test_rotation_reconfigure_resumes_byte_accounting(tmp_path, monkeypatch):
    """configure() on an existing file seeds bytes_written from its size, so
    a restarted process keeps honoring the cap instead of starting from 0."""
    monkeypatch.setenv("DCR_TRACE_MAX_MB", "0.001")
    tracing.configure(tmp_path, rank=0)
    _emit_events(5)
    tracing.reset_for_tests()
    monkeypatch.setenv("DCR_TRACE_MAX_MB", "0.001")
    tracing.configure(tmp_path, rank=0)                  # "restart"
    assert tracing._state.bytes_written > 0


# ---------------------------------------------------------------------------
# Prometheus exposition hygiene
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*"'
    r'(?:,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*")*)\})?'
    r' (?P<value>-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?|\+Inf|-Inf|NaN)$')


def _assert_valid_exposition(text: str) -> dict[str, str]:
    """Strict-enough exposition-format check: every line is a HELP/TYPE
    comment or a sample; identifiers are legal; one TYPE per metric, HELP
    precedes it; every sample belongs to a declared metric family. Returns
    {sample line name+labels: value string}."""
    typed: dict[str, str] = {}
    helped: set[str] = set()
    samples: dict[str, str] = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            name = line.split(" ", 3)[2]
            assert _NAME_RE.match(name), line
            assert name not in helped, f"duplicate HELP: {line}"
            helped.add(name)
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert _NAME_RE.match(name), line
            assert kind in ("counter", "gauge", "summary"), line
            assert name not in typed, f"duplicate TYPE: {line}"
            assert name in helped, f"TYPE without preceding HELP: {line}"
            typed[name] = kind
            continue
        assert not line.startswith("#"), f"unknown comment: {line}"
        m = _SAMPLE_RE.match(line)
        assert m, f"malformed sample line: {line!r}"
        base = m.group("name")
        family = (base.rsplit("_", 1)[0]
                  if base.endswith(("_sum", "_count")) else base)
        assert base in typed or family in typed, \
            f"sample without TYPE header: {line!r}"
        samples[line.rsplit(" ", 1)[0]] = m.group("value")
    return samples


@pytest.mark.fast
def test_prometheus_text_is_format_valid_with_hostile_names():
    reg = tracing.registry()
    reg.counter("faults/weird-kind.x").inc(2)
    reg.gauge("stage/eval time (s)").set(1.5)
    reg.gauge("serve/inf_gauge").set(float("inf"))
    reg.gauge("serve/nan_gauge").set(float("nan"))
    h = reg.histogram("serve/latency s", window=8)
    h.observe(0.5)
    text = reg.prometheus_text()
    samples = _assert_valid_exposition(text)
    assert samples["dcr_faults_weird_kind_x"] == "2"
    assert samples["dcr_faults_total"] == "2"
    assert samples["dcr_stage_eval_time__s_"] == "1.5"
    assert samples["dcr_serve_inf_gauge"] == "+Inf"
    assert samples["dcr_serve_nan_gauge"] == "NaN"
    assert 'dcr_serve_latency_s{quantile="0.50"}' in samples
    # HELP lines name the internal metric the identifier was sanitized from
    assert "# HELP dcr_faults_weird_kind_x" in text
    assert "'faults/weird-kind.x'" in text


@pytest.mark.fast
def test_sanitize_and_value_helpers():
    assert tracing.sanitize_metric_name("faults/x-y.z") == "dcr_faults_x_y_z"
    assert _NAME_RE.match(tracing.sanitize_metric_name("0weird"))
    assert tracing.sanitize_label_name("9worker") == "_9worker"
    assert tracing.sanitize_label_name("wor-ker") == "wor_ker"
    assert tracing.prometheus_value(float("inf")) == "+Inf"
    assert tracing.prometheus_value(float("-inf")) == "-Inf"
    assert tracing.prometheus_value(float("nan")) == "NaN"
    assert tracing.prometheus_value(3) == "3"
    assert float(tracing.prometheus_value(0.25)) == 0.25


@pytest.mark.fast
def test_colliding_sanitized_names_share_one_header():
    reg = tracing.registry()
    reg.gauge("serve/a-b").set(1.0)
    reg.gauge("serve/a.b").set(2.0)          # sanitizes to the same identifier
    _assert_valid_exposition(reg.prometheus_text())   # no duplicate TYPE


# ---------------------------------------------------------------------------
# distributed trace context: wire format + journal round-trip
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_wire_context_carries_trace_and_attempt(tmp_path):
    tracing.configure(tmp_path, rank=0)
    tid = tracing.new_trace_id()
    assert re.fullmatch(r"[0-9a-f]{16}", tid)
    assert tracing.new_trace_id() != tid
    root = tracing.begin_span("serve/request", parent=None, trace=tid)
    ctx = tracing.wire_context(root, attempt=2)
    assert ctx == {"trace_id": tid, "parent_span": root.id, "attempt": 2}
    root.end()
    [rec] = tracing.flight_records()
    assert rec["trace"] == tid


@pytest.mark.fast
def test_span_inherits_trace_via_contextvars(tmp_path):
    tracing.configure(tmp_path, rank=0)
    tid = tracing.new_trace_id()
    with tracing.span("serve/request", trace=tid):
        with tracing.span("serve/inner"):
            tracing.event("serve/mark")
        assert tracing.current_trace_id() == tid
    assert tracing.current_trace_id() is None
    recs = {r["name"]: r for r in tracing.flight_records()}
    assert recs["serve/inner"]["trace"] == tid
    assert recs["serve/mark"]["trace"] == tid


@pytest.mark.fast
def test_journal_round_trips_trace_id_across_requeue(tmp_path):
    from dcr_tpu.serve.fleet import RequestJournal
    from dcr_tpu.serve.queue import GenBucket, Request

    bucket = GenBucket(resolution=16, steps=2, guidance=7.5, sampler="ddim",
                       rand_noise_lam=0.0)
    req = Request(prompt="p", seed=0, bucket=bucket)
    req.trace_id = tracing.new_trace_id()
    path = tmp_path / "journal.jsonl"
    j = RequestJournal(path)
    e = j.add(req)
    assert e.trace_id == req.trace_id
    assert j.dispatch(req.id, worker=0) == 1
    # worker died: requeue keeps the trace id, the NEXT dispatch is attempt 2
    j.requeue(req.id, worker=0, reason="crash")
    assert j.entry(req.id).trace_id == req.trace_id
    assert j.dispatch(req.id, worker=1) == 2
    j.ack(req.id, worker=1)
    j.close()
    add = [json.loads(l) for l in path.read_text().splitlines()
           if json.loads(l)["op"] == "add"]
    assert add[0]["trace"] == req.trace_id       # durable: survives replay


# ---------------------------------------------------------------------------
# flight recorder: worker-indexed filenames
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_flight_recorder_filename_includes_worker_index(tmp_path, monkeypatch):
    monkeypatch.setenv("DCR_WORKER_INDEX", "3")
    tracing.configure(tmp_path, rank=0)
    tracing.event("about_to_die")
    path = tracing.dump_flight_recorder("worker 3 post-mortem")
    assert path == tmp_path / "flightrec_w3_0.json"
    assert json.loads(path.read_text())["reason"] == "worker 3 post-mortem"


@pytest.mark.fast
def test_flight_recorder_plain_name_without_worker_index(tmp_path, monkeypatch):
    monkeypatch.delenv("DCR_WORKER_INDEX", raising=False)
    tracing.configure(tmp_path, rank=0)
    assert tracing.dump_flight_recorder("x") == tmp_path / "flightrec_0.json"


# ---------------------------------------------------------------------------
# LatencyTracker / histogram under concurrency
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_latency_tracker_concurrent_observe_and_percentiles():
    from dcr_tpu.core.metrics import LatencyTracker

    lt = LatencyTracker(name="scope/concurrency_test", window=256)
    errors: list = []

    def observer(base):
        try:
            for i in range(500):
                lt.observe(base + i / 1000.0)
        except Exception as e:                        # pragma: no cover
            errors.append(e)

    def reader():
        try:
            for _ in range(200):
                p = lt.percentiles((50, 99))
                assert p["p99"] >= p["p50"] >= 0.0
        except Exception as e:                        # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=observer, args=(w,)) for w in range(6)]
    threads += [threading.Thread(target=reader) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    snap = lt.snapshot()
    assert snap["count"] == 3000                      # no lost observations
    assert snap["sum"] == pytest.approx(
        sum(w + i / 1000.0 for w in range(6) for i in range(500)))
    assert 0.0 <= snap["p50"] <= 6.0


# ---------------------------------------------------------------------------
# scrape helpers: label injection, exposition merge, bounded scraping
# ---------------------------------------------------------------------------

@pytest.mark.fast
def test_inject_labels_extends_and_creates_label_sets():
    text = ("# HELP dcr_x help\n# TYPE dcr_x counter\n"
            "dcr_x 3\n"
            'dcr_lat{quantile="0.99"} 0.5\n')
    out = inject_labels(text, {"worker": "1"})
    assert 'dcr_x{worker="1"} 3' in out
    assert 'dcr_lat{quantile="0.99",worker="1"} 0.5' in out
    assert "# HELP dcr_x help" in out                 # comments untouched
    # label values escape quotes/backslashes; names sanitize
    out = inject_labels("m 1\n", {"wor-ker": 'a"b\\c'})
    assert out == 'm{wor_ker="a\\"b\\\\c"} 1\n'


@pytest.mark.fast
def test_merge_expositions_dedupes_headers_keeps_samples():
    a = ('# HELP dcr_x h\n# TYPE dcr_x counter\ndcr_x{worker="0"} 1\n')
    b = ('# HELP dcr_x h\n# TYPE dcr_x counter\ndcr_x{worker="1"} 2\n')
    merged = merge_expositions([a, b])
    assert merged.count("# TYPE dcr_x counter") == 1
    assert 'dcr_x{worker="0"} 1' in merged and 'dcr_x{worker="1"} 2' in merged
    _assert_valid_exposition(merged)


class _MetricsHandler(BaseHTTPRequestHandler):
    payload = b"# HELP dcr_up h\n# TYPE dcr_up gauge\ndcr_up 1\n"

    def do_GET(self):                                 # noqa: N802 (stdlib API)
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(self.payload)))
        self.end_headers()
        self.wfile.write(self.payload)

    def log_message(self, fmt, *args):
        pass


@pytest.mark.fast
def test_scrape_cache_last_good_text_and_bounded_failure():
    server = ThreadingHTTPServer(("127.0.0.1", 0), _MetricsHandler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        port = server.server_address[1]
        cache = ScrapeCache("127.0.0.1", timeout_s=1.0)
        assert cache.scrape(0, port) is True
        snap = cache.snapshot()
        text, age = snap[0]
        assert "dcr_up 1" in text and age < 5.0
        # a dead worker: quick typed failure, last-good cache untouched
        with ThreadingHTTPServer(("127.0.0.1", 0), _MetricsHandler) as tmp:
            dead_port = tmp.server_address[1]
        t0 = time.monotonic()
        assert cache.scrape(1, dead_port) is False
        assert time.monotonic() - t0 < 5.0            # bounded, no hang
        assert 1 not in cache.snapshot()
        assert tracing.registry().counter("fleet/scrape_errors").value >= 1
        cache.forget(0)
        assert cache.snapshot() == {}
    finally:
        server.shutdown()
        server.server_close()


@pytest.mark.fast
def test_supervisor_merged_exposition_from_cache_only(tmp_path):
    """prometheus_merged builds the fleet document from the scrape cache and
    slot states alone — no sockets — with worker-labeled series, staleness
    gauges, up=0 for a dead slot, and deduplicated headers."""
    from dcr_tpu.core.config import FleetConfig, ServeConfig
    from dcr_tpu.serve.supervisor import ALIVE, FleetSupervisor

    cfg = ServeConfig(resolution=16, num_inference_steps=2, sampler="ddim",
                      fleet=FleetConfig(workers=2, dir=str(tmp_path)))
    sup = FleetSupervisor(cfg)                        # never started
    try:
        worker_text = ("# HELP dcr_serve_completed_total h\n"
                       "# TYPE dcr_serve_completed_total counter\n"
                       "dcr_serve_completed_total 4\n")
        sup._slots[0].state = ALIVE
        sup._scrape._cache = {0: (worker_text, time.time()),
                              1: (worker_text, time.time() - 3600.0)}
        merged = sup.prometheus_merged()
        samples = _assert_valid_exposition(merged)
        assert samples['dcr_serve_completed_total{worker="0"}'] == "4"
        assert samples['dcr_fleet_worker_up{worker="0"}'] == "1"
        # slot 1 never went ALIVE and its scrape is an hour stale: down,
        # but its last-good numbers still serve with a loud age
        assert samples['dcr_fleet_worker_up{worker="1"}'] == "0"
        assert float(
            samples['dcr_fleet_worker_scrape_age_seconds{worker="1"}']) > 1000
        assert samples['dcr_serve_completed_total{worker="1"}'] == "4"
        # supervisor-side SLO gauges ride the same document
        sup._update_slo_gauges(alive=1)
        samples = _assert_valid_exposition(sup.prometheus_merged())
        assert samples["dcr_fleet_availability"] == "0.5"
        assert "dcr_fleet_shed_rate" in samples
    finally:
        sup.journal.close()


# ---------------------------------------------------------------------------
# on-demand profiling: the armer state machine (profiler stubbed)
# ---------------------------------------------------------------------------

def _stub_profiler(monkeypatch, calls, fail_start=False):
    from dcr_tpu.utils import profiling

    def start_trace(logdir):
        if fail_start:
            raise RuntimeError("profiler unsupported here")
        calls.append(("start", logdir))

    monkeypatch.setattr(profiling.jax.profiler, "start_trace", start_trace)
    monkeypatch.setattr(profiling.jax.profiler, "stop_trace",
                        lambda: calls.append(("stop", None)))


@pytest.mark.fast
def test_profile_armer_captures_k_steps_then_disarms(monkeypatch, tmp_path):
    from dcr_tpu.utils.profiling import _ProfileArmer

    calls: list = []
    _stub_profiler(monkeypatch, calls)
    armer = _ProfileArmer()
    with armer.capture():                             # unarmed: pure no-op
        pass
    assert calls == [] and armer.status()["armed"] is False
    doc = armer.arm(str(tmp_path), steps=2)
    assert doc["armed"] is True and doc["remaining"] == 2
    with pytest.raises(RuntimeError, match="already armed"):
        armer.arm(str(tmp_path))
    with pytest.raises(ValueError):
        armer.arm(str(tmp_path), steps=0)
    with armer.capture():
        pass
    assert armer.status()["remaining"] == 1           # started, still open
    with armer.capture():
        pass
    assert calls == [("start", str(tmp_path)), ("stop", None)]
    status = armer.status()
    assert status["armed"] is False
    assert status["artifact"] == str(tmp_path)
    assert status["error"] is None
    armer.arm(str(tmp_path), steps=1)                 # re-armable after done
    with armer.capture():
        pass
    assert calls.count(("stop", None)) == 2


@pytest.mark.fast
def test_profile_armer_failure_disarms_without_breaking_region(
        monkeypatch, tmp_path):
    from dcr_tpu.utils.profiling import _ProfileArmer

    calls: list = []
    _stub_profiler(monkeypatch, calls, fail_start=True)
    armer = _ProfileArmer()
    armer.arm(str(tmp_path), steps=3)
    ran = []
    with armer.capture():
        ran.append(True)                              # the hot region RUNS
    assert ran == [True]
    status = armer.status()
    assert status["armed"] is False and "unsupported" in status["error"]
    with armer.capture():                             # back to no-op
        pass
    assert calls == []


# ---------------------------------------------------------------------------
# trace_report fleet merge over synthetic multi-process traces
# ---------------------------------------------------------------------------

def _rec(name, id, ts, *, ph="X", dur=1000, parent=None, trace=None,
         args=None):
    rec = {"ph": ph, "name": name, "id": id, "parent": parent, "ts": ts,
           "pid": 0, "tid": 1, "tname": "t", "args": args or {}}
    if ph == "X":
        rec["dur"] = dur
    if trace is not None:
        rec["trace"] = trace
    return rec


def _write(path: Path, records) -> None:
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text("".join(json.dumps(r) + "\n" for r in records))


_T0 = 1_700_000_000_000_000                           # an arbitrary epoch us


def _write_fleet_dir(tmp_path: Path, *, skew_us=0) -> Path:
    """Supervisor + 2 workers. Trace A is dispatched to worker 0 (which dies
    mid-batch: its root span never lands, leaving an orphan queue_wait),
    requeued to worker 1 as attempt 2. Trace B runs on worker 1 whose clock
    is ``skew_us`` BEHIND the supervisor's."""
    a, b = "aaaa000000000001", "bbbb000000000002"
    sup = [
        _rec("serve/request", 1, _T0, dur=400_000, trace=a,
             args={"request_id": 1}),
        _rec("serve/queue_wait", 2, _T0 + 1_000, parent=1, trace=a,
             args={"request_id": 1}),
        _rec("fleet/dispatch", 3, _T0 + 5_000, dur=60_000,
             args={"worker": 0, "trace_ids": [a]}),
        _rec("fleet/dispatch", 4, _T0 + 80_000, dur=90_000,
             args={"worker": 1, "trace_ids": [a]}),     # requeued re-dispatch
        _rec("serve/request", 5, _T0 + 2_000, dur=300_000, trace=b,
             args={"request_id": 2}),
        _rec("fleet/dispatch", 6, _T0 + 10_000, dur=80_000,
             args={"worker": 1, "trace_ids": [b]}),
    ]
    # worker 0 was SIGKILLed before its serve/request root (id=9) ended:
    # only the retroactive queue_wait landed — parent id 9 never written
    w0 = [
        _rec("serve/queue_wait", 10, _T0 + 8_000, parent=9, trace=a,
             args={"request_id": 1}),
    ]
    w1 = [
        _rec("serve/request", 1, _T0 + 85_000 - skew_us, dur=80_000, trace=a,
             args={"remote_parent": 1, "attempt": 2, "request_id": 1}),
        _rec("serve/queue_wait", 2, _T0 + 86_000 - skew_us, parent=1, trace=a,
             args={"request_id": 1}),
        _rec("serve/assemble", 3, _T0 + 87_000 - skew_us, dur=5_000,
             args={"trace_ids": [a]}),
        _rec("serve/request", 4, _T0 + 12_000 - skew_us, dur=70_000, trace=b,
             args={"remote_parent": 5, "attempt": 1, "request_id": 2}),
        _rec("serve/assemble", 5, _T0 + 13_000 - skew_us, dur=5_000,
             args={"trace_ids": [b]}),
        _rec("serve/respond", 6, _T0 + 70_000 - skew_us, parent=4, trace=b,
             args={"request_id": 2}),
    ]
    _write(tmp_path / "trace.jsonl", sup)
    _write(tmp_path / "worker_0" / "trace.jsonl", w0)
    _write(tmp_path / "worker_1" / "trace.jsonl", w1)
    return tmp_path


@pytest.mark.fast
def test_fleet_merge_one_connected_tree_per_trace(tmp_path):
    fleet_dir = _write_fleet_dir(tmp_path)
    records, errors, meta = trace_report.load_fleet(
        [fleet_dir], trace_report.load_schema())
    assert not errors
    assert meta["processes"] == ["trace.jsonl", "worker_0/trace.jsonl",
                                 "worker_1/trace.jsonl"]
    assert meta["clock_offset_us"] == {}              # shared host clock
    summary = trace_report.summarize(records, meta)
    fleet = summary["fleet"]
    assert fleet["traces"] == 2
    assert fleet["connected"] == 2                    # one root each, links ok
    assert fleet["cross_process"] == 2
    assert fleet["requeued"] == 1 and fleet["max_attempts"] == 2
    assert fleet["orphan_spans"] == 1                 # w0's dead attempt
    trees = {t["trace"]: t for t in fleet["trees"]}
    assert trees["aaaa000000000001"]["attempts"] == 2
    assert trees["aaaa000000000001"]["orphan_spans"] == 1
    assert set(trees["aaaa000000000001"]["processes"]) == {
        "trace.jsonl", "worker_0/trace.jsonl", "worker_1/trace.jsonl"}
    assert trees["bbbb000000000002"]["orphan_spans"] == 0


@pytest.mark.fast
def test_fleet_merge_clock_offset_anchored_on_dispatch_assemble(tmp_path):
    skew = 50_000
    fleet_dir = _write_fleet_dir(tmp_path, skew_us=skew)
    records, errors, meta = trace_report.load_fleet(
        [fleet_dir], trace_report.load_schema())
    assert not errors
    # worker 1's assemble for trace B began before its dispatch — impossible
    # causally — so its whole stream shifts forward by the violation
    off = meta["clock_offset_us"]["worker_1/trace.jsonl"]
    assert off >= skew - 3_000                        # recovered (±in-flight)
    [dispatch_b] = [r for r in records if r["name"] == "fleet/dispatch"
                    and r["args"]["trace_ids"] == ["bbbb000000000002"]]
    [root_b] = [r for r in records
                if r["_plabel"] == "worker_1/trace.jsonl"
                and r["name"] == "serve/request"
                and r.get("trace") == "bbbb000000000002"]
    assert root_b["ts"] >= dispatch_b["ts"] - 3_000   # causal after adjust
    assert trace_report.summarize(records, meta)["fleet"]["connected"] == 2


@pytest.mark.fast
def test_fleet_merge_detects_disconnected_trace(tmp_path):
    _write_fleet_dir(tmp_path)
    # a worker root claiming a remote parent that is NOT the trace root
    _write(tmp_path / "worker_0" / "trace.jsonl", [
        _rec("serve/queue_wait", 10, _T0 + 8_000, parent=9,
             trace="aaaa000000000001", args={"request_id": 1}),
        _rec("serve/request", 11, _T0 + 9_000, trace="cccc000000000003",
             args={"remote_parent": 999, "attempt": 1}),
        _rec("serve/request", 12, _T0 + 9_500, trace="cccc000000000003",
             args={}),
    ])
    records, _, meta = trace_report.load_fleet(
        [tmp_path], trace_report.load_schema())
    fleet = trace_report.summarize(records, meta)["fleet"]
    trees = {t["trace"]: t for t in fleet["trees"]}
    assert trees["cccc000000000003"]["connected"] is False
    assert fleet["connected"] == 2                    # a and b still are


@pytest.mark.fast
def test_fleet_chrome_export_one_track_per_process(tmp_path):
    fleet_dir = _write_fleet_dir(tmp_path)
    records, _, _ = trace_report.load_fleet(
        [fleet_dir], trace_report.load_schema())
    doc = trace_report.chrome_trace(records)
    procs = {e["pid"]: e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert sorted(procs.values()) == ["trace.jsonl", "worker_0/trace.jsonl",
                                      "worker_1/trace.jsonl"]
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in spans} == set(procs)    # distinct tracks
    assert any(e["args"].get("trace") for e in spans)


@pytest.mark.fast
def test_trace_report_cli_on_fleet_dir(tmp_path, capsys):
    fleet_dir = _write_fleet_dir(tmp_path, skew_us=20_000)
    chrome = tmp_path / "chrome.json"
    assert trace_report.main([str(fleet_dir), "--chrome", str(chrome)]) == 0
    out = capsys.readouterr().out
    assert "fleet: 2 distributed trace(s)" in out
    assert "2 connected" in out and "1 requeued" in out
    assert "clock offset worker_1/trace.jsonl" in out
    json.loads(chrome.read_text())                    # loadable
    # multiple explicit paths merge too (files, not just dirs)
    assert trace_report.main([str(tmp_path / "trace.jsonl"),
                              str(tmp_path / "worker_1" / "trace.jsonl"),
                              "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["fleet"]["traces"] == 2


# ---------------------------------------------------------------------------
# acceptance e2e: fleet trace merge + merged metrics + /debug/profile
# (slow; CI `observability` job)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_trainer_profile_at_step_writes_artifact(tmp_path):
    """DCR_PROFILE_AT_STEP reuses the serve armer: a tiny CPU train run with
    it set produces a readable jax.profiler artifact under
    <output_dir>/profile and still trains to completion."""
    from tests.test_tracing import _run_train_cli, _tiny_train_cfg

    cfg = _tiny_train_cfg(tmp_path)
    proc, out = _run_train_cli(cfg, tmp_path / "cfg.json",
                               extra_env={"DCR_PROFILE_AT_STEP": "2"})
    assert proc.returncode == 0, out[-3000:]
    assert "profile_armed" in out
    dumped = list((Path(cfg.output_dir) / "profile").rglob("*.xplane.pb"))
    assert dumped, f"no profiler artifact under {cfg.output_dir}/profile"

@pytest.mark.slow
def test_fleet_scope_e2e_trace_merge_metrics_profile(tmp_path, cpu_devices):
    """dcr-scope acceptance: a 2-worker fleet with an injected worker_crash
    serves every request; the merged /metrics carries worker-labeled series
    and up/staleness gauges without blocking on the dead worker; a
    POST /debug/profile round-trip yields a readable jax.profiler artifact;
    and trace_report over the fleet dir reconstructs ONE connected span
    tree per request — the requeued request as attempt-tagged siblings
    under the same supervisor root."""
    import signal
    import subprocess
    import sys
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor

    from dcr_tpu.core.coordination import EXIT_PREEMPTED
    from tests._multiproc import free_port
    from tests.test_serve import (_export_tiny_ckpt, _get, _post_generate,
                                  _serve_env)

    ckpt = _export_tiny_ckpt(tmp_path)
    env, repo = _serve_env()
    env["DCR_FAULTS"] = "worker_crash@batch=0&rank=0"
    fleet_dir = tmp_path / "fleet"
    port = free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "dcr_tpu.cli.serve",
         f"--model_path={ckpt}", f"--port={port}",
         "--resolution=16", "--num_inference_steps=2", "--sampler=ddim",
         "--max_batch=2", "--max_wait_ms=60", "--queue_depth=64",
         "--request_timeout_s=300", "--seed=0",
         "--fleet.workers=2", f"--fleet.dir={fleet_dir}",
         "--fleet.heartbeat_s=0.5", "--fleet.lease_s=3",
         "--fleet.dispatch_timeout_s=240", "--fleet.spawn_timeout_s=240",
         "--fleet.max_attempts=6", "--fleet.respawn_max=2",
         "--fleet.respawn_base_delay_s=2",
         "--fleet.scrape_period_s=0.5", "--fleet.scrape_timeout_s=2"],
        env=env, cwd=repo, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        deadline = time.monotonic() + 300
        while True:
            try:
                _, health = _get(port, "/healthz", timeout=2)
                _, status = _get(port, "/metrics", timeout=2)
                if health["status"] == "ok" and status["workers_alive"] == 2:
                    break
            except OSError:
                pass
            if proc.poll() is not None or time.monotonic() > deadline:
                out = proc.stdout.read() if proc.stdout else ""
                raise AssertionError(
                    f"fleet did not come up (rc={proc.poll()}): {out[-4000:]}")
            time.sleep(0.5)

        # -- serve through the crash: worker 0 dies on its first batch ------
        prompts = ["a red square", "a blue circle"] * 3
        with ThreadPoolExecutor(max_workers=len(prompts)) as ex:
            results = list(ex.map(
                lambda a: _post_generate(port, a[1], seed=a[0], timeout=280),
                enumerate(prompts)))
        assert all(code == 200 for code, _ in results), results

        # -- merged prometheus: worker-labeled series, no blocking ----------
        time.sleep(2.0)                               # > one scrape period
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics?format=prometheus",
                timeout=30) as resp:
            text = resp.read().decode()
        samples = _assert_valid_exposition(text)
        assert 'dcr_fleet_worker_up{worker="0"}' in samples
        assert 'dcr_fleet_worker_up{worker="1"}' in samples
        # at least the surviving worker's full registry is merged in,
        # worker-labeled (completed counter counts its executed requests)
        assert float(
            samples['dcr_serve_completed_total{worker="1"}']) >= 1.0
        assert 'dcr_fleet_worker_scrape_age_seconds{worker="1"}' in samples
        # fleet SLO series are first-class gauges
        assert "dcr_fleet_availability" in samples
        assert "dcr_fleet_queue_wait_p99_s" in samples
        assert float(samples["dcr_fleet_requeue_rate"]) > 0.0

        # -- on-demand device profiling round-trip --------------------------
        body = json.dumps({"worker": 1, "steps": 1}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/debug/profile", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            armed = json.loads(resp.read())
        assert armed["worker"] == 1 and armed["armed"] is True

        # drive batches until the armed capture closes and reports its path
        artifact = None
        for i in range(30):
            _post_generate(port, "profile me", seed=100 + i, timeout=280)
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/profile",
                    timeout=30) as resp:
                doc = json.loads(resp.read())
            assert doc.get("error") in (None, ""), doc
            if doc.get("artifact"):
                artifact = Path(doc["artifact"])
                break
        assert artifact is not None, "profiler capture never completed"
        assert artifact.is_dir()
        dumped = list(artifact.rglob("*.xplane.pb"))
        assert dumped, f"no profiler artifact under {artifact}"

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=180)
        out = proc.stdout.read() if proc.stdout else ""
        assert rc == EXIT_PREEMPTED, (rc, out[-4000:])
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    # -- fleet trace merge: one connected tree per request ------------------
    records, errors, meta = trace_report.load_fleet(
        [fleet_dir], trace_report.load_schema())
    assert not errors, errors[:5]
    assert len(meta["processes"]) >= 3                # supervisor + 2 workers
    fleet = trace_report.summarize(records, meta)["fleet"]
    assert fleet is not None
    assert fleet["traces"] == fleet["connected"], fleet
    assert fleet["cross_process"] == fleet["traces"], fleet
    # the crashed batch's requests were requeued: same trace id, attempt 2+
    assert fleet["requeued"] >= 1 and fleet["max_attempts"] >= 2, fleet
    # worker-side roots really join the supervisor's tree (not fresh roots)
    worker_roots = [r for r in records
                    if r["name"] == "serve/request"
                    and r["args"].get("remote_parent") is not None]
    assert worker_roots
    # and the report CLI ships it end to end
    import subprocess as sp
    import sys as _sys
    env2, repo2 = _serve_env()
    chrome = tmp_path / "fleet_chrome.json"
    rep = sp.run([_sys.executable, "-m", "tools.trace_report",
                  str(fleet_dir), "--chrome", str(chrome)],
                 env=env2, cwd=repo2, capture_output=True, text=True,
                 timeout=60)
    assert rep.returncode == 0, rep.stdout + rep.stderr
    assert "fleet:" in rep.stdout and "requeued" in rep.stdout
    procs = {e["args"]["name"]
             for e in json.loads(chrome.read_text())["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert len(procs) >= 3                            # one track per process

"""Torch transcriptions of diffusers' UNet2DConditionModel / AutoencoderKL.

Independent torch implementations of the architectures the reference
finetunes (diff_train.py:370-408 loads them from HF diffusers; diffusers is
not installed in this image). Module/parameter naming follows the real
diffusers state-dict layout byte-for-byte (validated against the vendored
SD-2.1 manifests, tests/fixtures/sd21_*_keys.json), so
`load_state_dict(..., strict=True)` on tensors produced by
dcr_tpu.models.export proves the exporter emits genuinely loadable
checkpoints — and running the loaded model proves cross-framework
activation parity of the NHWC Flax stack against torch NCHW semantics
(SURVEY.md §4 item 2, §7.3 "UNet weight-conversion fidelity").

SD-2.x variant: linear transformer projections, GEGLU feed-forward,
eps=1e-5 resnet norms / 1e-6 transformer+VAE norms, 0.14-era VAE
AttentionBlock naming (query/key/value/proj_attn).
"""

from __future__ import annotations

import math

import torch
import torch.nn as nn
import torch.nn.functional as F


def timestep_embedding(t: torch.Tensor, dim: int) -> torch.Tensor:
    """Sinusoidal embedding, flip_sin_to_cos=True, freq_shift=0 (SD config)."""
    half = dim // 2
    freqs = torch.exp(-math.log(10000.0) * torch.arange(half, dtype=torch.float32) / half)
    args = t.float()[:, None] * freqs[None, :]
    return torch.cat([torch.cos(args), torch.sin(args)], dim=-1)


def attention(q: torch.Tensor, k: torch.Tensor, v: torch.Tensor,
              heads: int) -> torch.Tensor:
    b, sq, inner = q.shape
    hd = inner // heads
    split = lambda x: x.reshape(b, -1, heads, hd).transpose(1, 2)
    q, k, v = split(q), split(k), split(v)
    w = torch.softmax(q @ k.transpose(-1, -2) / math.sqrt(hd), dim=-1)
    return (w @ v).transpose(1, 2).reshape(b, sq, inner)


class ResnetBlock2D(nn.Module):
    def __init__(self, in_ch: int, out_ch: int, temb_ch: int = 0,
                 groups: int = 32, eps: float = 1e-5):
        super().__init__()
        self.norm1 = nn.GroupNorm(groups, in_ch, eps=eps)
        self.conv1 = nn.Conv2d(in_ch, out_ch, 3, padding=1)
        if temb_ch:
            self.time_emb_proj = nn.Linear(temb_ch, out_ch)
        self.norm2 = nn.GroupNorm(groups, out_ch, eps=eps)
        self.conv2 = nn.Conv2d(out_ch, out_ch, 3, padding=1)
        if in_ch != out_ch:
            self.conv_shortcut = nn.Conv2d(in_ch, out_ch, 1)

    def forward(self, x, temb=None):
        h = self.conv1(F.silu(self.norm1(x)))
        if temb is not None:
            h = h + self.time_emb_proj(F.silu(temb))[:, :, None, None]
        h = self.conv2(F.silu(self.norm2(h)))
        skip = self.conv_shortcut(x) if hasattr(self, "conv_shortcut") else x
        return h + skip


class GEGLU(nn.Module):
    def __init__(self, dim: int, inner: int):
        super().__init__()
        self.proj = nn.Linear(dim, inner * 2)

    def forward(self, x):
        h, gate = self.proj(x).chunk(2, dim=-1)
        return h * F.gelu(gate)


class CrossAttention(nn.Module):
    def __init__(self, dim: int, ctx_dim: int, heads: int):
        super().__init__()
        self.heads = heads
        self.to_q = nn.Linear(dim, dim, bias=False)
        self.to_k = nn.Linear(ctx_dim, dim, bias=False)
        self.to_v = nn.Linear(ctx_dim, dim, bias=False)
        self.to_out = nn.ModuleList([nn.Linear(dim, dim)])

    def forward(self, x, ctx=None):
        ctx = x if ctx is None else ctx
        out = attention(self.to_q(x), self.to_k(ctx), self.to_v(ctx), self.heads)
        return self.to_out[0](out)


class BasicTransformerBlock(nn.Module):
    def __init__(self, dim: int, ctx_dim: int, heads: int):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim)
        self.attn1 = CrossAttention(dim, dim, heads)
        self.norm2 = nn.LayerNorm(dim)
        self.attn2 = CrossAttention(dim, ctx_dim, heads)
        self.norm3 = nn.LayerNorm(dim)
        self.ff = nn.Sequential(GEGLU(dim, dim * 4), nn.Identity(),
                                nn.Linear(dim * 4, dim))
        # diffusers names: ff.net.0 (GEGLU), ff.net.2 (Linear)
        self.ff = nn.ModuleDict({"net": self.ff})

    def forward(self, x, ctx):
        x = x + self.attn1(self.norm1(x))
        x = x + self.attn2(self.norm2(x), ctx)
        return x + self.ff["net"](self.norm3(x))


class Transformer2DModel(nn.Module):
    """Spatial transformer. use_linear: SD-2.x linear projections applied
    after the reshape; else SD-1.x 1x1 convs applied before it."""

    def __init__(self, ch: int, ctx_dim: int, heads: int, layers: int,
                 groups: int = 32, use_linear: bool = True):
        super().__init__()
        self.use_linear = use_linear
        self.norm = nn.GroupNorm(groups, ch, eps=1e-6)
        proj = (lambda: nn.Linear(ch, ch)) if use_linear else \
               (lambda: nn.Conv2d(ch, ch, 1))
        self.proj_in = proj()
        self.transformer_blocks = nn.ModuleList(
            [BasicTransformerBlock(ch, ctx_dim, heads) for _ in range(layers)])
        self.proj_out = proj()

    def forward(self, x, ctx):
        b, c, h, w = x.shape
        res = x
        out = self.norm(x)
        if self.use_linear:
            out = out.permute(0, 2, 3, 1).reshape(b, h * w, c)
            out = self.proj_in(out)
        else:
            out = self.proj_in(out).permute(0, 2, 3, 1).reshape(b, h * w, c)
        for blk in self.transformer_blocks:
            out = blk(out, ctx)
        if self.use_linear:
            out = self.proj_out(out)
            out = out.reshape(b, h, w, c).permute(0, 3, 1, 2)
        else:
            out = out.reshape(b, h, w, c).permute(0, 3, 1, 2)
            out = self.proj_out(out)
        return out + res


class Downsample2D(nn.Module):
    def __init__(self, ch: int, asymmetric: bool = False):
        super().__init__()
        self.asymmetric = asymmetric
        self.conv = nn.Conv2d(ch, ch, 3, stride=2, padding=0 if asymmetric else 1)

    def forward(self, x):
        if self.asymmetric:                       # diffusers VAE encoder pad
            x = F.pad(x, (0, 1, 0, 1))
        return self.conv(x)


class Upsample2D(nn.Module):
    def __init__(self, ch: int):
        super().__init__()
        self.conv = nn.Conv2d(ch, ch, 3, padding=1)

    def forward(self, x):
        return self.conv(F.interpolate(x, scale_factor=2.0, mode="nearest"))


class _Blockset(nn.Module):
    """Container matching diffusers' {resnets, attentions, downsamplers,
    upsamplers} child naming inside each down/up block."""

    def __init__(self, resnets, attentions=None, downsamplers=None,
                 upsamplers=None):
        super().__init__()
        self.resnets = nn.ModuleList(resnets)
        if attentions is not None:
            self.attentions = nn.ModuleList(attentions)
        if downsamplers is not None:
            self.downsamplers = nn.ModuleList(downsamplers)
        if upsamplers is not None:
            self.upsamplers = nn.ModuleList(upsamplers)


class TorchUNet2DCondition(nn.Module):
    """diffusers UNet2DConditionModel (SD-2.x), built from our ModelConfig."""

    def __init__(self, cfg):
        super().__init__()
        bo = cfg.block_out_channels
        n = len(bo)
        temb_ch = bo[0] * 4
        ctx = cfg.cross_attention_dim
        lpb = cfg.layers_per_block
        g = cfg.norm_num_groups
        self.cfg = cfg

        def t2d(ch: int) -> Transformer2DModel:
            heads = (cfg.attention_num_heads
                     or ch // cfg.attention_head_dim)
            return Transformer2DModel(
                ch, ctx, heads, cfg.transformer_layers, g,
                use_linear=cfg.use_linear_projection)

        self.conv_in = nn.Conv2d(cfg.in_channels, bo[0], 3, padding=1)
        self.time_embedding = nn.ModuleDict({
            "linear_1": nn.Linear(bo[0], temb_ch),
            "linear_2": nn.Linear(temb_ch, temb_ch)})

        down = []
        ch = bo[0]
        for i, out_ch in enumerate(bo):
            final = i == n - 1
            resnets, attns = [], []
            for j in range(lpb):
                resnets.append(ResnetBlock2D(ch if j == 0 else out_ch, out_ch,
                                             temb_ch, g))
                if not final:
                    attns.append(t2d(out_ch))
            ch = out_ch
            down.append(_Blockset(
                resnets, attentions=attns if not final else None,
                downsamplers=[Downsample2D(out_ch)] if not final else None))
        self.down_blocks = nn.ModuleList(down)

        mid_ch = bo[-1]
        self.mid_block = _Blockset(
            [ResnetBlock2D(mid_ch, mid_ch, temb_ch, g),
             ResnetBlock2D(mid_ch, mid_ch, temb_ch, g)],
            attentions=[t2d(mid_ch)])

        # skip channel bookkeeping mirrors the down path
        skip_chs = [bo[0]]
        for i, out_ch in enumerate(bo):
            skip_chs += [out_ch] * lpb
            if i < n - 1:
                skip_chs.append(out_ch)
        up = []
        ch = bo[-1]
        for i, out_ch in enumerate(reversed(bo)):
            first = i == 0                    # bottom of the U: no attention
            resnets, attns = [], []
            for j in range(lpb + 1):
                skip = skip_chs.pop()
                resnets.append(ResnetBlock2D(ch + skip, out_ch, temb_ch, g))
                ch = out_ch
                if not first:
                    attns.append(t2d(out_ch))
            up.append(_Blockset(
                resnets, attentions=attns if not first else None,
                upsamplers=[Upsample2D(out_ch)] if i < n - 1 else None))
        self.up_blocks = nn.ModuleList(up)

        self.conv_norm_out = nn.GroupNorm(g, bo[0], eps=1e-5)
        self.conv_out = nn.Conv2d(bo[0], cfg.out_channels, 3, padding=1)

    def forward(self, sample, timesteps, context):
        temb = timestep_embedding(timesteps, self.cfg.block_out_channels[0])
        temb = self.time_embedding["linear_2"](
            F.silu(self.time_embedding["linear_1"](temb)))

        h = self.conv_in(sample)
        skips = [h]
        for blk in self.down_blocks:
            attns = list(getattr(blk, "attentions", []))
            for j, res in enumerate(blk.resnets):
                h = res(h, temb)
                if attns:
                    h = attns[j](h, context)
                skips.append(h)
            if hasattr(blk, "downsamplers"):
                h = blk.downsamplers[0](h)
                skips.append(h)

        h = self.mid_block.resnets[0](h, temb)
        h = self.mid_block.attentions[0](h, context)
        h = self.mid_block.resnets[1](h, temb)

        for blk in self.up_blocks:
            attns = list(getattr(blk, "attentions", []))
            for j, res in enumerate(blk.resnets):
                h = res(torch.cat([h, skips.pop()], dim=1), temb)
                if attns:
                    h = attns[j](h, context)
            if hasattr(blk, "upsamplers"):
                h = blk.upsamplers[0](h)

        return self.conv_out(F.silu(self.conv_norm_out(h)))


class AttentionBlock(nn.Module):
    """diffusers 0.14-era VAE attention (query/key/value/proj_attn naming)."""

    def __init__(self, ch: int, groups: int):
        super().__init__()
        self.group_norm = nn.GroupNorm(groups, ch, eps=1e-6)
        self.query = nn.Linear(ch, ch)
        self.key = nn.Linear(ch, ch)
        self.value = nn.Linear(ch, ch)
        self.proj_attn = nn.Linear(ch, ch)

    def forward(self, x):
        b, c, h, w = x.shape
        out = self.group_norm(x).permute(0, 2, 3, 1).reshape(b, h * w, c)
        out = attention(self.query(out), self.key(out), self.value(out), 1)
        out = self.proj_attn(out)
        return out.reshape(b, h, w, c).permute(0, 3, 1, 2) + x


class TorchAutoencoderKL(nn.Module):
    """diffusers AutoencoderKL built from our ModelConfig (encode side returns
    moments [mean, logvar]; decode maps latents to pixels)."""

    def __init__(self, cfg):
        super().__init__()
        bo = cfg.vae_block_out_channels
        lpb = cfg.vae_layers_per_block
        g = min(cfg.norm_num_groups, bo[0])
        zc = cfg.vae_latent_channels
        n = len(bo)

        enc = nn.Module()
        enc.conv_in = nn.Conv2d(3, bo[0], 3, padding=1)
        blocks = []
        ch = bo[0]
        for i, out_ch in enumerate(bo):
            resnets = [ResnetBlock2D(ch if j == 0 else out_ch, out_ch,
                                     0, g, eps=1e-6) for j in range(lpb)]
            ch = out_ch
            blocks.append(_Blockset(
                resnets,
                downsamplers=[Downsample2D(out_ch, asymmetric=True)]
                if i < n - 1 else None))
        enc.down_blocks = nn.ModuleList(blocks)
        enc.mid_block = _Blockset(
            [ResnetBlock2D(bo[-1], bo[-1], 0, g, eps=1e-6),
             ResnetBlock2D(bo[-1], bo[-1], 0, g, eps=1e-6)],
            attentions=[AttentionBlock(bo[-1], g)])
        enc.conv_norm_out = nn.GroupNorm(g, bo[-1], eps=1e-6)
        enc.conv_out = nn.Conv2d(bo[-1], 2 * zc, 3, padding=1)
        self.encoder = enc
        self.quant_conv = nn.Conv2d(2 * zc, 2 * zc, 1)

        dec = nn.Module()
        dec.conv_in = nn.Conv2d(zc, bo[-1], 3, padding=1)
        dec.mid_block = _Blockset(
            [ResnetBlock2D(bo[-1], bo[-1], 0, g, eps=1e-6),
             ResnetBlock2D(bo[-1], bo[-1], 0, g, eps=1e-6)],
            attentions=[AttentionBlock(bo[-1], g)])
        blocks = []
        ch = bo[-1]
        for i, out_ch in enumerate(reversed(bo)):
            resnets = [ResnetBlock2D(ch if j == 0 else out_ch, out_ch,
                                     0, g, eps=1e-6) for j in range(lpb + 1)]
            ch = out_ch
            blocks.append(_Blockset(
                resnets,
                upsamplers=[Upsample2D(out_ch)] if i < n - 1 else None))
        dec.up_blocks = nn.ModuleList(blocks)
        dec.conv_norm_out = nn.GroupNorm(g, bo[0], eps=1e-6)
        dec.conv_out = nn.Conv2d(bo[0], 3, 3, padding=1)
        self.decoder = dec
        self.post_quant_conv = nn.Conv2d(zc, zc, 1)

    def encode(self, x):
        h = self.encoder.conv_in(x)
        for blk in self.encoder.down_blocks:
            for res in blk.resnets:
                h = res(h)
            if hasattr(blk, "downsamplers"):
                h = blk.downsamplers[0](h)
        mb = self.encoder.mid_block
        h = mb.resnets[1](mb.attentions[0](mb.resnets[0](h)))
        h = self.encoder.conv_out(F.silu(self.encoder.conv_norm_out(h)))
        return self.quant_conv(h)

    def decode(self, z):
        h = self.decoder.conv_in(self.post_quant_conv(z))
        mb = self.decoder.mid_block
        h = mb.resnets[1](mb.attentions[0](mb.resnets[0](h)))
        for blk in self.decoder.up_blocks:
            for res in blk.resnets:
                h = res(h)
            if hasattr(blk, "upsamplers"):
                h = blk.upsamplers[0](h)
        return self.decoder.conv_out(F.silu(self.decoder.conv_norm_out(h)))

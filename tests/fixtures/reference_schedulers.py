"""Independent NumPy transcription of the diffusers scheduler step semantics.

The build environment has no diffusers install and zero egress, so true
record-and-replay against the reference pipeline (diff_inference.py:93) is not
possible here. This module is the next-best evidence: a from-scratch NumPy
implementation of the *published* algorithms — DDIM (Song et al. 2020, eq. 12)
and DPM-Solver++(2M) (Lu et al. 2022, §4) — carrying the diffusers-specific
bookkeeping the SD pipelines layer on top (``set_timesteps`` spacing grids,
``steps_offset=1``, ``set_alpha_to_one=False``, final-step target t=0,
``lower_order_final``). It is written as stateful per-step classes mirroring
how the torch pipeline consumes a scheduler, shares no code with
``dcr_tpu.models.schedulers``, and works in float64 — so the test comparing the
two is a comparison of independently derived trajectories, not a self-golden.

If a diffusers install ever becomes available, `record_fixture.py`-style dumps
should replace this module as the source of truth.
"""

from __future__ import annotations

import numpy as np


def _make_betas(num_train_timesteps: int, beta_schedule: str,
                beta_start: float, beta_end: float) -> np.ndarray:
    if beta_schedule == "linear":
        return np.linspace(beta_start, beta_end, num_train_timesteps, dtype=np.float64)
    if beta_schedule == "scaled_linear":
        return np.linspace(beta_start ** 0.5, beta_end ** 0.5,
                           num_train_timesteps, dtype=np.float64) ** 2
    raise ValueError(beta_schedule)


class RefDDIMScheduler:
    """diffusers.DDIMScheduler semantics, eta=0, no thresholding/clipping
    (the SD pipeline configuration)."""

    def __init__(self, num_train_timesteps: int = 1000,
                 beta_schedule: str = "scaled_linear",
                 beta_start: float = 0.00085, beta_end: float = 0.012,
                 prediction_type: str = "epsilon",
                 steps_offset: int = 1, set_alpha_to_one: bool = False):
        self.num_train_timesteps = num_train_timesteps
        self.prediction_type = prediction_type
        self.steps_offset = steps_offset
        betas = _make_betas(num_train_timesteps, beta_schedule, beta_start, beta_end)
        self.alphas_cumprod = np.cumprod(1.0 - betas)
        self.final_alpha_cumprod = 1.0 if set_alpha_to_one else self.alphas_cumprod[0]
        self.timesteps: np.ndarray | None = None
        self.num_inference_steps: int | None = None

    def set_timesteps(self, num_inference_steps: int) -> None:
        # "leading" spacing + steps_offset, as in SD's shipped configs
        self.num_inference_steps = num_inference_steps
        step_ratio = self.num_train_timesteps // num_inference_steps
        ts = (np.arange(0, num_inference_steps) * step_ratio).round()[::-1].copy()
        self.timesteps = (ts + self.steps_offset).astype(np.int64)

    def _x0_eps(self, model_output, sample, t):
        acp = self.alphas_cumprod[t]
        a, s = np.sqrt(acp), np.sqrt(1.0 - acp)
        if self.prediction_type == "epsilon":
            eps = model_output
            x0 = (sample - s * eps) / a
        elif self.prediction_type == "v_prediction":
            x0 = a * sample - s * model_output
            eps = a * model_output + s * sample
        else:
            raise ValueError(self.prediction_type)
        return x0, eps

    def step(self, model_output: np.ndarray, timestep: int,
             sample: np.ndarray) -> np.ndarray:
        prev_t = timestep - self.num_train_timesteps // self.num_inference_steps
        x0, eps = self._x0_eps(model_output, sample, timestep)
        acp_prev = (self.alphas_cumprod[prev_t] if prev_t >= 0
                    else self.final_alpha_cumprod)
        direction = np.sqrt(1.0 - acp_prev) * eps  # eta = 0
        return np.sqrt(acp_prev) * x0 + direction


class RefDPMSolverMultistepScheduler:
    """diffusers.DPMSolverMultistepScheduler semantics: algorithm dpmsolver++,
    solver_order=2, solver_type=midpoint, lower_order_final=True, no
    thresholding — the configuration diff_inference.py:93 runs stock SD with."""

    def __init__(self, num_train_timesteps: int = 1000,
                 beta_schedule: str = "scaled_linear",
                 beta_start: float = 0.00085, beta_end: float = 0.012,
                 prediction_type: str = "epsilon",
                 lower_order_final: bool = True):
        self.num_train_timesteps = num_train_timesteps
        self.prediction_type = prediction_type
        self.lower_order_final = lower_order_final
        betas = _make_betas(num_train_timesteps, beta_schedule, beta_start, beta_end)
        self.alphas_cumprod = np.cumprod(1.0 - betas)
        self.alpha_t = np.sqrt(self.alphas_cumprod)
        self.sigma_t = np.sqrt(1.0 - self.alphas_cumprod)
        self.lambda_t = np.log(self.alpha_t) - np.log(self.sigma_t)
        self.timesteps: np.ndarray | None = None
        self._model_outputs: list[np.ndarray] = []
        self._timestep_list: list[int] = []
        self._lower_order_nums = 0

    def set_timesteps(self, num_inference_steps: int) -> None:
        # "linspace" spacing: n+1 points over [0, T-1], reversed, last dropped
        ts = np.linspace(0, self.num_train_timesteps - 1,
                         num_inference_steps + 1).round()[::-1][:-1].copy()
        self.timesteps = ts.astype(np.int64)
        self._model_outputs = []
        self._timestep_list = []
        self._lower_order_nums = 0

    def _convert_model_output(self, model_output, sample, t):
        # dpmsolver++ works on x0 predictions
        a, s = self.alpha_t[t], self.sigma_t[t]
        if self.prediction_type == "epsilon":
            return (sample - s * model_output) / a
        if self.prediction_type == "v_prediction":
            return a * sample - s * model_output
        raise ValueError(self.prediction_type)

    def _first_order_update(self, m0, t, prev_t, sample):
        lam_t, lam_s = self.lambda_t[prev_t], self.lambda_t[t]
        h = lam_t - lam_s
        return (self.sigma_t[prev_t] / self.sigma_t[t]) * sample \
            - self.alpha_t[prev_t] * (np.exp(-h) - 1.0) * m0

    def _second_order_update(self, prev_t, sample):
        t = prev_t
        s0, s1 = self._timestep_list[-1], self._timestep_list[-2]
        m0, m1 = self._model_outputs[-1], self._model_outputs[-2]
        lam_t, lam_s0, lam_s1 = self.lambda_t[t], self.lambda_t[s0], self.lambda_t[s1]
        h, h_0 = lam_t - lam_s0, lam_s0 - lam_s1
        r0 = h_0 / h
        D0, D1 = m0, (1.0 / r0) * (m0 - m1)
        # midpoint rule
        return (self.sigma_t[t] / self.sigma_t[s0]) * sample \
            - self.alpha_t[t] * (np.exp(-h) - 1.0) * D0 \
            - 0.5 * self.alpha_t[t] * (np.exp(-h) - 1.0) * D1

    def step(self, model_output: np.ndarray, timestep: int,
             sample: np.ndarray) -> np.ndarray:
        step_index = int(np.where(self.timesteps == timestep)[0][0])
        prev_t = (0 if step_index == len(self.timesteps) - 1
                  else int(self.timesteps[step_index + 1]))
        final_first = (step_index == len(self.timesteps) - 1
                       and self.lower_order_final and len(self.timesteps) < 15)
        x0 = self._convert_model_output(model_output, sample, timestep)
        self._model_outputs = (self._model_outputs + [x0])[-2:]
        self._timestep_list = (self._timestep_list + [int(timestep)])[-2:]
        if self._lower_order_nums < 1 or final_first:
            out = self._first_order_update(x0, int(timestep), prev_t, sample)
        else:
            out = self._second_order_update(prev_t, sample)
        if self._lower_order_nums < 2:
            self._lower_order_nums += 1
        return out

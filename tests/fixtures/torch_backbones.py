"""Torch transcriptions of the eval-backbone architectures (torchvision naming).

The reference's copy-detection/metric backbones ship as torch checkpoints:
SSCD TorchScript resnet50 (diff_retrieval.py:277-285), torchvision VGG16
(metrics/ipr.py:41), pt_inception-2015-12-05 (metrics/inception.py:219).
torchvision is not installed here, so these modules re-create the exact
architectures + state-dict naming in plain torch; tests/test_torch_parity.py
seeds them, feeds their state dicts through models/convert.py, and checks
Flax activations against the torch forwards — cross-framework parity with
the checkpoint-source layout (NCHW convs, eval-mode BatchNorm, torch
maxpool semantics).
"""

from __future__ import annotations

import torch
import torch.nn as nn
import torch.nn.functional as F


class Bottleneck(nn.Module):
    """torchvision resnet50 v1.5 bottleneck: stride on the 3x3 conv."""

    def __init__(self, in_ch: int, mid: int, stride: int = 1):
        super().__init__()
        out = mid * 4
        self.conv1 = nn.Conv2d(in_ch, mid, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(mid)
        self.conv2 = nn.Conv2d(mid, mid, 3, stride=stride, padding=1, bias=False)
        self.bn2 = nn.BatchNorm2d(mid)
        self.conv3 = nn.Conv2d(mid, out, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(out)
        if stride != 1 or in_ch != out:
            self.downsample = nn.Sequential(
                nn.Conv2d(in_ch, out, 1, stride=stride, bias=False),
                nn.BatchNorm2d(out))

    def forward(self, x):
        h = F.relu(self.bn1(self.conv1(x)))
        h = F.relu(self.bn2(self.conv2(h)))
        h = self.bn3(self.conv3(h))
        skip = self.downsample(x) if hasattr(self, "downsample") else x
        return F.relu(h + skip)


class TorchResNet50(nn.Module):
    """torchvision resnet50 trunk (conv1..layer4), no head."""

    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 64, 7, stride=2, padding=3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        mid, in_ch = 64, 64
        for stage, blocks in enumerate((3, 4, 6, 3), start=1):
            layers = []
            for b in range(blocks):
                layers.append(Bottleneck(in_ch, mid,
                                         stride=2 if stage > 1 and b == 0 else 1))
                in_ch = mid * 4
            setattr(self, f"layer{stage}", nn.Sequential(*layers))
            mid *= 2

    def forward(self, x):
        h = F.relu(self.bn1(self.conv1(x)))
        h = F.max_pool2d(h, 3, stride=2, padding=1)
        for stage in (1, 2, 3, 4):
            h = getattr(self, f"layer{stage}")(h)
        return h


class TorchSSCD(nn.Module):
    """SSCD descriptor: resnet50 trunk (`backbone.`) -> GeM(p=3) -> Linear
    (`embeddings.`), the TorchScript archive's structure."""

    def __init__(self, embed_dim: int = 512):
        super().__init__()
        self.backbone = TorchResNet50()
        self.embeddings = nn.Linear(2048, embed_dim)

    def forward(self, x, p: float = 3.0, eps: float = 1e-6):
        h = self.backbone(x)
        pooled = h.clamp(min=eps).pow(p).mean(dim=(2, 3)).pow(1.0 / p)
        return self.embeddings(pooled)


class BasicConv2d(nn.Module):
    """conv(bias=False) + BN(eps=1e-3) + relu — the Inception cell, named
    `conv`/`bn` like the pt_inception-2015-12-05 checkpoint."""

    def __init__(self, in_ch: int, out_ch: int, **kw):
        super().__init__()
        self.conv = nn.Conv2d(in_ch, out_ch, bias=False, **kw)
        self.bn = nn.BatchNorm2d(out_ch, eps=1e-3)

    def forward(self, x):
        return F.relu(self.bn(self.conv(x)))


def _avg3_exclude_pad(x):
    """TF-FID average pool: 3x3/1 pad 1, padding excluded from the divisor."""
    return F.avg_pool2d(x, 3, stride=1, padding=1, count_include_pad=False)


class IncA(nn.Module):
    def __init__(self, in_ch: int, pool: int):
        super().__init__()
        self.branch1x1 = BasicConv2d(in_ch, 64, kernel_size=1)
        self.branch5x5_1 = BasicConv2d(in_ch, 48, kernel_size=1)
        self.branch5x5_2 = BasicConv2d(48, 64, kernel_size=5, padding=2)
        self.branch3x3dbl_1 = BasicConv2d(in_ch, 64, kernel_size=1)
        self.branch3x3dbl_2 = BasicConv2d(64, 96, kernel_size=3, padding=1)
        self.branch3x3dbl_3 = BasicConv2d(96, 96, kernel_size=3, padding=1)
        self.branch_pool = BasicConv2d(in_ch, pool, kernel_size=1)

    def forward(self, x):
        b5 = self.branch5x5_2(self.branch5x5_1(x))
        bd = self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x)))
        return torch.cat([self.branch1x1(x), b5, bd,
                          self.branch_pool(_avg3_exclude_pad(x))], 1)


class IncB(nn.Module):
    def __init__(self, in_ch: int):
        super().__init__()
        self.branch3x3 = BasicConv2d(in_ch, 384, kernel_size=3, stride=2)
        self.branch3x3dbl_1 = BasicConv2d(in_ch, 64, kernel_size=1)
        self.branch3x3dbl_2 = BasicConv2d(64, 96, kernel_size=3, padding=1)
        self.branch3x3dbl_3 = BasicConv2d(96, 96, kernel_size=3, stride=2)

    def forward(self, x):
        bd = self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x)))
        return torch.cat([self.branch3x3(x), bd,
                          F.max_pool2d(x, 3, stride=2)], 1)


class IncC(nn.Module):
    def __init__(self, in_ch: int, c7: int):
        super().__init__()
        self.branch1x1 = BasicConv2d(in_ch, 192, kernel_size=1)
        self.branch7x7_1 = BasicConv2d(in_ch, c7, kernel_size=1)
        self.branch7x7_2 = BasicConv2d(c7, c7, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7_3 = BasicConv2d(c7, 192, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_1 = BasicConv2d(in_ch, c7, kernel_size=1)
        self.branch7x7dbl_2 = BasicConv2d(c7, c7, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_3 = BasicConv2d(c7, c7, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7dbl_4 = BasicConv2d(c7, c7, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_5 = BasicConv2d(c7, 192, kernel_size=(1, 7), padding=(0, 3))
        self.branch_pool = BasicConv2d(in_ch, 192, kernel_size=1)

    def forward(self, x):
        b7 = self.branch7x7_3(self.branch7x7_2(self.branch7x7_1(x)))
        bd = self.branch7x7dbl_1(x)
        bd = self.branch7x7dbl_3(self.branch7x7dbl_2(bd))
        bd = self.branch7x7dbl_5(self.branch7x7dbl_4(bd))
        return torch.cat([self.branch1x1(x), b7, bd,
                          self.branch_pool(_avg3_exclude_pad(x))], 1)


class IncD(nn.Module):
    def __init__(self, in_ch: int):
        super().__init__()
        self.branch3x3_1 = BasicConv2d(in_ch, 192, kernel_size=1)
        self.branch3x3_2 = BasicConv2d(192, 320, kernel_size=3, stride=2)
        self.branch7x7x3_1 = BasicConv2d(in_ch, 192, kernel_size=1)
        self.branch7x7x3_2 = BasicConv2d(192, 192, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7x3_3 = BasicConv2d(192, 192, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7x3_4 = BasicConv2d(192, 192, kernel_size=3, stride=2)

    def forward(self, x):
        b3 = self.branch3x3_2(self.branch3x3_1(x))
        b7 = self.branch7x7x3_4(self.branch7x7x3_3(
            self.branch7x7x3_2(self.branch7x7x3_1(x))))
        return torch.cat([b3, b7, F.max_pool2d(x, 3, stride=2)], 1)


class IncE(nn.Module):
    def __init__(self, in_ch: int, pool_mode: str):
        super().__init__()
        self.pool_mode = pool_mode
        self.branch1x1 = BasicConv2d(in_ch, 320, kernel_size=1)
        self.branch3x3_1 = BasicConv2d(in_ch, 384, kernel_size=1)
        self.branch3x3_2a = BasicConv2d(384, 384, kernel_size=(1, 3), padding=(0, 1))
        self.branch3x3_2b = BasicConv2d(384, 384, kernel_size=(3, 1), padding=(1, 0))
        self.branch3x3dbl_1 = BasicConv2d(in_ch, 448, kernel_size=1)
        self.branch3x3dbl_2 = BasicConv2d(448, 384, kernel_size=3, padding=1)
        self.branch3x3dbl_3a = BasicConv2d(384, 384, kernel_size=(1, 3), padding=(0, 1))
        self.branch3x3dbl_3b = BasicConv2d(384, 384, kernel_size=(3, 1), padding=(1, 0))
        self.branch_pool = BasicConv2d(in_ch, 192, kernel_size=1)

    def forward(self, x):
        b3 = self.branch3x3_1(x)
        b3 = torch.cat([self.branch3x3_2a(b3), self.branch3x3_2b(b3)], 1)
        bd = self.branch3x3dbl_2(self.branch3x3dbl_1(x))
        bd = torch.cat([self.branch3x3dbl_3a(bd), self.branch3x3dbl_3b(bd)], 1)
        if self.pool_mode == "max":        # Mixed_7c FID quirk
            bp = F.max_pool2d(x, 3, stride=1, padding=1)
        else:
            bp = _avg3_exclude_pad(x)
        return torch.cat([self.branch1x1(x), b3, bd, self.branch_pool(bp)], 1)


class TorchInceptionFID(nn.Module):
    """pt_inception-2015-12-05 network sliced at pool3 (2048-d), with the
    TF-faithful pooling quirks (reference metrics/inception.py:224-341).
    Input in [0,1]; resized to 299 and scaled to (-1,1) like the reference's
    wrapper (metrics/inception.py:146-153)."""

    def __init__(self):
        super().__init__()
        self.Conv2d_1a_3x3 = BasicConv2d(3, 32, kernel_size=3, stride=2)
        self.Conv2d_2a_3x3 = BasicConv2d(32, 32, kernel_size=3)
        self.Conv2d_2b_3x3 = BasicConv2d(32, 64, kernel_size=3, padding=1)
        self.Conv2d_3b_1x1 = BasicConv2d(64, 80, kernel_size=1)
        self.Conv2d_4a_3x3 = BasicConv2d(80, 192, kernel_size=3)
        self.Mixed_5b = IncA(192, 32)
        self.Mixed_5c = IncA(256, 64)
        self.Mixed_5d = IncA(288, 64)
        self.Mixed_6a = IncB(288)
        self.Mixed_6b = IncC(768, 128)
        self.Mixed_6c = IncC(768, 160)
        self.Mixed_6d = IncC(768, 160)
        self.Mixed_6e = IncC(768, 192)
        self.Mixed_7a = IncD(768)
        self.Mixed_7b = IncE(1280, "avg")
        self.Mixed_7c = IncE(2048, "max")

    def forward(self, x, resize_input: bool = True):
        if resize_input and x.shape[-1] != 299:
            x = F.interpolate(x, size=(299, 299), mode="bilinear",
                              align_corners=False)
        x = 2.0 * x - 1.0
        x = self.Conv2d_2b_3x3(self.Conv2d_2a_3x3(self.Conv2d_1a_3x3(x)))
        x = F.max_pool2d(x, 3, stride=2)
        x = self.Conv2d_4a_3x3(self.Conv2d_3b_1x1(x))
        x = F.max_pool2d(x, 3, stride=2)
        for name in ("Mixed_5b", "Mixed_5c", "Mixed_5d", "Mixed_6a",
                     "Mixed_6b", "Mixed_6c", "Mixed_6d", "Mixed_6e",
                     "Mixed_7a", "Mixed_7b", "Mixed_7c"):
            x = getattr(self, name)(x)
        return x.mean(dim=(2, 3))


class _XcitConvBN(nn.Sequential):
    """conv3x3(s=2, bias=False) + BN — the xcit repo's patch-embed cell."""

    def __init__(self, in_ch: int, out_ch: int):
        super().__init__(nn.Conv2d(in_ch, out_ch, 3, stride=2, padding=1,
                                   bias=False),
                         nn.BatchNorm2d(out_ch))


class XcitConvPatchEmbed(nn.Module):
    """Stride-2 conv tower; Sequential indices 0/2/4(/6) with GELU between,
    matching the hub checkpoints' `patch_embed.proj.{i}.{0,1}` keys."""

    def __init__(self, patch_size: int, embed_dim: int):
        super().__init__()
        if patch_size == 16:
            plan = (embed_dim // 8, embed_dim // 4, embed_dim // 2, embed_dim)
        else:  # patch 8
            plan = (embed_dim // 4, embed_dim // 2, embed_dim)
        mods, in_ch = [], 3
        for i, out_ch in enumerate(plan):
            if i:
                mods.append(nn.GELU())
            mods.append(_XcitConvBN(in_ch, out_ch))
            in_ch = out_ch
        self.proj = nn.Sequential(*mods)

    def forward(self, x):
        x = self.proj(x)
        hp, wp = x.shape[2], x.shape[3]
        return x.flatten(2).transpose(1, 2), (hp, wp)


class XcitPositionalEncodingFourier(nn.Module):
    """2D sinusoidal encoding -> 1x1 conv (`token_projection`), hidden 32,
    temperature 10000, positions cumsum-normalised to (0, 2pi]."""

    def __init__(self, dim: int, hidden_dim: int = 32, temperature: float = 1e4):
        super().__init__()
        self.token_projection = nn.Conv2d(hidden_dim * 2, dim, kernel_size=1)
        self.hidden_dim = hidden_dim
        self.temperature = temperature

    def forward(self, b, h, w):
        import math

        eps, scale = 1e-6, 2 * math.pi
        y = torch.arange(1, h + 1, dtype=torch.float32) / (h + eps) * scale
        x = torch.arange(1, w + 1, dtype=torch.float32) / (w + eps) * scale
        dim_t = torch.arange(self.hidden_dim, dtype=torch.float32)
        dim_t = self.temperature ** (2 * torch.div(dim_t, 2, rounding_mode="floor")
                                     / self.hidden_dim)

        def bank(pos):
            t = pos[:, None] / dim_t
            return torch.stack((t[:, 0::2].sin(), t[:, 1::2].cos()),
                               dim=2).flatten(1)

        py = bank(y)[:, None, :].expand(h, w, self.hidden_dim)
        px = bank(x)[None, :, :].expand(h, w, self.hidden_dim)
        pos = torch.cat((py, px), dim=2).permute(2, 0, 1)[None]
        return self.token_projection(pos).expand(b, -1, -1, -1)


class XcitXCA(nn.Module):
    """Cross-covariance attention: softmax over the per-head channel Gram
    matrix of L2-normalised q/k, learned per-head temperature."""

    def __init__(self, dim: int, num_heads: int):
        super().__init__()
        self.num_heads = num_heads
        self.temperature = nn.Parameter(torch.ones(num_heads, 1, 1))
        self.qkv = nn.Linear(dim, dim * 3, bias=True)
        self.proj = nn.Linear(dim, dim)

    def forward(self, x):
        b, n, c = x.shape
        qkv = self.qkv(x).reshape(b, n, 3, self.num_heads, c // self.num_heads)
        q, k, v = qkv.permute(2, 0, 3, 1, 4).unbind(0)
        q = F.normalize(q.transpose(-2, -1), dim=-1)
        k = F.normalize(k.transpose(-2, -1), dim=-1)
        v = v.transpose(-2, -1)
        attn = (q @ k.transpose(-2, -1)) * self.temperature
        attn = attn.softmax(dim=-1)
        return self.proj((attn @ v).permute(0, 3, 1, 2).reshape(b, n, c))


class XcitLPI(nn.Module):
    """depthwise 3x3 -> GELU -> BN -> depthwise 3x3 over the token grid."""

    def __init__(self, dim: int):
        super().__init__()
        self.conv1 = nn.Conv2d(dim, dim, 3, padding=1, groups=dim)
        self.act = nn.GELU()
        self.bn = nn.BatchNorm2d(dim)
        self.conv2 = nn.Conv2d(dim, dim, 3, padding=1, groups=dim)

    def forward(self, x, h, w):
        b, n, c = x.shape
        g = x.permute(0, 2, 1).reshape(b, c, h, w)
        g = self.conv2(self.bn(self.act(self.conv1(g))))
        return g.reshape(b, c, n).permute(0, 2, 1)


class XcitMlp(nn.Module):
    def __init__(self, dim: int, hidden: int):
        super().__init__()
        self.fc1 = nn.Linear(dim, hidden)
        self.act = nn.GELU()
        self.fc2 = nn.Linear(hidden, dim)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


class XcitBlock(nn.Module):
    """Trunk layer: LayerScale'd XCA / LPI / MLP residual branches."""

    def __init__(self, dim: int, num_heads: int, mlp_ratio: float, eta: float):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim, eps=1e-6)
        self.attn = XcitXCA(dim, num_heads)
        self.norm3 = nn.LayerNorm(dim, eps=1e-6)
        self.local_mp = XcitLPI(dim)
        self.norm2 = nn.LayerNorm(dim, eps=1e-6)
        self.mlp = XcitMlp(dim, int(dim * mlp_ratio))
        self.gamma1 = nn.Parameter(eta * torch.ones(dim))
        self.gamma2 = nn.Parameter(eta * torch.ones(dim))
        self.gamma3 = nn.Parameter(eta * torch.ones(dim))

    def forward(self, x, h, w):
        x = x + self.gamma1 * self.attn(self.norm1(x))
        x = x + self.gamma3 * self.local_mp(self.norm3(x), h, w)
        return x + self.gamma2 * self.mlp(self.norm2(x))


class XcitClassAttention(nn.Module):
    """CaiT class attention: only the CLS query attends; patch rows of the
    (normed) input pass through."""

    def __init__(self, dim: int, num_heads: int):
        super().__init__()
        self.num_heads = num_heads
        self.scale = (dim // num_heads) ** -0.5
        self.qkv = nn.Linear(dim, dim * 3, bias=True)
        self.proj = nn.Linear(dim, dim)

    def forward(self, x):
        b, n, c = x.shape
        qkv = self.qkv(x).reshape(b, n, 3, self.num_heads, c // self.num_heads)
        q, k, v = qkv.permute(2, 0, 3, 1, 4).unbind(0)
        attn = (q[:, :, :1] * k).sum(-1) * self.scale
        attn = attn.softmax(dim=-1)
        cls = (attn.unsqueeze(2) @ v).transpose(1, 2).reshape(b, 1, c)
        return torch.cat([self.proj(cls), x[:, 1:]], dim=1)


class XcitClassAttentionBlock(nn.Module):
    """tokens_norm=True variant (the hub models'): norm2 over all tokens;
    final residual adds post-norm tokens onto [gamma2*mlp(cls), patches]
    (the original's patch-token doubling, reproduced deliberately)."""

    def __init__(self, dim: int, num_heads: int, mlp_ratio: float, eta: float):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim, eps=1e-6)
        self.attn = XcitClassAttention(dim, num_heads)
        self.norm2 = nn.LayerNorm(dim, eps=1e-6)
        self.mlp = XcitMlp(dim, int(dim * mlp_ratio))
        self.gamma1 = nn.Parameter(eta * torch.ones(dim))
        self.gamma2 = nn.Parameter(eta * torch.ones(dim))

    def forward(self, x):
        x = x + self.gamma1 * self.attn(self.norm1(x))
        x = self.norm2(x)
        cls = self.gamma2 * self.mlp(x[:, :1])
        return x + torch.cat([cls, x[:, 1:]], dim=1)


class TorchXCiT(nn.Module):
    """facebookresearch/xcit trunk with hub state-dict naming (cls_token,
    pos_embeder, patch_embed.proj.*, blocks.*, cls_attn_blocks.*, norm);
    num_classes=0 semantics — returns the CLS embedding."""

    def __init__(self, patch_size: int = 16, embed_dim: int = 384,
                 depth: int = 12, num_heads: int = 8, mlp_ratio: float = 4.0,
                 cls_attn_layers: int = 2, eta: float = 1.0):
        super().__init__()
        self.patch_embed = XcitConvPatchEmbed(patch_size, embed_dim)
        self.pos_embeder = XcitPositionalEncodingFourier(embed_dim)
        self.cls_token = nn.Parameter(torch.zeros(1, 1, embed_dim))
        self.blocks = nn.ModuleList(
            [XcitBlock(embed_dim, num_heads, mlp_ratio, eta)
             for _ in range(depth)])
        self.cls_attn_blocks = nn.ModuleList(
            [XcitClassAttentionBlock(embed_dim, num_heads, mlp_ratio, eta)
             for _ in range(cls_attn_layers)])
        self.norm = nn.LayerNorm(embed_dim, eps=1e-6)

    def forward(self, x):
        b = x.shape[0]
        x, (hp, wp) = self.patch_embed(x)
        pos = self.pos_embeder(b, hp, wp).reshape(b, -1, x.shape[1])
        x = x + pos.permute(0, 2, 1)
        for blk in self.blocks:
            x = blk(x, hp, wp)
        x = torch.cat((self.cls_token.expand(b, -1, -1), x), dim=1)
        for blk in self.cls_attn_blocks:
            x = blk(x)
        return self.norm(x)[:, 0]


class TorchVGG16(nn.Module):
    """torchvision vgg16 features + first two classifier linears, exact
    Sequential index naming (features.0..28, classifier.0/.3)."""

    def __init__(self):
        super().__init__()
        plan = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
                512, 512, 512, "M", 512, 512, 512, "M")
        mods, in_ch = [], 3
        for item in plan:
            if item == "M":
                mods.append(nn.MaxPool2d(2, 2))
            else:
                mods += [nn.Conv2d(in_ch, int(item), 3, padding=1),
                         nn.ReLU(inplace=False)]
                in_ch = int(item)
        self.features = nn.Sequential(*mods)
        self.classifier = nn.Sequential(
            nn.Linear(512 * 7 * 7, 4096), nn.ReLU(), nn.Dropout(),
            nn.Linear(4096, 4096))

    def forward(self, x):
        """x in [0,1]; ImageNet-normalized inside (mirrors VGG16Features)."""
        mean = torch.tensor([0.485, 0.456, 0.406]).view(1, 3, 1, 1)
        std = torch.tensor([0.229, 0.224, 0.225]).view(1, 3, 1, 1)
        h = self.features((x - mean) / std)
        h = torch.flatten(h, 1)
        return F.relu(self.classifier(h))

#!/bin/bash
# One-window measurement bank: the tunneled TPU backend has been available
# only intermittently (down for the whole round-3 driver window), so when it
# IS up, capture every number the evidence chain needs in one pass:
#
#   1. bench.py            — 256px ladder + bs32/remat + 512px flash pair
#   2. tools/sweep_flash.py      — isolated-kernel table (SWEEP_FLASH.jsonl)
#   3. tools/check_flash_timing.py — independent scan-chain corroboration
#   4. tools/bench_sample.py     — config-3 sampling throughput
#
# Each stage gets its own timeout so a mid-run wedge can't eat the window.
# The bench progress trail is snapshotted to BENCH_PROGRESS_r${ROUND}${TAG}.json
# for committing (the raw artifact BASELINE.md cites).
#
# Usage: ROUND=4 TAG=a bash tools/measure_all.sh
#        ONLY=bench ... runs just the bench ladder (retry of the stage of
#        record without redundantly re-running already-banked stages)
set -u
cd "$(dirname "$0")/.."
ROUND="${ROUND:-4}"
TAG="${TAG:-a}"
ONLY="${ONLY:-}"
LOG="measure_all_r${ROUND}${TAG}.log"
# per-stage completion sentinels: tools/tpu_watch.sh narrows a retry to
# ONLY=bench only when every other stage banked its artifact on a prior pass
SENTINEL_DIR=".measure_done_r${ROUND}"
mkdir -p "$SENTINEL_DIR"

run() { # name timeout_s cmd...
  local name="$1" t="$2"; shift 2
  echo "=== $name (timeout ${t}s) $(date +%H:%M:%S) ===" | tee -a "$LOG"
  timeout "$t" "$@" >> "$LOG" 2>&1
  local rc=$?
  echo "=== $name rc=$rc $(date +%H:%M:%S) ===" | tee -a "$LOG"
  return "$rc"
}

# manual window: no driver kill looming, so give the ladder its full room
# (the in-repo defaults are sized for the driver's ~30min window)
run bench     5400 env BENCH_TIME_BUDGET_SECS=4800 BENCH_TIMEOUT_SECS=2400 python bench.py
BENCH_RC=$?
cp -f BENCH_PROGRESS.json "BENCH_PROGRESS_r${ROUND}${TAG}.json" 2>/dev/null
if [ "$ONLY" != "bench" ]; then
  run sweep     2400 python tools/sweep_flash.py           && touch "$SENTINEL_DIR/sweep"
  run crosscheck 1800 python tools/check_flash_timing.py   && touch "$SENTINEL_DIR/crosscheck"
  run sample    1800 python tools/bench_sample.py          && touch "$SENTINEL_DIR/sample"
  # trace is additive diagnostics (never the number of record — tracing
  # perturbs timing); a wedge here must not eat the banked results above
  run profile    900 python tools/capture_profile.py 3 16 "profile_trace_r${ROUND}${TAG}" \
                                                           && touch "$SENTINEL_DIR/profile"
fi

echo "=== done; snapshot: BENCH_PROGRESS_r${ROUND}${TAG}.json ===" | tee -a "$LOG"
echo "commit the snapshot + SWEEP_FLASH.jsonl + CHECK_FLASH_TIMING.jsonl +"
echo "BENCH_SAMPLE.jsonl and update BASELINE.md from them."
# the bench ladder is the stage of record: propagate its failure so callers
# (tools/tpu_watch.sh) can retry it — later stages bank their own artifacts
# regardless, so a retry should use ONLY=bench
exit "$BENCH_RC"

"""Serving-throughput bench: dynamic batching vs one-request-at-a-time.

Drives the real GenerationService in-process (no HTTP overhead in the
numbers): a sequential baseline completes each request before submitting the
next (max_batch=1 — the offline-loop serving model dcr-serve replaces), then
the batched run submits the same workload concurrently against max_batch=N
dynamic batching. Compilation is paid up front for both and excluded.

Writes BENCH_SERVE.json. Acceptance: batched throughput > sequential.

Usage: python tools/bench_serve.py
Env knobs: BENCH_SERVE_REQUESTS (default 32), BENCH_SERVE_BATCH (default 8),
BENCH_SERVE_STEPS (default 4), BENCH_SERVE_RES (default 16, tiny model).
"""

from __future__ import annotations

import json
import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT = Path(__file__).resolve().parent.parent / "BENCH_SERVE.json"


def _build_stack():
    import jax

    from dcr_tpu.core.config import MeshConfig, ModelConfig, TrainConfig
    from dcr_tpu.data.tokenizer import HashTokenizer
    from dcr_tpu.diffusion.trainer import build_models
    from dcr_tpu.parallel import mesh as pmesh
    from dcr_tpu.sampling.pipeline import GenerationStack

    tiny = ModelConfig.tiny()
    tcfg = TrainConfig(mixed_precision="no")
    tcfg.model = tiny
    models, params = build_models(tcfg, jax.random.key(0))
    tok = HashTokenizer(vocab_size=tiny.text_vocab_size,
                        model_max_length=tiny.text_max_length)
    return GenerationStack(models, params, tiny, tok,
                           pmesh.make_mesh(MeshConfig()))


def _service(stack, *, max_batch: int, steps: int, res: int):
    from dcr_tpu.core.config import ServeConfig
    from dcr_tpu.serve.worker import GenerationService

    cfg = ServeConfig(resolution=res, num_inference_steps=steps,
                      sampler="ddim", max_batch=max_batch, max_wait_ms=25.0,
                      queue_depth=256, seed=0)
    svc = GenerationService(cfg, stack)
    svc.start()
    return svc


def _prompts(n: int) -> list[str]:
    # 4 unique prompts cycled: a realistic repeat-heavy stream, so the
    # embedding cache participates in both legs identically
    uniq = ["a red square", "a blue circle", "a green triangle",
            "a yellow star"]
    return [uniq[i % len(uniq)] for i in range(n)]


def main() -> None:
    n_requests = int(os.environ.get("BENCH_SERVE_REQUESTS", "32"))
    max_batch = int(os.environ.get("BENCH_SERVE_BATCH", "8"))
    steps = int(os.environ.get("BENCH_SERVE_STEPS", "4"))
    res = int(os.environ.get("BENCH_SERVE_RES", "16"))

    cache_dir = Path(__file__).resolve().parent.parent / ".jax_cache"
    import jax

    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    print(f"bench_serve: {n_requests} requests, max_batch={max_batch}, "
          f"steps={steps}, res={res}, devices={len(jax.devices())}", flush=True)

    stack = _build_stack()
    prompts = _prompts(n_requests)
    result: dict = {"requests": n_requests, "max_batch": max_batch,
                    "steps": steps, "resolution": res, "sampler": "ddim",
                    "model": "tiny"}

    from dcr_tpu.serve.queue import Request

    def warmup(svc):
        # pay the compile outside the queue so timing AND latency telemetry
        # (p50/p99) reflect steady-state serving only
        svc.execute([Request(prompt="warmup", seed=0,
                             bucket=svc.default_bucket())])

    # -- sequential baseline: one request at a time, batch shape 1 ----------
    seq = _service(stack, max_batch=1, steps=steps, res=res)
    warmup(seq)
    t0 = time.perf_counter()
    for i, p in enumerate(prompts):
        seq.submit(p, seed=i).future.result(timeout=600)
    seq_s = time.perf_counter() - t0
    seq.stop(timeout=60)
    result["sequential"] = {
        "total_s": round(seq_s, 3),
        "requests_per_s": round(n_requests / seq_s, 3),
        "cache": seq.cache.stats(),
    }
    print("sequential:", json.dumps(result["sequential"]), flush=True)

    # -- batched: same workload submitted concurrently ----------------------
    bat = _service(stack, max_batch=max_batch, steps=steps, res=res)
    warmup(bat)
    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=min(32, n_requests)) as ex:
        futs = list(ex.map(lambda a: bat.submit(a[1], seed=a[0]).future,
                           enumerate(prompts)))
        for f in futs:
            f.result(timeout=600)
    bat_s = time.perf_counter() - t0
    snap = bat.metrics.snapshot()
    bat.stop(timeout=60)
    result["batched"] = {
        "total_s": round(bat_s, 3),
        "requests_per_s": round(n_requests / bat_s, 3),
        "batch_occupancy_avg": round(snap["batch_occupancy_avg"], 3),
        "batch_occupancy_max": snap["batch_occupancy_max"],
        "latency_ms": snap["latency_ms"],
        "cache": bat.cache.stats(),
    }
    result["speedup"] = round(seq_s / bat_s, 3)
    print("batched:", json.dumps(result["batched"]), flush=True)
    print(f"speedup: {result['speedup']}x", flush=True)

    OUT.write_text(json.dumps(result, indent=2) + "\n")
    print(f"wrote {OUT}", flush=True)


if __name__ == "__main__":
    main()

"""Serving-throughput bench: dynamic batching vs one-request-at-a-time —
plus a ``--chaos`` mode that proves availability under worker churn.

Default mode drives the real GenerationService in-process (no HTTP overhead
in the numbers): a sequential baseline completes each request before
submitting the next (max_batch=1 — the offline-loop serving model dcr-serve
replaces), then the batched run submits the same workload concurrently
against max_batch=N dynamic batching. Compilation is paid up front for both
and excluded. Writes BENCH_SERVE.json. Acceptance: batched > sequential.

``--chaos`` drives a real fleet (in-process FleetSupervisor, real worker
SUBPROCESSES spawned through ``dcr_tpu.cli.serve``): the same fixed request
load runs twice — once uninjected (baseline p99), once while a kill loop
SIGKILLs an alive worker every K seconds (targets found via the fleet lease
directory). Writes BENCH_SERVE_CHAOS.json with availability %, the
dropped-accepted-request count replayed from the durable journal (MUST be
0 — the process exits 1 otherwise), p99 with/without churn, and whether
every churn-run response was bit-identical to the uninjected run (it must
be: every image is a pure function of (ckpt, prompt, seed, bucket)).

Usage: python tools/bench_serve.py [--chaos]
Env knobs (default mode): BENCH_SERVE_REQUESTS (default 32),
BENCH_SERVE_BATCH (default 8), BENCH_SERVE_STEPS (default 4),
BENCH_SERVE_RES (default 16, tiny model).
Env knobs (--chaos): BENCH_SERVE_CHAOS_REQUESTS (default 24),
BENCH_SERVE_CHAOS_WORKERS (default 2), BENCH_SERVE_CHAOS_KILL_EVERY_S
(default 10), BENCH_SERVE_STEPS / BENCH_SERVE_RES as above.
"""

from __future__ import annotations

import json
import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT = Path(__file__).resolve().parent.parent / "BENCH_SERVE.json"
OUT_CHAOS = Path(__file__).resolve().parent.parent / "BENCH_SERVE_CHAOS.json"


def _build_stack():
    import jax

    from dcr_tpu.core.config import MeshConfig, ModelConfig, TrainConfig
    from dcr_tpu.data.tokenizer import HashTokenizer
    from dcr_tpu.diffusion.trainer import build_models
    from dcr_tpu.parallel import mesh as pmesh
    from dcr_tpu.sampling.pipeline import GenerationStack

    tiny = ModelConfig.tiny()
    tcfg = TrainConfig(mixed_precision="no")
    tcfg.model = tiny
    models, params = build_models(tcfg, jax.random.key(0))
    tok = HashTokenizer(vocab_size=tiny.text_vocab_size,
                        model_max_length=tiny.text_max_length)
    return GenerationStack(models, params, tiny, tok,
                           pmesh.make_mesh(MeshConfig()))


def _service(stack, *, max_batch: int, steps: int, res: int):
    from dcr_tpu.core.config import ServeConfig
    from dcr_tpu.serve.worker import GenerationService

    cfg = ServeConfig(resolution=res, num_inference_steps=steps,
                      sampler="ddim", max_batch=max_batch, max_wait_ms=25.0,
                      queue_depth=256, seed=0)
    svc = GenerationService(cfg, stack)
    svc.start()
    return svc


def _prompts(n: int) -> list[str]:
    # 4 unique prompts cycled: a realistic repeat-heavy stream, so the
    # embedding cache participates in both legs identically
    uniq = ["a red square", "a blue circle", "a green triangle",
            "a yellow star"]
    return [uniq[i % len(uniq)] for i in range(n)]


def main() -> None:
    n_requests = int(os.environ.get("BENCH_SERVE_REQUESTS", "32"))
    max_batch = int(os.environ.get("BENCH_SERVE_BATCH", "8"))
    steps = int(os.environ.get("BENCH_SERVE_STEPS", "4"))
    res = int(os.environ.get("BENCH_SERVE_RES", "16"))

    cache_dir = Path(__file__).resolve().parent.parent / ".jax_cache"
    import jax

    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    print(f"bench_serve: {n_requests} requests, max_batch={max_batch}, "
          f"steps={steps}, res={res}, devices={len(jax.devices())}", flush=True)

    stack = _build_stack()
    prompts = _prompts(n_requests)
    result: dict = {"requests": n_requests, "max_batch": max_batch,
                    "steps": steps, "resolution": res, "sampler": "ddim",
                    "model": "tiny"}

    from dcr_tpu.serve.queue import Request

    def warmup(svc):
        # pay the compile outside the queue so timing AND latency telemetry
        # (p50/p99) reflect steady-state serving only
        svc.execute([Request(prompt="warmup", seed=0,
                             bucket=svc.default_bucket())])

    # -- sequential baseline: one request at a time, batch shape 1 ----------
    seq = _service(stack, max_batch=1, steps=steps, res=res)
    warmup(seq)
    t0 = time.perf_counter()
    for i, p in enumerate(prompts):
        seq.submit(p, seed=i).future.result(timeout=600)
    seq_s = time.perf_counter() - t0
    seq.stop(timeout=60)
    result["sequential"] = {
        "total_s": round(seq_s, 3),
        "requests_per_s": round(n_requests / seq_s, 3),
        "cache": seq.cache.stats(),
    }
    print("sequential:", json.dumps(result["sequential"]), flush=True)

    # -- batched: same workload submitted concurrently ----------------------
    bat = _service(stack, max_batch=max_batch, steps=steps, res=res)
    warmup(bat)
    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=min(32, n_requests)) as ex:
        futs = list(ex.map(lambda a: bat.submit(a[1], seed=a[0]).future,
                           enumerate(prompts)))
        for f in futs:
            f.result(timeout=600)
    bat_s = time.perf_counter() - t0
    snap = bat.metrics.snapshot()
    bat.stop(timeout=60)
    result["batched"] = {
        "total_s": round(bat_s, 3),
        "requests_per_s": round(n_requests / bat_s, 3),
        "batch_occupancy_avg": round(snap["batch_occupancy_avg"], 3),
        "batch_occupancy_max": snap["batch_occupancy_max"],
        "latency_ms": snap["latency_ms"],
        "cache": bat.cache.stats(),
    }
    result["speedup"] = round(seq_s / bat_s, 3)
    print("batched:", json.dumps(result["batched"]), flush=True)
    print(f"speedup: {result['speedup']}x", flush=True)

    OUT.write_text(json.dumps(result, indent=2) + "\n")
    print(f"wrote {OUT}", flush=True)


# ---------------------------------------------------------------------------
# --chaos: availability under worker churn (real fleet, real SIGKILLs)
# ---------------------------------------------------------------------------

def _export_tiny_ckpt(dirpath: Path) -> Path:
    """HF-layout tiny checkpoint the spawned worker subprocesses load —
    the exact exporter the serve/fleet tests use (one source of truth for
    the tiny model's layout; the repo root is already on sys.path)."""
    from tests.test_serve import _export_tiny_ckpt as export

    return export(dirpath)


def _chaos_config(ckpt: Path, fleet_dir: Path, *, workers: int, steps: int,
                  res: int):
    from dcr_tpu.core.config import FleetConfig, ServeConfig

    # churn-friendly knobs: quick death detection (tight lease), quick
    # respawn (short backoff, high budget — the bench wants churn, not
    # retirement), and enough dispatch attempts that a request surviving
    # several kills still completes rather than 500s
    return ServeConfig(
        model_path=str(ckpt), resolution=res, num_inference_steps=steps,
        sampler="ddim", max_batch=4, max_wait_ms=50.0, queue_depth=512,
        request_timeout_s=600.0, seed=0,
        fleet=FleetConfig(workers=workers, dir=str(fleet_dir),
                          heartbeat_s=0.5, lease_s=3.0,
                          dispatch_timeout_s=300.0, spawn_timeout_s=300.0,
                          max_attempts=8, respawn_max=50,
                          respawn_base_delay_s=0.5, respawn_max_delay_s=2.0))


def _kill_loop(paths, workers: int, every_s: float, stop, kills: list) -> None:
    """SIGKILL one alive worker every ``every_s`` seconds, targets found the
    way any out-of-process chaos tool would: the lease directory. The victim
    is the LONGEST-ALIVE worker (oldest ``started_at``): killing the first
    alive index would keep executing a fresh respawn the moment it joined,
    which models a crash-looping binary rather than churn — under that
    regime nothing can complete anywhere and "availability" measures the
    kill cadence, not the fleet."""
    import signal

    from dcr_tpu.serve.fleet import read_lease

    # first blood comes fast: with a warm compile cache the whole workload
    # can finish inside one full interval, and a churn run with zero kills
    # proves nothing (chaos_main fails it)
    delay = min(every_s, 1.5)
    while not stop.wait(delay):
        delay = every_s
        alive = [l for l in (read_lease(paths, i) for i in range(workers))
                 if l is not None and not l.expired()]
        for lease in sorted(alive, key=lambda l: l.started_at):
            try:
                os.kill(lease.pid, signal.SIGKILL)
            except OSError:
                continue             # already gone — pick the next victim
            kills.append({"t": time.time(), "worker": lease.index,
                          "pid": lease.pid})
            print(f"chaos: SIGKILL worker {lease.index} (pid {lease.pid})",
                  flush=True)
            break


def _run_fleet_workload(cfg, jobs, *, kill_every_s=None) -> dict:
    """One fleet run: submit every (prompt, seed) job concurrently, return
    response docs keyed by job plus availability/latency/journal numbers."""
    import threading

    from dcr_tpu.serve.fleet import RequestJournal
    from dcr_tpu.serve.supervisor import FleetSupervisor

    sup = FleetSupervisor(cfg)
    sup.start()
    deadline = time.monotonic() + cfg.fleet.spawn_timeout_s
    while sup.health() != "ok":
        if time.monotonic() > deadline:
            raise RuntimeError(
                f"fleet did not come up: health={sup.health()!r} "
                f"status={sup.status()!r}")
        time.sleep(0.25)

    stop_kills = threading.Event()
    kills: list = []
    killer = None
    if kill_every_s:
        killer = threading.Thread(
            target=_kill_loop,
            args=(sup.paths, cfg.fleet.workers, kill_every_s, stop_kills,
                  kills),
            daemon=True, name="chaos-killer")
        killer.start()

    t0 = time.perf_counter()
    accepted, rejected, completed, failed = [], 0, {}, {}
    for prompt, seed in jobs:
        try:
            accepted.append(((prompt, seed), sup.submit(prompt, seed=seed)))
        except Exception as e:
            rejected += 1
            print(f"chaos: rejected ({prompt!r}, {seed}): {e!r}", flush=True)
    for job, req in accepted:
        try:
            completed[job] = req.future.result(
                timeout=cfg.request_timeout_s)
        except Exception as e:
            failed[f"{job[0]}#{job[1]}"] = repr(e)   # str key: JSON-safe
    total_s = time.perf_counter() - t0

    stop_kills.set()
    if killer is not None:
        killer.join(timeout=2 * (kill_every_s or 1.0))
    sup.begin_drain()
    sup.join_drained(cfg.request_timeout_s)
    sup.shutdown()
    replay = RequestJournal.replay(sup.paths.journal)

    pct = sup.metrics.latency.percentiles((50, 99))
    n_acc = len(accepted)
    return {
        "attempted": len(jobs),
        "accepted": n_acc,
        "rejected": rejected,
        "completed": len(completed),
        "failed": failed,
        "availability_pct": round(100.0 * len(completed) / max(1, n_acc), 3),
        "total_s": round(total_s, 3),
        "requests_per_s": round(len(completed) / total_s, 3),
        "latency_ms": {k: round(v * 1000.0, 3) for k, v in pct.items()},
        "kills": kills,
        "journal": replay["counts"],
        "results": completed,
    }


def _response_key(doc: dict) -> tuple:
    # the content that must be bit-identical across runs/workers; id, worker,
    # cache_hit, and latency legitimately differ
    return (doc.get("image_png_b64"), doc.get("width"), doc.get("height"))


def chaos_main() -> None:
    import tempfile

    n_requests = int(os.environ.get("BENCH_SERVE_CHAOS_REQUESTS", "24"))
    workers = int(os.environ.get("BENCH_SERVE_CHAOS_WORKERS", "2"))
    # the interval must leave a worker's survivors room to actually finish
    # batches between kills: on this CPU a respawned worker takes ~10s to
    # rejoin and a batch runs for several seconds, so sub-5s cadences degrade
    # into a crash loop where nothing completes anywhere
    kill_every_s = float(os.environ.get("BENCH_SERVE_CHAOS_KILL_EVERY_S",
                                        "10"))
    steps = int(os.environ.get("BENCH_SERVE_STEPS", "4"))
    res = int(os.environ.get("BENCH_SERVE_RES", "16"))

    # share one persistent XLA compile cache across worker (re)spawns —
    # respawned workers then reload in seconds instead of recompiling
    repo = Path(__file__).resolve().parent.parent
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                          str(repo / "tests" / ".jax_cache_cpu"))
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1.0")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

    print(f"bench_serve --chaos: {n_requests} requests, {workers} workers, "
          f"kill every {kill_every_s}s, steps={steps}, res={res}", flush=True)
    jobs = [(p, i) for i, p in enumerate(_prompts(n_requests))]

    with tempfile.TemporaryDirectory(prefix="dcr-chaos-") as td:
        tmp = Path(td)
        ckpt = _export_tiny_ckpt(tmp)
        baseline = _run_fleet_workload(
            _chaos_config(ckpt, tmp / "fleet_baseline", workers=workers,
                          steps=steps, res=res), jobs)
        print("baseline:", json.dumps({k: v for k, v in baseline.items()
                                       if k != "results"}), flush=True)
        churn = _run_fleet_workload(
            _chaos_config(ckpt, tmp / "fleet_churn", workers=workers,
                          steps=steps, res=res), jobs,
            kill_every_s=kill_every_s)
        print("churn:", json.dumps({k: v for k, v in churn.items()
                                    if k != "results"}), flush=True)

    mismatched = [job for job in baseline["results"]
                  if job in churn["results"]
                  and _response_key(baseline["results"][job])
                  != _response_key(churn["results"][job])]
    result = {
        "requests": n_requests, "workers": workers,
        "kill_every_s": kill_every_s, "steps": steps, "resolution": res,
        "sampler": "ddim", "model": "tiny",
        "baseline": {k: v for k, v in baseline.items() if k != "results"},
        "churn": {k: v for k, v in churn.items() if k != "results"},
        "kills": len(churn["kills"]),
        "dropped_accepted_requests": churn["journal"]["dropped"],
        "requeued": churn["journal"]["requeued_total"],
        "availability_pct": churn["availability_pct"],
        "p99_ms_baseline": baseline["latency_ms"].get("p99"),
        "p99_ms_churn": churn["latency_ms"].get("p99"),
        "bit_identical_responses": not mismatched,
        "mismatched_jobs": [list(j) for j in mismatched],
    }
    OUT_CHAOS.write_text(json.dumps(result, indent=2) + "\n")
    print(f"wrote {OUT_CHAOS}", flush=True)

    problems = []
    if churn["journal"]["dropped"] != 0:
        problems.append(
            f"dropped accepted requests: {churn['journal']['dropped']}")
    if churn["availability_pct"] < 100.0:
        problems.append(f"availability {churn['availability_pct']}% "
                        f"(failed: {churn['failed']})")
    if mismatched:
        problems.append(f"{len(mismatched)} response(s) not bit-identical "
                        f"to the uninjected run")
    if not churn["kills"]:
        problems.append("kill loop never fired — the churn run proved "
                        "nothing (workload too short for the cadence?)")
    if problems:
        print("CHAOS FAIL: " + "; ".join(problems), flush=True)
        raise SystemExit(1)
    print(f"CHAOS OK: {len(churn['kills'])} kill(s), "
          f"{churn['journal']['requeued_total']} requeue(s), 0 drops, "
          f"bit-identical responses", flush=True)


if __name__ == "__main__":
    if "--chaos" in sys.argv[1:]:
        chaos_main()
    else:
        main()

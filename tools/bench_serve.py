"""Serving-throughput bench: dynamic batching vs one-request-at-a-time —
plus a ``--chaos`` mode that proves availability under worker churn.

Default mode drives the real GenerationService in-process (no HTTP overhead
in the numbers): a sequential baseline completes each request before
submitting the next (max_batch=1 — the offline-loop serving model dcr-serve
replaces), then the batched run submits the same workload concurrently
against max_batch=N dynamic batching. Compilation is paid up front for both
and excluded. Writes BENCH_SERVE.json. Acceptance: batched > sequential.

``--chaos`` drives a real fleet (in-process FleetSupervisor, real worker
SUBPROCESSES spawned through ``dcr_tpu.cli.serve``): the same fixed request
load runs twice — once uninjected (baseline p99), once while a kill loop
SIGKILLs a READY worker every K seconds (targets found via the fleet lease
directory). Both runs share one dcr-warm persistent executable cache: the
baseline populates it cold, so its boot-to-ready times are the COLD numbers,
while every churn boot and respawn must come up WARM. Writes
BENCH_SERVE_CHAOS.json with availability %, the dropped-accepted-request
count replayed from the durable journal (MUST be 0 — the process exits 1
otherwise), p99 with/without churn, whether every churn-run response was
bit-identical to the uninjected run (it must be: every image is a pure
function of (ckpt, prompt, seed, bucket)), per-kill crash-to-ready and
crash-to-first-completion times (cold vs warm cache), and the trace-verified
compile count per process incarnation — a warm respawn that recompiles ANY
bucket fails the bench.

``--risk`` banks the cost of dcr-watch online copy-risk scoring: the same
batched workload runs with scoring off and with a synthetic train-embedding
index loaded (SSCD forward + top-k matmul after every device step), and
BENCH_RISK.json records throughput for both plus the overhead percentage.
Acceptance: overhead < 15% of batched throughput (the process exits 1
otherwise). The default knobs use more denoising steps than the throughput
bench — scoring cost is per-IMAGE while generation cost scales with steps,
so a 2-step tiny-model run would measure a regime no real deployment is in
(SD-2.1 at 50 steps amortizes SSCD to well under 1%).

``--fast`` banks the dcr-fast serving win next to the chaos/risk legs: the
same batched workload runs once on the dense default bucket and once with
the fast plan on (``FastSampleConfig`` defaults: reuse_ratio 0.5, order 2),
and BENCH_SERVE_FAST.json records throughput for both, the speedup, and
the per-trajectory UNet-call reduction. The fidelity side of the same
operating point is gated separately by tools/bench_fastsample.py — this
leg is the wall-clock half of that story.

Usage: python tools/bench_serve.py [--chaos|--risk|--fast]
Env knobs (default mode): BENCH_SERVE_REQUESTS (default 32),
BENCH_SERVE_BATCH (default 8), BENCH_SERVE_STEPS (default 4),
BENCH_SERVE_RES (default 16, tiny model).
Env knobs (--chaos): BENCH_SERVE_CHAOS_REQUESTS (default 24),
BENCH_SERVE_CHAOS_WORKERS (default 2), BENCH_SERVE_CHAOS_KILL_EVERY_S
(default 10), BENCH_SERVE_STEPS / BENCH_SERVE_RES as above.
Env knobs (--risk): BENCH_RISK_REQUESTS (default 48), BENCH_RISK_STEPS
(default 24), BENCH_RISK_IMAGE_SIZE (default 32), BENCH_RISK_INDEX_N
(default 4096), BENCH_SERVE_BATCH / BENCH_SERVE_RES as above.
Env knobs (--fast): BENCH_FAST_SERVE_REQUESTS (default 32),
BENCH_FAST_SERVE_STEPS (default 32 — the UNet-dominated regime fast
sampling targets), BENCH_FAST_REPS (median-of-N workload passes per leg,
default 3), BENCH_SERVE_BATCH / BENCH_SERVE_RES as above.
"""

from __future__ import annotations

import json
import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT = Path(__file__).resolve().parent.parent / "BENCH_SERVE.json"
OUT_CHAOS = Path(__file__).resolve().parent.parent / "BENCH_SERVE_CHAOS.json"
OUT_RISK = Path(__file__).resolve().parent.parent / "BENCH_RISK.json"
OUT_FAST = Path(__file__).resolve().parent.parent / "BENCH_SERVE_FAST.json"


def _build_stack():
    import jax

    from dcr_tpu.core.config import MeshConfig, ModelConfig, TrainConfig
    from dcr_tpu.data.tokenizer import HashTokenizer
    from dcr_tpu.diffusion.trainer import build_models
    from dcr_tpu.parallel import mesh as pmesh
    from dcr_tpu.sampling.pipeline import GenerationStack

    tiny = ModelConfig.tiny()
    tcfg = TrainConfig(mixed_precision="no")
    tcfg.model = tiny
    models, params = build_models(tcfg, jax.random.key(0))
    tok = HashTokenizer(vocab_size=tiny.text_vocab_size,
                        model_max_length=tiny.text_max_length)
    return GenerationStack(models, params, tiny, tok,
                           pmesh.make_mesh(MeshConfig()))


def _service(stack, *, max_batch: int, steps: int, res: int, risk=None,
             fast=None):
    from dcr_tpu.core.config import FastSampleConfig, RiskConfig, ServeConfig
    from dcr_tpu.serve.worker import GenerationService

    cfg = ServeConfig(resolution=res, num_inference_steps=steps,
                      sampler="ddim", max_batch=max_batch, max_wait_ms=25.0,
                      queue_depth=256, seed=0,
                      risk=risk if risk is not None else RiskConfig(),
                      fast=fast if fast is not None else FastSampleConfig())
    svc = GenerationService(cfg, stack)
    svc.start()
    return svc


def _prompts(n: int) -> list[str]:
    # 4 unique prompts cycled: a realistic repeat-heavy stream, so the
    # embedding cache participates in both legs identically
    uniq = ["a red square", "a blue circle", "a green triangle",
            "a yellow star"]
    return [uniq[i % len(uniq)] for i in range(n)]


def _peak_bytes():
    """dcr-hbm: peak device bytes so far (None on stats-less backends) —
    the HBM number every banked leg carries. Monotonic per process (no
    XLA peak reset): legs sharing one process bank the high-water mark as
    of THEIR end, so compare consecutive legs' steps, not absolute
    values."""
    from dcr_tpu.obs.memwatch import peak_bytes

    return peak_bytes()


def main() -> None:
    n_requests = int(os.environ.get("BENCH_SERVE_REQUESTS", "32"))
    max_batch = int(os.environ.get("BENCH_SERVE_BATCH", "8"))
    steps = int(os.environ.get("BENCH_SERVE_STEPS", "4"))
    res = int(os.environ.get("BENCH_SERVE_RES", "16"))

    cache_dir = Path(__file__).resolve().parent.parent / ".jax_cache"
    import jax

    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    print(f"bench_serve: {n_requests} requests, max_batch={max_batch}, "
          f"steps={steps}, res={res}, devices={len(jax.devices())}", flush=True)

    stack = _build_stack()
    prompts = _prompts(n_requests)
    result: dict = {"requests": n_requests, "max_batch": max_batch,
                    "steps": steps, "resolution": res, "sampler": "ddim",
                    "model": "tiny"}

    from dcr_tpu.serve.queue import Request

    def warmup(svc):
        # pay the compile outside the queue so timing AND latency telemetry
        # (p50/p99) reflect steady-state serving only
        svc.execute([Request(prompt="warmup", seed=0,
                             bucket=svc.default_bucket())])

    # -- sequential baseline: one request at a time, batch shape 1 ----------
    seq = _service(stack, max_batch=1, steps=steps, res=res)
    warmup(seq)
    t0 = time.perf_counter()
    for i, p in enumerate(prompts):
        seq.submit(p, seed=i).future.result(timeout=600)
    seq_s = time.perf_counter() - t0
    seq.stop(timeout=60)
    result["sequential"] = {
        "total_s": round(seq_s, 3),
        "requests_per_s": round(n_requests / seq_s, 3),
        "cache": seq.cache.stats(),
        # dcr-hbm: peak device bytes after the leg (null without backend
        # memory stats — XLA:CPU)
        "hbm_peak_bytes": _peak_bytes(),
    }
    print("sequential:", json.dumps(result["sequential"]), flush=True)

    # -- batched: same workload submitted concurrently ----------------------
    bat = _service(stack, max_batch=max_batch, steps=steps, res=res)
    warmup(bat)
    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=min(32, n_requests)) as ex:
        futs = list(ex.map(lambda a: bat.submit(a[1], seed=a[0]).future,
                           enumerate(prompts)))
        for f in futs:
            f.result(timeout=600)
    bat_s = time.perf_counter() - t0
    snap = bat.metrics.snapshot()
    bat.stop(timeout=60)
    result["batched"] = {
        "total_s": round(bat_s, 3),
        "requests_per_s": round(n_requests / bat_s, 3),
        "batch_occupancy_avg": round(snap["batch_occupancy_avg"], 3),
        "batch_occupancy_max": snap["batch_occupancy_max"],
        "latency_ms": snap["latency_ms"],
        "cache": bat.cache.stats(),
        "hbm_peak_bytes": _peak_bytes(),
    }
    result["speedup"] = round(seq_s / bat_s, 3)
    print("batched:", json.dumps(result["batched"]), flush=True)
    print(f"speedup: {result['speedup']}x", flush=True)

    OUT.write_text(json.dumps(result, indent=2) + "\n")
    print(f"wrote {OUT}", flush=True)


# ---------------------------------------------------------------------------
# --chaos: availability under worker churn (real fleet, real SIGKILLs)
# ---------------------------------------------------------------------------

def _export_tiny_ckpt(dirpath: Path) -> Path:
    """HF-layout tiny checkpoint the spawned worker subprocesses load —
    the exact exporter the serve/fleet tests use (one source of truth for
    the tiny model's layout; the repo root is already on sys.path)."""
    from tests.test_serve import _export_tiny_ckpt as export

    return export(dirpath)


def _chaos_config(ckpt: Path, fleet_dir: Path, *, workers: int, steps: int,
                  res: int, warm_dir: Path):
    from dcr_tpu.core.config import (FleetConfig, ServeConfig,
                                     WarmCacheConfig)

    # churn-friendly knobs: quick death detection (tight lease), quick
    # respawn (short backoff, high budget — the bench wants churn, not
    # retirement), and enough dispatch attempts that a request surviving
    # several kills still completes rather than 500s. The shared warm_dir is
    # the persistent executable cache: the baseline run populates it cold,
    # and every churn (re)spawn must reach ready from it with ZERO compiles.
    return ServeConfig(
        model_path=str(ckpt), resolution=res, num_inference_steps=steps,
        sampler="ddim", max_batch=4, max_wait_ms=50.0, queue_depth=512,
        request_timeout_s=600.0, seed=0,
        warm=WarmCacheConfig(dir=str(warm_dir)),
        fleet=FleetConfig(workers=workers, dir=str(fleet_dir),
                          heartbeat_s=0.5, lease_s=3.0,
                          dispatch_timeout_s=300.0, spawn_timeout_s=300.0,
                          max_attempts=8, respawn_max=50,
                          respawn_base_delay_s=0.5, respawn_max_delay_s=2.0))


def _kill_loop(paths, workers: int, every_s: float, stop, kills: list) -> None:
    """SIGKILL one READY worker every ``every_s`` seconds, targets found the
    way any out-of-process chaos tool would: the lease directory. The victim
    is the LONGEST-ALIVE worker (oldest ``started_at``): killing the first
    alive index would keep executing a fresh respawn the moment it joined,
    which models a crash-looping binary rather than churn — under that
    regime nothing can complete anywhere and "availability" measures the
    kill cadence, not the fleet.

    First blood lands deterministically MID-FLIGHT: the loop watches the
    durable journal for the first ``dispatch`` record before striking. With
    the dcr-warm executable cache a fully warm fleet can finish the entire
    workload in well under a second — any fixed first-kill delay races the
    workload, and a churn run with zero kills proves nothing (chaos_main
    fails it)."""
    import signal

    from dcr_tpu.serve.fleet import read_lease

    def ready_leases():
        # only READY leases are victims: killing a still-warming spawn would
        # measure spawn time, not crash-to-ready recovery
        return [l for l in (read_lease(paths, i) for i in range(workers))
                if l is not None and not l.expired() and l.ready]

    def dispatched() -> bool:
        # parsed, not substring-matched: the trigger must not couple to
        # json.dumps separator defaults (the journal is tiny this early —
        # admission has barely begun)
        try:
            lines = paths.journal.read_text().splitlines()
        except OSError:
            return False
        for line in lines:
            try:
                if line.strip() and json.loads(line).get("op") == "dispatch":
                    return True
            except ValueError:
                continue
        return False

    while not stop.wait(0.02):
        if dispatched():
            break
    while not stop.wait(0.02 if not kills else every_s):
        for lease in sorted(ready_leases(), key=lambda l: l.started_at):
            try:
                os.kill(lease.pid, signal.SIGKILL)
            except OSError:
                continue             # already gone — pick the next victim
            kills.append({"t": time.time(), "worker": lease.index,
                          "pid": lease.pid})
            print(f"chaos: SIGKILL worker {lease.index} (pid {lease.pid})",
                  flush=True)
            break


def _watch_leases(paths, workers: int, stop, events: list) -> None:
    """Record every (worker, pid, ready) lease transition with a wall-clock
    stamp — the out-of-process observer the time-to-ready numbers come from
    (the same files any ops tooling would watch)."""
    from dcr_tpu.serve.fleet import read_lease

    seen: dict = {}
    while not stop.wait(0.05):
        for i in range(workers):
            lease = read_lease(paths, i)
            if lease is None:
                continue
            cur = (lease.pid, bool(lease.ready))
            if seen.get(i) != cur:
                seen[i] = cur
                events.append({"t": time.time(), "worker": i,
                               "pid": lease.pid, "ready": bool(lease.ready)})


def _journal_ack_times(journal_path) -> list:
    """[(t, worker)] for every ack in the durable journal — the
    time-to-first-completion anchor after a respawn."""
    acks = []
    for line in Path(journal_path).read_text().splitlines():
        if not line.strip():
            continue
        rec = json.loads(line)
        if rec.get("op") == "ack":
            acks.append((rec["t"], rec.get("worker", -1)))
    return sorted(acks)


def _respawn_metrics(kills: list, lease_events: list, acks: list) -> list:
    """Per-kill crash-to-ready and crash-to-first-completion times, from the
    lease transitions and the journal alone."""
    out = []
    for k in kills:
        w, t_kill = k["worker"], k["t"]
        ready = next((e for e in lease_events
                      if e["worker"] == w and e["ready"] and e["t"] > t_kill
                      and e["pid"] != k["pid"]), None)
        row = {"worker": w,
               "time_to_ready_s": (round(ready["t"] - t_kill, 3)
                                   if ready else None),
               "time_to_first_completion_s": None,
               "respawn_pid": ready["pid"] if ready else None}
        if ready is not None:
            ack = next((t for t, aw in acks
                        if aw == w and t > ready["t"]), None)
            if ack is not None:
                row["time_to_first_completion_s"] = round(ack - t_kill, 3)
        out.append(row)
    return out


def _compiles_by_pid(fleet_dir: Path) -> dict:
    """XLA compiles per process incarnation across the fleet's trace files
    (tools/trace_report's recompile-budget counter)."""
    from tools import trace_report as TR

    records, errors, _ = TR.load_fleet([Path(fleet_dir)], TR.load_schema())
    if errors:
        print(f"chaos: {len(errors)} invalid trace record(s) under "
              f"{fleet_dir} (first: {errors[0]})", flush=True)
    return TR.compiles_per_incarnation(records)


def _run_fleet_workload(cfg, jobs, *, kill_every_s=None) -> dict:
    """One fleet run: submit every (prompt, seed) job concurrently, return
    response docs keyed by job plus availability/latency/journal numbers."""
    import threading

    from dcr_tpu.serve.fleet import RequestJournal
    from dcr_tpu.serve.supervisor import FleetSupervisor

    t_start = time.time()
    sup = FleetSupervisor(cfg)
    sup.start()
    stop_watch = threading.Event()
    lease_events: list = []
    watcher = threading.Thread(
        target=_watch_leases,
        args=(sup.paths, cfg.fleet.workers, stop_watch, lease_events),
        daemon=True, name="chaos-lease-watch")
    watcher.start()
    deadline = time.monotonic() + cfg.fleet.spawn_timeout_s
    while sup.health() != "ok" or sup.status()["workers_alive"] == 0:
        if time.monotonic() > deadline:
            raise RuntimeError(
                f"fleet did not come up: health={sup.health()!r} "
                f"status={sup.status()!r}")
        time.sleep(0.25)

    stop_kills = threading.Event()
    kills: list = []
    killer = None
    if kill_every_s:
        killer = threading.Thread(
            target=_kill_loop,
            args=(sup.paths, cfg.fleet.workers, kill_every_s, stop_kills,
                  kills),
            daemon=True, name="chaos-killer")
        killer.start()

    t0 = time.perf_counter()
    accepted, rejected, completed, failed = [], 0, {}, {}
    for prompt, seed in jobs:
        try:
            accepted.append(((prompt, seed), sup.submit(prompt, seed=seed)))
        except Exception as e:
            rejected += 1
            print(f"chaos: rejected ({prompt!r}, {seed}): {e!r}", flush=True)
    for job, req in accepted:
        try:
            completed[job] = req.future.result(
                timeout=cfg.request_timeout_s)
        except Exception as e:
            failed[f"{job[0]}#{job[1]}"] = repr(e)   # str key: JSON-safe
    total_s = time.perf_counter() - t0
    # latency percentiles snapshot BEFORE the post-respawn probe phase: the
    # banked p50/p99 must describe the measured workload only, or the churn
    # run's tail would be diluted by probes the baseline never sends
    pct = sup.metrics.latency.percentiles((50, 99))

    stop_kills.set()
    if killer is not None:
        killer.join(timeout=2 * (kill_every_s or 1.0))
    # observe crash-to-ready recovery BEFORE draining: a short workload can
    # finish on survivors while the victim is still respawning — without
    # this wait the bench would bank nulls instead of time-to-ready. Then a
    # probe workload gives the respawned worker completions, so
    # time-to-first-completion is measurable too.
    probe_done = 0
    if kills:
        deadline = time.monotonic() + 90.0
        def respawn_ready(k):
            return any(e["worker"] == k["worker"] and e["ready"]
                       and e["pid"] != k["pid"] and e["t"] > k["t"]
                       for e in lease_events)
        while (not all(respawn_ready(k) for k in kills)
               and time.monotonic() < deadline):
            time.sleep(0.1)
        probe_reqs = []
        for i in range(2 * cfg.fleet.workers * cfg.max_batch):
            try:
                probe_reqs.append(sup.submit("post-respawn probe",
                                             seed=100_000 + i))
            except Exception as e:
                print(f"chaos: probe rejected: {e!r}", flush=True)
        for req in probe_reqs:
            try:
                req.future.result(timeout=cfg.request_timeout_s)
                probe_done += 1
            except Exception as e:
                print(f"chaos: probe failed: {e!r}", flush=True)
    sup.begin_drain()
    sup.join_drained(cfg.request_timeout_s)
    sup.shutdown()
    stop_watch.set()
    watcher.join(timeout=2.0)
    replay = RequestJournal.replay(sup.paths.journal)
    acks = _journal_ack_times(sup.paths.journal)
    # crash-to-ready / crash-to-first-completion per kill, and initial
    # boot-to-ready per worker (the cold-vs-warm cache comparison)
    first_ready = {}
    first_pids = {}
    for e in lease_events:
        first_pids.setdefault(e["worker"], e["pid"])
        if e["ready"] and e["worker"] not in first_ready:
            first_ready[e["worker"]] = e["t"]
    boot_ttr = [round(t - t_start, 3) for _, t in sorted(first_ready.items())]
    # compiles per incarnation from the fleet's trace files, split into the
    # first (boot) incarnation of each worker vs respawns: a warm respawn
    # performing ANY compile is a bench failure (chaos_main enforces it)
    compiles = _compiles_by_pid(Path(cfg.fleet.dir))
    boot_pids = {str(p) for p in first_pids.values()}
    respawn_compiles = {
        inc: n for inc, n in compiles.items()
        if inc.rpartition("@pid")[2] not in boot_pids and n > 0}

    n_acc = len(accepted)
    return {
        "attempted": len(jobs),
        "accepted": n_acc,
        "rejected": rejected,
        "completed": len(completed),
        "failed": failed,
        "availability_pct": round(100.0 * len(completed) / max(1, n_acc), 3),
        "total_s": round(total_s, 3),
        "requests_per_s": round(len(completed) / total_s, 3),
        "latency_ms": {k: round(v * 1000.0, 3) for k, v in pct.items()},
        "kills": kills,
        "journal": replay["counts"],
        "boot_time_to_ready_s": boot_ttr,
        "respawns": _respawn_metrics(kills, lease_events, acks),
        "probes_completed": probe_done,
        "compiles_per_incarnation": compiles,
        "respawn_compiles": respawn_compiles,
        "results": completed,
    }


def _response_key(doc: dict) -> tuple:
    # the content that must be bit-identical across runs/workers; id, worker,
    # cache_hit, and latency legitimately differ
    return (doc.get("image_png_b64"), doc.get("width"), doc.get("height"))


def chaos_main() -> None:
    import tempfile

    n_requests = int(os.environ.get("BENCH_SERVE_CHAOS_REQUESTS", "24"))
    workers = int(os.environ.get("BENCH_SERVE_CHAOS_WORKERS", "2"))
    # the interval must leave a worker's survivors room to actually finish
    # batches between kills: on this CPU a respawned worker takes ~10s to
    # rejoin and a batch runs for several seconds, so sub-5s cadences degrade
    # into a crash loop where nothing completes anywhere
    kill_every_s = float(os.environ.get("BENCH_SERVE_CHAOS_KILL_EVERY_S",
                                        "10"))
    steps = int(os.environ.get("BENCH_SERVE_STEPS", "4"))
    res = int(os.environ.get("BENCH_SERVE_RES", "16"))

    # deliberately NO JAX persistent compile cache: dcr-warm's executable
    # cache is the thing under test, the baseline leg must be genuinely
    # COLD, and with XLA's cache active this jaxlib's CPU backend emits
    # executables whose raw serialization is broken — every entry would
    # degrade to the export tier, whose compile-on-load is (correctly)
    # counted by the recompile budget and would fail the zero-compile
    # respawn gate below. Strip the vars in case the caller's shell set them.
    for k in list(os.environ):
        if k.startswith("JAX_COMPILATION") or k.startswith("JAX_PERSISTENT"):
            os.environ.pop(k)

    print(f"bench_serve --chaos: {n_requests} requests, {workers} workers, "
          f"kill every {kill_every_s}s, steps={steps}, res={res}", flush=True)
    jobs = [(p, i) for i, p in enumerate(_prompts(n_requests))]

    with tempfile.TemporaryDirectory(prefix="dcr-chaos-") as td:
        tmp = Path(td)
        ckpt = _export_tiny_ckpt(tmp)
        # one persistent executable cache shared across BOTH runs: the
        # baseline populates it cold (its boot_time_to_ready_s is the cold
        # number), then every churn spawn AND respawn must come up warm —
        # zero compiles, trace-verified below
        warm_dir = tmp / "warmcache"
        baseline = _run_fleet_workload(
            _chaos_config(ckpt, tmp / "fleet_baseline", workers=workers,
                          steps=steps, res=res, warm_dir=warm_dir), jobs)
        print("baseline:", json.dumps({k: v for k, v in baseline.items()
                                       if k != "results"}), flush=True)
        churn = _run_fleet_workload(
            _chaos_config(ckpt, tmp / "fleet_churn", workers=workers,
                          steps=steps, res=res, warm_dir=warm_dir), jobs,
            kill_every_s=kill_every_s)
        print("churn:", json.dumps({k: v for k, v in churn.items()
                                    if k != "results"}), flush=True)

    mismatched = [job for job in baseline["results"]
                  if job in churn["results"]
                  and _response_key(baseline["results"][job])
                  != _response_key(churn["results"][job])]
    result = {
        "requests": n_requests, "workers": workers,
        "kill_every_s": kill_every_s, "steps": steps, "resolution": res,
        "sampler": "ddim", "model": "tiny",
        "baseline": {k: v for k, v in baseline.items() if k != "results"},
        "churn": {k: v for k, v in churn.items() if k != "results"},
        "kills": len(churn["kills"]),
        "dropped_accepted_requests": churn["journal"]["dropped"],
        "requeued": churn["journal"]["requeued_total"],
        "availability_pct": churn["availability_pct"],
        "p99_ms_baseline": baseline["latency_ms"].get("p99"),
        "p99_ms_churn": churn["latency_ms"].get("p99"),
        "bit_identical_responses": not mismatched,
        "mismatched_jobs": [list(j) for j in mismatched],
        # crash-to-ready recovery (dcr-warm): baseline boots are COLD (empty
        # executable cache), churn boots and every respawn are WARM
        "cold_boot_time_to_ready_s": baseline["boot_time_to_ready_s"],
        "warm_boot_time_to_ready_s": churn["boot_time_to_ready_s"],
        "warm_respawn_time_to_ready_s": [
            r["time_to_ready_s"] for r in churn["respawns"]],
        "warm_respawn_time_to_first_completion_s": [
            r["time_to_first_completion_s"] for r in churn["respawns"]],
        "respawn_compiles": churn["respawn_compiles"],
    }
    OUT_CHAOS.write_text(json.dumps(result, indent=2) + "\n")
    print(f"wrote {OUT_CHAOS}", flush=True)

    problems = []
    if churn["journal"]["dropped"] != 0:
        problems.append(
            f"dropped accepted requests: {churn['journal']['dropped']}")
    if churn["availability_pct"] < 100.0:
        problems.append(f"availability {churn['availability_pct']}% "
                        f"(failed: {churn['failed']})")
    if mismatched:
        problems.append(f"{len(mismatched)} response(s) not bit-identical "
                        f"to the uninjected run")
    if not churn["kills"]:
        problems.append("kill loop never fired — the churn run proved "
                        "nothing (workload too short for the cadence?)")
    if churn["respawn_compiles"]:
        problems.append(
            f"warm respawn recompiled: {churn['respawn_compiles']} — the "
            "persistent executable cache did not serve the respawned worker")
    if problems:
        print("CHAOS FAIL: " + "; ".join(problems), flush=True)
        raise SystemExit(1)
    print(f"CHAOS OK: {len(churn['kills'])} kill(s), "
          f"{churn['journal']['requeued_total']} requeue(s), 0 drops, "
          f"bit-identical responses", flush=True)


# ---------------------------------------------------------------------------
# --risk: online copy-risk scoring overhead (dcr-watch)
# ---------------------------------------------------------------------------

def _timed_batched_leg(stack, prompts, *, max_batch, steps, res, risk=None):
    """One batched serving leg (the same shape as main()'s): build, warm,
    submit the whole workload concurrently. Returns (wall seconds,
    seconds spent inside the risk-scoring path, service) — scoring time is
    measured around the service's own ``_score_risk`` so the overhead
    number comes from ONE leg and cannot be polluted by machine-load drift
    between two separately-timed runs (this box is a noisy shared core)."""
    from dcr_tpu.serve.queue import Request

    svc = _service(stack, max_batch=max_batch, steps=steps, res=res,
                   risk=risk)
    if risk is not None:
        if not svc.wait_risk_ready(timeout=600):
            raise RuntimeError("risk index never terminalized")
        if svc.risk_status() != "ok":
            raise RuntimeError(f"risk index load: {svc.risk_status()}")
    scoring = {"s": 0.0}
    orig_score = svc._score_risk

    def timed_score(*args, **kw):
        t = time.perf_counter()
        try:
            return orig_score(*args, **kw)
        finally:
            scoring["s"] += time.perf_counter() - t

    svc._score_risk = timed_score
    # warm outside the timed window: sampler compile AND (risk leg) the
    # first scored batch, so both legs time steady-state serving only
    svc.execute([Request(prompt="warmup", seed=0,
                         bucket=svc.default_bucket())])
    scoring["s"] = 0.0
    t0 = time.perf_counter()
    with ThreadPoolExecutor(max_workers=min(32, len(prompts))) as ex:
        futs = list(ex.map(lambda a: svc.submit(a[1], seed=a[0]).future,
                           enumerate(prompts)))
        for f in futs:
            f.result(timeout=600)
    elapsed = time.perf_counter() - t0
    return elapsed, scoring["s"], svc


def risk_main() -> None:
    import tempfile

    import numpy as np

    n_requests = int(os.environ.get("BENCH_RISK_REQUESTS", "48"))
    max_batch = int(os.environ.get("BENCH_SERVE_BATCH", "8"))
    # steps calibrates the generation:scoring work ratio. Measured on this
    # 1-core CPU: SSCD-at-32px scoring costs ~170ms per batch of 8 while a
    # 24-step tiny-model batch generates in ~290ms — a ratio ~10^4 MORE
    # pessimistic than any real deployment (SD-2.1 at 256px/50 steps is
    # ~70 TFLOPs of denoising per image vs ~0.1 GFLOPs of SSCD). 128 steps
    # still under-states generation cost by orders of magnitude but keeps
    # the bench honest about the scoring path's absolute cost.
    steps = int(os.environ.get("BENCH_RISK_STEPS", "128"))
    res = int(os.environ.get("BENCH_SERVE_RES", "16"))
    image_size = int(os.environ.get("BENCH_RISK_IMAGE_SIZE", "32"))
    index_n = int(os.environ.get("BENCH_RISK_INDEX_N", "4096"))

    cache_dir = Path(__file__).resolve().parent.parent / ".jax_cache"
    import jax

    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    print(f"bench_serve --risk: {n_requests} requests, max_batch={max_batch},"
          f" steps={steps}, res={res}, index_n={index_n}, "
          f"image_size={image_size}", flush=True)

    stack = _build_stack()
    prompts = _prompts(n_requests)
    result: dict = {"requests": n_requests, "max_batch": max_batch,
                    "steps": steps, "resolution": res, "sampler": "ddim",
                    "model": "tiny", "index_n": index_n,
                    "image_size": image_size}

    with tempfile.TemporaryDirectory(prefix="dcr-bench-risk-") as td:
        # synthetic train index at a realistic-for-CPU size: deterministic
        # features (jax PRNG, not global numpy RNG), threshold above 1 so
        # the timed loop never pays evidence I/O — this bench measures
        # SCORING, the flag path is covered by tests
        from dcr_tpu.core.config import RiskConfig
        from dcr_tpu.obs.copyrisk import EMBED_DIM
        from dcr_tpu.search.embed import save_embeddings

        feats = np.asarray(jax.random.normal(
            jax.random.key(7), (index_n, EMBED_DIM)), np.float32)
        index_path = Path(td) / "embedding.npz"
        save_embeddings(index_path, feats,
                        [f"train/{i}" for i in range(index_n)])

        off_s, _, svc_off = _timed_batched_leg(
            stack, prompts, max_batch=max_batch, steps=steps, res=res)
        snap_off = svc_off.metrics.snapshot()
        svc_off.stop(timeout=60)
        result["scoring_off"] = {
            "total_s": round(off_s, 3),
            "requests_per_s": round(n_requests / off_s, 3),
            "latency_ms": snap_off["latency_ms"],
            "hbm_peak_bytes": _peak_bytes(),
        }
        print("scoring off:", json.dumps(result["scoring_off"]), flush=True)

        risk = RiskConfig(index_path=str(index_path), image_size=image_size,
                          threshold=2.0, max_evidence=0)
        on_s, score_s, svc_on = _timed_batched_leg(
            stack, prompts, max_batch=max_batch, steps=steps, res=res,
            risk=risk)
        snap_on = svc_on.metrics.snapshot()
        scored = svc_on.status()["risk"]
        svc_on.stop(timeout=60)
        result["scoring_on"] = {
            "total_s": round(on_s, 3),
            "requests_per_s": round(n_requests / on_s, 3),
            "scoring_s": round(score_s, 3),
            "latency_ms": snap_on["latency_ms"],
            "risk": scored,
            "hbm_peak_bytes": _peak_bytes(),
        }
        print("scoring on:", json.dumps(result["scoring_on"]), flush=True)

    # the load-bearing number comes from ONE leg: scoring seconds vs the
    # same leg's non-scoring (generation) seconds. The serving pipeline is
    # a single worker thread, so this ratio IS the steady-state throughput
    # overhead — and unlike wall-clock A/B between two legs it cannot be
    # polluted by the shared box speeding up or slowing down between runs
    # (observed swings > 25% leg-to-leg on this 1-core container). The
    # off leg is banked as a reference point.
    overhead = 100.0 * score_s / max(1e-9, on_s - score_s)
    result["scoring_overhead_pct"] = round(overhead, 2)
    result["wall_delta_pct"] = round(100.0 * (on_s - off_s) / off_s, 2)
    print(f"scoring overhead: {result['scoring_overhead_pct']}% of batched "
          f"throughput (wall-clock A/B delta {result['wall_delta_pct']}%)",
          flush=True)
    OUT_RISK.write_text(json.dumps(result, indent=2) + "\n")
    print(f"wrote {OUT_RISK}", flush=True)
    if overhead >= 15.0:
        print(f"RISK BENCH FAIL: scoring overhead {overhead:.1f}% >= 15% "
              "of batched throughput", flush=True)
        raise SystemExit(1)
    print("RISK BENCH OK", flush=True)


# ---------------------------------------------------------------------------
# --fast: serving throughput with the dcr-fast score-reuse plan on
# ---------------------------------------------------------------------------

def fast_main() -> None:
    from dcr_tpu.core.config import FastSampleConfig
    from dcr_tpu.sampling import fastsample
    from dcr_tpu.serve.queue import Request

    n_requests = int(os.environ.get("BENCH_FAST_SERVE_REQUESTS", "32"))
    max_batch = int(os.environ.get("BENCH_SERVE_BATCH", "8"))
    # more steps than the throughput bench: fast sampling's win scales with
    # the denoiser fraction of a request, and a 4-step run measures batching
    # overhead, not sampling
    steps = int(os.environ.get("BENCH_FAST_SERVE_STEPS", "32"))
    res = int(os.environ.get("BENCH_SERVE_RES", "16"))

    cache_dir = Path(__file__).resolve().parent.parent / ".jax_cache"
    import jax

    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    print(f"bench_serve --fast: {n_requests} requests, max_batch={max_batch},"
          f" steps={steps}, res={res}", flush=True)

    stack = _build_stack()
    prompts = _prompts(n_requests)
    fast_cfg = FastSampleConfig(enabled=True)        # the default operating
    plan = fastsample.fast_plan(steps, fast_cfg.reuse_ratio)  # point
    calls = fastsample.unet_calls(plan)
    result: dict = {"requests": n_requests, "max_batch": max_batch,
                    "steps": steps, "resolution": res, "sampler": "ddim",
                    "model": "tiny", "reuse_ratio": fast_cfg.reuse_ratio,
                    "order": fast_cfg.order, "unet_calls_per_trajectory": calls,
                    "call_reduction": round(steps / max(1, calls), 3)}

    import statistics

    reps = int(os.environ.get("BENCH_FAST_REPS", "3"))

    def leg(fast=None) -> dict:
        # median of `reps` workload passes per leg: cross-leg wall A/B on
        # this shared box swings ±25% (see the --risk leg's rationale), so
        # a single-shot comparison would gate on machine-load noise
        svc = _service(stack, max_batch=max_batch, steps=steps, res=res,
                       fast=fast)
        svc.execute([Request(prompt="warmup", seed=0,
                             bucket=svc.default_bucket())])
        walls = []
        for _ in range(reps):
            t0 = time.perf_counter()
            with ThreadPoolExecutor(max_workers=min(32, n_requests)) as ex:
                futs = list(ex.map(
                    lambda a: svc.submit(a[1], seed=a[0]).future,
                    enumerate(prompts)))
                for f in futs:
                    f.result(timeout=600)
            walls.append(time.perf_counter() - t0)
        elapsed = statistics.median(walls)
        snap = svc.metrics.snapshot()
        svc.stop(timeout=60)
        return {"total_s": round(elapsed, 3),
                "reps": reps,
                "requests_per_s": round(n_requests / elapsed, 3),
                "latency_ms": snap["latency_ms"],
                "hbm_peak_bytes": _peak_bytes()}

    result["dense"] = leg()
    print("dense:", json.dumps(result["dense"]), flush=True)
    result["fast"] = leg(fast=fast_cfg)
    print("fast:", json.dumps(result["fast"]), flush=True)
    result["speedup"] = round(result["dense"]["total_s"]
                              / result["fast"]["total_s"], 3)
    print(f"fast-plan speedup: {result['speedup']}x at "
          f"{result['call_reduction']}x fewer UNet calls", flush=True)
    OUT_FAST.write_text(json.dumps(result, indent=2) + "\n")
    print(f"wrote {OUT_FAST}", flush=True)
    if result["speedup"] <= 1.0:
        # the plan skips real work; slower-than-dense means the machinery
        # broke (or the box is so loaded the numbers are meaningless)
        print("FAST BENCH FAIL: fast leg not faster than dense", flush=True)
        raise SystemExit(1)
    print("FAST BENCH OK", flush=True)


if __name__ == "__main__":
    if "--chaos" in sys.argv[1:]:
        chaos_main()
    elif "--risk" in sys.argv[1:]:
        risk_main()
    elif "--fast" in sys.argv[1:]:
        fast_main()
    else:
        main()

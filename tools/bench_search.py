"""Similarity-search bench: brute-force folder scan vs store-backed
sharded top-k (dcr-store, ISSUE 15).

Builds a synthetic SSCD-width corpus (random unit-scale float32 rows split
across N folder dumps — the reference's LAION-chunk layout), then measures
the SAME query set through both paths:

- **brute**: ``search_folders`` — the reference-equivalent per-folder scan:
  every folder dump re-loaded from disk, device matmul per gen-chunk, host
  ``argpartition`` + top-k merge per chunk. This is what every search pays
  today, so disk re-reads are part of its honest cost;
- **store**: ``dcr-search build`` once (banked separately as
  ``build_seconds`` — ingestion is paid once per corpus, not per search),
  then the mesh-sharded ``search/topk`` engine: fixed device segments,
  on-device ``lax.top_k`` merge, [B, K] host traffic instead of [B, N].

Gate (full mode): store-backed query throughput must reach
``MIN_SEARCH_SPEEDUP`` (1.5x) over brute force, or exit 1. Both modes pin
the store-backed results EXACTLY equal (scores and keys) to the brute
force — "faster" provably isn't "different". Results bank as
BENCH_SEARCH.json.

``--smoke`` (CI): small corpus; validates the JSON schema + the
exact-equality pin; the throughput gate is recorded but not enforced
(shared CI runners don't gate perf — the banked full run does).

Usage: python tools/bench_search.py [--smoke]
Env knobs: BENCH_SEARCH_ROWS (default 16384; smoke 768),
BENCH_SEARCH_FOLDERS (4; smoke 3), BENCH_SEARCH_QUERIES (64; smoke 16),
BENCH_SEARCH_TOPK (4), BENCH_SEARCH_DIM (512; smoke 64),
BENCH_SEARCH_REPEATS (3; smoke 1), BENCH_SEARCH_MIN (gate, default 1.5).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT = Path(__file__).resolve().parent.parent / "BENCH_SEARCH.json"

#: ISSUE 15 acceptance floor: store-backed vs brute-force query throughput.
MIN_SEARCH_SPEEDUP = 1.5


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name) or default)


def build_corpus(root: Path, *, rows: int, folders: int, dim: int,
                 seed: int = 0):
    """Folder dumps (the brute path's input) + the query matrix."""
    import numpy as np

    from dcr_tpu.search.embed import save_embeddings

    rng = np.random.default_rng(seed)
    per = -(-rows // folders)
    paths = []
    total = 0
    for i in range(folders):
        n = min(per, rows - total)
        total += n
        folder = root / f"chunk_{i:03d}"
        folder.mkdir(parents=True)
        feats = rng.standard_normal((n, dim)).astype(np.float32)
        save_embeddings(folder / "embedding.npz", feats,
                        [f"chunk{i}_img{j}" for j in range(n)])
        paths.append(folder)
    return paths


def run_brute(gen, gen_keys, folders, *, top_k: int, repeats: int) -> dict:
    from dcr_tpu.search.search import search_folders

    result = None
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        result = search_folders(gen, gen_keys, folders, top_k=top_k)
        best = min(best, time.perf_counter() - t0)
    return {"seconds": round(best, 4), "result": result}


def run_store(gen, store_dir, *, top_k: int, query_batch: int,
              repeats: int) -> dict:
    from dcr_tpu.search.shardindex import open_engine

    t0 = time.perf_counter()
    engine = open_engine(store_dir, top_k=top_k, query_batch=query_batch)
    ready_s = time.perf_counter() - t0
    engine.query(gen[:1])          # warmup: shapes already compiled by build
    best = float("inf")
    scores = keys = None
    for _ in range(repeats):
        t0 = time.perf_counter()
        scores, keys = engine.query(gen)
        best = min(best, time.perf_counter() - t0)
    return {"seconds": round(best, 4), "ready_seconds": round(ready_s, 4),
            "segments": engine.num_segments, "resident": engine.resident,
            "scores": scores, "keys": keys}


def validate_result(doc: dict) -> list[str]:
    """Schema problems with a BENCH_SEARCH document ([] = valid). Used by
    the --smoke leg and tests/test_store.py."""
    problems: list[str] = []

    def need(obj, field, types, where):
        v = obj.get(field)
        if not isinstance(v, types) or isinstance(v, bool) and types != bool:
            problems.append(f"{where}.{field}: missing/wrong type")
            return None
        return v

    need(doc, "version", int, "$")
    cfg = need(doc, "config", dict, "$") or {}
    for f in ("corpus_rows", "folders", "queries", "top_k", "embed_dim",
              "query_batch", "repeats"):
        need(cfg, f, int, "$.config")
    brute = need(doc, "brute", dict, "$") or {}
    need(brute, "seconds", (int, float), "$.brute")
    need(brute, "rows_per_s", (int, float), "$.brute")
    store = need(doc, "store", dict, "$") or {}
    for f in ("seconds", "rows_per_s", "build_seconds"):
        need(store, f, (int, float), "$.store")
    need(store, "segments", int, "$.store")
    eq = need(doc, "equality", dict, "$") or {}
    for f in ("scores_equal", "keys_equal"):
        if not isinstance(eq.get(f), bool):
            problems.append(f"$.equality.{f}: missing/not bool")
    gate = need(doc, "gate", dict, "$") or {}
    need(gate, "min_speedup", (int, float), "$.gate")
    need(gate, "speedup", (int, float), "$.gate")
    need(gate, "enforced", bool, "$.gate")
    if not isinstance(gate.get("passed"), bool):
        problems.append("$.gate.passed: missing/not bool")
    return problems


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    smoke = "--smoke" in argv

    import numpy as np

    from dcr_tpu.search.store import EmbeddingStoreWriter, ingest_dumps

    rows = _env_int("BENCH_SEARCH_ROWS", 768 if smoke else 16384)
    folders_n = _env_int("BENCH_SEARCH_FOLDERS", 3 if smoke else 4)
    queries = _env_int("BENCH_SEARCH_QUERIES", 16 if smoke else 64)
    top_k = _env_int("BENCH_SEARCH_TOPK", 4)
    dim = _env_int("BENCH_SEARCH_DIM", 64 if smoke else 512)
    repeats = _env_int("BENCH_SEARCH_REPEATS", 1 if smoke else 3)
    min_speedup = float(os.environ.get("BENCH_SEARCH_MIN")
                        or MIN_SEARCH_SPEEDUP)
    print(f"bench_search{' --smoke' if smoke else ''}: corpus {rows}x{dim} "
          f"across {folders_n} folders, {queries} queries, top_k={top_k}")

    rng = np.random.default_rng(1)
    gen = rng.standard_normal((queries, dim)).astype(np.float32)
    gen_keys = [f"g{i}" for i in range(queries)]

    with tempfile.TemporaryDirectory(prefix="bench_search_") as td:
        root = Path(td)
        folders = build_corpus(root / "corpus", rows=rows,
                               folders=folders_n, dim=dim)
        brute = run_brute(gen, gen_keys, folders, top_k=top_k,
                          repeats=repeats)
        t0 = time.perf_counter()
        report = ingest_dumps(
            EmbeddingStoreWriter.create(root / "store", shard_rows=4096),
            folders)
        build_s = time.perf_counter() - t0
        store = run_store(gen, root / "store", top_k=top_k,
                          query_batch=max(queries, 1), repeats=repeats)

        scores_equal = bool(np.array_equal(brute["result"]["scores"],
                                           store["scores"]))
        keys_equal = bool((brute["result"]["keys"] == store["keys"]).all())
        speedup = brute["seconds"] / max(store["seconds"], 1e-9)
        doc = {
            "version": 1,
            "config": {"corpus_rows": rows, "folders": folders_n,
                       "queries": queries, "top_k": top_k, "embed_dim": dim,
                       "query_batch": queries, "repeats": repeats,
                       "ingested_rows": int(report["rows"])},
            "brute": {
                "seconds": brute["seconds"],
                "rows_per_s": round(queries * rows / max(brute["seconds"],
                                                         1e-9)),
            },
            "store": {
                "build_seconds": round(build_s, 4),
                "ready_seconds": store["ready_seconds"],
                "seconds": store["seconds"],
                "rows_per_s": round(queries * rows / max(store["seconds"],
                                                         1e-9)),
                "segments": int(store["segments"]),
                "resident": bool(store["resident"]),
            },
            "equality": {"scores_equal": scores_equal,
                         "keys_equal": keys_equal},
            "gate": {"min_speedup": min_speedup,
                     "speedup": round(speedup, 3),
                     "enforced": not smoke,
                     "passed": bool(speedup >= min_speedup)},
        }

    problems = validate_result(doc)
    OUT.write_text(json.dumps(doc, indent=1, sort_keys=True) + "\n")
    print(f"bench_search: brute {brute['seconds']}s vs store "
          f"{store['seconds']}s -> speedup {doc['gate']['speedup']}x "
          f"(build {doc['store']['build_seconds']}s, paid once) -> {OUT}")
    if problems:
        print("bench_search: SCHEMA problems:\n  " + "\n  ".join(problems))
        return 1
    if not (scores_equal and keys_equal):
        print("bench_search: EQUALITY FAILED — store-backed results differ "
              f"from brute force (scores_equal={scores_equal}, "
              f"keys_equal={keys_equal})")
        return 1
    if not smoke and not doc["gate"]["passed"]:
        print(f"bench_search: GATE FAILED — speedup "
              f"{doc['gate']['speedup']}x < {min_speedup}x")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""dcr-lint: first-party static analysis for the dcr_tpu training stack.

The paper's replication measurements — and the resilience layer's bit-exact
rollback/resume and pod-wide fault agreement — only hold if the stack is
*provably* deterministic and collective-safe. One unsplit RNG key, one host
sync inside a jitted step, or one rank-conditional collective silently breaks
bit-exact recovery or hangs a pod hours into a run. dcr-lint enforces those
invariants mechanically, before any TPU time is spent:

=======  ====================================================================
DCR001   host-sync / tracer leak inside a jitted function (``.item()``,
         ``np.*`` on traced values, ``jax.device_get``, casts on traced args)
DCR002   donation-after-use: an argument named in ``donate_argnums`` is read
         after the donating call (XLA freed/aliased that buffer)
DCR003   RNG key reuse: the same key consumed by two sampling calls without
         an intervening ``split``/``fold_in``
DCR004   unbounded collective: ``barrier``/``kv_allgather``/allgather calls
         with no timeout — a dead peer hangs the pod forever
DCR005   rank-divergent collective: a collective issued under a
         ``process_index() == 0``-style conditional — the other ranks never
         enter it and the pod deadlocks
DCR006   silent exception swallow: ``except Exception: pass`` with no
         structured log / counter / quarantine on a recovery path
DCR007   recompilation hazard: Python ``if``/``while`` on a traced argument
         inside a jitted function without ``static_argnames``
DCR008   nondeterminism: global ``random.*`` / ``np.random.*`` state, or
         wall-clock reads traced into a jitted function
=======  ====================================================================

Usage::

    python -m tools.lint [paths...]            # human output, exit 1 on findings
    python -m tools.lint --format json ...     # machine-readable report
    python -m tools.lint --list-rules          # rule table
    python -m tools.lint --write-baseline ...  # grandfather current findings

Suppression: a per-line ``# dcr-lint: disable=DCR004`` pragma, or an entry in
``tools/lint/baseline.json`` (every entry must carry a written justification).
Configuration lives in ``[tool.dcr-lint]`` in pyproject.toml.
"""

from tools.lint.engine import Finding, LintError, lint_source, scan  # noqa: F401
from tools.lint.rules import RULES  # noqa: F401

"""Per-module AST analysis shared by every dcr-lint checker.

One pass over the module builds everything the rules need:

- import alias resolution (``np`` -> ``numpy``, ``jr`` -> ``jax.random``,
  ``from jax import jit`` -> ``jax.jit``) so checkers match on canonical
  dotted names instead of guessing at surface spellings;
- the *jit index*: every function that is traced — decorated with
  ``@jax.jit`` / ``@partial(jax.jit, ...)``, passed to ``jax.jit(f, ...)``
  (including lambdas and ``jax.jit(jax.grad(f))``), plus its
  static/donate argument metadata;
- the *donation index*: local names bound to ``jax.jit(..., donate_argnums=)``
  results, per scope, so DCR002 can follow donated buffers at call sites;
- a parent map and scope/branch-aware statement linearization for the
  order-sensitive rules (donation-after-use, key reuse).

Everything here is heuristic in the way a first-party linter can afford to
be: module-local, name-based, no type inference. The rules it feeds are
written so a miss is possible but a hit is near-certainly real.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Iterator, Optional

# canonical dotted names that mean "this function is traced"
JIT_WRAPPERS = {
    "jax.jit", "jax.pjit",
    "jax.experimental.pjit.pjit",
    "flax.linen.jit", "nn.jit",
}
PARTIAL_WRAPPERS = {"functools.partial", "partial"}


@dataclass
class JitInfo:
    """Tracing metadata attached to one jitted function/lambda."""

    static_argnums: tuple[int, ...] = ()
    static_argnames: tuple[str, ...] = ()
    donate_argnums: tuple[int, ...] = ()
    donate_argnames: tuple[str, ...] = ()

    def merge(self, other: "JitInfo") -> "JitInfo":
        return JitInfo(
            static_argnums=tuple(sorted(set(self.static_argnums) | set(other.static_argnums))),
            static_argnames=tuple(sorted(set(self.static_argnames) | set(other.static_argnames))),
            donate_argnums=tuple(sorted(set(self.donate_argnums) | set(other.donate_argnums))),
            donate_argnames=tuple(sorted(set(self.donate_argnames) | set(other.donate_argnames))),
        )


def _const_ints(node: ast.AST) -> tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return tuple(out)
    return ()


def _const_strs(node: ast.AST) -> tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant) and isinstance(e.value, str))
    return ()


FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)
ScopeNode = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.Module)


@dataclass
class LinearStmt:
    """One statement in execution-ish order within a scope.

    ``loop_depth`` counts enclosing loops *within the scope*; ``branch``
    is the chain of (if-node-id, arm) choices that guard the statement, so
    order-sensitive rules can tell mutually-exclusive arms apart.
    """

    stmt: ast.stmt
    loop_depth: int
    branch: tuple[tuple[int, int], ...] = ()

    def exclusive_with(self, other: "LinearStmt") -> bool:
        """True when the two statements sit on opposite arms of some branch
        (at most one of them runs in any given execution)."""
        mine = dict(self.branch)
        for node_id, arm in other.branch:
            if node_id in mine and mine[node_id] != arm:
                return True
        return False


class ModuleAnalysis:
    def __init__(self, tree: ast.Module, source: str, path: str):
        self.tree = tree
        self.path = path
        self.source_lines = source.splitlines()

        self.parent: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node

        # local name -> canonical dotted target
        self.aliases: dict[str, str] = {}
        self._collect_imports()

        self.defs_by_name: dict[str, list[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, FuncNode):
                self.defs_by_name.setdefault(node.name, []).append(node)

        # jitted function/lambda node -> JitInfo
        self.jit_infos: dict[ast.AST, JitInfo] = {}
        # scope node id -> {callable name: donate_argnums}
        self.donated_callables: dict[int, dict[str, tuple[int, ...]]] = {}
        self._collect_jit()

        # node id -> jitted root node (innermost registration wins the
        # setdefault; for the param set only the root's info matters)
        self.jit_root: dict[int, ast.AST] = {}
        # jitted root id -> names that are traced values inside the region
        self.traced_params: dict[int, set[str]] = {}
        for root, info in self.jit_infos.items():
            params: set[str] = set()
            for n in ast.walk(root):
                if isinstance(n, FuncNode) or isinstance(n, ast.Lambda):
                    params |= self._param_names(n, info if n is root else None)
            self.traced_params[id(root)] = params
            for n in ast.walk(root):
                self.jit_root.setdefault(id(n), root)

    # -- source helpers ------------------------------------------------------

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.source_lines):
            return self.source_lines[lineno - 1]
        return ""

    # -- name resolution -----------------------------------------------------

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.aliases[a.asname] = a.name
                    else:
                        head = a.name.split(".")[0]
                        self.aliases[head] = head
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for a in node.names:
                    local = a.asname or a.name
                    target = f"{mod}.{a.name}" if mod else a.name
                    self.aliases[local] = target

    def dotted(self, node: ast.AST) -> Optional[str]:
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        return None

    def resolve(self, dotted: str) -> str:
        head, sep, rest = dotted.partition(".")
        target = self.aliases.get(head)
        if target is None:
            return dotted
        return f"{target}.{rest}" if rest else target

    def resolve_call(self, call: ast.Call) -> Optional[str]:
        d = self.dotted(call.func)
        return self.resolve(d) if d else None

    @staticmethod
    def last_segment(node: ast.AST) -> Optional[str]:
        """Terminal attribute/name of a call target: ``self.x.barrier`` ->
        ``barrier`` — matches methods regardless of the receiver."""
        if isinstance(node, ast.Attribute):
            return node.attr
        if isinstance(node, ast.Name):
            return node.id
        return None

    # -- jit index -----------------------------------------------------------

    def _jit_kwargs(self, call: ast.Call) -> JitInfo:
        info = JitInfo()
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                info.static_argnums = _const_ints(kw.value)
            elif kw.arg == "static_argnames":
                info.static_argnames = _const_strs(kw.value)
            elif kw.arg == "donate_argnums":
                info.donate_argnums = _const_ints(kw.value)
            elif kw.arg == "donate_argnames":
                info.donate_argnames = _const_strs(kw.value)
        return info

    def _add_jit(self, node: ast.AST, info: JitInfo) -> None:
        prev = self.jit_infos.get(node)
        self.jit_infos[node] = prev.merge(info) if prev else info

    def _decorator_jit_info(self, dec: ast.AST) -> Optional[JitInfo]:
        d = self.dotted(dec)
        if d and self.resolve(d) in JIT_WRAPPERS:
            return JitInfo()
        if isinstance(dec, ast.Call):
            fd = self.dotted(dec.func)
            if fd and self.resolve(fd) in JIT_WRAPPERS:
                return self._jit_kwargs(dec)
            # @partial(jax.jit, static_argnames=...)
            if fd and self.resolve(fd) in PARTIAL_WRAPPERS and dec.args:
                inner = self.dotted(dec.args[0])
                if inner and self.resolve(inner) in JIT_WRAPPERS:
                    return self._jit_kwargs(dec)
        return None

    def enclosing_scope(self, node: ast.AST) -> ast.AST:
        cur = self.parent.get(node)
        while cur is not None and not isinstance(cur, ScopeNode):
            cur = self.parent.get(cur)
        return cur if cur is not None else self.tree

    def _collect_jit(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, FuncNode):
                for dec in node.decorator_list:
                    info = self._decorator_jit_info(dec)
                    if info is not None:
                        self._add_jit(node, info)
                        if info.donate_argnums or info.donate_argnames:
                            scope = self.enclosing_scope(node)
                            nums = self._donate_indices(node, info)
                            self.donated_callables.setdefault(
                                id(scope), {})[node.name] = nums
            elif isinstance(node, ast.Call):
                resolved = self.resolve_call(node)
                if resolved not in JIT_WRAPPERS or not node.args:
                    continue
                info = self._jit_kwargs(node)
                first = node.args[0]
                # every def/lambda reachable by name inside the wrapped
                # expression is traced (covers jax.jit(jax.grad(f)) too)
                for sub in ast.walk(first):
                    if isinstance(sub, ast.Lambda):
                        self._add_jit(sub, info)
                    elif isinstance(sub, ast.Name):
                        for d in self.defs_by_name.get(sub.id, []):
                            self._add_jit(d, info)
                if info.donate_argnums or info.donate_argnames:
                    nums = info.donate_argnums
                    if isinstance(first, ast.Name):
                        for d in self.defs_by_name.get(first.id, []):
                            nums = self._donate_indices(d, info)
                            break
                    assign = self.parent.get(node)
                    targets: list[ast.AST] = []
                    if isinstance(assign, ast.Assign):
                        targets = list(assign.targets)
                    elif isinstance(assign, ast.AnnAssign) and assign.target is not None:
                        targets = [assign.target]
                    for t in targets:
                        if isinstance(t, ast.Name):
                            scope = self.enclosing_scope(assign)
                            self.donated_callables.setdefault(
                                id(scope), {})[t.id] = nums

    @staticmethod
    def _param_names(fn: ast.AST, root_info: Optional[JitInfo]) -> set[str]:
        a = fn.args
        ordered = [x.arg for x in (a.posonlyargs + a.args)]
        names = set(ordered) | {x.arg for x in a.kwonlyargs}
        if root_info is not None:
            static = set(root_info.static_argnames)
            for i in root_info.static_argnums:
                if 0 <= i < len(ordered):
                    static.add(ordered[i])
            names -= static
        return names - {"self", "cls"}

    def _donate_indices(self, fn: ast.AST, info: JitInfo) -> tuple[int, ...]:
        """donate_argnames folded into positional indices via the def."""
        nums = set(info.donate_argnums)
        if info.donate_argnames and isinstance(fn, FuncNode):
            a = fn.args
            ordered = [x.arg for x in (a.posonlyargs + a.args)]
            for name in info.donate_argnames:
                if name in ordered:
                    nums.add(ordered.index(name))
        return tuple(sorted(nums))

    def in_jit(self, node: ast.AST) -> Optional[ast.AST]:
        return self.jit_root.get(id(node))

    # -- scopes / statement order --------------------------------------------

    def scopes(self) -> Iterator[tuple[ast.AST, list[ast.stmt]]]:
        """(scope node, body) for the module and every def — each analyzed
        independently by the order-sensitive rules."""
        yield self.tree, self.tree.body
        for node in ast.walk(self.tree):
            if isinstance(node, FuncNode):
                yield node, node.body

    def linearize(self, body: list[ast.stmt], loop_depth: int = 0,
                  branch: tuple = ()) -> Iterator[LinearStmt]:
        """Flatten a scope body into approximate execution order without
        descending into nested defs (separate scopes) — loops bump
        ``loop_depth``, if/try arms carry exclusivity markers."""
        for stmt in body:
            yield LinearStmt(stmt, loop_depth, branch)
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                yield from self.linearize(stmt.body, loop_depth + 1, branch)
                yield from self.linearize(stmt.orelse, loop_depth, branch)
            elif isinstance(stmt, ast.If):
                key = id(stmt)
                yield from self.linearize(stmt.body, loop_depth,
                                          branch + ((key, 0),))
                yield from self.linearize(stmt.orelse, loop_depth,
                                          branch + ((key, 1),))
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                yield from self.linearize(stmt.body, loop_depth, branch)
            elif isinstance(stmt, ast.Try):
                key = id(stmt)
                yield from self.linearize(stmt.body, loop_depth,
                                          branch + ((key, 0),))
                for i, handler in enumerate(stmt.handlers):
                    yield from self.linearize(handler.body, loop_depth,
                                              branch + ((key, i + 1),))
                yield from self.linearize(stmt.orelse, loop_depth,
                                          branch + ((key, 0),))
                yield from self.linearize(stmt.finalbody, loop_depth, branch)

    @staticmethod
    def stmt_calls(stmt: ast.stmt) -> Iterator[ast.Call]:
        """Call nodes executed *by this statement* — nested defs/lambdas
        run later (or never), so their bodies are excluded; for compound
        statements only the header (test/iter/items) counts, the body is
        linearized separately."""
        for node in _walk_shallow(stmt):
            if isinstance(node, ast.Call):
                yield node

    @staticmethod
    def deep_calls(stmt: ast.AST) -> Iterator[ast.Call]:
        """Every Call anywhere under ``stmt`` except inside nested
        function/lambda bodies — for containment rules (DCR005)."""
        stack: list[ast.AST] = [stmt]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Call):
                yield node
            if isinstance(node, FuncNode) or isinstance(node, ast.Lambda):
                continue
            stack.extend(ast.iter_child_nodes(node))

    @staticmethod
    def bound_names(stmt: ast.stmt) -> set[str]:
        """Names (re)bound by this statement, including tuple unpacking,
        loop targets, with-as, and walrus."""
        out: set[str] = set()
        for node in _walk_shallow(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                out.add(node.id)
        return out

    @staticmethod
    def loaded_names(stmt: ast.stmt) -> set[str]:
        out: set[str] = set()
        for node in _walk_shallow(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                out.add(node.id)
        return out


_COMPOUND = (ast.If, ast.For, ast.AsyncFor, ast.While, ast.With,
             ast.AsyncWith, ast.Try)
_BODY_FIELDS = {"body", "orelse", "handlers", "finalbody"}


def _walk_shallow(stmt: ast.AST) -> Iterator[ast.AST]:
    """ast.walk restricted to what *this statement itself* executes: no
    nested function/lambda bodies (deferred; separate scopes) and no
    compound-statement bodies (linearized as separate statements — only the
    if-test / for-iter / with-items header belongs to this node)."""
    stack: list[ast.AST] = [stmt]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, FuncNode) or isinstance(node, ast.Lambda):
            continue  # deferred body: a `def` statement only binds the name
        for fieldname, value in ast.iter_fields(node):
            if isinstance(node, _COMPOUND) and fieldname in _BODY_FIELDS:
                continue
            if isinstance(value, ast.AST):
                stack.append(value)
            elif isinstance(value, list):
                stack.extend(v for v in value if isinstance(v, ast.AST))


_LOOP = (ast.For, ast.AsyncFor, ast.While)


def enclosing_loop(body: list[ast.stmt], stmt: ast.stmt) -> Optional[ast.AST]:
    """Innermost For/While in this scope whose subtree contains ``stmt``
    (nested function/lambda bodies excluded), or None when ``stmt`` is not
    under a loop. Used by the DCR002 loop leg in both layers: a donated arg
    rebound by a LATER statement of the same loop body is fresh again on the
    next iteration, so only truly un-rebound donation gets flagged."""

    def walk(node: ast.AST, current: Optional[ast.AST]) -> bool:
        if node is stmt:
            found.append(current)
            return True
        if isinstance(node, FuncNode) or isinstance(node, ast.Lambda):
            return False
        nxt = node if isinstance(node, _LOOP) else current
        return any(walk(child, nxt) for child in ast.iter_child_nodes(node))

    found: list[Optional[ast.AST]] = []
    for top in body:
        if walk(top, None):
            break
    return found[0] if found else None

"""dcr-lint scan driver: file discovery, pragmas, baseline, reporting.

Suppression model (two layers, both auditable):

- **pragma** — ``# dcr-lint: disable=DCR004`` (comma-separated ids, or
  ``all``) on the finding's line silences it at the source, next to the
  justifying comment;
- **baseline** — ``tools/lint/baseline.json`` grandfathers findings by
  (rule, path, stripped-source-line) so unrelated edits that shift line
  numbers don't invalidate it. Every entry MUST carry a non-empty written
  justification; an unjustified entry is a configuration error (exit 2),
  not a suppression. Stale entries (matching nothing) are reported so the
  baseline only ever shrinks.

Exit codes: 0 clean, 1 findings, 2 internal/config error — the contract
the ``static-analysis`` CI job relies on.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional, Sequence

from tools.lint.analysis import ModuleAnalysis
from tools.lint.config import LintConfig
from tools.lint.rules import RULES, Finding

JSON_SCHEMA_VERSION = 1

_PRAGMA_RE = re.compile(r"#\s*dcr-lint:\s*disable=([A-Za-z0-9_,\s]+)")


class LintError(Exception):
    """Configuration/usage problem (bad baseline, unreadable path) — exit 2."""


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)
    files_scanned: int = 0
    pragma_suppressed: int = 0
    baseline_suppressed: int = 0
    stale_baseline: list[dict] = field(default_factory=list)

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return dict(sorted(out.items()))

    def to_json(self) -> dict:
        return {
            "version": JSON_SCHEMA_VERSION,
            "files_scanned": self.files_scanned,
            "findings": [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "col": f.col, "message": f.message, "snippet": f.snippet}
                for f in self.findings
            ],
            "counts": self.counts(),
            "suppressed": {"pragma": self.pragma_suppressed,
                           "baseline": self.baseline_suppressed},
            "stale_baseline": self.stale_baseline,
        }


def github_annotation(f: Finding) -> str:
    """One GitHub Actions workflow command per finding — surfaces inline on
    the PR diff when printed from a CI job. Newlines are %0A-escaped per the
    workflow-command spec."""
    msg = f.message.replace("%", "%25").replace("\r", "%0D").replace("\n", "%0A")
    return (f"::error file={f.path},line={f.line},col={f.col + 1},"
            f"title={f.rule}::{msg}")


def parse_failures(findings: Sequence[Finding]) -> list[Finding]:
    """DCR000 pseudo-findings: files the scan could not parse. The scan is
    incomplete over those files, so CLIs report them as exit-2 configuration
    errors (with the finding as the structured diagnostic), not as ordinary
    exit-1 findings."""
    return [f for f in findings if f.rule == "DCR000"]


def _pragma_rules(line: str) -> set[str]:
    m = _PRAGMA_RE.search(line)
    if not m:
        return set()
    return {tok.strip().upper() for tok in m.group(1).split(",") if tok.strip()}


def lint_source_counted(source: str, path: str = "<string>",
                        rules: Optional[Sequence[str]] = None
                        ) -> tuple[list[Finding], int]:
    """(findings, pragma-suppressed count) for one source blob."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(rule="DCR000", path=path, line=e.lineno or 1,
                        col=e.offset or 0,
                        message=f"syntax error: {e.msg}", snippet="")], 0
    analysis = ModuleAnalysis(tree, source, path)
    findings: list[Finding] = []
    for rule_id in (rules if rules is not None else RULES):
        rule = RULES.get(rule_id)
        if rule is None:
            raise LintError(f"unknown rule id {rule_id!r} "
                            f"(known: {', '.join(sorted(RULES))})")
        findings.extend(rule.check(analysis))
    # dedupe: containment rules can reach the same node via nested contexts
    findings = list(dict.fromkeys(findings))
    kept, suppressed = [], 0
    for f in findings:
        disabled = _pragma_rules(analysis.line(f.line))
        if f.rule in disabled or "ALL" in disabled:
            suppressed += 1
        else:
            kept.append(f)
    return kept, suppressed


def lint_source(source: str, path: str = "<string>",
                rules: Optional[Sequence[str]] = None) -> list[Finding]:
    """Run the (selected) checkers over one source blob; pragma-filtered,
    baseline-free. The in-process API tests and tools build on."""
    return lint_source_counted(source, path, rules)[0]


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

UNJUSTIFIED = "UNJUSTIFIED"


def load_baseline(path: Path) -> list[dict]:
    if not path.is_file():
        return []
    try:
        data = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError) as e:
        raise LintError(f"baseline {path}: unreadable ({e})") from e
    entries = data.get("entries", [])
    for entry in entries:
        for key in ("rule", "path", "snippet", "justification"):
            if key not in entry:
                raise LintError(f"baseline {path}: entry missing {key!r}: "
                                f"{json.dumps(entry)[:120]}")
        just = entry["justification"].strip()
        if not just or just.upper().startswith(UNJUSTIFIED) or \
                just.upper().startswith("TODO"):
            raise LintError(
                f"baseline {path}: {entry['rule']} at {entry['path']} has no "
                "written justification — every grandfathered finding must "
                "say why it is acceptable")
    return entries


def write_baseline(path: Path, findings: Sequence[Finding]) -> None:
    counts: dict[tuple[str, str, str], int] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        if f.rule == "DCR000":
            continue  # parse failures are exit-2 errors, never grandfathered
        counts[f.key()] = counts.get(f.key(), 0) + 1
    entries = [
        {"rule": rule, "path": fpath, "snippet": snippet,
         **({"count": n} if n > 1 else {}),
         "justification": f"{UNJUSTIFIED}: replace with why this is acceptable"}
        for (rule, fpath, snippet), n in counts.items()
    ]
    payload = {
        "comment": ("dcr-lint baseline: grandfathered findings, matched by "
                    "(rule, path, stripped source line). Every entry must "
                    "carry a real justification or the lint run fails."),
        "entries": entries,
    }
    path.write_text(json.dumps(payload, indent=2) + "\n", encoding="utf-8")


# ---------------------------------------------------------------------------
# scanning
# ---------------------------------------------------------------------------

def iter_py_files(paths: Sequence[Path], cfg: LintConfig) -> list[Path]:
    out: list[Path] = []
    seen = set()
    for p in paths:
        if not p.exists():
            raise LintError(f"no such path: {p}")
        if p.is_file() and p.suffix != ".py":
            # an explicitly named file that would be silently skipped is a
            # misconfigured invocation, not a clean scan
            raise LintError(f"not a Python file: {p}")
        if p.is_file() and p.stat().st_size == 0:
            # an explicitly named empty file means the invocation points at
            # the wrong thing (a truncated write, a bad glob) — surface it as
            # a configuration error instead of silently reporting "clean"
            raise LintError(f"empty file: {p} (nothing to scan)")
        candidates = [p] if p.is_file() else sorted(p.rglob("*.py"))
        for c in candidates:
            rel = _relpath(c, cfg.root)
            if cfg.excluded(rel):
                continue
            if c not in seen:
                seen.add(c)
                out.append(c)
    return out


def _relpath(p: Path, root: Path) -> str:
    try:
        return p.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return p.as_posix()


def scan(paths: Sequence[str | Path], cfg: Optional[LintConfig] = None, *,
         use_baseline: bool = True,
         baseline_override: Optional[Path] = None) -> Report:
    cfg = cfg or LintConfig()
    report = Report()
    all_rules = tuple(RULES)
    raw: list[Finding] = []
    scanned_rel: set[str] = set()
    for path in iter_py_files([Path(p) for p in paths], cfg):
        rel = _relpath(path, cfg.root)
        selected = cfg.rules_for(rel, all_rules)
        if not selected:
            continue
        scanned_rel.add(rel)
        try:
            source = path.read_text(encoding="utf-8")
        except UnicodeDecodeError as e:
            # a non-UTF8 .py file is unreadable to CPython itself; lint-
            # skipping it silently would report a clean scan over a file the
            # rules never saw — structured exit-2 diagnostic instead
            raise LintError(
                f"{rel}: not valid UTF-8 ({e.reason} at byte {e.start}) — "
                "the scan is incomplete; fix the file encoding") from e
        found, n_pragma = lint_source_counted(source, rel, rules=sorted(selected))
        report.pragma_suppressed += n_pragma
        raw.extend(found)
        report.files_scanned += 1

    entries: list[dict] = []
    if use_baseline:
        bl_path = baseline_override
        if bl_path is None and cfg.baseline:
            bl_path = cfg.root / cfg.baseline
        if bl_path is not None:
            entries = load_baseline(Path(bl_path))
    # each entry suppresses at most `count` occurrences (default 1): one
    # grandfathered finding must never silently absolve a NEW duplicate of
    # the same pattern added to the same file later
    matched_entries: set[int] = set()
    budget = [int(e.get("count", 1)) for e in entries]
    for f in raw:
        suppressed = False
        for i, entry in enumerate(entries):
            if f.rule == "DCR000":
                # a parse failure can never be grandfathered: a baselined
                # DCR000 would report "clean" (exit 0) over a file the rules
                # never saw, silently defeating the exit-2 incomplete-scan
                # contract
                break
            if budget[i] > 0 and \
                    (entry["rule"], entry["path"], entry["snippet"]) == f.key():
                matched_entries.add(i)
                budget[i] -= 1
                suppressed = True
                break
        if suppressed:
            report.baseline_suppressed += 1
        else:
            report.findings.append(f)
    # an entry is stale when its file WAS scanned and nothing matched —
    # partial scans (one file, a subdir) must not cry wolf about the rest —
    # or when its file no longer exists at all: a deleted file can never
    # match any scan, so keeping its entry around only hides baseline rot.
    # Entries for rules outside this layer's registry (the program-layer
    # DCR01x rules) are never judged here: only `python -m tools.check`,
    # which runs those rules, can tell whether they still match.
    report.stale_baseline = [e for i, e in enumerate(entries)
                             if i not in matched_entries
                             and e["rule"] in RULES
                             and (e["path"] in scanned_rel
                                  or not (cfg.root / e["path"]).is_file())]
    report.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return report

"""CLI: ``python -m tools.lint [paths...]``.

Exit codes: 0 clean, 1 findings, 2 configuration/usage error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from tools.lint.config import load_config
from tools.lint.engine import (LintError, github_annotation, parse_failures,
                               scan, write_baseline)
from tools.lint.rules import RULES


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="dcr-lint: JAX-aware determinism/donation/RNG/collective "
                    "static analysis for the dcr_tpu stack")
    p.add_argument("paths", nargs="*", default=["dcr_tpu", "tests", "tools"],
                   help="files/directories to scan (default: dcr_tpu tests tools)")
    p.add_argument("--format", choices=("human", "json", "github"),
                   default="human",
                   help="github = GitHub Actions ::error annotations "
                        "(findings surface inline on the PR diff)")
    p.add_argument("--select", default=None,
                   help="comma-separated rule ids to run (overrides config)")
    p.add_argument("--ignore", default=None,
                   help="comma-separated rule ids to drop (overrides config)")
    p.add_argument("--config", type=Path, default=None,
                   help="pyproject.toml to read [tool.dcr-lint] from "
                        "(default: nearest to cwd)")
    p.add_argument("--baseline", type=Path, default=None,
                   help="baseline file (default: [tool.dcr-lint].baseline)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report baselined findings too")
    p.add_argument("--write-baseline", action="store_true",
                   help="write every current finding to the baseline file "
                        "(you must then fill in each justification)")
    p.add_argument("--list-rules", action="store_true")
    return p


def _list_rules() -> int:
    width = max(len(r.title) for r in RULES.values())
    for rule in RULES.values():
        print(f"{rule.rule_id}  {rule.title:<{width}}  {rule.summary}")
    return 0


def main(argv: list[str] | None = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.list_rules:
        return _list_rules()
    try:
        cfg = load_config(pyproject=args.config)
        if args.select:
            cfg.select = tuple(s.strip().upper()
                               for s in args.select.split(",") if s.strip())
        if args.ignore:
            cfg.ignore = tuple(s.strip().upper()
                               for s in args.ignore.split(",") if s.strip())
        use_baseline = not (args.no_baseline or args.write_baseline)
        report = scan(args.paths, cfg, use_baseline=use_baseline,
                      baseline_override=args.baseline)
    except LintError as e:
        print(f"dcr-lint: error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        bl = args.baseline or (cfg.root / (cfg.baseline or
                                           "tools/lint/baseline.json"))
        write_baseline(Path(bl), report.findings)
        print(f"dcr-lint: wrote {len(report.findings)} entr"
              f"{'y' if len(report.findings) == 1 else 'ies'} to {bl}; "
              "fill in each justification (the run fails until you do)")
        return 0

    broken = parse_failures(report.findings)
    if args.format == "json":
        print(json.dumps(report.to_json(), indent=2))
    elif args.format == "github":
        for f in report.findings:
            print(github_annotation(f))
    else:
        for f in report.findings:
            print(f"{f.path}:{f.line}:{f.col + 1}: {f.rule} {f.message}")
        for entry in report.stale_baseline:
            print(f"dcr-lint: stale baseline entry (no longer matches): "
                  f"{entry['rule']} {entry['path']} — remove it",
                  file=sys.stderr)
        counts = report.counts()
        summary = ", ".join(f"{k}×{v}" for k, v in counts.items()) or "clean"
        print(f"dcr-lint: {len(report.findings)} finding"
              f"{'' if len(report.findings) == 1 else 's'} "
              f"({summary}) in {report.files_scanned} files "
              f"[suppressed: {report.baseline_suppressed} baseline, "
              f"{report.pragma_suppressed} pragma]")
    if broken:
        # the scan is INCOMPLETE over unparseable files: that is a
        # configuration error (exit 2), not an ordinary finding (exit 1)
        for f in broken:
            print(f"dcr-lint: error: {f.path}:{f.line}: {f.message} — "
                  "file could not be parsed; the scan is incomplete",
                  file=sys.stderr)
        return 2
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())

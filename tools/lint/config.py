"""``[tool.dcr-lint]`` configuration loading.

Rule sets are declared in pyproject.toml, not hardcoded::

    [tool.dcr-lint]
    select = ["DCR001", ...]        # rules to run (default: all)
    ignore = ["DCR0xx"]             # rules to drop after select
    exclude = ["tests/fixtures"]    # path prefixes never scanned
    baseline = "tools/lint/baseline.json"

    [tool.dcr-lint.per-path-ignores]
    "tools/" = ["DCR008"]           # rule ids ignored under a path prefix

Python 3.11+ parses with stdlib tomllib; on 3.10 (this container) a
minimal TOML-subset reader handles the constructs pyproject.toml actually
uses (tables, strings, ints/floats/bools, string arrays, inline tables).
No third-party dependency either way — the lint job must run on a bare
checkout before anything is pip-installed.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional


@dataclass
class LintConfig:
    select: tuple[str, ...] = ()          # empty = all registered rules
    ignore: tuple[str, ...] = ()
    exclude: tuple[str, ...] = ("__pycache__",)
    per_path_ignores: dict[str, tuple[str, ...]] = field(default_factory=dict)
    baseline: Optional[str] = "tools/lint/baseline.json"
    root: Path = Path(".")

    def rules_for(self, relpath: str, all_rules: tuple[str, ...]) -> set[str]:
        selected = set(self.select or all_rules) - set(self.ignore)
        posix = relpath.replace("\\", "/")
        for prefix, ignored in self.per_path_ignores.items():
            if posix.startswith(prefix.rstrip("/") + "/") or posix == prefix:
                selected -= set(ignored)
        return selected

    def excluded(self, relpath: str) -> bool:
        posix = relpath.replace("\\", "/")
        parts = posix.split("/")
        for pat in self.exclude:
            pat = pat.rstrip("/")
            if posix == pat or posix.startswith(pat + "/") or pat in parts:
                return True
        return False


def _parse_toml(text: str) -> dict:
    try:
        import tomllib

        return tomllib.loads(text)
    except ModuleNotFoundError:
        return _mini_toml(text)


_KEY_RE = re.compile(r'^\s*(?:"([^"]+)"|([A-Za-z0-9_.-]+))\s*=\s*(.*)$')


def _split_table_path(header: str) -> list[str]:
    """Dotted table header -> segments, honoring quoted segments."""
    out, cur, quoted = [], "", False
    for ch in header:
        if ch == '"':
            quoted = not quoted
        elif ch == "." and not quoted:
            out.append(cur)
            cur = ""
        else:
            cur += ch
    out.append(cur)
    return [s.strip() for s in out]


def _parse_value(raw: str):
    raw = raw.strip()
    if raw.startswith("["):
        # tolerate trailing commas / newlines already joined by caller
        inner = raw[1:-1] if raw.endswith("]") else raw[1:]
        items = [x.strip() for x in _split_commas(inner) if x.strip()]
        return [_parse_value(x) for x in items]
    if raw.startswith("{"):
        inner = raw[1:-1] if raw.endswith("}") else raw[1:]
        out = {}
        for part in _split_commas(inner):
            m = _KEY_RE.match(part.strip())
            if m:
                out[m.group(1) or m.group(2)] = _parse_value(m.group(3))
        return out
    if raw.startswith('"'):
        try:
            return json.loads(raw)
        except json.JSONDecodeError:
            return raw.strip('"')
    if raw in ("true", "false"):
        return raw == "true"
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        return raw


def _split_commas(text: str) -> list[str]:
    out, cur, depth, quoted = [], "", 0, False
    for ch in text:
        if ch == '"':
            quoted = not quoted
        if not quoted:
            if ch in "[{":
                depth += 1
            elif ch in "]}":
                depth -= 1
            elif ch == "," and depth == 0:
                out.append(cur)
                cur = ""
                continue
        cur += ch
    if cur.strip():
        out.append(cur)
    return out


def _strip_comment(line: str) -> str:
    out, quoted = "", False
    for ch in line:
        if ch == '"':
            quoted = not quoted
        if ch == "#" and not quoted:
            break
        out += ch
    return out


def _mini_toml(text: str) -> dict:
    root: dict = {}
    table = root
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        line = _strip_comment(lines[i]).strip()
        i += 1
        if not line:
            continue
        if line.startswith("["):
            path = _split_table_path(line.strip("[]"))
            table = root
            for seg in path:
                table = table.setdefault(seg, {})
            continue
        m = _KEY_RE.match(line)
        if not m:
            continue
        key = m.group(1) or m.group(2)
        raw = m.group(3).strip()
        # multiline arrays: keep consuming until brackets balance
        while raw.count("[") > raw.count("]") and i < len(lines):
            raw += " " + _strip_comment(lines[i]).strip()
            i += 1
        table[key] = _parse_value(raw)
    return root


def find_pyproject(start: Path) -> Optional[Path]:
    cur = start.resolve()
    for candidate in [cur, *cur.parents]:
        p = candidate / "pyproject.toml"
        if p.is_file():
            return p
    return None


def load_config(pyproject: Optional[Path] = None,
                start: Optional[Path] = None) -> LintConfig:
    if pyproject is None:
        pyproject = find_pyproject(start or Path.cwd())
    if pyproject is None or not pyproject.is_file():
        return LintConfig()
    data = _parse_toml(pyproject.read_text(encoding="utf-8"))
    section = data.get("tool", {}).get("dcr-lint", {})
    if not isinstance(section, dict):
        section = {}
    ppi = section.get("per-path-ignores", {})
    cfg = LintConfig(
        select=tuple(section.get("select", ())),
        ignore=tuple(section.get("ignore", ())),
        exclude=tuple(section.get("exclude", ("__pycache__",))),
        per_path_ignores={k: tuple(v) for k, v in ppi.items()
                          if isinstance(v, list)},
        baseline=section.get("baseline", "tools/lint/baseline.json"),
        root=pyproject.parent,
    )
    return cfg

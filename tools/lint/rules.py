"""The dcr-lint rule set (DCR001–DCR008).

Each checker is a function ``(ModuleAnalysis) -> list[Finding]`` registered
in :data:`RULES`. Every rule is motivated by a real hazard class in this
repo — see the rule table in README.md §"Static analysis" and the
footgun-to-rule mapping in MIGRATION.md. Checkers are deliberately
precision-biased: module-local, name-based, no cross-file inference. The
escape hatches for the residue are per-line pragmas and the justified
baseline, both enforced by tools/lint/engine.py.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Callable, Optional

from tools.lint.analysis import (FuncNode, LinearStmt, ModuleAnalysis,
                                 enclosing_loop)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str = ""

    def key(self) -> tuple[str, str, str]:
        """Line-number-free identity used for baseline matching (stable
        across unrelated edits that shift line numbers)."""
        return (self.rule, self.path, self.snippet)


def _finding(analysis: ModuleAnalysis, rule: str, node: ast.AST,
             message: str) -> Finding:
    line = getattr(node, "lineno", 1)
    col = getattr(node, "col_offset", 0)
    return Finding(rule=rule, path=analysis.path, line=line, col=col,
                   message=message, snippet=analysis.line(line).strip())


# ---------------------------------------------------------------------------
# DCR001 — host sync / tracer leak inside a jitted function
# ---------------------------------------------------------------------------

# zero/low-arg array methods that force a device->host transfer (or make no
# sense on a tracer at all)
_HOST_SYNC_METHODS = {"item", "tolist", "numpy", "block_until_ready",
                      "copy_to_host_async"}
_HOST_SYNC_CALLS = {"jax.device_get"}
_PY_CASTS = {"float", "int", "bool", "complex"}


def check_dcr001(analysis: ModuleAnalysis) -> list[Finding]:
    out = []
    for node in ast.walk(analysis.tree):
        if not isinstance(node, ast.Call):
            continue
        root = analysis.in_jit(node)
        if root is None:
            continue
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _HOST_SYNC_METHODS:
            out.append(_finding(
                analysis, "DCR001", node,
                f".{node.func.attr}() inside a jitted function forces a "
                "host sync (or fails on a tracer) — return the array and "
                "materialize outside jit"))
            continue
        resolved = analysis.resolve_call(node)
        if resolved is None:
            continue
        if resolved in _HOST_SYNC_CALLS:
            out.append(_finding(
                analysis, "DCR001", node,
                f"{resolved} inside a jitted function is a host transfer — "
                "hoist it out of the traced region"))
        elif resolved.split(".")[0] == "numpy":
            out.append(_finding(
                analysis, "DCR001", node,
                f"host numpy call ({resolved.replace('numpy', 'np', 1)}) "
                "inside a jitted function — it either bakes a constant at "
                "trace time or fails on a tracer; use jnp"))
        elif resolved in _PY_CASTS and node.args:
            traced = analysis.traced_params.get(id(root), set())
            arg = node.args[0]
            if isinstance(arg, ast.Name) and arg.id in traced:
                out.append(_finding(
                    analysis, "DCR001", node,
                    f"{resolved}({arg.id}) casts a traced argument to a "
                    "Python scalar inside jit — a host sync (ConcretizationError "
                    "on abstract tracers); keep it as a jnp array"))
    return out


# ---------------------------------------------------------------------------
# DCR002 — donation-after-use
# ---------------------------------------------------------------------------

def check_dcr002(analysis: ModuleAnalysis) -> list[Finding]:
    out = []
    module_donated = analysis.donated_callables.get(id(analysis.tree), {})
    for scope, body in analysis.scopes():
        donated = dict(module_donated)
        donated.update(analysis.donated_callables.get(id(scope), {}))
        if not donated:
            continue
        stmts = list(analysis.linearize(body))
        for i, ls in enumerate(stmts):
            for call in analysis.stmt_calls(ls.stmt):
                if not isinstance(call.func, ast.Name):
                    continue
                indices = donated.get(call.func.id)
                if not indices:
                    continue
                for k in indices:
                    if k >= len(call.args) or not isinstance(call.args[k], ast.Name):
                        continue
                    name = call.args[k].id
                    if name in analysis.bound_names(ls.stmt):
                        continue  # x, ... = f(x, ...) — the donated name is rebound
                    if ls.loop_depth > 0:
                        loop = enclosing_loop(body, ls.stmt)
                        if loop is not None and (
                                name in analysis.bound_names(loop) or any(
                                    name in analysis.bound_names(inner.stmt)
                                    for inner in analysis.linearize(loop.body, 1)
                                    if inner.stmt is not ls.stmt)):
                            continue  # rebound in the loop body (or the loop
                            # target itself): fresh before the next iteration
                        out.append(_finding(
                            analysis, "DCR002", call,
                            f"'{name}' is donated to {call.func.id}() inside a "
                            "loop but never rebound — the second iteration "
                            "passes a buffer XLA already freed; rebind it "
                            f"(e.g. `{name}, ... = {call.func.id}({name}, ...)`)"))
                        continue
                    out.extend(_use_after_donation(analysis, stmts, i, ls,
                                                   name, call))
    return out


def _use_after_donation(analysis: ModuleAnalysis, stmts: list[LinearStmt],
                        i: int, donate_ls: LinearStmt, name: str,
                        call: ast.Call) -> list[Finding]:
    for later in stmts[i + 1:]:
        if later.exclusive_with(donate_ls):
            continue
        if name in analysis.loaded_names(later.stmt):
            return [_finding(
                analysis, "DCR002", later.stmt,
                f"'{name}' is read after being donated to "
                f"{call.func.id}() on line {call.lineno} — donate_argnums "
                "freed/aliased that buffer (undefined contents); read it "
                "before the call or drop the donation")]
        if name in analysis.bound_names(later.stmt):
            return []
    return []


# ---------------------------------------------------------------------------
# DCR003 — RNG key reuse
# ---------------------------------------------------------------------------

# producers: calls whose result is a fresh key (assignment target becomes a
# tracked key variable); last-segment match covers jax.random.* and the
# repo's core.rng helpers alike
_KEY_PRODUCERS = {"key", "PRNGKey", "split", "fold_in", "root_key",
                  "stream_key", "step_key", "wrap_key_data", "clone"}
# consumers: sampling calls that exhaust the key passed as arg 0 / key=
_KEY_CONSUMERS = {
    "normal", "uniform", "randint", "bits", "beta", "gamma", "poisson",
    "bernoulli", "categorical", "choice", "permutation", "shuffle",
    "truncated_normal", "dirichlet", "exponential", "laplace", "logistic",
    "gumbel", "cauchy", "rademacher", "maxwell", "t", "orthogonal", "ball",
    "loggamma", "binomial", "multivariate_normal", "double_sided_maxwell",
    "generalized_normal", "rayleigh", "triangular", "weibull_min",
}


def _is_jax_random(analysis: ModuleAnalysis, call: ast.Call,
                   vocabulary: set[str]) -> Optional[str]:
    """The terminal fn name when this call is jax.random.<fn> (or an aliased
    spelling / repo rng helper) with <fn> in ``vocabulary``."""
    last = analysis.last_segment(call.func)
    if last not in vocabulary:
        return None
    resolved = analysis.resolve_call(call) or ""
    head = resolved.rsplit(".", 1)[0] if "." in resolved else ""
    # exclude stdlib random / numpy.random — DCR008 territory
    if head == "random" or head.startswith("numpy"):
        return None
    return last


def _consumed_key(call: ast.Call) -> Optional[str]:
    for kw in call.keywords:
        if kw.arg == "key" and isinstance(kw.value, ast.Name):
            return kw.value.id
    if call.args and isinstance(call.args[0], ast.Name):
        return call.args[0].id
    return None


def check_dcr003(analysis: ModuleAnalysis) -> list[Finding]:
    out = []
    for scope, body in analysis.scopes():
        key_depth: dict[str, int] = {}          # key var -> binding loop depth
        consumed: dict[str, LinearStmt] = {}    # key var -> first consuming stmt
        consumed_line: dict[str, int] = {}
        # seed: conventionally-named key parameters are keys from line one
        for p in _param_key_names(scope):
            key_depth[p] = 0
        for ls in analysis.linearize(body):
            for call in analysis.stmt_calls(ls.stmt):
                if _is_jax_random(analysis, call, _KEY_CONSUMERS) is None:
                    continue
                name = _consumed_key(call)
                if name is None or name not in key_depth:
                    continue
                prev = consumed.get(name)
                if prev is not None and not prev.exclusive_with(ls):
                    out.append(_finding(
                        analysis, "DCR003", call,
                        f"RNG key '{name}' is consumed again (first used on "
                        f"line {consumed_line[name]}) without split/fold_in — "
                        "identical randomness in both draws breaks the "
                        "one-use-per-key discipline"))
                    continue
                if ls.loop_depth > key_depth.get(name, 0):
                    out.append(_finding(
                        analysis, "DCR003", call,
                        f"RNG key '{name}' (bound outside this loop) is "
                        "consumed every iteration — every draw is identical; "
                        "fold_in the loop index or split per iteration"))
                    continue
                consumed[name] = ls
                consumed_line[name] = call.lineno
            bound = analysis.bound_names(ls.stmt)
            for name in bound:
                consumed.pop(name, None)
                consumed_line.pop(name, None)
            # track fresh key bindings: <targets> = <producer>(...)
            for call in analysis.stmt_calls(ls.stmt):
                if _is_jax_random(analysis, call, _KEY_PRODUCERS) is not None:
                    for name in bound:
                        key_depth[name] = ls.loop_depth
                    break
    return out


def _param_key_names(fn: ast.AST) -> list[str]:
    if not isinstance(fn, FuncNode):
        return []
    a = fn.args
    return [x.arg for x in (a.posonlyargs + a.args + a.kwonlyargs)
            if x.arg in ("key", "rng", "rng_key", "prng_key", "root_key")]


# ---------------------------------------------------------------------------
# DCR004 — unbounded collectives
# ---------------------------------------------------------------------------

# collective -> index of its timeout positional parameter
_BOUNDED_COLLECTIVES = {"barrier": 1, "wait_at_barrier": 1, "kv_allgather": 2}
# collectives with no timeout parameter at all: only OK under run_with_timeout
_WRAP_ONLY_COLLECTIVES = {"sync_global_devices", "process_allgather"}
_TIMEOUT_KWARGS = {"timeout_s", "timeout_ms", "timeout_in_ms", "timeout"}


def _is_zero(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value in (0, 0.0)


def _under_run_with_timeout(analysis: ModuleAnalysis, node: ast.AST) -> bool:
    cur = node
    while cur is not None:
        if isinstance(cur, ast.Call) and \
                analysis.last_segment(cur.func) == "run_with_timeout":
            return True
        cur = analysis.parent.get(cur)
    return False


def check_dcr004(analysis: ModuleAnalysis) -> list[Finding]:
    out = []
    for node in ast.walk(analysis.tree):
        if not isinstance(node, ast.Call):
            continue
        last = analysis.last_segment(node.func)
        if last in _BOUNDED_COLLECTIVES:
            pos = _BOUNDED_COLLECTIVES[last]
            bounded = None
            if len(node.args) > pos:
                bounded = not _is_zero(node.args[pos])
            for kw in node.keywords:
                if kw.arg in _TIMEOUT_KWARGS:
                    bounded = not _is_zero(kw.value)
            if bounded is None:
                bounded = _under_run_with_timeout(analysis, node)
            if not bounded:
                out.append(_finding(
                    analysis, "DCR004", node,
                    f"{last}() without a timeout — a dead or wedged peer "
                    "hangs the pod here forever; pass timeout_s (the "
                    "BarrierTimeout discipline, core/dist.py) so the hang "
                    "watchdog can turn it into a diagnosable abort"))
        elif last in _WRAP_ONLY_COLLECTIVES:
            if not _under_run_with_timeout(analysis, node):
                out.append(_finding(
                    analysis, "DCR004", node,
                    f"{last}() has no native deadline — wrap it in "
                    "dist.run_with_timeout(...) so a missing peer raises "
                    "BarrierTimeout instead of hanging the pod"))
    return out


# ---------------------------------------------------------------------------
# DCR005 — rank-divergent collectives
# ---------------------------------------------------------------------------

_RANK_CALLS = {"process_index", "is_primary"}
_RANK_NAMES = {"rank", "process_id", "process_index", "pidx"}
_COLLECTIVE_CALLS = (set(_BOUNDED_COLLECTIVES) | _WRAP_ONLY_COLLECTIVES |
                     {"psum", "pmean", "pmax", "pmin", "all_gather",
                      "all_reduce", "all_to_all", "agree_int", "assert_same",
                      "exchange", "ppermute"})


def _rank_conditional(analysis: ModuleAnalysis, test: ast.AST) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Call) and \
                analysis.last_segment(node.func) in _RANK_CALLS:
            return True
        if isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            if any(isinstance(s, ast.Name) and s.id in _RANK_NAMES
                   for s in sides):
                return True
    return False


def check_dcr005(analysis: ModuleAnalysis) -> list[Finding]:
    out = []
    for node in ast.walk(analysis.tree):
        if not isinstance(node, ast.If) or not _rank_conditional(analysis, node.test):
            continue
        for arm in (node.body, node.orelse):
            for stmt in arm:
                for call in analysis.deep_calls(stmt):
                    last = analysis.last_segment(call.func)
                    if last in _COLLECTIVE_CALLS:
                        out.append(_finding(
                            analysis, "DCR005", call,
                            f"collective {last}() under a rank-conditional "
                            "branch — the other ranks never enter it and the "
                            "pod deadlocks; issue the collective on every "
                            "rank and branch on the (identical) result"))
    return out


# ---------------------------------------------------------------------------
# DCR006 — silent exception swallowing
# ---------------------------------------------------------------------------

_BROAD_EXC = {"Exception", "BaseException"}


def _is_broad(analysis: ModuleAnalysis, type_node: Optional[ast.AST]) -> bool:
    if type_node is None:
        return True  # bare except:
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(analysis, e) for e in type_node.elts)
    last = analysis.last_segment(type_node)
    return last in _BROAD_EXC


def _is_silent(body: list[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring / `...`
        return False
    return True


def check_dcr006(analysis: ModuleAnalysis) -> list[Finding]:
    out = []
    for node in ast.walk(analysis.tree):
        if isinstance(node, ast.ExceptHandler) and \
                _is_broad(analysis, node.type) and _is_silent(node.body):
            out.append(_finding(
                analysis, "DCR006", node,
                "broad `except ...: pass` swallows the failure with no "
                "trace — on a recovery path this hides real faults; emit a "
                "structured log (resilience.log_event) and bump a faults/* "
                "counter (resilience.bump_counter), or narrow the type"))
    return out


# ---------------------------------------------------------------------------
# DCR007 — recompilation hazards (Python branching on traced values)
# ---------------------------------------------------------------------------

def _is_none_check(node: ast.AST) -> bool:
    """``x is None`` / ``x is not None``: a pytree-STRUCTURE check, decided
    at trace time from the treedef — stable, never touches traced values."""
    return (isinstance(node, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops)
            and all(isinstance(c, ast.Constant) and c.value is None
                    for c in node.comparators))


def _walk_skipping_none_checks(test: ast.AST):
    stack = [test]
    while stack:
        node = stack.pop()
        if _is_none_check(node):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def check_dcr007(analysis: ModuleAnalysis) -> list[Finding]:
    out = []
    for node in ast.walk(analysis.tree):
        if not isinstance(node, (ast.If, ast.While, ast.IfExp)):
            continue
        root = analysis.in_jit(node)
        if root is None:
            continue
        traced = analysis.traced_params.get(id(root), set())
        hits = sorted({n.id for n in _walk_skipping_none_checks(node.test)
                       if isinstance(n, ast.Name)
                       and isinstance(n.ctx, ast.Load)
                       and n.id in traced})
        if hits:
            out.append(_finding(
                analysis, "DCR007", node,
                f"Python branch on traced argument(s) {', '.join(hits)} "
                "inside a jitted function — concrete values raise at trace "
                "time and shape/flag values recompile per variant; mark the "
                "argument static (static_argnames) or use lax.cond/jnp.where"))
    return out


# ---------------------------------------------------------------------------
# DCR008 — wall-clock / global-RNG nondeterminism
# ---------------------------------------------------------------------------

_STDLIB_RANDOM_FNS = {
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "gauss", "normalvariate", "seed", "betavariate",
    "expovariate", "triangular", "vonmisesvariate", "paretovariate",
    "weibullvariate", "lognormvariate", "getrandbits", "randbytes",
}
# numpy.random attributes that are explicitly-seeded generator constructors
# (deterministic by construction) rather than the hidden global stream
_NP_RANDOM_SAFE = {
    "default_rng", "Generator", "RandomState", "SeedSequence", "BitGenerator",
    "PCG64", "PCG64DXSM", "MT19937", "Philox", "SFC64",
}
_WALL_CLOCK = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow", "datetime.date.today",
}


def check_dcr008(analysis: ModuleAnalysis) -> list[Finding]:
    out = []
    for node in ast.walk(analysis.tree):
        if not isinstance(node, ast.Call):
            continue
        resolved = analysis.resolve_call(node)
        if resolved is None:
            continue
        if resolved.startswith("numpy.random."):
            fn = resolved.split(".")[-1]
            if fn not in _NP_RANDOM_SAFE:
                out.append(_finding(
                    analysis, "DCR008", node,
                    f"np.random.{fn}() uses numpy's hidden global RNG state — "
                    "order-dependent and resume-unsafe; derive a Generator "
                    "from core.rng.host_python_rng(seed, stream)"))
        elif resolved.startswith("random.") and \
                resolved.split(".")[-1] in _STDLIB_RANDOM_FNS and \
                resolved.count(".") == 1:
            out.append(_finding(
                analysis, "DCR008", node,
                f"stdlib {resolved}() draws from process-global RNG state — "
                "nondeterministic under reordering/restart; use an explicit "
                "seeded stream (core/rng.py)"))
        elif resolved in _WALL_CLOCK and analysis.in_jit(node) is not None:
            out.append(_finding(
                analysis, "DCR008", node,
                f"{resolved}() inside a jitted function bakes the trace-time "
                "clock in as a constant — nondeterministic across "
                "compilations; pass times in as arguments"))
    return out


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Rule:
    rule_id: str
    title: str
    summary: str
    check: Callable[[ModuleAnalysis], list[Finding]]


RULES: dict[str, Rule] = {r.rule_id: r for r in [
    Rule("DCR001", "host-sync-in-jit",
         "host sync / tracer leak (.item(), np.*, device_get, casts) inside "
         "a jitted function", check_dcr001),
    Rule("DCR002", "donation-after-use",
         "argument named in donate_argnums is read after the donating call",
         check_dcr002),
    Rule("DCR003", "rng-key-reuse",
         "same RNG key consumed twice without split/fold_in", check_dcr003),
    Rule("DCR004", "unbounded-collective",
         "barrier/kv_allgather/allgather call without a timeout",
         check_dcr004),
    Rule("DCR005", "rank-divergent-collective",
         "collective issued under a process_index()==0-style conditional",
         check_dcr005),
    Rule("DCR006", "silent-exception-swallow",
         "broad `except: pass` with no log/counter/quarantine", check_dcr006),
    Rule("DCR007", "recompilation-hazard",
         "Python branching on traced arguments inside a jitted function "
         "without static_argnames", check_dcr007),
    Rule("DCR008", "nondeterminism",
         "global random.*/np.random.* state, or wall-clock reads traced "
         "into jit", check_dcr008),
]}

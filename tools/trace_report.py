"""trace_report: turn a run directory's trace.jsonl into answers.

    python -m tools.trace_report <run_dir> [--chrome out.json] [--json]

Reads every ``trace*.jsonl`` the run's processes wrote (core/tracing.py),
validates each record against the checked-in ``tools/trace_schema.json``,
and prints the report a perf investigation starts from:

- stage-time breakdown: wall time per span name and per category
  (data vs step vs ckpt vs eval vs serve), with p50/p99 per name;
- serve queue-wait percentiles (the ``serve/queue_wait`` spans) and
  recompile count per bucket (``serve/compile`` events);
- fault timeline: every ``fault/*`` event in chronological order, plus any
  flight-recorder dumps present in the directory.

``--chrome`` additionally writes a Chrome-trace JSON (``traceEvents`` array)
loadable in Perfetto / chrome://tracing. Exit codes: 0 = report produced,
1 = no trace records found, 2 = schema violations (the trace is corrupt or
a writer drifted from the schema — CI fails on this).

Pure stdlib on purpose (like tools/lint): runs on a bare checkout.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

SCHEMA_PATH = Path(__file__).resolve().parent / "trace_schema.json"

_TYPES = {
    "string": str,
    "integer": int,
    "number": (int, float),
    "object": dict,
    "integer_or_null": (int, type(None)),
}


def load_schema(path: Path = SCHEMA_PATH) -> dict:
    return json.loads(path.read_text())


def validate_record(rec: dict, schema: dict) -> list[str]:
    """Field-level problems with one record ([] = valid)."""
    problems = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]
    for field, tname in schema["required"].items():
        if field not in rec:
            problems.append(f"missing required field {field!r}")
        elif not isinstance(rec[field], _TYPES[tname]) or isinstance(rec[field], bool):
            problems.append(f"field {field!r} is {type(rec[field]).__name__}, "
                            f"want {tname}")
    ph = rec.get("ph")
    if ph not in schema["allowed_ph"]:
        problems.append(f"ph={ph!r} not in {schema['allowed_ph']}")
    if ph == "X":
        for field, tname in schema["span_required"].items():
            if field not in rec:
                problems.append(f"span missing required field {field!r}")
            elif not isinstance(rec[field], _TYPES[tname]):
                problems.append(f"span field {field!r} is "
                                f"{type(rec[field]).__name__}, want {tname}")
    for field, tname in schema.get("optional", {}).items():
        if field in rec and not isinstance(rec[field], _TYPES[tname]):
            problems.append(f"field {field!r} is {type(rec[field]).__name__}, "
                            f"want {tname}")
    return problems


def load_trace(run_dir: Path, schema: dict) -> tuple[list[dict], list[str]]:
    """(records, errors) across every trace*.jsonl under run_dir (all ranks)."""
    records: list[dict] = []
    errors: list[str] = []
    for path in sorted(run_dir.glob("trace*.jsonl")):
        for lineno, line in enumerate(path.read_text().splitlines(), 1):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                errors.append(f"{path.name}:{lineno}: not JSON ({e})")
                continue
            problems = validate_record(rec, schema)
            if problems:
                errors.append(f"{path.name}:{lineno}: " + "; ".join(problems))
                continue
            records.append(rec)
    records.sort(key=lambda r: r["ts"])
    return records, errors


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------

# span-name prefix -> report category (the "where did the time go" buckets)
_CATEGORIES = (
    ("train/data_wait", "data"),
    ("data/", "data"),
    ("train/step", "step"),
    ("ckpt/", "ckpt"),
    ("stage/eval", "eval"),
    ("serve/", "serve"),
    ("stage/", "stage"),
    ("train/", "train"),
)


def category_of(name: str) -> str:
    for prefix, cat in _CATEGORIES:
        if name.startswith(prefix):
            return cat
    return name.split("/", 1)[0]


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile without numpy (stdlib-only tool)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q / 100 * (len(sorted_vals) - 1))))
    return sorted_vals[int(idx)]


def summarize(records: list[dict]) -> dict:
    """The report document (also the --json output)."""
    spans = [r for r in records if r["ph"] == "X"]
    events = [r for r in records if r["ph"] == "i"]
    by_name: dict[str, list[float]] = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s["dur"] / 1e3)  # ms
    names = {}
    categories: dict[str, dict] = {}
    for name, durs in sorted(by_name.items()):
        durs_sorted = sorted(durs)
        row = {
            "count": len(durs),
            "total_ms": round(sum(durs), 3),
            "mean_ms": round(sum(durs) / len(durs), 3),
            "p50_ms": round(_percentile(durs_sorted, 50), 3),
            "p99_ms": round(_percentile(durs_sorted, 99), 3),
        }
        names[name] = row
        cat = categories.setdefault(category_of(name), {"count": 0, "total_ms": 0.0})
        cat["count"] += row["count"]
        cat["total_ms"] = round(cat["total_ms"] + row["total_ms"], 3)

    queue_waits = sorted(by_name.get("serve/queue_wait", []))
    queue_wait = {
        "count": len(queue_waits),
        "p50_ms": round(_percentile(queue_waits, 50), 3),
        "p90_ms": round(_percentile(queue_waits, 90), 3),
        "p99_ms": round(_percentile(queue_waits, 99), 3),
    } if queue_waits else None

    recompiles: dict[str, int] = {}
    for e in events:
        if e["name"] == "serve/compile":
            bucket = str(e["args"].get("bucket", "?"))
            recompiles[bucket] = recompiles.get(bucket, 0) + 1

    faults = [{
        "time": time.strftime("%H:%M:%S", time.localtime(e["ts"] / 1e6)),
        "ts": e["ts"],
        "rank": e["pid"],
        "name": e["name"],
        "args": e["args"],
    } for e in events if e["name"].startswith("fault/")]

    ranks = sorted({r["pid"] for r in records})
    span_ts = [s["ts"] for s in spans]
    return {
        "records": len(records),
        "spans": len(spans),
        "events": len(events),
        "ranks": ranks,
        "wall_span_s": (round((max(span_ts) - min(span_ts)) / 1e6, 3)
                        if span_ts else 0.0),
        "categories": categories,
        "by_name": names,
        "serve_queue_wait": queue_wait,
        "serve_recompiles_per_bucket": recompiles,
        "fault_timeline": faults,
    }


def chrome_trace(records: list[dict]) -> dict:
    """Chrome-trace/Perfetto document: spans -> complete ('X') events, instants
    -> 'i' events with thread scope, plus thread_name metadata so Perfetto
    labels rows with real thread names instead of idents."""
    out = []
    seen_threads = set()
    for r in records:
        key = (r["pid"], r["tid"])
        if key not in seen_threads:
            seen_threads.add(key)
            out.append({"ph": "M", "name": "thread_name", "pid": r["pid"],
                        "tid": r["tid"], "args": {"name": r["tname"]}})
        ev = {"ph": r["ph"], "name": r["name"], "ts": r["ts"],
              "pid": r["pid"], "tid": r["tid"], "cat": category_of(r["name"]),
              "args": dict(r["args"], id=r["id"], parent=r.get("parent"))}
        if r["ph"] == "X":
            ev["dur"] = r["dur"]
        else:
            ev["s"] = "t"
        out.append(ev)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def render_text(summary: dict, run_dir: Path) -> str:
    lines = [f"trace report: {run_dir}",
             f"  {summary['spans']} spans / {summary['events']} events "
             f"from ranks {summary['ranks']} over {summary['wall_span_s']}s"]
    lines.append("\nstage-time breakdown (host wall time per category):")
    total = sum(c["total_ms"] for c in summary["categories"].values()) or 1.0
    for cat, row in sorted(summary["categories"].items(),
                           key=lambda kv: -kv[1]["total_ms"]):
        lines.append(f"  {cat:<8} {row['total_ms']:>12.1f} ms  "
                     f"({100 * row['total_ms'] / total:5.1f}%)  "
                     f"x{row['count']}")
    lines.append("\nper-span-name:")
    for name, row in sorted(summary["by_name"].items(),
                            key=lambda kv: -kv[1]["total_ms"]):
        lines.append(f"  {name:<24} x{row['count']:<6} total "
                     f"{row['total_ms']:>10.1f} ms  mean {row['mean_ms']:>8.2f}  "
                     f"p50 {row['p50_ms']:>8.2f}  p99 {row['p99_ms']:>8.2f}")
    if summary["serve_queue_wait"]:
        q = summary["serve_queue_wait"]
        lines.append(f"\nserve queue wait: x{q['count']}  p50 {q['p50_ms']} ms  "
                     f"p90 {q['p90_ms']} ms  p99 {q['p99_ms']} ms")
    if summary["serve_recompiles_per_bucket"]:
        lines.append("serve compiles per bucket:")
        for bucket, n in sorted(summary["serve_recompiles_per_bucket"].items()):
            lines.append(f"  {n}x {bucket}")
    if summary["fault_timeline"]:
        lines.append("\nfault timeline:")
        for f in summary["fault_timeline"]:
            lines.append(f"  {f['time']} r{f['rank']} {f['name']} {f['args']}")
    else:
        lines.append("\nfault timeline: clean (no fault/* events)")
    flightrecs = sorted(run_dir.glob("flightrec_*.json"))
    if flightrecs:
        lines.append("flight-recorder dumps:")
        for p in flightrecs:
            try:
                reason = json.loads(p.read_text()).get("reason", "?")
            except (OSError, json.JSONDecodeError) as e:
                reason = f"<unreadable: {e}>"
            lines.append(f"  {p.name}: {reason}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.trace_report",
        description="Stage-time breakdown + fault timeline from a run's "
                    "trace.jsonl; optional Chrome-trace export.")
    ap.add_argument("run_dir", type=Path,
                    help="directory holding trace*.jsonl (a run's output_dir "
                         "or a serve --logdir)")
    ap.add_argument("--chrome", type=Path, default=None, metavar="OUT.json",
                    help="also write a Chrome-trace/Perfetto JSON export")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON instead of text")
    args = ap.parse_args(argv)

    if not args.run_dir.is_dir():
        print(f"trace_report: {args.run_dir} is not a directory", file=sys.stderr)
        return 1
    schema = load_schema()
    records, errors = load_trace(args.run_dir, schema)
    if errors:
        for e in errors[:20]:
            print(f"trace_report: SCHEMA: {e}", file=sys.stderr)
        print(f"trace_report: {len(errors)} invalid record(s)", file=sys.stderr)
        return 2
    if not records:
        print(f"trace_report: no trace records under {args.run_dir} "
              "(no trace*.jsonl, or all files empty)", file=sys.stderr)
        return 1
    summary = summarize(records)
    if args.chrome:
        args.chrome.write_text(json.dumps(chrome_trace(records)))
        print(f"trace_report: wrote chrome trace -> {args.chrome}", file=sys.stderr)
    print(json.dumps(summary, indent=1) if args.json
          else render_text(summary, args.run_dir))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""trace_report: turn trace.jsonl files — one process or a whole fleet —
into answers.

    python -m tools.trace_report <path> [<path> ...] [--chrome out.json]
                                 [--json]

Each path is a directory (searched RECURSIVELY for ``trace*.jsonl``, so a
fleet dir whose supervisor writes ``trace.jsonl`` and whose workers write
``worker_<i>/trace.jsonl`` merges in one invocation) or a single trace
file. Size-capped rotation segments (``trace.jsonl.1..N``, core/tracing.py)
are read oldest-first as part of their base file's stream. Every record is
validated against the checked-in ``tools/trace_schema.json``. The report:

- stage-time breakdown: wall time per span name and per category
  (data vs step vs ckpt vs eval vs serve), with p50/p99 per name;
- serve queue-wait percentiles (the ``serve/queue_wait`` spans) and
  recompile count per bucket (``serve/compile`` events);
- fault timeline: every ``fault/*`` event in chronological order, plus any
  flight-recorder dumps present in the directory;
- memory (dcr-hbm): resident-delta per stage and a peak timeline from the
  ``hbm_peak``/``hbm_delta`` attrs hot-region spans carry on backends with
  ``memory_stats()``, plus the compiled surfaces ranked by XLA temp bytes
  (``memwatch/surface_memory`` events);
- search (dcr-store): store-backed top-k segment-scan throughput
  (``search/topk`` spans), brute-force chunk time (``search/chunk``), and
  store ingestion volume (``search/ingest``);
- copy risk (dcr-watch): flagged-generation count, gen↔train similarity
  percentiles (from ``serve/risk_score`` / ``risk/score`` span ``sims``),
  the most-hit train keys, and a flagged-request timeline from
  ``risk/flagged`` events;
- fleet section (when spans carry distributed trace ids): per-file clock
  offsets anchored on supervisor ``fleet/dispatch`` ↔ worker
  ``serve/assemble`` pairs (a dispatch causally precedes its assemble, so a
  worker file whose assemble timestamps land before their dispatch is
  shifted forward by the largest violation — zero on one host), then one
  span tree per trace id across processes: connectivity, cross-process
  reach, requeue attempts, and partial spans left by attempts that died
  mid-flight.

``--chrome`` additionally writes a Chrome-trace JSON (``traceEvents`` array)
loadable in Perfetto / chrome://tracing, one track (pid) per source
process. ``--max-compiles N`` is the recompile budget (ROADMAP item 3): the
report counts XLA compiles (``serve/compile`` events + ``warmcache/compile``
spans) per PROCESS INCARNATION — streams tell respawns apart by the
``os_pid`` attr those records carry — and exits 3 when any incarnation
exceeds N, so a code change that silently introduces new recompiles (or a
respawn that should have been served from the persistent executable cache)
fails pre-merge. Exit codes: 0 = report produced (budget OK when given),
1 = no trace records found, 2 = schema violations (the trace is corrupt or
a writer drifted from the schema — CI fails on this), 3 = recompile budget
exceeded.

Pure stdlib on purpose (like tools/lint): runs on a bare checkout.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

SCHEMA_PATH = Path(__file__).resolve().parent / "trace_schema.json"

_TYPES = {
    "string": str,
    "integer": int,
    "number": (int, float),
    "object": dict,
    "integer_or_null": (int, type(None)),
}


def load_schema(path: Path = SCHEMA_PATH) -> dict:
    return json.loads(path.read_text())


def validate_record(rec: dict, schema: dict) -> list[str]:
    """Field-level problems with one record ([] = valid)."""
    problems = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]
    for field, tname in schema["required"].items():
        if field not in rec:
            problems.append(f"missing required field {field!r}")
        elif not isinstance(rec[field], _TYPES[tname]) or isinstance(rec[field], bool):
            problems.append(f"field {field!r} is {type(rec[field]).__name__}, "
                            f"want {tname}")
    ph = rec.get("ph")
    if ph not in schema["allowed_ph"]:
        problems.append(f"ph={ph!r} not in {schema['allowed_ph']}")
    if ph == "X":
        for field, tname in schema["span_required"].items():
            if field not in rec:
                problems.append(f"span missing required field {field!r}")
            elif not isinstance(rec[field], _TYPES[tname]):
                problems.append(f"span field {field!r} is "
                                f"{type(rec[field]).__name__}, want {tname}")
    for field, tname in schema.get("optional", {}).items():
        if field in rec and not isinstance(rec[field], _TYPES[tname]):
            problems.append(f"field {field!r} is {type(rec[field]).__name__}, "
                            f"want {tname}")
    return problems


def _rotation_index(path: Path) -> int:
    """0 for a base ``trace*.jsonl``, N for a rotated ``trace*.jsonl.N``."""
    suffix = path.name.rpartition(".jsonl")[2]
    return int(suffix[1:]) if suffix.startswith(".") else 0


def discover_streams(paths: list[Path]) -> list[tuple[str, list[Path]]]:
    """[(label, [files oldest-first])] — one stream per writing process.

    A stream is a base ``trace*.jsonl`` plus its size-rotation segments
    (``.1`` newest rotated … ``.N`` oldest), read oldest-first so records
    stay time-ordered per process. Directories are searched recursively
    (a fleet dir nests worker traces in ``worker_<i>/``); labels are the
    base file's path relative to the argument that found it."""
    streams: list[tuple[str, list[Path]]] = []
    seen: set[Path] = set()
    labels: set[str] = set()
    for arg in paths:
        bases = ([arg] if arg.is_file() else
                 sorted(p for p in arg.rglob("trace*.jsonl") if p.is_file()))
        for base in bases:
            base = base.resolve()
            if base in seen:
                continue
            seen.add(base)
            segments = sorted(
                (p for p in base.parent.glob(base.name + ".*")
                 if p.name[len(base.name) + 1:].isdigit()),
                key=_rotation_index, reverse=True)
            try:
                label = str(base.relative_to(arg.resolve())) \
                    if arg.is_dir() else str(arg)
            except ValueError:
                label = str(base)
            if label in labels:
                # two args with identical relative layouts (two fleet dirs):
                # labels must stay 1:1 with streams — clock offsets, per-tree
                # process sets and Chrome tracks all key on them
                label = f"{arg}:{label}"
            while label in labels:
                label += "'"
            labels.add(label)
            streams.append((label, segments + [base]))
    return streams


def _anchor_offsets(records: list[dict],
                    labels: list[str]) -> dict[str, int]:
    """Per-stream clock offset (microseconds to ADD) from dispatch↔assemble
    causality: a supervisor's ``fleet/dispatch`` span for a batch begins
    before any member's ``serve/assemble`` on the worker. A stream whose
    assemble starts earlier than its anchoring dispatch has a clock behind
    the supervisor's; shift it forward by the largest violation. Streams
    sharing a host clock (the common fleet-on-one-host case) get 0."""
    dispatches = [r for r in records
                  if r["ph"] == "X" and r["name"] == "fleet/dispatch"]
    if not dispatches:
        return {lab: 0 for lab in labels}
    by_trace: dict[str, int] = {}          # trace id -> earliest dispatch ts
    for d in dispatches:
        for t in d["args"].get("trace_ids") or []:
            if t is not None:
                by_trace[t] = min(by_trace.get(t, d["ts"]), d["ts"])
    offsets = {lab: 0 for lab in labels}
    for r in records:
        if r["ph"] != "X" or r["name"] != "serve/assemble":
            continue
        anchors = [by_trace[t] for t in (r["args"].get("trace_ids") or [])
                   if t in by_trace]
        if anchors:
            violation = min(anchors) - r["ts"]
            offsets[r["_plabel"]] = max(offsets[r["_plabel"]], violation)
    return offsets


def load_fleet(paths: list[Path],
               schema: dict) -> tuple[list[dict], list[str], dict]:
    """(records, errors, meta) across every stream under ``paths``.

    Each record gains ``_proc`` (stream index — the Chrome-export pid, since
    fleet processes are all jax rank 0) and ``_plabel`` (stream label);
    timestamps are clock-offset-adjusted per stream (see
    :func:`_anchor_offsets`). ``meta`` carries the stream labels and the
    applied offsets."""
    records: list[dict] = []
    errors: list[str] = []
    streams = discover_streams(paths)
    for proc, (label, files) in enumerate(streams):
        for path in files:
            for lineno, line in enumerate(path.read_text().splitlines(), 1):
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError as e:
                    errors.append(f"{path.name}:{lineno}: not JSON ({e})")
                    continue
                problems = validate_record(rec, schema)
                if problems:
                    errors.append(f"{path.name}:{lineno}: "
                                  + "; ".join(problems))
                    continue
                rec["_proc"] = proc
                rec["_plabel"] = label
                records.append(rec)
    labels = [label for label, _ in streams]
    offsets = _anchor_offsets(records, labels)
    for rec in records:
        rec["ts"] += offsets[rec["_plabel"]]
    records.sort(key=lambda r: r["ts"])
    meta = {"processes": labels,
            "clock_offset_us": {k: v for k, v in offsets.items() if v}}
    return records, errors, meta


def load_trace(run_dir: Path, schema: dict) -> tuple[list[dict], list[str]]:
    """(records, errors) across every trace*.jsonl under run_dir (all ranks,
    rotated segments included). Compatibility wrapper over load_fleet."""
    records, errors, _ = load_fleet([run_dir], schema)
    return records, errors


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------

# span-name prefix -> report category (the "where did the time go" buckets)
_CATEGORIES = (
    ("train/data_wait", "data"),
    ("data/", "data"),
    ("train/step", "step"),
    ("ckpt/", "ckpt"),
    ("stage/eval", "eval"),
    ("serve/risk_score", "risk"),
    ("serve/", "serve"),
    ("stage/", "stage"),
    ("train/", "train"),
    ("risk/", "risk"),
    ("search/", "search"),
    ("ingest/", "ingest"),
)


def category_of(name: str) -> str:
    for prefix, cat in _CATEGORIES:
        if name.startswith(prefix):
            return cat
    return name.split("/", 1)[0]


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile without numpy (stdlib-only tool)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, round(q / 100 * (len(sorted_vals) - 1))))
    return sorted_vals[int(idx)]


def assemble_trace_trees(records: list[dict]) -> list[dict]:
    """One document per distributed trace id: the cross-process span tree.

    Span ids are process-local (core/tracing.py counts from 1 in every
    process), so tree edges resolve per stream: a span's ``parent`` points
    within its own file, while a worker's ``serve/request`` root crosses
    streams via ``args.remote_parent`` — the supervisor root span id shipped
    in the wire context. A trace is **connected** when exactly one global
    root exists and every remote_parent reference names it. Spans whose
    parent was never written (an attempt SIGKILLed mid-batch emits children
    before its root ends) are counted as ``orphan_spans`` — expected debris
    of a crashed attempt, attributed to the trace by id but outside the
    tree."""
    spans = [r for r in records if r["ph"] == "X" and r.get("trace")]
    by_trace: dict[str, list[dict]] = {}
    for s in spans:
        by_trace.setdefault(s["trace"], []).append(s)
    trees = []
    for trace_id, group in sorted(by_trace.items()):
        roots = [s for s in group if s["parent"] is None
                 and s["args"].get("remote_parent") is None]
        remote_roots = [s for s in group
                        if s["args"].get("remote_parent") is not None]
        root = roots[0] if len(roots) == 1 else None
        links_ok = root is not None and all(
            r["args"]["remote_parent"] == root["id"]
            and r["_proc"] != root["_proc"] for r in remote_roots)
        # reachability: same-process parent edges + the remote hops
        anchored_keys: set[tuple[int, int]] = set()
        if root is not None:
            frontier = [root] + (remote_roots if links_ok else [])
            anchored_keys = {(s["_proc"], s["id"]) for s in frontier}
            grew = True
            while grew:
                grew = False
                for s in group:
                    key = (s["_proc"], s["id"])
                    if key in anchored_keys or s["parent"] is None:
                        continue
                    if (s["_proc"], s["parent"]) in anchored_keys:
                        anchored_keys.add(key)
                        grew = True
        anchored = len(anchored_keys)
        attempts = [int(s["args"].get("attempt", 1)) for s in remote_roots]
        trees.append({
            "trace": trace_id,
            "spans": len(group),
            "processes": sorted({s["_plabel"] for s in group}),
            "roots": len(roots),
            "connected": len(roots) == 1 and links_ok,
            "anchored_spans": anchored,
            "orphan_spans": len(group) - anchored,
            "attempts": max(attempts) if attempts else 1,
            "names": sorted({s["name"] for s in group}),
        })
    return trees


# per-trace tree documents kept in the summary/--json output. Aggregates
# cover everything; the individual docs are for drill-down, and a long
# single-process serve run (every request carries a trace id) would
# otherwise embed one doc per lifetime request.
_MAX_TREES = 50


def fleet_summary(records: list[dict], meta: dict) -> dict | None:
    """The distributed-trace section of the report (None when nothing
    carries a trace id — e.g. train/eval runs keep their old report shape).
    Aggregate counts cover every trace; ``trees`` lists the interesting ones
    first (disconnected, then requeued) capped at ``_MAX_TREES`` with the
    overflow counted in ``trees_truncated``."""
    trees = assemble_trace_trees(records)
    if not trees:
        return None
    shown = sorted(trees, key=lambda t: (t["connected"], -t["attempts"]))
    return {
        "processes": meta.get("processes", []),
        "clock_offset_us": meta.get("clock_offset_us", {}),
        "traces": len(trees),
        "connected": sum(t["connected"] for t in trees),
        "cross_process": sum(len(t["processes"]) > 1 for t in trees),
        "requeued": sum(t["attempts"] > 1 for t in trees),
        "max_attempts": max(t["attempts"] for t in trees),
        "orphan_spans": sum(t["orphan_spans"] for t in trees),
        "trees": shown[:_MAX_TREES],
        "trees_truncated": max(0, len(trees) - _MAX_TREES),
    }


def copy_risk_summary(records: list[dict]) -> dict | None:
    """The "Copy risk" section (dcr-watch): similarity percentiles from the
    per-row ``sims`` attr that ``serve/risk_score`` (serving) and
    ``risk/score`` (training sample grids) spans carry, plus the flagged
    timeline from ``risk/flagged`` events. None when nothing was scored —
    pre-dcr-watch traces keep their old report shape."""
    sims: list[float] = []
    for r in records:
        if r["ph"] == "X" and r["name"] in ("serve/risk_score", "risk/score"):
            sims.extend(float(s) for s in (r["args"].get("sims") or []))
    flagged = [r for r in records
               if r["ph"] == "i" and r["name"] == "risk/flagged"]
    if not sims and not flagged:
        return None
    sims_sorted = sorted(sims)
    top_keys: dict[str, int] = {}
    for e in flagged:
        key = str(e["args"].get("top_key", "?"))
        top_keys[key] = top_keys.get(key, 0) + 1
    timeline = [{
        "time": time.strftime("%H:%M:%S", time.localtime(e["ts"] / 1e6)),
        "ts": e["ts"],
        "request_id": e["args"].get("request_id"),
        "max_sim": e["args"].get("max_sim"),
        "top_key": e["args"].get("top_key"),
        "prompt": e["args"].get("prompt"),
    } for e in flagged]
    return {
        "scored": len(sims),
        "flagged": len(flagged),
        "sim_p50": round(_percentile(sims_sorted, 50), 6),
        "sim_p90": round(_percentile(sims_sorted, 90), 6),
        "sim_p99": round(_percentile(sims_sorted, 99), 6),
        "sim_max": round(sims_sorted[-1], 6) if sims_sorted else 0.0,
        "flagged_train_keys": dict(sorted(top_keys.items(),
                                          key=lambda kv: -kv[1])[:10]),
        "flagged_timeline": timeline[:50],
    }


def search_summary(records: list[dict]) -> dict | None:
    """The "Search" section (dcr-store): similarity-search time breakdown.

    Built from three span families: ``search/topk`` (the store-backed
    mesh-sharded query program — one span per segment scan, carrying
    ``rows`` and ``batch``), ``search/chunk`` (the brute-force per-folder
    matmul+host-merge path), and ``search/ingest`` (store shard writes).
    None when nothing searched/ingested — other traces keep their shape.
    """
    topk = [r for r in records
            if r["ph"] == "X" and r["name"] == "search/topk"]
    chunk = [r for r in records
             if r["ph"] == "X" and r["name"] == "search/chunk"]
    ingest = [r for r in records
              if r["ph"] == "X" and r["name"] == "search/ingest"]
    if not topk and not chunk and not ingest:
        return None
    out: dict = {}
    if topk:
        durs = sorted(r["dur"] / 1e3 for r in topk)
        rows = sum(int(r["args"].get("rows", 0)) for r in topk)
        total_ms = sum(durs)
        out["store_topk"] = {
            "segment_scans": len(topk),
            "rows_scanned": rows,
            "total_ms": round(total_ms, 3),
            "p50_ms": round(_percentile(durs, 50), 3),
            "p99_ms": round(_percentile(durs, 99), 3),
            "rows_per_s": round(rows / max(total_ms / 1e3, 1e-9)),
        }
    if chunk:
        durs = sorted(r["dur"] / 1e3 for r in chunk)
        out["brute_chunks"] = {
            "chunks": len(chunk),
            "total_ms": round(sum(durs), 3),
            "p50_ms": round(_percentile(durs, 50), 3),
            "p99_ms": round(_percentile(durs, 99), 3),
        }
    if ingest:
        out["ingest"] = {
            "shards": len(ingest),
            "rows": sum(int(r["args"].get("rows", 0)) for r in ingest),
            "total_ms": round(sum(r["dur"] for r in ingest) / 1e3, 3),
        }
    return out


def ann_summary(records: list[dict]) -> dict | None:
    """The "ANN" section (dcr-ann): IVF approximate-search health.

    Built from the ``search/ivf_scan`` spans (one per probed segment scan:
    nprobe, lists hit, segment rows), the ``search/ivf_rerank`` spans (the
    exact f32 re-rank of the shortlist union), the ``ann/query_funnel``
    events (the probe -> shortlist -> re-rank funnel per query chunk, plus
    the segment skip ratio — the sublinearity evidence), the ``search/
    kmeans`` spans (training Lloyd iterations), and the ``ann/
    recall_spot_check`` events (sampled recall vs the exact oracle). None
    when the ann tier never ran — other traces keep their shape.
    """
    scans = [r for r in records
             if r["ph"] == "X" and r["name"] == "search/ivf_scan"]
    reranks = [r for r in records
               if r["ph"] == "X" and r["name"] == "search/ivf_rerank"]
    kmeans = [r for r in records
              if r["ph"] == "X" and r["name"] == "search/kmeans"]
    funnels = [r for r in records
               if r["ph"] == "i" and r["name"] == "ann/query_funnel"]
    recalls = [r for r in records
               if r["ph"] == "i" and r["name"] == "ann/recall_spot_check"]
    if not scans and not kmeans and not funnels:
        return None
    out: dict = {}
    if scans:
        durs = sorted(r["dur"] / 1e3 for r in scans)
        nprobes: dict[str, int] = {}
        for r in scans:
            key = str(r["args"].get("nprobe", "?"))
            nprobes[key] = nprobes.get(key, 0) + 1
        out["scan"] = {
            "segment_scans": len(scans),
            "lists_scanned": sum(int(r["args"].get("lists", 0))
                                 for r in scans),
            "rows_scanned": sum(int(r["args"].get("rows", 0))
                                for r in scans),
            "total_ms": round(sum(durs), 3),
            "p50_ms": round(_percentile(durs, 50), 3),
            "p99_ms": round(_percentile(durs, 99), 3),
            "nprobe_distribution": dict(sorted(nprobes.items(),
                                               key=lambda kv: kv[0])),
        }
    if funnels:
        scanned = sum(int(e["args"].get("segments_scanned", 0))
                      for e in funnels)
        skipped = sum(int(e["args"].get("segments_skipped", 0))
                      for e in funnels)
        out["funnel"] = {
            "query_chunks": len(funnels),
            "queries": sum(int(e["args"].get("batch", 0)) for e in funnels),
            "lists_probed": sum(int(e["args"].get("lists_probed", 0))
                                for e in funnels),
            "shortlist_candidates": sum(int(e["args"].get("shortlist", 0))
                                        for e in funnels),
            "reranked_to_top_k": sum(
                int(e["args"].get("batch", 0)) * int(e["args"].get("top_k", 1))
                for e in funnels),
            "segments_scanned": scanned,
            "segments_skipped": skipped,
            "segment_skip_pct": round(
                100.0 * skipped / max(scanned + skipped, 1), 1),
        }
    if reranks:
        durs = sorted(r["dur"] / 1e3 for r in reranks)
        out["rerank"] = {
            "calls": len(reranks),
            "candidates": sum(int(r["args"].get("candidates", 0))
                              for r in reranks),
            "total_ms": round(sum(durs), 3),
            "p50_ms": round(_percentile(durs, 50), 3),
            "p99_ms": round(_percentile(durs, 99), 3),
        }
    if kmeans:
        restarts = max((int(r["args"].get("restart", 0)) for r in kmeans),
                       default=0)
        out["train"] = {
            "lloyd_iters": len(kmeans),
            "restarts": restarts,
            "total_ms": round(sum(r["dur"] for r in kmeans) / 1e3, 3),
        }
    if recalls:
        # sample-count-weighted (dcr-slo): a 256-query check must outweigh
        # a 4-query one — an unweighted mean of check means is not a recall
        vals = sorted(float(e["args"].get("recall", 0.0)) for e in recalls)
        weighted = sum(float(e["args"].get("recall", 0.0))
                       * max(1, int(e["args"].get("queries", 1)))
                       for e in recalls)
        samples = sum(max(1, int(e["args"].get("queries", 1)))
                      for e in recalls)
        out["recall_spot_checks"] = {
            "checks": len(recalls),
            "k": int(recalls[-1]["args"].get("k", 0)),
            "samples": samples,
            "min_recall": round(vals[0], 4),
            "mean_recall": round(weighted / samples, 4),
        }
    probes = [r for r in records
              if r["ph"] == "i" and r["name"] == "ann/recall_probe"]
    if probes:
        weighted = sum(float(e["args"].get("recall", 0.0))
                       * max(1, int(e["args"].get("queries", 1)))
                       for e in probes)
        samples = sum(max(1, int(e["args"].get("queries", 1)))
                      for e in probes)
        last = probes[-1]["args"]
        out["recall_online"] = {
            "probes": len(probes),
            "k": int(last.get("k", 0)),
            "samples": samples,
            "mean_recall": round(weighted / samples, 4),
            "last_rolling": round(float(last.get("rolling", 0.0)), 4),
        }
    return out


def _fmt_ts(ts_us: float) -> str:
    return time.strftime("%H:%M:%S", time.localtime(ts_us / 1e6))


def ingest_summary(records: list[dict]) -> dict | None:
    """The "Ingest" section (dcr-live): streaming-provenance health.

    Built from the ``ingest/append`` spans (WAL append throughput + fsync
    latency percentiles), the ``ingest/compact`` spans (the compaction
    timeline: rows folded, snapshot published, duration), and the
    ``ingest/recover`` spans + ``ingest/recovered`` events (what a restart
    replayed, how many torn tails it truncated). None when nothing
    ingested — other traces keep their shape.
    """
    appends = [r for r in records
               if r["ph"] == "X" and r["name"] == "ingest/append"]
    compacts = [r for r in records
                if r["ph"] == "X" and r["name"] == "ingest/compact"]
    recovers = [r for r in records
                if r["ph"] == "X" and r["name"] == "ingest/recover"]
    if not appends and not compacts and not recovers:
        return None
    out: dict = {}
    if appends:
        durs = sorted(r["dur"] / 1e3 for r in appends)
        rows = sum(int(r["args"].get("rows", 0)) for r in appends)
        wall_s = (max(r["ts"] + r["dur"] for r in appends)
                  - min(r["ts"] for r in appends)) / 1e6
        out["append"] = {
            "records": len(appends),
            "rows": rows,
            "total_ms": round(sum(durs), 3),
            "p50_ms": round(_percentile(durs, 50), 3),
            "p99_ms": round(_percentile(durs, 99), 3),
            "rows_per_s": round(rows / max(wall_s, 1e-9)),
        }
    if compacts:
        out["compactions"] = [
            {"time": _fmt_ts(r["ts"]),
             "rows": int(r["args"].get("rows", 0)),
             "records": int(r["args"].get("records", 0)),
             "snapshot": r["args"].get("snapshot"),
             "ms": round(r["dur"] / 1e3, 3)}
            for r in sorted(compacts, key=lambda r: r["ts"])][:50]
    if recovers:
        out["recoveries"] = [
            {"time": _fmt_ts(r["ts"]),
             "rows": int(r["args"].get("rows", 0)),
             "torn": int(r["args"].get("torn", 0)),
             "segments": int(r["args"].get("segments", 0)),
             "ms": round(r["dur"] / 1e3, 3)}
            for r in sorted(recovers, key=lambda r: r["ts"])][:50]
    return out


def slo_summary(records: list[dict]) -> dict | None:
    """The "SLO" section (dcr-slo): breach/recover timeline per objective.

    Built from the ``slo/breach`` and ``slo/recover`` instant events the
    supervisor-side engine emits on every state transition. Each breach is
    paired with the next recover of the same objective so the rendered
    timeline shows breach duration; an unrecovered breach is marked open.
    None when no SLO events — other traces keep their shape.
    """
    transitions = sorted((r for r in records if r["ph"] == "i"
                          and r["name"] in ("slo/breach", "slo/recover")),
                         key=lambda r: r["ts"])
    if not transitions:
        return None
    objectives: dict[str, dict] = {}
    timeline = []
    open_breach: dict[str, dict] = {}
    for r in transitions:
        obj = str(r["args"].get("objective", "?"))
        st = objectives.setdefault(obj, {"breaches": 0, "recoveries": 0})
        entry = {
            "time": _fmt_ts(r["ts"]), "ts": r["ts"],
            "event": r["name"].split("/", 1)[1],
            "objective": obj,
            "value": r["args"].get("value"),
            "target": r["args"].get("target"),
        }
        if r["name"] == "slo/breach":
            st["breaches"] += 1
            entry["burn"] = r["args"].get("burn_short")
            open_breach[obj] = entry
        else:
            st["recoveries"] += 1
            entry["breach_s"] = r["args"].get("breach_s")
            open_breach.pop(obj, None)
        timeline.append(entry)
    return {
        "objectives": dict(sorted(objectives.items())),
        "open_breaches": sorted(open_breach),
        "timeline": timeline[:100],
    }


def _interval_overlap_us(a: list[tuple[float, float]],
                         b: list[tuple[float, float]]) -> float:
    """Total pairwise intersection of two interval lists (start, end),
    linear merge over the sorted lists — the encode-vs-denoise overlap."""
    a = sorted(a)
    b = sorted(b)
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def pipeline_summary(records: list[dict]) -> dict | None:
    """The "Pipeline" section (dcr-pipe): how well the frozen-encoder
    producer stage overlaps the denoiser hot loop. Built from the
    ``train/encode`` spans (producer thread), ``train/step`` spans (the
    denoiser in pipelined runs), and ``train/encode_wait`` spans (the train
    thread blocked on the prefetch ring — the pipeline bubble). None when
    nothing was pipelined — fused traces keep their old report shape.

    - ``bubble_pct``: encode_wait time over (encode_wait + step) time — the
      fraction of the hot loop spent stalled on the producer;
    - ``overlap_pct``: wall-clock intersection of encode spans with step
      spans over total encode time — how much encoder work genuinely hid
      behind the denoiser (≈0 on a single-core host, where the win comes
      from the latent cache instead);
    - ``data_wait``: the producer's own stall on the host loader, to tell a
      loader-bound pipeline from an encode-bound one.
    """
    encode = [r for r in records
              if r["ph"] == "X" and r["name"] == "train/encode"]
    if not encode:
        return None
    waits = [r["dur"] / 1e3 for r in records
             if r["ph"] == "X" and r["name"] == "train/encode_wait"]
    steps = [r for r in records
             if r["ph"] == "X" and r["name"] == "train/step"]
    data_waits = [r["dur"] / 1e3 for r in records
                  if r["ph"] == "X" and r["name"] == "train/data_wait"]
    encode_ms = sum(r["dur"] for r in encode) / 1e3
    step_ms = sum(r["dur"] for r in steps) / 1e3
    wait_ms = sum(waits)
    overlap_ms = _interval_overlap_us(
        [(r["ts"], r["ts"] + r["dur"]) for r in encode],
        [(r["ts"], r["ts"] + r["dur"]) for r in steps]) / 1e3
    waits_sorted = sorted(waits)
    return {
        "encoded_batches": len(encode),
        "encode_total_ms": round(encode_ms, 3),
        "denoise_total_ms": round(step_ms, 3),
        "encode_wait_total_ms": round(wait_ms, 3),
        "data_wait_total_ms": round(sum(data_waits), 3),
        "bubble_pct": round(100 * wait_ms / max(wait_ms + step_ms, 1e-9), 2),
        "overlap_ms": round(overlap_ms, 3),
        "overlap_pct": round(100 * overlap_ms / max(encode_ms, 1e-9), 2),
        "encode_wait_p50_ms": round(_percentile(waits_sorted, 50), 3),
        "encode_wait_p99_ms": round(_percentile(waits_sorted, 99), 3),
    }


def memory_summary(records: list[dict]) -> dict | None:
    """The "Memory" section (dcr-hbm): where the device memory went.

    Built from two record families: hot-region spans carrying
    ``hbm_peak``/``hbm_delta`` attrs (``train/step``, ``train/encode``,
    ``serve/device_step`` — obs/memwatch.span_hbm; only emitted on backends
    with real ``memory_stats()``), and ``memwatch/surface_memory`` events
    (one per AOT-compiled surface, carrying its XLA memory analysis).
    None when nothing carries memory info — CPU-backend traces keep their
    pre-dcr-hbm report shape.

    - ``resident_delta_by_stage``: summed ``hbm_delta`` per span name — the
      stages that grew (or released) resident memory;
    - ``peak_timeline``: the last 50 ``hbm_peak`` samples in time order —
      how the high-water mark moved across the run;
    - ``top_surfaces_by_temp_bytes``: the compiled programs ranked by XLA
      temp (scratch) bytes — the first place to look when a peak says the
      device is fuller than the params explain.
    """
    spans = [r for r in records
             if r["ph"] == "X" and "hbm_peak" in r["args"]]
    surfaces: dict[str, dict] = {}
    for r in records:
        if r["ph"] == "i" and r["name"] == "memwatch/surface_memory":
            label = (f"{r['args'].get('surface', '?')}"
                     f"@{str(r['args'].get('key', ''))[:8]}")
            surfaces[label] = r["args"]
    if not spans and not surfaces:
        return None
    by_stage: dict[str, dict] = {}
    for s in sorted(spans, key=lambda r: r["ts"]):
        row = by_stage.setdefault(
            s["name"], {"count": 0, "delta_bytes": 0, "peak_bytes": 0})
        row["count"] += 1
        row["delta_bytes"] += int(s["args"].get("hbm_delta", 0))
        row["peak_bytes"] = max(row["peak_bytes"],
                                int(s["args"].get("hbm_peak", 0)))
    timeline = [{"ts": s["ts"], "peak_bytes": int(s["args"]["hbm_peak"])}
                for s in sorted(spans, key=lambda r: r["ts"])][-50:]
    top = sorted(
        surfaces.items(),
        key=lambda kv: -(kv[1].get("temp_bytes") or 0))[:10]
    return {
        "sampled_spans": len(spans),
        # over ALL spans, not the truncated timeline: in a merged fleet
        # trace the process that peaked highest may have died early, and
        # its samples must not fall out of the headline number
        "peak_bytes": max((int(s["args"]["hbm_peak"]) for s in spans),
                          default=0),
        "resident_delta_by_stage": by_stage,
        "peak_timeline": timeline,
        "surfaces": len(surfaces),
        "top_surfaces_by_temp_bytes": [{
            "surface": label,
            "temp_bytes": mem.get("temp_bytes"),
            "argument_bytes": mem.get("argument_bytes"),
            "output_bytes": mem.get("output_bytes"),
            "total_bytes": mem.get("total_bytes"),
        } for label, mem in top],
    }


def fast_sampling_summary(records: list[dict]) -> dict | None:
    """The "Fast sampling" section (dcr-fast): denoiser-call reduction from
    ``sample/fast`` spans — one per accelerated batch EXECUTION, carrying
    the static ``steps`` (solver steps taken) and ``unet_calls`` (denoiser
    calls actually made) of its plan plus ``batch`` (trajectories sharing
    it: the plan is batch-uniform, so per-trajectory totals are the span
    numbers weighted by batch). None when nothing ran fast — dense traces
    keep their pre-fast report shape."""
    spans = [r for r in records
             if r["ph"] == "X" and r["name"] == "sample/fast"]
    rows = []
    for s in spans:
        steps = s["args"].get("steps")
        calls = s["args"].get("unet_calls")
        batch = s["args"].get("batch")
        if isinstance(steps, int) and isinstance(calls, int) and steps > 0:
            rows.append((steps, calls,
                         batch if isinstance(batch, int) and batch > 0
                         else 1))
    if not rows:
        return None
    total_steps = sum(s * b for s, _, b in rows)
    total_calls = sum(c * b for _, c, b in rows)
    # calls-saved histogram: how many trajectories skipped how many calls
    saved_hist: dict[str, int] = {}
    for steps, calls, batch in rows:
        key = str(steps - calls)
        saved_hist[key] = saved_hist.get(key, 0) + batch
    return {
        "executions": len(rows),
        "trajectories": sum(b for _, _, b in rows),
        "steps_total": total_steps,
        "unet_calls_total": total_calls,
        "calls_saved_total": total_steps - total_calls,
        "call_reduction": round(total_steps / max(1, total_calls), 3),
        "calls_saved_histogram": dict(sorted(saved_hist.items(),
                                             key=lambda kv: int(kv[0]))),
    }


def compiles_per_incarnation(records: list[dict]) -> dict[str, int]:
    """XLA compiles per PROCESS INCARNATION — the recompile-budget unit.

    A respawned worker appends to the same per-stream trace file, so
    incarnations within a stream are told apart by the ``os_pid`` attr that
    ``serve/compile`` events and ``warmcache/compile`` spans carry (each
    respawn is a fresh pid). Per group the count is
    ``max(warmcache/compile spans, serve/compile events)``: on dcr-warm
    streams every real compile produces a warmcache span (bucket compiles
    additionally emit the serve event — counting both would double-bill),
    while pre-dcr-warm traces have only the events.
    ``warmcache/load_compile`` spans (an export-tier cache entry's
    compile-on-load) count too: they are real XLA compiles, and excluding
    them would let a broken executable tier pass a ``--max-compiles 0``
    gate while every boot silently recompiles."""
    spans: dict[str, int] = {}
    events: dict[str, int] = {}
    for r in records:
        if r["ph"] == "X" and r["name"] in ("warmcache/compile",
                                            "warmcache/load_compile"):
            bucket = spans
        elif r["ph"] == "i" and r["name"] == "serve/compile":
            bucket = events
        else:
            continue
        key = f"{r['_plabel']}@pid{r['args'].get('os_pid', '?')}"
        bucket[key] = bucket.get(key, 0) + 1
    return {k: max(spans.get(k, 0), events.get(k, 0))
            for k in sorted(set(spans) | set(events))}


def summarize(records: list[dict], meta: dict | None = None) -> dict:
    """The report document (also the --json output)."""
    spans = [r for r in records if r["ph"] == "X"]
    events = [r for r in records if r["ph"] == "i"]
    by_name: dict[str, list[float]] = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s["dur"] / 1e3)  # ms
    names = {}
    categories: dict[str, dict] = {}
    for name, durs in sorted(by_name.items()):
        durs_sorted = sorted(durs)
        row = {
            "count": len(durs),
            "total_ms": round(sum(durs), 3),
            "mean_ms": round(sum(durs) / len(durs), 3),
            "p50_ms": round(_percentile(durs_sorted, 50), 3),
            "p99_ms": round(_percentile(durs_sorted, 99), 3),
        }
        names[name] = row
        cat = categories.setdefault(category_of(name), {"count": 0, "total_ms": 0.0})
        cat["count"] += row["count"]
        cat["total_ms"] = round(cat["total_ms"] + row["total_ms"], 3)

    queue_waits = sorted(by_name.get("serve/queue_wait", []))
    queue_wait = {
        "count": len(queue_waits),
        "p50_ms": round(_percentile(queue_waits, 50), 3),
        "p90_ms": round(_percentile(queue_waits, 90), 3),
        "p99_ms": round(_percentile(queue_waits, 99), 3),
    } if queue_waits else None

    recompiles: dict[str, int] = {}
    for e in events:
        if e["name"] == "serve/compile":
            bucket = str(e["args"].get("bucket", "?"))
            recompiles[bucket] = recompiles.get(bucket, 0) + 1

    faults = [{
        "time": time.strftime("%H:%M:%S", time.localtime(e["ts"] / 1e6)),
        "ts": e["ts"],
        "rank": e["pid"],
        "name": e["name"],
        "args": e["args"],
    } for e in events if e["name"].startswith("fault/")]

    ranks = sorted({r["pid"] for r in records})
    span_ts = [s["ts"] for s in spans]
    return {
        "records": len(records),
        "spans": len(spans),
        "events": len(events),
        "ranks": ranks,
        "wall_span_s": (round((max(span_ts) - min(span_ts)) / 1e6, 3)
                        if span_ts else 0.0),
        "categories": categories,
        "by_name": names,
        "serve_queue_wait": queue_wait,
        "serve_recompiles_per_bucket": recompiles,
        "compiles_per_incarnation": compiles_per_incarnation(records),
        "copy_risk": copy_risk_summary(records),
        "search": search_summary(records),
        "ann": ann_summary(records),
        "ingest": ingest_summary(records),
        "fast_sampling": fast_sampling_summary(records),
        "pipeline": pipeline_summary(records),
        "memory": memory_summary(records),
        "fault_timeline": faults,
        "slo": slo_summary(records),
        "fleet": fleet_summary(records, meta or {}),
    }


def chrome_trace(records: list[dict]) -> dict:
    """Chrome-trace/Perfetto document: spans -> complete ('X') events, instants
    -> 'i' events with thread scope, plus process_name/thread_name metadata —
    one track (pid) per SOURCE PROCESS (stream), since fleet supervisor and
    workers are all jax rank 0 and would otherwise collapse onto one row."""
    out = []
    seen_procs = set()
    seen_threads = set()
    for r in records:
        pid = r.get("_proc", r["pid"])
        if pid not in seen_procs:
            seen_procs.add(pid)
            out.append({"ph": "M", "name": "process_name", "pid": pid,
                        "tid": 0,
                        "args": {"name": r.get("_plabel", f"rank {r['pid']}")}})
        key = (pid, r["tid"])
        if key not in seen_threads:
            seen_threads.add(key)
            out.append({"ph": "M", "name": "thread_name", "pid": pid,
                        "tid": r["tid"], "args": {"name": r["tname"]}})
        ev = {"ph": r["ph"], "name": r["name"], "ts": r["ts"],
              "pid": pid, "tid": r["tid"], "cat": category_of(r["name"]),
              "args": dict(r["args"], id=r["id"], parent=r.get("parent"),
                           **({"trace": r["trace"]} if r.get("trace")
                              else {}))}
        if r["ph"] == "X":
            ev["dur"] = r["dur"]
        else:
            ev["s"] = "t"
        out.append(ev)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def render_text(summary: dict, paths: list[Path] | Path) -> str:
    paths = [paths] if isinstance(paths, Path) else list(paths)
    lines = [f"trace report: {', '.join(map(str, paths))}",
             f"  {summary['spans']} spans / {summary['events']} events "
             f"from ranks {summary['ranks']} over {summary['wall_span_s']}s"]
    fleet = summary.get("fleet")
    if fleet:
        lines.append(
            f"\nfleet: {fleet['traces']} distributed trace(s) across "
            f"{len(fleet['processes'])} process file(s) — "
            f"{fleet['connected']} connected, "
            f"{fleet['cross_process']} cross-process, "
            f"{fleet['requeued']} requeued (max attempt "
            f"{fleet['max_attempts']}), "
            f"{fleet['orphan_spans']} orphan span(s) from dead attempts")
        for lab, off in sorted(fleet["clock_offset_us"].items()):
            lines.append(f"  clock offset {lab}: +{off} us "
                         "(anchored on dispatch<->assemble)")
        broken = [t for t in fleet["trees"] if not t["connected"]]
        for t in broken[:10]:
            lines.append(f"  DISCONNECTED trace {t['trace']}: "
                         f"{t['roots']} root(s), spans {t['names']}")
    lines.append("\nstage-time breakdown (host wall time per category):")
    total = sum(c["total_ms"] for c in summary["categories"].values()) or 1.0
    for cat, row in sorted(summary["categories"].items(),
                           key=lambda kv: -kv[1]["total_ms"]):
        lines.append(f"  {cat:<8} {row['total_ms']:>12.1f} ms  "
                     f"({100 * row['total_ms'] / total:5.1f}%)  "
                     f"x{row['count']}")
    lines.append("\nper-span-name:")
    for name, row in sorted(summary["by_name"].items(),
                            key=lambda kv: -kv[1]["total_ms"]):
        lines.append(f"  {name:<24} x{row['count']:<6} total "
                     f"{row['total_ms']:>10.1f} ms  mean {row['mean_ms']:>8.2f}  "
                     f"p50 {row['p50_ms']:>8.2f}  p99 {row['p99_ms']:>8.2f}")
    if summary["serve_queue_wait"]:
        q = summary["serve_queue_wait"]
        lines.append(f"\nserve queue wait: x{q['count']}  p50 {q['p50_ms']} ms  "
                     f"p90 {q['p90_ms']} ms  p99 {q['p99_ms']} ms")
    if summary["serve_recompiles_per_bucket"]:
        lines.append("serve compiles per bucket:")
        for bucket, n in sorted(summary["serve_recompiles_per_bucket"].items()):
            lines.append(f"  {n}x {bucket}")
    if summary.get("compiles_per_incarnation"):
        lines.append("XLA compiles per process incarnation:")
        for inc, n in summary["compiles_per_incarnation"].items():
            lines.append(f"  {n}x {inc}")
    pipe = summary.get("pipeline")
    if pipe:
        lines.append(
            f"\npipeline: {pipe['encoded_batches']} batch(es) through the "
            f"encoder producer — bubble {pipe['bubble_pct']}% "
            f"(encode_wait {pipe['encode_wait_total_ms']} ms vs denoise "
            f"{pipe['denoise_total_ms']} ms), encode-vs-denoise overlap "
            f"{pipe['overlap_pct']}% of {pipe['encode_total_ms']} ms encode")
        lines.append(
            f"  encode_wait p50 {pipe['encode_wait_p50_ms']} ms  "
            f"p99 {pipe['encode_wait_p99_ms']} ms  "
            f"producer data_wait {pipe['data_wait_total_ms']} ms")
    fast = summary.get("fast_sampling")
    if fast:
        lines.append(
            f"\nfast sampling: {fast['trajectories']} trajectory(ies) in "
            f"{fast['executions']} execution(s) — "
            f"{fast['unet_calls_total']} UNet calls for "
            f"{fast['steps_total']} solver steps "
            f"({fast['call_reduction']}x fewer calls, "
            f"{fast['calls_saved_total']} saved)")
        for saved, count in fast["calls_saved_histogram"].items():
            lines.append(f"  {count}x trajectories saved {saved} call(s)")
    mem = summary.get("memory")
    if mem:
        lines.append(
            f"\nmemory: peak {mem['peak_bytes']} bytes across "
            f"{mem['sampled_spans']} sampled span(s), "
            f"{mem['surfaces']} compiled surface(s) accounted")
        for name, row in sorted(mem["resident_delta_by_stage"].items(),
                                key=lambda kv: -abs(kv[1]["delta_bytes"])):
            lines.append(f"  {name:<24} x{row['count']:<6} resident delta "
                         f"{row['delta_bytes']:+d} B  peak "
                         f"{row['peak_bytes']} B")
        for s in mem["top_surfaces_by_temp_bytes"][:5]:
            lines.append(f"  surface {s['surface']:<40} temp "
                         f"{s['temp_bytes']} B  total {s['total_bytes']} B")
    search = summary.get("search")
    if search:
        lines.append("\nsearch:")
        topk = search.get("store_topk")
        if topk:
            lines.append(
                f"  store top-k: {topk['segment_scans']} segment scan(s), "
                f"{topk['rows_scanned']} rows in {topk['total_ms']} ms "
                f"({topk['rows_per_s']} rows/s)  p50 {topk['p50_ms']} ms  "
                f"p99 {topk['p99_ms']} ms")
        brute = search.get("brute_chunks")
        if brute:
            lines.append(
                f"  brute force: {brute['chunks']} chunk(s) in "
                f"{brute['total_ms']} ms  p50 {brute['p50_ms']} ms  "
                f"p99 {brute['p99_ms']} ms")
        ing = search.get("ingest")
        if ing:
            lines.append(
                f"  ingest: {ing['shards']} shard(s), {ing['rows']} rows in "
                f"{ing['total_ms']} ms")
    annsec = summary.get("ann")
    if annsec:
        lines.append("\nANN (IVF approximate search):")
        scan = annsec.get("scan")
        if scan:
            lines.append(
                f"  scan: {scan['segment_scans']} segment scan(s), "
                f"{scan['lists_scanned']} list(s) over "
                f"{scan['rows_scanned']} rows in {scan['total_ms']} ms  "
                f"p50 {scan['p50_ms']} ms  p99 {scan['p99_ms']} ms")
            dist = ", ".join(f"nprobe={k}: x{v}" for k, v in
                             scan["nprobe_distribution"].items())
            lines.append(f"  nprobe distribution: {dist}")
        fun = annsec.get("funnel")
        if fun:
            lines.append(
                f"  funnel: {fun['queries']} query(ies) probed "
                f"{fun['lists_probed']} list(s) -> "
                f"{fun['shortlist_candidates']} shortlist candidate(s) -> "
                f"{fun['reranked_to_top_k']} re-ranked slot(s)")
            lines.append(
                f"  segments: {fun['segments_scanned']} scanned, "
                f"{fun['segments_skipped']} skipped "
                f"({fun['segment_skip_pct']}% skipped)")
        rr = annsec.get("rerank")
        if rr:
            lines.append(
                f"  re-rank: {rr['calls']} call(s), {rr['candidates']} "
                f"candidate(s) in {rr['total_ms']} ms  p50 {rr['p50_ms']} ms"
                f"  p99 {rr['p99_ms']} ms")
        tr = annsec.get("train")
        if tr:
            lines.append(
                f"  train: {tr['lloyd_iters']} Lloyd iteration(s), "
                f"{tr['restarts']} restart(s), {tr['total_ms']} ms")
        rc = annsec.get("recall_spot_checks")
        if rc:
            lines.append(
                f"  recall spot-check: {rc['checks']} check(s) at "
                f"k={rc['k']} over {rc['samples']} query(ies) — "
                f"sample-weighted mean {rc['mean_recall']}, "
                f"min {rc['min_recall']}")
        ro = annsec.get("recall_online")
        if ro:
            lines.append(
                f"  online recall (shadow-oracle probes): {ro['probes']} "
                f"probe(s) at k={ro['k']} over {ro['samples']} query(ies) — "
                f"sample-weighted mean {ro['mean_recall']}, "
                f"last rolling {ro['last_rolling']}")
    ing = summary.get("ingest")
    if ing:
        lines.append("\ningest:")
        ap = ing.get("append")
        if ap:
            lines.append(
                f"  append: {ap['records']} record(s), {ap['rows']} rows "
                f"({ap['rows_per_s']} rows/s)  p50 {ap['p50_ms']} ms  "
                f"p99 {ap['p99_ms']} ms")
        for c in ing.get("compactions", []):
            lines.append(
                f"  {c['time']} compacted {c['rows']} rows "
                f"({c['records']} record(s)) -> snapshot v{c['snapshot']} "
                f"in {c['ms']} ms")
        for rec in ing.get("recoveries", []):
            lines.append(
                f"  {rec['time']} recovered {rec['rows']} rows from "
                f"{rec['segments']} segment(s), {rec['torn']} torn tail(s) "
                f"truncated, in {rec['ms']} ms")
    risk = summary.get("copy_risk")
    if risk:
        lines.append(f"\ncopy risk: {risk['scored']} generation(s) scored, "
                     f"{risk['flagged']} flagged — sim p50 {risk['sim_p50']}"
                     f"  p90 {risk['sim_p90']}  p99 {risk['sim_p99']}"
                     f"  max {risk['sim_max']}")
        for key, count in risk["flagged_train_keys"].items():
            lines.append(f"  {count}x nearest train key {key}")
        for f in risk["flagged_timeline"][:10]:
            lines.append(f"  {f['time']} FLAGGED req {f['request_id']} "
                         f"sim {f['max_sim']} -> {f['top_key']}")
    slo = summary.get("slo")
    if slo:
        counts = ", ".join(
            f"{name}: {st['breaches']} breach(es)/{st['recoveries']} "
            f"recovery(ies)" for name, st in slo["objectives"].items())
        lines.append(f"\nSLO: {counts}")
        if slo["open_breaches"]:
            lines.append(
                "  still in breach at end of trace: "
                + ", ".join(slo["open_breaches"]))
        for t in slo["timeline"]:
            mark = "BREACH " if t["event"] == "breach" else "recover"
            extra = (f"burn {t.get('burn')}" if t["event"] == "breach"
                     else f"after {t.get('breach_s')}s in breach")
            lines.append(
                f"  {t['time']} {mark} {t['objective']:<20} "
                f"value={t.get('value')} target={t.get('target')}  {extra}")
    if summary["fault_timeline"]:
        lines.append("\nfault timeline:")
        for f in summary["fault_timeline"]:
            lines.append(f"  {f['time']} r{f['rank']} {f['name']} {f['args']}")
    else:
        lines.append("\nfault timeline: clean (no fault/* events)")
    flightrecs = sorted({p for d in paths if d.is_dir()
                         for p in d.rglob("flightrec_*.json")})
    if flightrecs:
        lines.append("flight-recorder dumps:")
        for p in flightrecs:
            try:
                reason = json.loads(p.read_text()).get("reason", "?")
            except (OSError, json.JSONDecodeError) as e:
                reason = f"<unreadable: {e}>"
            lines.append(f"  {p.name}: {reason}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.trace_report",
        description="Stage-time breakdown + fault timeline + fleet trace "
                    "merge from trace.jsonl files; optional Chrome-trace "
                    "export.")
    ap.add_argument("paths", type=Path, nargs="+", metavar="PATH",
                    help="directories searched recursively for trace*.jsonl "
                         "(a run's output_dir, a serve --logdir, or a fleet "
                         "dir) and/or individual trace files")
    ap.add_argument("--chrome", type=Path, default=None, metavar="OUT.json",
                    help="also write a Chrome-trace/Perfetto JSON export "
                         "(one track per source process)")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON instead of text")
    ap.add_argument("--max-compiles", type=int, default=None, metavar="N",
                    help="recompile budget: fail (exit 3) when any process "
                         "incarnation (stream + os_pid) performed more than "
                         "N XLA compiles (serve/compile events and "
                         "warmcache/compile spans). --max-compiles 0 asserts "
                         "a fully warm run — e.g. a respawned worker served "
                         "entirely from the persistent executable cache")
    args = ap.parse_args(argv)

    for p in args.paths:
        if not p.is_dir() and not p.is_file():
            print(f"trace_report: {p} is not a directory or file",
                  file=sys.stderr)
            return 1
    schema = load_schema()
    records, errors, meta = load_fleet(args.paths, schema)
    if errors:
        for e in errors[:20]:
            print(f"trace_report: SCHEMA: {e}", file=sys.stderr)
        print(f"trace_report: {len(errors)} invalid record(s)", file=sys.stderr)
        return 2
    if not records:
        print(f"trace_report: no trace records under "
              f"{', '.join(map(str, args.paths))} "
              "(no trace*.jsonl, or all files empty)", file=sys.stderr)
        return 1
    summary = summarize(records, meta)
    if args.chrome:
        args.chrome.write_text(json.dumps(chrome_trace(records)))
        print(f"trace_report: wrote chrome trace -> {args.chrome}", file=sys.stderr)
    print(json.dumps(summary, indent=1) if args.json
          else render_text(summary, args.paths))
    if args.max_compiles is not None:
        over = {inc: n for inc, n
                in summary["compiles_per_incarnation"].items()
                if n > args.max_compiles}
        if over:
            for inc, n in over.items():
                print(f"trace_report: RECOMPILE BUDGET: {inc} performed "
                      f"{n} compile(s) > budget {args.max_compiles}",
                      file=sys.stderr)
            return 3
        print(f"trace_report: recompile budget OK (<= {args.max_compiles} "
              f"per incarnation)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

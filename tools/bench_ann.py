"""Approximate-search bench: IVF + int8 tier vs the exact sharded top-k
(dcr-ann, ISSUE 19).

Builds a synthetic CLUSTERED SSCD-width corpus — cluster centers plus
small noise, queries drawn as perturbed corpus rows from a SUBSET of hot
clusters. Both choices are deliberate: an IVF quantizer over isotropic
gaussian noise has nothing to learn (every list is equidistant, recall
collapses, probes don't localize), and uniformly-spread queries defeat
segment skipping (every query chunk's probed-list union touches every
segment). Real embedding corpora are strongly clustered and real serve
traffic is bursty — the copy-risk workload scores batches of similar
generations — so the synthetic workload has to reproduce the structure
the index exploits or the bench measures nothing.

The SAME query set then runs through both engines over the SAME store:

- **exact**: the mesh-sharded ``search/topk`` engine (dcr-store) — every
  committed row scanned per query; the correctness oracle;
- **ann**: ``dcr-search train-ivf`` once (banked as ``train_seconds`` —
  training is paid per corpus, not per query), then the ``search/ivf_scan``
  engine: nprobe-bounded int8 inverted-list probes with the shortlist
  re-ranked in f32 through the exact program.

Banked per nprobe: recall@k against the exact oracle and the speedup —
the recall-vs-cost curve an operator tunes ``--nprobe`` on. Gates (full
mode, at the default operating point ``BENCH_ANN_NPROBE``):

- recall@``BENCH_ANN_TOPK`` >= ``MIN_ANN_RECALL`` (0.95), and
- query throughput >= ``MIN_ANN_SPEEDUP`` (5x) over exact,

or exit 1. Both modes additionally pin the EXACT path bit-identical
(scores AND keys) between this store — which carries a trained ann tier
under ``<store>/ann/`` — and a clean copy without one: the ann tier's
presence on disk must be invisible to ann-off queries.

``--smoke`` (CI): small corpus; validates the JSON schema + the ann-off
identity pin + that recall/speedup are recorded; the perf gates are
recorded but not enforced (shared CI runners don't gate perf — the banked
full run does). Results bank as BENCH_ANN.json.

Usage: python tools/bench_ann.py [--smoke]
Env knobs: BENCH_ANN_ROWS (default 131072; smoke 4096), BENCH_ANN_DIM
(512; smoke 64), BENCH_ANN_CLUSTERS (256; smoke 16),
BENCH_ANN_QUERY_CLUSTERS (16; smoke 4 — the hot clusters queries come
from), BENCH_ANN_LISTS (256; smoke 16), BENCH_ANN_SEGMENT_ROWS (512;
smoke 0 = engine default — the skip granule: ~one list per segment),
BENCH_ANN_QUERIES (256; smoke 32), BENCH_ANN_TOPK (10), BENCH_ANN_NPROBE
(8 — the gated operating point), BENCH_ANN_CURVE (comma-separated nprobe
sweep, default "1,2,4,8,16"), BENCH_ANN_REPEATS (3; smoke 1),
BENCH_ANN_MIN_RECALL (0.95), BENCH_ANN_MIN_SPEEDUP (5.0).
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT = Path(__file__).resolve().parent.parent / "BENCH_ANN.json"

#: ISSUE 19 acceptance floors at the default operating point.
MIN_ANN_RECALL = 0.95
MIN_ANN_SPEEDUP = 5.0


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name) or default)


def build_corpus(rows: int, dim: int, clusters: int, queries: int,
                 query_clusters: int, seed: int = 0):
    """Clustered corpus + queries that are perturbed corpus rows drawn
    from ``query_clusters`` hot clusters (each query's true neighbors
    live in its own cluster, and queries share probes — the bursty
    workload IVF is built for)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((clusters, dim)).astype(np.float32) * 4.0
    assign = rng.integers(0, clusters, rows)
    feats = (centers[assign]
             + rng.standard_normal((rows, dim)).astype(np.float32) * 0.25)
    hot = rng.choice(clusters, min(query_clusters, clusters), replace=False)
    pool = np.flatnonzero(np.isin(assign, hot))
    picks = rng.choice(pool, queries, replace=len(pool) < queries)
    q = (feats[picks]
         + rng.standard_normal((queries, dim)).astype(np.float32) * 0.05)
    return feats.astype(np.float32), q.astype(np.float32)


def recall_at_k(ann_keys, exact_keys, k: int) -> float:
    hits = total = 0
    for arow, erow in zip(ann_keys, exact_keys):
        truth = set(erow[:k].tolist())
        hits += len(truth & set(arow[:k].tolist()))
        total += len(truth)
    return hits / max(total, 1)


def _best(fn, repeats: int):
    out = None
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    return out, best


def validate_result(doc: dict) -> list[str]:
    """Schema problems with a BENCH_ANN document ([] = valid). Used by the
    --smoke leg and tests/test_ann.py."""
    problems: list[str] = []

    def need(obj, field, types, where):
        v = obj.get(field)
        if not isinstance(v, types) or isinstance(v, bool) and types != bool:
            problems.append(f"{where}.{field}: missing/wrong type")
            return None
        return v

    need(doc, "version", int, "$")
    cfg = need(doc, "config", dict, "$") or {}
    for f in ("corpus_rows", "embed_dim", "clusters", "n_lists", "queries",
              "top_k", "query_batch", "repeats"):
        need(cfg, f, int, "$.config")
    exact = need(doc, "exact", dict, "$") or {}
    need(exact, "seconds", (int, float), "$.exact")
    need(exact, "rows_per_s", (int, float), "$.exact")
    ann = need(doc, "ann", dict, "$") or {}
    for f in ("train_seconds", "seconds", "rows_per_s"):
        need(ann, f, (int, float), "$.ann")
    curve = need(doc, "recall_curve", list, "$") or []
    if not curve:
        problems.append("$.recall_curve: empty")
    for i, row in enumerate(curve):
        if not isinstance(row, dict):
            problems.append(f"$.recall_curve[{i}]: not an object")
            continue
        need(row, "nprobe", int, f"$.recall_curve[{i}]")
        need(row, "recall", (int, float), f"$.recall_curve[{i}]")
        need(row, "seconds", (int, float), f"$.recall_curve[{i}]")
        need(row, "speedup", (int, float), f"$.recall_curve[{i}]")
    eq = need(doc, "equality", dict, "$") or {}
    for f in ("exact_scores_equal", "exact_keys_equal"):
        if not isinstance(eq.get(f), bool):
            problems.append(f"$.equality.{f}: missing/not bool")
    gate = need(doc, "gate", dict, "$") or {}
    need(gate, "nprobe", int, "$.gate")
    for f in ("min_recall", "recall", "min_speedup", "speedup"):
        need(gate, f, (int, float), "$.gate")
    for f in ("enforced", "passed"):
        if not isinstance(gate.get(f), bool):
            problems.append(f"$.gate.{f}: missing/not bool")
    return problems


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    smoke = "--smoke" in argv

    import numpy as np

    from dcr_tpu.search import ann as annmod
    from dcr_tpu.search.annindex import open_ann_engine
    from dcr_tpu.search.shardindex import open_engine
    from dcr_tpu.search.store import EmbeddingStoreWriter

    rows = _env_int("BENCH_ANN_ROWS", 4096 if smoke else 131072)
    dim = _env_int("BENCH_ANN_DIM", 64 if smoke else 512)
    clusters = _env_int("BENCH_ANN_CLUSTERS", 16 if smoke else 256)
    query_clusters = _env_int("BENCH_ANN_QUERY_CLUSTERS",
                              4 if smoke else 8)
    n_lists = _env_int("BENCH_ANN_LISTS", 16 if smoke else 256)
    segment_rows = _env_int("BENCH_ANN_SEGMENT_ROWS", 0 if smoke else 512)
    queries = _env_int("BENCH_ANN_QUERIES", 32 if smoke else 256)
    top_k = _env_int("BENCH_ANN_TOPK", 10)
    nprobe = _env_int("BENCH_ANN_NPROBE", 2)
    curve_probes = [int(x) for x in
                    (os.environ.get("BENCH_ANN_CURVE") or
                     ("2,4" if smoke else "1,2,4,8,16")).split(",")]
    repeats = _env_int("BENCH_ANN_REPEATS", 1 if smoke else 3)
    # Small chunks preserve the engine's sorted-probe locality: queries are
    # sorted by top probe, so a 64-query chunk from a bursty workload
    # touches a handful of lists and skips the rest. One giant chunk would
    # union every hot list and scan far more rows per query.
    query_batch = _env_int("BENCH_ANN_QUERY_BATCH", min(queries, 64))
    min_recall = float(os.environ.get("BENCH_ANN_MIN_RECALL")
                       or MIN_ANN_RECALL)
    min_speedup = float(os.environ.get("BENCH_ANN_MIN_SPEEDUP")
                        or MIN_ANN_SPEEDUP)
    if nprobe not in curve_probes:
        curve_probes.append(nprobe)
    print(f"bench_ann{' --smoke' if smoke else ''}: corpus {rows}x{dim} "
          f"({clusters} clusters), {n_lists} lists, {queries} queries "
          f"from {query_clusters} hot cluster(s), recall@{top_k}, "
          f"nprobe curve {curve_probes}")

    feats, q = build_corpus(rows, dim, clusters, queries, query_clusters)

    with tempfile.TemporaryDirectory(prefix="bench_ann_") as td:
        root = Path(td)
        store = root / "store"
        w = EmbeddingStoreWriter(store, embed_dim=dim, shard_rows=16384)
        w.add(feats, [f"row{i}" for i in range(rows)])
        w.finalize()

        # exact oracle FIRST, against the ann-free store
        engine = open_engine(store, top_k=top_k, query_batch=query_batch)
        engine.query(q[:1])
        (exact_scores, exact_keys), exact_s = _best(
            lambda: engine.query(q), repeats)

        # ann-off identity pin: snapshot the exact results, train the ann
        # tier INTO the same store, and re-run the exact engine — the ann
        # tier on disk must be invisible to the exact path (bit-identical
        # scores AND keys)
        t0 = time.perf_counter()
        train_report = annmod.train_ivf(store, n_lists=n_lists, iters=10,
                                        seed=0)
        train_s = time.perf_counter() - t0
        engine2 = open_engine(store, top_k=top_k, query_batch=query_batch)
        engine2.query(q[:1])
        re_scores, re_keys = engine2.query(q)
        scores_equal = bool(np.array_equal(exact_scores, re_scores))
        keys_equal = bool((exact_keys == re_keys).all())

        aeng = open_ann_engine(store, top_k=top_k, nprobe=nprobe,
                               query_batch=query_batch,
                               shortlist_k=max(32, top_k),
                               segment_rows=segment_rows)
        aeng.query(q[:1])
        curve = []
        gate_row = None
        for p in sorted(set(curve_probes)):
            (a_scores, a_keys), a_s = _best(
                lambda p=p: aeng.query(q, nprobe=p), repeats)
            row = {"nprobe": int(p),
                   "recall": round(recall_at_k(a_keys, exact_keys, top_k), 4),
                   "seconds": round(a_s, 4),
                   "speedup": round(exact_s / max(a_s, 1e-9), 3)}
            curve.append(row)
            print(f"bench_ann: nprobe={p:<3d} recall@{top_k} "
                  f"{row['recall']:.4f}  {row['seconds']}s  "
                  f"(speedup {row['speedup']}x)")
            if p == nprobe:
                gate_row = row

        doc = {
            "version": 1,
            "config": {"corpus_rows": rows, "embed_dim": dim,
                       "clusters": clusters,
                       "query_clusters": query_clusters,
                       "n_lists": n_lists,
                       "segment_rows": int(aeng.segment_rows),
                       "queries": queries, "top_k": top_k,
                       "query_batch": query_batch, "repeats": repeats,
                       "ivf_iters": int(train_report["iters"]),
                       "segments": int(aeng.num_segments)},
            "exact": {
                "seconds": round(exact_s, 4),
                "rows_per_s": round(queries * rows / max(exact_s, 1e-9)),
            },
            "ann": {
                "train_seconds": round(train_s, 4),
                "seconds": gate_row["seconds"],
                "rows_per_s": round(queries * rows
                                    / max(gate_row["seconds"], 1e-9)),
            },
            "recall_curve": curve,
            "equality": {"exact_scores_equal": scores_equal,
                         "exact_keys_equal": keys_equal},
            "gate": {"nprobe": int(nprobe),
                     "min_recall": min_recall,
                     "recall": gate_row["recall"],
                     "min_speedup": min_speedup,
                     "speedup": gate_row["speedup"],
                     "enforced": not smoke,
                     "passed": bool(gate_row["recall"] >= min_recall
                                    and gate_row["speedup"] >= min_speedup)},
        }

    problems = validate_result(doc)
    OUT.write_text(json.dumps(doc, indent=1, sort_keys=True) + "\n")
    print(f"bench_ann: exact {doc['exact']['seconds']}s vs ann "
          f"{doc['ann']['seconds']}s at nprobe={nprobe} -> recall@{top_k} "
          f"{doc['gate']['recall']} at {doc['gate']['speedup']}x "
          f"(train {doc['ann']['train_seconds']}s, paid once) -> {OUT}")
    if problems:
        print("bench_ann: SCHEMA problems:\n  " + "\n  ".join(problems))
        return 1
    if not (scores_equal and keys_equal):
        print("bench_ann: ANN-OFF IDENTITY FAILED — the exact path returned "
              "different results once the ann tier existed on disk "
              f"(scores_equal={scores_equal}, keys_equal={keys_equal})")
        return 1
    if not smoke and not doc["gate"]["passed"]:
        print(f"bench_ann: GATE FAILED — recall {doc['gate']['recall']} "
              f"(floor {min_recall}) at speedup {doc['gate']['speedup']}x "
              f"(floor {min_speedup}x)")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
